# weaviate-tpu server image (reference analog: /root/reference/Dockerfile —
# build stage compiles the native pieces, the runtime stage is minimal and
# 12-factor: all configuration through environment variables).
#
# Build:  docker build -t weaviate-tpu .
# Run:    docker run -p 8080:8080 -v wtpu-data:/var/lib/weaviate weaviate-tpu
# Ready:  curl localhost:8080/v1/.well-known/ready
#
# The default install is the CPU jax wheel so the image runs anywhere; on a
# TPU VM build with:  --build-arg JAX_EXTRA="jax[tpu]" (pulls libtpu).

###############################################################################
FROM python:3.12-slim AS server_builder
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*
WORKDIR /app
COPY native/ native/
COPY weaviate_tpu/ weaviate_tpu/
# compile the native engines (CPU HNSW graph, gRPC reply marshaller) into
# weaviate_tpu/_native — the runtime never needs a compiler. Portable
# baseline ISA: the image must run on any x86-64-v2 host, not just the
# build machine (-march=native would SIGILL elsewhere).
RUN ARCH_FLAGS="-march=x86-64-v2" sh native/build.sh

###############################################################################
FROM python:3.12-slim AS weaviate-tpu
RUN apt-get update && apt-get install -y --no-install-recommends \
        curl libgomp1 && rm -rf /var/lib/apt/lists/* \
    && useradd -r -u 10001 weaviate \
    && mkdir -p /var/lib/weaviate && chown weaviate /var/lib/weaviate
ARG JAX_EXTRA="jax[cpu]"
RUN pip install --no-cache-dir "${JAX_EXTRA}" numpy grpcio protobuf
WORKDIR /app
COPY --from=server_builder /app/weaviate_tpu/ weaviate_tpu/
USER weaviate
ENV PERSISTENCE_DATA_PATH=/var/lib/weaviate \
    QUERY_DEFAULTS_LIMIT=25 \
    DEFAULT_VECTORIZER_MODULE=none \
    PYTHONUNBUFFERED=1
EXPOSE 8080 50051 7946 7947 2112
VOLUME /var/lib/weaviate
HEALTHCHECK --interval=10s --timeout=3s --start-period=30s \
    CMD curl -sf http://localhost:8080/v1/.well-known/ready || exit 1
ENTRYPOINT ["python", "-m", "weaviate_tpu"]
