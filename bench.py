"""Headline benchmark: batched kNN QPS on a SIFT1M-shaped workload.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload mirrors BASELINE.md config #1/#5: 1M x 128 float32 vectors (SIFT1M
shape), L2, k=10, 256-query batches — the reference's SIFT harness
(test/benchmark/benchmark_sift.go: l2, efC=64, maxConn=64) and the gRPC
256-query batched-kNN config.

vs_baseline compares TPU QPS against a CPU comparator measured in-process on
the same data: the native C++ HNSW engine if built (the reference's real
comparator — CPU graph traversal), else single-thread numpy brute force.
Recall@10 of the TPU path is measured against exact float64 ground truth and
the run only counts if recall >= 0.95 (it is 1.0 by construction for the
exact device index at f32).
"""

import json
import os
import sys
import time

import numpy as np

N = int(os.environ.get("BENCH_N", 1_000_000))
DIM = int(os.environ.get("BENCH_DIM", 128))
B = int(os.environ.get("BENCH_BATCH", 1024))
K = 10
N_QUERY_BATCHES = int(os.environ.get("BENCH_QUERY_BATCHES", 10))
N_GT = 64  # queries used for recall ground truth


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    import jax

    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.tpu import TpuVectorIndex

    rng = np.random.default_rng(7)
    log(f"generating {N}x{DIM} vectors...")
    vecs = rng.standard_normal((N, DIM), dtype=np.float32)
    queries = rng.standard_normal((B, DIM), dtype=np.float32)

    cfg = vi.HnswUserConfig.from_dict({"distance": vi.DISTANCE_L2}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, "/tmp/bench_shard", persist=False)

    t0 = time.perf_counter()
    idx.add_batch(np.arange(N), vecs)
    idx.flush()
    import_s = time.perf_counter() - t0
    log(f"import: {import_s:.1f}s ({N/import_s:.0f} vec/s) on {jax.devices()[0]}")

    # warmup + compile
    ids, dists = idx.search_by_vectors(queries, K)
    jax.block_until_ready(idx._store)

    t0 = time.perf_counter()
    for _ in range(N_QUERY_BATCHES):
        ids, dists = idx.search_by_vectors(queries, K)
    elapsed = time.perf_counter() - t0
    qps = (N_QUERY_BATCHES * B) / elapsed
    log(f"TPU batched kNN: {qps:.0f} QPS ({elapsed/N_QUERY_BATCHES*1000:.2f} ms / {B}-query batch)")

    # recall@10 against exact ground truth
    recall_hits = 0
    for i in range(N_GT):
        d = ((vecs.astype(np.float32) - queries[i]) ** 2).sum(1)
        gt = set(np.argsort(d)[:K].tolist())
        got = set(int(x) for x in ids[i][:K])
        recall_hits += len(gt & got)
    recall = recall_hits / (N_GT * K)
    log(f"recall@10 = {recall:.4f}")

    # CPU baseline: numpy brute force, single batch timed
    nb = 4
    t0 = time.perf_counter()
    for i in range(nb):
        d = ((vecs - queries[i]) ** 2).sum(1)
        np.argpartition(d, K)[:K]
    cpu_elapsed = time.perf_counter() - t0
    cpu_qps = nb / cpu_elapsed
    log(f"CPU numpy brute force: {cpu_qps:.1f} QPS")

    out = {
        "metric": f"batched kNN QPS (N={N}, d={DIM}, k={K}, batch={B}, L2, recall@10={recall:.3f})",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / cpu_qps, 1),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
