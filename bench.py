"""Headline benchmark: batched kNN QPS on a SIFT1M-shaped workload.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload mirrors BASELINE.md config #1/#5: 1M x 128 float32 clustered
vectors (SIFT1M shape and cluster structure), L2, k=10, 256..1024-query
batches — the reference's SIFT harness (test/benchmark/benchmark_sift.go:
l2, efC=64, maxConn=64) and the gRPC 256-query batched-kNN config.

vs_baseline = TPU QPS / CPU-HNSW QPS at recall@10 >= 0.95. The CPU baseline
is our native C++ HNSW engine (the same role the reference's Go HNSW plays),
measured on the same data distribution and cached in baseline_cpu.json
(re-measure with BENCH_MEASURE_CPU=1 — it builds a graph, which takes
minutes and doesn't affect query QPS, so it is not re-run every bench).
TPU recall@10 is measured against exact ground truth every run and must be
>= 0.95 (it is 1.0: the device index is exact at f32).
"""

import json
import os
import sys
import time

import numpy as np

N = int(os.environ.get("BENCH_N", 1_000_000))
DIM = int(os.environ.get("BENCH_DIM", 128))
B = int(os.environ.get("BENCH_BATCH", 16384))
K = 10
N_QUERY_BATCHES = int(os.environ.get("BENCH_QUERY_BATCHES", 6))
N_GT = 64  # queries used for recall ground truth
N_CLUSTERS = 1024
BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline_cpu.json")
CPU_N = int(os.environ.get("BENCH_CPU_N", 100_000))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_data(n, dim, rng):
    """SIFT-like clustered distribution: mixture of gaussians."""
    centers = rng.standard_normal((N_CLUSTERS, dim), dtype=np.float32) * 2.0
    assign = rng.integers(0, N_CLUSTERS, n)
    vecs = centers[assign] + 0.35 * rng.standard_normal((n, dim), dtype=np.float32)
    return vecs


def exact_gt(vecs, queries, k):
    gt = []
    for q in queries:
        d = ((vecs - q) ** 2).sum(1)
        gt.append(np.argpartition(d, k)[:k][np.argsort(d[np.argpartition(d, k)[:k]])])
    return gt


def measure_cpu_baseline(rng):
    """CPU HNSW (native C++ engine) QPS at recall@10 >= 0.95 on CPU_N points,
    reference SIFT params (efC=64, maxConn=64), ef swept upward to recall."""
    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.hnsw import HnswIndex

    vecs = make_data(CPU_N, DIM, rng)
    queries = rng.standard_normal((256, DIM), dtype=np.float32) * 0.1 + vecs[
        rng.integers(0, CPU_N, 256)
    ]
    cfg = vi.HnswUserConfig.from_dict(
        {"distance": vi.DISTANCE_L2, "efConstruction": 64, "maxConnections": 64}, "hnsw"
    )
    idx = HnswIndex(cfg, "/tmp/bench_cpu_hnsw", persist=False)
    log(f"building CPU HNSW graph on {CPU_N} vectors (efC=64, M=64)...")
    t0 = time.perf_counter()
    idx.add_batch(np.arange(CPU_N), vecs)
    build_s = time.perf_counter() - t0
    log(f"built in {build_s:.0f}s ({CPU_N/build_s:.0f} vec/s)")
    gt = exact_gt(vecs, queries[:32], K)
    result = None
    for ef in (64, 128, 256, 512, 1024):
        idx.config.ef = ef
        t0 = time.perf_counter()
        ids, _ = idx.search_by_vectors(queries, K)
        qps = 256 / (time.perf_counter() - t0)
        hits = sum(
            len(set(int(x) for x in ids[i][:K]) & set(gt[i].tolist())) for i in range(32)
        )
        recall = hits / (32 * K)
        log(f"  ef={ef}: {qps:.0f} QPS, recall@10={recall:.3f}")
        result = {"ef": ef, "qps": qps, "recall": recall}
        if recall >= 0.95:
            break
    out = {
        "comparator": "native C++ HNSW (weaviate_tpu.index.hnsw), single-thread",
        "n": CPU_N,
        "dim": DIM,
        "k": K,
        "efConstruction": 64,
        "maxConnections": 64,
        "build_seconds": round(build_s, 1),
        "qps": round(result["qps"], 1),
        "recall": round(result["recall"], 4),
        "ef": result["ef"],
        "note": "measured at n=%d; HNSW QPS decreases with n, so using it as the 1M baseline is conservative in the TPU's favor"
        % CPU_N,
    }
    with open(BASELINE_FILE, "w") as f:
        json.dump(out, f, indent=1)
    log(f"wrote {BASELINE_FILE}: {out['qps']} QPS @ recall {out['recall']}")
    return out


def main():
    rng = np.random.default_rng(7)
    if os.environ.get("BENCH_MEASURE_CPU"):
        measure_cpu_baseline(rng)
        return

    import jax

    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.tpu import TpuVectorIndex

    log(f"generating {N}x{DIM} clustered vectors...")
    vecs = make_data(N, DIM, rng)
    queries = rng.standard_normal((B, DIM), dtype=np.float32) * 0.1 + vecs[
        rng.integers(0, N, B)
    ]

    cfg = vi.HnswUserConfig.from_dict({"distance": vi.DISTANCE_L2}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, "/tmp/bench_shard", persist=False)

    t0 = time.perf_counter()
    idx.add_batch(np.arange(N), vecs)
    idx.flush()
    import_s = time.perf_counter() - t0
    log(f"import: {import_s:.1f}s ({N/import_s:.0f} vec/s) on {jax.devices()[0]}")

    # warmup + compile
    ids, dists = idx.search_by_vectors(queries, K)

    # median per-batch time: the relay's per-call latency is noisy (2x swings
    # between runs); the median reflects steady-state device throughput
    times = []
    for _ in range(N_QUERY_BATCHES):
        t0 = time.perf_counter()
        ids, dists = idx.search_by_vectors(queries, K)
        times.append(time.perf_counter() - t0)
    med = float(np.median(times))
    log(
        f"TPU batched kNN (sync): {B/med:.0f} QPS (median {med*1000:.1f} ms, "
        f"min {min(times)*1000:.1f} ms / {B}-query batch)"
    )

    # depth-2 pipelined throughput: dispatch batch i+1 before finalizing
    # batch i so the host->device query upload hides behind device compute
    t0 = time.perf_counter()
    pending = idx.search_by_vectors_async(queries, K)
    for _ in range(N_QUERY_BATCHES - 1):
        nxt = idx.search_by_vectors_async(queries, K)
        pending()
        pending = nxt
    pending()
    pipel = (time.perf_counter() - t0) / N_QUERY_BATCHES
    qps = B / med  # headline = sync path (the one recall is measured on)
    log(f"TPU batched kNN (pipelined): {B/pipel:.0f} QPS ({pipel*1000:.1f} ms/batch)")

    gt = exact_gt(vecs, queries[:N_GT], K)
    hits = sum(len(set(int(x) for x in ids[i][:K]) & set(gt[i].tolist())) for i in range(N_GT))
    recall = hits / (N_GT * K)
    log(f"recall@10 = {recall:.4f}")

    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            cpu = json.load(f)
        cpu_qps = cpu["qps"]
        base_note = f"CPU HNSW ef={cpu['ef']}"
    else:
        # fallback: numpy brute force, single queries
        nb = 4
        t0 = time.perf_counter()
        for i in range(nb):
            d = ((vecs - queries[i]) ** 2).sum(1)
            np.argpartition(d, K)[:K]
        cpu_qps = nb / (time.perf_counter() - t0)
        base_note = "numpy brute force"
    log(f"baseline ({base_note}): {cpu_qps:.1f} QPS")

    out = {
        "metric": f"batched kNN QPS (N={N}, d={DIM}, k={K}, batch={B}, L2, recall@10={recall:.3f}, baseline={base_note})",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / cpu_qps, 1),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
