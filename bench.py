"""Headline benchmark: batched kNN on a SIFT1M-shaped workload.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload mirrors BASELINE.md config #1/#5: 1M x 128 float32 clustered
vectors (SIFT1M shape and cluster structure), L2, k=10, 16384-query batches
— the reference's SIFT harness (test/benchmark/benchmark_sift.go: l2,
efC=64, maxConn=64) scaled to the batch-first serving path.

The measured serving path is the depth-2 PIPELINED dispatch (the gRPC
BatchSearch shape: batch i+1's upload hides behind batch i's compute).
Recall@10 is measured against exact numpy float32 ground truth on 1024
queries every run; the device path is a fast-scan + exact-rescore (recall
1.0 measured).

vs_baseline = TPU QPS / CPU-HNSW QPS at recall@10 >= 0.95, where the CPU
baseline is the native C++ HNSW engine (the role the reference's Go HNSW
plays) measured on the SAME n=1M data with a MULTI-THREADED (OpenMP) query
loop on this host's cores, cached in baseline_cpu.json (re-measure with
BENCH_MEASURE_CPU=1; the graph build takes ~1h at 1M and does not affect
query QPS). Because the bench host exposes a single CPU core, the baseline
file also carries an 8-core linear extrapolation (the CPU's best case);
the ratio against that appears as vs_baseline_8core_equiv so both the
measured-hardware and scaled-CPU comparisons are visible.

BENCH_MATRIX=1 additionally measures BASELINE.md configs 2-5 (cosine,
filtered, PQ, gRPC 256-query batch latency) and writes bench_matrix.json.

BENCH_BACKEND=cpu runs the CPU-backend artifact matrix instead: it forces
JAX onto the host CPU (no relay probe) and reproduces the round-3
serving/import/PQ claims as bench rows — full-stack import objs/s, gRPC
256-query p50, PQ tier QPS (uncompressed / rescored / codes-only), and
vector-log restart replay. Rows are labeled "backend": "cpu" and merged
into bench_matrix.json WITHOUT touching the TPU-measured rows, which get a
one-time {"backend": "tpu-v5e", "round": 2, "stale": ...} annotation. These
are NOT TPU numbers; they exist so the host-path work is a reproducible
artifact even when the TPU relay is unreachable.
"""

import json
import os
import sys
import time
from typing import Optional

import numpy as np

N = int(os.environ.get("BENCH_N", 1_000_000))
DIM = int(os.environ.get("BENCH_DIM", 128))
B = int(os.environ.get("BENCH_BATCH", 16384))
K = 10
N_QUERY_BATCHES = int(os.environ.get("BENCH_QUERY_BATCHES", 8))
N_GT = int(os.environ.get("BENCH_GT", 1024))  # queries with exact ground truth
N_CLUSTERS = 1024
BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline_cpu.json")
MATRIX_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_matrix.json")
CPU_N = int(os.environ.get("BENCH_CPU_N", 1_000_000))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# --- roofline model (VERDICT r4 item 2) ------------------------------------
# The model lives in the SHARED cost-model module now
# (weaviate_tpu/monitoring/costmodel.py) so the serving path's per-dispatch
# attribution and these offline rows compute identical numbers from
# identical formulas; the old bench-local PEAKS/_roofline are these
# aliases. tests/test_bench_roofline.py pins the math through them.
from weaviate_tpu.monitoring import costmodel  # noqa: E402

PEAKS = costmodel.PEAKS
_roofline = costmodel.roofline_from_qps


# --- perf regression gate (VERDICT r4 item 2) ------------------------------
# The analog of the reference's CI perf tracker
# (test/benchmark/run_performance_tracker.sh): every matrix merge compares
# new rows against the last recorded row of the SAME backend and collects
# >BENCH_REGRESSION_PCT% QPS drops; the bench still writes all artifacts
# and prints its JSON line, then exits rc=4 so the driver sees the failure.
# Rows annotated "stale" (pre-rewrite round-2 TPU rows) are exempt: the
# first hardware re-measure replaces them instead of racing them.
_REGRESSIONS = []
_GATE_PCT = float(os.environ.get("BENCH_REGRESSION_PCT", 10.0))


def _qps_fields(row):
    """Yield (path, qps) for a row's top-level and one-deep nested QPS.
    Any top-level qps* float counts (qps, qps_e2e, qps_2term, ...) so rows
    like bm25_cpu are gated too."""
    for key, val in row.items():
        if (key.startswith("qps") or key in ("vecs_per_s", "objs_per_s")) \
                and isinstance(val, (int, float)):
            yield key, float(val)
        elif isinstance(val, dict):
            for sub, v in val.items():
                if isinstance(v, dict) and isinstance(v.get("qps"), (int, float)):
                    yield f"{key}.{sub}.qps", float(v["qps"])
                elif sub == "qps" and isinstance(v, (int, float)):
                    yield f"{key}.qps", float(v)


def _gate_check(old_data, new_rows):
    if os.environ.get("BENCH_GATE", "1") == "0":
        return
    for key, new in new_rows.items():
        old = old_data.get(key)
        if not isinstance(old, dict) or not isinstance(new, dict):
            continue
        if old.get("backend") != new.get("backend") or old.get("stale"):
            continue
        # rows are only comparable at the same workload shape (a smoke run
        # with BENCH_CPU_PQ_N=20000 must not race a 200k artifact row)
        if any(old.get(f) != new.get(f)
               for f in ("n", "batch", "n_docs") if f in old or f in new):
            continue
        old_q = dict(_qps_fields(old))
        for path, n_q in _qps_fields(new):
            o_q = old_q.get(path)
            if o_q and n_q < o_q * (1.0 - _GATE_PCT / 100.0):
                reg = {"row": key, "field": path, "was": o_q, "now": round(n_q, 1),
                       "drop_pct": round(100.0 * (1.0 - n_q / o_q), 1)}
                if not any(r["row"] == key and r["field"] == path
                           for r in _REGRESSIONS):
                    _REGRESSIONS.append(reg)
                    log(f"PERF REGRESSION {key}:{path} {o_q} -> {n_q:.1f} "
                        f"(-{reg['drop_pct']}% > {_GATE_PCT}% gate)")


def _gate_exit():
    """Call after the JSON line is printed: rc=4 iff regressions tripped."""
    if _REGRESSIONS:
        log(f"regression gate FAILED: {len(_REGRESSIONS)} row(s) slower "
            f"than the last recorded run (see above); artifacts were "
            "still written")
        raise SystemExit(4)


def make_data(n, dim, rng):
    """SIFT-like clustered distribution: mixture of gaussians."""
    centers = rng.standard_normal((N_CLUSTERS, dim), dtype=np.float32) * 2.0
    assign = rng.integers(0, N_CLUSTERS, n)
    vecs = centers[assign] + 0.35 * rng.standard_normal((n, dim), dtype=np.float32)
    return vecs


def exact_gt(vecs, queries, k, metric="l2"):
    """Exact numpy ground truth via chunked BLAS matmul (f32)."""
    out = []
    norms = (vecs.astype(np.float32) ** 2).sum(1)
    step = 256
    for s in range(0, len(queries), step):
        q = queries[s : s + step].astype(np.float32)
        if metric == "l2":
            d = (q ** 2).sum(1, keepdims=True) - 2.0 * (q @ vecs.T) + norms[None, :]
        else:  # cosine on normalized rows
            d = 1.0 - q @ vecs.T
        part = np.argpartition(d, k, axis=1)[:, :k]
        for i in range(q.shape[0]):
            row = part[i][np.argsort(d[i, part[i]], kind="stable")]
            out.append(row)
    return out


def recall_at_k(ids, gt, k):
    hits = 0
    for i, want in enumerate(gt):
        hits += len(set(int(x) for x in ids[i][:k]) & set(want.tolist()))
    return hits / (len(gt) * k)


def measure_cpu_baseline(rng):
    """CPU HNSW (native C++ engine) QPS at recall@10 >= 0.95 on CPU_N points
    (default 1M — same data size the TPU is measured on), reference SIFT
    params (efC=64, maxConn=64), ef swept upward until recall.

    The query loop is MULTI-THREADED: hnsw_search_batch fans queries over an
    OpenMP parallel-for with per-thread visited lists (the reference serves
    queries on all cores via goroutines). On hosts with fewer than 8 cores
    the baseline is additionally extrapolated LINEARLY to 8 cores — the
    CPU's best case (HNSW query scaling is sublinear in practice), recorded
    separately so both comparisons stay visible."""
    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.hnsw import HnswIndex

    cores = os.cpu_count() or 1
    vecs = make_data(CPU_N, DIM, rng)
    queries = rng.standard_normal((512, DIM), dtype=np.float32) * 0.1 + vecs[
        rng.integers(0, CPU_N, 512)
    ]
    cfg = vi.HnswUserConfig.from_dict(
        {"distance": vi.DISTANCE_L2, "efConstruction": 64, "maxConnections": 64}, "hnsw"
    )
    idx = HnswIndex(cfg, "/tmp/bench_cpu_hnsw", persist=False)
    log(f"building CPU HNSW graph on {CPU_N} vectors (efC=64, M=64)...")
    t0 = time.perf_counter()
    idx.add_batch(np.arange(CPU_N), vecs)
    build_s = time.perf_counter() - t0
    log(f"built in {build_s:.0f}s ({CPU_N/build_s:.0f} vec/s)")
    gt = exact_gt(vecs, queries[:64], K)
    result = None
    for ef in (64, 128, 256, 512, 1024):
        idx.config.ef = ef
        idx.search_by_vectors(queries[:64], K)  # warm caches
        t0 = time.perf_counter()
        ids, _ = idx.search_by_vectors(queries, K)
        qps = len(queries) / (time.perf_counter() - t0)
        recall = recall_at_k(ids, gt, K)
        log(f"  ef={ef}: {qps:.0f} QPS ({cores} cores), recall@10={recall:.3f}")
        result = {"ef": ef, "qps": qps, "recall": recall}
        if recall >= 0.95:
            break
    out = {
        "comparator": (
            "native C++ HNSW (weaviate_tpu.index.hnsw), multi-threaded "
            f"(OpenMP batch query loop over {cores} core(s))"
        ),
        "n": CPU_N,
        "dim": DIM,
        "k": K,
        "efConstruction": 64,
        "maxConnections": 64,
        "build_seconds": round(build_s, 1),
        "qps": round(result["qps"], 1),
        "cores": cores,
        "qps_8core_equiv": round(result["qps"] * max(1.0, 8.0 / cores), 1),
        "recall": round(result["recall"], 4),
        "ef": result["ef"],
        "note": (
            f"multi-threaded, n={CPU_N}, measured on {cores} core(s); "
            "qps_8core_equiv = linear extrapolation to 8 cores (the CPU's "
            "best case)"
        ),
    }
    with open(BASELINE_FILE, "w") as f:
        json.dump(out, f, indent=1)
    log(f"wrote {BASELINE_FILE}: {out['qps']} QPS measured / {out['qps_8core_equiv']} 8-core-equiv")
    return out


def _build_index(vecs, metric="l2-squared", pq=None):
    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.tpu import TpuVectorIndex

    d = {"distance": metric}
    if pq:
        d["pq"] = pq
    cfg = vi.HnswUserConfig.from_dict(d, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, "/tmp/bench_shard", persist=False)
    t0 = time.perf_counter()
    idx.add_batch(np.arange(len(vecs)), vecs)
    idx.flush()
    return idx, time.perf_counter() - t0


def _measure_pipelined(idx, queries, k, n_batches):
    """Depth-2 pipelined dispatch — the serving path."""
    idx.search_by_vectors(queries, k)  # compile + warm
    t0 = time.perf_counter()
    pending = idx.search_by_vectors_async(queries, k)
    for _ in range(n_batches - 1):
        nxt = idx.search_by_vectors_async(queries, k)
        pending()
        pending = nxt
    pending()
    per_batch = (time.perf_counter() - t0) / n_batches
    return queries.shape[0] / per_batch, per_batch


def _measure_sync(idx, queries, k, n_batches):
    idx.search_by_vectors(queries, k)
    times = []
    ids = None
    for _ in range(n_batches):
        t0 = time.perf_counter()
        ids, _ = idx.search_by_vectors(queries, k)
        times.append(time.perf_counter() - t0)
    med = float(np.median(times))
    return queries.shape[0] / med, med, ids


def _pq_tier_rows(vecs, queries, gt, tiers=("rescored",), reps=4,
                  rotation="none", suffix="", backend="tpu-v5e"):
    """Build a segments=32 PQ index, compress, and measure the requested
    serving tiers -> {"fit_seconds", tier: {"qps", "recall@10"}, ...}.
    Shared by the TPU matrix (config 4) and the CPU artifact matrix so both
    measure the same thing. rotation='opq' fits the OPQ rotation before
    quantizing (tier keys gain `suffix`, e.g. codes_only_opq). Roofline
    bytes/row: the rescored tier scans the bf16 rescore store (2·D); the
    codes-only tier scans the uint8 codes (M=32 bytes)."""
    out = {}
    n, dim = vecs.shape
    segs = 32
    idx_pq, _ = _build_index(
        vecs, pq={"enabled": False, "segments": segs, "centroids": 256,
                  "rotation": rotation})
    t0 = time.perf_counter()
    idx_pq.compress()
    out["fit_seconds" + suffix] = round(time.perf_counter() - t0, 1)
    try:
        for tier in tiers:
            idx_pq.config.pq.rescore = tier == "rescored"
            qps, _, ids = _measure_sync(idx_pq, queries, K, reps)
            bytes_per_row = 2 * dim if tier == "rescored" else segs
            out[tier + suffix] = {
                "qps": round(qps, 1),
                "recall@10": round(recall_at_k(ids, gt, K), 4),
                "roofline": _roofline(qps, n, dim, queries.shape[0],
                                      bytes_per_row, backend),
            }
    finally:
        idx_pq.config.pq.rescore = True
        idx_pq.drop()
    return out


def run_matrix(rng, vecs, queries, idx_l2, gt, headline=None):
    """BASELINE.md configs 2-5 (config 1 lands as the headline row, keyed by
    the dataset that was actually measured)."""
    import jax

    from weaviate_tpu.storage.bitmap import Bitmap

    plat = jax.devices()[0].platform
    common = {
        # axon is the relay platform name for the same v5e hardware the
        # legacy rows were measured on — keep ONE backend vocabulary
        "backend": "tpu-v5e" if plat in ("tpu", "axon") else plat,
        "round": 5,
        "date": time.strftime("%Y-%m-%d"),
    }
    results = {}
    if headline:
        label = headline.pop("label")
        results[label] = {**headline, **common}

    def flush():
        _merge_matrix({k: dict(v, **common) for k, v in results.items()})

    # config 3: filtered ANN (10% allowList -> masked device bitmap path)
    log("matrix: filtered ANN (10% allowList)...")
    mask = rng.random(len(vecs)) < 0.10
    allow = Bitmap(np.nonzero(mask)[0].astype(np.uint64))
    idx_l2.search_by_vectors(queries, K, allow_list=allow)
    t0 = time.perf_counter()
    ids, _ = idx_l2.search_by_vectors(queries, K, allow_list=allow)
    f_time = time.perf_counter() - t0
    sub = np.nonzero(mask)[0]
    gt_f = exact_gt(vecs[sub], queries[:128], K)
    sentinel = np.iinfo(np.uint64).max
    hits = sum(
        len(set(int(x) for x in ids[i][:K] if x != sentinel)
            & set(sub[gt_f[i]].tolist()))
        for i in range(128)
    )
    results["filtered_10pct"] = {
        "qps": round(B / f_time, 1),
        "recall@10": round(hits / (128 * K), 4),
        "roofline": _roofline(B / f_time, len(vecs), vecs.shape[1], B,
                              vecs.shape[1] * 4, common["backend"]),
    }
    flush()

    # filtered selectivity sweep on the live backend (VERDICT r4 #5): the
    # gather vs masked-scan crossover, tuned from hardware measurement
    log("matrix: filtered scaling sweep (1%/10%/50%)...")
    results["filtered_scaling"] = _filtered_scaling_row(
        rng, idx_l2, vecs, common["backend"])
    flush()

    # config 2: cosine — real glove-100-angular when available
    log("matrix: cosine (glove-100-angular)...")
    from bench_datasets import load_or_synthetic, tile_queries

    def synth_glove():
        vecs_cos = make_data(N, 100, rng)
        vecs_cos /= np.linalg.norm(vecs_cos, axis=1, keepdims=True)
        return {"train": vecs_cos, "queries": None, "metric": "cosine"}

    gdata, glabel = load_or_synthetic(
        "glove-100-angular", synth_glove,
        max_rows=None if N >= 1_000_000 else N)
    vecs_cos = gdata["train"]
    if gdata["queries"] is not None:
        q_cos = tile_queries(gdata["queries"], B)
    else:
        q_cos = vecs_cos[rng.integers(0, len(vecs_cos), B)] + \
            0.05 * rng.standard_normal((B, vecs_cos.shape[1]), dtype=np.float32)
    idx_cos, _ = _build_index(vecs_cos, metric="cosine")
    qps_cos, med_cos, ids_cos = _measure_sync(idx_cos, q_cos, K, 4)
    if gdata.get("gt") is not None:
        gt_cos = [row[:K] for row in gdata["gt"][: min(128, B)]]
    else:
        qn = q_cos[:128] / np.linalg.norm(q_cos[:128], axis=1, keepdims=True)
        gt_cos = exact_gt(vecs_cos, qn, K, metric="cosine")
    results[glabel] = {
        "qps": round(qps_cos, 1),
        "recall@10": round(recall_at_k(ids_cos, gt_cos, K), 4),
        "n": len(vecs_cos), "dim": int(vecs_cos.shape[1]),
        "roofline": _roofline(qps_cos, len(vecs_cos), vecs_cos.shape[1], B,
                              vecs_cos.shape[1] * 4, common["backend"]),
    }
    flush()
    idx_cos.drop()
    del idx_cos

    # config 5: gRPC 256-query batched kNN end-to-end (p50 latency)
    log("matrix: gRPC 256-query batch e2e (n=50k objects)...")
    results["grpc_batch256"] = _grpc_e2e(rng)
    flush()

    # BM25 host vs device on the live backend (hybrid's keyword half):
    # smaller corpus than the CPU row — the device engine's per-query cost
    # is a relay round trip, which is what this row exists to measure
    n_kw = int(os.environ.get("BENCH_BM25_TPU_N", 200_000))
    log(f"matrix: BM25 host vs device dense-row (n={n_kw} docs)...")
    results["bm25"] = _bm25_row(n_kw)
    flush()

    log("matrix: hybrid solo vs batched...")
    results["hybrid_batch"] = _hybrid_batch_row()
    flush()

    # config 4 LAST: PQ-compressed (segments=32, bf16 rescore-store scan).
    # The PQ-ADC Mosaic kernel is the one compile that has wedged the relay
    # (chip_session.log 03:20); every row above is already flushed when it
    # runs, so a wedge here costs only this row.
    log("matrix: PQ (segments=32, rescored)...")
    pq_out = _pq_tier_rows(vecs, queries, gt, backend=common["backend"])
    results["pq_seg32_rescored"] = {
        **pq_out["rescored"], "fit_seconds": pq_out["fit_seconds"],
    }
    flush()
    log(f"wrote {MATRIX_FILE}: {json.dumps(results)}")
    return results


def _filtered_scaling_row(rng, idx_f, fvecs, backend: str) -> dict:
    """Filtered-search selectivity sweep (1%/10%/50%) over an existing
    index: gather vs masked-scan path choice, allowList pack cost, QPS,
    roofline, recall. Shared by the CPU matrix and the hardware matrix so
    the crossover is tuned from the SAME measurement shape on both
    backends (reference semantics: hnsw/search.go:73-77 flat cutoff)."""
    from weaviate_tpu.storage.bitmap import Bitmap

    n_f = len(fvecs)
    b_f = 256
    fq = fvecs[rng.integers(0, n_f, b_f)] + 0.05 * rng.standard_normal(
        (b_f, DIM), dtype=np.float32)
    frow: dict = {"n": n_f, "batch": b_f, "selectivities": {}}
    for sel in (0.01, 0.10, 0.50):
        ids_sel = np.nonzero(rng.random(n_f) < sel)[0].astype(np.uint64)
        allow = Bitmap(ids_sel, _sorted=True)
        gather_path = len(allow) < idx_f.config.flat_search_cutoff
        entry = {"allow_size": int(len(allow)),
                 "path": "gather" if gather_path else "masked-scan"}
        if not gather_path:
            # host pack cost: cold (scatter table + packbits + upload) vs
            # cached (repeated queries with the same filter)
            snap_f = idx_f._read_snapshot()
            t0 = time.perf_counter()
            idx_f._allow_words(snap_f, allow)
            entry["pack_cold_ms"] = round((time.perf_counter() - t0) * 1000, 2)
            t0 = time.perf_counter()
            for _ in range(5):
                idx_f._allow_words(snap_f, allow)
            entry["pack_cached_ms"] = round(
                (time.perf_counter() - t0) / 5 * 1000, 3)
        idx_f.search_by_vectors(fq, K, allow_list=allow)  # warm/compile
        t0 = time.perf_counter()
        reps = 2
        for _ in range(reps):
            ids_out, _d = idx_f.search_by_vectors(fq, K, allow_list=allow)
        q_ms = (time.perf_counter() - t0) / reps * 1000
        entry["query_ms"] = round(q_ms, 1)
        entry["qps"] = round(b_f / (q_ms / 1000), 1)
        # the gather path only computes distances over the allowed rows —
        # charge it allow_size flops/bytes, not full-N
        n_scanned = len(allow) if gather_path else n_f
        entry["roofline"] = _roofline(
            entry["qps"], n_scanned, DIM, b_f, DIM * 4, backend)
        if "pack_cold_ms" in entry:
            entry["pack_pct_of_query"] = round(
                100 * entry["pack_cached_ms"] / q_ms, 2)
        # recall vs exact GT over the allowed subset (64 queries)
        gt_f = exact_gt(fvecs[ids_sel.astype(np.int64)], fq[:64], K)
        sentinel = np.iinfo(np.uint64).max
        hits = sum(
            len(set(int(x) for x in ids_out[i][:K] if x != sentinel)
                & set(ids_sel[gt_f[i]].tolist()))
            for i in range(64))
        entry["recall@10"] = round(hits / (64 * K), 4)
        frow["selectivities"][f"{int(sel*100)}pct"] = entry
        log(f"  {sel:.0%}: {entry}")
    return frow


def _bm25_row(n_docs: int) -> dict:
    """BM25F keyword QPS at serving steady state: host MaxScore engine,
    then the SAME shard with the device dense-row engine engaged
    (inverted/bm25_device.py) — the keyword half of hybrid on the chip.
    Per-query relay round trips are in the measurement on purpose: that is
    the serving cost a hybrid query actually pays."""
    import random
    import shutil
    import tempfile as _tf
    import uuid as _uuidlib

    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.inverted.bm25_device import DeviceBM25
    from weaviate_tpu.server import App
    from weaviate_tpu.usecases.traverser import GetParams

    words = [f"w{i}" for i in range(5000)]
    prng = random.Random(0)
    row: dict = {"n_docs": n_docs}
    bdir = _tf.mkdtemp(prefix="benchbm25")
    try:
        app = App(data_path=bdir)
        app.schema.add_class({
            "class": "Kw", "vectorIndexType": "noop",
            "properties": [{"name": "body", "dataType": ["text"]}]})
        kidx = app.db.get_index("Kw")
        for s in range(0, n_docs, 10_000):
            kidx.put_batch([
                StorObj(class_name="Kw", uuid=str(_uuidlib.UUID(int=i + 1)),
                        properties={"body": " ".join(prng.choices(words, k=40))})
                for i in range(s, min(s + 10_000, n_docs))])
        # serving steady state, like the gRPC row: memtables flushed,
        # postings compacted to single segments
        shard = next(iter(kidx.shards.values()))
        shard.inverted.store.flush_memtables()
        shard.inverted.store.compact_once(1)
        tr = app.traverser

        # Zipf-distributed query terms: the hot-term postings LRU + WAND
        # pruning workload real text produces
        ranks = np.arange(1, len(words) + 1)
        zp = (1.0 / ranks) / (1.0 / ranks).sum()
        zrng = np.random.default_rng(1)
        warr = np.array(words)
        qsets = {f"{nt}term": [" ".join(prng.choices(words, k=nt))
                               for _ in range(64)] for nt in (2, 8)}
        qsets["8term_zipf"] = [" ".join(warr[zrng.choice(len(words), 8, p=zp)])
                               for _ in range(96)]

        def sweep(tag: str) -> None:
            for label, qs in qsets.items():
                tr.get_class(GetParams(class_name="Kw",
                                       keyword_ranking={"query": qs[0]},
                                       limit=10))
                t0 = time.perf_counter()
                for qtext in qs:
                    tr.get_class(GetParams(
                        class_name="Kw", keyword_ranking={"query": qtext},
                        limit=10))
                row[f"qps_{label}{tag}"] = round(
                    len(qs) / (time.perf_counter() - t0), 1)

        sweep("")
        engine = DeviceBM25(shard.bm25)
        shard.bm25_device = engine
        sweep("_device")
        # batched lane: the whole query set as ONE get_class_batched call —
        # one device matmul + one fetch (the gRPC BatchSearch shape)
        for label, qs in qsets.items():
            plist = [GetParams(class_name="Kw",
                               keyword_ranking={"query": qtext}, limit=10)
                     for qtext in qs]
            tr.get_class_batched(plist)  # warm at the REAL (q_pad, u_pad)
            t0 = time.perf_counter()
            res = tr.get_class_batched(plist)
            row[f"qps_{label}_device_batch"] = round(
                len(qs) / (time.perf_counter() - t0), 1)
            assert not any(isinstance(r, Exception) for r in res)
        bshape = engine.last_batch_shape
        # the shape must be the ZIPF sweep's own dispatch (the last one
        # timed): a host-path fallback clears it, so a stale shape can
        # never pair with host QPS into a fabricated device roofline. The
        # matmul flops/bytes model lives in the shared costmodel
        # (DispatchShape built by inverted/bm25_device.py).
        if bshape is not None and bshape.dim \
                and bshape.batch == len(qsets["8term_zipf"]):
            import jax as _jax

            bknd = costmodel.backend_for_platform(_jax.default_backend())
            row["roofline_device_batch"] = bshape.roofline_at_qps(
                row["qps_8term_zipf_device_batch"], bknd)
            row["device_batch_shape"] = bshape.describe()
        shard.bm25_device = None
        app.shutdown()
    finally:
        shutil.rmtree(bdir, ignore_errors=True)
    return row


def _hybrid_batch_row(n_docs: int = 20_000, dim: int = 64,
                      n_q: int = 64) -> dict:
    """Hybrid serving: per-slot legacy path vs the batched lane (one
    overlapped dense dispatch + one keyword matmul per group)."""
    import random
    import shutil
    import tempfile as _tf
    import uuid as _uuidlib

    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.server import App
    from weaviate_tpu.usecases.traverser import GetParams

    rng = np.random.default_rng(7)
    prng = random.Random(7)
    words = [f"w{i}" for i in range(2000)]
    bdir = _tf.mkdtemp(prefix="benchhyb")
    row: dict = {"n_docs": n_docs, "dim": dim, "n_queries": n_q,
                 "alpha": 0.5}
    try:
        app = App(data_path=bdir)
        app.schema.add_class({
            "class": "Hy", "vectorIndexType": "hnsw_tpu",
            "vectorIndexConfig": {"distance": "l2-squared"},
            "invertedIndexConfig": {"bm25": {"device": True}},
            "properties": [{"name": "body", "dataType": ["text"]}]})
        hidx = app.db.get_index("Hy")
        for s in range(0, n_docs, 5_000):
            hidx.put_batch([
                StorObj(class_name="Hy", uuid=str(_uuidlib.UUID(int=i + 1)),
                        properties={"body": " ".join(
                            prng.choices(words, k=20))},
                        vector=rng.standard_normal(dim).astype(np.float32))
                for i in range(s, min(s + 5_000, n_docs))])
        shard = next(iter(hidx.shards.values()))
        shard.inverted.store.flush_memtables()
        shard.inverted.store.compact_once(1)
        plist = [GetParams(
            class_name="Hy", limit=10,
            hybrid={"query": " ".join(prng.choices(words, k=4)),
                    "vector": rng.standard_normal(dim).astype(
                        np.float32).tolist(),
                    "alpha": 0.5})
            for _ in range(n_q)]
        ex = app.traverser.explorer
        ex._get_one(plist[0])                       # warm legacy path
        t0 = time.perf_counter()
        for p in plist:
            ex._get_one(p)
        row["qps_solo"] = round(n_q / (time.perf_counter() - t0), 1)
        app.traverser.get_class_batched(plist)       # warm batched lane
        t0 = time.perf_counter()
        res = app.traverser.get_class_batched(plist)
        row["qps_batched"] = round(n_q / (time.perf_counter() - t0), 1)
        assert not any(isinstance(r, Exception) for r in res)
        assert shard.bm25_device is not None \
            and shard.bm25_device.last_batch_stats is not None
        row["speedup"] = round(row["qps_batched"] / max(row["qps_solo"], 1e-9), 2)
        app.shutdown()
    finally:
        shutil.rmtree(bdir, ignore_errors=True)
    return row


def _grpc_e2e(rng, n=50_000):
    """Full-stack 256-query BatchSearch over real gRPC (serialization + REST
    object store hydration included), p50 batch latency."""
    import tempfile
    import uuid as uuidlib

    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server import App
    from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

    app = App(data_path=tempfile.mkdtemp(prefix="benchgrpc"))
    app.schema.add_class({
        "class": "Bench", "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "tag", "dataType": ["text"]}],
    })
    idx = app.db.get_index("Bench")
    vecs = make_data(n, DIM, rng)
    from weaviate_tpu.entities.storobj import StorObj

    objs = [
        StorObj(class_name="Bench", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"tag": f"t{i % 32}"}, vector=vecs[i])
        for i in range(n)
    ]
    t0 = time.perf_counter()
    for s in range(0, n, 10_000):
        idx.put_batch(objs[s : s + 10_000])
    import_s = time.perf_counter() - t0
    # serving steady state: memtables flushed to segments (idle flush would
    # do this) — the zero-object raw lane requires it for exactness
    for sh in idx.shards.values():
        sh.objects.flush_memtable()
        sh.docid_lookup.flush_memtable()
    srv = GrpcServer(app, port=0)
    srv.start()
    client = SearchClient(f"127.0.0.1:{srv.port}")
    qs = vecs[rng.integers(0, n, 256)] + 0.05 * rng.standard_normal((256, DIM), dtype=np.float32)
    req = pb.BatchSearchRequest(requests=[
        pb.SearchRequest(class_name="Bench", limit=K,
                         near_vector=pb.NearVectorParams(vector=q.tolist()))
        for q in qs
    ])
    client.batch_search(req)  # warm
    from weaviate_tpu.server.grpc_server import SearchServicer

    raw_lane = SearchServicer(app)._raw_batch_lane(req, 0.0) is not None
    lats = []
    for _ in range(7):
        t0 = time.perf_counter()
        reply = client.batch_search(req)
        lats.append(time.perf_counter() - t0)
    p50 = float(np.median(lats))
    ok = sum(1 for r in reply.replies if len(r.results) == K)
    # concurrent throughput: 8 in-flight batches — device dispatch overlaps
    # another request's hydration (the async serving path)
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(8)
    m = 24
    t0 = time.perf_counter()
    futs = [pool.submit(client.batch_search, req) for _ in range(m)]
    for f in futs:
        f.result()
    conc_qps = m * 256 / (time.perf_counter() - t0)
    pool.shutdown(wait=False)
    client.close()
    srv.stop()
    # the ledger's byte picture of the imported corpus (captured before
    # shutdown unconfigures it): the insert row's capacity baseline
    mem_block = (app.memory_ledger.bench_block()
                 if getattr(app, "memory_ledger", None) is not None else None)
    app.shutdown()
    out = {
        "n": n, "batch": 256, "p50_ms": round(p50 * 1000, 1),
        "qps_e2e": round(256 / p50, 1),
        "qps_concurrent8": round(conc_qps, 1), "complete_replies": ok,
        "import_seconds": round(import_s, 1),
        "objs_per_s": round(n / import_s, 1),
        "raw_lane": raw_lane,
    }
    if mem_block is not None:
        out["memory"] = mem_block
    return out


# pre-run image of the matrix's LIVE (non-stale) rows, captured at the
# first merge of this process: if the device later proves unreachable
# (rc=3), _restore_live_rows puts back any live row this dying run
# overwrote — BENCH_r02-r05 all died on an unreachable device, and a
# half-made measurement from a doomed session must never replace a
# previously live row for the same key.
_MATRIX_PREIMAGE = None


def _merge_matrix(new_rows: dict) -> dict:
    """Merge rows into bench_matrix.json, preserving TPU-measured history.

    Legacy rows (written before per-row provenance existed) are annotated
    once as round-2 TPU numbers that predate the round-3 rewrites
    (``stale: true`` + the reason in ``stale_note``); new rows carry their
    own backend/round fields."""
    global _MATRIX_PREIMAGE
    data = {}
    if os.path.exists(MATRIX_FILE):
        with open(MATRIX_FILE) as f:
            data = json.load(f)
    for key, row in data.items():
        if key == "_meta" or not isinstance(row, dict):
            continue
        if "backend" not in row:
            row["backend"] = "tpu-v5e"
            row["round"] = 2
            row["stale"] = True
            row["stale_note"] = (
                "predates the round-3 serving/import/PQ rewrites; regenerate "
                "with BENCH_MATRIX=1 on hardware"
            )
    if _MATRIX_PREIMAGE is None:
        _MATRIX_PREIMAGE = {
            k: json.loads(json.dumps(r)) for k, r in data.items()
            if k != "_meta" and isinstance(r, dict) and not r.get("stale")
        }
    _gate_check(data, new_rows)
    data.update(new_rows)
    data["_meta"] = {
        "provenance": "per-row: see each row's backend/round fields",
        "rounds": sorted({r.get("round", 0) for k, r in data.items()
                          if k != "_meta" and isinstance(r, dict)}),
    }
    with open(MATRIX_FILE, "w") as f:
        json.dump(data, f, indent=1)
    return data


def _restore_live_rows() -> list:
    """Undo this process's overwrites of previously LIVE matrix rows (the
    rc=3 unreachable-device path). Rows this run ADDED under new keys are
    kept — they were measured before the device died; only replacements
    of live history roll back. -> the restored keys."""
    if not _MATRIX_PREIMAGE or not os.path.exists(MATRIX_FILE):
        return []
    try:
        with open(MATRIX_FILE) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    restored = []
    for key, old in _MATRIX_PREIMAGE.items():
        if data.get(key) != old:
            data[key] = old
            restored.append(key)
    if restored:
        with open(MATRIX_FILE, "w") as f:
            json.dump(data, f, indent=1)
        log(f"unreachable-device exit: restored previously live matrix "
            f"row(s) {restored} (a doomed session's partial rows must not "
            "replace measured history)")
    return restored


def run_cpu_matrix(rng):
    """CPU-backend artifact run (VERDICT r3 item 2): reproduce the round-3
    serving/import/PQ commit-message claims as bench rows that need no TPU.

    Single-core host: the absolute QPS here is the XLA-CPU scan, which is
    NOT the serving target — the value of these rows is (a) the host-path
    costs (import, gRPC p50, replay) that are backend-independent, and
    (b) the RELATIVE PQ tier ordering (rescored vs codes-only)."""
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    stamp = time.strftime("%Y-%m-%d")
    common = {"backend": "cpu", "round": 5, "date": stamp,
              "cores": os.cpu_count() or 1}
    rows = {}

    # -- row 1+2: full-stack import rate + gRPC 256-query batch p50 -------
    log("cpu matrix: gRPC 256-batch e2e + full-stack import (n=50k)...")
    g = _grpc_e2e(rng)
    g.update(common)
    g["provenance"] = (
        "full-stack put_batch import (batched LSM + grouped postings) and "
        "the round-4 zero-object raw serving lane (native point-get plane "
        "-> packed native reply marshaller; raw_lane flags engagement), "
        "measured over real gRPC on the CPU backend"
    )
    rows["grpc_batch256_cpu"] = g
    _merge_matrix(rows)

    # -- row 3: PQ tiers at n=200k ----------------------------------------
    n_pq = int(os.environ.get("BENCH_CPU_PQ_N", 200_000))
    b_pq = 256
    log(f"cpu matrix: PQ tiers (n={n_pq}, batch={b_pq})...")
    vecs = make_data(n_pq, DIM, rng)
    queries = vecs[rng.integers(0, n_pq, b_pq)] + 0.05 * rng.standard_normal(
        (b_pq, DIM), dtype=np.float32)
    gt = exact_gt(vecs, queries[:128], K)

    tiers = dict(common)
    tiers["n"] = n_pq
    tiers["batch"] = b_pq
    idx, _ = _build_index(vecs)
    qps_u, _, ids_u = _measure_sync(idx, queries, K, 3)
    tiers["uncompressed"] = {
        "qps": round(qps_u, 1),
        "recall@10": round(recall_at_k(ids_u, gt, K), 4),
        "roofline": _roofline(qps_u, n_pq, DIM, b_pq, DIM * 4, "cpu"),
    }
    idx.drop()
    del idx

    tiers.update(_pq_tier_rows(
        vecs, queries, gt, tiers=("rescored", "codes_only"), reps=3,
        backend="cpu"))
    tiers.update(_pq_tier_rows(
        vecs, queries, gt, tiers=("rescored", "codes_only"), reps=3,
        rotation="opq", suffix="_opq", backend="cpu"))
    tiers["provenance"] = (
        "PQ QPS-recall curve (VERDICT r4 item 6): uncompressed / rescored / "
        "codes-only, each with and without the OPQ rotation. Rescored scans "
        "the bf16 rescore store via gmin; codes-only rides the fused PQ-ADC "
        "group-min kernel (ops/pq_gmin.py). Raw-ADC recall is the "
        "quantizer's accuracy — rescore=true is the quality tier; OPQ is "
        "~neutral on this isotropic synthetic set but >=2x codes-only "
        "recall on correlated data (tests/test_pq_opq.py)."
    )
    rows["pq_tiers_cpu"] = tiers
    _merge_matrix(rows)

    # -- row 4: filtered-search scaling at n=1M (VERDICT r3 item 6) -------
    n_f = int(os.environ.get("BENCH_CPU_FILTER_N", 1_000_000))
    log(f"cpu matrix: filtered scaling (n={n_f}, 1%/10%/50% allowLists)...")
    fvecs = make_data(n_f, DIM, rng)
    idx_f, _ = _build_index(fvecs)
    frow = dict(common)
    frow.update(_filtered_scaling_row(rng, idx_f, fvecs, "cpu"))
    idx_f.drop()
    del idx_f, fvecs
    frow["provenance"] = (
        "filtered masked-scan with scatter-table allowList pack + per-filter "
        "device-words cache (round 4); gather path serves small allowLists "
        "below flatSearchCutoff"
    )
    rows["filtered_scaling_cpu"] = frow
    _merge_matrix(rows)

    # -- row 5: BM25 keyword search (host MaxScore + device dense rows) ---
    n_b = int(os.environ.get("BENCH_BM25_N", 500_000))
    log(f"cpu matrix: BM25 (n={n_b} docs, 40 terms/doc)...")
    brow = dict(common)
    brow.update(_bm25_row(n_b))
    brow["provenance"] = (
        "BM25F keyword search at serving steady state: MaxScore/WAND-pruned "
        "vectorized term-at-a-time scoring over fixed-stride postings "
        "decode, big-endian pre-sorted subkeys, generation-cached "
        "length/posting tables (round 5 — 13x the round-4 engine at 8 "
        "terms/500k docs; round 4 itself was 66x the round-3 Python loop). "
        "*_device rows: the dense-row device engine "
        "(inverted/bm25_device.py) on the same shard — per-query device "
        "round trips included, rows cached per write generation. NOTE: at "
        "n=500k on the 1-core CPU backend the zipf sweep's ~1 GB row "
        "working set exceeds the row-cache budget "
        "(WEAVIATE_TPU_BM25_ROW_CACHE_MB) and thrashes — the host engine "
        "is the right default there; the device lane targets chip HBM, "
        "where the budget fits hot-term sets and each dispatch replaces a "
        "relay round trip")
    rows["bm25_cpu"] = brow
    _merge_matrix(rows)

    # -- row 5b: batched hybrid (2 dispatches for Q slots vs 2Q) ----------
    log("cpu matrix: hybrid solo vs batched (n=20k, d=64)...")
    hrow = dict(common)
    hrow.update(_hybrid_batch_row())
    hrow["provenance"] = (
        "hybrid search, 64 slots alpha=0.5: per-slot legacy path (2 device "
        "dispatches per query) vs the round-5 batched lane (one async dense "
        "kNN dispatch overlapped with one keyword selection-matrix matmul "
        "for the whole group; fusion host-side per slot)")
    rows["hybrid_batch_cpu"] = hrow
    _merge_matrix(rows)

    # -- row 6: restart replay (vector-log bulk replay, commit 6d39c68) ---
    n_r = 50_000
    log(f"cpu matrix: restart replay (n={n_r})...")
    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.tpu import TpuVectorIndex

    rdir = tempfile.mkdtemp(prefix="benchreplay")
    try:
        cfg = vi.HnswUserConfig.from_dict({"distance": "l2-squared"}, "hnsw_tpu")
        idx = TpuVectorIndex(cfg, rdir, persist=True)
        rvecs = make_data(n_r, DIM, rng)
        idx.add_batch(np.arange(n_r), rvecs)
        idx.flush()
        del idx
        t0 = time.perf_counter()
        idx2 = TpuVectorIndex(cfg, rdir, persist=True)
        idx2.post_startup()
        replay_s = time.perf_counter() - t0
        assert idx2.live == n_r, f"replay lost rows: {idx2.live} != {n_r}"
        del idx2
    finally:
        import shutil

        shutil.rmtree(rdir, ignore_errors=True)
    row = dict(common)
    row.update({
        "n": n_r,
        "replay_seconds": round(replay_s, 2),
        "vecs_per_s": round(n_r / replay_s, 1),
        "provenance": (
            "vector-log bulk replay (commits b7e608e, 6d39c68: vectorized "
            "decode + bulk staged adds)"
        ),
    })
    rows["restart_replay_cpu"] = row
    data = _merge_matrix(rows)
    log(f"wrote {MATRIX_FILE} ({len(data) - 1} rows)")
    print(json.dumps({
        "metric": "cpu-backend artifact matrix (backend: cpu — host-path "
                  "claims, not TPU serving numbers)",
        "value": rows["grpc_batch256_cpu"]["p50_ms"],
        "unit": "ms p50 per 256-query gRPC batch",
        "vs_baseline": 0,
        "rows": sorted(rows.keys()),
    }))
    _gate_exit()


def _probe_device(timeout_s: Optional[int] = None) -> None:
    """Fail fast with a diagnosis when the TPU relay is wedged: a hung
    device claim would otherwise block the whole bench until the caller's
    timeout with no explanation. The probe runs in a subprocess because a
    hung PJRT init cannot be interrupted in-process.

    Bounded: BENCH_PROBE_TIMEOUT_S (default 60 — BENCH_r05 showed 180 s of
    hang buys no extra signal; a healthy claim completes in seconds). Exits
    rc=3, the bench's DISTINCT unreachable-device code (rc=4 is the perf
    regression gate), so drivers can tell infrastructure failure from a
    benchmark result without parsing logs."""
    import subprocess
    import sys as _sys

    import jax

    if timeout_s is None:
        timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", 60))
    if (jax.config.jax_platforms or "").startswith("cpu"):
        return  # CPU smoke runs need no relay probe
    code = "import jax; x = jax.numpy.ones((8, 8)); (x @ x).block_until_ready(); print('ok')"
    try:
        proc = subprocess.run([_sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=timeout_s)
        if proc.returncode == 0 and "ok" in proc.stdout:
            return
        detail = (proc.stderr or proc.stdout)[-500:]
    except subprocess.TimeoutExpired:
        detail = f"device claim still hung after {timeout_s}s"
    log(f"FATAL: TPU device unreachable ({detail}); refusing to hang — "
        "this is an infrastructure failure, not a benchmark result (rc=3)")
    _restore_live_rows()
    # preserve the evidence before dying: whatever perf/quality/memory
    # window state (or post-App recent_summaries stashes) this process
    # still holds goes into one incident bundle — the post-mortem
    # BENCH_r02-r05 never left behind (ROADMAP standing chore). Never
    # blocks the exit: emergency_dump is exception-proof by contract.
    from weaviate_tpu.monitoring import incidents as _incidents

    bundle = _incidents.emergency_dump(
        "unreachable device at bench probe (rc=3)",
        detail={"probe_detail": detail, "timeout_s": timeout_s})
    if bundle:
        log(f"incident bundle preserved: {bundle}")
    raise SystemExit(3)


def _parse_args(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="weaviate-tpu bench. Default: the headline batched-kNN "
        "run (env-driven, see module docstring). With --clients N: a "
        "closed-loop SERVING benchmark through the real gRPC stack — N "
        "concurrent single-query clients — measuring QPS/p50/p99/recall "
        "with the cross-request query coalescer on, off, or both.")
    p.add_argument("--clients", type=int, default=0,
                   help="closed-loop client threads (0 = headline bench)")
    p.add_argument("--readers", type=int, default=0,
                   help="closed-loop READ-SCALING mode (direct index path, "
                        "no gRPC): sweep 1/4/16/64 reader threads (plus "
                        "this value) against one index, snapshot read "
                        "plane vs the pre-PR single-lock serialization, "
                        "into the bench_matrix reader_scaling row")
    p.add_argument("--mesh-scale", action="store_true",
                   help="MESH-SCALING A/B (direct index path): the same "
                        "corpus on one TpuVectorIndex device vs sharded "
                        "across the 8-device MeshVectorIndex, driven with "
                        "coalesced-width batches through the two-phase "
                        "enqueue/finalize pipeline at depth 2, into the "
                        "bench_matrix mesh_scaling row (BENCH_BACKEND=cpu "
                        "uses the 8-virtual-device CPU mesh)")
    p.add_argument("--coalesce", choices=("on", "off", "both"),
                   default="both",
                   help="query coalescer state for the serving run")
    p.add_argument("--fused", choices=("on", "off", "both"),
                   default="on",
                   help="fused device dispatch (device-side slot->doc "
                        "translation, index/tpu.py) for the serving run; "
                        "'both' additionally commits a fused-vs-staged A/B "
                        "row (phase shares, duty cycle, online recall) into "
                        "bench_matrix.json serving_fused_*")
    p.add_argument("--ivf", choices=("on", "off", "both"), default=None,
                   help="IVF partition-pruned scan A/B (index/tpu.py + "
                        "ops/ivf.py, ROADMAP item 3): closed-loop batched "
                        "kNN on the SHARD serving path (direct, no gRPC — "
                        "the scan-bound regime where pruning is the "
                        "lever), with the shadow recall auditor sampling "
                        "live dispatches for online_recall. `both` "
                        "measures flat vs probed under identical load and "
                        "commits QPS, recall@10, online_recall, and "
                        "probed_fraction into the bench_matrix ivf_scan_* "
                        "row. Knobs: BENCH_IVF_{N,DIM,CLIENTS,BATCH,"
                        "SECONDS,WARMUP,NLIST,TOP_P,PCA_DIM,AUDIT_RATE}")
    p.add_argument("--quant", choices=("exact", "pq8", "pq4-funnel", "all"),
                   default=None,
                   help="quantization-ladder A/B (ops/pq4.py + index/"
                        "tpu.py): closed-loop batched kNN on the SHARD "
                        "serving path comparing the exact scan, the 8-bit "
                        "codes tier, and the 4-bit Quick-ADC three-stage "
                        "funnel (nibble scan -> 8-bit re-rank -> exact "
                        "rescore) under identical load, with the shadow "
                        "recall auditor sampling live dispatches for "
                        "online_recall and code bytes/vector read from "
                        "the memory ledger. `all` commits QPS, recall@10, "
                        "online_recall, and funnel survivor counts into "
                        "the bench_matrix quant_ladder_* row. Knobs: "
                        "BENCH_QUANT_{N,DIM,SEGMENTS,CLIENTS,BATCH,"
                        "SECONDS,WARMUP,AUDIT_RATE}")
    p.add_argument("--overload", type=int, default=0,
                   help="closed-loop OVERLOAD mode: N client threads, each "
                        "request under a tight deadline "
                        "(BENCH_OVERLOAD_DEADLINE_MS, default 75) against a "
                        "deliberately undersized admission queue "
                        "(BENCH_OVERLOAD_MAX_QUEUED_ROWS, default 64) — "
                        "records goodput (successes inside the deadline), "
                        "shed rate, and p99-within-deadline into the "
                        "bench_matrix overload row. Optional fault storm "
                        "via BENCH_OVERLOAD_FAULTS (a FAULT_INJECTION "
                        "spec, e.g. "
                        "'index.tpu.dispatch:device_error:times=inf:p=0.2')")
    p.add_argument("--tenants", type=int, default=0,
                   help="closed-loop FAIRNESS mode: one saturating tenant "
                        "vs N-1 light tenants through the real gRPC stack "
                        "(x-tenant-id metadata), proving the light tenants' "
                        "p99 isolation bound under the abusive one. Phase "
                        "1 measures each light tenant SOLO (no abuser); "
                        "phase 2 adds the abuser with the remaining "
                        "--clients budget. Records per-tenant goodput/p99/"
                        "shed-rate into the bench_matrix fairness row. "
                        "Optional chaos via BENCH_FAIRNESS_FAULTS (a "
                        "FAULT_INJECTION spec, e.g. "
                        "'serving.coalescer.admit:stall:times=inf:p=0.05')")
    p.add_argument("--controllers", choices=("on", "off", "both"),
                   default="off",
                   help="self-tuning control plane (serving/controller.py) "
                        "state for the --overload / --tenants storm "
                        "modes: on/off apply to the run; `both` measures "
                        "adaptive vs static under the same storm and "
                        "writes the comparison into the bench_matrix row")
    p.add_argument("--zipf", type=float, nargs="?", const=1.1, default=None,
                   help="skew the light tenants' traffic zipf(a) across "
                        "tenant ids (default a=1.1 when given bare) "
                        "instead of uniform")
    p.add_argument("--serve-n", type=int,
                   default=int(os.environ.get("BENCH_SERVE_N", 50_000)),
                   help="objects imported for the serving run")
    p.add_argument("--serve-dim", type=int,
                   default=int(os.environ.get("BENCH_SERVE_DIM", 64)))
    p.add_argument("--serve-seconds", type=float,
                   default=float(os.environ.get("BENCH_SERVE_SECONDS", 6.0)),
                   help="measured window per mode (after warmup)")
    p.add_argument("--serve-warmup", type=float,
                   default=float(os.environ.get("BENCH_SERVE_WARMUP", 2.5)),
                   help="untimed warmup (jit-compiles the padding buckets)")
    return p.parse_args(argv)


def _trace_phase_breakdown(tracer) -> Optional[dict]:
    """Per-request phase percentiles from the serving run's trace ring:
    queue-wait / device / hydrate p50+p99 (ms), summed per request across
    its dispatch spans (a retried request counts both dispatches — that IS
    its cost). None when tracing was off or nothing was sampled."""
    if tracer is None:
        return None
    qw: list[float] = []
    dev: list[float] = []
    hyd: list[float] = []
    for doc in tracer.snapshot():
        tq = td = th = 0.0
        found = False
        stack = [doc["root"]]
        while stack:
            s = stack.pop()
            if s.get("name") == "dispatch":
                found = True
                a = s.get("attrs", {})
                tq += float(a.get("queue_wait_ms", 0.0))
                td += float(a.get("device_ms", 0.0))
                th += sum(float(c.get("duration_ms", 0.0))
                          for c in s.get("children", [])
                          if c.get("name") == "hydrate")
            stack.extend(s.get("children", []))
        if found:
            qw.append(tq)
            dev.append(td)
            hyd.append(th)
    if not qw:
        return None

    def pct(vals: list[float]) -> dict:
        arr = np.asarray(vals, np.float64)
        return {"p50_ms": round(float(np.percentile(arr, 50)), 3),
                "p99_ms": round(float(np.percentile(arr, 99)), 3)}

    return {"sampled_requests": len(qw), "queue_wait": pct(qw),
            "device": pct(dev), "hydrate": pct(hyd)}


def run_overload_bench(args, rng):
    """Closed-loop OVERLOAD mode (robustness satellite): N clients hammer
    the gRPC stack, every request under a tight server-side deadline
    (x-request-timeout-ms metadata), against a deliberately undersized
    admission queue — the saturation regime where a serving stack is
    judged on tail behavior, not steady-state QPS. Records GOODPUT
    (successes that finished inside the deadline), the shed rate
    (RESOURCE_EXHAUSTED + retry hint), the deadline-miss rate, and
    p99-within-deadline into the bench_matrix `overload_{cpu,tpu}` row.
    BENCH_OVERLOAD_FAULTS (a FAULT_INJECTION spec) adds a deterministic
    device-fault storm on top, exercising the breaker + host fallback
    under load.

    --controllers on|off|both toggles the self-tuning control plane
    (serving/controller.py) for the run; `both` measures one run per
    mode against identical config/data and writes the adaptive-vs-static
    comparison into the row — the brownout ladder + adaptive budgets
    must beat (or shed strictly earlier than) the static knobs under the
    same storm. The shadow auditor rides along in both modes so the
    recall-guarded budget controller has its signal and the row carries
    proof the online recall EWMA never crossed the configured floor."""
    n, dim = args.serve_n, args.serve_dim
    clients = args.overload
    deadline_ms = float(os.environ.get("BENCH_OVERLOAD_DEADLINE_MS", 75.0))
    max_rows = int(os.environ.get("BENCH_OVERLOAD_MAX_QUEUED_ROWS", 64))
    fault_spec = os.environ.get("BENCH_OVERLOAD_FAULTS", "")
    modes = {"on": [True], "off": [False],
             "both": [False, True]}[args.controllers]
    log(f"overload bench: n={n} dim={dim} clients={clients} "
        f"deadline={deadline_ms}ms max_queued_rows={max_rows} "
        f"faults={fault_spec or 'none'} controllers={args.controllers}")
    import jax

    if os.environ.get("BENCH_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        _probe_device()
    vecs = make_data(n, dim, rng)
    pool_q = vecs[rng.integers(0, n, 256)] + 0.05 * rng.standard_normal(
        (256, dim), dtype=np.float32)
    rows = {}
    for controllers_on in modes:
        key = "on" if controllers_on else "off"
        log(f"  overload run: controllers {key}")
        rows[key] = _overload_once(
            args, vecs, pool_q, n, dim, clients, deadline_ms,
            max_rows, fault_spec, controllers_on)
    # the matrix row leads with the static (off) run when both were
    # measured (back-compat with the PR-5 row shape); the adaptive
    # run and the comparison ride alongside
    row = dict(rows.get("off") or rows["on"])
    row["controllers"] = args.controllers
    if "on" in rows and "off" in rows:
        on, off = rows["on"], rows["off"]
        row["controllers_on"] = on
        row["adaptive_vs_static"] = {
            "goodput_qps": [off["goodput_qps"], on["goodput_qps"]],
            "p99_within_deadline_ms": [
                off["p99_within_deadline_ms"],
                on["p99_within_deadline_ms"]],
            "shed_rate": [off["shed_rate"], on["shed_rate"]],
            "deadline_miss_rate": [off["deadline_miss_rate"],
                                   on["deadline_miss_rate"]],
        }
    log(f"  overload: {row}")
    plat = jax.devices()[0].platform
    backend = "tpu-v5e" if plat in ("tpu", "axon") else "cpu"
    suffix = "cpu" if backend == "cpu" else "tpu"
    out_row = {"backend": backend, "round": 6,
               "date": time.strftime("%Y-%m-%d"), **row}
    _merge_matrix({f"overload_{suffix}": out_row})
    print(json.dumps({
        "metric": (
            f"closed-loop goodput under overload ({clients} clients, "
            f"deadline {deadline_ms:.0f}ms, queue cap {max_rows} rows, "
            f"n={n}, d={dim}, backend {backend}, controllers "
            f"{args.controllers})"),
        "value": row["goodput_qps"],
        "unit": "qps-within-deadline",
        "vs_baseline": 0,
        "row": out_row,
    }))
    _gate_exit()


def _overload_once(args, vecs, pool_q, n, dim, clients, deadline_ms,
                   max_rows, fault_spec, controllers_on):
    """One measured overload run (fresh App/server/data dir per mode so
    the controllers-on/off comparison shares nothing but the host)."""
    import shutil
    import tempfile
    import threading
    import uuid as uuidlib

    import grpc

    from weaviate_tpu.config import Config
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server import App
    from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

    cfg = Config()
    cfg.coalescer.enabled = True
    cfg.coalescer.max_queued_rows = max_rows
    cfg.coalescer.wait_timeout_s = max(deadline_ms / 1000.0 * 4, 2.0)
    cfg.robustness.breaker_reset_ms = 250.0
    # the shadow auditor rides in BOTH modes (identical observability
    # cost either way): it is the recall-guard signal for the budget
    # controller, and the row proves the floor held
    cfg.quality.audit_sample_rate = float(
        os.environ.get("BENCH_AUDIT_SAMPLE_RATE", 0.15))
    cfg.quality.alert_min_samples = 5
    if controllers_on:
        cfg.controller.enabled = True
        cfg.controller.tick_s = float(
            os.environ.get("BENCH_CONTROLLER_TICK_S", 0.25))
        cfg.controller.hold_ticks = 2
        cfg.controller.recall_min_samples = 5
    # incident bundles must OUTLIVE the bench's throwaway data dir (the
    # finally rmtree's it): route them to the driver's INCIDENT_DIR, else
    # beside the bench artifacts
    cfg.incidents.dir = os.environ.get("INCIDENT_DIR") or "./incidents"
    if fault_spec:
        cfg.robustness.fault_injection = fault_spec
        cfg.robustness.fault_injection_seed = 17
    data_dir = tempfile.mkdtemp(prefix="benchoverload")
    app = srv = None
    try:
        app = App(config=cfg, data_path=data_dir)
        app.schema.add_class({
            "class": "Serve", "vectorIndexType": "hnsw_tpu",
            "vectorIndexConfig": {"distance": "l2-squared"},
            "properties": [{"name": "tag", "dataType": ["text"]}],
        })
        idx = app.db.get_index("Serve")
        for s in range(0, n, 10_000):
            idx.put_batch([
                StorObj(class_name="Serve",
                        uuid=str(uuidlib.UUID(int=i + 1)),
                        properties={"tag": f"t{i % 16}"}, vector=vecs[i])
                for i in range(s, min(s + 10_000, n))])
        srv = GrpcServer(app, port=0, max_workers=max(32, clients + 8))
        srv.start()
        addr = f"127.0.0.1:{srv.port}"
        reqs = [pb.SearchRequest(
            class_name="Serve", limit=K,
            near_vector=pb.NearVectorParams(vector=q.tolist()))
            for q in pool_q]
        meta = (("x-request-timeout-ms", f"{deadline_ms:.0f}"),)
        stop = threading.Event()
        counting = threading.Event()
        ok_lat: list[list[float]] = [[] for _ in range(clients)]
        counts = [dict(ok=0, shed=0, deadline=0, error=0, hung=0)
                  for _ in range(clients)]

        def loop(tid: int) -> None:
            cl = SearchClient(addr)
            lrng = np.random.default_rng(2000 + tid)
            try:
                while not stop.is_set():
                    qi = int(lrng.integers(0, len(reqs)))
                    t0 = time.perf_counter()
                    outcome = "ok"
                    try:
                        # generous transport timeout: the SERVER must
                        # resolve the request (shed/expire/serve); a
                        # client-side transport timeout = a hung request
                        cl.search(reqs[qi], timeout=30.0, metadata=meta)
                    except grpc.RpcError as e:
                        code = e.code()
                        if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                            outcome = "shed"
                        elif code == grpc.StatusCode.DEADLINE_EXCEEDED:
                            outcome = "deadline"
                        else:
                            outcome = "error"
                    except Exception:  # noqa: BLE001 — outcome accounting
                        outcome = "error"
                    dt = time.perf_counter() - t0
                    if dt > 25.0:
                        outcome = "hung"  # the zero-hung-requests gate
                    if counting.is_set():
                        counts[tid][outcome] += 1
                        if outcome == "ok":
                            ok_lat[tid].append(dt)
            finally:
                cl.close()

        threads = [threading.Thread(target=loop, args=(i,), daemon=True)
                   for i in range(clients)]
        for t in threads:
            t.start()
        time.sleep(args.serve_warmup)
        counting.set()
        t0 = time.perf_counter()
        time.sleep(args.serve_seconds)
        counting.clear()
        elapsed = time.perf_counter() - t0
        stop.set()
        for t in threads:
            t.join(timeout=30)
        tot = {k: sum(c[k] for c in counts)
               for k in ("ok", "shed", "deadline", "error", "hung")}
        flat = np.array([x for per in ok_lat for x in per], np.float64)
        within = flat[flat <= deadline_ms / 1000.0]
        requests = int(sum(tot.values()))
        st = app.coalescer.stats() if app.coalescer is not None else {}
        row = {
            "clients": clients, "n": n, "dim": dim, "k": K,
            "deadline_ms": deadline_ms, "max_queued_rows": max_rows,
            "faults": fault_spec or None,
            "duration_s": round(elapsed, 2),
            "requests": requests,
            "goodput_qps": round(within.size / elapsed, 1),
            "shed_rate": round(tot["shed"] / requests, 4) if requests else None,
            "deadline_miss_rate": round(
                (tot["deadline"] + (flat.size - within.size)) / requests, 4)
            if requests else None,
            "error_rate": round(tot["error"] / requests, 4) if requests else None,
            "hung_requests": tot["hung"],
            "p50_ok_ms": round(float(np.percentile(flat, 50)) * 1000, 2)
            if flat.size else None,
            "p99_within_deadline_ms": round(
                float(np.percentile(within, 99)) * 1000, 2)
            if within.size else None,
            "outcomes": tot,
            "shed": st.get("shed"),
            "breaker_state": (app.breaker.state()
                              if app.breaker is not None else None),
        }
        if app.quality_auditor is not None:
            # recall-floor proof: the budget controller steers the PQ
            # candidate cap against this EWMA — the row records it never
            # crossed the configured floor during the storm
            app.quality_auditor.drain(timeout_s=10.0)
            ewmas = app.quality_auditor.tier_ewmas()
            vals = [ew for ew, cnt in ewmas.values() if cnt > 0]
            row["online_recall_ewma_min"] = (round(min(vals), 4)
                                             if vals else None)
            row["recall_floor"] = cfg.controller.recall_floor
        if app.control_plane is not None:
            cs = app.control_plane.summary()
            row["controller"] = {
                "brownout_stage": cs["controllers"]["brownout"]["stage"],
                "rescore_r_cap":
                    cs["controllers"]["budget"]["rescore_r_cap"],
                "actuations": cs["actuations"],
                "recent_actuations": cs["recent_actuations"][-8:],
            }
        return row
    finally:
        # this run's evidence bundle rides out BEFORE App.shutdown
        # unconfigures the planes: journal tail (sheds, breaker flaps,
        # injected faults, controller actuations), /debug/slo burn
        # state, perf/memory windows — one bundle per measured mode
        from weaviate_tpu.monitoring import incidents as _incidents

        _incidents.emergency_dump(
            "overload storm run complete (controllers "
            f"{'on' if controllers_on else 'off'})")
        if srv is not None:
            srv.stop()
        if app is not None:
            app.shutdown()
        shutil.rmtree(data_dir, ignore_errors=True)


def run_fairness_bench(args, rng):
    """Closed-loop FAIRNESS mode (multi-tenant tentpole): one saturating
    tenant hammers the serving stack while N-1 light tenants send modest
    traffic, all through the real gRPC stack with ``x-tenant-id``
    metadata. Phase 1 measures the light tenants SOLO (their baseline
    p99); phase 2 adds the abusive tenant with the rest of the --clients
    budget. The isolation claim under weighted-fair admission: each light
    tenant's p99 stays within 2x of its solo p99 and its shed rate stays
    under 5%, while the ABUSIVE tenant absorbs the shedding
    (tenant_budget / queue_full land on its label). Per-tenant goodput/
    p99/shed-rate go into the bench_matrix ``fairness_{cpu,tpu}`` row.
    BENCH_FAIRNESS_FAULTS (a FAULT_INJECTION spec) adds a deterministic
    chaos storm on top — e.g. admission stalls at
    serving.coalescer.admit."""
    import shutil
    import tempfile
    import threading
    import uuid as uuidlib

    import jax

    if os.environ.get("BENCH_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        _probe_device()
    import grpc

    from weaviate_tpu.config import Config
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server import App
    from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

    # the fairness regime needs the ADMISSION QUEUE to be the bottleneck:
    # per-dispatch device cost must be small enough that the host is not
    # compute-saturated by light traffic alone (then both phases just
    # measure CPU starvation and no admission policy can change the
    # ratio). Default the corpus to a size this host serves with
    # headroom; BENCH_FAIRNESS_N overrides for bigger hosts/chips.
    n = min(args.serve_n, int(os.environ.get("BENCH_FAIRNESS_N", 10_000)))
    dim = args.serve_dim
    n_tenants = max(int(args.tenants), 2)
    clients = args.clients or 64
    deadline_ms = float(os.environ.get("BENCH_FAIRNESS_DEADLINE_MS", 1000.0))
    # the queue cap is deliberately sized BELOW the abusive tenant's
    # in-flight row count (closed loop: ~1 row per abusive thread), and
    # the per-tenant fraction keeps its admitted backlog to a couple of
    # dispatches — the regime where admission-layer fairness, not raw
    # host capacity, decides the light tenants' tail
    max_rows = int(os.environ.get("BENCH_FAIRNESS_MAX_QUEUED_ROWS", 64))
    fraction = float(os.environ.get("BENCH_FAIRNESS_TENANT_FRACTION", 0.0625))
    # per-tenant front-door concurrency bound: a tenant's excess parallel
    # connections shed before any per-request work — the queue bounds a
    # tenant's ROWS, this bounds the host-side request-handling the
    # tenant can occupy (57 handler threads of one tenant would starve a
    # small host below the admission layer). Scaled to the host: roughly
    # one concurrent in-server request per tenant per two cores.
    max_conc = int(os.environ.get(
        "BENCH_FAIRNESS_MAX_CONCURRENT",
        max(1, (os.cpu_count() or 1) // 2)))
    # p99-of-p99 comparisons need samples: fairness windows default
    # longer than the generic serving modes' (a 6 s window gives a zipf
    # tail tenant a p99 that is just its max sample)
    measure_s = float(os.environ.get(
        "BENCH_FAIRNESS_SECONDS", max(args.serve_seconds, 15.0)))
    warm_s = max(args.serve_warmup, 4.0)
    think_s = float(os.environ.get("BENCH_FAIRNESS_THINK_MS", 10.0)) / 1000.0
    fault_spec = os.environ.get("BENCH_FAIRNESS_FAULTS", "")
    light = [f"light-{i}" for i in range(1, n_tenants)]
    ABUSER = "abusive-0"
    n_light_threads = min(len(light), 16)
    n_abuse_threads = max(clients - n_light_threads, 4)
    log(f"fairness bench: n={n} dim={dim} tenants={n_tenants} "
        f"(1 abusive + {len(light)} light) zipf={args.zipf} "
        f"threads={n_light_threads} light / {n_abuse_threads} abusive "
        f"deadline={deadline_ms}ms max_queued_rows={max_rows} "
        f"faults={fault_spec or 'none'}")
    vecs = make_data(n, dim, rng)
    pool_q = vecs[rng.integers(0, n, 256)] + 0.05 * rng.standard_normal(
        (256, dim), dtype=np.float32)

    cfg = Config()
    cfg.coalescer.enabled = True
    cfg.coalescer.max_queued_rows = max_rows
    cfg.coalescer.wait_timeout_s = max(deadline_ms / 1000.0 * 4, 2.0)
    cfg.tenancy.max_queued_rows_fraction = fraction
    cfg.tenancy.max_concurrent_requests = max_conc
    # the per-tenant cap floors at max_request_rows (a budget below one
    # admissible request would deadlock that tenant); this workload is
    # single-query requests, so lower the per-request bound to let the
    # fraction bite — the abusive tenant's head-of-line dispatch is then
    # a few rows, not a full direct-path-width batch
    cfg.coalescer.max_request_rows = max(int(max_rows * fraction), 2)
    # bundles must outlive the throwaway data dir (the overload twin)
    cfg.incidents.dir = os.environ.get("INCIDENT_DIR") or "./incidents"
    # --controllers on: the self-tuning control plane runs for the WHOLE
    # bench (both phases); `both` keeps the App static and engages a
    # plane only for the extra storm re-run below, so the on/off storms
    # share one data import and one solo baseline
    cfg.controller.tick_s = float(
        os.environ.get("BENCH_CONTROLLER_TICK_S", 0.25))
    cfg.controller.hold_ticks = 2
    # per-tenant rate quota (controller 4) — the one controller BUILT
    # for an abusive tenant: the front-door gate caps its concurrency
    # but not its request rate, so its refusal churn and its admitted
    # dispatches still tax the box. A 4 QPS quota sits under the
    # abuser's gate-limited throughput (≈8 QPS on the 2-core CPU host)
    # and far over a light tenant's storm rate (≈1.6 QPS) — the quota
    # binds ONLY the abuser, shedding `tenant_rate` cheaply before any
    # queue state with Retry-After = time-to-next-token
    cfg.controller.tenant_rate_qps = float(
        os.environ.get("BENCH_TENANT_RATE_QPS", 4.0))
    if args.controllers == "on":
        cfg.controller.enabled = True
    if fault_spec:
        cfg.robustness.fault_injection = fault_spec
        cfg.robustness.fault_injection_seed = 23
    data_dir = tempfile.mkdtemp(prefix="benchfairness")
    app = srv = None
    try:
        app = App(config=cfg, data_path=data_dir)
        app.schema.add_class({
            "class": "Serve", "vectorIndexType": "hnsw_tpu",
            "vectorIndexConfig": {"distance": "l2-squared"},
            "properties": [{"name": "tag", "dataType": ["text"]}],
        })
        idx = app.db.get_index("Serve")
        for s in range(0, n, 10_000):
            idx.put_batch([
                StorObj(class_name="Serve",
                        uuid=str(uuidlib.UUID(int=i + 1)),
                        properties={"tag": f"t{i % 16}"}, vector=vecs[i])
                for i in range(s, min(s + 10_000, n))])
        srv = GrpcServer(app, port=0,
                         max_workers=max(32, clients + 8))
        srv.start()
        addr = f"127.0.0.1:{srv.port}"
        reqs = [pb.SearchRequest(
            class_name="Serve", limit=K,
            near_vector=pb.NearVectorParams(vector=q.tolist()))
            for q in pool_q]

        # deterministic prewarm: the first dispatch of each padded shape
        # pays the jit compile (seconds on the CPU backend) — that cost
        # must not land inside EITHER measured phase, or the solo
        # baseline is compile noise and every ratio is fiction. Merged
        # lanes dispatch at EVERY padding bucket up to max_batch's floor,
        # so warm each bucket via same-width direct batches (the jit
        # cache keys on (padded rows, k) — a direct 8-wide dispatch
        # compiles the exact shape an 8-row merged lane uses).
        warm_cl = SearchClient(addr)
        try:
            for i in range(10):
                try:
                    warm_cl.search(reqs[i % len(reqs)], timeout=120.0)
                except Exception:  # noqa: BLE001 — warmup best-effort
                    pass
            for width in (2, 4, 8, 16, 32, 64):
                breq = pb.BatchSearchRequest(requests=[
                    pb.SearchRequest(
                        class_name="Serve", limit=K,
                        near_vector=pb.NearVectorParams(
                            vector=pool_q[j % len(pool_q)].tolist()))
                    for j in range(width)])
                for _ in range(2):
                    try:
                        warm_cl.batch_search(breq, timeout=120.0)
                    except Exception:  # noqa: BLE001 — warmup best-effort
                        pass
        finally:
            warm_cl.close()

        def tenant_stats():
            return dict(ok=0, shed=0, deadline=0, error=0, hung=0, lat=[])

        def run_phase(with_abuser: bool) -> dict:
            stop = threading.Event()
            counting = threading.Event()
            acc_lock = threading.Lock()
            acc: dict = {}

            def record(tenant, outcome, dt):
                with acc_lock:
                    st = acc.setdefault(tenant, tenant_stats())
                    st[outcome] += 1
                    if outcome == "ok":
                        st["lat"].append(dt)

            def one(cl, lrng, tenant):
                """-> the server's retry-after hint in seconds when the
                request was shed, else 0.0."""
                qi = int(lrng.integers(0, len(reqs)))
                meta = (("x-tenant-id", tenant),
                        ("x-request-timeout-ms", f"{deadline_ms:.0f}"))
                t0 = time.perf_counter()
                outcome, retry_after = "ok", 0.0
                try:
                    # generous transport timeout: the SERVER must resolve
                    # (serve/shed/expire); a transport timeout = a hang
                    cl.search(reqs[qi], timeout=30.0, metadata=meta)
                except grpc.RpcError as e:
                    code = e.code()
                    if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                        outcome = "shed"
                        retry_after = 0.02
                        try:
                            md = {k: v for k, v in
                                  (e.trailing_metadata() or ())}
                            retry_after = float(
                                md.get("retry-after-s", retry_after))
                        except Exception:  # noqa: BLE001 — hint optional
                            pass
                    elif code == grpc.StatusCode.DEADLINE_EXCEEDED:
                        outcome = "deadline"
                    else:
                        outcome = "error"
                except Exception:  # noqa: BLE001 — outcome accounting
                    outcome = "error"
                dt = time.perf_counter() - t0
                if dt > 25.0:
                    outcome = "hung"  # the zero-hung-requests gate
                if counting.is_set():
                    record(tenant, outcome, dt)
                return retry_after

            def light_loop(tid: int) -> None:
                # one client session pinned to one light tenant; --zipf
                # skews the PER-TENANT request rate (think time scales
                # with the tenant's zipf rank) instead of sampling the
                # tenant per request — sampling would let two light
                # threads collide on one tenant id and muddy per-tenant
                # accounting (and concurrency budgets) with phantom
                # parallelism no real light tenant has
                cl = SearchClient(addr)
                lrng = np.random.default_rng(3000 + tid)
                tenant = light[tid % len(light)]
                think = think_s * ((tid % len(light) + 1) ** args.zipf
                                   if args.zipf else 1.0)
                try:
                    while not stop.is_set():
                        one(cl, lrng, tenant)
                        time.sleep(think)
                finally:
                    cl.close()

            def abuse_loop(tid: int) -> None:
                # saturating but PROTOCOL-CONFORMANT: no think time, and
                # on a shed it honors the server's Retry-After hint
                # (bounded) — the saturation the fairness layer is built
                # for. A client that ignores Retry-After in a hot retry
                # loop is a connection-level DoS (rate limiting's job),
                # not an admission-fairness workload.
                cl = SearchClient(addr)
                lrng = np.random.default_rng(9000 + tid)
                try:
                    while not stop.is_set():
                        ra = one(cl, lrng, ABUSER)
                        if ra > 0.0:
                            # back off at least the server's hint (a
                            # client may wait LONGER than Retry-After —
                            # doubling with jitter is the conformant
                            # congestion response), floored at 20 ms so a
                            # sub-ms hint can't license a hot retry loop
                            time.sleep(min(max(2.0 * ra, 0.02), 2.0)
                                       * (0.75 + 0.5 * lrng.random()))
                finally:
                    cl.close()

            threads = [threading.Thread(target=light_loop, args=(i,),
                                        daemon=True)
                       for i in range(n_light_threads)]
            if with_abuser:
                threads += [threading.Thread(target=abuse_loop, args=(i,),
                                             daemon=True)
                            for i in range(n_abuse_threads)]
            for t in threads:
                t.start()
            time.sleep(warm_s)
            counting.set()
            t0 = time.perf_counter()
            time.sleep(measure_s)
            counting.clear()
            elapsed = time.perf_counter() - t0
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not any(t.is_alive() for t in threads), "client hung"
            out = {}
            for tenant, st in acc.items():
                lat = np.asarray(st.pop("lat"), np.float64)
                total = int(sum(st.values()))
                out[tenant] = {
                    "requests": total,
                    "goodput_qps": round(lat.size / elapsed, 2),
                    "shed_rate": round(st["shed"] / total, 4) if total else 0,
                    "p50_ms": round(float(np.percentile(lat, 50)) * 1000, 2)
                    if lat.size else None,
                    "p99_ms": round(float(np.percentile(lat, 99)) * 1000, 2)
                    if lat.size else None,
                    **st,
                }
            return out

        log("  phase 1: light tenants SOLO (baseline p99)...")
        solo = run_phase(with_abuser=False)
        log(f"  solo: { {t: v['p99_ms'] for t, v in sorted(solo.items())} }")
        log("  phase 2: + abusive tenant storm...")
        storm = run_phase(with_abuser=True)
        # snapshot the server-side counters NOW: they are cumulative, and
        # the static row's shed / server_tenants keys must not absorb the
        # controllers-on phase 3 traffic (tenant_rate sheds are impossible
        # without the plane — leaking them poisons the comparison)
        co_stats = app.coalescer.stats() if app.coalescer is not None else {}
        storm_on = plane_summary = None
        if args.controllers == "both":
            # adaptive-vs-static storm: engage a control plane against
            # the SAME App (same coalescer, same data, same solo
            # baseline) and re-run the storm; unconfigure reverts every
            # knob afterward, so nothing leaks into the row merge
            log("  phase 3: abusive storm again, controllers ON...")
            from weaviate_tpu.serving import controller as _ctl

            plane = _ctl.configure(_ctl.ControlPlane(
                config=cfg.controller, coalescer=app.coalescer,
                metrics=app.metrics, tenant_weights=cfg.tenancy.weights))
            try:
                storm_on = run_phase(with_abuser=True)
                plane_summary = plane.summary()
            finally:
                _ctl.unconfigure(plane)

        # the isolation gate: per light tenant with enough samples (a
        # zipf tail tenant with a handful of requests has no meaningful
        # p99), the storm p99 vs its own solo p99, and its shed rate
        MIN_SAMPLES = 15
        ratios = {}
        light_shed = {}
        for t in light:
            s, st = solo.get(t), storm.get(t)
            if not s or not st or s["p99_ms"] is None \
                    or st["p99_ms"] is None \
                    or min(s["requests"], st["requests"]) < MIN_SAMPLES:
                continue
            ratios[t] = round(st["p99_ms"] / max(s["p99_ms"], 1e-6), 2)
            light_shed[t] = st["shed_rate"]
        hung = sum(v.get("hung", 0) for v in storm.values()) \
            + sum(v.get("hung", 0) for v in solo.values())
        worst_ratio = max(ratios.values()) if ratios else None
        worst_shed = max(light_shed.values()) if light_shed else None
        abuse_row = storm.get(ABUSER, {})
        isolation_pass = (
            hung == 0 and worst_ratio is not None
            and worst_ratio <= 2.0
            and (worst_shed or 0.0) < 0.05)
        row = {
            "tenants": n_tenants, "zipf": args.zipf, "clients": clients,
            "n": n, "dim": dim, "k": K, "deadline_ms": deadline_ms,
            "max_queued_rows": max_rows,
            "tenant_row_cap": co_stats.get("tenant_row_cap"),
            "tenant_max_concurrent": max_conc,
            "faults": fault_spec or None,
            "light_threads": n_light_threads,
            "abusive_threads": n_abuse_threads,
            "hung_requests": hung,
            "light_p99_worst_ratio_vs_solo": worst_ratio,
            "light_p99_ratios": ratios,
            "light_shed_worst": worst_shed,
            "abusive_shed_rate": abuse_row.get("shed_rate"),
            "abusive_goodput_qps": abuse_row.get("goodput_qps"),
            "isolation_pass_2x_p99_5pct_shed": isolation_pass,
            "controllers": args.controllers,
            "solo": solo, "storm": storm,
            "server_tenants": co_stats.get("tenants"),
            "shed": co_stats.get("shed"),
        }
        if storm_on is not None:
            on_ratios = {}
            for t in light:
                s, st = solo.get(t), storm_on.get(t)
                if not s or not st or s["p99_ms"] is None \
                        or st["p99_ms"] is None \
                        or min(s["requests"], st["requests"]) < MIN_SAMPLES:
                    continue
                on_ratios[t] = round(st["p99_ms"] / max(s["p99_ms"], 1e-6),
                                     2)
            row["storm_controllers_on"] = storm_on
            row["controllers_on"] = {
                "light_p99_worst_ratio_vs_solo":
                    max(on_ratios.values()) if on_ratios else None,
                "light_p99_ratios": on_ratios,
                "abusive_shed_rate":
                    storm_on.get(ABUSER, {}).get("shed_rate"),
                "hung_requests":
                    sum(v.get("hung", 0) for v in storm_on.values()),
                "brownout_stage_final": (plane_summary["controllers"]
                                         ["brownout"]["stage"]
                                         if plane_summary else None),
                "actuations": (plane_summary["actuations"]
                               if plane_summary else None),
            }
        log(f"  fairness: worst light p99 ratio {worst_ratio} "
            f"(bound 2.0), worst light shed {worst_shed} (bound 0.05), "
            f"abusive shed {abuse_row.get('shed_rate')}, hung {hung} -> "
            f"{'PASS' if isolation_pass else 'MISS'}")
        plat = jax.devices()[0].platform
        backend = "tpu-v5e" if plat in ("tpu", "axon") else "cpu"
        suffix = "cpu" if backend == "cpu" else "tpu"
        out_row = {"backend": backend, "round": 6,
                   "date": time.strftime("%Y-%m-%d"), **row}
        _merge_matrix({f"fairness_{suffix}": out_row})
        print(json.dumps({
            "metric": (
                f"light-tenant p99 isolation under one abusive tenant "
                f"({n_tenants} tenants, {clients} clients, zipf "
                f"{args.zipf}, queue cap {max_rows} rows, backend "
                f"{backend}) — worst light p99 storm/solo ratio "
                "(bound 2.0)"),
            "value": worst_ratio,
            "unit": "x-solo-p99",
            "vs_baseline": 0,
            "row": out_row,
        }))
    finally:
        # fairness-storm twin of the overload dump above
        from weaviate_tpu.monitoring import incidents as _incidents

        _incidents.emergency_dump("fairness storm bench complete")
        if srv is not None:
            srv.stop()
        if app is not None:
            app.shutdown()
        shutil.rmtree(data_dir, ignore_errors=True)
    _gate_exit()


def run_serving_bench(args, rng):
    """Closed-loop serving QPS through the real gRPC stack (satellite of the
    query-coalescer tentpole): N client threads each issue single-query kNN
    Searches back-to-back — the 256-concurrent-users shape where
    cross-request coalescing is the QPS lever. Reports QPS, p50/p99 request
    latency, recall@10 of sampled replies vs exact GT, and (coalesce=on)
    the batch-occupancy achieved, into bench_matrix.json."""
    import shutil
    import tempfile
    import threading
    import uuid as uuidlib

    import jax

    if os.environ.get("BENCH_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        _probe_device()
    from weaviate_tpu.config import Config
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server import App
    from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

    n, dim = args.serve_n, args.serve_dim
    log(f"serving bench: n={n} dim={dim} clients={args.clients} "
        f"coalesce={args.coalesce}")
    vecs = make_data(n, dim, rng)
    pool_q = vecs[rng.integers(0, n, 256)] + 0.05 * rng.standard_normal(
        (256, dim), dtype=np.float32)
    gt = exact_gt(vecs, pool_q, K)

    def measure(coalesce_on: bool, fused_on: bool = True) -> dict:
        cfg = Config()
        cfg.coalescer.enabled = coalesce_on
        # fused device dispatch A/B lever: App applies the knob to the
        # index layer's process-wide toggle at init
        cfg.fused_dispatch_enabled = fused_on
        cfg.coalescer.window_ms = float(
            os.environ.get("BENCH_COALESCE_WINDOW_MS", 1.5))
        # re-tune hook for the dispatch pipeline now that finalize no
        # longer contends with enqueue on an index lock (snapshot reads)
        cfg.coalescer.pipeline_depth = int(
            os.environ.get("BENCH_COALESCE_PIPELINE_DEPTH", 1))
        # trace a sample of requests so the row carries a PHASE-LEVEL
        # baseline (queue-wait / device / hydrate p50+p99) next to QPS —
        # future perf PRs can see WHICH phase moved, not just the headline.
        # Sampled (default 10%) so the tracer itself stays out of the
        # measurement; ring sized to hold a full window of samples.
        cfg.tracing.enabled = True
        cfg.tracing.sample_rate = float(
            os.environ.get("BENCH_TRACE_SAMPLE_RATE", 0.1))
        cfg.tracing.ring_size = 4096
        cfg.tracing.slow_query_threshold_ms = 0.0  # no slow-log noise
        # shadow recall auditor (monitoring/quality.py): audit a sample of
        # the live serving traffic against the exact host plane so the row
        # carries an ONLINE recall estimate next to the bench's own
        # sampled-reply recall — the acceptance cross-check is that the
        # two agree within ±0.01. Sampled (default 10%) and strictly
        # subordinate (drop-not-queue, one worker), so the auditor itself
        # stays out of the measurement. BENCH_AUDIT_SAMPLE_RATE=0 disables.
        cfg.quality.audit_sample_rate = float(
            os.environ.get("BENCH_AUDIT_SAMPLE_RATE", 0.1))
        data_dir = tempfile.mkdtemp(prefix="benchserve")
        app = srv = None
        try:
            app = App(config=cfg, data_path=data_dir)
            app.schema.add_class({
                "class": "Serve", "vectorIndexType": "hnsw_tpu",
                "vectorIndexConfig": {"distance": "l2-squared"},
                "properties": [{"name": "tag", "dataType": ["text"]}],
            })
            idx = app.db.get_index("Serve")
            for s in range(0, n, 10_000):
                idx.put_batch([
                    StorObj(class_name="Serve",
                            uuid=str(uuidlib.UUID(int=i + 1)),
                            properties={"tag": f"t{i % 16}"}, vector=vecs[i])
                    for i in range(s, min(s + 10_000, n))])
            srv = GrpcServer(app, port=0,
                             max_workers=max(32, args.clients + 8))
            srv.start()
            addr = f"127.0.0.1:{srv.port}"
            reqs = [pb.SearchRequest(
                class_name="Serve", limit=K,
                near_vector=pb.NearVectorParams(vector=q.tolist()))
                for q in pool_q]
            stop = threading.Event()
            counting = threading.Event()
            lats: list[list[float]] = [[] for _ in range(args.clients)]
            samples: list[list] = [[] for _ in range(args.clients)]
            errors = [0] * args.clients

            def loop(tid: int) -> None:
                cl = SearchClient(addr)
                lrng = np.random.default_rng(1000 + tid)
                try:
                    while not stop.is_set():
                        qi = int(lrng.integers(0, len(reqs)))
                        t0 = time.perf_counter()
                        try:
                            rep = cl.search(reqs[qi])
                        except Exception:  # noqa: BLE001 — a dead client
                            # thread would silently shrink the measured
                            # pool; count the error and keep the loop alive
                            errors[tid] += 1
                            time.sleep(0.05)
                            continue
                        dt = time.perf_counter() - t0
                        if counting.is_set():
                            lats[tid].append(dt)
                            if len(samples[tid]) < 32:
                                samples[tid].append(
                                    (qi, [r.id for r in rep.results]))
                finally:
                    cl.close()

            threads = [threading.Thread(target=loop, args=(i,), daemon=True)
                       for i in range(args.clients)]
            for t in threads:
                t.start()
            time.sleep(args.serve_warmup)  # compile the padding buckets
            base = app.coalescer.stats() if app.coalescer is not None else None
            if app.tracer is not None:
                app.tracer.clear()  # phase stats cover the counted window only
            if app.perf_window is not None:
                # same discipline for the perf-attribution window: the
                # roofline/duty-cycle row fields cover the counted window
                app.perf_window.clear()
            base_audits = None
            if app.quality_auditor is not None:
                # ...and for the quality window: drain the still-queued
                # warmup audits FIRST (clear alone would let them score
                # into the counted window milliseconds later), then reset;
                # outcome counters are lifetime, so snapshot them for the
                # row's window-only deltas
                app.quality_auditor.drain(timeout_s=15.0)
                app.quality_auditor.clear()
                base_audits = app.quality_auditor.summary().get("audits", {})
            counting.set()
            t0 = time.perf_counter()
            time.sleep(args.serve_seconds)
            counting.clear()
            elapsed = time.perf_counter() - t0
            stop.set()
            for t in threads:
                t.join(timeout=30)
            flat = np.array([x for per in lats for x in per], np.float64)
            hit = tot = 0
            for per in samples:
                for qi, ids in per:
                    want = set(int(x) for x in gt[qi])
                    got = set(int(uuidlib.UUID(u).int) - 1 for u in ids)
                    hit += len(want & got)
                    tot += K
            row = {
                "clients": args.clients, "n": n, "dim": dim, "k": K,
                "coalesce": coalesce_on,
                "fused": fused_on,
                "duration_s": round(elapsed, 2),
                "requests": int(flat.size),
                "qps": round(flat.size / elapsed, 1),
                "p50_ms": round(float(np.percentile(flat, 50)) * 1000, 2)
                if flat.size else None,
                "p99_ms": round(float(np.percentile(flat, 99)) * 1000, 2)
                if flat.size else None,
                "recall@10": round(hit / tot, 4) if tot else None,
                "request_errors": int(sum(errors)),
            }
            if sum(errors):
                log(f"  WARNING: {sum(errors)} request error(s) during the "
                    "serving run — QPS/latency may understate the failure")
            if app.coalescer is not None:
                st = app.coalescer.stats()
                d = st["dispatches"] - base["dispatches"]
                row["window_ms"] = cfg.coalescer.window_ms
                row["dispatches"] = d
                if d > 0:
                    row["requests_per_dispatch"] = round(
                        (st["requests"] - base["requests"]) / d, 2)
                    row["rows_per_dispatch"] = round(
                        (st["rows"] - base["rows"]) / d, 2)
                # window-only deltas, like dispatches above: warmup-time
                # bypasses must not pollute the measured occupancy story
                row["bypass"] = {
                    k: v - base["bypass"].get(k, 0)
                    for k, v in st["bypass"].items()
                    if v - base["bypass"].get(k, 0)}
            phases = _trace_phase_breakdown(app.tracer)
            if phases is not None:
                row["trace_phases"] = phases
            if app.quality_auditor is not None:
                # the shadow auditor's online recall over the counted
                # window, cross-checked against the bench's own sampled-
                # reply recall above (the two must agree within ±0.01 —
                # they measure the same serving path two different ways)
                app.quality_auditor.drain(timeout_s=15.0)
                qs = app.quality_auditor.summary()
                row["online_recall"] = qs.get("online_recall")
                # window-only outcome deltas (counters are lifetime)
                row["online_audits"] = {
                    k: v - (base_audits or {}).get(k, 0)
                    for k, v in qs.get("audits", {}).items()}
                if row["online_recall"] is not None \
                        and row.get("recall@10") is not None:
                    row["online_recall_delta"] = round(abs(
                        row["online_recall"] - row["recall@10"]), 4)
            if app.perf_window is not None:
                # the shared-costmodel window summary (monitoring/perf.py):
                # roofline + duty cycle + per-stage shares of the
                # host-overhead ledger — the before/after baseline the
                # ROADMAP item-1/2/3 PRs measure their win against.
                # Coverage is FULL (every dispatch feeds the window;
                # trace sampling only thins trace_phases above).
                ps = app.perf_window.summary()
                if ps.get("roofline"):
                    row["roofline"] = ps["roofline"]
                if ps.get("roofline_device_busy"):
                    row["roofline_device_busy"] = ps["roofline_device_busy"]
                row["duty_cycle"] = ps.get("duty_cycle")
                row["phase_share"] = {
                    p: v.get("share_of_wall")
                    for p, v in ps.get("phases", {}).items()}
                # absolute per-dispatch stage medians too: share-of-wall
                # is queue_wait-diluted at high client counts, and the
                # fused-dispatch hop win must be readable either way
                row["phase_p50_ms"] = {
                    p: v.get("p50_ms")
                    for p, v in ps.get("phases", {}).items()}
                row["perf_tiers"] = ps.get("tiers")
                # fused-dispatch coverage + ledger-invariant violations
                # over the counted window (must be 0 violations)
                row["fused_dispatch"] = ps.get("fused")
            if getattr(app, "memory_ledger", None) is not None:
                # the byte ledger's compact block (monitoring/memory.py):
                # device/host footprint, headroom, ingest rate, COW costs
                # — the capacity baseline the ROADMAP item-1/2/3 sizing
                # changes regress against
                row["memory"] = app.memory_ledger.bench_block()
            log(f"  coalesce={'on' if coalesce_on else 'off'}: {row}")
            return row
        finally:
            if srv is not None:
                srv.stop()
            if app is not None:
                app.shutdown()
            from weaviate_tpu.index import tpu as _tpu

            _tpu.set_fused_enabled(None)  # no ambient toggle leaks out
            shutil.rmtree(data_dir, ignore_errors=True)

    fused_default = args.fused != "off"
    modes = {}
    if args.coalesce in ("off", "both"):
        modes["off"] = measure(False, fused_default)
    if args.coalesce in ("on", "both"):
        modes["on"] = measure(True, fused_default)
    plat = jax.devices()[0].platform
    backend = "tpu-v5e" if plat in ("tpu", "axon") else "cpu"
    out_row = {
        "backend": backend, "round": 6, "date": time.strftime("%Y-%m-%d"),
        "clients": args.clients, "n": n, "dim": dim, **modes,
    }
    if "on" in modes and "off" in modes and modes["off"]["qps"]:
        out_row["speedup"] = round(
            modes["on"]["qps"] / modes["off"]["qps"], 2)
    suffix = "cpu" if backend == "cpu" else "tpu"
    _merge_matrix({f"serving_coalesce_{suffix}": out_row})
    if args.fused == "both":
        # fused-vs-staged A/B at the primary coalesce setting: the fused
        # half was measured above; measure the staged (legacy host
        # slot->doc translation) control and commit the decomposition —
        # phase shares, duty cycle, online recall — so the next live chip
        # session regenerates the TPU rows with the before/after already
        # instrumented (ROADMAP standing chore)
        co = args.coalesce != "off"
        fused_row = modes["on" if co else "off"]
        staged_row = measure(co, False)

        def _hop_share(r: dict) -> float:
            ph = r.get("phase_share") or {}
            return ((ph.get("gather_hop") or 0.0)
                    + (ph.get("hydrate") or 0.0))

        def _hop_p50(r: dict) -> float:
            ph = r.get("phase_p50_ms") or {}
            return ((ph.get("gather_hop") or 0.0)
                    + (ph.get("hydrate") or 0.0))

        ab = {
            "backend": backend, "round": 6,
            "date": time.strftime("%Y-%m-%d"),
            "clients": args.clients, "n": n, "dim": dim,
            "coalesce": co,
            "fused_on": fused_row, "fused_off": staged_row,
            # the acceptance decomposition: host share of accounted wall
            # spent past the fetch (gather_hop) + hydration
            "gather_hop_hydrate_share": {
                "fused": round(_hop_share(fused_row), 4),
                "staged": round(_hop_share(staged_row), 4),
            },
            # absolute per-dispatch form (ms): immune to the queue_wait
            # dilution of share-of-wall at high client counts
            "gather_hop_hydrate_p50_ms": {
                "fused": round(_hop_p50(fused_row), 4),
                "staged": round(_hop_p50(staged_row), 4),
            },
            # gather_hop alone — the stage the fusion actually deletes
            # (hydrate is LSM object materialization, out of scope by
            # design): the number that must read ~0 on a live chip
            "gather_hop_p50_ms": {
                "fused": (fused_row.get("phase_p50_ms") or {}).get(
                    "gather_hop"),
                "staged": (staged_row.get("phase_p50_ms") or {}).get(
                    "gather_hop"),
            },
        }
        if staged_row.get("qps"):
            ab["speedup_fused_vs_staged"] = round(
                fused_row["qps"] / staged_row["qps"], 2)
        if _hop_share(fused_row) > 0:
            ab["hop_share_drop_x"] = round(
                _hop_share(staged_row) / _hop_share(fused_row), 2)
        gh_f = ab["gather_hop_p50_ms"]["fused"]
        gh_s = ab["gather_hop_p50_ms"]["staged"]
        if gh_f is not None and gh_s is not None:
            # an eps floor so a fully-collapsed fused hop (0.0 ms — the
            # design goal) reports a large finite factor instead of
            # silently dropping the headline field
            ab["gather_hop_drop_x"] = round(gh_s / max(gh_f, 1e-3), 2)
        _merge_matrix({f"serving_fused_{suffix}": ab})
        log(f"fused A/B: {ab['gather_hop_hydrate_share']} "
            f"speedup={ab.get('speedup_fused_vs_staged')}")
    headline = modes.get("on") or modes.get("off")
    print(json.dumps({
        "metric": (
            f"closed-loop serving QPS over gRPC ({args.clients} clients, "
            f"single-query kNN, n={n}, d={dim}, k={K}, coalescer "
            f"{args.coalesce}, backend {backend})"),
        "value": headline["qps"],
        "unit": "qps",
        "vs_baseline": out_row.get("speedup", 0),
        "row": out_row,
    }))
    _gate_exit()


def run_quant_bench(args, rng):
    """Quantization-ladder A/B (the 4-bit Quick-ADC funnel tentpole):
    closed-loop batched kNN against ONE shard on the direct serving path,
    comparing three rungs under identical load — the exact scan, the
    8-bit codes tier (rescore off: the tier the funnel must beat on
    QPS), and the 4-bit funnel (nibble scan -> exact 8-bit ADC re-rank
    of the top C -> exact rescore of the top c, OPQ-rotated). The shadow
    recall auditor samples live dispatches against the exact pinned host
    plane, so the committed row carries ONLINE recall next to the
    bench's own sampled-reply recall@10; code bytes/vector come from the
    memory ledger components (pq4_codes / pq_codes over slab capacity),
    and the funnel's per-stage survivor counts come from the index's
    funnel accounting. Acceptance: funnel recall@10 >= 0.99 and funnel
    QPS > the 8-bit codes tier's QPS on the CPU A/B; 4-bit code
    bytes/vector <= M/2 plus the shared rotation matrix."""
    import shutil
    import tempfile
    import threading
    import uuid as uuidlib

    import jax

    if os.environ.get("BENCH_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        _probe_device()
    from weaviate_tpu.config import Config
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.server import App

    n = int(os.environ.get("BENCH_QUANT_N", 60_000))
    dim = int(os.environ.get("BENCH_QUANT_DIM", 64))
    segments = int(os.environ.get("BENCH_QUANT_SEGMENTS", dim // 4))
    clients = int(os.environ.get("BENCH_QUANT_CLIENTS", 2))
    # small batches: the regime where the per-query LUT build amortizes
    # and the scan (not the select) dominates — the funnel's home turf
    batch = int(os.environ.get("BENCH_QUANT_BATCH", 4))
    seconds = float(os.environ.get("BENCH_QUANT_SECONDS", 6.0))
    warmup = float(os.environ.get("BENCH_QUANT_WARMUP", 4.0))
    log(f"quant bench: n={n} dim={dim} m={segments} clients={clients} "
        f"batch={batch} mode={args.quant}")
    vecs = make_data(n, dim, rng)
    pool_q = vecs[rng.integers(0, n, 256)] + 0.05 * rng.standard_normal(
        (256, dim), dtype=np.float32)
    gt = exact_gt(vecs, pool_q, K)

    PQ_MODES = {
        "exact": None,
        "pq8": {"enabled": True, "segments": segments, "centroids": 256,
                "rescore": False, "rotation": "none"},
        "pq4-funnel": {"enabled": True, "segments": segments,
                       "centroids": 256, "bits": 4, "rescore": True,
                       "rotation": "opq"},
    }

    def measure(mode: str) -> dict:
        pq_cfg = PQ_MODES[mode]
        cfg = Config()
        cfg.quality.audit_sample_rate = float(
            os.environ.get("BENCH_QUANT_AUDIT_RATE", 0.2))
        cfg.quality.audit_deadline_ms = 10_000.0  # host scans n rows
        cfg.quality.audit_max_rows = batch
        data_dir = tempfile.mkdtemp(prefix="benchquant")
        app = None
        try:
            app = App(config=cfg, data_path=data_dir)
            vic = {"distance": "l2-squared"}
            if pq_cfg is not None:
                vic["pq"] = pq_cfg
            app.schema.add_class({
                "class": "Quant", "vectorIndexType": "hnsw_tpu",
                "vectorIndexConfig": vic,
                "properties": [{"name": "tag", "dataType": ["text"]}],
            })
            ci = app.db.get_index("Quant")
            t0 = time.perf_counter()
            for s in range(0, n, 10_000):
                ci.put_batch([
                    StorObj(class_name="Quant",
                            uuid=str(uuidlib.UUID(int=i + 1)),
                            properties={"tag": f"t{i % 16}"},
                            vector=vecs[i])
                    for i in range(s, min(s + 10_000, n))])
            import_s = time.perf_counter() - t0
            shard = ci.single_local_shard()
            vidx = shard.vector_index
            if pq_cfg is not None:
                assert vidx.compressed, f"quant bench: {mode} did not compress"
            if mode == "pq4-funnel":
                assert getattr(vidx, "_codes4", None) is not None, \
                    "quant bench: the 4-bit rung did not build"
            log(f"  import {import_s:.1f}s; mode={mode} "
                f"health={vidx.health().get('pq')}")
            stop = threading.Event()
            counting = threading.Event()
            lats: list[list[float]] = [[] for _ in range(clients)]
            samples: list[list] = [[] for _ in range(clients)]
            errors = [0] * clients

            def loop(tid: int) -> None:
                lrng = np.random.default_rng(700 + tid)
                while not stop.is_set():
                    qi = int(lrng.integers(0, len(pool_q) - batch))
                    qb = pool_q[qi: qi + batch]
                    t1 = time.perf_counter()
                    try:
                        res = shard.object_vector_search(qb, K)
                    except Exception:  # noqa: BLE001 — keep the loop alive
                        errors[tid] += 1
                        time.sleep(0.05)
                        continue
                    dt = time.perf_counter() - t1
                    if counting.is_set():
                        lats[tid].append(dt)
                        if len(samples[tid]) < 32:
                            ids = [[int(uuidlib.UUID(r.obj.uuid).int) - 1
                                    for r in row] for row in res]
                            samples[tid].append((qi, ids))

            threads = [threading.Thread(target=loop, args=(i,), daemon=True)
                       for i in range(clients)]
            for t in threads:
                t.start()
            time.sleep(warmup)  # compile the padding buckets
            base_audits = None
            if app.quality_auditor is not None:
                app.quality_auditor.drain(timeout_s=30.0)
                app.quality_auditor.clear()
                base_audits = app.quality_auditor.summary().get("audits", {})
            counting.set()
            t1 = time.perf_counter()
            time.sleep(seconds)
            counting.clear()
            elapsed = time.perf_counter() - t1
            stop.set()
            for t in threads:
                t.join(timeout=30)
            flat = np.array([x for per in lats for x in per], np.float64)
            hit = tot = 0
            for per in samples:
                for qi, rows in per:
                    for j, ids in enumerate(rows):
                        want = set(int(x) for x in gt[qi + j])
                        hit += len(want & set(ids))
                        tot += K
            row = {
                "mode": mode, "n": n, "dim": dim, "k": K,
                "segments": segments, "clients": clients, "batch": batch,
                "duration_s": round(elapsed, 2),
                "requests": int(flat.size),
                "qps": round(flat.size * batch / elapsed, 1),
                "p50_ms": round(float(np.percentile(flat, 50)) * 1000, 2)
                if flat.size else None,
                "p99_ms": round(float(np.percentile(flat, 99)) * 1000, 2)
                if flat.size else None,
                "recall@10": round(hit / tot, 4) if tot else None,
                "request_errors": int(sum(errors)),
                "import_s": round(import_s, 1),
            }
            if app.quality_auditor is not None:
                app.quality_auditor.drain(timeout_s=30.0)
                qs = app.quality_auditor.summary()
                row["online_recall"] = qs.get("online_recall")
                row["online_audits"] = {
                    k: v - (base_audits or {}).get(k, 0)
                    for k, v in qs.get("audits", {}).items()}
            # code bytes/vector from the ledger's analytic components —
            # the acceptance claim (<= M/2 + rotation) reads the same
            # numbers /debug/memory serves
            comps = vidx._memory_components()
            if "pq_codes" in comps and getattr(vidx, "_codes", None) is not None:
                row["code_bytes_per_vector"] = round(
                    comps["pq_codes"] / int(vidx._codes.shape[0]), 2)
            if "pq4_codes" in comps and getattr(vidx, "_codes4", None) is not None:
                row["pq4_code_bytes_per_vector"] = round(
                    comps["pq4_codes"] / int(vidx._codes4.shape[0]), 2)
                row["opq_rot_bytes"] = comps.get("opq_rot", 0)
            if mode == "pq4-funnel":
                row["pq_health"] = vidx.health().get("pq")
                assert (row["pq_health"] or {}).get("funnel"), \
                    "quant bench: funnel never dispatched"
            scan_bpr = {"exact": 4 * dim, "pq8": segments,
                        "pq4-funnel": segments // 2}[mode]
            plat = jax.devices()[0].platform
            backend = costmodel.backend_for_platform(plat)
            shape = costmodel.DispatchShape(
                costmodel.TIER_PQ_ADC4 if mode == "pq4-funnel"
                else (costmodel.TIER_PQ_CODES if mode == "pq8"
                      else costmodel.TIER_EXACT),
                n=n, dim=dim, batch=batch, bytes_per_row=scan_bpr, k=K)
            row["costmodel"] = {
                "scan_bytes_per_row": scan_bpr,
                "flops_per_dispatch": shape.flops(),
                "bytes_per_dispatch": shape.bytes(),
                "roofline": shape.roofline_at_qps(max(row["qps"], 1e-9),
                                                  backend),
            }
            log(f"  mode={mode}: {row}")
            return row
        finally:
            if app is not None:
                app.shutdown()
            shutil.rmtree(data_dir, ignore_errors=True)

    wanted = (("exact", "pq8", "pq4-funnel") if args.quant == "all"
              else (args.quant,))
    modes = {m: measure(m) for m in wanted}
    plat = jax.devices()[0].platform
    backend = "tpu-v5e" if plat in ("tpu", "axon") else "cpu"
    out_row = {
        "backend": backend, "round": 6, "date": time.strftime("%Y-%m-%d"),
        "n": n, "dim": dim, "segments": segments, "clients": clients,
        "batch": batch, **modes,
    }
    if "pq4-funnel" in modes and "pq8" in modes and modes["pq8"]["qps"]:
        out_row["speedup_pq4_vs_pq8"] = round(
            modes["pq4-funnel"]["qps"] / modes["pq8"]["qps"], 2)
    if "pq4-funnel" in modes and "exact" in modes and modes["exact"]["qps"]:
        out_row["speedup_pq4_vs_exact"] = round(
            modes["pq4-funnel"]["qps"] / modes["exact"]["qps"], 2)
    suffix = "cpu" if backend == "cpu" else "tpu"
    _merge_matrix({f"quant_ladder_{suffix}": out_row})
    head = (modes.get("pq4-funnel") or modes.get("pq8")
            or modes.get("exact"))
    print(json.dumps({
        "metric": (
            f"quantization ladder QPS — exact vs 8-bit codes vs 4-bit "
            f"funnel (shard direct path, n={n}, d={dim}, M={segments}, "
            f"k={K}, batch={batch}, {clients} clients, backend {backend}; "
            f"online_recall from the shadow auditor)"),
        "value": head["qps"],
        "unit": "qps",
        "vs_baseline": out_row.get("speedup_pq4_vs_pq8", 0),
        "row": out_row,
    }))
    _gate_exit()


def run_ivf_bench(args, rng):
    """IVF-vs-flat A/B (the partition-pruning tentpole, ROADMAP item 3):
    closed-loop batched kNN against ONE shard on the direct serving path
    — shard.object_vector_search, so dispatches ride the real snapshot/
    trace/audit planes but no gRPC/coalescer overhead dilutes the
    scan-bound comparison. The shadow recall auditor (monitoring/
    quality.py) samples the live dispatches against the exact pinned
    snapshot, so the committed row carries ONLINE recall next to the
    bench's own sampled-reply recall@10; probed_fraction comes from the
    index's probe accounting over the counted window, and the costmodel
    block carries the probed-aware flops (no phantom work in the
    roofline). Acceptance: probed QPS >= 3x flat at online recall
    >= 0.99 with probed_fraction < 0.25."""
    import shutil
    import tempfile
    import threading
    import uuid as uuidlib

    import jax

    if os.environ.get("BENCH_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        _probe_device()
    from weaviate_tpu.config import Config
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.server import App

    n = int(os.environ.get("BENCH_IVF_N", 120_000))
    dim = int(os.environ.get("BENCH_IVF_DIM", 64))
    clients = int(os.environ.get("BENCH_IVF_CLIENTS", 4))
    batch = int(os.environ.get("BENCH_IVF_BATCH", 16))
    seconds = float(os.environ.get("BENCH_IVF_SECONDS", 8.0))
    warmup = float(os.environ.get("BENCH_IVF_WARMUP", 4.0))
    log(f"ivf bench: n={n} dim={dim} clients={clients} batch={batch} "
        f"mode={args.ivf}")
    vecs = make_data(n, dim, rng)
    pool_q = vecs[rng.integers(0, n, 256)] + 0.05 * rng.standard_normal(
        (256, dim), dtype=np.float32)
    gt = exact_gt(vecs, pool_q, K)

    def measure(ivf_on: bool) -> dict:
        cfg = Config()
        # online recall: the shadow auditor samples live dispatches and
        # re-executes them on the exact pinned host plane — the recall
        # claim is measured on the serving path, not offline
        cfg.quality.audit_sample_rate = float(
            os.environ.get("BENCH_IVF_AUDIT_RATE", 0.2))
        cfg.quality.audit_deadline_ms = 10_000.0  # host scans n rows
        cfg.quality.audit_max_rows = batch
        cfg.ivf.enabled = ivf_on
        # train ONCE at full import (min_n = n): the A/B measures the
        # steady-state layout, not a half-stale mid-import one — and the
        # import doesn't pay len(import)/growth reclusters
        cfg.ivf.min_n = n
        cfg.ivf.nlist = int(os.environ.get("BENCH_IVF_NLIST", 0))
        cfg.ivf.top_p = int(os.environ.get("BENCH_IVF_TOP_P", 0))
        # the low-dim prefilter defaults OFF on the CPU A/B: at D=64 the
        # candidate pass is gather/selection-bound, not dim-bound, so a
        # prefilter stage ADDS more selection work than the dims it cuts
        # (measured: 60 -> 82 ms/batch). It earns its keep on wide
        # vectors / bandwidth-bound stores — BENCH_IVF_PCA_DIM enables it
        cfg.ivf.pca_dim = int(os.environ.get("BENCH_IVF_PCA_DIM", 0))
        data_dir = tempfile.mkdtemp(prefix="benchivf")
        app = None
        try:
            app = App(config=cfg, data_path=data_dir)
            app.schema.add_class({
                "class": "Ivf", "vectorIndexType": "hnsw_tpu",
                "vectorIndexConfig": {"distance": "l2-squared"},
                "properties": [{"name": "tag", "dataType": ["text"]}],
            })
            ci = app.db.get_index("Ivf")
            t0 = time.perf_counter()
            for s in range(0, n, 10_000):
                ci.put_batch([
                    StorObj(class_name="Ivf",
                            uuid=str(uuidlib.UUID(int=i + 1)),
                            properties={"tag": f"t{i % 16}"},
                            vector=vecs[i])
                    for i in range(s, min(s + 10_000, n))])
            import_s = time.perf_counter() - t0
            shard = ci.single_local_shard()
            vidx = shard.vector_index
            if ivf_on:
                assert getattr(vidx, "_ivf_buckets", None) is not None, \
                    "ivf bench: layout did not train"
            log(f"  import {import_s:.1f}s; ivf={'on' if ivf_on else 'off'}"
                f" health={vidx.health().get('ivf')}")
            stop = threading.Event()
            counting = threading.Event()
            lats: list[list[float]] = [[] for _ in range(clients)]
            samples: list[list] = [[] for _ in range(clients)]
            errors = [0] * clients

            def loop(tid: int) -> None:
                lrng = np.random.default_rng(500 + tid)
                while not stop.is_set():
                    qi = int(lrng.integers(0, len(pool_q) - batch))
                    qb = pool_q[qi: qi + batch]
                    t1 = time.perf_counter()
                    try:
                        res = shard.object_vector_search(qb, K)
                    except Exception:  # noqa: BLE001 — keep the loop alive
                        errors[tid] += 1
                        time.sleep(0.05)
                        continue
                    dt = time.perf_counter() - t1
                    if counting.is_set():
                        lats[tid].append(dt)
                        if len(samples[tid]) < 16:
                            ids = [[int(uuidlib.UUID(r.obj.uuid).int) - 1
                                    for r in row] for row in res]
                            samples[tid].append((qi, ids))

            threads = [threading.Thread(target=loop, args=(i,), daemon=True)
                       for i in range(clients)]
            for t in threads:
                t.start()
            time.sleep(warmup)  # compile the padding buckets
            base_stats = vidx.ivf_stats() if ivf_on else None
            base_audits = None
            if app.quality_auditor is not None:
                app.quality_auditor.drain(timeout_s=30.0)
                app.quality_auditor.clear()
                base_audits = app.quality_auditor.summary().get("audits", {})
            counting.set()
            t1 = time.perf_counter()
            time.sleep(seconds)
            counting.clear()
            elapsed = time.perf_counter() - t1
            stop.set()
            for t in threads:
                t.join(timeout=30)
            flat = np.array([x for per in lats for x in per], np.float64)
            hit = tot = 0
            for per in samples:
                for qi, rows in per:
                    for j, ids in enumerate(rows):
                        want = set(int(x) for x in gt[qi + j])
                        hit += len(want & set(ids))
                        tot += K
            row = {
                "ivf": ivf_on, "n": n, "dim": dim, "k": K,
                "clients": clients, "batch": batch,
                "duration_s": round(elapsed, 2),
                "requests": int(flat.size),
                "qps": round(flat.size * batch / elapsed, 1),
                "p50_ms": round(float(np.percentile(flat, 50)) * 1000, 2)
                if flat.size else None,
                "p99_ms": round(float(np.percentile(flat, 99)) * 1000, 2)
                if flat.size else None,
                "recall@10": round(hit / tot, 4) if tot else None,
                "request_errors": int(sum(errors)),
                "import_s": round(import_s, 1),
            }
            if app.quality_auditor is not None:
                app.quality_auditor.drain(timeout_s=30.0)
                qs = app.quality_auditor.summary()
                row["online_recall"] = qs.get("online_recall")
                row["online_audits"] = {
                    k: v - (base_audits or {}).get(k, 0)
                    for k, v in qs.get("audits", {}).items()}
            if ivf_on:
                st = vidx.ivf_stats()
                dp = st["dispatches"] - base_stats["dispatches"]
                pr = st["probed_rows"] - base_stats["probed_rows"]
                br = st["base_rows"] - base_stats["base_rows"]
                row["probed_fraction"] = round(pr / br, 4) if br else None
                row["ivf_health"] = vidx.health().get("ivf")
                # the resolved operating point (reproducibility: auto
                # knobs resolve against n/nlist at run time)
                plan = vidx._ivf_plan(vidx._read_snapshot(), K)
                row["ivf_top_p"] = plan[0] if plan else None
                row["ivf_prefilter_c"] = plan[1] if plan else None
                h = row["ivf_health"] or {}
                # rows the device reads per dispatch: the probed bucket
                # rows plus the nlist centroid rows of the probe itself
                probed_n = pr // max(dp, 1) + h.get("nlist", 0)
            else:
                probed_n = n
            # probed-aware costmodel block: flops/bytes reflect the rows
            # the device actually reads, so the roofline carries no
            # phantom work for the rows the probe skipped
            plat = jax.devices()[0].platform
            backend = costmodel.backend_for_platform(plat)
            shape = costmodel.DispatchShape(
                costmodel.TIER_EXACT, n=int(probed_n), dim=dim, batch=batch,
                bytes_per_row=4 * dim, k=K)
            row["costmodel"] = {
                "scanned_rows_per_dispatch": int(probed_n),
                "flops_per_dispatch": shape.flops(),
                "bytes_per_dispatch": shape.bytes(),
                "roofline": shape.roofline_at_qps(max(row["qps"], 1e-9),
                                                  backend),
            }
            log(f"  ivf={'on' if ivf_on else 'off'}: {row}")
            return row
        finally:
            if app is not None:
                app.shutdown()
            shutil.rmtree(data_dir, ignore_errors=True)

    modes = {}
    if args.ivf in ("off", "both"):
        modes["flat"] = measure(False)
    if args.ivf in ("on", "both"):
        modes["ivf"] = measure(True)
    import jax

    plat = jax.devices()[0].platform
    backend = "tpu-v5e" if plat in ("tpu", "axon") else "cpu"
    out_row = {
        "backend": backend, "round": 6, "date": time.strftime("%Y-%m-%d"),
        "n": n, "dim": dim, "clients": clients, "batch": batch, **modes,
    }
    if "ivf" in modes and "flat" in modes and modes["flat"]["qps"]:
        out_row["speedup_ivf_vs_flat"] = round(
            modes["ivf"]["qps"] / modes["flat"]["qps"], 2)
    suffix = "cpu" if backend == "cpu" else "tpu"
    _merge_matrix({f"ivf_scan_{suffix}": out_row})
    head = modes.get("ivf") or modes.get("flat")
    print(json.dumps({
        "metric": (
            f"IVF partition-pruned vs flat scan QPS (shard direct path, "
            f"n={n}, d={dim}, k={K}, batch={batch}, {clients} clients, "
            f"backend {backend}; online_recall from the shadow auditor)"),
        "value": head["qps"],
        "unit": "qps",
        "vs_baseline": out_row.get("speedup_ivf_vs_flat", 0),
        "row": out_row,
    }))
    _gate_exit()


def run_reader_scaling_bench(args, rng):
    """Closed-loop read scaling on the DIRECT index path (no gRPC, no
    coalescer): N reader threads each issue single-query kNN searches
    back-to-back against one TpuVectorIndex. Measured twice per N —

      - snapshot: the shipped lock-free read plane (index/tpu.py
        IndexSnapshot), recording each reader's lock-wait (p99 pins the
        'readers never wait' claim);
      - single_lock: the identical search serialized under ONE shared
        mutex, reproducing the pre-PR read path that held the per-index
        RLock across flush + dispatch + device fetch;

    so the reader_scaling row records the speedup this PR's tentpole buys
    at N = 1/4/16/64 at identical recall (same index, same queries)."""
    import threading

    if os.environ.get("BENCH_BACKEND") == "cpu":
        # On the CPU backend, XLA's default intra-op parallelism lets ONE
        # query saturate every host core — the "device" then has zero idle
        # capacity and NO serialization policy can show a difference (a
        # lock around a saturated device is free). A real TPU is not like
        # that: a 1-wide dispatch leaves almost all device capacity idle,
        # which is exactly what concurrent readers reclaim. Pin each
        # XLA execution to one thread so the host models that situation
        # (N cores = N independent execution units); both modes below run
        # under the SAME flags, so the comparison stays apples-to-apples.
        os.environ.setdefault(
            "XLA_FLAGS",
            "--xla_cpu_multi_thread_eigen=false "
            "intra_op_parallelism_threads=1")

    import jax

    if os.environ.get("BENCH_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        _probe_device()
    n, dim = args.serve_n, args.serve_dim
    log(f"reader scaling bench: n={n} dim={dim} (direct index path)")
    vecs = make_data(n, dim, rng)
    idx, import_s = _build_index(vecs)
    log(f"import: {import_s:.1f}s")
    pool_q = vecs[rng.integers(0, n, 256)] + 0.05 * rng.standard_normal(
        (256, dim), dtype=np.float32)
    gt = exact_gt(vecs, pool_q[:64], K)
    idx.search_by_vectors(pool_q[:1], K)  # compile the 1-wide bucket
    serial = threading.Lock()  # the emulated pre-PR per-index mutex

    def measure_pair(n_threads: int, rounds: int = 4) -> tuple[dict, dict]:
        """One reader count, BOTH modes, as interleaved paired slices
        (locked slice, snapshot slice, locked, snapshot, ...): a shared
        or thermally-drifting host hits adjacent slices equally, so the
        RATIO survives noise that makes back-to-back whole-window runs
        disagree by 30%+."""
        slice_s = max(args.serve_seconds / rounds, 1.0)
        acc = {m: {"lats": [], "waits": [], "samples": [], "secs": 0.0}
               for m in ("locked", "snapshot")}

        def run_slice(mode: str) -> None:
            stop = threading.Event()
            counting = threading.Event()
            a = acc[mode]
            lats: list[float] = []
            waits: list[float] = []
            samples: list = []
            lk = threading.Lock()  # guards the result lists only

            def loop(tid: int) -> None:
                lrng = np.random.default_rng(500 + tid)
                while not stop.is_set():
                    qi = int(lrng.integers(0, len(pool_q)))
                    q1 = pool_q[qi : qi + 1]
                    t0 = time.perf_counter()
                    if mode == "locked":
                        with serial:
                            ids, _d = idx.search_by_vectors(q1, K)
                    else:
                        ids, _d = idx.search_by_vectors(q1, K)
                    dt = time.perf_counter() - t0
                    w = idx.pop_read_lock_wait()
                    if counting.is_set():
                        with lk:
                            lats.append(dt)
                            waits.append(w)
                            if qi < 64 and len(samples) < 64:
                                samples.append((qi, ids[0].copy()))

            threads = [threading.Thread(target=loop, args=(i,), daemon=True)
                       for i in range(n_threads)]
            for t in threads:
                t.start()
            time.sleep(max(args.serve_warmup / rounds, 0.5))
            counting.set()
            t0 = time.perf_counter()
            time.sleep(slice_s)
            counting.clear()
            elapsed = time.perf_counter() - t0
            stop.set()
            for t in threads:
                t.join(timeout=30)
            a["lats"].extend(lats)
            a["waits"].extend(waits)
            a["samples"].extend(samples)
            a["secs"] += elapsed

        for _ in range(rounds):
            run_slice("locked")
            run_slice("snapshot")

        def stats(mode: str) -> dict:
            a = acc[mode]
            flat = np.asarray(a["lats"], np.float64)
            wflat = np.asarray(a["waits"], np.float64)
            hit = tot = 0
            for qi, ids in a["samples"]:
                got = set(int(x) for x in ids[:K])
                hit += len(got & set(int(x) for x in gt[qi]))
                tot += K
            return {
                "requests": int(flat.size),
                "qps": round(flat.size / a["secs"], 1) if a["secs"] else None,
                "p50_ms": round(float(np.percentile(flat, 50)) * 1000, 2)
                if flat.size else None,
                "p99_ms": round(float(np.percentile(flat, 99)) * 1000, 2)
                if flat.size else None,
                "lock_wait_p99_ms": round(
                    float(np.percentile(wflat, 99)), 3)
                if wflat.size else None,
                "recall@10": round(hit / tot, 4) if tot else None,
            }

        return stats("snapshot"), stats("locked")

    ladder = sorted({1, 4, 16, 64} | {max(int(args.readers), 1)})
    per_n: dict = {}
    for nt in ladder:
        snap, lck = measure_pair(nt)
        row = {
            "qps": snap["qps"],
            "single_lock_qps": lck["qps"],
            "speedup_vs_single_lock": round(snap["qps"] / lck["qps"], 2)
            if lck["qps"] else None,
            "p99_ms": snap["p99_ms"],
            "lock_wait_p99_ms": snap["lock_wait_p99_ms"],
            "recall@10": snap["recall@10"],
            "single_lock_recall@10": lck["recall@10"],
        }
        per_n[str(nt)] = row
        log(f"  readers={nt}: snapshot {snap['qps']} QPS vs single-lock "
            f"{lck['qps']} QPS ({row['speedup_vs_single_lock']}x), "
            f"lock-wait p99 {snap['lock_wait_p99_ms']} ms, "
            f"recall {snap['recall@10']} / {lck['recall@10']}")
    plat = jax.devices()[0].platform
    backend = "tpu-v5e" if plat in ("tpu", "axon") else "cpu"
    cores = os.cpu_count() or 1
    out_row = {
        "backend": backend, "round": 6, "date": time.strftime("%Y-%m-%d"),
        "n": n, "dim": dim, "k": K, "host_cores": cores,
        "mode": "direct index, closed loop, single-query readers; "
                "single_lock = same build with every search serialized "
                "under one index-wide mutex (the pre-PR read path held "
                "the per-index RLock across flush+dispatch+fetch); cpu "
                "backend pins XLA intra-op to 1 thread so one query does "
                "not saturate the host (models the TPU's idle-capacity "
                "situation) — the speedup ceiling is therefore "
                "min(host_cores, bandwidth headroom), NOT unbounded",
        "readers": per_n,
    }
    suffix = "cpu" if backend == "cpu" else "tpu"
    _merge_matrix({f"reader_scaling_{suffix}": out_row})
    anchor = per_n.get(str(max(int(args.readers), 1))) or per_n["16"]
    print(json.dumps({
        "metric": (
            f"closed-loop direct-index read QPS ({args.readers or 1} "
            f"readers, single-query kNN, n={n}, d={dim}, k={K}, backend "
            f"{backend}) — snapshot read plane vs pre-PR single-lock"),
        "value": anchor["qps"],
        "unit": "qps",
        "vs_baseline": anchor["speedup_vs_single_lock"],
        "row": out_row,
    }))
    _gate_exit()


def run_mesh_scale_bench(args, rng):
    """Single-device vs 8-device-mesh A/B on the coalesced serving shape
    (direct index path, no gRPC): the SAME corpus lives once on one
    TpuVectorIndex device and once sharded row-wise across the
    MeshVectorIndex, and both serve coalesced-width batches (64 queries =
    one full lane) through the two-phase enqueue/finalize pipeline at
    depth 2 — exactly what the coalescer's flush thread dispatches since
    the mesh serving promotion. Interleaved paired slices (A,B,A,B,...)
    per the reader_scaling precedent so host drift cancels out of the
    ratio. BENCH_BACKEND=cpu runs the 8-virtual-device CPU mesh; the TPU
    twin runs the same function against real chips."""
    if os.environ.get("BENCH_BACKEND") == "cpu":
        # the virtual device count must land before the backend initializes
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    if os.environ.get("BENCH_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except AttributeError:
            pass
    else:
        _probe_device()
    ndev = len(jax.devices())
    n, dim = args.serve_n, args.serve_dim
    log(f"mesh scaling bench: n={n} dim={dim} devices={ndev} "
        "(direct index path, coalesced-width batches)")
    vecs = make_data(n, dim, rng)
    batch = 64  # one full coalescer lane (snapped padding bucket)
    queries = vecs[rng.integers(0, n, batch)] + 0.05 * rng.standard_normal(
        (batch, dim), dtype=np.float32)
    gt = exact_gt(vecs, queries, K)

    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.mesh import MeshVectorIndex

    idx_single, import_s = _build_index(vecs)
    log(f"single-device import: {import_s:.1f}s")
    cfg = vi.HnswUserConfig.from_dict(
        {"distance": "l2-squared"}, "hnsw_tpu_mesh")
    idx_mesh = MeshVectorIndex(cfg, "/tmp/bench_mesh_shard", persist=False)
    t0 = time.perf_counter()
    idx_mesh.add_batch(np.arange(n), vecs)
    idx_mesh.flush()
    log(f"mesh import: {time.perf_counter() - t0:.1f}s")

    def recall(ids) -> float:
        hit = sum(len(set(map(int, ids[i, :K])) & set(map(int, gt[i])))
                  for i in range(batch))
        return round(hit / (batch * K), 4)

    # correctness first: both indexes are exact scans over the same rows,
    # so the result sets must agree before any throughput number counts
    ids_s, d_s = idx_single.search_by_vectors(queries, K)
    ids_m, d_m = idx_mesh.search_by_vectors(queries, K)
    rec_s, rec_m = recall(ids_s), recall(ids_m)
    bit_identical = bool(np.array_equal(ids_s, ids_m))

    # interleaved paired slices: (single, mesh) x rounds, medians reported
    rounds, n_batches = 4, 24
    qps_s_r, qps_m_r = [], []
    for _ in range(rounds):
        q, _pb = _measure_pipelined(idx_single, queries, K, n_batches)
        qps_s_r.append(q)
        q, _pb = _measure_pipelined(idx_mesh, queries, K, n_batches)
        qps_m_r.append(q)
    qps_s = float(np.median(qps_s_r))
    qps_m = float(np.median(qps_m_r))

    # per-chip duty cycle: device busy time per batch (blocking sync
    # round-trip, median of 8) over the pipelined inter-batch interval —
    # how much of each chip's wall clock the depth-2 pipeline keeps full.
    # One SPMD program spans every chip, so the duty is uniform per chip.
    def duty(idx, qps) -> float:
        ts = []
        for _ in range(8):
            t0 = time.perf_counter()
            idx.search_by_vectors(queries, K)
            ts.append(time.perf_counter() - t0)
        busy = float(np.median(ts))
        interval = batch / qps if qps else busy
        return round(min(busy / interval, 1.0), 3)

    duty_s = duty(idx_single, qps_s)
    duty_m = duty(idx_mesh, qps_m)
    speedup = round(qps_m / qps_s, 2) if qps_s else None
    log(f"  single-device {qps_s:.0f} QPS (duty {duty_s}) vs mesh "
        f"{qps_m:.0f} QPS (duty {duty_m}) = {speedup}x, recall "
        f"{rec_s} / {rec_m}, bit_identical={bit_identical}")

    plat = jax.devices()[0].platform
    backend = "tpu-v5e" if plat in ("tpu", "axon") else "cpu"
    cores = os.cpu_count() or 1
    out_row = {
        "backend": backend, "round": 7, "date": time.strftime("%Y-%m-%d"),
        "n": n, "dim": dim, "k": K, "batch": batch, "devices": ndev,
        "host_cores": cores,
        "mode": "direct index, coalesced-width batches (64 = one full "
                "lane) through two-phase enqueue/finalize at pipeline "
                "depth 2; interleaved paired slices, medians",
        "single_device": {
            "qps": round(qps_s, 1), "recall@10": rec_s,
            "per_chip_duty_cycle": duty_s,
        },
        "mesh": {
            "qps": round(qps_m, 1), "recall@10": rec_m,
            "per_chip_duty_cycle": duty_m,
            "speedup_vs_single_device": speedup,
        },
        "bit_identical_ids": bit_identical,
    }
    if backend == "cpu":
        # reader_scaling precedent: on this host the A/B cannot show the
        # chip-count speedup, and pretending otherwise would poison the
        # matrix — say so in the row instead of inflating the number
        out_row["qps_note"] = (
            f"{cores}-core host: all {ndev} virtual mesh devices "
            "timeshare the same core(s), so the mesh ceiling is ~1x "
            "single-device QPS minus SPMD overhead — the CPU row pins "
            "CORRECTNESS (bit-identical ids at equal recall) and the "
            "serving-shape plumbing; the >=2x scaling claim is the TPU "
            "twin's to make (same function, BENCH_BACKEND unset)")
    suffix = "cpu" if backend == "cpu" else "tpu"
    _merge_matrix({f"mesh_scaling_{suffix}": out_row})
    print(json.dumps({
        "metric": (
            f"coalesced-batch kNN QPS (batch={batch}, n={n}, d={dim}, "
            f"k={K}, backend {backend}) — {ndev}-device mesh vs "
            "single-device"),
        "value": round(qps_m, 1),
        "unit": "qps",
        "vs_baseline": speedup,
        "row": out_row,
    }))
    _gate_exit()


def main():
    args = _parse_args()
    rng = np.random.default_rng(7)
    if args.ivf:
        run_ivf_bench(args, rng)
        return
    if args.quant:
        run_quant_bench(args, rng)
        return
    if args.readers:
        run_reader_scaling_bench(args, rng)
        return
    if args.mesh_scale:
        run_mesh_scale_bench(args, rng)
        return
    if args.tenants:
        # before --clients: the acceptance command passes both (--clients
        # is the fairness mode's thread budget, not the serving mode)
        run_fairness_bench(args, rng)
        return
    if args.overload:
        run_overload_bench(args, rng)
        return
    if args.clients:
        run_serving_bench(args, rng)
        return
    if os.environ.get("BENCH_MEASURE_CPU"):
        measure_cpu_baseline(rng)
        return
    if os.environ.get("BENCH_BACKEND") == "cpu":
        run_cpu_matrix(rng)
        return

    _probe_device()
    import jax

    from bench_datasets import load_or_synthetic, tile_queries

    # real SIFT1M when available (BASELINE.json config 1; reference harness
    # test/benchmark/benchmark_sift.go); shape-matched synthetic otherwise —
    # the metric line names whichever was measured
    def synth():
        log(f"generating {N}x{DIM} clustered vectors...")
        return {"train": make_data(N, DIM, rng), "queries": None,
                "metric": "l2-squared"}

    data, data_label = load_or_synthetic(
        "sift1m", synth, max_rows=None if N >= 1_000_000 else N)
    vecs = data["train"]
    n_eff, dim_eff = vecs.shape
    if data["queries"] is not None:
        queries = tile_queries(data["queries"], B)
    else:
        queries = rng.standard_normal((B, dim_eff), dtype=np.float32) * 0.1 + vecs[
            rng.integers(0, n_eff, B)
        ]

    idx, import_s = _build_index(vecs)
    log(f"import: {import_s:.1f}s ({n_eff/import_s:.0f} vec/s) on {jax.devices()[0]}")

    qps_sync, med, ids = _measure_sync(idx, queries, K, N_QUERY_BATCHES)
    log(f"TPU batched kNN (sync): {qps_sync:.0f} QPS (median {med*1000:.1f} ms / {B}-query batch)")
    log(f"kernel: {'fused gmin (pallas)' if getattr(idx, '_gmin_validated', False) else 'lax.scan'}")

    qps_pipe, per_batch = _measure_pipelined(idx, queries, K, N_QUERY_BATCHES)
    log(f"TPU batched kNN (pipelined, serving path): {qps_pipe:.0f} QPS ({per_batch*1000:.1f} ms/batch)")

    if data.get("gt") is not None:
        # clamp to the measured batch: ids has B rows
        gt = [row[:K] for row in data["gt"][: min(N_GT, B)]]
        log(f"using shipped ground truth ({len(gt)} queries)")
    else:
        log(f"computing exact ground truth on {N_GT} queries...")
        gt = exact_gt(vecs, queries[:N_GT], K)
    recall = recall_at_k(ids, gt, K)
    log(f"recall@10 = {recall:.4f} ({len(gt)} queries)")

    if recall < 0.95 and getattr(idx, "_gmin_validated", False):
        # the fused kernel missed the recall bar on this platform — a
        # result we never accept silently: disable it, re-measure on the
        # lax.scan kernel, and say so
        log("recall below 0.95 on the fused kernel; re-measuring on lax.scan")
        idx._gmin_broken = True
        qps_sync, med, ids = _measure_sync(idx, queries, K, N_QUERY_BATCHES)
        qps_pipe, per_batch = _measure_pipelined(idx, queries, K, N_QUERY_BATCHES)
        recall = recall_at_k(ids, gt, K)
        log(f"kernel: lax.scan (fallback) — ALL reported numbers re-measured")
        log(f"sync {qps_sync:.0f} QPS / pipelined {qps_pipe:.0f} QPS, recall@10 = {recall:.4f}")

    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            cpu = json.load(f)
        cpu_qps = cpu["qps"]
        cpu_8core = cpu.get("qps_8core_equiv", cpu_qps)
        cores = cpu.get("cores", "?")
        base_note = (
            f"CPU HNSW n={cpu['n']} ef={cpu['ef']} multi-threaded on "
            f"{cores} core(s)"
        )
    else:
        nb = 4
        t0 = time.perf_counter()
        for i in range(nb):
            d = ((vecs - queries[i]) ** 2).sum(1)
            np.argpartition(d, K)[:K]
        cpu_qps = cpu_8core = nb / (time.perf_counter() - t0)
        base_note = "numpy brute force"
    log(f"baseline ({base_note}): {cpu_qps:.1f} QPS measured, {cpu_8core:.1f} 8-core-equiv")

    out = {
        "metric": (
            f"pipelined batched kNN QPS ({data_label}, N={n_eff}, d={dim_eff}, "
            f"k={K}, batch={B}, L2, "
            f"recall@10={recall:.3f} on {len(gt)} queries vs exact GT, "
            f"baseline={base_note})"
        ),
        "value": round(qps_pipe, 1),
        "unit": "qps",
        "vs_baseline": round(qps_pipe / cpu_qps, 1),
        "vs_baseline_8core_equiv": round(qps_pipe / cpu_8core, 1),
        "sync_qps": round(qps_sync, 1),
    }
    plat = jax.devices()[0].platform
    backend = "tpu-v5e" if plat in ("tpu", "axon") else "cpu"
    store_bytes = dim_eff * (2 if idx.config.store_dtype == "bfloat16" else 4)
    out["roofline"] = _roofline(qps_pipe, n_eff, dim_eff, B, store_bytes,
                                backend)
    log(f"roofline: {out['roofline']['tflops']} TFLOP/s "
        f"({out['roofline']['mfu_pct']}% of peak), "
        f"{out['roofline']['hbm_gbs']} GB/s "
        f"({out['roofline']['bw_pct']}% of HBM), "
        f"{out['roofline']['regime']}")

    if os.environ.get("BENCH_MATRIX"):
        run_matrix(rng, vecs, queries, idx, gt, headline={
            "label": data_label,
            "qps": round(qps_pipe, 1), "sync_qps": round(qps_sync, 1),
            "recall@10": round(recall, 4),
            "n": int(n_eff), "dim": int(dim_eff),
            "roofline": out["roofline"],
        })

    print(json.dumps(out))
    _gate_exit()


if __name__ == "__main__":
    main()
