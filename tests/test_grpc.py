"""gRPC Search/BatchSearch service tests.

Reference surface: adapters/handlers/grpc/server.go + grpc/weaviate.proto.
"""

import json
import uuid as uuidlib

import grpc
import numpy as np
import pytest

from weaviate_tpu.grpcapi import weaviate_pb2 as pb
from weaviate_tpu.server import App
from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    app = App(data_path=str(tmp_path_factory.mktemp("data")))
    app.schema.add_class({
        "class": "Doc",
        "properties": [
            {"name": "body", "dataType": ["text"]},
            {"name": "rank", "dataType": ["int"]},
        ],
        "vectorIndexConfig": {"distance": "l2-squared"},
    })
    rng = np.random.default_rng(11)
    vecs = rng.standard_normal((30, 16)).astype(np.float32)
    app.batch.add_objects([{
        "class": "Doc",
        "id": str(uuidlib.UUID(int=i + 1)),
        "properties": {"body": f"common term{i} text", "rank": i},
        "vector": vecs[i].tolist(),
    } for i in range(30)])
    srv = GrpcServer(app, port=0)
    srv.start()
    client = SearchClient(f"127.0.0.1:{srv.port}")
    yield app, srv, client, vecs
    client.close()
    srv.stop()
    app.shutdown()


def test_near_vector_search(setup):
    app, srv, client, vecs = setup
    req = pb.SearchRequest(
        class_name="Doc", limit=3,
        near_vector=pb.NearVectorParams(vector=vecs[5].tolist()),
        additional_properties=["distance", "vector"],
    )
    reply = client.search(req)
    assert len(reply.results) == 3
    top = reply.results[0]
    assert top.id == str(uuidlib.UUID(int=6))
    assert top.distance < 1e-3
    assert len(top.vector) == 16
    props = json.loads(top.properties_json)
    assert props["rank"] == 5


def test_property_selection(setup):
    _, _, client, vecs = setup
    req = pb.SearchRequest(
        class_name="Doc", limit=1, properties=["rank"],
        near_vector=pb.NearVectorParams(vector=vecs[0].tolist()))
    props = json.loads(client.search(req).results[0].properties_json)
    assert set(props) == {"rank"}


def test_bm25_and_filter(setup):
    _, _, client, _ = setup
    req = pb.SearchRequest(
        class_name="Doc", limit=5,
        bm25=pb.BM25Params(query="term7"),
    )
    reply = client.search(req)
    assert reply.results and json.loads(reply.results[0].properties_json)["rank"] == 7

    req = pb.SearchRequest(
        class_name="Doc", limit=30,
        where_json=json.dumps(
            {"operator": "GreaterThanEqual", "path": ["rank"], "valueInt": 25}),
    )
    reply = client.search(req)
    ranks = {json.loads(r.properties_json)["rank"] for r in reply.results}
    assert ranks == {25, 26, 27, 28, 29}


def test_unknown_class_aborts(setup):
    _, _, client, vecs = setup
    req = pb.SearchRequest(class_name="Nope", limit=1,
                           near_vector=pb.NearVectorParams(vector=vecs[0].tolist()))
    with pytest.raises(grpc.RpcError) as e:
        client.search(req)
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_batch_search_one_dispatch(setup):
    _, _, client, vecs = setup
    breq = pb.BatchSearchRequest(requests=[
        pb.SearchRequest(class_name="Doc", limit=2,
                         near_vector=pb.NearVectorParams(vector=vecs[i].tolist()))
        for i in range(8)
    ])
    reply = client.batch_search(breq)
    assert len(reply.replies) == 8
    for i, one in enumerate(reply.replies):
        assert one.results[0].id == str(uuidlib.UUID(int=i + 1))


def test_native_reply_marshaller_equivalence(setup):
    """The native wire builder (native/reply.cpp) must produce bytes that
    parse to EXACTLY what the upb marshaller produces, across unicode
    props, empty props, missing distance, and nested JSON."""
    from weaviate_tpu.db.shard import SearchResult
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.server import reply_native
    from weaviate_tpu.server.grpc_server import fast_reply_bytes, result_to_proto

    assert reply_native.available(), "native reply marshaller must build"
    cases = [
        {"body": "héllo wörld é中文", "rank": 1, "tags": ["a", "b"]},
        {},
        {"nested": {"x": [1.5, None, True], "y": "z"}},
    ]
    results = []
    for i, props in enumerate(cases):
        raw = StorObj(class_name="Doc", uuid=str(uuidlib.UUID(int=900 + i)),
                      properties=props, vector=np.arange(4, dtype=np.float32),
                      doc_id=900 + i).to_binary()
        obj = StorObj.from_binary(raw, include_vector=False)
        results.append(SearchResult(
            obj=obj, distance=0.25 * i if i != 1 else None, shard="s"))
    req = pb.SearchRequest(class_name="Doc", limit=3)
    fast = fast_reply_bytes(results, req, took=0.125)
    assert fast is not None, "fast path must engage for pristine objects"
    got = pb.SearchReply.FromString(fast)
    want = pb.SearchReply(took_seconds=0.125)
    want.results.extend(result_to_proto(r, req) for r in results)
    assert got == want

    # whole-batch builder: two replies (2 + 1 results) parse identically
    raws = [r.obj.raw_if_pristine() for r in results]
    batch = reply_native.build_batch_reply(
        raws, [r.distance for r in results], [None] * 3, [2, 1], 0.125)
    got_b = pb.BatchSearchReply.FromString(batch)
    want_b = pb.BatchSearchReply()
    for rows in (results[:2], results[2:]):
        one = pb.SearchReply(took_seconds=0.125)
        one.results.extend(result_to_proto(r, req) for r in rows)
        want_b.replies.append(one)
    assert got_b == want_b

    # property filtering / vectors / mutated objects refuse the fast path
    assert fast_reply_bytes(
        results, pb.SearchRequest(properties=["rank"]), 0.0) is None
    assert fast_reply_bytes(
        results, pb.SearchRequest(additional_properties=["vector"]), 0.0) is None
    results[0].obj.properties["body"] = "mutated"
    assert fast_reply_bytes(results, req, 0.0) is None


def test_batch_search_uses_native_path(setup):
    """BatchSearch over the real wire must serve nearVector batches through
    the native marshaller (not silently fall back)."""
    from weaviate_tpu.server import grpc_server as gs

    _, _, client, vecs = setup
    calls = []
    orig_one = gs.reply_native.build_search_reply
    orig_batch = gs.reply_native.build_batch_reply

    def spy_one(*a, **k):
        out = orig_one(*a, **k)
        calls.append(out is not None)
        return out

    def spy_batch(*a, **k):
        out = orig_batch(*a, **k)
        calls.append(out is not None)
        return out

    gs.reply_native.build_search_reply = spy_one
    gs.reply_native.build_batch_reply = spy_batch
    try:
        breq = pb.BatchSearchRequest(requests=[
            pb.SearchRequest(class_name="Doc", limit=2,
                             near_vector=pb.NearVectorParams(vector=vecs[i].tolist()))
            for i in range(4)
        ])
        reply = client.batch_search(breq)
    finally:
        gs.reply_native.build_search_reply = orig_one
        gs.reply_native.build_batch_reply = orig_batch
    assert len(reply.replies) == 4 and calls and all(calls)
    for i, one in enumerate(reply.replies):
        assert one.results[0].id == str(uuidlib.UUID(int=i + 1))
        assert json.loads(one.results[0].properties_json)["rank"] == i


def test_raw_batch_lane_equivalence_and_engagement(tmp_path):
    """The zero-object raw lane (device search -> packed native point-gets
    -> packed native reply) must ENGAGE once memtables are flushed, and its
    replies must be message-equal to the general path's — including when a
    winner was deleted between import and serving (dropped by both)."""
    from weaviate_tpu.server.grpc_server import SearchServicer

    app = App(data_path=str(tmp_path / "raw"))
    app.schema.add_class({
        "class": "Raw",
        "properties": [{"name": "rank", "dataType": ["int"]}],
        "vectorIndexConfig": {"distance": "l2-squared"},
    })
    rng = np.random.default_rng(5)
    vecs = rng.standard_normal((300, 16)).astype(np.float32)
    app.batch.add_objects([{
        "class": "Raw", "id": str(uuidlib.UUID(int=i + 1)),
        "properties": {"rank": i}, "vector": vecs[i].tolist(),
    } for i in range(300)])
    idx = app.db.get_index("Raw")
    shard = next(iter(idx.shards.values()))
    sv = SearchServicer(app)
    breq = pb.BatchSearchRequest(requests=[
        pb.SearchRequest(class_name="Raw", limit=3,
                         near_vector=pb.NearVectorParams(vector=vecs[i].tolist()))
        for i in range(16)
    ])

    class Ctx:
        def abort(self, *a):
            raise AssertionError(a)

    # memtable-resident: raw lane must decline (exactness), general path serves
    assert sv._raw_batch_lane(breq, 0.0) is None
    got = sv.BatchSearch(breq, Ctx())
    general_before = pb.BatchSearchReply.FromString(
        got if isinstance(got, (bytes, bytearray)) else got.SerializeToString())

    # flush memtables -> segments: the raw lane must now engage
    for b in (shard.objects, shard.docid_lookup):
        b.flush_memtable()
    raw_bytes = sv._raw_batch_lane(breq, 0.0)
    assert raw_bytes is not None, "raw lane did not engage on flushed segments"
    raw = pb.BatchSearchReply.FromString(raw_bytes)
    assert len(raw.replies) == 16
    for i, one in enumerate(raw.replies):
        want = general_before.replies[i]
        assert len(one.results) == len(want.results) == 3
        for a, b_ in zip(one.results, want.results):
            assert a.id == b_.id
            assert abs(a.distance - b_.distance) < 1e-5
            assert json.loads(a.properties_json) == json.loads(b_.properties_json)
            assert a.creation_time_unix == b_.creation_time_unix

    # ineligible requests (properties filter) must decline
    breq2 = pb.BatchSearchRequest(requests=[
        pb.SearchRequest(class_name="Raw", limit=3, properties=["rank"],
                         near_vector=pb.NearVectorParams(vector=vecs[0].tolist()))])
    assert sv._raw_batch_lane(breq2, 0.0) is None
    app.shutdown()


def test_raw_lane_concurrent_searches_and_writes(tmp_path):
    """Production concurrency shape: batch searches hammer the raw lane
    from multiple threads while a writer keeps mutating the class. Every
    reply must be well-formed with correct distances for its own query —
    the lane may bounce between engaged (flushed) and declined (memtable
    busy), but never corrupt a result."""
    import threading

    from weaviate_tpu.server.grpc_server import SearchServicer

    app = App(data_path=str(tmp_path / "conc"))
    app.schema.add_class({
        "class": "C",
        "properties": [{"name": "rank", "dataType": ["int"]}],
        "vectorIndexConfig": {"distance": "l2-squared"},
    })
    rng = np.random.default_rng(9)
    vecs = rng.standard_normal((400, 16)).astype(np.float32)
    app.batch.add_objects([{
        "class": "C", "id": str(uuidlib.UUID(int=i + 1)),
        "properties": {"rank": i}, "vector": vecs[i].tolist(),
    } for i in range(400)])
    idx = app.db.get_index("C")
    shard = next(iter(idx.shards.values()))
    for b in (shard.objects, shard.docid_lookup):
        b.flush_memtable()
    sv = SearchServicer(app)

    class Ctx:
        def abort(self, *a):
            raise AssertionError(a)

    breq = pb.BatchSearchRequest(requests=[
        pb.SearchRequest(class_name="C", limit=3,
                         near_vector=pb.NearVectorParams(vector=vecs[i].tolist()))
        for i in range(16)
    ])
    errors: list = []
    stop = threading.Event()

    def searcher():
        try:
            _searcher()
        except Exception as e:  # noqa: BLE001 — a dead thread must fail the test
            errors.append(("searcher-raised", repr(e)))

    def _searcher():
        while not stop.is_set():
            out = sv.BatchSearch(breq, Ctx())
            rep = pb.BatchSearchReply.FromString(
                out if isinstance(out, (bytes, bytearray))
                else out.SerializeToString())
            if len(rep.replies) != 16:
                errors.append(("replies", len(rep.replies)))
                return
            for i, one in enumerate(rep.replies):
                if one.error_message or not one.results:
                    errors.append((i, one.error_message))
                    return
                # query i is doc i's own vector: its top hit is itself with
                # ~zero distance (docs 0..15 are never touched by the writer)
                if one.results[0].id != str(uuidlib.UUID(int=i + 1)) or \
                        one.results[0].distance > 1e-3:
                    errors.append((i, one.results[0].id,
                                   one.results[0].distance))
                    return

    def writer():
        try:
            _writer()
        except Exception as e:  # noqa: BLE001
            errors.append(("writer-raised", repr(e)))

    def _writer():
        j = 1000
        while not stop.is_set():
            app.batch.add_objects([{
                "class": "C", "id": str(uuidlib.UUID(int=j + 1)),
                "properties": {"rank": j},
                "vector": (rng.standard_normal(16) * 10 + 50).astype(
                    np.float32).tolist(),  # far away: never a top hit
            }])
            j += 1
            if j % 7 == 0:  # re-flush so the raw lane re-engages
                for b in (shard.objects, shard.docid_lookup):
                    b.flush_memtable()

    threads = [threading.Thread(target=searcher) for _ in range(3)]
    wt = threading.Thread(target=writer)
    for t in threads:
        t.start()
    wt.start()
    import time as _t

    _t.sleep(3.0)
    stop.set()
    for t in threads + [wt]:
        t.join()
    assert not errors, errors[:3]
    app.shutdown()


def test_batch_search_per_slot_errors(setup):
    _, _, client, vecs = setup
    breq = pb.BatchSearchRequest(requests=[
        pb.SearchRequest(class_name="Doc", limit=2,
                         near_vector=pb.NearVectorParams(vector=vecs[0].tolist())),
        pb.SearchRequest(class_name="Doc", limit=2, where_json="{not json"),
        pb.SearchRequest(class_name="Ghost", limit=2,
                         near_vector=pb.NearVectorParams(vector=vecs[0].tolist())),
    ])
    reply = client.batch_search(breq)
    assert len(reply.replies) == 3
    assert reply.replies[0].results and not reply.replies[0].error_message
    assert reply.replies[1].error_message  # malformed where_json
    assert reply.replies[2].error_message  # unknown class
    assert not reply.replies[1].results and not reply.replies[2].results
