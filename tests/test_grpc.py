"""gRPC Search/BatchSearch service tests.

Reference surface: adapters/handlers/grpc/server.go + grpc/weaviate.proto.
"""

import json
import uuid as uuidlib

import grpc
import numpy as np
import pytest

from weaviate_tpu.grpcapi import weaviate_pb2 as pb
from weaviate_tpu.server import App
from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    app = App(data_path=str(tmp_path_factory.mktemp("data")))
    app.schema.add_class({
        "class": "Doc",
        "properties": [
            {"name": "body", "dataType": ["text"]},
            {"name": "rank", "dataType": ["int"]},
        ],
        "vectorIndexConfig": {"distance": "l2-squared"},
    })
    rng = np.random.default_rng(11)
    vecs = rng.standard_normal((30, 16)).astype(np.float32)
    app.batch.add_objects([{
        "class": "Doc",
        "id": str(uuidlib.UUID(int=i + 1)),
        "properties": {"body": f"common term{i} text", "rank": i},
        "vector": vecs[i].tolist(),
    } for i in range(30)])
    srv = GrpcServer(app, port=0)
    srv.start()
    client = SearchClient(f"127.0.0.1:{srv.port}")
    yield app, srv, client, vecs
    client.close()
    srv.stop()
    app.shutdown()


def test_near_vector_search(setup):
    app, srv, client, vecs = setup
    req = pb.SearchRequest(
        class_name="Doc", limit=3,
        near_vector=pb.NearVectorParams(vector=vecs[5].tolist()),
        additional_properties=["distance", "vector"],
    )
    reply = client.search(req)
    assert len(reply.results) == 3
    top = reply.results[0]
    assert top.id == str(uuidlib.UUID(int=6))
    assert top.distance < 1e-3
    assert len(top.vector) == 16
    props = json.loads(top.properties_json)
    assert props["rank"] == 5


def test_property_selection(setup):
    _, _, client, vecs = setup
    req = pb.SearchRequest(
        class_name="Doc", limit=1, properties=["rank"],
        near_vector=pb.NearVectorParams(vector=vecs[0].tolist()))
    props = json.loads(client.search(req).results[0].properties_json)
    assert set(props) == {"rank"}


def test_bm25_and_filter(setup):
    _, _, client, _ = setup
    req = pb.SearchRequest(
        class_name="Doc", limit=5,
        bm25=pb.BM25Params(query="term7"),
    )
    reply = client.search(req)
    assert reply.results and json.loads(reply.results[0].properties_json)["rank"] == 7

    req = pb.SearchRequest(
        class_name="Doc", limit=30,
        where_json=json.dumps(
            {"operator": "GreaterThanEqual", "path": ["rank"], "valueInt": 25}),
    )
    reply = client.search(req)
    ranks = {json.loads(r.properties_json)["rank"] for r in reply.results}
    assert ranks == {25, 26, 27, 28, 29}


def test_unknown_class_aborts(setup):
    _, _, client, vecs = setup
    req = pb.SearchRequest(class_name="Nope", limit=1,
                           near_vector=pb.NearVectorParams(vector=vecs[0].tolist()))
    with pytest.raises(grpc.RpcError) as e:
        client.search(req)
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_batch_search_one_dispatch(setup):
    _, _, client, vecs = setup
    breq = pb.BatchSearchRequest(requests=[
        pb.SearchRequest(class_name="Doc", limit=2,
                         near_vector=pb.NearVectorParams(vector=vecs[i].tolist()))
        for i in range(8)
    ])
    reply = client.batch_search(breq)
    assert len(reply.replies) == 8
    for i, one in enumerate(reply.replies):
        assert one.results[0].id == str(uuidlib.UUID(int=i + 1))


def test_batch_search_per_slot_errors(setup):
    _, _, client, vecs = setup
    breq = pb.BatchSearchRequest(requests=[
        pb.SearchRequest(class_name="Doc", limit=2,
                         near_vector=pb.NearVectorParams(vector=vecs[0].tolist())),
        pb.SearchRequest(class_name="Doc", limit=2, where_json="{not json"),
        pb.SearchRequest(class_name="Ghost", limit=2,
                         near_vector=pb.NearVectorParams(vector=vecs[0].tolist())),
    ])
    reply = client.batch_search(breq)
    assert len(reply.replies) == 3
    assert reply.replies[0].results and not reply.replies[0].error_message
    assert reply.replies[1].error_message  # malformed where_json
    assert reply.replies[2].error_message  # unknown class
    assert not reply.replies[1].results and not reply.replies[2].results
