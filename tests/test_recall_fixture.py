"""The recall fixture tier: every index implementation measured against the
committed fixture dataset with exact ground truth.

Reference: adapters/repos/db/vector/hnsw/recall_test.go:32,137 — fixture
vectors/queries/ground-truth with recall >= 0.99 asserted. Covered paths:

- hnsw_tpu exact scan (l2 + cosine)      >= 0.99
- hnsw_tpu filtered: masked full scan AND small-allowList gather path
- hnsw_tpu + PQ with float rescoring     >= 0.95 (reference's PQ tier)
- hnsw_tpu + PQ without rescoring        >= 0.70 (sanity floor, code path)
- hnsw native graph (l2 + cosine)        >= 0.99
- hnsw_tpu_mesh (8-chip virtual mesh)    >= 0.99
"""

import os

import numpy as np
import pytest

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index import new_vector_index
from weaviate_tpu.storage.bitmap import Bitmap

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "recall_fixture.npz")
K = 10
SENTINEL = np.iinfo(np.uint64).max


@pytest.fixture(scope="module")
def fixture():
    data = np.load(FIXTURE)
    return (
        data["vectors"].astype(np.float32),
        data["queries"].astype(np.float32),
        data["gt"],
        data["gt_cos"],
    )


def fixture_is_reproducible():
    """The committed artifact must match its committed generator."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "genfix", os.path.join(os.path.dirname(FIXTURE), "generate_recall_fixture.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.generate()


def test_fixture_matches_generator(fixture):
    vectors, queries, gt, gt_cos = fixture
    g_vectors, g_queries, g_gt, g_gt_cos = fixture_is_reproducible()
    np.testing.assert_array_equal(vectors, g_vectors)
    np.testing.assert_array_equal(queries, g_queries)
    np.testing.assert_array_equal(gt, g_gt)
    np.testing.assert_array_equal(gt_cos, g_gt_cos)


def _recall(index, queries, gt, k=K, allow=None, gt_filter=None):
    ids, dists = index.search_by_vectors(queries, k, allow_list=allow)
    hits = 0
    for i in range(queries.shape[0]):
        want = set((gt_filter[i] if gt_filter is not None else gt[i])[:k].tolist())
        got = set(int(x) for x in ids[i] if x != SENTINEL)
        hits += len(want & got)
    return hits / (queries.shape[0] * k)


def _mk(tmp_path, index_type, metric=vi.DISTANCE_L2, **cfg):
    config = vi.parse_and_validate_config(index_type, {"distance": metric, **cfg})
    return new_vector_index(config, str(tmp_path))


def test_tpu_exact_l2(tmp_path, fixture):
    vectors, queries, gt, _ = fixture
    idx = _mk(tmp_path, "hnsw_tpu")
    idx.add_batch(np.arange(len(vectors)), vectors)
    r = _recall(idx, queries, gt)
    assert r >= 0.99, r
    # the recall bar must hold on the SERVING kernel: 200-query batches
    # qualify for the fused gmin path, and a silent gating regression
    # (gmin disabled -> legacy scan) would otherwise pass unnoticed
    assert idx._gmin_validated and not idx._gmin_broken
    idx.shutdown()


def test_tpu_exact_cosine(tmp_path, fixture):
    vectors, queries, _, gt_cos = fixture
    idx = _mk(tmp_path, "hnsw_tpu", metric=vi.DISTANCE_COSINE)
    idx.add_batch(np.arange(len(vectors)), vectors)
    r = _recall(idx, queries, gt_cos)
    assert r >= 0.99, r
    idx.shutdown()


def _filtered_gt(vectors, queries, allowed_mask, k):
    allowed_rows = np.nonzero(allowed_mask)[0]
    sub = vectors[allowed_rows]
    gt = np.empty((len(queries), k), np.int64)
    for i, q in enumerate(queries):
        d = ((sub - q) ** 2).sum(1)
        gt[i] = allowed_rows[np.argsort(d, kind="stable")[:k]]
    return gt


def test_tpu_filtered_masked_scan(tmp_path, fixture):
    """allowList ABOVE the flat-search cutoff: device bitmap masked scan."""
    vectors, queries, _, _ = fixture
    idx = _mk(tmp_path, "hnsw_tpu", flatSearchCutoff=10)
    idx.add_batch(np.arange(len(vectors)), vectors)
    mask = np.arange(len(vectors)) % 3 == 0
    allow = Bitmap(np.nonzero(mask)[0].astype(np.uint64))
    gt_f = _filtered_gt(vectors, queries[:50], mask, K)
    r = _recall(idx, queries[:50], None, allow=allow, gt_filter=gt_f)
    assert r >= 0.99, r
    idx.shutdown()


def test_tpu_filtered_gather_path(tmp_path, fixture):
    """small allowList BELOW the cutoff: gather kernel (flat_search.go)."""
    vectors, queries, _, _ = fixture
    idx = _mk(tmp_path, "hnsw_tpu")  # default cutoff 40000 > 500
    idx.add_batch(np.arange(len(vectors)), vectors)
    rng = np.random.default_rng(7)
    allowed = np.sort(rng.choice(len(vectors), 500, replace=False))
    mask = np.zeros(len(vectors), bool)
    mask[allowed] = True
    allow = Bitmap(allowed.astype(np.uint64))
    gt_f = _filtered_gt(vectors, queries[:50], mask, K)
    r = _recall(idx, queries[:50], None, allow=allow, gt_filter=gt_f)
    assert r >= 0.99, r
    idx.shutdown()


def test_tpu_pq_rescored(tmp_path, fixture):
    vectors, queries, gt, _ = fixture
    idx = _mk(tmp_path, "hnsw_tpu",
              pq={"enabled": False, "segments": 8, "centroids": 256})
    idx.add_batch(np.arange(len(vectors)), vectors)
    idx.compress()
    assert idx.compressed
    r = _recall(idx, queries, gt)
    assert r >= 0.95, r
    idx.shutdown()


def test_tpu_pq_unrescored_floor(tmp_path, fixture):
    """Raw PQ without rescoring: segments=dims/2 keeps quantization error
    small enough for a 0.90 floor (8 segments on this clustered fixture
    lands near 0.40 — rescoring is the default for a reason)."""
    vectors, queries, gt, _ = fixture
    idx = _mk(tmp_path, "hnsw_tpu",
              pq={"enabled": False, "segments": 16, "centroids": 256,
                  "rescore": False})
    idx.add_batch(np.arange(len(vectors)), vectors)
    idx.compress()
    r = _recall(idx, queries, gt)
    assert r >= 0.70, r
    idx.shutdown()


def test_tpu_pq_filtered(tmp_path, fixture):
    vectors, queries, _, _ = fixture
    idx = _mk(tmp_path, "hnsw_tpu", flatSearchCutoff=10,
              pq={"enabled": False, "segments": 8, "centroids": 256})
    idx.add_batch(np.arange(len(vectors)), vectors)
    idx.compress()
    mask = np.arange(len(vectors)) % 2 == 0
    allow = Bitmap(np.nonzero(mask)[0].astype(np.uint64))
    gt_f = _filtered_gt(vectors, queries[:50], mask, K)
    r = _recall(idx, queries[:50], None, allow=allow, gt_filter=gt_f)
    assert r >= 0.95, r
    idx.shutdown()


def test_hnsw_graph_l2(tmp_path, fixture):
    vectors, queries, gt, _ = fixture
    idx = _mk(tmp_path, "hnsw", efConstruction=128, maxConnections=16)
    idx.add_batch(np.arange(len(vectors)), vectors)
    r = _recall(idx, queries, gt)
    assert r >= 0.99, r
    idx.shutdown()


def test_hnsw_graph_cosine(tmp_path, fixture):
    vectors, queries, _, gt_cos = fixture
    idx = _mk(tmp_path, "hnsw", metric=vi.DISTANCE_COSINE,
              efConstruction=128, maxConnections=16)
    idx.add_batch(np.arange(len(vectors)), vectors)
    r = _recall(idx, queries, gt_cos)
    assert r >= 0.99, r
    idx.shutdown()


def test_mesh_index(tmp_path, fixture):
    vectors, queries, gt, _ = fixture
    idx = _mk(tmp_path, "hnsw_tpu_mesh")
    idx.add_batch(np.arange(len(vectors)), vectors)
    r = _recall(idx, queries, gt)
    assert r >= 0.99, r
    idx.shutdown()
