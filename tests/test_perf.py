"""Continuous device-performance attribution (monitoring/costmodel.py +
monitoring/perf.py) and its wiring.

The acceptance-critical invariants pinned here:

  1. ATTRIBUTION IDENTITY — per-rider flops/bytes are integer telescoping
     splits, so when every rider of a coalesced dispatch is sampled they
     sum BIT-EXACTLY to the dispatch totals (the cost-model twin of the
     PR-3 device-time identity).
  2. DUTY-CYCLE MATH — the busy integrator computes the interval UNION
     (overlaps merged, window trimmed) on synthetic interval sets.
  3. DISABLED = ZERO PERF WORK — with TRACING_ENABLED unset, the serving
     path constructs no DispatchShape and never touches the PerfWindow
     (spy-asserted the same way as the tracing spy).
  4. EXPOSITION — /debug/perf serves the window summary end to end and
     /metrics carries the rolling roofline/duty gauges.

Plus: cost-model tier formulas, the shared-costmodel BM25 batch shape,
the front-door gate sheds surfaced in coalescer stats, and the
signal/atexit device-trace teardown.
"""

import json
import threading
import urllib.request
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import Config, load_config
from weaviate_tpu.monitoring import costmodel, perf, tracing
from weaviate_tpu.serving import robustness
from weaviate_tpu.usecases.traverser import GetParams

N, DIM, K = 400, 16, 5


@pytest.fixture(autouse=True)
def _reset_globals():
    yield
    tracing.configure(None)
    perf.configure(None)


def _mk_app(tmp_path, tracing_on=True, coalesce=True, window_ms=200.0,
            n=N, pq=False):
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.server import App

    cfg = Config()
    cfg.coalescer.enabled = coalesce
    cfg.coalescer.window_ms = window_ms
    cfg.tracing.enabled = tracing_on
    cfg.tracing.sample_rate = 1.0
    cfg.tracing.slow_query_threshold_ms = 0.0
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    cls = {"class": "Pf", "vectorIndexType": "hnsw_tpu",
           "vectorIndexConfig": {"distance": "l2-squared"},
           "properties": [{"name": "tag", "dataType": ["text"]}]}
    if pq:
        cls["vectorIndexConfig"]["pq"] = {
            "enabled": True, "segments": 4, "centroids": 16}
    app.schema.add_class(cls)
    rng = np.random.default_rng(11)
    vecs = rng.integers(-8, 8, (n, DIM)).astype(np.float32)
    idx = app.db.get_index("Pf")
    idx.put_batch([
        StorObj(class_name="Pf", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"tag": "even" if i % 2 == 0 else "odd"},
                vector=vecs[i])
        for i in range(n)])
    return app, idx, vecs


def _walk(span):
    yield span
    for c in span.get("children", []):
        yield from _walk(c)


def _dispatch_spans(trace_dicts):
    return [s for tr in trace_dicts for s in _walk(tr["root"])
            if s["name"] == "dispatch"]


# -- cost model ---------------------------------------------------------------

def test_split_exact_sums_bit_exactly():
    for total, rows in [(0, [1, 2]), (7, [1, 1, 1]),
                        (2 * 21 * 50_000 * 64, [1] * 21),
                        (123456789, [3, 7, 11, 2]),
                        (10**15, [5, 9, 2, 200])]:
        parts = costmodel.split_exact(total, rows, sum(rows))
        assert sum(parts) == total
        assert all(isinstance(p, int) for p in parts)
    # partial coverage (unsampled riders): parts stay proportional and
    # never exceed the total
    parts = costmodel.split_exact(1000, [1, 1], 4)
    assert sum(parts) == 500


def test_dispatch_shape_tier_formulas():
    # exact f32 scan: flops 2·B·N·D, bytes N·4D
    s = costmodel.DispatchShape(costmodel.TIER_EXACT, n=1000, dim=64,
                                batch=8, bytes_per_row=64 * 4, k=10)
    assert s.flops() == 2 * 8 * 1000 * 64
    assert s.bytes() == 1000 * 64 * 4
    # pq codes: same useful flops, M bytes per row
    s = costmodel.DispatchShape(costmodel.TIER_PQ_CODES, n=1000, dim=64,
                                batch=8, bytes_per_row=32, k=10)
    assert s.bytes() == 1000 * 32
    # bm25 matmul: n=n_pad, dim=U, batch=Q, bytes U·n_pad·4
    s = costmodel.DispatchShape(costmodel.TIER_BM25_MATMUL, n=4096,
                                dim=16, batch=64, bytes_per_row=16 * 4)
    assert s.flops() == 2 * 64 * 4096 * 16
    assert s.bytes() == 4096 * 16 * 4


def test_shape_ledger_and_hop():
    s = costmodel.DispatchShape(costmodel.TIER_EXACT, n=10, dim=4,
                                batch=1, bytes_per_row=16)
    assert s.ledger() == {}          # nothing measured yet
    assert s.hop_ms() == -1.0
    s.enqueue_ms = 1.0
    s.device_ms = 3.0
    s.finalize_ms = 5.0
    s.hydrate_ms = 2.0
    assert s.hop_ms() == pytest.approx(2.0)
    led = s.ledger()
    assert led == {"enqueue": 1.0, "device": 3.0,
                   "gather_hop": pytest.approx(2.0), "hydrate": 2.0}


def test_roofline_time_and_qps_forms_agree():
    # 1 batch/s of (B=256, N=1e5, D=128, f32): the QPS form at qps=256
    # equals the time form over 1 second of the same work
    f = 2.0 * 256 * 100_000 * 128
    b = 100_000 * 512
    a = costmodel.roofline(f, b, 1.0, "tpu-v5e")
    q = costmodel.roofline_from_qps(256.0, 100_000, 128, 256, 512, "tpu-v5e")
    assert a == q


# -- duty cycle ---------------------------------------------------------------

def test_duty_cycle_union_math():
    d = perf.DutyCycle(window_s=100.0)
    # disjoint: [0,1] + [2,3] = 2 busy over observed 10s
    d.record(0.0, 1.0)
    d.record(2.0, 3.0)
    assert d.value(now=10.0) == pytest.approx(0.2)
    # overlap merged: [2.5, 4] adds only 1s (2.5-3 already covered)
    d.record(2.5, 4.0)
    assert d.value(now=10.0) == pytest.approx(0.3)
    # containment adds nothing
    d.record(2.6, 3.9)
    assert d.value(now=10.0) == pytest.approx(0.3)


def test_duty_cycle_window_trim_and_saturation():
    d = perf.DutyCycle(window_s=5.0)
    d.record(0.0, 4.0)
    # at t=4 observed span is 4s, busy 4s -> 1.0
    assert d.value(now=4.0) == pytest.approx(1.0)
    # at t=20 the interval (attributed at its end, t=4) left the window
    assert d.value(now=20.0) == 0.0


def test_duty_cycle_empty():
    assert perf.DutyCycle(10.0).value(now=5.0) == 0.0


# -- the perf window (unit) ---------------------------------------------------

def _stamped_shape(device_ms=4.0, wall_ms=10.0, **kw):
    s = costmodel.DispatchShape(
        kw.pop("tier", costmodel.TIER_EXACT), n=kw.pop("n", 50_000),
        dim=kw.pop("dim", 64), batch=kw.pop("batch", 16),
        bytes_per_row=kw.pop("bytes_per_row", 256), k=10)
    s.enqueue_ms = 1.0
    s.device_ms = device_ms
    s.finalize_ms = device_ms + 1.5
    s.hydrate_ms = 2.0
    import time

    t = time.perf_counter()
    s.t_start = t - wall_ms / 1000.0
    s.t_fetch = t - 0.001
    s.t_end = t
    s.t_fetch_mono = time.monotonic()
    return s


def test_perf_window_summary_and_clear():
    w = perf.PerfWindow(window_s=60.0, backend="tpu-v5e")
    for _ in range(4):
        w.record_dispatch(_stamped_shape(), rows=16)
    w.note_phase("queue_wait", 1.2)
    w.note_phase("scatter", 0.3)
    s = w.summary()
    assert s["dispatches"] == 4
    assert s["rows"] == 64
    assert 0.0 < s["duty_cycle"] <= 1.0
    assert s["tiers"] == {costmodel.TIER_EXACT: 4}
    assert set(s["phases"]) >= {"enqueue", "device", "gather_hop",
                                "hydrate", "queue_wait", "scatter"}
    shares = [v["share_of_wall"] for v in s["phases"].values()]
    assert all(sh is not None for sh in shares)
    assert sum(shares) == pytest.approx(1.0, abs=0.01)
    # both roofline forms present and consistent with the cost model
    assert s["roofline"]["mfu_pct"] > 0.0
    assert s["roofline_device_busy"]["mfu_pct"] > 0.0
    w.clear()
    s2 = w.summary()
    assert s2["dispatches"] == 0 and s2["duty_cycle"] == 0.0
    assert s2["dispatches_lifetime"] == 4  # lifetime survives clear


def test_perf_window_gauges(tmp_path):
    from weaviate_tpu.monitoring import noop_metrics

    m = noop_metrics()
    w = perf.PerfWindow(window_s=60.0, metrics=m, backend="tpu-v5e")
    w.record_dispatch(_stamped_shape(), rows=16)
    text = m.expose().decode()
    assert "weaviate_device_mfu_pct" in text
    assert "weaviate_device_duty_cycle" in text
    assert "weaviate_perf_phase_share" in text


def test_duty_interval_anchored_at_fetch_not_record_time():
    """Two concurrent dispatches whose in-flight windows fully overlap
    must not double-count duty just because their HYDRATE times differ:
    the interval is anchored at the monotonic fetch stamp, not at the
    (hydration-delayed) record call."""
    import time

    w = perf.PerfWindow(window_s=60.0, backend="tpu-v5e")
    fetch_mono = time.monotonic() - 0.05  # both fetched 50ms ago
    for _ in range(2):
        s = costmodel.DispatchShape(costmodel.TIER_EXACT, n=1000, dim=16,
                                    batch=4, bytes_per_row=64)
        t = time.perf_counter()
        s.t_start, s.t_fetch, s.t_end = t - 0.010, t, t + 0.001
        s.device_ms = 10.0
        s.t_fetch_mono = fetch_mono
        w.record_dispatch(s)  # second record is "after a slow hydrate"
    busy = w.summary()["device_busy_s"]
    assert busy == pytest.approx(0.010, abs=0.004)  # union, not 0.020


def test_gather_empty_shard_records_zero_cost(tmp_path):
    """An allowList whose docs are absent from this shard runs no device
    work — the perf shape must credit neither phantom flops/bytes nor a
    phantom duty-cycle interval (a multi-shard filtered workload must not
    read near-1.0 duty while the device is idle)."""
    from weaviate_tpu.storage.bitmap import Bitmap

    app, idx, vecs = _mk_app(tmp_path, coalesce=False)
    try:
        vidx = idx.single_local_shard().vector_index
        absent = Bitmap(np.array([10**9], dtype=np.uint64))
        ids, dists = vidx.search_by_vectors(vecs[:1], K, absent)
        assert ids.shape[1] == 0
        shape = vidx.pop_dispatch_shape()
        assert shape is not None and shape.tier == costmodel.TIER_GATHER
        assert shape.n == 0 and shape.flops() == 0 and shape.bytes() == 0
        assert shape.t_fetch == 0.0  # no device call ran
        w = perf.PerfWindow(window_s=60.0, backend="tpu-v5e")
        w.record_dispatch(shape, rows=1)
        s = w.summary()
        assert s["duty_cycle"] == 0.0 and s["device_busy_s"] == 0.0
    finally:
        app.shutdown()


def test_sigterm_teardown_honors_sig_ign(monkeypatch):
    """A process that deliberately ignored SIGTERM must not be killed by
    the teardown chain: stop the capture, swallow the signal."""
    import signal

    from weaviate_tpu.monitoring import profiling

    killed = []
    monkeypatch.setattr(profiling.os, "kill",
                        lambda *a: killed.append(a))
    monkeypatch.setitem(profiling._teardown_state, "prev_sigterm",
                        signal.SIG_IGN)
    profiling._sigterm_teardown(signal.SIGTERM, None)
    assert killed == []


def test_teardown_signal_half_retries_after_thread_failure(monkeypatch):
    """A first install off the main thread must not latch the signal half
    closed — a later main-thread call still arms the SIGTERM handler."""
    import signal

    from weaviate_tpu.monitoring import profiling

    monkeypatch.setitem(profiling._teardown_state, "signal_installed", False)
    prev = signal.getsignal(signal.SIGTERM)
    try:
        got = []
        t = threading.Thread(
            target=lambda: got.append(profiling.install_trace_teardown()))
        t.start(); t.join()
        assert got == [False]  # signal.signal refuses off the main thread
        assert profiling._teardown_state["signal_installed"] is False
        if threading.current_thread() is threading.main_thread():
            assert profiling.install_trace_teardown() is True
            assert profiling._teardown_state["signal_installed"] is True
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_per_dispatch_mfu_divides_by_wall_not_fetch():
    """A dispatch whose result was already resident fetches in ~0 ms; the
    blocked-fetch time is a LOWER bound on device time, so the roofline
    fact must divide by the dispatch's enqueue->fetch wall — dividing by
    the fetch would fabricate absurd >100% MFU (seen live: 418%)."""
    import time

    tracing.configure(tracing.Tracer())
    try:
        tr = tracing.Tracer().start_request("test", "q")
        shape = costmodel.DispatchShape(
            costmodel.TIER_EXACT, n=2000, dim=32, batch=14,
            bytes_per_row=128, k=5)
        shape.enqueue_ms = 800.0
        shape.device_ms = 0.002      # result was resident: ~instant fetch
        shape.finalize_ms = 0.2
        t = time.perf_counter()
        shape.t_start = t - 0.850
        shape.t_end = t
        rec = tracing.DispatchRecord([(tr.root, 14, 0.0)], owned=True,
                                     actual_rows=14)
        rec.phase("device_search", 0.2)
        rec.attach_shape(shape)
        rec.finish()
        d = [s for s in tr.root.children if s.name == "dispatch"][0]
        expect = costmodel.roofline(
            shape.flops(), shape.bytes(),
            d.attrs["dispatch_wall_ms"] / 1000.0)["mfu_pct"]
        assert d.attrs["mfu_pct"] == expect
        assert d.attrs["mfu_pct"] < 1.0  # honest: most of the wall is host
    finally:
        tracing.configure(None)


# -- serving-path integration -------------------------------------------------

def test_rider_flops_bytes_sum_bit_exact(tmp_path):
    """Coalesced dispatch: every rider's integer flops/bytes attribution
    sums EXACTLY to the dispatch totals (acceptance criterion)."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        n_req = 10
        barrier = threading.Barrier(n_req)

        def run(i):
            with tracing.request("test", f"q{i}"):
                barrier.wait()
                app.traverser.get_class(GetParams(
                    class_name="Pf",
                    near_vector={"vector": (vecs[i] + 0.5).tolist()},
                    limit=K))

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_req)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        by_dispatch: dict = {}
        for d in _dispatch_spans(app.tracer.snapshot()):
            by_dispatch.setdefault(d["attrs"]["dispatch_id"], []).append(
                d["attrs"])
        assert by_dispatch
        coalesced = [v for v in by_dispatch.values() if len(v) > 1]
        assert coalesced, "requests never shared a dispatch"
        for riders in by_dispatch.values():
            a0 = riders[0]
            assert a0["tier"] == costmodel.TIER_EXACT
            # the dispatch's analytic totals match the cost model at the
            # dispatch's actual rows
            assert a0["dispatch_flops"] == 2 * a0["actual_rows"] * \
                a0["n_live"] * a0["dim"]
            # BIT-exact: integer sums, no approx
            assert sum(r["flops"] for r in riders) == a0["dispatch_flops"]
            assert sum(r["bytes"] for r in riders) == a0["dispatch_bytes"]
            assert all(isinstance(r["flops"], int) for r in riders)
    finally:
        app.shutdown()


def test_dispatch_span_carries_roofline_and_ledger(tmp_path):
    app, idx, vecs = _mk_app(tmp_path)
    try:
        with tracing.request("test", "q"):
            app.traverser.get_class(GetParams(
                class_name="Pf",
                near_vector={"vector": (vecs[0] + 0.5).tolist()}, limit=K))
        d = _dispatch_spans(app.tracer.snapshot())
        assert len(d) == 1
        a = d[0]["attrs"]
        assert a["tier"] == costmodel.TIER_EXACT
        assert a["n_live"] == N and a["dim"] == DIM
        assert a["mfu_pct"] >= 0.0 and a["hbm_bw_pct"] >= 0.0
        assert a["regime"] in ("compute-bound", "hbm-bandwidth-bound")
        led = a["ledger_ms"]
        assert {"enqueue", "device", "gather_hop", "hydrate"} <= set(led)
        assert all(v >= 0.0 for v in led.values())
        # the window saw the dispatch too (full coverage)
        s = perf.get_window().summary()
        assert s["dispatches"] >= 1
        assert s["duty_cycle"] > 0.0
    finally:
        app.shutdown()


def test_pq_tiers_report_their_bytes(tmp_path):
    """The PQ-rescore tier's cost model reads the bf16 copy (2·D per
    row), pinned through a real compressed dispatch."""
    app, idx, vecs = _mk_app(tmp_path, pq=True, n=512)
    try:
        vidx = idx.single_local_shard().vector_index
        assert vidx.compressed
        with tracing.request("test", "q"):
            app.traverser.get_class(GetParams(
                class_name="Pf",
                near_vector={"vector": (vecs[0] + 0.5).tolist()}, limit=K))
        a = _dispatch_spans(app.tracer.snapshot())[0]["attrs"]
        assert a["tier"] == costmodel.TIER_PQ_RESCORE
        assert a["dispatch_bytes"] == a["n_live"] * 2 * DIM
    finally:
        app.shutdown()


def test_disabled_serving_path_constructs_no_perf_objects(tmp_path,
                                                          monkeypatch):
    """TRACING_ENABLED unset: no DispatchShape is built, the PerfWindow is
    never touched — direct AND coalesced paths (the zero-cost contract,
    same spy style as the tracing test)."""
    app, idx, vecs = _mk_app(tmp_path, tracing_on=False)
    calls = []

    def spy(name):
        def boom(*a, **kw):
            calls.append(name)
            raise AssertionError(f"perf.{name} touched while disabled")
        return boom

    monkeypatch.setattr(costmodel, "DispatchShape", spy("DispatchShape"))
    monkeypatch.setattr(perf.PerfWindow, "record_dispatch",
                        spy("PerfWindow.record_dispatch"))
    monkeypatch.setattr(perf.PerfWindow, "note_phase",
                        spy("PerfWindow.note_phase"))
    try:
        assert app.perf_window is None
        assert perf.get_window() is None
        # coalesced lane
        res = app.traverser.get_class(GetParams(
            class_name="Pf",
            near_vector={"vector": (vecs[0] + 0.5).tolist()}, limit=K))
        assert len(res) == K
        # direct path (oversize batched group bypasses the coalescer)
        out = app.traverser.get_class_batched([
            GetParams(class_name="Pf",
                      near_vector={"vector": (vecs[i] + 0.5).tolist()},
                      limit=K)
            for i in range(20)])
        assert not any(isinstance(r, Exception) for r in out)
        assert calls == []
    finally:
        app.shutdown()


# -- exposition ---------------------------------------------------------------

def test_debug_perf_endpoint_and_metrics(tmp_path):
    from weaviate_tpu.server import App, RestServer

    app, idx, vecs = _mk_app(tmp_path)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        with tracing.request("test", "q"):
            app.traverser.get_class(GetParams(
                class_name="Pf",
                near_vector={"vector": (vecs[0] + 0.5).tolist()}, limit=K))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/perf", timeout=30) as r:
            body = json.loads(r.read())
        assert body["enabled"] is True
        assert body["dispatches"] >= 1
        assert 0.0 <= body["duty_cycle"] <= 1.0
        assert "phases" in body and "device" in body["phases"]
        assert body["phases"]["device"]["p99_ms"] >= 0.0
        assert body["tiers"].get(costmodel.TIER_EXACT, 0) >= 1
        # rolling gauges ride the same scrape as everything else
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=30) as r:
            text = r.read().decode()
        assert "weaviate_device_mfu_pct" in text
        assert "weaviate_device_hbm_bw_pct" in text
        assert "weaviate_device_duty_cycle" in text
    finally:
        srv.stop()
        app.shutdown()


def test_debug_perf_disabled_reports_disabled(tmp_path):
    from weaviate_tpu.server import App, RestServer

    app, idx, vecs = _mk_app(tmp_path, tracing_on=False)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/perf", timeout=30) as r:
            assert json.loads(r.read()) == {"enabled": False}
    finally:
        srv.stop()
        app.shutdown()


def test_final_summary_stashed_for_ci_artifact(tmp_path):
    app, idx, vecs = _mk_app(tmp_path)
    with tracing.request("test", "q"):
        app.traverser.get_class(GetParams(
            class_name="Pf",
            near_vector={"vector": (vecs[0] + 0.5).tolist()}, limit=K))
    app.shutdown()
    assert any(s.get("dispatches_lifetime", 0) >= 1
               for s in perf.recent_summaries())


# -- satellites ---------------------------------------------------------------

def test_gate_sheds_surface_in_coalescer_stats(tmp_path):
    """ROADMAP item-4 follow-up: the front-door concurrency gate's
    refusals show up in coalescer.stats() and on the gate-level metric."""
    from weaviate_tpu.monitoring import noop_metrics

    m = noop_metrics()
    gate = robustness.configure_tenant_gate(
        robustness.TenantConcurrencyGate(1, metrics=m))
    app = None
    try:
        assert gate.enter("tA")
        assert not gate.enter("tA")   # over budget -> shed, counted
        assert not gate.enter("tA")
        gate.leave("tA")
        st = gate.stats()
        assert st["shed_total"] == 2 and st["shed"] == {"tA": 2}
        assert st["in_flight_total"] == 0
        # the coalescer's operator view includes the gate section
        app, idx, vecs = _mk_app(tmp_path, tracing_on=False)
        co_stats = app.coalescer.stats()
        assert co_stats["tenant_gate"]["shed_total"] == 2
        assert "weaviate_tenant_gate_shed_total 2.0" in m.expose().decode()
    finally:
        robustness.unconfigure_tenant_gate(gate)
        if app is not None:
            app.shutdown()


def test_gate_shed_tenant_keys_bounded():
    gate = robustness.TenantConcurrencyGate(1)
    gate._SHED_KEYS_MAX = 4  # type: ignore[misc]
    for i in range(10):
        assert gate.enter(f"t{i}")
        assert not gate.enter(f"t{i}")  # over ITS budget -> shed
        gate.leave(f"t{i}")
    st = gate.stats()
    assert len(st["shed"]) <= 5  # 4 tenant keys + "other"
    assert st["shed_total"] == 10
    assert st["shed"].get("other", 0) >= 6


def test_bm25_batch_shape_uses_costmodel():
    from weaviate_tpu.inverted.bm25_device import DeviceBM25

    eng = DeviceBM25.__new__(DeviceBM25)
    eng.last_batch_shape = costmodel.DispatchShape(
        costmodel.TIER_BM25_MATMUL, n=4096, dim=10.0, batch=96,
        bytes_per_row=40, k=10,
        extra={"q": 96, "u": 10, "n_pad": 4096, "slices": 1, "qu": 960})
    st = eng.last_batch_stats
    assert st["q"] == 96 and st["n_pad"] == 4096 and st["u"] == 10
    assert st["tier"] == costmodel.TIER_BM25_MATMUL
    r = eng.last_batch_shape.roofline_at_qps(960.0, "cpu")
    assert r == costmodel.roofline_from_qps(960.0, 4096, 10.0, 96, 40, "cpu")


def test_device_trace_teardown_stops_capture(monkeypatch):
    """The r05 wedge fix: an active capture is stopped by the emergency
    teardown exactly once, from any of atexit / SIGTERM / finally."""
    from weaviate_tpu.monitoring import profiling

    stopped = []
    import jax

    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stopped.append(1))
    with profiling._teardown_lock:
        profiling._teardown_state["active"] = True
    assert profiling.stop_active_trace() is True
    assert profiling.stop_active_trace() is False  # idempotent
    assert stopped == [1]


def test_trace_teardown_install_registers_sigterm_chain():
    import signal

    from weaviate_tpu.monitoring import profiling

    prev = signal.getsignal(signal.SIGTERM)
    try:
        # idempotent; in the main test thread installation succeeds
        assert profiling.install_trace_teardown() in (True, False)
        profiling.install_trace_teardown()
        if threading.current_thread() is threading.main_thread():
            assert signal.getsignal(signal.SIGTERM) is \
                profiling._sigterm_teardown
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_perf_window_s_config_parses():
    cfg = load_config({"TRACING_ENABLED": "true", "PERF_WINDOW_S": "12.5"})
    assert cfg.tracing.perf_window_s == 12.5
    with pytest.raises(Exception):
        load_config({"TRACING_ENABLED": "true", "PERF_WINDOW_S": "0"})
