"""LSM store: strategies, WAL recovery, flush/segments, compaction, blooms.

Models the reference's lsmkv unit/integration tiers (strategy tests,
bucket_recover_from_wal.go behavior)."""

import pytest

from weaviate_tpu.storage.docid import Counter
from weaviate_tpu.storage.lsm import (
    STRATEGY_MAP,
    STRATEGY_REPLACE,
    STRATEGY_ROARINGSET,
    STRATEGY_SET,
    Bucket,
    LsmError,
    Store,
)


def test_replace_basic(tmp_path):
    b = Bucket(str(tmp_path / "b"), STRATEGY_REPLACE)
    b.put(b"k1", b"v1")
    b.put(b"k2", b"v2")
    b.put(b"k1", b"v1b")
    assert b.get(b"k1") == b"v1b"
    b.delete(b"k2")
    assert b.get(b"k2") is None
    assert b.keys() == [b"k1"]


def test_replace_wal_recovery(tmp_path):
    p = str(tmp_path / "b")
    b = Bucket(p, STRATEGY_REPLACE)
    b.put(b"a", b"1")
    b.delete(b"a")
    b.put(b"b", b"2")
    b.flush()
    # no shutdown — simulate crash
    b2 = Bucket(p, STRATEGY_REPLACE)
    assert b2.get(b"a") is None
    assert b2.get(b"b") == b"2"


def test_replace_segments_and_tombstones(tmp_path):
    p = str(tmp_path / "b")
    b = Bucket(p, STRATEGY_REPLACE)
    b.put(b"x", b"old")
    b.flush_memtable()  # segment 1
    b.put(b"x", b"new")
    b.delete(b"y")
    b.flush_memtable()  # segment 2
    b.put(b"y", b"alive")
    assert b.get(b"x") == b"new"
    assert b.get(b"y") == b"alive"
    b.shutdown()
    b3 = Bucket(p, STRATEGY_REPLACE)
    assert b3.get(b"x") == b"new"
    assert b3.get(b"y") == b"alive"


def test_replace_compaction(tmp_path):
    p = str(tmp_path / "b")
    b = Bucket(p, STRATEGY_REPLACE)
    for i in range(10):
        b.put(f"k{i}".encode(), f"v{i}".encode())
        if i % 3 == 0:
            b.flush_memtable()
    b.delete(b"k5")
    b.flush_memtable()
    assert len(b._segments) > 2
    b.compact()
    assert len(b._segments) == 1
    assert b.get(b"k5") is None
    assert b.get(b"k4") == b"v4"
    assert len(b.keys()) == 9


def test_set_strategy(tmp_path):
    b = Bucket(str(tmp_path / "b"), STRATEGY_SET)
    b.set_add(b"k", b"a")
    b.set_add(b"k", b"b")
    b.flush_memtable()
    b.set_remove(b"k", b"a")
    b.set_add(b"k", b"c")
    assert b.set_get(b"k") == {b"b", b"c"}
    b.compact()  # single segment is a no-op here but must not corrupt
    b.flush_memtable()
    b.compact()
    assert b.set_get(b"k") == {b"b", b"c"}


def test_map_strategy(tmp_path):
    p = str(tmp_path / "b")
    b = Bucket(p, STRATEGY_MAP)
    b.map_put(b"term", b"doc1", b"tf=3")
    b.map_put(b"term", b"doc2", b"tf=1")
    b.flush_memtable()
    b.map_delete(b"term", b"doc1")
    b.map_put(b"term", b"doc3", b"tf=9")
    assert b.map_get(b"term") == {b"doc2": b"tf=1", b"doc3": b"tf=9"}
    b.shutdown()
    b2 = Bucket(p, STRATEGY_MAP)
    assert b2.map_get(b"term") == {b"doc2": b"tf=1", b"doc3": b"tf=9"}


def test_roaringset_strategy(tmp_path):
    p = str(tmp_path / "b")
    b = Bucket(p, STRATEGY_ROARINGSET)
    b.roaring_add_many(b"color:red", [1, 2, 3, 100])
    b.flush_memtable()
    b.roaring_remove_many(b"color:red", [2])
    b.roaring_add_many(b"color:red", [200])
    got = b.roaring_get(b"color:red")
    assert sorted(got) == [1, 3, 100, 200]
    b.flush_memtable()
    b.compact()
    assert sorted(b.roaring_get(b"color:red")) == [1, 3, 100, 200]


def test_bloom_survives_cross_process_restart(tmp_path):
    """Persisted blooms must use a DETERMINISTIC hash: Python's builtin
    hash() is siphash-randomized per process, so a bloom written by one
    process read by another turns ~99% of present keys into false
    negatives — silent loss of all flushed data on real restarts (in-process
    reopens share the seed and never catch this)."""
    import subprocess
    import sys

    d = str(tmp_path / "b")
    write = (
        "import sys; sys.path.insert(0, %r)\n"
        "from weaviate_tpu.storage.lsm import Bucket, STRATEGY_REPLACE\n"
        "b = Bucket(%r, STRATEGY_REPLACE)\n"
        "[b.put(f'key{i}'.encode(), f'val{i}'.encode()) for i in range(200)]\n"
        "b.flush_memtable()\n"
    )
    read = (
        "import sys; sys.path.insert(0, %r)\n"
        "from weaviate_tpu.storage.lsm import Bucket, STRATEGY_REPLACE\n"
        "b = Bucket(%r, STRATEGY_REPLACE)\n"
        "missing = sum(1 for i in range(200)"
        " if b.get(f'key{i}'.encode()) is None)\n"
        "assert missing == 0, f'{missing}/200 keys lost across processes'\n"
    )
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "PYTHONHASHSEED"}
    for code in (write % (repo, d), read % (repo, d)):
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]


def test_legacy_bloom_file_rebuilt(tmp_path):
    """A pre-versioning bloom file (or a corrupt one) must be discarded and
    rebuilt from the segment's key footer, not trusted."""
    p = str(tmp_path / "b")
    b = Bucket(p, STRATEGY_REPLACE)
    for i in range(50):
        b.put(f"k{i}".encode(), f"v{i}".encode())
    b.flush_memtable()
    seg_path = b._segments[-1].path
    # overwrite with a legacy-format file: raw m/k header, garbage bits
    import struct

    with open(seg_path + ".bloom", "wb") as f:
        f.write(struct.pack("<QI", 4096, 7) + b"\xaa" * 512)
    b2 = Bucket(p, STRATEGY_REPLACE)
    for i in range(50):
        assert b2.get(f"k{i}".encode()) == f"v{i}".encode()
    # and the rebuilt file is now versioned
    from weaviate_tpu.storage.lsm import BloomFilter

    with open(seg_path + ".bloom", "rb") as f:
        assert BloomFilter.from_bytes(f.read()) is not None


def test_native_multi_get_races_compaction(tmp_path):
    """The native point-get plane reads mmap'd segments OUTSIDE the bucket
    lock; compaction rewrites and retires segments concurrently. Hammer
    both: every read must return either the correct value — never garbage,
    never a crash — and retired segments must eventually close."""
    import threading

    from weaviate_tpu.storage import lsm_native

    if not lsm_native.available():
        pytest.skip("native lsm plane unavailable")
    b = Bucket(str(tmp_path / "b"), STRATEGY_REPLACE, memtable_max_bytes=1)
    n = 2000
    keys = [f"key-{i:05d}".encode() for i in range(n)]
    for i, k in enumerate(keys):
        b.put(k, b"v%d" % i)
    b.flush_memtable()
    errors: list = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            got = b.multi_get(keys)
            for i, v in enumerate(got):
                if v != b"v%d" % i:
                    errors.append((i, v))
                    return

    threads = [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    # repeated pair compactions while readers are in flight
    for _ in range(30):
        if not b.compact_pair():
            break
    b.compact()
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    with b._lock:
        assert b._native_inflight == 0
        assert not b._retired_segments  # all retired segments were closed


def test_reserved_tombstone_value_refused(tmp_path):
    """Storing the in-band delete marker as a value would silently read
    back as deleted — the bucket must refuse it loudly (found by the
    native-plane property fuzzer before the guard existed). Pure-Python
    behavior: runs regardless of native availability."""
    from weaviate_tpu.storage.lsm import _TOMBSTONE

    b = Bucket(str(tmp_path / "b"), STRATEGY_REPLACE)
    with pytest.raises(LsmError):
        b.put(b"k", _TOMBSTONE)
    with pytest.raises(LsmError):
        b.put_many([(b"a", b"ok"), (b"k", _TOMBSTONE)])
    assert b.get(b"a") is None  # the batch was refused atomically


def test_wal_torn_tail(tmp_path):
    p = str(tmp_path / "b")
    b = Bucket(p, STRATEGY_REPLACE)
    b.put(b"good", b"1")
    b.flush()
    b._wal.close()
    with open(p + "/bucket.wal", "ab") as f:
        f.write(b"\x01\x02\xff\xff\xff")  # torn record
    b2 = Bucket(p, STRATEGY_REPLACE)
    assert b2.get(b"good") == b"1"


def test_cursor_sorted(tmp_path):
    b = Bucket(str(tmp_path / "b"), STRATEGY_REPLACE)
    for k in [b"c", b"a", b"b"]:
        b.put(k, k)
    b.flush_memtable()
    b.put(b"d", b"d")
    assert [k for k, _ in b.cursor()] == [b"a", b"b", b"c", b"d"]


def test_memtable_autoflush(tmp_path):
    b = Bucket(str(tmp_path / "b"), STRATEGY_REPLACE, memtable_max_bytes=100)
    for i in range(50):
        b.put(f"key{i:04d}".encode(), b"x" * 20)
    assert len(b._segments) > 0
    assert b.get(b"key0000") == b"x" * 20


def test_store_buckets(tmp_path):
    s = Store(str(tmp_path / "store"))
    obj = s.create_or_load_bucket("objects", STRATEGY_REPLACE)
    inv = s.create_or_load_bucket("inv", STRATEGY_ROARINGSET)
    obj.put(b"k", b"v")
    inv.roaring_add_many(b"p", [7])
    with pytest.raises(LsmError):
        s.create_or_load_bucket("objects", STRATEGY_SET)
    assert s.bucket("objects").get(b"k") == b"v"


def test_docid_counter(tmp_path):
    p = str(tmp_path / "cnt" / "counter.bin")
    c = Counter(p, reserve=10)
    ids = [c.get_and_inc() for _ in range(5)]
    assert ids == [0, 1, 2, 3, 4]
    first = c.get_and_inc_many(3)
    assert first == 5
    # crash-restart must never reuse
    c2 = Counter(p, reserve=10)
    assert c2.get_and_inc() >= 8


def test_idle_memtable_flush(tmp_path):
    """PERSISTENCE_FLUSH_IDLE_MEMTABLES_AFTER: the background cycle flushes
    write-quiet memtables so crash recovery never replays an old WAL
    (lsmkv FlushAfterIdle)."""
    import time as _t

    store = Store(str(tmp_path / "s"), memtable_max_bytes=1 << 30,
                  flush_idle_seconds=0.2)
    b = store.create_or_load_bucket("r", STRATEGY_REPLACE)
    assert b.memtable_max_bytes == 1 << 30  # store default propagated
    t0 = _t.monotonic()
    b.put(b"k", b"v")
    assert len(b._mem)  # still in the memtable
    # not idle yet — unless a CI stall already burned the window
    if _t.monotonic() - t0 < 0.2:
        assert store.flush_idle_once() == 0
    _t.sleep(0.25)
    assert store.flush_idle_once() >= 1 or not len(b._mem)
    assert not len(b._mem) and b.segment_count() >= 1
    assert b.get(b"k") == b"v"
    # fresh writes reset the idle clock
    t1 = _t.monotonic()
    b.put(b"k2", b"v2")
    if _t.monotonic() - t1 < 0.2:
        assert store.flush_idle_once() == 0
    store.shutdown()
