"""Snapshot-isolated concurrent reads (index/tpu.py IndexSnapshot).

Pins the three contracts of the lock-free read plane:

1. no torn results — a reader racing inserts/deletes/compaction only ever
   sees ids that were live in SOME published snapshot, with distances that
   match the vector actually stored for that id;
2. bit-identical results — snapshot reads (sync AND async two-phase)
   return exactly what a quiesced sync search returns on the same data,
   on every read-path case: full scan, filtered masked scan,
   small-allowList gather, PQ rescore tier, PQ codes-only tier;
3. readers never block on a writer-held lock (timeout-guarded).

Kept bounded (thread counts, seconds) so the stress tier is '-m not slow'
safe for every CI run; crank _SECONDS up for a soak.
"""

import threading
import time

import numpy as np

from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.index.tpu import TpuVectorIndex

_SECONDS = 1.5
DIM = 16


def _mk_index(tmp_path, n=400, pq=None, seed=0, **cfg_extra):
    rng = np.random.default_rng(seed)
    # small-integer vectors: every L2 distance is exact integer arithmetic
    # in f32 regardless of accumulation order, so equality checks are exact
    vecs = rng.integers(-8, 8, (n, DIM)).astype(np.float32)
    d = {"distance": "l2-squared", **cfg_extra}
    if pq is not None:
        d["pq"] = pq
    cfg = parse_and_validate_config("hnsw_tpu", d)
    idx = TpuVectorIndex(cfg, str(tmp_path / "snapix"), persist=False)
    idx.add_batch(np.arange(n), vecs)
    idx.flush()
    return idx, vecs, rng


# -- 1. reader/writer stress: no torn results --------------------------------

def test_stress_concurrent_readers_writers_no_torn_results(tmp_path):
    """4 search threads against 3 insert/delete/compact threads on one
    index: every returned id must have been inserted by the time the
    search returned (live in some published snapshot — deleted ids may
    legitimately appear while an older snapshot serves), every distance
    must match the id's actual stored vector, and rows stay sorted."""
    n0 = 300
    idx, vecs, rng = _mk_index(tmp_path, n=n0)
    all_vecs = {i: vecs[i] for i in range(n0)}  # id -> vector ever stored
    next_id = [n0]
    deleted: list[int] = []
    book = threading.Lock()
    stop = threading.Event()
    errors: list[BaseException] = []

    def guard(fn):
        def go():
            try:
                while not stop.is_set():
                    fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)
                stop.set()
        return go

    def inserter():
        with book:
            i = next_id[0]
            next_id[0] += 1
            v = np.random.default_rng(i).integers(
                -8, 8, DIM).astype(np.float32)
            all_vecs[i] = v
        idx.add(i, v)

    def deleter():
        with book:
            if len(deleted) >= n0 - 50:
                return
            target = deleted[-1] + 2 if deleted else 0
            if target >= n0:
                return
            deleted.append(target)
        idx.delete(target)

    def compactor():
        idx.compact()
        time.sleep(0.05)

    def searcher():
        q = np.random.default_rng(2).integers(
            -8, 8, (4, DIM)).astype(np.float32)
        ids, dists = idx.search_by_vectors(q, 5)
        with book:
            known = int(next_id[0])
        for row_ids, row_d in zip(ids, dists):
            valid = ~np.isinf(row_d)
            got_d = row_d[valid]
            # rows come back ascending — a torn merge would not
            assert np.all(np.diff(got_d) >= 0)
            for doc, dd in zip(row_ids[valid], got_d):
                doc = int(doc)
                # the id existed when the search returned (no snapshot
                # ever contained an id that was never inserted)...
                assert doc < known, f"id {doc} returned before insertion"
                with book:
                    v = all_vecs[doc]
                # ...and its distance is the distance to ITS vector for
                # one of the queries (integer-exact): a torn store read
                # would produce a distance matching no stored row
                true = ((q - v[None, :]) ** 2).sum(1)
                assert np.any(np.abs(true - dd) < 1e-3), (
                    f"id {doc}: returned distance {dd} matches no query "
                    "against its stored vector (torn read?)")

    workers = [inserter, inserter, deleter, compactor,
               searcher, searcher, searcher, searcher]
    threads = [threading.Thread(target=guard(w), daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    deadline = time.monotonic() + _SECONDS
    while time.monotonic() < deadline and not stop.is_set():
        time.sleep(0.02)
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "worker wedged (deadlock?)"
    if errors:
        raise errors[0]
    # recall parity after quiesce: the stressed index answers exactly like
    # a single-threaded brute force over its final live set
    idx.flush()
    live = sorted(set(all_vecs) - set(deleted))
    mat = np.stack([all_vecs[i] for i in live])
    q = np.random.default_rng(3).integers(-8, 8, (8, DIM)).astype(np.float32)
    ids, dists = idx.search_by_vectors(q, 5)
    for r in range(len(q)):
        true = np.sort(((mat - q[r]) ** 2).sum(1))[:5]
        np.testing.assert_allclose(np.sort(dists[r]), true, atol=1e-3)


# -- 2. readers never block on a writer-held lock ----------------------------

def test_reader_never_blocks_on_writer_held_lock(tmp_path):
    """A writer sitting on the index lock (the worst-case convoy pre-PR)
    must not delay a reader at all: the published snapshot serves the
    search lock-free. Timeout-guarded well under the hold time."""
    idx, vecs, _ = _mk_index(tmp_path)
    idx.search_by_vectors(vecs[:4], 3)  # publish + compile
    hold_s = 3.0
    holding = threading.Event()
    release = threading.Event()

    def writer():
        with idx._lock:
            holding.set()
            release.wait(hold_s)

    w = threading.Thread(target=writer, daemon=True)
    w.start()
    assert holding.wait(5.0)
    t0 = time.perf_counter()
    ids, dists = idx.search_by_vectors(vecs[:4], 3)
    elapsed = time.perf_counter() - t0
    release.set()
    w.join(timeout=10)
    assert ids.shape == (4, 3)
    assert elapsed < 1.0, (
        f"reader took {elapsed:.2f}s while a writer held the lock — "
        "the snapshot fast path must not touch it")
    # the fast path reports zero lock wait
    assert idx.pop_read_lock_wait() == 0.0


# -- 3. bit-identical: snapshot/async reads == quiesced sync reads -----------

def _case_queries(vecs, rng):
    return vecs[:6] + rng.integers(0, 2, (6, DIM)).astype(np.float32)


def _assert_identical(idx, q, k, allow=None):
    sync_ids, sync_d = idx.search_by_vectors(q, k, allow)
    fin = idx.search_by_vectors_async(q, k, allow)
    async_ids, async_d = fin()
    np.testing.assert_array_equal(sync_ids, async_ids)
    np.testing.assert_array_equal(sync_d, async_d)
    # and a repeat sync search (still quiesced) is bit-identical too
    again_ids, again_d = idx.search_by_vectors(q, k, allow)
    np.testing.assert_array_equal(sync_ids, again_ids)
    np.testing.assert_array_equal(sync_d, again_d)


def test_bit_identical_sync_async_uncompressed_paths(tmp_path):
    from weaviate_tpu.storage.bitmap import Bitmap

    idx, vecs, rng = _mk_index(tmp_path)
    q = _case_queries(vecs, rng)
    _assert_identical(idx, q, 5)                       # full scan
    allow = Bitmap(range(0, 300, 2))
    idx.config.flat_search_cutoff = 0
    _assert_identical(idx, q, 5, allow)                # filtered masked scan
    idx.config.flat_search_cutoff = 10_000
    _assert_identical(idx, q, 5, allow)                # small-allow gather
    small = Bitmap(range(0, 40))
    _assert_identical(idx, q, 5, small)


def test_bit_identical_sync_async_pq_tiers(tmp_path):
    from weaviate_tpu.storage.bitmap import Bitmap

    for rescore in (True, False):
        sub = tmp_path / ("rs" if rescore else "codes")
        sub.mkdir()
        idx, vecs, rng = _mk_index(
            sub, pq={"enabled": False, "segments": 8, "centroids": 16,
                     "rescore": rescore})
        idx.compress()
        assert idx.compressed
        q = _case_queries(vecs, rng)
        _assert_identical(idx, q, 5)                   # PQ tier, unfiltered
        allow = Bitmap(range(0, 300, 2))
        idx.config.flat_search_cutoff = 0
        _assert_identical(idx, q, 5, allow)            # PQ tier, filtered
        idx.config.flat_search_cutoff = 10_000
        _assert_identical(idx, q, 5, Bitmap(range(0, 40)))  # gather under PQ


def test_pq_codes_only_async_is_lock_free_two_phase(tmp_path):
    """The PQ codes-only tier — pre-PR the documented sync fallback of
    search_by_vectors_async — now enqueues without touching the lock."""
    idx, vecs, rng = _mk_index(
        tmp_path, pq={"enabled": False, "segments": 8, "centroids": 16,
                      "rescore": False})
    idx.compress()
    assert idx.compressed and idx._rescore_dev is None
    q = _case_queries(vecs, rng)
    idx.search_by_vectors(q, 5)  # publish + compile

    class SpyLock:
        def __init__(self, inner):
            self.inner, self.count = inner, 0

        def acquire(self, *a, **kw):
            self.count += 1
            return self.inner.acquire(*a, **kw)

        def release(self):
            return self.inner.release()

        def __enter__(self):
            self.count += 1
            return self.inner.__enter__()

        def __exit__(self, *exc):
            return self.inner.__exit__(*exc)

    spy = SpyLock(idx._lock)
    idx._lock = spy
    try:
        fin = idx.search_by_vectors_async(q, 5)
        ids, dists = fin()
    finally:
        idx._lock = spy.inner
    assert ids.shape == (6, 5)
    assert spy.count == 0, "codes-only async dispatch took the index lock"


def test_snapshot_pins_arrays_across_delete_and_compact(tmp_path):
    """A dispatch enqueued BEFORE a delete+compact finalizes AFTER it with
    the old snapshot's answer — the mutation cannot tear it."""
    idx, vecs, _ = _mk_index(tmp_path)
    q = vecs[:4].copy()
    expect_ids, expect_d = idx.search_by_vectors(q, 3)
    fin = idx.search_by_vectors_async(q, 3)  # enqueued on snapshot S
    # mutate heavily: delete the current winners, then compact (rebuilds
    # device state wholesale and refreshes the allow token)
    for row in expect_ids:
        for doc in row:
            idx.delete(int(doc))
    idx.compact()
    got_ids, got_d = fin()  # finalizes against pinned snapshot S
    np.testing.assert_array_equal(got_ids, expect_ids)
    np.testing.assert_array_equal(got_d, expect_d)
    # a FRESH search sees the post-mutation state (winners gone)
    new_ids, _ = idx.search_by_vectors(q, 3)
    old = {int(x) for x in expect_ids.ravel()}
    assert not ({int(x) for x in new_ids.ravel()} & old)


def test_read_your_writes_after_staged_mutations(tmp_path):
    """The pre-read check: a search immediately after add/delete sees the
    write (flush + republish on the slow path), exactly like the old
    flush-under-lock behavior."""
    idx, vecs, _ = _mk_index(tmp_path, n=100)
    gen0 = idx.snapshot_gen
    v = np.full(DIM, 7.0, np.float32)
    idx.add(5000, v)
    ids, dists = idx.search_by_vectors(v[None, :], 1)
    assert int(ids[0, 0]) == 5000 and float(dists[0, 0]) == 0.0
    assert idx.snapshot_gen > gen0  # the read published a new snapshot
    idx.delete(5000)
    ids, dists = idx.search_by_vectors(v[None, :], 1)
    assert int(ids[0, 0]) != 5000


# -- 4. shard satellite: allowList cache is LRU, not FIFO ---------------------

def test_allow_cache_lru_eviction_order(tmp_path):
    import uuid as uuidlib

    from weaviate_tpu.db.shard import Shard, filter_signature
    from weaviate_tpu.entities.filters import LocalFilter
    from weaviate_tpu.entities.schema import ClassDef, Property
    from weaviate_tpu.entities.storobj import StorObj

    cd = ClassDef(name="Lru", properties=[
        Property(name="n", data_type=["int"]),
    ], vector_index_type="hnsw_tpu")
    shard = Shard("s0", str(tmp_path / "lru"), cd,
                  parse_and_validate_config(
                      "hnsw_tpu", {"distance": "l2-squared"}))
    try:
        rng = np.random.default_rng(0)
        shard.put_batch([
            StorObj(class_name="Lru", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"n": i},
                    vector=rng.standard_normal(DIM).astype(np.float32))
            for i in range(20)])

        def flt(i):
            return LocalFilter.from_dict(
                {"operator": "Equal", "path": ["n"], "valueInt": i})

        # fill the 16-entry cache in insertion order 0..15
        first = [shard.build_allow_list(flt(i)) for i in range(16)]
        # HIT filter 0: under LRU it moves to most-recently-used (the old
        # FIFO left it first in line for eviction)
        assert shard.build_allow_list(flt(0)) is first[0]
        # one more filter evicts exactly ONE entry: the least recently
        # used is now filter 1 — the hot filter 0 survives
        shard.build_allow_list(flt(16))
        sig = filter_signature
        assert sig(flt(0)) in shard._allow_cache
        assert sig(flt(1)) not in shard._allow_cache
        assert sig(flt(16)) in shard._allow_cache
        # and the hot filter still serves the SAME cached bitmap object
        assert shard.build_allow_list(flt(0)) is first[0]
    finally:
        shard.shutdown()
