"""Fused group-min fast-scan kernel (ops/gmin_scan.py) vs the legacy
lax.scan kernel and exact numpy ground truth — interpret mode on the CPU
mesh (the compiled Mosaic path is exercised on real TPU by bench.py)."""

import numpy as np
import pytest

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.tpu import TpuVectorIndex
from weaviate_tpu.storage.bitmap import Bitmap


def _mk_index(tmp_path, metric, n=600, d=32, seed=0):
    rng = np.random.default_rng(seed)
    vecs = rng.standard_normal((n, d)).astype(np.float32)
    if metric == vi.DISTANCE_COSINE:
        vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    cfg = vi.HnswUserConfig.from_dict({"distance": metric}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, str(tmp_path / metric), persist=False)
    idx.add_batch(np.arange(n), vecs)
    idx.flush()
    return idx, vecs, rng


def _exact(vecs, q, k, metric):
    if metric == vi.DISTANCE_L2:
        d = ((q[:, None, :] - vecs[None, :, :]) ** 2).sum(-1)
    elif metric == vi.DISTANCE_DOT:
        d = -(q @ vecs.T)
    else:
        qn = q / np.linalg.norm(q, axis=1, keepdims=True)
        d = 1.0 - qn @ vecs.T
    return np.argsort(d, axis=1, kind="stable")[:, :k], np.sort(d, axis=1)[:, :k]


@pytest.mark.parametrize("metric", [vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE])
def test_gmin_matches_exact(tmp_path, metric):
    idx, vecs, rng = _mk_index(tmp_path, metric)
    q = rng.standard_normal((16, vecs.shape[1])).astype(np.float32)
    assert idx._use_gmin(idx._read_snapshot(), 16, 10)
    ids, dists = idx.search_by_vectors(q, 10)
    assert not idx._gmin_broken  # the fused path actually ran
    gt_ids, gt_d = _exact(vecs, q, 10, metric)
    for i in range(len(q)):
        assert set(ids[i].tolist()) == set(gt_ids[i].tolist())
    np.testing.assert_allclose(dists, gt_d, rtol=1e-3, atol=1e-3)


def test_gmin_tombstones_and_filter(tmp_path):
    idx, vecs, rng = _mk_index(tmp_path, vi.DISTANCE_L2)
    n = len(vecs)
    # tombstone the even docs
    for doc in range(0, 40, 2):
        idx.delete(doc)
    idx.flush()
    q = vecs[:16] + 0.01 * rng.standard_normal((16, vecs.shape[1])).astype(np.float32)
    # allowList: docs 0..99 only -> live allowed = odd docs < 40 + 40..99
    allow = Bitmap(range(100))
    idx.config.flat_search_cutoff = 0  # force the masked full-scan path
    ids, _ = idx.search_by_vectors(q, 5, allow_list=allow)
    assert not idx._gmin_broken
    flat = ids.ravel()
    flat = flat[flat != np.uint64(0xFFFFFFFFFFFFFFFF)]
    assert all(int(x) < 100 for x in flat)
    assert all(int(x) % 2 == 1 or int(x) >= 40 for x in flat)
    # query i's nearest live allowed doc is itself (odd/40+) or its
    # neighborhood; exact check against numpy over the allowed live set
    live_allowed = np.array([d for d in range(100) if not (d < 40 and d % 2 == 0)])
    dd = ((q[:, None, :] - vecs[live_allowed][None, :, :]) ** 2).sum(-1)
    want = live_allowed[np.argsort(dd, axis=1)[:, :5]]
    for i in range(len(q)):
        assert set(int(x) for x in ids[i]) == set(int(x) for x in want[i])


def test_gmin_small_batch_uses_legacy(tmp_path):
    idx, vecs, _ = _mk_index(tmp_path, vi.DISTANCE_L2, n=50)
    assert not idx._use_gmin(idx._read_snapshot(), 4, 10)  # b < 8 -> legacy
    ids, _ = idx.search_by_vectors(vecs[:2], 3)
    assert ids.shape == (2, 3)


def test_gmin_async_path(tmp_path):
    idx, vecs, rng = _mk_index(tmp_path, vi.DISTANCE_L2)
    q = vecs[:32] + 0.001 * rng.standard_normal((32, vecs.shape[1])).astype(np.float32)
    fin = idx.search_by_vectors_async(q, 1)
    ids, _ = fin()
    assert not idx._gmin_broken
    np.testing.assert_array_equal(ids.ravel(), np.arange(32, dtype=np.uint64))


def test_gmin_per_shape_fallback(tmp_path, monkeypatch):
    """A Mosaic rejection on one compiled shape falls back to the legacy
    kernel for THAT shape only; other shapes keep the fused path. Only
    repeated distinct-shape failures with zero successes disable the path
    (a restart may make an oversized batch the first-ever query)."""
    idx, vecs, rng = _mk_index(tmp_path, vi.DISTANCE_L2)
    real = idx._search_full_gmin

    def failing(snap, q, kk, allow_words, *a, **k):
        if q.shape[0] >= 64:  # "over VMEM budget" for big batches
            raise RuntimeError("Mosaic: scoped vmem limit exceeded")
        return real(snap, q, kk, allow_words, *a, **k)

    monkeypatch.setattr(idx, "_search_full_gmin", failing)
    big = rng.standard_normal((64, vecs.shape[1])).astype(np.float32)
    ids, _ = idx.search_by_vectors(big, 5)  # first-ever query fails
    assert ids.shape == (64, 5)
    assert not idx._gmin_broken and len(idx._gmin_shape_broken) == 1
    # a small shape still compiles and validates the fused path
    ids, _ = idx.search_by_vectors(big[:16], 5)
    assert idx._gmin_validated and not idx._gmin_broken
    # the broken shape stays on the legacy kernel without re-raising
    ids, _ = idx.search_by_vectors(big, 5)
    assert ids.shape == (64, 5) and len(idx._gmin_shape_broken) == 1


def test_gmin_disables_after_repeated_distinct_failures(tmp_path, monkeypatch):
    idx, vecs, rng = _mk_index(tmp_path, vi.DISTANCE_L2)
    monkeypatch.setattr(
        idx, "_search_full_gmin",
        lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("platform broken")))
    q = rng.standard_normal((16, vecs.shape[1])).astype(np.float32)
    for k in (3, 5, 7):  # three distinct compiled shapes all fail
        ids, _ = idx.search_by_vectors(q, k)
        assert ids.shape == (16, k)  # legacy kernel answered
    assert idx._gmin_broken and not idx._gmin_validated


def test_vmem_tile_plan():
    """plan_tiles keeps every shape under the 12 MB budget by shrinking the
    store tile (then the query tile); fits_vmem refuses only when even the
    smallest tiling is over (the round-2 relay wedge was a VMEM-oversized
    kernel reaching Mosaic — this is the gate that prevents a repeat)."""
    from weaviate_tpu.ops import gmin_scan as gs

    # SIFT-shaped: full 512x512 tiles fit
    qb, scg, fp = gs.plan_tiles(16384, 128, 65536, 16, 4)
    assert (qb, scg) == (512, 512) and fp <= gs._VMEM_BUDGET
    # d=768 with a full slab: the f32 store block alone (16*128*768*4 =
    # 6.3 MB, double-buffered) is over budget even at the smallest tiling —
    # the index must fall back to the legacy scan rather than compile it...
    assert not gs.fits_vmem(4096, 768, 4096, 16, 4)
    # ...but the bf16 rescore store (PQ serving) fits at a shrunk tile
    qb2, scg2, fp2 = gs.plan_tiles(4096, 768, 4096, 16, 2)
    assert scg2 < 512 and fp2 <= gs._VMEM_BUDGET
    assert gs.fits_vmem(4096, 768, 4096, 16, 2)
    # and a part-full slab (active_g=4) fits even at f32
    assert gs.fits_vmem(4096, 768, 4096, 4, 4)
    # absurdly wide vectors: refuse instead of compiling a wedge
    assert not gs.fits_vmem(512, 65536, 1024, 16, 4)
    # every plan is a power-of-two divisor of the padded dims
    for d in (32, 128, 256, 512, 1024, 2048):
        qb, scg, fp = gs.plan_tiles(1024, d, 1024, 16, 4)
        assert 1024 % qb == 0 and 1024 % scg == 0
        assert scg >= 128 and qb >= 64  # lane-width / sublane floors hold


def test_gmin_wide_vectors_adaptive_tiles(tmp_path):
    """d=768 forces a reduced store tile; the kernel must stay correct
    (interpret mode) at the adapted tiling."""
    idx, vecs, rng = _mk_index(tmp_path, vi.DISTANCE_L2, n=700, d=768)
    q = vecs[:16] + 0.001 * rng.standard_normal((16, 768)).astype(np.float32)
    ids, dists = idx.search_by_vectors(q, 5)
    assert idx._gmin_validated and not idx._gmin_broken
    np.testing.assert_array_equal(ids[:, 0], np.arange(16, dtype=np.uint64))


def test_gmin_uneven_rescore_block(tmp_path):
    """b=3072 (a 1024-multiple bucket NOT divisible by the 2048 rescore
    block) exercises the ceil-split + pad path."""
    idx, vecs, rng = _mk_index(tmp_path, vi.DISTANCE_L2, n=400, d=16)
    q = np.repeat(vecs[:25], 84, axis=0)  # 2100 queries -> bucket 3072
    assert len(q) == 2100
    ids, dists = idx.search_by_vectors(q, 1)
    assert not idx._gmin_broken
    want = np.repeat(np.arange(25, dtype=np.uint64), 84)
    np.testing.assert_array_equal(ids.ravel(), want)
    np.testing.assert_allclose(dists.ravel(), 0.0, atol=1e-4)


def test_gmin_block_rescore_equals_strided(tmp_path):
    """The [ncols, G*D] block-gather rescore (round-5 gather fix: rg
    contiguous slices per query instead of rg*G scattered rows) must be
    bit-identical to the strided-take path it replaces."""
    import jax.numpy as jnp

    from weaviate_tpu.ops import gmin_scan

    rng = np.random.default_rng(3)
    n, d, b, k = 700, 32, 64, 10
    cap = 16384
    store = np.zeros((cap, d), np.float32)
    store[:n] = rng.standard_normal((n, d)).astype(np.float32)
    sq = jnp.asarray((store.astype(np.float64) ** 2).sum(1).astype(np.float32))
    store_j = jnp.asarray(store)
    tombs = np.zeros(cap, bool)
    tombs[5:50:7] = True  # some tombstones
    q = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    words = jnp.zeros((cap // 32,), jnp.uint32)
    args = (store_j, sq, jnp.asarray(tombs), n, q, words, False,
            k, "l2-squared", 8, 1, True)
    d0, i0 = gmin_scan.gmin_topk(*args)
    blk = gmin_scan.build_rescore_blocks(store_j)
    d1, i1 = gmin_scan.gmin_topk(*args, blk)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


def test_pq_gmin_block_rescore_equals_strided(tmp_path):
    """Codes twin of the block-rescore equivalence check."""
    import jax.numpy as jnp

    from weaviate_tpu.compress.pq import ProductQuantizer
    from weaviate_tpu.ops import pq_gmin

    rng = np.random.default_rng(4)
    n, d, b, k = 900, 32, 64, 10
    cap = 16384
    vecs = rng.standard_normal((n, d)).astype(np.float32)
    pq = ProductQuantizer(dim=d, segments=8, centroids=16, metric="l2-squared")
    pq.fit(vecs)
    codes = np.zeros((cap, 8), np.uint8)
    codes[:n] = pq.encode(vecs)
    recon = pq.decode(codes[:n])
    rn = np.zeros(cap, np.float32)
    rn[:n] = (recon.astype(np.float64) ** 2).sum(1).astype(np.float32)
    cb_chunks = jnp.asarray(
        pq_gmin.build_cb_chunks(pq.codebook, 8), jnp.bfloat16)
    flat_cb = jnp.asarray(pq.codebook.reshape(-1, pq.codebook.shape[2]))
    codes_j = jnp.asarray(codes)
    q = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    words = jnp.zeros((cap // 32,), jnp.uint32)
    args = (codes_j, jnp.asarray(rn), jnp.zeros((cap,), bool), n, q,
            cb_chunks, flat_cb, words, False, k, "l2-squared", 8, 1, True,
            None)
    d0, i0 = pq_gmin.pq_gmin_topk(*args)
    blk = pq_gmin.build_codes_blocks(codes_j)
    d1, i1 = pq_gmin.pq_gmin_topk(*args, blk)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
