"""Schema manager, auto-schema, objects/batch managers, traverser/explorer,
hybrid fusion (usecases layer tests; reference: usecases/*_test.go with real
repos instead of fakes — the TPU-sim CPU backend makes that cheap)."""

import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.db import DB
from weaviate_tpu.schema import AutoSchema, SchemaManager, SchemaValidationError
from weaviate_tpu.usecases.objects import BatchManager, NotFoundError, ObjectsManager, ObjectsError
from weaviate_tpu.usecases.traverser import Explorer, GetParams, Traverser


@pytest.fixture
def stack(tmp_path):
    db = DB(str(tmp_path / "data"))
    mgr = SchemaManager(str(tmp_path / "schema.json"), migrator=db)
    auto = AutoSchema(mgr)
    om = ObjectsManager(db, mgr, auto_schema=auto)
    bm = BatchManager(om)
    explorer = Explorer(db, mgr)
    trav = Traverser(explorer)
    yield db, mgr, om, bm, trav
    db.shutdown()


def make_article_class(mgr):
    return mgr.add_class(
        {
            "class": "Article",
            "properties": [
                {"name": "title", "dataType": ["text"]},
                {"name": "wordCount", "dataType": ["int"]},
            ],
            "vectorIndexType": "hnsw_tpu",
            "vectorIndexConfig": {"distance": "l2-squared"},
        }
    )


def test_schema_ddl_and_persistence(tmp_path, stack):
    db, mgr, om, bm, trav = stack
    make_article_class(mgr)
    assert mgr.get_class("Article") is not None
    assert db.get_index("Article") is not None

    with pytest.raises(SchemaValidationError):
        make_article_class(mgr)  # duplicate

    mgr.add_property("Article", {"name": "summary", "dataType": ["text"]})
    assert mgr.get_class("Article").get_property("summary") is not None
    with pytest.raises(SchemaValidationError):
        mgr.add_property("Article", {"name": "summary", "dataType": ["text"]})
    with pytest.raises(SchemaValidationError):
        mgr.add_property("Article", {"name": "id", "dataType": ["text"]})

    # reload from disk: schema + indexes rebuilt
    db2 = DB(str(tmp_path / "data2"))
    mgr2 = SchemaManager(str(tmp_path / "schema.json"), migrator=db2)
    assert mgr2.get_class("Article").get_property("summary") is not None
    assert db2.get_index("Article") is not None
    db2.shutdown()

    # immutables
    with pytest.raises(SchemaValidationError):
        mgr.update_class("Article", {"vectorizer": "text2vec-foo"})
    mgr.update_class("Article", {"description": "news articles"})
    assert mgr.get_class("Article").description == "news articles"

    # a fetch-tweak-PUT payload that merely REORDERS properties is not a
    # property change; an actual change still rejects
    cur = mgr.get_class("Article").to_dict()
    mgr.update_class("Article", {"description": "reordered",
                                 "properties": cur["properties"][::-1]})
    assert mgr.get_class("Article").description == "reordered"
    with pytest.raises(SchemaValidationError):
        mgr.update_class("Article", {"properties": [
            {"name": "title", "dataType": ["int"]}]})

    mgr.delete_class("Article")
    assert mgr.get_class("Article") is None
    assert db.get_index("Article") is None


def test_vector_config_hot_update(stack):
    db, mgr, om, bm, trav = stack
    make_article_class(mgr)
    mgr.update_class("Article", {"vectorIndexConfig": {"distance": "l2-squared", "ef": 256}})
    with pytest.raises(SchemaValidationError):
        # distance immutable
        mgr.update_class("Article", {"vectorIndexConfig": {"distance": "cosine"}})


def test_auto_schema_and_objects_crud(stack):
    db, mgr, om, bm, trav = stack
    obj = om.add(
        {
            "class": "Person",
            "properties": {"name": "ada", "age": 36, "score": 1.5, "active": True},
            "vector": [0.1, 0.2, 0.3],
        }
    )
    cd = mgr.get_class("Person")
    assert cd is not None
    assert cd.get_property("name").data_type == ["text"]
    assert cd.get_property("age").data_type == ["int"]
    assert cd.get_property("score").data_type == ["number"]
    assert cd.get_property("active").data_type == ["boolean"]

    got = om.get(obj.uuid, "Person", include_vector=True)
    assert got.properties["name"] == "ada"
    assert got.vector.shape == (3,)

    om.merge(obj.uuid, "Person", {"name": "ada lovelace"})
    assert om.get(obj.uuid).properties["name"] == "ada lovelace"
    assert om.get(obj.uuid).properties["age"] == 36

    om.update(obj.uuid, {"class": "Person", "properties": {"name": "replaced"}, "vector": [1, 0, 0]})
    got = om.get(obj.uuid)
    assert got.properties == {"name": "replaced"}

    om.delete(obj.uuid)
    with pytest.raises(NotFoundError):
        om.get(obj.uuid)

    with pytest.raises(ObjectsError):
        om.add({"properties": {"x": 1}})  # no class


def test_batch_manager(stack):
    db, mgr, om, bm, trav = stack
    make_article_class(mgr)
    rng = np.random.default_rng(0)
    payloads = [
        {
            "class": "Article",
            "id": str(uuidlib.UUID(int=i + 1)),
            "properties": {"title": f"story {i}", "wordCount": i},
            "vector": rng.standard_normal(8).tolist(),
        }
        for i in range(50)
    ]
    payloads.append({"class": "Article", "id": "not-a-uuid", "properties": {}})
    results = bm.add_objects(payloads)
    assert sum(1 for r in results if r.err is None) == 50
    assert results[-1].err is not None
    assert db.get_index("Article").object_count() == 50

    res = bm.delete_objects(
        "Article", {"operator": "LessThan", "path": ["wordCount"], "valueInt": 10}
    )
    assert res["results"]["successful"] == 10
    assert db.get_index("Article").object_count() == 40


def _import_articles(mgr, bm, n=60, dim=8):
    make_article_class(mgr)
    rng = np.random.default_rng(7)
    vecs = rng.standard_normal((n, dim)).astype(np.float32)
    payloads = [
        {
            "class": "Article",
            "id": str(uuidlib.UUID(int=i + 1)),
            "properties": {"title": f"common token{i}", "wordCount": i},
            "vector": vecs[i].tolist(),
        }
        for i in range(n)
    ]
    bm.add_objects(payloads)
    return vecs


def test_traverser_near_vector_and_near_object(stack):
    db, mgr, om, bm, trav = stack
    vecs = _import_articles(mgr, bm)
    res = trav.get_class(
        GetParams(class_name="Article", near_vector={"vector": vecs[5].tolist()}, limit=3)
    )
    assert res[0].obj.uuid == str(uuidlib.UUID(int=6))
    assert res[0].distance < 1e-3

    res2 = trav.get_class(
        GetParams(
            class_name="Article",
            near_object={"id": str(uuidlib.UUID(int=6))},
            limit=3,
        )
    )
    assert res2[0].obj.uuid == str(uuidlib.UUID(int=6))

    # distance threshold
    res3 = trav.get_class(
        GetParams(
            class_name="Article",
            near_vector={"vector": vecs[5].tolist(), "distance": 0.5},
            limit=10,
        )
    )
    assert all(r.distance <= 0.5 for r in res3)


def test_traverser_bm25_and_list_and_sort(stack):
    db, mgr, om, bm, trav = stack
    _import_articles(mgr, bm)
    res = trav.get_class(
        GetParams(class_name="Article", keyword_ranking={"query": "token42"}, limit=5)
    )
    assert len(res) == 1 and res[0].obj.properties["wordCount"] == 42

    listed = trav.get_class(GetParams(class_name="Article", limit=10))
    assert len(listed) == 10

    sorted_res = trav.get_class(
        GetParams(
            class_name="Article",
            limit=100,
            sort=[{"path": ["wordCount"], "order": "desc"}],
        )
    )
    counts = [r.obj.properties["wordCount"] for r in sorted_res]
    assert counts == sorted(counts, reverse=True)


def test_traverser_hybrid(stack):
    db, mgr, om, bm, trav = stack
    vecs = _import_articles(mgr, bm)
    res = trav.get_class(
        GetParams(
            class_name="Article",
            hybrid={"query": "token13", "vector": vecs[13].tolist(), "alpha": 0.5},
            limit=5,
        )
    )
    assert res[0].obj.uuid == str(uuidlib.UUID(int=14))  # both legs rank it first
    assert res[0].score is not None and res[0].explain_score

    # pure keyword (alpha=0)
    res_kw = trav.get_class(
        GetParams(class_name="Article", hybrid={"query": "token13", "alpha": 0.0}, limit=5)
    )
    assert res_kw[0].obj.uuid == str(uuidlib.UUID(int=14))


def test_batched_get(stack):
    db, mgr, om, bm, trav = stack
    vecs = _import_articles(mgr, bm)
    params = [
        GetParams(class_name="Article", near_vector={"vector": vecs[i].tolist()}, limit=2)
        for i in (3, 9, 27)
    ]
    out = trav.get_class_batched(params)
    assert [r[0].obj.uuid for r in out] == [str(uuidlib.UUID(int=i + 1)) for i in (3, 9, 27)]


def test_explore_cross_class(stack):
    db, mgr, om, bm, trav = stack
    vecs = _import_articles(mgr, bm)
    om.add({"class": "Author", "properties": {"name": "bob"}, "vector": vecs[2].tolist()})
    ex = trav.explorer.explore(near_vector={"vector": vecs[2].tolist()}, limit=4)
    classes = {e["className"] for e in ex[:2]}
    assert classes == {"Article", "Author"}  # both classes' exact hits first


def test_references(stack):
    db, mgr, om, bm, trav = stack
    mgr.add_class({"class": "Author", "properties": [{"name": "name", "dataType": ["text"]}]})
    mgr.add_class(
        {
            "class": "Book",
            "properties": [
                {"name": "title", "dataType": ["text"]},
                {"name": "writtenBy", "dataType": ["Author"]},
            ],
        }
    )
    a = om.add({"class": "Author", "properties": {"name": "bob"}})
    b = om.add({"class": "Book", "properties": {"title": "x"}})
    beacon = f"weaviate://localhost/Author/{a.uuid}"
    om.add_reference(b.uuid, "Book", "writtenBy", beacon)
    got = om.get(b.uuid, "Book")
    assert got.properties["writtenBy"] == [{"beacon": beacon}]
    om.delete_reference(b.uuid, "Book", "writtenBy", beacon)
    assert om.get(b.uuid, "Book").properties["writtenBy"] == []


def test_phone_number_parse_and_validate():
    """phoneNumber values validate + parse at import
    (validation/phone_numbers.go; payload shape phone_number.go)."""
    from weaviate_tpu.entities.phone import PhoneNumberError, parse_phone_number

    # international input needs no default country
    out = parse_phone_number({"input": "+49 171 1234567"})
    assert out["valid"] and out["countryCode"] == 49
    assert out["national"] == 1711234567
    assert out["internationalFormatted"] == "+49 1711234567"

    # 00-prefix international form
    assert parse_phone_number({"input": "0049 171 1234567"})["countryCode"] == 49

    # national input + defaultCountry
    out = parse_phone_number({"input": "0171 1234567", "defaultCountry": "DE"})
    assert out["valid"] and out["countryCode"] == 49 and out["national"] == 1711234567

    # malformed values are errors, not silent stores
    with pytest.raises(PhoneNumberError):
        parse_phone_number("+491711234567")        # not a map
    with pytest.raises(PhoneNumberError):
        parse_phone_number({"input": ""})          # empty input
    with pytest.raises(PhoneNumberError):
        parse_phone_number({"input": "0171 123"})  # national w/o country
    with pytest.raises(PhoneNumberError):
        parse_phone_number({"input": "123", "defaultCountry": "zz"})

    # parseable-but-invalid numbers store valid=false
    assert not parse_phone_number({"input": "+49 12"})["valid"]
    assert not parse_phone_number({"input": "+999 1234567"})["valid"]


def test_phone_number_through_objects_manager(stack):
    db, mgr, om, bm, trav = stack
    mgr.add_class({
        "class": "Contact",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "phone", "dataType": ["phoneNumber"]}],
    })
    obj = om.add({"class": "Contact",
                  "properties": {"phone": {"input": "+31 20 123 4567"}},
                  "vector": [0.0, 0.0]})
    got = om.get(obj.uuid, "Contact")
    assert got.properties["phone"]["valid"]
    assert got.properties["phone"]["countryCode"] == 31
    assert got.properties["phone"]["internationalFormatted"].startswith("+31 ")
    with pytest.raises(Exception):
        om.add({"class": "Contact",
                "properties": {"phone": "not-a-map"}, "vector": [0.0, 0.0]})


def test_primitive_type_validation(stack):
    """date/geo/blob/uuid values validate at import
    (validation/properties_validation.go): bad shapes are errors, good
    ones store."""
    db, mgr, om, bm, trav = stack
    mgr.add_class({
        "class": "Typed",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [
            {"name": "when", "dataType": ["date"]},
            {"name": "where", "dataType": ["geoCoordinates"]},
            {"name": "img", "dataType": ["blob"]},
            {"name": "ext", "dataType": ["uuid"]},
            {"name": "days", "dataType": ["date[]"]},
        ],
    })

    ok = om.add({"class": "Typed", "vector": [0.0, 0.0], "properties": {
        "when": "2023-06-01T12:00:00Z",
        "where": {"latitude": 52.5, "longitude": 13.4},
        "img": "aGVsbG8=",
        "ext": "7b2e1c66-0000-0000-0000-000000000001",
        "days": ["2023-06-01T12:00:00+02:00"],
    }})
    assert om.get(ok.uuid, "Typed").properties["when"].startswith("2023")

    bad = [
        {"when": "not-a-date"},
        {"when": 12345},
        {"where": {"latitude": 52.5}},                     # missing longitude
        {"where": {"latitude": 95.0, "longitude": 0.0}},   # out of range
        {"where": "52.5,13.4"},
        {"img": "not base64!!"},
        {"ext": "nope"},
        {"days": ["2023-06-01T12:00:00Z", "bad"]},         # arrays validate per item
        {"days": "2023-06-01T12:00:00Z"},                  # array type needs a list
    ]
    for props in bad:
        with pytest.raises(Exception):
            om.add({"class": "Typed", "vector": [0.0, 0.0], "properties": props})


def test_phone_trunk_zero_rules():
    from weaviate_tpu.entities.phone import PhoneNumberError, parse_phone_number

    # "(0)" notation: the marked trunk zero is dropped
    out = parse_phone_number({"input": "+49 (0)171 1234567"})
    assert out["internationalFormatted"] == "+49 1711234567"
    # bare leading zero after +CC is kept (significant in Italy)
    out = parse_phone_number({"input": "+39 06 1234567"})
    assert out["national"] == 61234567 and out["nationalFormatted"] == "061234567"
    # national Italian input keeps its zero too
    out = parse_phone_number({"input": "06 1234567", "defaultCountry": "IT"})
    assert out["nationalFormatted"] == "061234567"
    # unknown defaultCountry errors on BOTH input forms
    with pytest.raises(PhoneNumberError):
        parse_phone_number({"input": "+49 171 1234567", "defaultCountry": "zz"})
