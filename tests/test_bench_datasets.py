"""Dataset loader plumbing: TexMex fvecs/ivecs codecs + labeled fallback.

Reference harness analog: test/benchmark/benchmark_sift.go (SIFT fvecs
parsing); ann-benchmarks hdf5 for glove-100-angular.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench_datasets as bd  # noqa: E402


def _write_fvecs(path, arr):
    with open(path, "wb") as f:
        for row in arr:
            np.int32(arr.shape[1]).tofile(f)
            row.astype("<f4").tofile(f)


def _write_ivecs(path, arr):
    with open(path, "wb") as f:
        for row in arr:
            np.int32(arr.shape[1]).tofile(f)
            row.astype("<i4").tofile(f)


def test_fvecs_ivecs_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    vec = rng.standard_normal((50, 16)).astype(np.float32)
    ids = rng.integers(0, 1000, (50, 10)).astype(np.int32)
    fp, ip = str(tmp_path / "a.fvecs"), str(tmp_path / "a.ivecs")
    _write_fvecs(fp, vec)
    _write_ivecs(ip, ids)
    np.testing.assert_array_equal(bd.read_fvecs(fp), vec)
    np.testing.assert_array_equal(bd.read_ivecs(ip), ids)
    np.testing.assert_array_equal(bd.read_fvecs(fp, max_rows=7), vec[:7])
    np.testing.assert_array_equal(bd.read_ivecs(ip, max_rows=7), ids[:7])


def test_cached_sift_layout_loads(tmp_path, monkeypatch):
    """A pre-populated cache loads without any network attempt."""
    sift = tmp_path / "sift"
    sift.mkdir()
    rng = np.random.default_rng(1)
    base = rng.standard_normal((100, 8)).astype(np.float32)
    qs = rng.standard_normal((5, 8)).astype(np.float32)
    gt = rng.integers(0, 100, (5, 10)).astype(np.int32)
    _write_fvecs(str(sift / "sift_base.fvecs"), base)
    _write_fvecs(str(sift / "sift_query.fvecs"), qs)
    _write_ivecs(str(sift / "sift_groundtruth.ivecs"), gt)
    monkeypatch.setattr(bd, "CACHE", str(tmp_path))
    monkeypatch.setattr(bd, "_download", lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("no network attempt expected")))
    data = bd.load_sift1m()
    np.testing.assert_array_equal(data["train"], base)
    np.testing.assert_array_equal(data["gt"], gt)
    assert data["metric"] == "l2-squared"
    data, label = bd.load_or_synthetic("sift1m", lambda: {"train": None})
    assert label == "sift1m" and data["train"] is not None


def test_fallback_is_labeled_synthetic(tmp_path, monkeypatch):
    monkeypatch.setattr(bd, "CACHE", str(tmp_path / "empty"))
    monkeypatch.setattr(bd, "_download", lambda *a, **k: False)
    sentinel = {"train": "SYNTH", "queries": None, "metric": "l2-squared"}
    data, label = bd.load_or_synthetic("sift1m", lambda: sentinel)
    assert data is sentinel and label == "synthetic-sift1m-shaped"
