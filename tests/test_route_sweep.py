"""One-server sweep of every /v1 route group SURVEY.md names — a broad
regression net proving the whole API surface answers (status codes only;
the per-surface suites assert content)."""

import json
import urllib.error
import urllib.request
import uuid as uuidlib

import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.server import App, RestServer


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    c = Config()
    c.enable_modules = ["text2vec-local", "backup-filesystem"]
    c.backup_filesystem_path = str(tmp_path_factory.mktemp("backups"))
    app = App(config=c, data_path=str(tmp_path_factory.mktemp("data")))
    server = RestServer(app, port=0)
    server.start()
    yield server
    server.stop()
    app.shutdown()


def _st(srv, method, path, body=None):
    r = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(r, timeout=15) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


def test_every_route_group_answers(srv):
    uid = str(uuidlib.UUID(int=1))
    checks = [
        # group, method, path, body, expected
        ("GET", "/v1/meta", None, 200),
        ("GET", "/v1/.well-known/ready", None, 200),
        ("GET", "/v1/.well-known/live", None, 200),
        ("GET", "/v1/.well-known/openid-configuration", None, 404),  # oidc off
        ("POST", "/v1/schema", {"class": "Sweep", "vectorizer": "none",
                                "vectorIndexConfig": {"distance": "l2-squared"},
                                "properties": [{"name": "t", "dataType": ["text"]}]}, 200),
        ("GET", "/v1/schema", None, 200),
        ("GET", "/v1/schema/Sweep", None, 200),
        ("GET", "/v1/schema/Sweep/shards", None, 200),
        ("POST", "/v1/objects", {"class": "Sweep", "id": uid,
                                 "properties": {"t": "x"}, "vector": [0.0] * 4}, 200),
        ("GET", "/v1/objects", None, 200),
        ("GET", f"/v1/objects/Sweep/{uid}", None, 200),
        ("HEAD", f"/v1/objects/Sweep/{uid}", None, 204),
        ("POST", "/v1/batch/objects", {"objects": []}, 200),
        ("POST", "/v1/graphql",
         {"query": "{ __schema { queryType { name } } }"}, 200),
        ("POST", "/v1/graphql",
         {"query": "{ Get { Sweep (limit: 1) { t } } }"}, 200),
        ("GET", "/v1/nodes", None, 200),
        ("POST", "/v1/classifications", {}, 422),
        ("GET", f"/v1/classifications/{uuidlib.uuid4()}", None, 404),
        ("POST", "/v1/backups/filesystem", {"id": "sweep1"}, 200),
        ("GET", "/v1/backups/filesystem/sweep1", None, 200),
        ("GET", "/v1/modules/text2vec-local/extensions", None, 200),
        ("GET", "/v1/modules/nope/extensions", None, 404),
        ("GET", "/metrics", None, 200),
        ("GET", "/debug/pprof/goroutine", None, 200),
        ("DELETE", f"/v1/objects/Sweep/{uid}", None, 204),
        ("DELETE", "/v1/schema/Sweep", None, 200),
    ]
    failures = [
        (m, p, got, want) for m, p, b, want in checks
        if (got := _st(srv, m, p, b)) != want
    ]
    assert not failures, failures
