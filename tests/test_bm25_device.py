"""Device BM25 engine (inverted/bm25_device.py) vs the host MaxScore engine.

Contract: the dense-row device path must produce the same ranking as the
host engine (inverted/bm25.py) — scores agree to f32 resolution, the id
set is the true top-k, allowLists are honored exactly, and writes
invalidate the device row cache via the shard write generation. Runs on
the CPU jax backend (conftest pins JAX_PLATFORMS=cpu); the same code path
serves on TPU.
"""

import random
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.inverted.bm25 import BM25Searcher
from weaviate_tpu.inverted.bm25_device import DeviceBM25
from weaviate_tpu.inverted.index import InvertedIndex
from weaviate_tpu.storage.bitmap import Bitmap
from weaviate_tpu.storage.lsm import Store


CLASS_DEF = ClassDef.from_dict({
    "class": "Doc",
    "properties": [
        {"name": "body", "dataType": ["text"]},
        {"name": "title", "dataType": ["text"]},
    ],
})


def _corpus(rng, n_docs, vocab, doc_len=20):
    ranks = np.arange(1, len(vocab) + 1, dtype=np.float64)
    p = (1.0 / ranks) / (1.0 / ranks).sum()
    docs = []
    for _ in range(n_docs):
        sub = np.random.default_rng(rng.integers(1 << 31))
        docs.append((" ".join(sub.choice(vocab, size=doc_len, p=p)),
                     " ".join(sub.choice(vocab, size=3, p=p))))
    return docs


def _build(tmp_path, docs, name="dev"):
    store = Store(str(tmp_path / name))
    inv = InvertedIndex(store, CLASS_DEF)
    for i, (body, title) in enumerate(docs):
        inv.add_object(i, {"body": body, "title": title})
    return inv


def _score_map(searcher, query, allow):
    """Exhaustive host ground truth: doc id -> f64 score."""
    units = searcher._build_units(
        query, searcher._searchable_props(None),
        max(searcher._doc_count(), 1))
    if not units:
        return {}
    ids, scores = searcher._rank(units, 1 << 30, allow, prune=False)
    return {int(d): float(s) for d, s in zip(ids, scores)}


def test_device_matches_host_ranking(tmp_path):
    rng = np.random.default_rng(42)
    vocab = np.array([f"w{i}" for i in range(150)])
    inv = _build(tmp_path, _corpus(rng, 500, vocab))
    host = BM25Searcher(inv, CLASS_DEF)
    dev = DeviceBM25(host)

    prng = random.Random(7)
    checked = 0
    for trial in range(25):
        nterms = prng.choice([1, 2, 4, 8])
        query = " ".join(prng.choices(list(vocab), k=nterms))
        limit = prng.choice([1, 5, 20])
        allow = None
        if trial % 3 == 0:
            keep = rng.random(500) < prng.choice([0.1, 0.6])
            allow = Bitmap(np.nonzero(keep)[0].astype(np.uint64))
        truth = _score_map(host, query, allow)
        h = host.search(query, limit, allow_list=allow)
        d = dev.search(query, limit, allow_list=allow)
        assert len(d) == len(h)
        for (h_id, h_s, _), (d_id, d_s, _) in zip(h, d):
            # rank-wise score agreement (ids may swap on f32 near-ties)
            assert d_s == pytest.approx(h_s, rel=1e-5, abs=1e-5)
            # the device id must be a genuine scorer at that level
            assert truth[d_id] == pytest.approx(d_s, rel=1e-5, abs=1e-5)
            if allow is not None:
                assert allow.contains(d_id)
        checked += len(d)
    assert checked > 50


def test_device_row_cache_and_write_invalidation(tmp_path):
    rng = np.random.default_rng(3)
    vocab = np.array([f"w{i}" for i in range(40)])
    docs = _corpus(rng, 120, vocab)
    store = Store(str(tmp_path / "gen"))
    inv = InvertedIndex(store, CLASS_DEF)
    for i, (body, title) in enumerate(docs):
        inv.add_object(i, {"body": body, "title": title})

    gen = [0]
    host = BM25Searcher(inv, CLASS_DEF, gen_fn=lambda: gen[0])
    dev = DeviceBM25(host)
    q = " ".join(vocab[:4])
    first = dev.search(q, 10)
    assert dev._rows, "rows should be cached under the generation"
    again = dev.search(q, 10)
    assert [d for d, _, _ in again] == [d for d, _, _ in first]

    # a write bumps the generation BEFORE mutating (shard discipline)
    gen[0] += 1
    inv.add_object(500, {"body": " ".join(list(vocab[:4]) * 5), "title": "x"})
    after = dev.search(q, 10)
    host_after = host.search(q, 10)
    assert [d for d, _, _ in after] == [d for d, _, _ in host_after]
    assert 500 in _score_map(host, q, None), \
        "the new doc must be visible to scoring post-invalidation"
    assert all(v[0] == gen[0] for v in dev._rows.values()), \
        "stale-generation rows must be evicted"


def test_recycled_bitmap_id_never_aliases_mask(tmp_path):
    """A freed Bitmap's address can be recycled by a DIFFERENT filter's
    Bitmap within one write generation; the mask cache must detect this
    (the entry pins the original object and compares identity) instead of
    serving the stale mask. Simulated by planting a poisoned entry under
    the new Bitmap's id."""
    rng = np.random.default_rng(21)
    vocab = np.array([f"w{i}" for i in range(30)])
    inv = _build(tmp_path, _corpus(rng, 200, vocab), "alias")
    gen = [0]
    host = BM25Searcher(inv, CLASS_DEF, gen_fn=lambda: gen[0])
    dev = DeviceBM25(host)
    q = " ".join(vocab[:4])

    allow_a = Bitmap(np.arange(0, 50, dtype=np.uint64))
    res_a = dev.search(q, 10, allow_list=allow_a)
    assert res_a and all(d < 50 for d, _, _ in res_a)
    (mask_a,) = [v[2] for v in dev._masks.values()]

    allow_b = Bitmap(np.arange(150, 200, dtype=np.uint64))
    # worst case: B recycled A's address AND A's entry is still cached
    dev._masks.clear()
    dev._masks[id(allow_b)] = (gen[0], next(iter([16384])), mask_a, allow_a)
    res_b = dev.search(q, 10, allow_list=allow_b)
    assert res_b and all(150 <= d < 200 for d, _, _ in res_b), \
        "stale mask from a recycled id must not leak into results"


def test_search_batch_matches_per_query(tmp_path):
    """One matmul for Q queries == Q single searches (f32 tolerance),
    including empty-term and no-hit queries in the same batch."""
    rng = np.random.default_rng(17)
    vocab = np.array([f"w{i}" for i in range(100)])
    inv = _build(tmp_path, _corpus(rng, 300, vocab), "batch")
    host = BM25Searcher(inv, CLASS_DEF)
    dev = DeviceBM25(host)
    prng = random.Random(3)
    queries = [" ".join(prng.choices(list(vocab), k=prng.choice([1, 2, 4, 8])))
               for _ in range(40)]
    queries[7] = "zzz-not-in-vocab"      # no units at all
    queries[23] = ""                      # empty query
    batched = dev.search_batch(queries, 10)
    assert batched is not None and len(batched) == len(queries)
    assert batched[7] == [] and batched[23] == []
    for q, got in zip(queries, batched):
        want = dev.search(q, 10)
        assert len(got) == len(want)
        for (g_id, g_s, _), (w_id, w_s, _) in zip(got, want):
            assert g_s == pytest.approx(w_s, rel=1e-5, abs=1e-5)
        truth = _score_map(host, q, None)
        for g_id, g_s, _ in got:
            assert truth[g_id] == pytest.approx(g_s, rel=1e-5, abs=1e-5)


def test_duplicate_and_nonpositive_boosts(tmp_path):
    """properties=["body","body"] double-counts in EVERY path (selection
    matrix accumulates); non-positive boosts fall back to the host engine
    (the score>0 empty-slot sentinel cannot represent them)."""
    rng = np.random.default_rng(33)
    vocab = np.array([f"w{i}" for i in range(40)])
    inv = _build(tmp_path, _corpus(rng, 120, vocab), "boosts")
    host = BM25Searcher(inv, CLASS_DEF)
    dev = DeviceBM25(host)
    q = " ".join(vocab[:3])

    dup = ["body", "body"]
    h = host.search(q, 8, properties=dup)
    d = dev.search(q, 8, properties=dup)
    b = dev.search_batch([q], 8, properties=dup)[0]
    assert [x[1] for x in d] == pytest.approx([x[1] for x in h], rel=1e-5)
    assert [x[1] for x in b] == pytest.approx([x[1] for x in h], rel=1e-5)

    neg = ["body^-1"]
    h_neg = host.search(q, 8, properties=neg)
    d_neg = dev.search(q, 8, properties=neg)
    assert len(d_neg) == len(h_neg) > 0, \
        "negative boosts must serve (host fallback), not return empty"
    assert [x[1] for x in d_neg] == pytest.approx(
        [x[1] for x in h_neg], rel=1e-5)
    assert dev.search_batch([q], 8, properties=neg) is None, \
        "batch lane must decline non-positive boosts"


def test_search_batch_slices_under_stack_budget(tmp_path, monkeypatch):
    """With a tiny transient-stack budget the batch must split into
    multiple matmul slices and still produce identical results."""
    from weaviate_tpu.inverted import bm25_device as mod

    rng = np.random.default_rng(29)
    vocab = np.array([f"w{i}" for i in range(60)])
    inv = _build(tmp_path, _corpus(rng, 150, vocab), "slice")
    host = BM25Searcher(inv, CLASS_DEF)
    dev = DeviceBM25(host)
    prng = random.Random(11)
    queries = [" ".join(prng.choices(list(vocab), k=4)) for _ in range(20)]
    full = dev.search_batch(queries, 10)
    # budget of ~2 rows at this n_pad: every query pair forces a new slice
    monkeypatch.setattr(mod, "_BATCH_STACK_MAX_BYTES", 16384 * 4 * 2)
    dev2 = DeviceBM25(BM25Searcher(inv, CLASS_DEF))
    sliced = dev2.search_batch(queries, 10)
    assert len(sliced) == len(full)
    for a, b in zip(sliced, full):
        assert [d for d, _, _ in a] == [d for d, _, _ in b]
        assert [v for _, v, _ in a] == pytest.approx(
            [v for _, v, _ in b], rel=1e-6)  # matmul padding reorders f32 adds


def test_get_class_batched_kw_lane(tmp_path):
    """Explorer groups plain bm25 slots into the batched lane; filtered/
    explained slots take the per-query path; results match the host shard."""
    from weaviate_tpu.db.shard import Shard
    from weaviate_tpu.server import App
    from weaviate_tpu.usecases.traverser import GetParams

    app = App(data_path=str(tmp_path / "kwapp"))
    app.schema.add_class({
        "class": "Kw", "vectorIndexType": "noop",
        "invertedIndexConfig": {"bm25": {"device": True}},
        "properties": [{"name": "t", "dataType": ["text"]}]})
    kidx = app.db.get_index("Kw")
    vocab = [f"w{i}" for i in range(30)]
    from weaviate_tpu.entities.storobj import StorObj
    kidx.put_batch([
        StorObj(class_name="Kw", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"t": " ".join(
                    np.random.default_rng(i).choice(vocab, size=10))})
        for i in range(200)])
    try:
        qs = [" ".join(vocab[i:i + 3]) for i in range(12)]
        plist = [GetParams(class_name="Kw",
                           keyword_ranking={"query": q}, limit=5)
                 for q in qs]
        # one slot with a filter: must take the per-query path, not break
        from weaviate_tpu.entities.filters import LocalFilter
        plist.append(GetParams(
            class_name="Kw", keyword_ranking={"query": qs[0]}, limit=5,
            filters=LocalFilter.from_dict({
                "path": ["t"], "operator": "Like", "valueText": "w1*"})))
        batched = app.traverser.get_class_batched(plist)
        assert not any(isinstance(r, Exception) for r in batched), batched
        shard = next(iter(kidx.shards.values()))
        assert shard.bm25_device is not None
        for p, got in zip(plist, batched):
            solo = app.traverser.get_class(p)
            assert [r.obj.uuid for r in got] == [r.obj.uuid for r in solo]
            assert [r.score for r in got] == pytest.approx(
                [r.score for r in solo], rel=1e-5)
    finally:
        app.shutdown()


def test_batched_hybrid_matches_solo(tmp_path):
    """Hybrid slots batch both legs (one keyword matmul + one dense kNN
    dispatch); results must equal per-slot get_class across alphas 0 /
    0.5 / 1, with explicit vectors and keyword-only slots mixed."""
    from weaviate_tpu.server import App
    from weaviate_tpu.usecases.traverser import GetParams

    app = App(data_path=str(tmp_path / "hyb"))
    app.schema.add_class({
        "class": "Hy", "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "invertedIndexConfig": {"bm25": {"device": True}},
        "properties": [{"name": "t", "dataType": ["text"]}]})
    hidx = app.db.get_index("Hy")
    vocab = [f"w{i}" for i in range(25)]
    rng = np.random.default_rng(4)
    hidx.put_batch([
        StorObj(class_name="Hy", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"t": " ".join(
                    np.random.default_rng(i).choice(vocab, size=8))},
                vector=rng.standard_normal(16).astype(np.float32))
        for i in range(200)])
    tr = app.traverser
    try:
        prng = random.Random(2)
        plist = []
        for alpha in (0.0, 0.5, 1.0):
            for _ in range(4):
                q = " ".join(prng.choices(vocab, k=3))
                v = rng.standard_normal(16).astype(np.float32).tolist()
                plist.append(GetParams(
                    class_name="Hy",
                    hybrid={"query": q, "vector": v, "alpha": alpha},
                    limit=6))
        batched = tr.get_class_batched(plist)
        assert not any(isinstance(r, Exception) for r in batched), batched
        shard = next(iter(hidx.shards.values()))
        assert shard.bm25_device is not None \
            and shard.bm25_device.last_batch_stats is not None, \
            "hybrid sparse leg must have used the batched device engine"
        for p, got in zip(plist, batched):
            # the LEGACY per-slot path is the baseline — get_class itself
            # routes through the batched lane, which would compare the new
            # code against itself
            solo = tr.explorer._get_one(p)
            assert [r.score for r in got] == pytest.approx(
                [r.score for r in solo], rel=1e-4, abs=1e-5)
            key = lambda r: (-round(r.score or 0, 4), r.obj.uuid)  # noqa: E731
            assert [r.obj.uuid for r in sorted(got, key=key)] == \
                [r.obj.uuid for r in sorted(solo, key=key)]
    finally:
        app.shutdown()


def test_explanations_fall_back_to_host(tmp_path):
    rng = np.random.default_rng(5)
    vocab = np.array([f"w{i}" for i in range(30)])
    inv = _build(tmp_path, _corpus(rng, 60, vocab), "exp")
    dev = DeviceBM25(BM25Searcher(inv, CLASS_DEF))
    hits = dev.search(str(vocab[0]), 5, additional_explanations=True)
    assert hits and all(h[2] is not None for h in hits)
    assert any("frequency" in k for h in hits for k in h[2])


def test_device_engine_under_concurrent_writes(tmp_path):
    """Writers bump the shard generation mid-search; the device row/mask
    caches must never serve a stale generation's scores, and no search may
    raise. Final state: device ranking == host ranking."""
    import threading

    from weaviate_tpu.db.shard import Shard

    cd = ClassDef(name="Kw", properties=[
        Property(name="t", data_type=["text"]),
    ], vector_index_type="noop")
    cfg = parse_and_validate_config("noop", {})
    shard = Shard("c0", str(tmp_path / "conc"), cd, cfg,
                  invert_cfg={"bm25": {"device": True}})
    vocab = [f"w{i}" for i in range(20)]
    shard.put_batch([
        StorObj(class_name="Kw", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"t": " ".join(
                    np.random.default_rng(i).choice(vocab, size=8))})
        for i in range(100)])
    errs: list = []
    stop = threading.Event()

    def writer():
        i = 1000
        while not stop.is_set():
            try:
                shard.put_object(StorObj(
                    class_name="Kw", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"t": " ".join(vocab[:4])}))
                i += 1
            except Exception as e:  # noqa: BLE001
                errs.append(e)

    def reader():
        q = " ".join(vocab[:3])
        while not stop.is_set():
            try:
                shard.object_search(5, keyword_ranking={"query": q})
            except Exception as e:  # noqa: BLE001
                errs.append(e)

    threads = [threading.Thread(target=writer)] + \
        [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    import time
    time.sleep(2.5)
    stop.set()
    for t in threads:
        t.join()
    try:
        assert not errs, errs[:3]
        q = " ".join(vocab[:3])
        dev_hits = shard.object_search(10, keyword_ranking={"query": q})
        shard.bm25_device = None
        host_hits = shard.object_search(10, keyword_ranking={"query": q})
        key = lambda r: (-round(r.score, 4), r.obj.uuid)  # noqa: E731
        assert [r.obj.uuid for r in sorted(dev_hits, key=key)] == \
            [r.obj.uuid for r in sorted(host_hits, key=key)]
    finally:
        shard.shutdown()


def test_batched_lane_under_concurrent_writes(tmp_path):
    """The matmul batch lane under a write storm: no exceptions, and the
    post-storm batched ranking equals the host engine's."""
    import threading
    import time

    from weaviate_tpu.server import App
    from weaviate_tpu.usecases.traverser import GetParams

    app = App(data_path=str(tmp_path / "bconc"))
    app.schema.add_class({
        "class": "Kw", "vectorIndexType": "noop",
        "invertedIndexConfig": {"bm25": {"device": True}},
        "properties": [{"name": "t", "dataType": ["text"]}]})
    kidx = app.db.get_index("Kw")
    vocab = [f"w{i}" for i in range(20)]
    kidx.put_batch([
        StorObj(class_name="Kw", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"t": " ".join(
                    np.random.default_rng(i).choice(vocab, size=8))})
        for i in range(100)])
    tr = app.traverser
    errs: list = []
    stop = threading.Event()

    def writer():
        i = 2000
        while not stop.is_set():
            try:
                kidx.put_batch([StorObj(
                    class_name="Kw", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"t": " ".join(vocab[:4])})])
                i += 1
            except Exception as e:  # noqa: BLE001
                errs.append(e)

    def reader(seed):
        rr = random.Random(seed)
        while not stop.is_set():
            qs = [" ".join(rr.choices(vocab, k=3)) for _ in range(6)]
            try:
                res = tr.get_class_batched([
                    GetParams(class_name="Kw",
                              keyword_ranking={"query": q}, limit=5)
                    for q in qs])
                bad = [r for r in res if isinstance(r, Exception)]
                if bad:
                    errs.extend(bad)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

    threads = [threading.Thread(target=writer)] + \
        [threading.Thread(target=reader, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(2.5)
    stop.set()
    for t in threads:
        t.join()
    try:
        assert not errs, errs[:3]
        shard = next(iter(kidx.shards.values()))
        q = " ".join(vocab[:3])
        p = GetParams(class_name="Kw", keyword_ranking={"query": q}, limit=10)
        (batched,) = tr.get_class_batched([p])
        # the matmul lane must have actually served (not a vacuous
        # host-vs-host comparison after a silent fallback)
        assert shard.bm25_device is not None
        assert shard.bm25_device.last_batch_stats is not None, \
            "batched device dispatch did not engage"
        shard.bm25_device = None
        host = tr.get_class(p)
        key = lambda r: (-round(r.score, 4), r.obj.uuid)  # noqa: E731
        assert [r.obj.uuid for r in sorted(batched, key=key)] == \
            [r.obj.uuid for r in sorted(host, key=key)]
    finally:
        app.shutdown()


def test_shard_opt_in_serves_device_path(tmp_path):
    from weaviate_tpu.db.shard import Shard

    cd = ClassDef(name="Kw", properties=[
        Property(name="t", data_type=["text"]),
    ], vector_index_type="hnsw_tpu")
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    rng = np.random.default_rng(9)
    vocab = [f"w{i}" for i in range(30)]
    objs = [StorObj(class_name="Kw", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"t": " ".join(
                        np.random.default_rng(i).choice(vocab, size=12))},
                    vector=rng.standard_normal(8).astype(np.float32))
            for i in range(150)]

    on = Shard("s0", str(tmp_path / "on"), cd, cfg,
               invert_cfg={"bm25": {"device": True}})
    off = Shard("s1", str(tmp_path / "off"), cd, cfg)
    assert on.bm25_device is not None and off.bm25_device is None
    on.put_batch(objs)
    off.put_batch(objs)
    try:
        q = " ".join(vocab[:3])
        r_on = on.object_search(10, keyword_ranking={"query": q})
        r_off = off.object_search(10, keyword_ranking={"query": q})
        assert [r.score for r in r_on] == pytest.approx(
            [r.score for r in r_off], rel=1e-5)
        # uuid order may swap inside f32 near-tie groups; grouping by
        # rounded score makes the comparison tie-stable (strict ranking
        # equivalence is test_device_matches_host_ranking's job)
        key = lambda r: (-round(r.score, 4), r.obj.uuid)  # noqa: E731
        assert sorted(r_on, key=key)[0].obj.uuid == sorted(r_off, key=key)[0].obj.uuid
        assert [r.obj.uuid for r in sorted(r_on, key=key)] == \
            [r.obj.uuid for r in sorted(r_off, key=key)]
        assert on.bm25_device._rows, "device rows engaged on the shard path"
    finally:
        on.shutdown()
        off.shutdown()
