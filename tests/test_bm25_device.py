"""Device BM25 engine (inverted/bm25_device.py) vs the host MaxScore engine.

Contract: the dense-row device path must produce the same ranking as the
host engine (inverted/bm25.py) — scores agree to f32 resolution, the id
set is the true top-k, allowLists are honored exactly, and writes
invalidate the device row cache via the shard write generation. Runs on
the CPU jax backend (conftest pins JAX_PLATFORMS=cpu); the same code path
serves on TPU.
"""

import random
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.inverted.bm25 import BM25Searcher
from weaviate_tpu.inverted.bm25_device import DeviceBM25
from weaviate_tpu.inverted.index import InvertedIndex
from weaviate_tpu.storage.bitmap import Bitmap
from weaviate_tpu.storage.lsm import Store


CLASS_DEF = ClassDef.from_dict({
    "class": "Doc",
    "properties": [
        {"name": "body", "dataType": ["text"]},
        {"name": "title", "dataType": ["text"]},
    ],
})


def _corpus(rng, n_docs, vocab, doc_len=20):
    ranks = np.arange(1, len(vocab) + 1, dtype=np.float64)
    p = (1.0 / ranks) / (1.0 / ranks).sum()
    docs = []
    for _ in range(n_docs):
        sub = np.random.default_rng(rng.integers(1 << 31))
        docs.append((" ".join(sub.choice(vocab, size=doc_len, p=p)),
                     " ".join(sub.choice(vocab, size=3, p=p))))
    return docs


def _build(tmp_path, docs, name="dev"):
    store = Store(str(tmp_path / name))
    inv = InvertedIndex(store, CLASS_DEF)
    for i, (body, title) in enumerate(docs):
        inv.add_object(i, {"body": body, "title": title})
    return inv


def _score_map(searcher, query, allow):
    """Exhaustive host ground truth: doc id -> f64 score."""
    units = searcher._build_units(
        query, searcher._searchable_props(None),
        max(searcher._doc_count(), 1))
    if not units:
        return {}
    ids, scores = searcher._rank(units, 1 << 30, allow, prune=False)
    return {int(d): float(s) for d, s in zip(ids, scores)}


def test_device_matches_host_ranking(tmp_path):
    rng = np.random.default_rng(42)
    vocab = np.array([f"w{i}" for i in range(150)])
    inv = _build(tmp_path, _corpus(rng, 500, vocab))
    host = BM25Searcher(inv, CLASS_DEF)
    dev = DeviceBM25(host)

    prng = random.Random(7)
    checked = 0
    for trial in range(25):
        nterms = prng.choice([1, 2, 4, 8])
        query = " ".join(prng.choices(list(vocab), k=nterms))
        limit = prng.choice([1, 5, 20])
        allow = None
        if trial % 3 == 0:
            keep = rng.random(500) < prng.choice([0.1, 0.6])
            allow = Bitmap(np.nonzero(keep)[0].astype(np.uint64))
        truth = _score_map(host, query, allow)
        h = host.search(query, limit, allow_list=allow)
        d = dev.search(query, limit, allow_list=allow)
        assert len(d) == len(h)
        for (h_id, h_s, _), (d_id, d_s, _) in zip(h, d):
            # rank-wise score agreement (ids may swap on f32 near-ties)
            assert d_s == pytest.approx(h_s, rel=1e-5, abs=1e-5)
            # the device id must be a genuine scorer at that level
            assert truth[d_id] == pytest.approx(d_s, rel=1e-5, abs=1e-5)
            if allow is not None:
                assert allow.contains(d_id)
        checked += len(d)
    assert checked > 50


def test_device_row_cache_and_write_invalidation(tmp_path):
    rng = np.random.default_rng(3)
    vocab = np.array([f"w{i}" for i in range(40)])
    docs = _corpus(rng, 120, vocab)
    store = Store(str(tmp_path / "gen"))
    inv = InvertedIndex(store, CLASS_DEF)
    for i, (body, title) in enumerate(docs):
        inv.add_object(i, {"body": body, "title": title})

    gen = [0]
    host = BM25Searcher(inv, CLASS_DEF, gen_fn=lambda: gen[0])
    dev = DeviceBM25(host)
    q = " ".join(vocab[:4])
    first = dev.search(q, 10)
    assert dev._rows, "rows should be cached under the generation"
    again = dev.search(q, 10)
    assert [d for d, _, _ in again] == [d for d, _, _ in first]

    # a write bumps the generation BEFORE mutating (shard discipline)
    gen[0] += 1
    inv.add_object(500, {"body": " ".join(list(vocab[:4]) * 5), "title": "x"})
    after = dev.search(q, 10)
    host_after = host.search(q, 10)
    assert [d for d, _, _ in after] == [d for d, _, _ in host_after]
    assert 500 in _score_map(host, q, None), \
        "the new doc must be visible to scoring post-invalidation"
    assert all(v[0] == gen[0] for v in dev._rows.values()), \
        "stale-generation rows must be evicted"


def test_recycled_bitmap_id_never_aliases_mask(tmp_path):
    """A freed Bitmap's address can be recycled by a DIFFERENT filter's
    Bitmap within one write generation; the mask cache must detect this
    (the entry pins the original object and compares identity) instead of
    serving the stale mask. Simulated by planting a poisoned entry under
    the new Bitmap's id."""
    rng = np.random.default_rng(21)
    vocab = np.array([f"w{i}" for i in range(30)])
    inv = _build(tmp_path, _corpus(rng, 200, vocab), "alias")
    gen = [0]
    host = BM25Searcher(inv, CLASS_DEF, gen_fn=lambda: gen[0])
    dev = DeviceBM25(host)
    q = " ".join(vocab[:4])

    allow_a = Bitmap(np.arange(0, 50, dtype=np.uint64))
    res_a = dev.search(q, 10, allow_list=allow_a)
    assert res_a and all(d < 50 for d, _, _ in res_a)
    (mask_a,) = [v[2] for v in dev._masks.values()]

    allow_b = Bitmap(np.arange(150, 200, dtype=np.uint64))
    # worst case: B recycled A's address AND A's entry is still cached
    dev._masks.clear()
    dev._masks[id(allow_b)] = (gen[0], next(iter([16384])), mask_a, allow_a)
    res_b = dev.search(q, 10, allow_list=allow_b)
    assert res_b and all(150 <= d < 200 for d, _, _ in res_b), \
        "stale mask from a recycled id must not leak into results"


def test_explanations_fall_back_to_host(tmp_path):
    rng = np.random.default_rng(5)
    vocab = np.array([f"w{i}" for i in range(30)])
    inv = _build(tmp_path, _corpus(rng, 60, vocab), "exp")
    dev = DeviceBM25(BM25Searcher(inv, CLASS_DEF))
    hits = dev.search(str(vocab[0]), 5, additional_explanations=True)
    assert hits and all(h[2] is not None for h in hits)
    assert any("frequency" in k for h in hits for k in h[2])


def test_shard_opt_in_serves_device_path(tmp_path):
    from weaviate_tpu.db.shard import Shard

    cd = ClassDef(name="Kw", properties=[
        Property(name="t", data_type=["text"]),
    ], vector_index_type="hnsw_tpu")
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    rng = np.random.default_rng(9)
    vocab = [f"w{i}" for i in range(30)]
    objs = [StorObj(class_name="Kw", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"t": " ".join(
                        np.random.default_rng(i).choice(vocab, size=12))},
                    vector=rng.standard_normal(8).astype(np.float32))
            for i in range(150)]

    on = Shard("s0", str(tmp_path / "on"), cd, cfg,
               invert_cfg={"bm25": {"device": True}})
    off = Shard("s1", str(tmp_path / "off"), cd, cfg)
    assert on.bm25_device is not None and off.bm25_device is None
    on.put_batch(objs)
    off.put_batch(objs)
    try:
        q = " ".join(vocab[:3])
        r_on = on.object_search(10, keyword_ranking={"query": q})
        r_off = off.object_search(10, keyword_ranking={"query": q})
        assert [r.score for r in r_on] == pytest.approx(
            [r.score for r in r_off], rel=1e-5)
        # uuid order may swap inside f32 near-tie groups; grouping by
        # rounded score makes the comparison tie-stable (strict ranking
        # equivalence is test_device_matches_host_ranking's job)
        key = lambda r: (-round(r.score, 4), r.obj.uuid)  # noqa: E731
        assert sorted(r_on, key=key)[0].obj.uuid == sorted(r_off, key=key)[0].obj.uuid
        assert [r.obj.uuid for r in sorted(r_on, key=key)] == \
            [r.obj.uuid for r in sorted(r_off, key=key)]
        assert on.bm25_device._rows, "device rows engaged on the shard path"
    finally:
        on.shutdown()
        off.shutdown()
