"""DB core: Shard / ClassIndex / DB CRUD, batch, vector + BM25 + filtered
search, persistence across restart, sharding routing.

Mirrors the reference's integration tier (crud_integration_test.go,
restart_journey_integration_test.go) on real disk, JAX CPU backend.
"""

import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.cluster.sharding import ShardingConfig, ShardingState, murmur3_64
from weaviate_tpu.db import DB
from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.entities.vectorindex import parse_and_validate_config


def make_class(name="Article"):
    return ClassDef(
        name=name,
        properties=[
            Property(name="title", data_type=["text"]),
            Property(name="wordCount", data_type=["int"]),
            Property(name="published", data_type=["boolean"]),
        ],
        vector_index_type="hnsw_tpu",
    )


@pytest.fixture
def db(tmp_path):
    d = DB(str(tmp_path / "data"))
    yield d
    d.shutdown()


def new_obj(i, dim=8, cls="Article"):
    rng = np.random.default_rng(i)
    return StorObj(
        class_name=cls,
        uuid=str(uuidlib.UUID(int=i + 1)),
        properties={"title": f"hello world {i}", "wordCount": i, "published": i % 2 == 0},
        vector=rng.standard_normal(dim).astype(np.float32),
    )


def test_crud_roundtrip(db):
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    idx = db.add_class(make_class(), cfg)
    obj = new_obj(1)
    idx.put_object(obj)
    got = idx.object_by_uuid(obj.uuid)
    assert got is not None
    assert got.properties["title"] == "hello world 1"
    assert got.vector is not None and got.vector.shape == (8,)
    assert idx.exists(obj.uuid)
    assert idx.object_count() == 1

    # update: same uuid, new props; docID must advance, count stays 1
    old_doc = got.doc_id
    obj2 = new_obj(1)
    obj2.properties["title"] = "updated title"
    idx.put_object(obj2)
    got2 = idx.object_by_uuid(obj2.uuid)
    assert got2.properties["title"] == "updated title"
    assert got2.doc_id > old_doc
    assert idx.object_count() == 1

    assert idx.delete_object(obj.uuid)
    assert not idx.exists(obj.uuid)
    assert idx.object_count() == 0
    assert not idx.delete_object(obj.uuid)


def test_batch_and_vector_search(db):
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    idx = db.add_class(make_class(), cfg)
    objs = [new_obj(i) for i in range(200)]
    errs = idx.put_batch(objs)
    assert all(e is None for e in errs)
    assert idx.object_count() == 200

    # self-search: each query vector must find its own object first
    queries = np.stack([objs[i].vector for i in (0, 7, 42)])
    res = idx.object_vector_search(queries, k=5)
    assert len(res) == 3
    for qi, i in enumerate((0, 7, 42)):
        assert res[qi][0].obj.uuid == objs[i].uuid
        assert res[qi][0].distance < 1e-3


def test_uuid_bytes_fast_path():
    from weaviate_tpu.db.shard import _uuid_bytes

    u = str(uuidlib.UUID(int=0xDEADBEEF))
    assert _uuid_bytes(u) == uuidlib.UUID(u).bytes
    assert _uuid_bytes(u.upper()) == uuidlib.UUID(u).bytes
    assert _uuid_bytes("urn:uuid:" + u) == uuidlib.UUID(u).bytes
    for bad in ["0" * 36, "not-a-uuid-at-all-not-a-uuid-at-all!", "x" * 36]:
        with pytest.raises(ValueError):
            _uuid_bytes(bad)


def test_batch_duplicate_uuid_within_batch(db):
    """A batch carrying the same uuid twice keeps only the LAST version —
    object store, inverted postings, and vector index must all agree
    (the staged batch path must treat the earlier version as 'previous')."""
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    idx = db.add_class(make_class(), cfg)
    a = new_obj(1)
    b = new_obj(1)  # same uuid
    b.properties = dict(b.properties)
    b.properties["title"] = "second version only"
    b.vector = a.vector + 1.0
    filler = [new_obj(i) for i in range(2, 30)]
    errs = idx.put_batch([a] + filler[:10] + [b] + filler[10:])
    assert all(e is None for e in errs)
    assert idx.object_count() == 29  # 28 fillers + 1 (deduped)
    got = idx.object_by_uuid(a.uuid)
    assert got.properties["title"] == "second version only"
    # inverted postings: only the second version's tokens match
    from weaviate_tpu.entities.filters import LocalFilter as LF

    hits = idx.object_search(10, flt=LF.from_dict(
        {"operator": "Equal", "path": ["title"], "valueText": "second"}))
    assert [h.obj.uuid for h in hits] == [a.uuid]
    hits = idx.object_search(
        10, keyword_ranking={"query": "second version"})
    assert hits and hits[0].obj.uuid == a.uuid
    # vector index holds the second vector, not the first
    res = idx.object_vector_search(b.vector, k=1)
    assert res[0][0].obj.uuid == a.uuid and res[0][0].distance < 1e-3
    res = idx.object_vector_search(a.vector, k=1)
    assert res[0][0].distance > 1.0


def test_allow_list_cached_across_fresh_filter_objects(db):
    """The serving path builds a fresh LocalFilter per request: the shard's
    allowList cache must key on filter CONTENT (same Bitmap object back, so
    the device-words cache downstream also engages) and invalidate on ANY
    write."""
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    idx = db.add_class(make_class(), cfg)
    idx.put_batch([new_obj(i) for i in range(30)])
    shard = next(iter(idx.shards.values()))
    d = {"operator": "LessThan", "path": ["wordCount"], "valueInt": 10}
    a1 = shard.build_allow_list(LocalFilter.from_dict(d))
    a2 = shard.build_allow_list(LocalFilter.from_dict(dict(d)))  # fresh objs
    assert a2 is a1, "content-equal filters must reuse the cached Bitmap"
    assert sorted(int(x) for x in a1.to_array()) == sorted(
        shard.object_by_uuid(new_obj(i).uuid).doc_id for i in range(10))
    # ANY write invalidates: the new matching object must appear
    extra = new_obj(100)
    extra.properties["wordCount"] = 5
    idx.put_object(extra)
    a3 = shard.build_allow_list(LocalFilter.from_dict(d))
    assert a3 is not a1
    assert shard.object_by_uuid(extra.uuid).doc_id in [int(x) for x in a3.to_array()]
    # deletes invalidate too
    idx.delete_object(new_obj(3).uuid)
    a4 = shard.build_allow_list(LocalFilter.from_dict(d))
    assert a4 is not a3


def test_filtered_vector_search(db):
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    idx = db.add_class(make_class(), cfg)
    idx.put_batch([new_obj(i) for i in range(100)])
    flt = LocalFilter.from_dict(
        {"operator": "Equal", "path": ["published"], "valueBoolean": True}
    )
    res = idx.object_vector_search(new_obj(3).vector, k=10, flt=flt)
    assert len(res[0]) == 10
    for r in res[0]:
        assert r.obj.properties["published"] is True

    # range filter
    flt2 = LocalFilter.from_dict(
        {"operator": "LessThan", "path": ["wordCount"], "valueInt": 5}
    )
    res2 = idx.object_vector_search(new_obj(3).vector, k=10, flt=flt2)
    assert 0 < len(res2[0]) <= 5
    for r in res2[0]:
        assert r.obj.properties["wordCount"] < 5


def test_bm25_and_filter_only_search(db):
    cfg = parse_and_validate_config("hnsw_tpu", {})
    idx = db.add_class(make_class(), cfg)
    objs = [new_obj(i) for i in range(20)]
    objs[5].properties["title"] = "quantum computing breakthrough"
    objs[6].properties["title"] = "quantum supremacy"
    idx.put_batch(objs)

    hits = idx.object_search(limit=10, keyword_ranking={"query": "quantum"})
    assert len(hits) == 2
    assert {h.obj.uuid for h in hits} == {objs[5].uuid, objs[6].uuid}
    assert all(h.score is not None and h.score > 0 for h in hits)

    listed = idx.object_search(limit=7)
    assert len(listed) == 7

    flt = LocalFilter.from_dict(
        {"operator": "Equal", "path": ["published"], "valueBoolean": False}
    )
    res = idx.object_search(limit=100, flt=flt)
    assert len(res) == 10
    assert all(r.obj.properties["published"] is False for r in res)


def test_merge_object(db):
    cfg = parse_and_validate_config("hnsw_tpu", {})
    idx = db.add_class(make_class(), cfg)
    obj = new_obj(9)
    idx.put_object(obj)
    idx.merge_object(obj.uuid, {"title": "patched"})
    got = idx.object_by_uuid(obj.uuid)
    assert got.properties["title"] == "patched"
    assert got.properties["wordCount"] == 9  # untouched prop survives


def test_delete_by_filter(db):
    cfg = parse_and_validate_config("hnsw_tpu", {})
    idx = db.add_class(make_class(), cfg)
    idx.put_batch([new_obj(i) for i in range(30)])
    flt = LocalFilter.from_dict(
        {"operator": "Equal", "path": ["published"], "valueBoolean": True}
    )
    dry = idx.delete_by_filter(flt, dry_run=True)
    assert dry["matches"] == 15
    assert idx.object_count() == 30
    res = idx.delete_by_filter(flt)
    assert res["matches"] == 15
    assert idx.object_count() == 15


def test_restart_journey(tmp_path):
    """restart_journey_integration_test.go analog: write, shutdown, reopen."""
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    db1 = DB(str(tmp_path / "data"))
    idx = db1.add_class(make_class(), cfg)
    objs = [new_obj(i) for i in range(50)]
    idx.put_batch(objs)
    idx.delete_object(objs[10].uuid)
    db1.flush()
    db1.shutdown()

    db2 = DB(str(tmp_path / "data"))
    idx2 = db2.add_class(make_class(), cfg)
    assert idx2.object_count() == 49
    got = idx2.object_by_uuid(objs[3].uuid)
    assert got is not None and got.properties["wordCount"] == 3
    assert idx2.object_by_uuid(objs[10].uuid) is None
    res = idx2.object_vector_search(objs[3].vector, k=3)
    assert res[0][0].obj.uuid == objs[3].uuid
    db2.shutdown()


def test_multi_shard_routing_and_search(tmp_path):
    """Multiple local shards: routing is deterministic, search fans out."""
    state = ShardingState("Article", ShardingConfig(desired_count=4), ["node-0"])
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    db = DB(str(tmp_path / "data"))
    idx = db.add_class(make_class(), cfg, sharding_state=state)
    assert len(idx.shards) == 4
    objs = [new_obj(i) for i in range(120)]
    idx.put_batch(objs)
    per_shard = [s.object_count() for s in idx.shards.values()]
    assert sum(per_shard) == 120
    assert all(c > 0 for c in per_shard)  # murmur3 spreads over all shards

    res = idx.object_vector_search(objs[17].vector, k=5)
    assert res[0][0].obj.uuid == objs[17].uuid

    hits = idx.object_search(limit=200)
    assert len(hits) == 120
    db.shutdown()


def test_murmur3_kat():
    """Known-answer vectors for murmur3 x64_128 (first 64 bits)."""
    # values computed from the canonical C++ MurmurHash3_x64_128
    assert murmur3_64(b"") == 0
    assert murmur3_64(b"hello") == 0xCBD8A7B341BD9B02
    assert murmur3_64(b"hello, world") == 0x342FAC623A5EBC8E
    assert murmur3_64(b"The quick brown fox jumps over the lazy dog") == 0xE34BBC7BBC071B6C


def test_geo_filter(db):
    cls = ClassDef(
        name="Place",
        properties=[
            Property(name="name", data_type=["text"]),
            Property(name="location", data_type=["geoCoordinates"]),
        ],
    )
    cfg = parse_and_validate_config("hnsw_tpu", {})
    idx = db.add_class(cls, cfg)
    places = [
        ("berlin", 52.52, 13.405),
        ("potsdam", 52.39, 13.065),
        ("munich", 48.137, 11.575),
    ]
    for i, (name, lat, lon) in enumerate(places):
        idx.put_object(
            StorObj(
                class_name="Place",
                uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"name": name, "location": {"latitude": lat, "longitude": lon}},
            )
        )
    flt = LocalFilter.from_dict(
        {
            "operator": "WithinGeoRange",
            "path": ["location"],
            "valueGeoRange": {
                "geoCoordinates": {"latitude": 52.52, "longitude": 13.405},
                "distance": {"max": 40_000},
            },
        }
    )
    res = idx.object_search(limit=10, flt=flt)
    names = {r.obj.properties["name"] for r in res}
    assert names == {"berlin", "potsdam"}
