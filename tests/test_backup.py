"""Backup subsystem: create -> destroy -> restore -> query journeys,
single-node over REST and multi-node through the cluster harness.

Reference test model: usecases/backup tests + backup journey acceptance
tests (create/status/restore endpoints over a filesystem backend).
"""

import json
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.modules import Provider
from weaviate_tpu.modules.backup_fs import FilesystemBackupBackend
from weaviate_tpu.server import App, RestServer
from weaviate_tpu.usecases.backup import BackupError, BackupScheduler


def _req(port, method, path, body=None):
    import urllib.error
    import urllib.request

    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(f"http://127.0.0.1:{port}{path}", data=data, method=method)
    r.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None


@pytest.fixture
def backed_app(tmp_path):
    c = Config()
    c.enable_modules = ["backup-filesystem"]
    c.backup_filesystem_path = str(tmp_path / "backups")
    app = App(config=c, data_path=str(tmp_path / "data"))
    srv = RestServer(app, port=0)
    srv.start()
    yield app, srv
    srv.stop()
    app.shutdown()


def _import_docs(port, n=20, cls="Doc"):
    _req(port, "POST", "/v1/schema", {
        "class": cls,
        "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "title", "dataType": ["text"]},
                       {"name": "n", "dataType": ["int"]}],
    })
    objs = [{"class": cls, "id": str(uuidlib.UUID(int=i + 1)),
             "properties": {"title": f"doc {i}", "n": i},
             "vector": np.random.default_rng(i).standard_normal(8).tolist()}
            for i in range(n)]
    st, out = _req(port, "POST", "/v1/batch/objects", {"objects": objs})
    assert st == 200 and all(o["result"]["status"] == "SUCCESS" for o in out)
    return objs


def test_backup_restore_journey_rest(backed_app):
    """The full journey over REST: import -> backup -> drop class ->
    restore -> data and vector search are back."""
    app, srv = backed_app
    objs = _import_docs(srv.port)

    st, out = _req(srv.port, "POST", "/v1/backups/filesystem", {"id": "snap1"})
    assert st == 200 and out["status"] in ("STARTED", "TRANSFERRING", "SUCCESS")
    final = app.backup_scheduler.wait("snap1")
    assert final["status"] == "SUCCESS"
    st, out = _req(srv.port, "GET", "/v1/backups/filesystem/snap1")
    assert st == 200 and out["status"] == "SUCCESS"

    # destroy the data
    st, _ = _req(srv.port, "DELETE", "/v1/schema/Doc")
    assert st == 200
    st, _ = _req(srv.port, "GET", f"/v1/objects/Doc/{objs[3]['id']}")
    assert st in (404, 422)

    # restore
    st, out = _req(srv.port, "POST", "/v1/backups/filesystem/snap1/restore", {})
    assert st == 200
    final = app.backup_scheduler.wait("snap1", restore=True)
    assert final["status"] == "SUCCESS", final
    st, out = _req(srv.port, "GET", "/v1/backups/filesystem/snap1/restore")
    assert st == 200 and out["status"] == "SUCCESS"

    # data is back, including vectors (search works)
    st, got = _req(srv.port, "GET", f"/v1/objects/Doc/{objs[3]['id']}")
    assert st == 200 and got["properties"]["n"] == 3
    q = json.dumps(objs[7]["vector"])
    st, res = _req(srv.port, "POST", "/v1/graphql", {"query":
        '{ Get { Doc(nearVector: {vector: %s}, limit: 1) { n _additional { id } } } }' % q})
    assert res["data"]["Get"]["Doc"][0]["_additional"]["id"] == objs[7]["id"]


def test_backup_errors(backed_app):
    app, srv = backed_app
    _import_docs(srv.port)
    # unknown backend
    st, out = _req(srv.port, "POST", "/v1/backups/s3", {"id": "x"})
    assert st == 422
    # duplicate id
    _req(srv.port, "POST", "/v1/backups/filesystem", {"id": "dup"})
    app.backup_scheduler.wait("dup")
    st, out = _req(srv.port, "POST", "/v1/backups/filesystem", {"id": "dup"})
    assert st == 422
    # restore while class exists
    st, out = _req(srv.port, "POST", "/v1/backups/filesystem/dup/restore", {})
    assert st == 422 and "already exists" in json.dumps(out)
    # unknown include class
    st, out = _req(srv.port, "POST", "/v1/backups/filesystem",
                   {"id": "y", "include": ["Nope"]})
    assert st == 422
    # unknown backup id status
    st, out = _req(srv.port, "GET", "/v1/backups/filesystem/ghost")
    assert st == 422


def test_backup_include_exclude(tmp_path):
    provider = Provider()
    provider.register(FilesystemBackupBackend(str(tmp_path / "b")))
    app = App(config=Config(), data_path=str(tmp_path / "d"), modules=provider)
    try:
        for cls in ("A", "B"):
            app.schema.add_class({
                "class": cls, "vectorIndexType": "hnsw_tpu",
                "properties": [{"name": "t", "dataType": ["text"]}]})
        sched = app.backup_scheduler
        sched.backup("filesystem", {"id": "only-a", "include": ["A"]})
        meta = sched.wait("only-a")
        assert meta["classes"] == ["A"]
        sched.backup("filesystem", {"id": "not-a", "exclude": ["A"]})
        assert sched.wait("not-a")["classes"] == ["B"]
        with pytest.raises(BackupError):
            sched.backup("filesystem", {"id": "z", "include": ["A"], "exclude": ["B"]})
    finally:
        app.shutdown()


def test_multinode_backup_restore(tmp_path):
    """Distributed journey: 2 nodes, shards on both; the coordinator backs
    up every node's shards; restore brings data back on both nodes."""
    from tests.test_cluster import make_class, make_cluster, new_obj, teardown_cluster

    nodes = make_cluster(tmp_path, 2)
    try:
        shared_root = str(tmp_path / "shared-backups")
        for n in nodes:
            p = Provider()
            p.register(FilesystemBackupBackend(shared_root))
            sched = BackupScheduler(
                n.db, n.schema, p, node_name=n.node_name,
                cluster=n.cluster, node_client=n.transfer_client,
            )
            n.api.backup = sched

        n0, n1 = nodes
        n0.schema.add_class(make_class(shards=2, replicas=1))
        idx0 = n0.db.get_index("Dist")
        objs = [new_obj(i) for i in range(30)]
        assert all(e is None for e in idx0.put_batch(objs))
        per_node_before = [
            sum(s.object_count() for s in n.db.get_index("Dist").shards.values())
            for n in nodes
        ]
        assert sum(per_node_before) == 30 and all(c > 0 for c in per_node_before)

        sched0 = n0.api.backup
        sched0.backup("filesystem", {"id": "dist1"})
        assert sched0.wait("dist1")["status"] == "SUCCESS"

        n0.schema.delete_class("Dist")
        for n in nodes:
            assert n.db.get_index("Dist") is None

        sched0.restore("filesystem", "dist1", {})
        assert sched0.wait("dist1", restore=True)["status"] == "SUCCESS"

        for n, want in zip(nodes, per_node_before):
            idx = n.db.get_index("Dist")
            assert idx is not None
            got = sum(s.object_count() for s in idx.shards.values())
            assert got == want
        res = n1.db.get_index("Dist").object_vector_search(objs[5].vector, k=3)
        assert res[0][0].obj.uuid == objs[5].uuid
    finally:
        teardown_cluster(nodes)
