"""Mid-log corruption tolerance: flipped bytes anywhere in a WAL or vector
log must cost at most the damaged record(s), never the rest of the file.

Reference parity: the HNSW commit-log fixer replays AROUND corrupt regions
(adapters/repos/db/vector/hnsw/corrupt_commit_logs_fixer.go:1) instead of
abandoning everything after the first bad byte. Round 4 handled torn TAILS;
these tests drive the round-5 skip-ahead machinery: v2 records carry
checksums (additive sum32 in the vector log, crc32 in the WAL), replay
resyncs at the next record that parses AND checksums, and the skipped span
is reported via stats — bounded, *reported* loss.

The 1000-case loops are seeded numpy (not hypothesis) so each case is one
cheap flip+replay; hypothesis covers structural variety separately.
"""

import os
import struct

import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="optional dep not in this image")
from hypothesis import given, settings, strategies as st

from weaviate_tpu.index.tpu import VectorLog, _LOG_ADD, _LOG_DELETE
from weaviate_tpu.storage.lsm import (
    STRATEGY_REPLACE,
    Bucket,
    _WAL_MAGIC2,
)


# ---------------------------------------------------------------- vector log


def _build_log(path, records):
    """records: list of ('add', doc_id, vec) / ('delete', doc_id, None).
    Returns [(kind, doc_id, payload, start, end)] byte extents per record."""
    log = VectorLog(path)
    extents = []
    off = 6
    for kind, doc_id, vec in records:
        if kind == "add":
            log.append_add(doc_id, vec)
            end = off + 17 + 4 * len(vec)
        else:
            log.append_delete(doc_id)
            end = off + 13
        extents.append((kind, doc_id, vec, off, end))
        off = end
    log.close()
    return extents


def _replay_all(path, stats=None):
    return list(VectorLog.replay(path, stats=stats))


def _mk_records(rng, n, dims=(8, 8, 8)):
    recs = []
    for i in range(n):
        if rng.random() < 0.2 and i > 0:
            recs.append(("delete", int(rng.integers(0, i)), None))
        else:
            d = int(rng.choice(dims))
            recs.append(
                ("add", i, rng.standard_normal(d).astype(np.float32)))
    return recs


def test_veclog_clean_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    recs = _mk_records(rng, 40)
    path = str(tmp_path / "v.log")
    _build_log(path, recs)
    stats = {}
    got = _replay_all(path, stats)
    assert len(got) == len(recs)
    assert stats == {}
    for (k0, d0, v0), (k1, d1, v1) in zip(recs, got):
        assert (k0, d0) == (k1, d1)
        if k0 == "add":
            np.testing.assert_array_equal(v0, v1)


def test_veclog_single_flip_loses_at_most_one_record(tmp_path):
    """1000 seeded cases: one flipped byte anywhere past the header loses
    at most the record containing it; every other record replays intact,
    and the loss is reported in stats."""
    rng = np.random.default_rng(7)
    recs = _mk_records(rng, 30)
    path = str(tmp_path / "v.log")
    extents = _build_log(path, recs)
    with open(path, "rb") as f:
        orig = bytearray(f.read())
    size = len(orig)
    flip_path = str(tmp_path / "flip.log")
    for case in range(1000):
        pos = int(rng.integers(6, size))
        data = bytearray(orig)
        data[pos] ^= 1 << int(rng.integers(0, 8))
        with open(flip_path, "wb") as f:
            f.write(bytes(data))
        stats = {}
        got = _replay_all(flip_path, stats)
        got_kd = [(k, d) for (k, d, v) in got]
        expected = [(k, d) for (k, d, v, s, e) in extents
                    if not s <= pos < e]
        lost_any = len(got_kd) < len(extents)
        assert got_kd == expected, (
            f"case {case}: flip at {pos} -> replay diverged beyond the "
            f"damaged record")
        if lost_any:
            assert stats.get("skipped_bytes", 0) > 0, (
                f"case {case}: loss at {pos} was not reported")


def test_veclog_batched_equals_scalar_under_corruption(tmp_path):
    rng = np.random.default_rng(3)
    recs = _mk_records(rng, 50, dims=(16,))
    path = str(tmp_path / "v.log")
    _build_log(path, recs)
    with open(path, "rb") as f:
        orig = bytearray(f.read())
    for case in range(200):
        pos = int(rng.integers(6, len(orig)))
        data = bytearray(orig)
        data[pos] ^= 0x40
        with open(path, "wb") as f:
            f.write(bytes(data))
        scalar = list(VectorLog.replay(path))
        flat = []
        for op, ids, vecs in VectorLog.replay_batches(path):
            if op == "add":
                for i in range(len(ids)):
                    flat.append(("add", int(ids[i]), vecs[i]))
            else:
                flat.append(("delete", int(ids), None))
        assert len(scalar) == len(flat)
        for (k0, d0, v0), (k1, d1, v1) in zip(scalar, flat):
            assert (k0, d0) == (k1, d1)
            if k0 == "add":
                np.testing.assert_array_equal(v0, v1)


def test_veclog_multi_region_corruption(tmp_path):
    """Several flipped bytes in distinct records: each damaged record is
    lost independently; regions are reported."""
    rng = np.random.default_rng(11)
    recs = [("add", i, rng.standard_normal(12).astype(np.float32))
            for i in range(40)]
    path = str(tmp_path / "v.log")
    extents = _build_log(path, recs)
    data = bytearray(open(path, "rb").read())
    # damage records 5, 17, 33 (payload bytes)
    hit = []
    for ri in (5, 17, 33):
        _, doc, _, s, e = extents[ri]
        data[s + 20] ^= 0xFF
        hit.append(doc)
    with open(path, "wb") as f:
        f.write(bytes(data))
    stats = {}
    got = _replay_all(path, stats)
    got_ids = [d for _, d, _ in got]
    assert got_ids == [i for i in range(40) if i not in hit]
    assert stats["skipped_regions"] == 3


def test_veclog_reopen_preserves_tail_after_midfile_damage(tmp_path):
    """Opening a log with mid-file damage must NOT truncate the recoverable
    tail (round-4 behavior cut at the first bad record; v2 keeps the rest)."""
    rng = np.random.default_rng(5)
    recs = [("add", i, rng.standard_normal(8).astype(np.float32))
            for i in range(30)]
    path = str(tmp_path / "v.log")
    extents = _build_log(path, recs)
    data = bytearray(open(path, "rb").read())
    _, _, _, s, _ = extents[4]
    data[s + 9] ^= 0x10  # dim field of record 4: header walk stops here
    with open(path, "wb") as f:
        f.write(bytes(data))
    size_before = len(data)
    log = VectorLog(path)  # reopen: truncation decision happens here
    log.append_add(999, rng.standard_normal(8).astype(np.float32))
    log.close()
    assert os.path.getsize(path) > size_before - 64, "tail was truncated away"
    got_ids = [d for _, d, _ in _replay_all(path)]
    assert got_ids == [i for i in range(30) if i != 4] + [999]


def test_veclog_v1_upgrade_then_append(tmp_path):
    """Opening a v1 log upgrades it in place to v2, so appends (always v2
    records) never land in a v1 file — the mixed-format file would replay
    appended vectors misaligned by the checksum field (confirmed repro:
    [100,101,102,103] came back [1.5e-42, 100, 101, 102])."""
    path = str(tmp_path / "up.log")
    buf = b"WTVL" + struct.pack("<H", 1)
    for i in range(3):
        v = np.arange(4, dtype=np.float32) + 10 * i
        buf += struct.pack("<BQI", _LOG_ADD, i, 4) + v.tobytes()
    with open(path, "wb") as f:
        f.write(buf)
    log = VectorLog(path)
    appended = np.array([100.0, 101.0, 102.0, 103.0], dtype=np.float32)
    log.append_add(7, appended)
    log.close()
    assert VectorLog._version(path) == 2
    got = _replay_all(path)
    assert [(k, d) for k, d, _ in got] == [
        ("add", 0), ("add", 1), ("add", 2), ("add", 7)]
    np.testing.assert_array_equal(got[3][2], appended)
    for i in range(3):
        np.testing.assert_array_equal(
            got[i][2], np.arange(4, dtype=np.float32) + 10 * i)


def test_veclog_v1_still_replays(tmp_path):
    """Back-compat: a v1 log (no checksums) replays with the old
    stop-at-first-bad behavior."""
    path = str(tmp_path / "v1.log")
    buf = b"WTVL" + struct.pack("<H", 1)
    vecs = []
    for i in range(5):
        v = np.arange(4, dtype=np.float32) + i
        vecs.append(v)
        buf += struct.pack("<BQI", _LOG_ADD, i, 4) + v.tobytes()
    buf += struct.pack("<BQ", _LOG_DELETE, 2)
    with open(path, "wb") as f:
        f.write(buf)
    got = _replay_all(path)
    assert [(k, d) for k, d, _ in got] == [
        ("add", 0), ("add", 1), ("add", 2), ("add", 3), ("add", 4),
        ("delete", 2)]
    for i in range(5):
        np.testing.assert_array_equal(got[i][2], vecs[i])


@settings(max_examples=60, deadline=None)
@given(st.data())
def test_veclog_corruption_property(tmp_path_factory, data):
    """Structural variety: arbitrary add/delete interleavings + dims,
    arbitrary flip position — invariant: surviving records are exactly the
    undamaged ones, in order."""
    tmp = tmp_path_factory.mktemp("fuzz")
    rng = np.random.default_rng(data.draw(st.integers(0, 2**32 - 1)))
    n = data.draw(st.integers(2, 25))
    recs = _mk_records(rng, n, dims=(4, 8, 32))
    path = str(tmp / "v.log")
    extents = _build_log(path, recs)
    raw = bytearray(open(path, "rb").read())
    pos = data.draw(st.integers(6, len(raw) - 1))
    raw[pos] ^= 1 << data.draw(st.integers(0, 7))
    with open(path, "wb") as f:
        f.write(bytes(raw))
    got = [(k, d) for k, d, _ in _replay_all(path)]
    expected = [(k, d) for (k, d, v, s, e) in extents if not s <= pos < e]
    assert got == expected


# ----------------------------------------------------------------------- WAL


def _wal_extents(path):
    """Parse the v2 WAL framing -> [(start, end)] per record (header at 4)."""
    data = open(path, "rb").read()
    assert data[:4] == _WAL_MAGIC2
    out = []
    off = 4
    while off < len(data):
        (ln,) = struct.unpack_from("<I", data, off)
        out.append((off, off + 8 + ln))
        off += 8 + ln
    return out


def test_wal_single_flip_loses_at_most_one_record(tmp_path):
    """1000 seeded cases over a replace-bucket WAL: one flipped byte loses
    at most the put/delete it lands in; the bucket reports the skip."""
    rng = np.random.default_rng(13)
    src = str(tmp_path / "src")
    b = Bucket(src, STRATEGY_REPLACE)
    keys = [f"k{i:03d}".encode() for i in range(50)]
    for i, k in enumerate(keys):
        b.put(k, f"v{i}".encode() * 3)
    b.flush()
    wal = os.path.join(src, "bucket.wal")
    extents = _wal_extents(wal)
    assert len(extents) == 50
    orig = bytearray(open(wal, "rb").read())
    size = len(orig)
    for case in range(1000):
        pos = int(rng.integers(4, size))
        data = bytearray(orig)
        data[pos] ^= 1 << int(rng.integers(0, 8))
        dst = str(tmp_path / f"c{case % 4}")
        os.makedirs(dst, exist_ok=True)
        with open(os.path.join(dst, "bucket.wal"), "wb") as f:
            f.write(bytes(data))
        b2 = Bucket(dst, STRATEGY_REPLACE)
        damaged = [i for i, (s, e) in enumerate(extents) if s <= pos < e]
        missing = [i for i, k in enumerate(keys)
                   if b2.get(k) != f"v{i}".encode() * 3]
        assert set(missing) <= set(damaged), (
            f"case {case}: flip at {pos} lost undamaged keys {missing} "
            f"(damaged={damaged})")
        if missing:
            st = b2.wal_replay_stats
            assert st.get("skipped_bytes", 0) + st.get("torn_tail_bytes", 0) > 0


def test_wal_multi_region_and_reporting(tmp_path):
    src = str(tmp_path / "b")
    b = Bucket(src, STRATEGY_REPLACE)
    for i in range(30):
        b.put(f"key{i:02d}".encode(), f"value{i}".encode())
    b.flush()
    wal = os.path.join(src, "bucket.wal")
    extents = _wal_extents(wal)
    data = bytearray(open(wal, "rb").read())
    for ri in (3, 15, 27):
        s, e = extents[ri]
        data[s + 10] ^= 0xFF
    with open(wal, "wb") as f:
        f.write(bytes(data))
    b2 = Bucket(src, STRATEGY_REPLACE)
    for i in range(30):
        want = None if i in (3, 15, 27) else f"value{i}".encode()
        assert b2.get(f"key{i:02d}".encode()) == want
    assert b2.wal_replay_stats["skipped_regions"] == 3


def test_wal_heals_after_corruption(tmp_path):
    """The first reopen after damage reports the skip and HEALS the file;
    a second reopen must see a clean WAL (no re-scan, no re-warn) and
    appends after healing must survive another restart."""
    src = str(tmp_path / "b")
    b = Bucket(src, STRATEGY_REPLACE)
    for i in range(20):
        b.put(f"k{i:02d}".encode(), f"v{i}".encode())
    b.flush()
    wal = os.path.join(src, "bucket.wal")
    extents = _wal_extents(wal)
    data = bytearray(open(wal, "rb").read())
    s, _ = extents[7]
    data[s + 12] ^= 0xFF
    with open(wal, "wb") as f:
        f.write(bytes(data))
    b2 = Bucket(src, STRATEGY_REPLACE)
    assert b2.wal_replay_stats.get("skipped_regions") == 1
    b2.put(b"after-heal", b"yes")
    b2.flush()
    b3 = Bucket(src, STRATEGY_REPLACE)
    assert b3.wal_replay_stats == {}, b3.wal_replay_stats  # healed: clean
    assert b3.get(b"after-heal") == b"yes"
    for i in range(20):
        want = None if i == 7 else f"v{i}".encode()
        assert b3.get(f"k{i:02d}".encode()) == want


def test_wal_torn_tail_not_reported_as_corruption(tmp_path):
    """A crash-torn tail (truncated final record) is healed silently:
    counted as torn_tail_bytes, never warned as corruption."""
    src = str(tmp_path / "b")
    b = Bucket(src, STRATEGY_REPLACE)
    for i in range(10):
        b.put(f"k{i}".encode(), f"v{i}".encode())
    b.flush()
    wal = os.path.join(src, "bucket.wal")
    data = open(wal, "rb").read()
    with open(wal, "wb") as f:
        f.write(data[:-5])  # tear the last record
    b2 = Bucket(src, STRATEGY_REPLACE)
    st = b2.wal_replay_stats
    assert st.get("skipped_bytes", 0) == 0 and st.get("skipped_regions", 0) == 0
    assert st.get("torn_tail_bytes", 0) > 0
    assert b2.get(b"k9") is None and b2.get(b"k8") == b"v8"


def test_wal_v1_file_still_replays_and_appends(tmp_path):
    """A WAL written in the v1 format replays, and appends to it stay v1
    (no mixed-format file) until a flush rotates to v2."""
    src = str(tmp_path / "b")
    os.makedirs(src)
    # hand-craft a v1 WAL: magic + one put record (op, nparts, frames)
    rec = bytes([1, 2]) + struct.pack("<I", 1) + b"a" + struct.pack("<I", 2) + b"v1"
    with open(os.path.join(src, "bucket.wal"), "wb") as f:
        f.write(b"WTWL" + rec)
    b = Bucket(src, STRATEGY_REPLACE)
    assert b.get(b"a") == b"v1"
    b.put(b"b", b"v2")
    b.flush()
    b2 = Bucket(src, STRATEGY_REPLACE)
    assert b2.get(b"a") == b"v1"
    assert b2.get(b"b") == b"v2"
    b2.flush_memtable()
    with open(os.path.join(src, "bucket.wal"), "rb") as f:
        assert f.read(4) == _WAL_MAGIC2  # rotated to v2


@settings(max_examples=40, deadline=None)
@given(st.data())
def test_wal_corruption_property(tmp_path_factory, data):
    tmp = tmp_path_factory.mktemp("walfuzz")
    src = str(tmp / "b")
    b = Bucket(src, STRATEGY_REPLACE)
    n = data.draw(st.integers(2, 20))
    for i in range(n):
        # b"x" prefix: arbitrary values must not collide with the reserved
        # in-band tombstone sentinel (put refuses it loudly)
        b.put(f"k{i}".encode(), b"x" + data.draw(st.binary(max_size=40)))
    b.flush()
    wal = os.path.join(src, "bucket.wal")
    extents = _wal_extents(wal)
    raw = bytearray(open(wal, "rb").read())
    pos = data.draw(st.integers(4, len(raw) - 1))
    raw[pos] ^= 1 << data.draw(st.integers(0, 7))
    with open(wal, "wb") as f:
        f.write(bytes(raw))
    b2 = Bucket(src, STRATEGY_REPLACE)
    damaged = {i for i, (s, e) in enumerate(extents) if s <= pos < e}
    for i in range(n):
        if i not in damaged:
            assert b2.get(f"k{i}".encode()) is not None


def test_wal_oversized_roaring_record_is_chunked(tmp_path):
    """A roaring bulk add larger than one WAL record's id budget must split
    into multiple records (each under the replay resync bound) and replay
    losslessly — the write path may never produce a record replay would
    reject as corrupt."""
    import numpy as np

    from weaviate_tpu.storage.lsm import STRATEGY_ROARINGSET, _WAL_MAX_REC

    src = str(tmp_path / "rs")
    b = Bucket(src, STRATEGY_ROARINGSET)
    n = Bucket._RS_IDS_PER_REC + 1234  # one full record + a remainder
    ids = np.arange(n, dtype=np.uint64)
    b.roaring_add_many(b"tok", ids)
    b.flush()
    wal = os.path.join(src, "bucket.wal")
    for s, e in _wal_extents(wal):
        assert e - s - 8 <= _WAL_MAX_REC
    b2 = Bucket(src, STRATEGY_ROARINGSET)
    got = b2.roaring_get(b"tok")
    assert len(got) == n
