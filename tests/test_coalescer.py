"""Cross-request query coalescer (serving/coalescer.py) + its wiring.

The fixtures use SMALL-INTEGER-valued vectors on purpose: every distance is
then exact integer arithmetic in float32 regardless of accumulation order,
so a query's results are bit-identical whether it rides a 1-wide direct
dispatch or a coalesced [B, D] batch — which is exactly the contract these
tests pin (coalesced == uncoalesced, not merely close).
"""

import threading
import time
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.serving.coalescer import (
    CoalescerShutdownError,
    QueryCoalescer,
)
from weaviate_tpu.usecases.traverser import GetParams

N, DIM, K = 400, 16, 5


def _mk_app(tmp_path, enabled=True, window_ms=200.0, max_batch=256,
            max_request_rows=16, vecs=None):
    from weaviate_tpu.server import App

    cfg = Config()
    cfg.coalescer.enabled = enabled
    cfg.coalescer.window_ms = window_ms
    cfg.coalescer.max_batch = max_batch
    cfg.coalescer.max_request_rows = max_request_rows
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    app.schema.add_class({
        "class": "Co", "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "tag", "dataType": ["text"]}],
    })
    if vecs is None:
        rng = np.random.default_rng(11)
        vecs = rng.integers(-8, 8, (N, DIM)).astype(np.float32)
    idx = app.db.get_index("Co")
    idx.put_batch([
        StorObj(class_name="Co", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"tag": "even" if i % 2 == 0 else "odd"},
                vector=vecs[i])
        for i in range(N)])
    return app, idx, vecs


def _tie_free_queries(vecs, count, mask=None, depth=None):
    """Queries whose top-(K+8) exact distances (over `mask`ed docs) are all
    distinct. Integer-valued vectors make every distance exact in f32, but
    a TIE straddling the top-k boundary is resolved by selection order —
    which legitimately differs between a 1-wide and a coalesced dispatch —
    so the bit-identical comparison only stands on tie-free queries."""
    pool = vecs if mask is None else vecs[mask]
    depth = depth or K + 8
    out = []
    i = 0
    while len(out) < count:
        q = vecs[i] + 0.5
        i += 1
        d = np.sort(((pool - q) ** 2).sum(1))[:depth]
        if len(np.unique(d)) == len(d):
            out.append(q)
    return out


def _line_vecs():
    """Docs on an integer line: every pairwise distance to a x.25 query is
    unique AND exact in f32 — for the tests that need full-depth tie-free
    orderings (target-distance widening)."""
    v = np.zeros((N, DIM), np.float32)
    v[:, 0] = np.arange(N, dtype=np.float32)
    return v


def _rows(results):
    return [(r.obj.uuid, r.distance) for r in results]


def test_threaded_single_queries_bit_identical(tmp_path):
    """N concurrent single-query Gets through the serving path coalesce into
    shared dispatches AND return exactly what the direct path returns."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        queries = _tie_free_queries(vecs, 12)
        expected = [
            _rows(idx.object_vector_search(q, K)[0]) for q in queries]
        got = [None] * len(queries)
        barrier = threading.Barrier(len(queries))

        def run(i):
            barrier.wait()
            got[i] = _rows(app.traverser.get_class(GetParams(
                class_name="Co", near_vector={"vector": queries[i].tolist()},
                limit=K)))

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert got == expected
        st = app.coalescer.stats()
        assert st["requests"] == len(queries)
        # barrier-released threads land within one 200 ms window: the lane
        # must actually merge them (strictly fewer dispatches than requests)
        assert 1 <= st["dispatches"] < len(queries)
    finally:
        app.shutdown()


def test_deadline_flush_fires_under_low_load(tmp_path):
    """A lone request must not wait for a full bucket: the deadline window
    flushes a 1-deep lane."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=50.0)
    try:
        q = _tie_free_queries(vecs, 1)[0]
        t0 = time.monotonic()
        res = app.traverser.get_class(GetParams(
            class_name="Co", near_vector={"vector": q.tolist()}, limit=K))
        elapsed = time.monotonic() - t0
        assert _rows(res) == _rows(idx.object_vector_search(q, K)[0])
        st = app.coalescer.stats()
        assert st == {**st, "dispatches": 1, "requests": 1, "rows": 1}
        assert elapsed < 10.0  # deadline flush, not a hang
    finally:
        app.shutdown()


def test_full_bucket_flush_fires_under_high_load(tmp_path):
    """When a lane's rows fill the batch bucket it flushes IMMEDIATELY —
    long before a (deliberately huge) deadline window."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=30_000.0, max_batch=4,
                             max_request_rows=4)
    try:
        queries = _tie_free_queries(vecs, 4)
        expected = [_rows(idx.object_vector_search(q, K)[0]) for q in queries]
        got = [None] * 4
        barrier = threading.Barrier(4)

        def run(i):
            barrier.wait()
            got[i] = _rows(app.traverser.get_class(GetParams(
                class_name="Co", near_vector={"vector": queries[i].tolist()},
                limit=K)))

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        elapsed = time.monotonic() - t0
        assert got == expected
        assert elapsed < 20.0  # nowhere near the 30 s window
        st = app.coalescer.stats()
        assert st["dispatches"] == 1
        assert st["requests"] == 4
    finally:
        app.shutdown()


def test_oversize_request_bypasses_queue(tmp_path):
    """A request wider than max_request_rows takes the direct path (counted
    with reason=oversize) and still returns correct results."""
    app, idx, vecs = _mk_app(tmp_path, max_batch=8, max_request_rows=2)
    try:
        params = [GetParams(class_name="Co",
                            near_vector={"vector": q.tolist()},
                            limit=K)
                  for q in _tie_free_queries(vecs, 6)]
        res = app.traverser.get_class_batched(params)
        assert not any(isinstance(r, Exception) for r in res)
        for p, r in zip(params, res):
            direct = idx.object_vector_search(
                np.asarray(p.near_vector["vector"], np.float32), K)[0]
            assert _rows(r) == _rows(direct)
        st = app.coalescer.stats()
        assert st["bypass"].get("oversize", 0) >= 1
        assert st["dispatches"] == 0  # the whole group went direct
    finally:
        app.shutdown()


def test_unique_allowlist_filter_bypasses(tmp_path):
    """A filter with no stable signature (per-request allowList) can never
    share a lane: submit refuses it and counts the reason."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        shard = idx.single_local_shard()
        flt = LocalFilter.from_dict(
            {"operator": "Equal", "path": ["tag"], "valueText": "even"})
        flt.to_dict = lambda: (_ for _ in ()).throw(TypeError("no sig"))
        assert app.coalescer.submit(shard, vecs[0], K, flt=flt) is None
        assert app.coalescer.stats()["bypass"].get("unique_allow_list") == 1
    finally:
        app.shutdown()


class _SpyLock:
    """Counts every acquisition of the wrapped index lock while delegating,
    so a test can pin that a code path is genuinely lock-free."""

    def __init__(self, inner):
        self.inner = inner
        self.count = 0

    def acquire(self, *a, **kw):
        self.count += 1
        return self.inner.acquire(*a, **kw)

    def release(self):
        return self.inner.release()

    def __enter__(self):
        self.count += 1
        return self.inner.__enter__()

    def __exit__(self, *exc):
        return self.inner.__exit__(*exc)


def test_filtered_coalesced_dispatch_never_takes_index_lock(tmp_path):
    """The acceptance spy for the snapshot read plane: a FILTERED coalesced
    dispatch rides the async two-phase path end to end — enqueue, device
    work, finalize, hydration — without a single acquisition of the
    per-index lock (pre-PR, filtered lanes fell back to the sync path that
    held it across the whole dispatch)."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        shard = idx.single_local_shard()
        vidx = shard.vector_index

        def mk_flt():
            return LocalFilter.from_dict(
                {"operator": "Equal", "path": ["tag"], "valueText": "even"})

        queries = _tie_free_queries(vecs, 6, mask=np.arange(N) % 2 == 0)
        expected = [
            _rows(idx.object_vector_search(q, K, flt=mk_flt())[0])
            for q in queries]
        # warm: publishes the snapshot, seeds the filter-signature recency
        # (a cold signature would bypass) and the allowList cache
        app.traverser.get_class(GetParams(
            class_name="Co", near_vector={"vector": queries[0].tolist()},
            filters=mk_flt(), limit=K))
        base = app.coalescer.stats()
        spy = _SpyLock(vidx._lock)
        vidx._lock = spy
        try:
            got = [None] * len(queries)
            barrier = threading.Barrier(len(queries))

            def run(i):
                barrier.wait()
                got[i] = _rows(app.traverser.get_class(GetParams(
                    class_name="Co",
                    near_vector={"vector": queries[i].tolist()},
                    filters=mk_flt(), limit=K)))

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(len(queries))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
        finally:
            vidx._lock = spy.inner
        assert got == expected  # bit-identical through the lock-free path
        st = app.coalescer.stats()
        # the filtered requests went THROUGH the queue (no sync fallback,
        # no new bypasses) and actually merged into coalesced dispatches
        assert st["requests"] - base["requests"] == len(queries)
        assert st["bypass"] == base["bypass"]
        assert st["dispatches"] - base["dispatches"] < len(queries)
        assert spy.count == 0, (
            f"filtered coalesced dispatch acquired the index lock "
            f"{spy.count} time(s) — the snapshot read plane must be "
            "lock-free")
    finally:
        app.shutdown()


def test_shared_filter_lane_coalesces_and_matches_direct(tmp_path):
    """Filtered queries with the SAME filter signature share a lane once the
    signature is warm (a COLD first sighting goes direct — a one-off filter
    must not pay the window for a singleton lane); results equal the direct
    filtered path exactly."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        def mk_flt():
            # fresh object per request, same content — the serving shape
            return LocalFilter.from_dict(
                {"operator": "Equal", "path": ["tag"], "valueText": "even"})

        queries = _tie_free_queries(vecs, 8, mask=np.arange(N) % 2 == 0)
        expected = [
            _rows(idx.object_vector_search(q, K, flt=mk_flt())[0])
            for q in queries]

        # first sighting is cold: bypasses with zero queue hops
        warm = app.traverser.get_class(GetParams(
            class_name="Co", near_vector={"vector": queries[0].tolist()},
            filters=mk_flt(), limit=K))
        assert _rows(warm) == expected[0]
        assert app.coalescer.stats()["bypass"].get("cold_filter") == 1
        assert app.coalescer.stats()["requests"] == 0
        got = [None] * len(queries)
        barrier = threading.Barrier(len(queries))

        def run(i):
            barrier.wait()
            got[i] = _rows(app.traverser.get_class(GetParams(
                class_name="Co", near_vector={"vector": queries[i].tolist()},
                filters=mk_flt(), limit=K)))

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert got == expected
        for rows in got:  # the filter actually applied
            for u, _ in rows:
                assert (uuidlib.UUID(u).int - 1) % 2 == 0
        st = app.coalescer.stats()
        assert st["requests"] == len(queries)
        assert st["dispatches"] < len(queries)
    finally:
        app.shutdown()


def test_overflow_request_flushes_standing_lane_first(tmp_path):
    """A request that would push a lane past max_batch flushes the standing
    lane and starts fresh — no dispatch may exceed its padding bucket (that
    would compile a shape the direct path never uses)."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        shard = idx.single_local_shard()
        co = QueryCoalescer(window_s=30.0, max_batch=4, max_request_rows=4)
        try:
            w1 = co.submit(shard, vecs[:3], K)   # 3-row lane, queued
            # +4 would overflow the 4-row bucket: the standing 3-row lane
            # must flush AS-IS and this request fill a fresh lane (which is
            # itself full at 4 rows, so both dispatch despite the 30 s
            # window never expiring)
            w2 = co.submit(shard, vecs[3:7], K)
            r1, r2 = w1(), w2()
            assert len(r1) == 3 and len(r2) == 4
            st = co.stats()
            assert st["dispatches"] == 2
            assert st["rows"] == 7
            assert st["mean_rows_per_dispatch"] <= 4  # bucket never exceeded
        finally:
            co.shutdown()
    finally:
        app.shutdown()


def test_wrong_dim_request_fails_alone(tmp_path):
    """Dim is part of the lane key: a malformed-dimension request gets its
    own lane and fails by itself instead of poisoning the concatenated
    batch of its would-be lane-mates."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        shard = idx.single_local_shard()
        co = QueryCoalescer(window_s=0.05, max_batch=64, max_request_rows=4)
        try:
            good = [co.submit(shard, vecs[i], K) for i in range(3)]
            bad = co.submit(shard, np.zeros(DIM * 2, np.float32), K)
            for w in good:
                assert len(w()) == 1 and len(w()[0]) == K
            with pytest.raises(Exception):
                bad()
        finally:
            co.shutdown()
    finally:
        app.shutdown()


def test_dispatch_exception_wakes_every_waiter(tmp_path):
    """An injected dispatch failure must propagate to EVERY queued waiter —
    no request may hang on a dead batch."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        shard = idx.single_local_shard()
        co = QueryCoalescer(window_s=0.05, max_batch=64, max_request_rows=4)
        try:
            boom = RuntimeError("injected dispatch failure")

            def exploding(*a, **kw):
                raise boom

            shard.object_vector_search_async = exploding
            waiters = [co.submit(shard, vecs[i], K) for i in range(6)]
            assert all(w is not None for w in waiters)
            errs = []
            for w in waiters:
                with pytest.raises(RuntimeError) as ei:
                    w()
                errs.append(ei.value)
            assert all(e is boom for e in errs)
        finally:
            co.shutdown()
            del shard.object_vector_search_async  # restore the class method
    finally:
        app.shutdown()


def test_shutdown_wakes_queued_waiters(tmp_path):
    """Waiters queued behind a never-expiring window get a shutdown error
    instead of hanging."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        shard = idx.single_local_shard()
        co = QueryCoalescer(window_s=60.0, max_batch=64, max_request_rows=4)
        w = co.submit(shard, vecs[0], K)
        assert w is not None
        co.shutdown()
        with pytest.raises(CoalescerShutdownError):
            w()
        # post-shutdown admission refuses instead of queueing forever
        assert co.submit(shard, vecs[1], K) is None
        assert co.stats()["bypass"].get("shutdown") == 1
    finally:
        app.shutdown()


def test_disabled_by_config_is_true_noop(tmp_path):
    """enabled=False => no coalescer object anywhere on the read path (zero
    queue hops), results unchanged."""
    app, idx, vecs = _mk_app(tmp_path, enabled=False)
    try:
        assert app.coalescer is None
        assert app.explorer.coalescer is None
        assert app.explorer._coalesce_submit(idx, vecs[:1], K, None,
                                             False) is None
        q = vecs[3] + 0.5
        res = app.traverser.get_class(GetParams(
            class_name="Co", near_vector={"vector": q.tolist()}, limit=K))
        assert _rows(res) == _rows(idx.object_vector_search(q, K)[0])
    finally:
        app.shutdown()


def test_grpc_search_coalesces_across_requests(tmp_path):
    """End to end over real gRPC: concurrent single-query Searches coalesce
    and the replies equal the direct path byte for byte."""
    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

    app, idx, vecs = _mk_app(tmp_path)
    srv = GrpcServer(app, port=0, max_workers=16)
    srv.start()
    try:
        queries = _tie_free_queries(vecs, 8)
        expected = [_rows(idx.object_vector_search(q, K)[0]) for q in queries]
        got = [None] * len(queries)
        barrier = threading.Barrier(len(queries))

        def run(i):
            cl = SearchClient(f"127.0.0.1:{srv.port}")
            try:
                barrier.wait()
                rep = cl.search(pb.SearchRequest(
                    class_name="Co", limit=K,
                    near_vector=pb.NearVectorParams(
                        vector=queries[i].tolist())))
                got[i] = [(r.id, r.distance) for r in rep.results]
            finally:
                cl.close()

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert got == expected
        assert app.coalescer.stats()["requests"] >= len(queries)
    finally:
        srv.stop()
        app.shutdown()


def test_rest_graphql_batch_concurrent_slots(tmp_path):
    """h_graphql_batch runs slots concurrently when coalescing is on; the
    envelope and results match the serial (disabled) path."""
    import json
    import urllib.request

    from weaviate_tpu.server.rest import RestServer

    app, idx, vecs = _mk_app(tmp_path)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        rest_queries = _tie_free_queries(vecs, 4)
        gq = ("query($v: [Float]) { Get { Co(nearVector: {vector: $v}, "
              "limit: 5) { _additional { id distance } } } }")
        body = json.dumps([
            {"query": gq, "variables": {"v": q.tolist()}}
            for q in rest_queries
        ]).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/graphql/batch", data=body,
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert len(out) == 4
        for q, one in zip(rest_queries, out):
            assert "errors" not in one, one
            hits = one["data"]["Get"]["Co"]
            direct = idx.object_vector_search(q, K)[0]
            assert [h["_additional"]["id"] for h in hits] == \
                [r.obj.uuid for r in direct]
    finally:
        srv.stop()
        app.shutdown()


def test_metrics_registered_and_observed(tmp_path):
    """The coalescer metric families exist in the app registry and a
    coalesced dispatch lands in them (occupancy, wait, depth)."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=30.0)
    try:
        app.traverser.get_class(GetParams(
            class_name="Co", near_vector={"vector": vecs[0].tolist()},
            limit=K))
        # the waiter wakes at result SCATTER, a few statements before the
        # dispatch thread books the lane and observes these histograms —
        # wait for the observation to land instead of racing it
        deadline = time.monotonic() + 5.0
        text = app.metrics.expose().decode()
        while "weaviate_coalescer_batch_requests_count 1.0" not in text \
                and time.monotonic() < deadline:
            time.sleep(0.001)
            text = app.metrics.expose().decode()
        assert "weaviate_coalescer_batch_requests_count 1.0" in text
        assert "weaviate_coalescer_batch_rows_count 1.0" in text
        assert "weaviate_coalescer_wait_ms_count 1.0" in text
        assert "weaviate_coalescer_queue_depth 0.0" in text
    finally:
        app.shutdown()


def test_target_distance_branch_is_batched_and_identical(tmp_path):
    """Satellite: Shard.object_vector_search(target_distance=...) routes all
    rows through batched dispatches and matches the per-row
    search_by_vector_distance results exactly."""
    app, idx, vecs = _mk_app(tmp_path, enabled=False, vecs=_line_vecs())
    try:
        shard = idx.single_local_shard()
        q = np.zeros((6, DIM), np.float32)
        q[:, 0] = np.array([3.25, 100.25, 250.25, 399.25, 17.25, 0.25])
        target = 120.0 ** 2  # wide enough to force a widening round
        calls = {"n": 0}
        orig = shard.vector_index.search_by_vectors

        def counting(*a, **kw):
            calls["n"] += 1
            return orig(*a, **kw)

        shard.vector_index.search_by_vectors = counting
        try:
            out = shard.object_vector_search(
                q, 50, None, target_distance=target)
        finally:
            del shard.vector_index.search_by_vectors
        per_row = [shard.vector_index.search_by_vector_distance(
            row, target, 50) for row in q]
        assert calls["n"] < len(q)  # batched, not one chain per row
        for rows, (pids, pdists) in zip(out, per_row):
            assert [uuidlib.UUID(r.obj.uuid).int - 1 for r in rows] == \
                [int(i) for i in pids]
            assert [r.distance for r in rows] == pdists.tolist()
            assert all(r.distance <= target for r in rows)
    finally:
        app.shutdown()


def test_coalescer_config_env_parsing():
    from weaviate_tpu.config import ConfigError, load_config

    cfg = load_config({
        "QUERY_COALESCER_ENABLED": "true",
        "QUERY_COALESCER_WINDOW_MS": "3.5",
        "QUERY_COALESCER_MAX_BATCH": "64",
        "QUERY_COALESCER_MAX_REQUEST_ROWS": "8",
    })
    assert cfg.coalescer.enabled is True
    assert cfg.coalescer.window_ms == 3.5
    assert cfg.coalescer.max_batch == 64
    assert cfg.coalescer.max_request_rows == 8
    assert load_config({}).coalescer.enabled is False
    with pytest.raises(ConfigError):
        load_config({"QUERY_COALESCER_MAX_BATCH": "1"})
    with pytest.raises(ConfigError):
        load_config({"QUERY_COALESCER_WINDOW_MS": "-1"})
    with pytest.raises(ConfigError):
        load_config({"QUERY_COALESCER_MAX_REQUEST_ROWS": "500"})
