"""graftlint rule tests: good/bad fixture snippets per rule (>=2 each),
suppression hygiene, and baseline mechanics. Pure AST — no JAX device, no
weaviate_tpu import — so this runs in tier-1 anywhere."""

import os
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftlint import analyze_source, apply_baseline, build_baseline
from tools.graftlint.engine import Finding

HOT = "weaviate_tpu/ops/fake_kernel.py"       # inside the hot-module scope
COLD = "weaviate_tpu/usecases/fake_host.py"   # outside it


def codes(src, rel=HOT):
    return [f.code for f in analyze_source(src, rel)]


# -- JGL001: implicit device->host sync --------------------------------------

def test_jgl001_item_and_block_until_ready_fire_in_hot_module():
    src = (
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    y = jnp.sum(x)\n"
        "    y.block_until_ready()\n"
        "    return y.item()\n"
    )
    assert codes(src).count("JGL001") == 2


def test_jgl001_scalar_coercion_of_device_value():
    src = (
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    d = jnp.dot(x, x)\n"
        "    return float(d)\n"
    )
    assert "JGL001" in codes(src)


def test_jgl001_asarray_on_device_attr_and_jitted_result():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def _kern(x):\n"
        "    return x\n"
        "def f(self, q):\n"
        "    a = np.asarray(self._store)\n"
        "    b = np.asarray(_kern(q))\n"
        "    return a, b\n"
    )
    assert codes(src).count("JGL001") == 2


def test_jgl001_good_host_code_and_cold_modules():
    src = (
        "import numpy as np\n"
        "def f(rows):\n"
        "    v = np.asarray(rows, dtype=np.float32)\n"  # host staging: fine
        "    return float(v.sum())\n"                    # numpy, not device
    )
    assert codes(src) == []
    # the same device-syncing code outside a hot module is not JGL001's job
    bad = "def f(y):\n    return y.item()\n"
    assert codes(bad, COLD) == []


def test_jgl001_boundary_function_allowlisted():
    src = (
        "import numpy as np\n"
        "def unpack_topk(packed):\n"
        "    return np.asarray(packed)\n"
        "def elsewhere(packed):\n"
        "    return np.asarray(packed)\n"
    )
    # analyze as ops/topk.py: unpack_topk is on the boundary allowlist
    out = analyze_source(src, "weaviate_tpu/ops/topk.py")
    assert [f.symbol for f in out if f.code == "JGL001"] == []
    # note: neither fires here anyway (plain param), so force device flow
    src2 = (
        "import jax.numpy as jnp, numpy as np\n"
        "def unpack_topk(q):\n"
        "    return np.asarray(jnp.abs(q))\n"
        "def elsewhere(q):\n"
        "    return np.asarray(jnp.abs(q))\n"
    )
    out2 = analyze_source(src2, "weaviate_tpu/ops/topk.py")
    assert [f.symbol for f in out2 if f.code == "JGL001"] == ["elsewhere"]


# -- JGL002: jit-cache churn --------------------------------------------------

def test_jgl002_jit_inside_function_body():
    src = (
        "import jax\n"
        "def f(g, x):\n"
        "    return jax.jit(g)(x)\n"
    )
    assert "JGL002" in codes(src)


def test_jgl002_jit_lambda_and_unhashable_static():
    src = (
        "import jax\n"
        "h = jax.jit(lambda x: x + 1)\n"
        "k = jax.jit(abs, static_argnums=[0])\n"
    )
    assert codes(src).count("JGL002") == 2


def test_jgl002_good_module_level_jit():
    src = (
        "import functools, jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x\n"
        "@functools.partial(jax.jit, static_argnames=('k',))\n"
        "def g(x, k):\n"
        "    return x[:k]\n"
        "h = jax.jit(f)\n"
    )
    assert codes(src) == []


def test_jgl002_applies_outside_hot_modules_too():
    src = "import jax\ndef f(g):\n    return jax.jit(g)\n"
    assert "JGL002" in codes(src, COLD)


# -- JGL003: tracer leak ------------------------------------------------------

def test_jgl003_store_on_self_inside_jit():
    src = (
        "import jax\n"
        "class C:\n"
        "    @jax.jit\n"
        "    def f(self, x):\n"
        "        self.cache = x * 2\n"
        "        return x\n"
    )
    assert "JGL003" in codes(src, COLD)


def test_jgl003_global_assignment_inside_jit():
    src = (
        "import functools, jax\n"
        "_STATE = None\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def g(x, k):\n"
        "    global _STATE\n"
        "    _STATE = x\n"
        "    return x\n"
    )
    assert "JGL003" in codes(src, COLD)


def test_jgl003_good_unjitted_or_returning():
    src = (
        "import jax\n"
        "class C:\n"
        "    def setup(self, x):\n"
        "        self.cache = x\n"  # not jitted: fine
        "@jax.jit\n"
        "def g(x):\n"
        "    y = x * 2\n"           # local: fine
        "    return y\n"
    )
    assert codes(src, COLD) == []


# -- JGL004: silent fallback --------------------------------------------------

def test_jgl004_silent_broad_except_in_hot_module():
    src = (
        "def dispatch(q):\n"
        "    try:\n"
        "        return _dev(q)\n"
        "    except Exception:\n"
        "        return _host(q)\n"
    )
    assert "JGL004" in codes(src)


def test_jgl004_bare_except_also_fires():
    src = (
        "def dispatch(q):\n"
        "    try:\n"
        "        return _dev(q)\n"
        "    except:\n"
        "        return _host(q)\n"
    )
    assert "JGL004" in codes(src)


def test_jgl004_honest_handlers_pass():
    src = (
        "import logging\n"
        "def a(q):\n"
        "    try:\n"
        "        return _dev(q)\n"
        "    except Exception as e:\n"
        "        logging.getLogger(__name__).warning('fallback: %s', e)\n"
        "        return _host(q)\n"
        "def b(q):\n"
        "    try:\n"
        "        return _dev(q)\n"
        "    except Exception:\n"
        "        raise\n"
        "def c(q):\n"
        "    try:\n"
        "        return _dev(q)\n"
        "    except ValueError:\n"   # narrow except: allowed
        "        return _host(q)\n"
    )
    assert "JGL004" not in codes(src)


def test_jgl004_out_of_scope_modules_unflagged():
    src = (
        "def handler(req):\n"
        "    try:\n"
        "        return route(req)\n"
        "    except Exception:\n"
        "        return 500\n"
    )
    assert codes(src, "weaviate_tpu/server/fake_rest.py") == []


# -- JGL005: unlocked module-level mutation -----------------------------------

def test_jgl005_dict_registry_mutation_without_lock():
    src = (
        "_REG = {}\n"
        "def register(name, v):\n"
        "    _REG[name] = v\n"
    )
    assert "JGL005" in codes(src, COLD)


def test_jgl005_list_append_without_lock():
    src = (
        "_CALLBACKS = []\n"
        "def on_update(cb):\n"
        "    _CALLBACKS.append(cb)\n"
    )
    assert "JGL005" in codes(src, COLD)


def test_jgl005_locked_mutation_and_import_time_seed_pass():
    src = (
        "import threading\n"
        "_REG = {}\n"
        "_lock = threading.Lock()\n"
        "_REG['builtin'] = object()\n"   # import-time: serialized, fine
        "def register(name, v):\n"
        "    with _lock:\n"
        "        _REG[name] = v\n"
        "def drop(name):\n"
        "    with _lock:\n"
        "        _REG.pop(name, None)\n"
    )
    assert codes(src, COLD) == []


# -- JGL006: dtype drift ------------------------------------------------------

def test_jgl006_float64_attr_and_dtype_string():
    src = (
        "import numpy as np\n"
        "def f(x):\n"
        "    a = x.astype(np.float64)\n"
        "    b = np.zeros(4, dtype='float64')\n"
        "    return a, b\n"
    )
    assert codes(src).count("JGL006") == 2


def test_jgl006_scoped_to_hot_modules_and_f32_ok():
    src = (
        "import numpy as np\n"
        "def f(x):\n"
        "    return x.astype(np.float64)\n"
    )
    assert codes(src, COLD) == []
    ok = "import numpy as np\ndef f(x):\n    return x.astype(np.float32)\n"
    assert codes(ok) == []


# -- JGL007: span leak --------------------------------------------------------

SERVING = "weaviate_tpu/serving/fake_lane.py"   # inside the span scope
DBMOD = "weaviate_tpu/db/fake_shard.py"         # also inside


def test_jgl007_bare_span_open_fires_in_serving_and_db():
    src = (
        "from weaviate_tpu.monitoring import tracing\n"
        "def f(parent, rows):\n"
        "    s = parent.child_start('dispatch')\n"
        "    rec = tracing.dispatch_record(rows)\n"
        "    return s, rec\n"
    )
    assert codes(src, SERVING).count("JGL007") == 2
    assert codes(src, DBMOD).count("JGL007") == 2


def test_jgl007_with_statement_is_structurally_closed():
    src = (
        "from weaviate_tpu.monitoring import tracing\n"
        "def f(q):\n"
        "    with tracing.start_span('x') as s:\n"
        "        s.annotate('k', 1)\n"
        "    return q\n"
    )
    assert codes(src, SERVING) == []


def test_jgl007_open_inside_try_with_closing_finally_passes():
    src = (
        "from weaviate_tpu.monitoring import tracing\n"
        "def f(rows):\n"
        "    rec = None\n"
        "    try:\n"
        "        rec = tracing.dispatch_record(rows)\n"
        "        return rec\n"
        "    finally:\n"
        "        if rec is not None:\n"
        "            rec.finish()\n"
    )
    assert codes(src, SERVING) == []


def test_jgl007_open_before_the_guarding_try_still_fires():
    # the open sits OUTSIDE the try: an exception between the two lines
    # leaks the span even though a closing finally exists below
    src = (
        "from weaviate_tpu.monitoring import tracing\n"
        "def f(rows):\n"
        "    rec = tracing.dispatch_record(rows)\n"
        "    try:\n"
        "        return rec\n"
        "    finally:\n"
        "        rec.finish()\n"
    )
    assert codes(src, SERVING).count("JGL007") == 1


def test_jgl007_unrelated_close_in_finally_does_not_waive():
    # fh.close() is a close-named call, but not on a name the try body
    # assigned from a span open — the leaked span must still fire
    src = (
        "from weaviate_tpu.monitoring import tracing\n"
        "def f(p):\n"
        "    try:\n"
        "        s = tracing.start_span('x')\n"
        "        fh = open(p)\n"
        "        return s, fh\n"
        "    finally:\n"
        "        fh.close()\n"
    )
    assert codes(src, SERVING).count("JGL007") == 1


def test_jgl007_nested_def_inside_covered_try_still_fires():
    # the nested function's body runs LATER, outside the enclosing
    # try/finally — its span open is not covered by rec.finish()
    src = (
        "from weaviate_tpu.monitoring import tracing\n"
        "def f(rows, register):\n"
        "    try:\n"
        "        rec = tracing.dispatch_record(rows)\n"
        "        def cb():\n"
        "            return tracing.start_span('late')\n"
        "        register(cb)\n"
        "    finally:\n"
        "        rec.finish()\n"
    )
    assert codes(src, SERVING).count("JGL007") == 1


def test_jgl007_open_in_finally_itself_is_uncovered():
    src = (
        "from weaviate_tpu.monitoring import tracing\n"
        "def f(rows):\n"
        "    try:\n"
        "        return rows\n"
        "    finally:\n"
        "        s = tracing.start_span('late')\n"
    )
    assert codes(src, SERVING).count("JGL007") == 1


def test_jgl007_scoped_to_serving_and_db_only():
    src = (
        "from weaviate_tpu.monitoring import tracing\n"
        "def f(rows):\n"
        "    return tracing.dispatch_record(rows)\n"
    )
    assert codes(src, COLD) == []      # usecases/: out of scope
    assert codes(src, HOT) == []       # ops/: out of scope too
    # module-level (import-time) calls are not serving-path leaks
    top = "from weaviate_tpu.monitoring import tracing\n" \
          "REC = tracing.dispatch_record(1)\n"
    assert codes(top, SERVING) == []


# -- JGL008: blocking device fetch under a held lock --------------------------

IDXMOD = "weaviate_tpu/index/fake_index.py"    # inside the lock-fetch scope


def test_jgl008_asarray_on_device_attr_under_lock_fires():
    src = (
        "import numpy as np\n"
        "def f(self, k):\n"
        "    with self._lock:\n"
        "        return np.asarray(self._store)\n"
    )
    assert codes(src, IDXMOD).count("JGL008") == 1
    assert codes(src, DBMOD).count("JGL008") == 1


def test_jgl008_block_until_ready_and_jitted_result_under_lock():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def _kern(x):\n"
        "    return x\n"
        "def f(self, q):\n"
        "    with self._lock:\n"
        "        out = _kern(q)\n"
        "        out.block_until_ready()\n"
        "        return np.asarray(out)\n"
    )
    assert codes(src, IDXMOD).count("JGL008") == 2


def test_jgl008_fetch_outside_the_lock_passes():
    # the snapshot two-phase shape: dispatch under the lock, fetch outside
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def _kern(x):\n"
        "    return x\n"
        "def f(self, q):\n"
        "    with self._lock:\n"
        "        out = _kern(q)\n"
        "    return np.asarray(out)\n"
    )
    assert "JGL008" not in codes(src, IDXMOD)


def test_jgl008_host_value_under_lock_passes():
    # np.asarray on a plain host value holds no device round trip
    src = (
        "import numpy as np\n"
        "def f(self, rows):\n"
        "    with self._lock:\n"
        "        return np.asarray(rows)\n"
    )
    assert "JGL008" not in codes(src, IDXMOD)


def test_jgl008_non_lock_with_block_passes():
    src = (
        "import numpy as np\n"
        "def f(self, path):\n"
        "    with open(path) as fh:\n"
        "        return np.asarray(self._store)\n"
    )
    assert "JGL008" not in codes(src, IDXMOD)


def test_jgl008_fetch_in_closure_defined_under_lock_passes():
    # the two-phase idiom itself: the finalize closure is DEFINED inside
    # the `with lock:` block but RUNS after release — no finding
    src = (
        "import numpy as np\n"
        "def f(self, q):\n"
        "    with self._lock:\n"
        "        def finalize():\n"
        "            return np.asarray(self._store)\n"
        "    return finalize\n"
    )
    assert "JGL008" not in codes(src, IDXMOD)


def test_jgl008_scoped_to_index_and_db_only():
    src = (
        "import numpy as np\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        return np.asarray(self._store)\n"
    )
    assert "JGL008" not in codes(src, SERVING)  # serving/: JGL007 scope only
    assert "JGL008" not in codes(src, COLD)     # usecases/: out of scope


def test_jgl008_baseline_shrink_only_contract():
    """JGL008 entries ride the same ratchet as every other rule: growth
    surfaces the overflow, shrinkage reports the entry stale (the
    strict-baseline CI gate then demands the prune)."""
    f = Finding("JGL008", "weaviate_tpu/index/mesh.py", 10, 0,
                "MeshVectorIndex.compact", "m")
    base = build_baseline([f])
    # same count: waived, nothing stale
    new, waived, stale = apply_baseline([f], base)
    assert new == [] and waived == 1 and stale == []
    # growth: the overflow surfaces
    new, waived, stale = apply_baseline([f, f], base)
    assert len(new) == 1 and waived == 1
    # shrinkage: the entry reports stale (shrink-only policy)
    new, waived, stale = apply_baseline([], base)
    assert new == [] and stale and stale[0]["code"] == "JGL008"


# -- JGL009: unbounded blocking wait ------------------------------------------


def test_jgl009_bare_wait_get_acquire_fire_in_serving_and_db():
    src = (
        "def f(self):\n"
        "    self.event.wait()\n"
        "    item = self.queue.get()\n"
        "    self.sem.acquire()\n"
        "    self.thread.join()\n"
        "    return item\n"
    )
    assert codes(src, SERVING).count("JGL009") == 4
    assert codes(src, DBMOD).count("JGL009") == 4


def test_jgl009_bounded_waits_pass():
    src = (
        "def f(self, timeout):\n"
        "    self.event.wait(5.0)\n"
        "    self.event.wait(timeout=timeout)\n"
        "    item = self.queue.get(timeout=0.5)\n"
        "    ok = self.sem.acquire(timeout=0.1)\n"
        "    ok2 = self.sem.acquire(blocking=False)\n"
        "    self.thread.join(2.0)\n"
        "    return item, ok, ok2\n"
    )
    assert "JGL009" not in codes(src, SERVING)


def test_jgl009_dict_get_and_contextvar_get_pass():
    src = (
        "import contextvars\n"
        "_VAR = contextvars.ContextVar('v', default=None)\n"
        "def f(self, d, key):\n"
        "    a = d.get(key)\n"          # keyed lookup, not a queue wait
        "    b = _VAR.get()\n"          # ContextVar: lookup, not blocking
        "    return a, b\n"
    )
    assert "JGL009" not in codes(src, SERVING)


def test_jgl009_out_of_scope_modules_unflagged():
    src = "def f(self):\n    self.event.wait()\n"
    assert "JGL009" not in codes(src, COLD)   # usecases/: out of scope
    assert "JGL009" not in codes(src, HOT)    # ops/: JGL001 scope, not 009


def test_jgl009_module_level_calls_unflagged():
    # import-time waits (e.g. a module bootstrap barrier) are not the
    # serving path; the rule scopes to function bodies like JGL005
    src = "import e\ne.EVENT.wait()\n"
    assert "JGL009" not in codes(src, SERVING)


# -- interprocedural JGL008/JGL009: the one-level intra-module call graph ----


def test_jgl008_interprocedural_helper_one_level_deep_fires():
    """A `with lock:` body calling a SAME-MODULE helper that fetches a
    device value — lexically invisible to the old per-statement check,
    now a finding at the call site."""
    src = (
        "import numpy as np\n"
        "def materialize(self):\n"
        "    return np.asarray(self._store)\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        return materialize(self)\n"
    )
    out = analyze_source(src, IDXMOD)
    hits = [f for f in out if f.code == "JGL008"]
    assert [f.symbol for f in hits] == ["f"]
    assert "materialize" in hits[0].message


def test_jgl008_interprocedural_self_method_helper_fires():
    src = (
        "import numpy as np\n"
        "class Idx:\n"
        "    def _materialize(self):\n"
        "        return np.asarray(self._store)\n"
        "    def compress(self):\n"
        "        with self._lock:\n"
        "            rows = self._materialize()\n"
        "        return rows\n"
    )
    hits = [f for f in analyze_source(src, IDXMOD) if f.code == "JGL008"]
    assert [f.symbol for f in hits] == ["Idx.compress"]


def test_jgl008_interprocedural_alias_chain_in_helper_fires():
    """The device value reaches the fetch through a FORWARD alias chain
    inside the helper — the device-name set must converge to a fixpoint
    regardless of the traversal order of the helper's statements."""
    src = (
        "import numpy as np\n"
        "class Idx:\n"
        "    def _materialize(self):\n"
        "        rows = self._store\n"
        "        out = rows\n"
        "        return np.asarray(out)\n"
        "    def compress(self):\n"
        "        with self._lock:\n"
        "            return self._materialize()\n"
    )
    hits = [f for f in analyze_source(src, IDXMOD) if f.code == "JGL008"]
    assert [f.symbol for f in hits] == ["Idx.compress"]


def test_jgl008_interprocedural_fetch_packed_and_burr_helpers_fire():
    src = (
        "class Idx:\n"
        "    def _finish(self, packed):\n"
        "        return _fetch_packed(packed)\n"
        "    def _sync(self, out):\n"
        "        out.block_until_ready()\n"
        "    def f(self, packed, out):\n"
        "        with self._lock:\n"
        "            self._finish(packed)\n"
        "            self._sync(out)\n"
    )
    hits = [f for f in analyze_source(src, IDXMOD) if f.code == "JGL008"]
    assert len(hits) == 2 and all(f.symbol == "Idx.f" for f in hits)


def test_jgl008_interprocedural_two_levels_deep_out_of_scope():
    """ONE level only, by design: a sync two calls down is not reported
    (the runtime graftsan device-sync sanitizer catches any depth)."""
    src = (
        "import numpy as np\n"
        "def deep(self):\n"
        "    return np.asarray(self._store)\n"
        "def shallow(self):\n"
        "    return deep(self)\n"        # sync is 2 hops from the lock
        "def f(self):\n"
        "    with self._lock:\n"
        "        return shallow(self)\n"
    )
    assert "JGL008" not in [f.code for f in analyze_source(src, IDXMOD)]


def test_jgl008_interprocedural_closure_and_unlocked_calls_pass():
    """The finalize-closure idiom stays exempt (a nested def's body runs
    AFTER the lock releases), and a helper call outside any lock is not
    this rule's business."""
    src = (
        "import numpy as np\n"
        "def materialize(self):\n"
        "    return np.asarray(self._store)\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        def finalize():\n"
        "            return materialize(self)\n"  # runs after release
        "    return finalize\n"
        "def g(self):\n"
        "    return materialize(self)\n"          # no lock held
    )
    assert "JGL008" not in [f.code for f in analyze_source(src, IDXMOD)]


def test_jgl008_interprocedural_host_only_helper_passes():
    src = (
        "import numpy as np\n"
        "def host_math(rows):\n"
        "    return np.asarray(rows, dtype=np.float32)\n"  # host staging
        "def f(self, rows):\n"
        "    with self._lock:\n"
        "        return host_math(rows)\n"
    )
    assert "JGL008" not in [f.code for f in analyze_source(src, IDXMOD)]


def test_jgl009_interprocedural_blocking_helper_under_lock_fires():
    src = (
        "class Pool:\n"
        "    def _drain(self):\n"
        "        return self.queue.get()\n"   # unbounded wait
        "    def f(self):\n"
        "        with self._lock:\n"
        "            return self._drain()\n"
    )
    out = analyze_source(src, SERVING)
    # the helper's own bare get() still fires directly; the NEW finding
    # is the lock-held call site
    sym = [f.symbol for f in out if f.code == "JGL009"]
    assert sorted(sym) == ["Pool._drain", "Pool.f"]


def test_jgl009_interprocedural_needs_the_lock_context():
    """Without a held lock the call site adds nothing: the helper's own
    body already carries the direct JGL009 — no double report."""
    src = (
        "class Pool:\n"
        "    def _drain(self):\n"
        "        return self.queue.get()\n"
        "    def f(self):\n"
        "        return self._drain()\n"
    )
    out = analyze_source(src, SERVING)
    assert [f.symbol for f in out if f.code == "JGL009"] == ["Pool._drain"]


def test_jgl009_interprocedural_bounded_helper_passes():
    src = (
        "class Pool:\n"
        "    def _drain(self):\n"
        "        return self.queue.get(timeout=0.5)\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            return self._drain()\n"
    )
    assert "JGL009" not in [f.code for f in analyze_source(src, SERVING)]


def test_interprocedural_repo_tree_only_gained_justified_baseline():
    """The repo gate stays green under the interprocedural upgrade: every
    new JGL008/JGL009 hit is either fixed or carries a written
    justification in the baseline (which may only shrink from here)."""
    import json

    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "weaviate_tpu",
         "--strict-baseline"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    base = json.load(open(os.path.join(
        REPO, "tools", "graftlint", "baseline.json")))
    for e in base["entries"]:
        assert e.get("justification", "").strip(), e
        assert "TODO" not in e["justification"], e


# -- JGL010: dynamically-constructed metric label value -----------------------


def test_jgl010_fstring_format_percent_concat_fire():
    src = (
        "def f(self, m, tenant, op):\n"
        "    m.requests.labels(f'tenant-{tenant}').inc()\n"
        "    m.requests.labels('tenant-{}'.format(tenant)).inc()\n"
        "    m.requests.labels('tenant-%s' % tenant).inc()\n"
        "    m.requests.labels('tenant-' + tenant).inc()\n"
        "    m.requests.labels(op, reason='shed-' + tenant).inc()\n"
    )
    # package-wide scope: the serving path AND cold modules both count
    assert codes(src, SERVING).count("JGL010") == 5
    assert codes(src, COLD).count("JGL010") == 5


def test_jgl010_bounded_values_pass():
    src = (
        "NAMES = {0: 'closed', 1: 'open'}\n"
        "def f(self, m, state, reason, tenant):\n"
        "    m.breaker.labels(NAMES[state]).inc()\n"      # dict lookup
        "    m.shed.labels(reason).inc()\n"               # plain name
        "    m.shed.labels('queue_full').inc()\n"         # constant
        "    m.t.labels(m.tenant_labels.observe(tenant)).inc()\n"  # mapper
        "    m.rows.labels('a' + 'b').inc()\n"            # all-constant concat
        "    s = f'tenant-{tenant}'\n"                    # f-string NOT a label
        "    return s\n"
    )
    assert "JGL010" not in codes(src, SERVING)


def test_jgl010_nested_concat_and_kwarg_fire():
    src = (
        "def f(self, m, cls, shard):\n"
        "    m.ops.labels('c-' + cls + '-s-' + shard).inc()\n"
        "    m.ops.labels(component=f'{cls}.{shard}').inc()\n"
    )
    assert codes(src, SERVING).count("JGL010") == 2


def test_jgl010_non_labels_calls_and_foreign_scope_pass():
    # .format()/f-strings OUTSIDE a .labels() call are not this rule's
    # business, and files outside weaviate_tpu/ are out of scope entirely
    src = (
        "def f(self, log, tenant):\n"
        "    log.warning('tenant %s shed', tenant)\n"
        "    return 'x-{}'.format(tenant)\n"
    )
    assert "JGL010" not in codes(src, SERVING)
    bad = "def f(m, t):\n    m.c.labels(f'{t}').inc()\n"
    assert "JGL010" not in codes(bad, "scripts/offline_report.py")
    assert "JGL010" in codes(bad, SERVING)


# -- JGL011: unguarded background-thread run-loop -----------------------------


def test_jgl011_unguarded_runloop_fires_for_name_and_method_targets():
    src = (
        "import threading\n"
        "class Auditor:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._run, daemon=True).start()\n"
        "    def _run(self):\n"
        "        while not self._stop.is_set():\n"
        "            self._audit_once()\n"
        "def start_monitor(check):\n"
        "    def loop():\n"
        "        while True:\n"
        "            check()\n"
        "    threading.Thread(target=loop, daemon=True).start()\n"
    )
    assert codes(src, SERVING).count("JGL011") == 2
    # package-wide scope: cold modules spawn daemons too
    assert codes(src, COLD).count("JGL011") == 2


def test_jgl011_guarded_runloops_pass():
    src = (
        "import threading\n"
        "class Auditor:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._run, daemon=True).start()\n"
        "        threading.Thread(target=self._sup, daemon=True).start()\n"
        "    def _run(self):\n"                 # while: try/except idiom
        "        while not self._stop.is_set():\n"
        "            try:\n"
        "                self._audit_once()\n"
        "            except Exception:\n"
        "                continue\n"
        "    def _sup(self):\n"                 # guarded-supervisor idiom
        "        try:\n"
        "            while True:\n"
        "                self._tick()\n"
        "        except Exception:\n"
        "            pass\n"
    )
    assert "JGL011" not in codes(src, SERVING)


def test_jgl011_non_target_loops_and_foreign_scope_pass():
    # an unguarded loop in a function NEVER handed to a Thread is not a
    # run-loop; deep attribute targets (another object's method) are
    # skipped; files outside weaviate_tpu/ are out of scope
    src = (
        "import threading\n"
        "def crunch(items):\n"
        "    while items:\n"
        "        items.pop()\n"
        "class Srv:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self.httpd.serve_forever).start()\n"
    )
    assert "JGL011" not in codes(src, SERVING)
    bad = (
        "import threading\n"
        "def loop():\n"
        "    while True:\n"
        "        tick()\n"
        "threading.Thread(target=loop).start()\n"
    )
    assert "JGL011" in codes(bad, SERVING)
    assert "JGL011" not in codes(bad, "tools/chip_watch.py")


def test_jgl011_runloop_inside_match_case_is_audited():
    src = (
        "import threading\n"
        "def loop(mode):\n"
        "    match mode:\n"
        "        case 'poll':\n"
        "            while True:\n"
        "                tick()\n"
        "threading.Thread(target=loop).start()\n"
    )
    assert "JGL011" in codes(src, SERVING)
    guarded = src.replace(
        "            while True:\n"
        "                tick()\n",
        "            while True:\n"
        "                try:\n"
        "                    tick()\n"
        "                except Exception:\n"
        "                    continue\n")
    assert "JGL011" not in codes(guarded, SERVING)


def test_jgl011_only_outermost_loops_audited():
    # a guarded outer loop owns its inner loops: the inner `for` needs no
    # guard of its own (the outer try/except already bounds the blast
    # radius to one iteration)
    src = (
        "import threading\n"
        "def loop(batches):\n"
        "    while True:\n"
        "        try:\n"
        "            for b in batches:\n"
        "                handle(b)\n"
        "        except Exception:\n"
        "            continue\n"
        "threading.Thread(target=loop).start()\n"
    )
    assert "JGL011" not in codes(src, SERVING)


def test_jgl011_clean_repo():
    """The shipped tree's own daemons (disk monitor, compaction cycle,
    gossip, coalescer flusher, quality audit workers) are all guarded —
    the rule lands with a clean baseline and must stay that way."""
    import tools.graftlint.engine as engine

    findings = engine.analyze_tree(
        os.path.join(REPO, "weaviate_tpu"), root=REPO)
    assert [f for f in findings if f.code == "JGL011"] == []


# -- suppressions (JGL000) ----------------------------------------------------

def test_suppression_with_reason_silences_finding():
    src = (
        "def f(y):\n"
        "    return y.item()  # graftlint: disable=JGL001 host numpy scalar\n"
    )
    assert codes(src) == []


def test_suppression_without_reason_is_flagged():
    src = (
        "def f(y):\n"
        "    return y.item()  # graftlint: disable=JGL001\n"
    )
    assert codes(src) == ["JGL000"]


def test_unused_suppression_is_flagged():
    src = "x = 1  # graftlint: disable=JGL006 no such finding here\n"
    assert codes(src) == ["JGL000"]


def test_stale_code_in_multi_code_suppression_is_flagged():
    # the JGL001 half still matches; the JGL006 half is dead and must not
    # linger behind it (per-code tracking, not per-comment)
    src = (
        "def f(y):\n"
        "    return y.item()  # graftlint: disable=JGL001,JGL006 legacy\n"
    )
    out = codes(src)
    assert out == ["JGL000"], out


def test_suppression_syntax_inside_string_literal_is_inert():
    # documenting the disable syntax in a string must neither trip JGL000
    # nor waive a real finding sharing the line — only COMMENT tokens count
    doc = 'MSG = "use # graftlint: disable=JGL001 like this"\n'
    assert codes(doc) == []
    waive_attempt = (
        "def f(y):\n"
        '    return y.item(), "# graftlint: disable=JGL001 nope"\n'
    )
    assert codes(waive_attempt) == ["JGL001"]


# -- baseline mechanics -------------------------------------------------------

def _mk(code="JGL001", path="p.py", symbol="f", line=1):
    return Finding(code, path, line, 0, symbol, "m")


def test_baseline_waives_up_to_count_and_reports_overflow():
    base = build_baseline([_mk(), _mk()])
    assert base["entries"][0]["count"] == 2
    new, waived, stale = apply_baseline([_mk(), _mk(), _mk(line=9)], base)
    assert waived == 2 and len(new) == 1 and not stale


def test_baseline_stale_entries_surface_the_ratchet():
    base = build_baseline([_mk(), _mk(symbol="g")])
    new, waived, stale = apply_baseline([_mk()], base)
    assert not new and waived == 1
    assert [e["symbol"] for e in stale] == ["g"]


def test_build_baseline_carries_justifications_forward():
    old = build_baseline([_mk()])
    old["entries"][0]["justification"] = "deliberate cold-path fetch"
    again = build_baseline([_mk()], old)
    assert again["entries"][0]["justification"] == "deliberate cold-path fetch"


# -- CLI ----------------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120)


def test_cli_list_rules_and_usage_errors():
    r = _cli("--list-rules")
    assert r.returncode == 0 and "JGL001" in r.stdout and "JGL006" in r.stdout
    assert _cli().returncode == 2
    assert _cli("definitely/not/a/path.py").returncode == 2
    # a non-Python file target is a usage error, not a JGL999 parse finding
    r = _cli("README.md")
    assert r.returncode == 2 and "not a Python file" in r.stderr


def test_cli_errors_when_nothing_is_analyzed(tmp_path):
    # a _pb2.py target or an empty directory analyzes zero files — a green
    # "0 finding(s)" there would be a false pass, so it is a usage error
    pb2 = tmp_path / "weaviate_tpu" / "ops"
    pb2.mkdir(parents=True)
    (pb2 / "gen_pb2.py").write_text("x = 1\n")
    r = _cli(str(pb2 / "gen_pb2.py"))
    assert r.returncode == 2 and "no Python files" in r.stderr
    empty = tmp_path / "empty"
    empty.mkdir()
    assert _cli(str(empty)).returncode == 2


def test_undecodable_and_unparsable_files_report_jgl999(tmp_path):
    # a legal latin-1 coding declaration must be honored (PEP 263), and
    # bytes the declared codec can't decode — or null bytes ast.parse
    # rejects with ValueError — must surface as JGL999, not a traceback
    from tools.graftlint import analyze_tree

    pkg = tmp_path / "weaviate_tpu" / "ops"
    pkg.mkdir(parents=True)
    (pkg / "latin.py").write_bytes(
        b"# -*- coding: latin-1 -*-\n# caf\xe9\ndef f(y):\n"
        b"    return y.item()\n")
    (pkg / "nul.py").write_bytes(b"x = 1\x00\n")
    (pkg / "badenc.py").write_bytes(b"# -*- coding: utf-8 -*-\n# \xff\xfe\n")
    out = {f.path.rsplit("/", 1)[-1]: f.code
           for f in analyze_tree(str(tmp_path / "weaviate_tpu"))}
    assert out["latin.py"] == "JGL001"  # decoded fine, rule still fires
    assert out["nul.py"] == "JGL999"
    assert out["badenc.py"] == "JGL999"


def test_symlinked_target_path_keys_like_the_direct_one(tmp_path):
    # reaching the repo through a symlink must not re-anchor findings at
    # the filesystem root and bypass the committed baseline
    from tools.graftlint import analyze_tree

    link = tmp_path / "repolink"
    os.symlink(REPO, str(link))
    direct = [f.path for f in
              analyze_tree(os.path.join(REPO, "weaviate_tpu", "ops"))]
    via_link = [f.path for f in
                analyze_tree(str(link / "weaviate_tpu" / "ops"))]
    assert via_link == direct
    for p in via_link:
        assert p.startswith("weaviate_tpu/"), p
    # an explicit root given through the symlink resolves the same way
    rooted = [f.path for f in
              analyze_tree(str(link / "weaviate_tpu" / "ops"),
                           root=str(link))]
    assert rooted == direct


def test_root_target_keeps_whole_baseline_in_scope():
    # scope "." (target IS the root) must match every entry — otherwise a
    # whole-repo run bypasses the baseline and --update-baseline merges
    # the old baseline back in as duplicates
    from tools.graftlint.__main__ import _split_by_scope

    entries = [{"code": "JGL001", "path": "weaviate_tpu/ops/a.py",
                "symbol": "f", "count": 1}]
    inside, outside = _split_by_scope(entries, ".")
    assert inside == entries and outside == []


def test_cli_findings_drive_exit_code(tmp_path):
    bad = tmp_path / "weaviate_tpu" / "ops" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("def f(y):\n    return y.item()\n")
    r = _cli(str(bad), "--no-baseline")
    assert r.returncode == 1 and "JGL001" in r.stdout
    bad.write_text("def f(y):\n    return y\n")
    assert _cli(str(bad), "--no-baseline").returncode == 0


def test_finding_paths_are_cwd_independent(tmp_path):
    # baseline entries are keyed by path; if paths depended on the cwd,
    # running from elsewhere would mark every entry stale and
    # --prune-baseline would empty the baseline
    from tools.graftlint import analyze_tree

    pkg = tmp_path / "weaviate_tpu" / "ops"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text("def f(y):\n    return y.item()\n")
    target = str(tmp_path / "weaviate_tpu")

    here = os.getcwd()
    os.chdir(str(tmp_path))
    try:
        from_tmp = [f.path for f in analyze_tree(target)]
    finally:
        os.chdir(here)
    from_repo = [f.path for f in analyze_tree(target)]
    assert from_tmp == from_repo == ["weaviate_tpu/ops/bad.py"]

    # the real package anchors at the repo root, matching baseline keys
    in_repo = analyze_tree(os.path.join(REPO, "weaviate_tpu", "__init__.py"))
    for f in in_repo:
        assert f.path.startswith("weaviate_tpu/"), f.path


def test_cli_default_baseline_found_from_any_cwd(tmp_path):
    # DEFAULT_BASELINE is repo-root-anchored: invoked from an unrelated
    # cwd with an absolute target, the gate must still load the committed
    # baseline (a cwd-relative default would load empty and exit 1)
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         os.path.join(REPO, "weaviate_tpu"), "--strict-baseline"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 finding(s)" in r.stderr, r.stderr


def test_prune_with_partial_target_keeps_out_of_scope_entries(tmp_path):
    # pruning after a run over weaviate_tpu/ops must not discard entries
    # for index/ etc. — those files were never analyzed, so their entries
    # are unknown, not stale
    import json as _json

    ops = tmp_path / "weaviate_tpu" / "ops"
    ops.mkdir(parents=True)
    (ops / "a.py").write_text("def f(y):\n    return y.item()\n")
    base = tmp_path / "b.json"
    base.write_text(_json.dumps({"version": 1, "entries": [
        {"code": "JGL001", "path": "weaviate_tpu/ops/a.py", "symbol": "f",
         "count": 1, "justification": "live, in scope"},
        {"code": "JGL001", "path": "weaviate_tpu/ops/gone.py", "symbol": "g",
         "count": 1, "justification": "stale, in scope"},
        {"code": "JGL001", "path": "weaviate_tpu/index/x.py", "symbol": "h",
         "count": 1, "justification": "out of scope, must survive"},
    ]}))
    r = _cli(str(ops), "--root", str(tmp_path),
             "--baseline", str(base), "--prune-baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    kept = {(e["path"], e["justification"])
            for e in _json.loads(base.read_text())["entries"]}
    assert kept == {
        ("weaviate_tpu/ops/a.py", "live, in scope"),
        ("weaviate_tpu/index/x.py", "out of scope, must survive"),
    }, kept


def test_partial_target_does_not_report_out_of_scope_entries_stale(tmp_path):
    # same scoping under --strict-baseline: an entry for an unanalyzed
    # file must not fail the ratchet
    import json as _json

    ops = tmp_path / "weaviate_tpu" / "ops"
    ops.mkdir(parents=True)
    (ops / "a.py").write_text("def f(y):\n    return y.item()\n")
    base = tmp_path / "b.json"
    base.write_text(_json.dumps({"version": 1, "entries": [
        {"code": "JGL001", "path": "weaviate_tpu/ops/a.py", "symbol": "f",
         "count": 1, "justification": "live"},
        {"code": "JGL001", "path": "weaviate_tpu/index/x.py", "symbol": "h",
         "count": 1, "justification": "not analyzed this run"},
    ]}))
    r = _cli(str(ops), "--root", str(tmp_path),
             "--baseline", str(base), "--strict-baseline")
    assert r.returncode == 0, r.stdout + r.stderr


# -- JGL012: unaccounted HBM allocation ---------------------------------------

INDEX = "weaviate_tpu/index/fake_index.py"  # inside the JGL012 scope


def test_jgl012_device_alloc_without_stamp_fires():
    src = (
        "import jax, jax.numpy as jnp\n"
        "class Idx:\n"
        "    def _grow(self, cap):\n"
        "        self._store = jax.device_put(jnp.zeros((cap, 8)))\n"
        "        self._tombs = _grow_1d(self._tombs, cap, False)\n"
    )
    assert codes(src, INDEX).count("JGL012") == 2


def test_jgl012_stamped_method_and_publish_pass():
    src = (
        "import jax, jax.numpy as jnp\n"
        "class Idx:\n"
        "    def _grow(self, cap):\n"
        "        self._store = jax.device_put(jnp.zeros((cap, 8)))\n"
        "        self._stamp_memory()\n"
        "    def _flush(self):\n"
        "        self._tombs = _set_tombs(self._tombs)\n"
        "        self._publish_snapshot()\n"
    )
    assert "JGL012" not in codes(src, INDEX)


def test_jgl012_tuple_target_and_none_teardown():
    src = (
        "class Idx:\n"
        "    def _write(self, c):\n"
        "        self._store, self._sq_norms = mesh_insert_step(c)\n"
        "    def drop(self):\n"
        "        self._store = self._sq_norms = None\n"  # constant: exempt
    )
    assert codes(src, INDEX).count("JGL012") == 2


def test_jgl012_out_of_scope_and_non_snapshot_fields_pass():
    src = (
        "import jax, jax.numpy as jnp\n"
        "class Idx:\n"
        "    def _grow(self, cap):\n"
        "        self._store = jax.device_put(jnp.zeros((cap, 8)))\n"
        "        self._scratch = jnp.zeros((cap,))\n"  # not a snapshot field
    )
    # ops/ is outside the index scope: no findings at all
    assert "JGL012" not in codes(src, HOT)
    # in scope, only the snapshot field fires
    assert codes(src, INDEX).count("JGL012") == 1


def test_jgl012_repo_index_layer_is_clean():
    import subprocess as _sp

    r = _sp.run([sys.executable, "-m", "tools.graftlint",
                 "weaviate_tpu/index"], capture_output=True, text=True,
                cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr


def test_jgl012_covers_the_ivf_slab_fields():
    """The IVF scan plane's device slabs (centroids / padded buckets /
    PCA projection + rows) ride the same snapshot-field audit as the
    store: bound from a call in a method that never stamps the ledger is
    a finding; a stamped method passes."""
    body = (
        "import jax, jax.numpy as jnp\n"
        "class Idx:\n"
        "    def _train(self, cent, buckets, proj, rows):\n"
        "        self._ivf_centroids = jax.device_put(jnp.asarray(cent))\n"
        "        self._ivf_buckets = jax.device_put(jnp.asarray(buckets))\n"
        "        self._ivf_pca_proj = jax.device_put(jnp.asarray(proj))\n"
        "        self._ivf_pca_rows = jax.device_put(jnp.asarray(rows))\n"
    )
    assert codes(body, INDEX).count("JGL012") == 4
    stamped = body + "        self._stamp_memory()\n"
    assert "JGL012" not in codes(stamped, INDEX)


def test_jgl012_annotated_assignment_fires_too():
    src = (
        "import jax, jax.numpy as jnp\n"
        "class Idx:\n"
        "    def _grow(self, cap):\n"
        "        self._store: jax.Array = jax.device_put(jnp.zeros((cap,)))\n"
    )
    assert codes(src, INDEX).count("JGL012") == 1
    stamped = src + "        self._stamp_memory()\n"
    assert "JGL012" not in codes(stamped, INDEX)


# -- JGL013: ops-journal event kinds must be registered literals --------------

def test_jgl013_dynamic_kind_fires():
    src = (
        "from weaviate_tpu.monitoring import incidents\n"
        "def f(reason):\n"
        "    incidents.emit(f'shed_{reason}', scope='q')\n"
        "    incidents.emit('breaker_' + reason)\n"
        "    incidents.emit(reason)\n"
    )
    assert codes(src, COLD).count("JGL013") == 3


def test_jgl013_unregistered_literal_fires():
    src = (
        "from weaviate_tpu.monitoring import incidents\n"
        "def f():\n"
        "    incidents.emit('totally_new_kind', scope='x')\n"
    )
    assert codes(src, COLD).count("JGL013") == 1


def test_jgl013_registered_literals_pass_dotted_and_bare():
    src = (
        "from weaviate_tpu.monitoring import incidents\n"
        "from weaviate_tpu.monitoring.incidents import emit as jemit\n"
        "def f():\n"
        "    incidents.emit('shed_burst', scope='queue_full')\n"
        "    incidents.emit(kind='breaker_open')\n"
        "    jemit('jit_compile', scope='dispatch')\n"
    )
    assert "JGL013" not in codes(src, COLD)


def test_jgl013_missing_kind_fires_and_exempt_module_passes():
    src = (
        "from weaviate_tpu.monitoring import incidents\n"
        "def f():\n"
        "    incidents.emit(scope='x')\n"
    )
    assert codes(src, COLD).count("JGL013") == 1
    # inside the journal module itself the rule stays silent (its own
    # emit implementation and internal re-emissions own the taxonomy)
    assert "JGL013" not in codes(
        src, "weaviate_tpu/monitoring/incidents.py")


def test_jgl013_unrelated_emit_calls_pass():
    # a logging handler's emit (or any foreign .emit) must not be flagged:
    # only the incidents module's emit is in scope
    src = (
        "import logging\n"
        "def f(handler, record, kind):\n"
        "    handler.emit(record)\n"
        "    logging.Handler().emit(kind)\n"
    )
    assert "JGL013" not in codes(src, COLD)


def test_jgl013_taxonomy_mirror_matches_runtime():
    """The rules.py mirror and the runtime taxonomy must be the SAME set
    — drift would let a registered kind fail lint or an unregistered one
    pass it. (The runtime import is safe here: tier-1 runs with JAX on
    CPU and incidents.py imports only the stdlib.)"""
    from tools.graftlint import rules as _rules
    from weaviate_tpu.monitoring import incidents as _incidents

    assert _rules.JOURNAL_EVENT_KINDS == frozenset(_incidents.EVENT_KINDS)


# -- JGL014: controller-owned knobs actuate only in controller.py -------------

def test_jgl014_knob_setter_calls_fire_outside_controller():
    src = (
        "def f(tracer, auditor, coalescer, plane):\n"
        "    tracer.set_sample_rate(0.0)\n"
        "    auditor.set_sample_rate(0.5)\n"
        "    coalescer.set_pipeline_depth(2)\n"
        "    plane._set_knob('admission_margin', 2.0, 'x')\n"
    )
    assert codes(src, COLD).count("JGL014") == 4


def test_jgl014_knob_field_writes_fire_outside_controller():
    src = (
        "def f(plane, co):\n"
        "    plane.rescore_r_cap = 32\n"
        "    co.admission_margin = 2.0\n"
        "    plane._knobs['rate_scale'] = (0.5, 0.0)\n"
        "    co.tenant_cap_scale: float = 0.5\n"
        "    plane.brownout_stage += 1\n"
    )
    assert codes(src, COLD).count("JGL014") == 5


def test_jgl014_bare_annotation_is_a_declaration_not_a_write():
    # `co.admission_margin: float` binds nothing — only an AnnAssign
    # WITH a value actuates a knob
    src = (
        "def f(co):\n"
        "    co.admission_margin: float\n"
    )
    assert "JGL014" not in codes(src, COLD)


def test_jgl014_self_writes_and_unrelated_attrs_pass():
    # an object's own constructor/defaults (self-writes) stay legal, and
    # fields outside the knob set are not this rule's business
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        self.admission_margin = 1.0\n"
        "        self._knobs = {}\n"
        "        self.sample_rate = 1.0\n"
        "    def g(self, other):\n"
        "        other.window_s = 5.0\n"
        "        other.unrelated = 1\n"
    )
    assert "JGL014" not in codes(src, COLD)


def test_jgl014_ivf_top_p_knob_is_controller_owned():
    """The IVF probe-count cap (the second recall-guarded budget) joins
    the knob-field set: writes outside serving/controller.py bypass the
    clamp/journal/lease machinery and are findings."""
    src = (
        "def f(plane):\n"
        "    plane.ivf_top_p = 4\n"
        "    plane.ivf_top_p_cap = 2\n"
    )
    assert codes(src, COLD).count("JGL014") == 2
    assert "JGL014" not in codes(
        src, "weaviate_tpu/serving/controller.py")


def test_jgl014_controller_module_is_exempt():
    src = (
        "def _actuate(plane, tracer):\n"
        "    plane._knobs['admission_margin'] = (2.0, 0.0)\n"
        "    tracer.set_sample_rate(0.0)\n"
    )
    assert "JGL014" not in codes(src, "weaviate_tpu/serving/controller.py")
    assert codes(src, COLD).count("JGL014") == 2


# -- JGL015: host post-processing in a fused finalize/unpack path -------------


def test_jgl015_row_loop_in_finalize_fires():
    src = (
        "def _dispatch(self):\n"
        "    def finalize():\n"
        "        packed = _fetch_packed(dev)\n"
        "        out = []\n"
        "        for row in packed:\n"           # per-row host loop
        "            out.append(row)\n"
        "        return out\n"
        "    return finalize\n"
    )
    assert codes(src, INDEX).count("JGL015") == 1


def test_jgl015_foreign_asarray_fires_packed_asarray_passes():
    src = (
        "import numpy as np\n"
        "def finalize():\n"
        "    packed = np.asarray(packed_dev)\n"  # packed buffer: legal
        "    extra = np.asarray(slot_to_doc)\n"
        "    return packed, extra\n"
        "def unpack_fused(packed):\n"
        "    return np.asarray(packed)\n"        # THE packed buffer: legal
    )
    out = codes(src, INDEX)
    # packed_dev (a packed name) and packed itself pass; slot_to_doc fires
    assert out.count("JGL015") == 1


def test_jgl015_while_loop_fires_too():
    src = (
        "def finalize():\n"
        "    packed = _fetch_packed(dev)\n"
        "    i = 0\n"
        "    while i < packed.shape[0]:\n"  # same per-row work, spelled
        "        i += 1\n"                  # as a while loop
        "    return packed\n"
    )
    assert codes(src, INDEX).count("JGL015") == 1


def test_jgl015_nested_helper_inherits_finalize_scope():
    src = (
        "import numpy as np\n"
        "def finalize():\n"
        "    def helper():\n"
        "        for r in rows:\n"
        "            np.asarray(r)\n"
        "    return helper()\n"
    )
    # the loop AND the asarray inside the nested helper both fire
    assert codes(src, INDEX).count("JGL015") == 2


def test_jgl015_out_of_scope_and_other_functions_pass():
    src = (
        "import numpy as np\n"
        "def finalize():\n"
        "    for r in rows:\n"
        "        pass\n"
    )
    # ops/ is outside the index scope
    assert "JGL015" not in codes(src, HOT)
    # a non-finalize function in scope may loop freely
    src2 = (
        "import numpy as np\n"
        "def _restore(self):\n"
        "    for rec in replay():\n"
        "        np.asarray(rec)\n"
    )
    assert "JGL015" not in codes(src2, INDEX)


def test_jgl015_fetch_packed_itself_is_exempt():
    src = (
        "import numpy as np\n"
        "def _fetch_packed(dev, shape=None):\n"
        "    return np.asarray(dev)\n"
    )
    assert "JGL015" not in codes(src, INDEX)


def test_jgl015_repo_index_layer_is_clean():
    import subprocess as _sp

    r = _sp.run([sys.executable, "-m", "tools.graftlint",
                 "weaviate_tpu/index"], capture_output=True, text=True,
                cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
