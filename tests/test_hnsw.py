"""Native HNSW engine: recall, deletes, filters, persistence.

Models the reference's recall fixture test (hnsw/recall_test.go:32 —
recall >= 0.99 at ef sweep) and persistence/delete integration tests."""

import numpy as np
import pytest

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.hnsw import HnswIndex
from weaviate_tpu.storage.bitmap import Bitmap


def make(tmp_path, metric=vi.DISTANCE_L2, **kw):
    cfg = vi.HnswUserConfig.from_dict({"distance": metric, **kw}, "hnsw")
    return HnswIndex(cfg, str(tmp_path))


def brute(vecs, q, k, metric):
    from weaviate_tpu.ops.distances import single_distance

    d = np.array([single_distance(q, v, metric) for v in vecs])
    order = np.argsort(d, kind="stable")[:k]
    return order


@pytest.mark.parametrize("metric", [vi.DISTANCE_L2, vi.DISTANCE_COSINE])
def test_recall_099(tmp_path, rng, metric):
    n, d, k, nq = 4000, 32, 10, 50
    vecs = rng.standard_normal((n, d)).astype(np.float32)
    idx = make(tmp_path / metric, metric, efConstruction=128, maxConnections=16)
    idx.add_batch(np.arange(n), vecs)
    queries = rng.standard_normal((nq, d)).astype(np.float32)
    hits = 0
    for q in queries:
        ids, _ = idx.search_by_vector(q, k)
        want = set(brute(vecs, q, k, metric).tolist())
        hits += len(want & set(ids.tolist()))
    recall = hits / (nq * k)
    assert recall >= 0.99, f"recall {recall}"


def test_batch_search(tmp_path, rng):
    n, d = 1000, 16
    vecs = rng.standard_normal((n, d)).astype(np.float32)
    idx = make(tmp_path)
    idx.add_batch(np.arange(n), vecs)
    qs = vecs[:5]
    ids, dists = idx.search_by_vectors(qs, 3)
    assert ids.shape == (5, 3)
    for i in range(5):
        assert ids[i][0] == i
        assert dists[i][0] < 1e-4


def test_delete_and_entrypoint_move(tmp_path, rng):
    idx = make(tmp_path)
    vecs = rng.standard_normal((200, 8)).astype(np.float32)
    idx.add_batch(np.arange(200), vecs)
    idx.delete(*range(100))
    assert len(idx) == 100
    ids, _ = idx.search_by_vector(vecs[150], 10)
    assert ids[0] == 150
    assert all(i >= 100 for i in ids.tolist())


def test_readd_replaces(tmp_path, rng):
    idx = make(tmp_path)
    idx.add(5, np.ones(8, np.float32))
    idx.add(5, -np.ones(8, np.float32))
    assert len(idx) == 1
    ids, dists = idx.search_by_vector(-np.ones(8, np.float32), 1)
    assert ids[0] == 5 and dists[0] < 1e-5


def test_allowlist_flat_and_graph(tmp_path, rng):
    vecs = rng.standard_normal((500, 8)).astype(np.float32)
    # small allowList -> flat path
    idx = make(tmp_path / "flat")
    idx.add_batch(np.arange(500), vecs)
    allow = Bitmap([3, 7, 450])
    ids, _ = idx.search_by_vector(vecs[0], 10, allow)
    assert set(ids.tolist()) == {3, 7, 450}
    # force graph path with cutoff 0
    idx2 = make(tmp_path / "graph", flatSearchCutoff=0)
    idx2.add_batch(np.arange(500), vecs)
    allow2 = Bitmap(np.arange(0, 500, 2))
    ids2, _ = idx2.search_by_vector(vecs[0], 10, allow2)
    assert len(ids2) > 0 and all(i % 2 == 0 for i in ids2.tolist())


def test_persistence_snapshot_and_delta(tmp_path, rng):
    p = tmp_path / "shard"
    vecs = rng.standard_normal((300, 8)).astype(np.float32)
    idx = make(p)
    idx.add_batch(np.arange(200), vecs[:200])
    idx.flush()  # snapshot + truncate log
    idx.add_batch(np.arange(200, 300), vecs[200:])  # delta in log only
    idx.delete(0)
    idx._log.flush()
    # simulate crash: no shutdown, reopen
    idx2 = make(p)
    assert len(idx2) == 299
    ids, _ = idx2.search_by_vector(vecs[250], 1)
    assert ids[0] == 250
    ids, _ = idx2.search_by_vector(vecs[0], 3)
    assert 0 not in ids.tolist()


def test_search_by_vector_distance(tmp_path, rng):
    idx = make(tmp_path)
    vecs = rng.standard_normal((200, 4)).astype(np.float32)
    idx.add_batch(np.arange(200), vecs)
    ids, dists = idx.search_by_vector_distance(vecs[0], 0.5, 100)
    assert (dists <= 0.5).all()


def test_manhattan_rejected(tmp_path):
    with pytest.raises(vi.ConfigValidationError):
        make(tmp_path, vi.DISTANCE_MANHATTAN)
