"""Native HNSW engine: recall, deletes, filters, persistence.

Models the reference's recall fixture test (hnsw/recall_test.go:32 —
recall >= 0.99 at ef sweep) and persistence/delete integration tests."""

import numpy as np
import pytest

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.hnsw import HnswIndex
from weaviate_tpu.storage.bitmap import Bitmap


def make(tmp_path, metric=vi.DISTANCE_L2, **kw):
    cfg = vi.HnswUserConfig.from_dict({"distance": metric, **kw}, "hnsw")
    return HnswIndex(cfg, str(tmp_path))


def brute(vecs, q, k, metric):
    from weaviate_tpu.ops.distances import single_distance

    d = np.array([single_distance(q, v, metric) for v in vecs])
    order = np.argsort(d, kind="stable")[:k]
    return order


@pytest.mark.parametrize("metric", [vi.DISTANCE_L2, vi.DISTANCE_COSINE])
def test_recall_099(tmp_path, rng, metric):
    n, d, k, nq = 4000, 32, 10, 50
    vecs = rng.standard_normal((n, d)).astype(np.float32)
    idx = make(tmp_path / metric, metric, efConstruction=128, maxConnections=16)
    idx.add_batch(np.arange(n), vecs)
    queries = rng.standard_normal((nq, d)).astype(np.float32)
    hits = 0
    for q in queries:
        ids, _ = idx.search_by_vector(q, k)
        want = set(brute(vecs, q, k, metric).tolist())
        hits += len(want & set(ids.tolist()))
    recall = hits / (nq * k)
    assert recall >= 0.99, f"recall {recall}"


def test_batch_search(tmp_path, rng):
    n, d = 1000, 16
    vecs = rng.standard_normal((n, d)).astype(np.float32)
    idx = make(tmp_path)
    idx.add_batch(np.arange(n), vecs)
    qs = vecs[:5]
    ids, dists = idx.search_by_vectors(qs, 3)
    assert ids.shape == (5, 3)
    for i in range(5):
        assert ids[i][0] == i
        assert dists[i][0] < 1e-4


def test_delete_and_entrypoint_move(tmp_path, rng):
    idx = make(tmp_path)
    vecs = rng.standard_normal((200, 8)).astype(np.float32)
    idx.add_batch(np.arange(200), vecs)
    idx.delete(*range(100))
    assert len(idx) == 100
    ids, _ = idx.search_by_vector(vecs[150], 10)
    assert ids[0] == 150
    assert all(i >= 100 for i in ids.tolist())


def test_readd_replaces(tmp_path, rng):
    idx = make(tmp_path)
    idx.add(5, np.ones(8, np.float32))
    idx.add(5, -np.ones(8, np.float32))
    assert len(idx) == 1
    ids, dists = idx.search_by_vector(-np.ones(8, np.float32), 1)
    assert ids[0] == 5 and dists[0] < 1e-5


def test_allowlist_flat_and_graph(tmp_path, rng):
    vecs = rng.standard_normal((500, 8)).astype(np.float32)
    # small allowList -> flat path
    idx = make(tmp_path / "flat")
    idx.add_batch(np.arange(500), vecs)
    allow = Bitmap([3, 7, 450])
    ids, _ = idx.search_by_vector(vecs[0], 10, allow)
    assert set(ids.tolist()) == {3, 7, 450}
    # force graph path with cutoff 0
    idx2 = make(tmp_path / "graph", flatSearchCutoff=0)
    idx2.add_batch(np.arange(500), vecs)
    allow2 = Bitmap(np.arange(0, 500, 2))
    ids2, _ = idx2.search_by_vector(vecs[0], 10, allow2)
    assert len(ids2) > 0 and all(i % 2 == 0 for i in ids2.tolist())


def test_persistence_snapshot_and_delta(tmp_path, rng):
    p = tmp_path / "shard"
    vecs = rng.standard_normal((300, 8)).astype(np.float32)
    idx = make(p)
    idx.add_batch(np.arange(200), vecs[:200])
    idx.flush()  # snapshot + truncate log
    idx.add_batch(np.arange(200, 300), vecs[200:])  # delta in log only
    idx.delete(0)
    idx._log.flush()
    # simulate crash: no shutdown, reopen
    idx2 = make(p)
    assert len(idx2) == 299
    ids, _ = idx2.search_by_vector(vecs[250], 1)
    assert ids[0] == 250
    ids, _ = idx2.search_by_vector(vecs[0], 3)
    assert 0 not in ids.tolist()


def test_search_by_vector_distance(tmp_path, rng):
    idx = make(tmp_path)
    vecs = rng.standard_normal((200, 4)).astype(np.float32)
    idx.add_batch(np.arange(200), vecs)
    ids, dists = idx.search_by_vector_distance(vecs[0], 0.5, 100)
    assert (dists <= 0.5).all()


def test_manhattan_rejected(tmp_path):
    with pytest.raises(vi.ConfigValidationError):
        make(tmp_path, vi.DISTANCE_MANHATTAN)


def test_tombstone_cleanup_churn(tmp_path, rng):
    """delete.go:177-422 parity: after delete-heavy churn + cleanup, node
    count shrinks back (memory reclaimed), recall stays high, and deleted
    docs never resurface."""
    n, d, k = 3000, 24, 10
    idx = make(tmp_path, efConstruction=64, maxConnections=16)
    idx._CLEANUP_MIN_TOMBS = 10**9  # exercise the EXPLICIT cycle here
    vecs = rng.standard_normal((n, d)).astype(np.float32)
    idx.add_batch(np.arange(n), vecs)
    n_phys_initial = idx.node_count()
    assert n_phys_initial == n

    # churn: delete 60%, in several waves with interleaved re-adds
    deleted = set()
    for wave in range(3):
        victims = rng.choice(
            [i for i in range(n) if i not in deleted], size=600, replace=False
        )
        idx.delete(*victims.tolist())
        deleted.update(int(v) for v in victims)
        # interleave some fresh inserts so cleanup runs on a live graph
        fresh = rng.standard_normal((100, d)).astype(np.float32)
        base = n + wave * 100
        idx.add_batch(np.arange(base, base + 100), fresh)
        vecs = np.concatenate([vecs, fresh])

    removed = idx.cleanup_tombstones()
    assert removed > 0
    live = len(idx)
    assert idx.node_count() == live  # every tombstone physically gone
    assert live == n + 300 - len(deleted)

    # recall over the surviving set stays high after the repair
    live_ids = np.array(
        [i for i in range(vecs.shape[0]) if i not in deleted], dtype=np.int64
    )
    live_vecs = vecs[live_ids]
    queries = rng.standard_normal((40, d)).astype(np.float32)
    hits = 0
    for q in queries:
        ids, _ = idx.search_by_vector(q, k)
        assert not (set(int(x) for x in ids) & deleted)  # no resurrections
        dd = ((live_vecs - q) ** 2).sum(1)
        want = set(live_ids[np.argsort(dd)[:k]].tolist())
        hits += len(want & set(int(x) for x in ids))
    recall = hits / (len(queries) * k)
    assert recall >= 0.95, recall

    # the index keeps working for inserts + searches after compaction
    idx.add(99_999, vecs[0])
    ids, dists = idx.search_by_vector(vecs[0], 2)
    assert 99_999 in set(int(x) for x in ids)


def _wait_cleanup(idx, want_phys, timeout=10.0):
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        if idx.node_count() <= want_phys:
            return
        time.sleep(0.02)
    raise AssertionError(f"cleanup never ran: phys={idx.node_count()}")


def test_cleanup_auto_trigger(tmp_path, rng):
    """Crossing the tombstone threshold kicks the background cycle."""
    idx = make(tmp_path, efConstruction=32, maxConnections=8)
    idx._CLEANUP_MIN_TOMBS = 50  # shrink the threshold for the test
    vecs = rng.standard_normal((300, 8)).astype(np.float32)
    idx.add_batch(np.arange(300), vecs)
    idx.delete(*range(200))  # 200 tombs > max(50, live=100)
    _wait_cleanup(idx, 100)  # background cycle reclaims the nodes
    assert len(idx) == 100


def test_cleanup_all_deleted(tmp_path, rng):
    idx = make(tmp_path)
    vecs = rng.standard_normal((50, 8)).astype(np.float32)
    idx.add_batch(np.arange(50), vecs)
    idx.delete(*range(50))
    idx.cleanup_tombstones()
    assert idx.node_count() == 0 and len(idx) == 0
    ids, _ = idx.search_by_vector(vecs[0], 5)
    assert len(ids) == 0
    # and it accepts new data afterwards
    idx.add_batch(np.arange(100, 110), vecs[:10])
    ids, dists = idx.search_by_vector(vecs[3], 1)
    assert ids[0] == 103 and dists[0] < 1e-5


def test_cleanup_triggers_on_readd_churn(tmp_path, rng):
    """Regression: update-heavy workloads (re-adds tombstone old nodes
    without any delete() call) must still trigger the cleanup cycle, or
    physical node count grows without bound."""
    idx = make(tmp_path, efConstruction=32, maxConnections=8)
    idx._CLEANUP_MIN_TOMBS = 64
    base = rng.standard_normal((100, 8)).astype(np.float32)
    idx.add_batch(np.arange(100), base)
    for round_i in range(5):
        idx.add_batch(np.arange(100), base + 0.01 * (round_i + 1))
    assert len(idx) == 100
    # 500 updates => 500 tombstones without cleanup; bounded with it
    _wait_cleanup(idx, 100 + 200)
    ids, dists = idx.search_by_vector(base[7] + 0.05, 1)
    assert ids[0] == 7
