"""Inverted index: analyzer, filters -> AllowList, BM25 ranking."""

import numpy as np
import pytest

from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.inverted import BM25Searcher, FilterSearcher, InvertedIndex
from weaviate_tpu.inverted.analyzer import encode_float, encode_int, tokenize
from weaviate_tpu.storage.lsm import Store


@pytest.fixture
def class_def():
    return ClassDef(
        name="Article",
        properties=[
            Property(name="title", data_type=["text"]),
            Property(name="body", data_type=["text"]),
            Property(name="wordCount", data_type=["int"]),
            Property(name="rating", data_type=["number"]),
            Property(name="published", data_type=["boolean"]),
            Property(name="tags", data_type=["text[]"], tokenization="field"),
        ],
    )


@pytest.fixture
def indexed(tmp_path, class_def):
    store = Store(str(tmp_path / "lsm"))
    inv = InvertedIndex(store, class_def)
    docs = {
        1: {"title": "The quick brown fox", "body": "jumps over the lazy dog", "wordCount": 100, "rating": 4.5, "published": True, "tags": ["animals", "fables"]},
        2: {"title": "Fox hunting banned", "body": "the fox is safe now, fox fox", "wordCount": 250, "rating": 3.0, "published": True, "tags": ["news"]},
        3: {"title": "Python programming", "body": "snakes and code", "wordCount": 500, "rating": 5.0, "published": False, "tags": ["tech"]},
        4: {"title": "Quick pasta recipes", "body": "cook dinner fast", "wordCount": 80, "rating": 2.5, "published": True},
    }
    for d, props in docs.items():
        inv.add_object(d, props)
    return inv, docs


def F(d):
    return LocalFilter.from_dict(d)


def test_tokenizations():
    assert tokenize("word", "Hello, World-2000!") == ["hello", "world", "2000"]
    assert tokenize("lowercase", "Hello, World!") == ["hello,", "world!"]
    assert tokenize("whitespace", "Hello W") == ["Hello", "W"]
    assert tokenize("field", "  Hello World ") == ["Hello World"]


def test_sortable_encodings():
    assert encode_int(-5) < encode_int(0) < encode_int(3) < encode_int(1000)
    assert encode_float(-2.5) < encode_float(-0.1) < encode_float(0.0) < encode_float(7.25)


def test_filter_equal_text(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "Equal", "path": ["title"], "valueText": "fox"}))
    assert sorted(got) == [1, 2]


def test_filter_int_range(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "GreaterThan", "path": ["wordCount"], "valueInt": 100}))
    assert sorted(got) == [2, 3]
    got = s.doc_ids(F({"operator": "GreaterThanEqual", "path": ["wordCount"], "valueInt": 100}))
    assert sorted(got) == [1, 2, 3]
    got = s.doc_ids(F({"operator": "LessThan", "path": ["rating"], "valueNumber": 4.5}))
    assert sorted(got) == [2, 4]


def test_filter_bool_and_or_not(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    pub = {"operator": "Equal", "path": ["published"], "valueBoolean": True}
    fox = {"operator": "Equal", "path": ["title"], "valueText": "fox"}
    got = s.doc_ids(F({"operator": "And", "operands": [pub, fox]}))
    assert sorted(got) == [1, 2]
    got = s.doc_ids(F({"operator": "Or", "operands": [fox, {"operator": "Equal", "path": ["title"], "valueText": "python"}]}))
    assert sorted(got) == [1, 2, 3]
    got = s.doc_ids(F({"operator": "Not", "operands": [pub]}))
    assert sorted(got) == [3]
    got = s.doc_ids(F({"operator": "NotEqual", "path": ["published"], "valueBoolean": True}))
    assert sorted(got) == [3]


def test_filter_like(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "Like", "path": ["title"], "valueText": "qu?ck"}))
    assert sorted(got) == [1, 4]
    got = s.doc_ids(F({"operator": "Like", "path": ["tags"], "valueText": "fab*"}))
    assert sorted(got) == [1]


def test_filter_is_null(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "IsNull", "path": ["tags"], "valueBoolean": True}))
    assert sorted(got) == [4]
    got = s.doc_ids(F({"operator": "IsNull", "path": ["tags"], "valueBoolean": False}))
    assert sorted(got) == [1, 2, 3]


def test_filter_contains(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "ContainsAny", "path": ["tags"], "valueText": ["news", "tech"]}))
    assert sorted(got) == [2, 3]


def test_delete_object(indexed, class_def):
    inv, docs = indexed
    inv.delete_object(2, docs[2])
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "Equal", "path": ["title"], "valueText": "fox"}))
    assert sorted(got) == [1]
    assert inv.doc_count() == 3


def test_bm25_ranking(indexed, class_def):
    inv, _ = indexed
    bm = BM25Searcher(inv, class_def)
    res = bm.search("fox", 10)
    ids = [d for d, _, _ in res]
    assert set(ids) == {1, 2}
    # doc 2 mentions fox 4x across title+body -> higher score
    assert ids[0] == 2
    scores = [s for _, s, _ in res]
    assert scores == sorted(scores, reverse=True)


def test_bm25_properties_and_allowlist(indexed, class_def):
    from weaviate_tpu.storage.bitmap import Bitmap

    inv, _ = indexed
    bm = BM25Searcher(inv, class_def)
    res = bm.search("fox", 10, properties=["title"])
    assert {d for d, _, _ in res} == {1, 2}
    res = bm.search("fox", 10, allow_list=Bitmap([1]))
    assert [d for d, _, _ in res] == [1]


def test_bm25_explain(indexed, class_def):
    inv, _ = indexed
    bm = BM25Searcher(inv, class_def)
    res = bm.search("fox", 10, additional_explanations=True)
    assert res[0][2] is not None
    assert any("frequency" in k for k in res[0][2])


def test_persistence(tmp_path, class_def):
    store = Store(str(tmp_path / "lsm"))
    inv = InvertedIndex(store, class_def)
    inv.add_object(7, {"title": "hello world", "wordCount": 9})
    store.shutdown()
    store2 = Store(str(tmp_path / "lsm"))
    inv2 = InvertedIndex(store2, class_def)
    s = FilterSearcher(inv2, class_def)
    got = s.doc_ids(F({"operator": "Equal", "path": ["title"], "valueText": "hello"}))
    assert sorted(got) == [7]


def test_missing_filterable_backfill(tmp_path):
    """INDEX_MISSING_TEXT_FILTERABLE_AT_STARTUP analog: a prop imported with
    indexFilterable=false gains working where-filters after the startup
    reindexer backfills its roaring postings
    (inverted_reindexer_missing_text_filterable.go)."""
    import uuid as uuidlib

    import numpy as np

    from weaviate_tpu.db.db import DB
    from weaviate_tpu.entities.filters import LocalFilter
    from weaviate_tpu.entities.schema import ClassDef, Property
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.entities.vectorindex import parse_and_validate_config

    db = DB(str(tmp_path / "d"))
    cd = ClassDef(name="BF", properties=[
        Property(name="tag", data_type=["text"], index_filterable=False),
    ], vector_index_type="hnsw_tpu")
    idx = db.add_class(cd, parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"}))
    objs = [StorObj(class_name="BF", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"tag": f"t{i % 3}"},
                    vector=np.zeros(4, np.float32))
            for i in range(30)]
    assert all(e is None for e in idx.put_batch(objs))

    flt = LocalFilter.from_dict(
        {"operator": "Equal", "path": ["tag"], "valueText": "t1"})

    # flip the flag (operator edits the schema) -> buckets exist but empty
    cd.properties[0].index_filterable = True
    for shard in idx.shards.values():
        shard.inverted.update_schema(cd)
    empty = [o for s in idx.shards.values() for o in s.find_objects(flt)]
    assert empty == []  # postings missing: the filter silently matches nothing

    rebuilt = db.reindex_missing_filterable()
    assert rebuilt == {"BF": {"tag": 30}}

    hits = [o for s in idx.shards.values() for o in s.find_objects(flt)]
    assert {o.properties["tag"] for o in hits} == {"t1"}
    assert len(hits) == 10
    # second run is a no-op (detection sees populated buckets)
    assert db.reindex_missing_filterable() == {}
    db.shutdown()


def test_partial_filterable_backfill(tmp_path):
    """Flag flipped MID-LIFE: docs written after the flip are indexed live;
    the reindexer backfills exactly the pre-flip docs (per-doc detection,
    not all-or-nothing)."""
    import uuid as uuidlib

    import numpy as np

    from weaviate_tpu.db.db import DB
    from weaviate_tpu.entities.filters import LocalFilter
    from weaviate_tpu.entities.schema import ClassDef, Property
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.entities.vectorindex import parse_and_validate_config

    db = DB(str(tmp_path / "d"))
    cd = ClassDef(name="PBF", properties=[
        Property(name="tag", data_type=["text"], index_filterable=False),
    ], vector_index_type="hnsw_tpu", sharding_config={"desiredCount": 1})
    idx = db.add_class(cd, parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"}))

    def put(lo, hi):
        return idx.put_batch([
            StorObj(class_name="PBF", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"tag": f"t{i % 2}"}, vector=np.zeros(4, np.float32))
            for i in range(lo, hi)])

    assert all(e is None for e in put(0, 20))     # pre-flip: unindexed
    cd.properties[0].index_filterable = True
    for shard in idx.shards.values():
        shard.inverted.update_schema(cd)
    assert all(e is None for e in put(20, 30))    # post-flip: indexed live

    flt = LocalFilter.from_dict(
        {"operator": "Equal", "path": ["tag"], "valueText": "t1"})
    hits = [o for s in idx.shards.values() for o in s.find_objects(flt, False)]
    assert len(hits) == 5  # only post-flip docs match before the backfill

    rebuilt = db.reindex_missing_filterable()
    assert rebuilt == {"PBF": {"tag": 20}}  # exactly the pre-flip docs
    hits = [o for s in idx.shards.values() for o in s.find_objects(flt, False)]
    assert len(hits) == 15
    assert db.reindex_missing_filterable() == {}
    db.shutdown()
