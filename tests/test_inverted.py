"""Inverted index: analyzer, filters -> AllowList, BM25 ranking."""

import numpy as np
import pytest

from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.inverted import BM25Searcher, FilterSearcher, InvertedIndex
from weaviate_tpu.inverted.analyzer import encode_float, encode_int, tokenize
from weaviate_tpu.storage.lsm import Store


@pytest.fixture
def class_def():
    return ClassDef(
        name="Article",
        properties=[
            Property(name="title", data_type=["text"]),
            Property(name="body", data_type=["text"]),
            Property(name="wordCount", data_type=["int"]),
            Property(name="rating", data_type=["number"]),
            Property(name="published", data_type=["boolean"]),
            Property(name="tags", data_type=["text[]"], tokenization="field"),
        ],
    )


@pytest.fixture
def indexed(tmp_path, class_def):
    store = Store(str(tmp_path / "lsm"))
    inv = InvertedIndex(store, class_def)
    docs = {
        1: {"title": "The quick brown fox", "body": "jumps over the lazy dog", "wordCount": 100, "rating": 4.5, "published": True, "tags": ["animals", "fables"]},
        2: {"title": "Fox hunting banned", "body": "the fox is safe now, fox fox", "wordCount": 250, "rating": 3.0, "published": True, "tags": ["news"]},
        3: {"title": "Python programming", "body": "snakes and code", "wordCount": 500, "rating": 5.0, "published": False, "tags": ["tech"]},
        4: {"title": "Quick pasta recipes", "body": "cook dinner fast", "wordCount": 80, "rating": 2.5, "published": True},
    }
    for d, props in docs.items():
        inv.add_object(d, props)
    return inv, docs


def F(d):
    return LocalFilter.from_dict(d)


def test_tokenizations():
    assert tokenize("word", "Hello, World-2000!") == ["hello", "world", "2000"]
    assert tokenize("lowercase", "Hello, World!") == ["hello,", "world!"]
    assert tokenize("whitespace", "Hello W") == ["Hello", "W"]
    assert tokenize("field", "  Hello World ") == ["Hello World"]


def test_sortable_encodings():
    assert encode_int(-5) < encode_int(0) < encode_int(3) < encode_int(1000)
    assert encode_float(-2.5) < encode_float(-0.1) < encode_float(0.0) < encode_float(7.25)


def test_filter_equal_text(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "Equal", "path": ["title"], "valueText": "fox"}))
    assert sorted(got) == [1, 2]


def test_filter_int_range(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "GreaterThan", "path": ["wordCount"], "valueInt": 100}))
    assert sorted(got) == [2, 3]
    got = s.doc_ids(F({"operator": "GreaterThanEqual", "path": ["wordCount"], "valueInt": 100}))
    assert sorted(got) == [1, 2, 3]
    got = s.doc_ids(F({"operator": "LessThan", "path": ["rating"], "valueNumber": 4.5}))
    assert sorted(got) == [2, 4]


def test_filter_bool_and_or_not(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    pub = {"operator": "Equal", "path": ["published"], "valueBoolean": True}
    fox = {"operator": "Equal", "path": ["title"], "valueText": "fox"}
    got = s.doc_ids(F({"operator": "And", "operands": [pub, fox]}))
    assert sorted(got) == [1, 2]
    got = s.doc_ids(F({"operator": "Or", "operands": [fox, {"operator": "Equal", "path": ["title"], "valueText": "python"}]}))
    assert sorted(got) == [1, 2, 3]
    got = s.doc_ids(F({"operator": "Not", "operands": [pub]}))
    assert sorted(got) == [3]
    got = s.doc_ids(F({"operator": "NotEqual", "path": ["published"], "valueBoolean": True}))
    assert sorted(got) == [3]


def test_filter_like(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "Like", "path": ["title"], "valueText": "qu?ck"}))
    assert sorted(got) == [1, 4]
    got = s.doc_ids(F({"operator": "Like", "path": ["tags"], "valueText": "fab*"}))
    assert sorted(got) == [1]


def test_filter_is_null(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "IsNull", "path": ["tags"], "valueBoolean": True}))
    assert sorted(got) == [4]
    got = s.doc_ids(F({"operator": "IsNull", "path": ["tags"], "valueBoolean": False}))
    assert sorted(got) == [1, 2, 3]


def test_filter_contains(indexed, class_def):
    inv, _ = indexed
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "ContainsAny", "path": ["tags"], "valueText": ["news", "tech"]}))
    assert sorted(got) == [2, 3]


def test_delete_object(indexed, class_def):
    inv, docs = indexed
    inv.delete_object(2, docs[2])
    s = FilterSearcher(inv, class_def)
    got = s.doc_ids(F({"operator": "Equal", "path": ["title"], "valueText": "fox"}))
    assert sorted(got) == [1]
    assert inv.doc_count() == 3


def test_bm25_ranking(indexed, class_def):
    inv, _ = indexed
    bm = BM25Searcher(inv, class_def)
    res = bm.search("fox", 10)
    ids = [d for d, _, _ in res]
    assert set(ids) == {1, 2}
    # doc 2 mentions fox 4x across title+body -> higher score
    assert ids[0] == 2
    scores = [s for _, s, _ in res]
    assert scores == sorted(scores, reverse=True)


def test_bm25_properties_and_allowlist(indexed, class_def):
    from weaviate_tpu.storage.bitmap import Bitmap

    inv, _ = indexed
    bm = BM25Searcher(inv, class_def)
    res = bm.search("fox", 10, properties=["title"])
    assert {d for d, _, _ in res} == {1, 2}
    res = bm.search("fox", 10, allow_list=Bitmap([1]))
    assert [d for d, _, _ in res] == [1]


def test_bm25_explain(indexed, class_def):
    inv, _ = indexed
    bm = BM25Searcher(inv, class_def)
    res = bm.search("fox", 10, additional_explanations=True)
    assert res[0][2] is not None
    assert any("frequency" in k for k in res[0][2])


def test_persistence(tmp_path, class_def):
    store = Store(str(tmp_path / "lsm"))
    inv = InvertedIndex(store, class_def)
    inv.add_object(7, {"title": "hello world", "wordCount": 9})
    store.shutdown()
    store2 = Store(str(tmp_path / "lsm"))
    inv2 = InvertedIndex(store2, class_def)
    s = FilterSearcher(inv2, class_def)
    got = s.doc_ids(F({"operator": "Equal", "path": ["title"], "valueText": "hello"}))
    assert sorted(got) == [7]
