"""OPQ rotation (TPU extension): learned orthogonal rotation before product
quantization (OPQ-NP, Ge et al. 2013). The reference's PQ segments the raw
dims; on correlated data that concentrates variance in few segments and
raw-ADC recall collapses. The rotation decorrelates segments — fitted once,
persisted with the codebook, applied to queries as one tiny device matmul
inside the jitted ADC paths."""

import numpy as np
import pytest

from weaviate_tpu.compress.pq import ProductQuantizer
from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.tpu import TpuVectorIndex

DIM = 32


def correlated_data(n=4000, dim=DIM, latent=6, seed=0):
    """Strongly cross-segment-correlated vectors: a low-rank mix + noise —
    the case plain dim-order segmentation quantizes worst."""
    rng = np.random.default_rng(seed)
    z = rng.standard_normal((n, latent)).astype(np.float32)
    mix = rng.standard_normal((latent, dim)).astype(np.float32)
    return z @ mix + 0.05 * rng.standard_normal((n, dim)).astype(np.float32)


def test_opq_rotation_orthogonal_and_persistent(tmp_path):
    data = correlated_data()
    pq = ProductQuantizer(DIM, 8, 16, vi.DISTANCE_L2,
                          rotation=vi.PQ_ROTATION_OPQ)
    pq.fit(data)
    r = pq.rotation_matrix
    assert r is not None and r.shape == (DIM, DIM)
    np.testing.assert_allclose(r @ r.T, np.eye(DIM), atol=1e-4)
    # encode/decode round-trip happens in the original space
    codes = pq.encode(data[:64])
    recon = pq.decode(codes)
    assert recon.shape == (64, DIM)
    # persistence carries the rotation; reload encodes identically
    p = str(tmp_path / "opq.npz")
    pq.save(p)
    pq2 = ProductQuantizer.load(p)
    assert pq2.rotation == vi.PQ_ROTATION_OPQ
    np.testing.assert_allclose(pq2.rotation_matrix, r, atol=1e-6)
    np.testing.assert_array_equal(pq2.encode(data[:64]), codes)


def test_opq_reduces_quantization_error():
    data = correlated_data(seed=3)
    plain = ProductQuantizer(DIM, 8, 16, vi.DISTANCE_L2)
    plain.fit(data)
    opq = ProductQuantizer(DIM, 8, 16, vi.DISTANCE_L2,
                           rotation=vi.PQ_ROTATION_OPQ)
    opq.fit(data)
    err_plain = np.mean((data - plain.decode(plain.encode(data))) ** 2)
    err_opq = np.mean((data - opq.decode(opq.encode(data))) ** 2)
    # the rotation exists to shrink exactly this; demand a real margin
    assert err_opq < 0.9 * err_plain, (err_opq, err_plain)


def test_opq_validation():
    with pytest.raises(vi.ConfigValidationError):
        ProductQuantizer(DIM, 8, 16, vi.DISTANCE_MANHATTAN,
                         rotation=vi.PQ_ROTATION_OPQ)
    with pytest.raises(vi.ConfigValidationError):
        ProductQuantizer(DIM, DIM, 16, vi.DISTANCE_L2,
                         encoder=vi.PQ_ENCODER_TILE,
                         rotation=vi.PQ_ROTATION_OPQ)
    with pytest.raises(vi.ConfigValidationError):
        ProductQuantizer(DIM, 8, 16, vi.DISTANCE_L2, rotation="spin")


def _codes_only_recall(tmp_path, name, rotation, data, queries):
    cfg = vi.HnswUserConfig.from_dict(
        {"distance": "l2-squared",
         "pq": {"enabled": True, "segments": 8, "centroids": 16,
                "rescore": False, "rotation": rotation}}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, str(tmp_path / name), persist=False)
    idx.add_batch(np.arange(len(data)), data)
    idx.flush()
    assert idx.compressed
    ids, _ = idx.search_by_vectors(queries, 10)
    assert idx._pqg_state._gmin_validated  # fused kernel served
    d = ((queries[:, None, :] - data[None, :, :]) ** 2).sum(-1)
    want = np.argsort(d, axis=1)[:, :10]
    hits = sum(len(set(ids[i].tolist()) & set(want[i].tolist()))
               for i in range(len(queries)))
    idx.drop()
    return hits / (len(queries) * 10)


def test_opq_codes_only_recall_beats_plain(tmp_path, rng):
    """End to end through the fused codes kernel: OPQ must beat plain PQ
    recall on correlated data (the whole point of the rotation)."""
    data = correlated_data(seed=7)
    queries = data[:16] + 0.01 * rng.standard_normal((16, DIM)).astype(np.float32)
    rec_plain = _codes_only_recall(tmp_path, "plain", "none", data, queries)
    rec_opq = _codes_only_recall(tmp_path, "opq", "opq", data, queries)
    assert rec_opq >= rec_plain, (rec_opq, rec_plain)
    assert rec_opq >= 0.5, rec_opq


def test_opq_restart_serves_from_persisted_rotation(tmp_path, rng):
    data = correlated_data(seed=11, n=1500)
    cfg = vi.HnswUserConfig.from_dict(
        {"distance": "l2-squared",
         "pq": {"enabled": True, "segments": 8, "centroids": 16,
                "rescore": False, "rotation": "opq"}}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, str(tmp_path / "r"), persist=True)
    idx.add_batch(np.arange(len(data)), data)
    idx.flush()
    q = data[:8]
    ids_ref, d_ref = idx.search_by_vectors(q, 3)
    idx.shutdown()

    idx2 = TpuVectorIndex(cfg, str(tmp_path / "r"), persist=True)
    idx2.post_startup()
    assert idx2.compressed and idx2._pq.rotation_matrix is not None
    ids2, d2 = idx2.search_by_vectors(q, 3)
    np.testing.assert_array_equal(ids2, ids_ref)
    np.testing.assert_allclose(d2, d_ref, rtol=1e-3, atol=1e-3)
    idx2.drop()


def test_opq_mesh_codes_only(tmp_path, rng):
    """The mesh codes kernel applies the same rotation per shard."""
    from weaviate_tpu.entities.vectorindex import parse_and_validate_config
    from weaviate_tpu.index.mesh import MeshVectorIndex

    data = correlated_data(seed=13, n=2000, dim=16)
    config = parse_and_validate_config(
        "hnsw_tpu_mesh", {"distance": "l2-squared"})
    idx = MeshVectorIndex(config, str(tmp_path / "m"),
                          initial_capacity_per_shard=1024)
    idx.add_batch(np.arange(len(data)), data)
    idx.update_user_config(parse_and_validate_config(
        "hnsw_tpu_mesh",
        {"distance": "l2-squared",
         "pq": {"enabled": True, "segments": 8, "centroids": 16,
                "rescore": False, "rotation": "opq"}}))
    assert idx.compressed and idx._pq.rotation_matrix is not None
    q = data[:8] + 0.001 * rng.standard_normal((8, 16)).astype(np.float32)
    ids, d = idx.search_by_vectors(q, 3)
    assert idx._pqg_state._gmin_validated
    for i in range(8):
        assert int(ids[i][0]) == i, (i, ids[i])
