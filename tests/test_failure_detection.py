"""Failure detection: disk-pressure READONLY automation + phase metrics.

Reference: entities/storagestate / shard_status.go (READONLY on disk
pressure) and shard_read.go:236-287 (filtered-vector phase instrumentation).
"""

import uuid as uuidlib
from collections import namedtuple

import numpy as np
import pytest

from weaviate_tpu.db import DB
from weaviate_tpu.db.shard import ShardReadOnlyError
from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.monitoring.disk import DiskMonitor

Usage = namedtuple("Usage", "total used free")


def make_db_with_data(tmp_path, metrics=None):
    db = DB(str(tmp_path / "data"), metrics=metrics)
    cd = ClassDef(name="D", properties=[
        Property(name="t", data_type=["text"]),
        Property(name="n", data_type=["int"])])
    idx = db.add_class(cd, parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"}))
    rng = np.random.default_rng(1)
    idx.put_batch([
        StorObj(class_name="D", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"t": f"x{i}", "n": i},
                vector=rng.standard_normal(4).astype(np.float32))
        for i in range(20)
    ])
    return db, idx


def test_disk_pressure_flips_readonly(tmp_path, monkeypatch):
    db, idx = make_db_with_data(tmp_path)
    try:
        mon = DiskMonitor(db, warning_pct=80, readonly_pct=90, interval=9999)
        monkeypatch.setattr(
            "weaviate_tpu.monitoring.disk.shutil.disk_usage",
            lambda p: Usage(100, 85, 15))
        mon.check_once()  # warning zone: still writable
        assert all(s.status == "READY" for s in idx.shards.values())

        monkeypatch.setattr(
            "weaviate_tpu.monitoring.disk.shutil.disk_usage",
            lambda p: Usage(100, 95, 5))
        mon.check_once()
        assert all(s.status == "READONLY" for s in idx.shards.values())
        assert mon.readonly_triggered
        with pytest.raises(Exception) as ei:
            idx.put_object(StorObj(class_name="D", uuid=str(uuidlib.uuid4()),
                                   properties={"t": "nope"}))
        assert isinstance(ei.value, ShardReadOnlyError)
        # reads still work
        res = idx.object_search(5)
        assert len(res) == 5

        # operator re-activation (shard status update API semantics)
        for s in idx.shards.values():
            s.set_status("READY")
        idx.put_object(StorObj(class_name="D", uuid=str(uuidlib.uuid4()),
                               properties={"t": "ok"}))
    finally:
        db.shutdown()


def test_filtered_search_phase_metrics(tmp_path):
    from weaviate_tpu.monitoring import Metrics

    m = Metrics()
    db, idx = make_db_with_data(tmp_path, metrics=m)
    try:
        flt = LocalFilter.from_dict(
            {"operator": "LessThan", "path": ["n"], "valueInt": 10})
        q = np.random.default_rng(2).standard_normal((1, 4)).astype(np.float32)
        idx.object_vector_search(q, k=3, flt=flt)
        text = m.expose().decode()
        assert "weaviate_filtered_vector_filter_durations_ms_count" in text
        assert "weaviate_filtered_vector_search_durations_ms_count" in text
        assert "weaviate_filtered_vector_objects_durations_ms_count" in text
        assert 'weaviate_vector_index_operations_total{class_name="D"' in text
    finally:
        db.shutdown()
