"""Failure detection: disk-pressure READONLY automation + phase metrics.

Reference: entities/storagestate / shard_status.go (READONLY on disk
pressure) and shard_read.go:236-287 (filtered-vector phase instrumentation).
"""

import uuid as uuidlib
from collections import namedtuple

import numpy as np
import pytest

from weaviate_tpu.db import DB
from weaviate_tpu.db.shard import ShardReadOnlyError
from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.monitoring.disk import DiskMonitor

Usage = namedtuple("Usage", "total used free")


def make_db_with_data(tmp_path, metrics=None):
    db = DB(str(tmp_path / "data"), metrics=metrics)
    cd = ClassDef(name="D", properties=[
        Property(name="t", data_type=["text"]),
        Property(name="n", data_type=["int"])])
    idx = db.add_class(cd, parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"}))
    rng = np.random.default_rng(1)
    idx.put_batch([
        StorObj(class_name="D", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"t": f"x{i}", "n": i},
                vector=rng.standard_normal(4).astype(np.float32))
        for i in range(20)
    ])
    return db, idx


def _wait_until(pred, timeout=10.0, step=0.05):
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


def test_gossip_seed_join_propagates_cluster_wide():
    """memberlist-style auto-discovery (state.go:38): a node that joins with
    ONE seed address becomes visible to every member, and learns every
    member itself, via epidemic table exchange."""
    from weaviate_tpu.cluster.gossip import GossipTransport
    from weaviate_tpu.cluster.membership import ClusterState

    nodes = []
    try:
        for i in range(3):
            st = ClusterState(local_name=f"g{i}")
            g = GossipTransport(st, f"g{i}", f"127.0.0.1:9{i}00",
                                interval=0.1, suspect_after=1.0, dead_after=3.0)
            g.start()
            nodes.append((st, g))
        seed = nodes[0][1].gossip_addr
        # every newcomer knows ONLY the seed
        for _, g in nodes[1:]:
            g.join([seed])
        assert _wait_until(lambda: all(
            sorted(st.all_names()) == ["g0", "g1", "g2"] for st, _ in nodes)), \
            [st.all_names() for st, _ in nodes]
        # piggybacked metadata: every node resolves every data address
        for st, _ in nodes:
            assert st.node_address("g2") == "127.0.0.1:9200"
        assert all(st.cluster_health_score() == 0 for st, _ in nodes)
    finally:
        for st, g in nodes:
            g.shutdown()
            st.shutdown()


def test_gossip_partition_detection_and_recovery():
    """A partitioned node goes suspect -> not alive on the survivors (reads
    fail over), and its advancing heartbeat revives it when it returns."""
    from weaviate_tpu.cluster.gossip import GossipTransport
    from weaviate_tpu.cluster.membership import ClusterState

    nodes = []
    try:
        for i in range(3):
            st = ClusterState(local_name=f"p{i}")
            g = GossipTransport(st, f"p{i}", f"127.0.0.1:91{i}0",
                                interval=0.1, suspect_after=0.6, dead_after=30.0)
            g.start()
            nodes.append((st, g))
        for _, g in nodes[1:]:
            g.join([nodes[0][1].gossip_addr])
        assert _wait_until(lambda: all(
            len(st.all_names()) == 3 for st, _ in nodes))
        # partition p2: stop its gossip entirely (no heartbeats leave it)
        nodes[2][1].shutdown()
        assert _wait_until(
            lambda: not nodes[0][0].is_alive("p2")
            and not nodes[1][0].is_alive("p2")), "p2 never went suspect"
        assert nodes[0][0].cluster_health_score() == 1
        assert nodes[0][1].status("p2") in ("suspect", "dead")
        # p0/p1 keep trusting each other across the partition
        assert nodes[0][0].is_alive("p1") and nodes[1][0].is_alive("p0")

        # p2 returns with a fresh transport on the SAME identity: its table
        # restarts at hb=0, but its first merge learns the cluster's higher
        # hb for itself... the new instance gossips its own entry, and the
        # survivors revive it once its heartbeat advances past what they saw
        st2 = nodes[2][0]
        g2 = GossipTransport(st2, "p2", "127.0.0.1:9120",
                             interval=0.1, suspect_after=0.6, dead_after=30.0)
        g2.start()
        g2.join([nodes[0][1].gossip_addr])
        nodes[2] = (st2, g2)
        assert _wait_until(lambda: nodes[0][0].is_alive("p2")
                           and nodes[1][0].is_alive("p2")), "p2 never revived"
    finally:
        for st, g in nodes:
            g.shutdown()
            st.shutdown()


def test_disk_pressure_flips_readonly(tmp_path, monkeypatch):
    db, idx = make_db_with_data(tmp_path)
    try:
        mon = DiskMonitor(db, warning_pct=80, readonly_pct=90, interval=9999)
        monkeypatch.setattr(
            "weaviate_tpu.monitoring.disk.shutil.disk_usage",
            lambda p: Usage(100, 85, 15))
        mon.check_once()  # warning zone: still writable
        assert all(s.status == "READY" for s in idx.shards.values())

        monkeypatch.setattr(
            "weaviate_tpu.monitoring.disk.shutil.disk_usage",
            lambda p: Usage(100, 95, 5))
        mon.check_once()
        assert all(s.status == "READONLY" for s in idx.shards.values())
        assert mon.readonly_triggered
        with pytest.raises(Exception) as ei:
            idx.put_object(StorObj(class_name="D", uuid=str(uuidlib.uuid4()),
                                   properties={"t": "nope"}))
        assert isinstance(ei.value, ShardReadOnlyError)
        # reads still work
        res = idx.object_search(5)
        assert len(res) == 5

        # operator re-activation (shard status update API semantics)
        for s in idx.shards.values():
            s.set_status("READY")
        idx.put_object(StorObj(class_name="D", uuid=str(uuidlib.uuid4()),
                               properties={"t": "ok"}))
    finally:
        db.shutdown()


def test_filtered_search_phase_metrics(tmp_path):
    from weaviate_tpu.monitoring import Metrics

    m = Metrics()
    db, idx = make_db_with_data(tmp_path, metrics=m)
    try:
        flt = LocalFilter.from_dict(
            {"operator": "LessThan", "path": ["n"], "valueInt": 10})
        q = np.random.default_rng(2).standard_normal((1, 4)).astype(np.float32)
        idx.object_vector_search(q, k=3, flt=flt)
        text = m.expose().decode()
        assert "weaviate_filtered_vector_filter_durations_ms_count" in text
        assert "weaviate_filtered_vector_search_durations_ms_count" in text
        assert "weaviate_filtered_vector_objects_durations_ms_count" in text
        assert 'weaviate_vector_index_operations_total{class_name="D"' in text
    finally:
        db.shutdown()
