"""Module system: provider dispatch, local vectorizer, nearText end-to-end
(GraphQL + gRPC fake sidecar), ref2vec-centroid, backup backend.

Reference test model: usecases/modules tests + text2vec-contextionary
client tests (with a fake gRPC server instead of a real sidecar).
"""

import json
import uuid as uuidlib
from concurrent import futures

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.modules import ModuleError, Provider, build_provider
from weaviate_tpu.modules.text2vec_local import LocalTextVectorizer
from weaviate_tpu.server import App, RestServer


def make_class(vectorizer="text2vec-local"):
    return ClassDef(
        name="Doc",
        properties=[
            Property(name="title", data_type=["text"]),
            Property(name="body", data_type=["text"]),
            Property(name="count", data_type=["int"]),
        ],
        vectorizer=vectorizer,
        vector_index_type="hnsw_tpu",
        vector_index_config={"distance": "cosine"},
    )


def test_local_vectorizer_semantics():
    v = LocalTextVectorizer()
    vecs = v.vectorize_text([
        "quantum computing hardware",
        "quantum computing research",
        "banana bread recipe",
    ])
    sim_close = float(vecs[0] @ vecs[1])
    sim_far = float(vecs[0] @ vecs[2])
    assert sim_close > sim_far + 0.2  # token overlap => closer
    # determinism across instances
    v2 = LocalTextVectorizer()
    np.testing.assert_allclose(v2.vectorize_text(["quantum computing hardware"])[0], vecs[0])


def test_provider_vectorize_object_and_query():
    p = Provider()
    p.register(LocalTextVectorizer())
    cd = make_class()
    from weaviate_tpu.entities.storobj import StorObj

    obj = StorObj(class_name="Doc", uuid=str(uuidlib.uuid4()),
                  properties={"title": "quantum computing", "body": "qubits", "count": 3})
    vec = p.vectorize_object(cd, obj)
    assert vec is not None and vec.shape == (256,)

    qv = p.vectorize_query(cd, {"concepts": ["quantum computing qubits"]})
    assert float(qv @ vec) > 0.3  # query near the object it describes

    # moveTo pulls the query toward a concept
    base = p.vectorize_query(cd, {"concepts": ["quantum"]})
    moved = p.vectorize_query(cd, {"concepts": ["quantum"],
                                   "moveTo": {"concepts": ["banana"], "force": 0.8}})
    banana = p.vectorize_query(cd, {"concepts": ["banana"]})
    assert float(moved @ banana) > float(base @ banana)

    # moveAwayFrom pushes it away
    away = p.vectorize_query(cd, {"concepts": ["quantum"],
                                  "moveAwayFrom": {"concepts": ["banana"], "force": 0.8}})
    assert float(away @ banana) < float(base @ banana)


def test_provider_errors():
    p = Provider()
    cd = make_class(vectorizer="text2vec-local")
    with pytest.raises(ModuleError):
        p.vectorize_query(cd, {"concepts": ["x"]})  # module not enabled
    p.register(LocalTextVectorizer())
    with pytest.raises(ModuleError):
        p.vectorize_query(cd, {})  # no concepts


def test_build_provider_unknown_module():
    c = Config()
    c.enable_modules = ["no-such-module"]
    with pytest.raises(ModuleError):
        build_provider(c)


@pytest.fixture(scope="module")
def neartext_app(tmp_path_factory):
    c = Config()
    c.enable_modules = ["text2vec-local"]
    c.default_vectorizer_module = "text2vec-local"
    app = App(config=c, data_path=str(tmp_path_factory.mktemp("moddata")))
    srv = RestServer(app, port=0)
    srv.start()
    yield app, srv
    srv.stop()
    app.shutdown()


def _req(port, method, path, body=None):
    import urllib.error
    import urllib.request

    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(f"http://127.0.0.1:{port}{path}", data=data, method=method)
    r.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None


def test_neartext_end_to_end(neartext_app):
    """Import WITHOUT vectors (module vectorizes at import), then nearText
    retrieves by meaning — the full journey the reference runs against a
    contextionary container, with zero external services."""
    app, srv = neartext_app
    st, _ = _req(srv.port, "POST", "/v1/schema", {
        "class": "Doc",
        "vectorizer": "text2vec-local",
        "vectorIndexConfig": {"distance": "cosine"},
        "properties": [{"name": "title", "dataType": ["text"]},
                       {"name": "body", "dataType": ["text"]}],
    })
    assert st == 200
    docs = [
        ("quantum computing breakthrough", "qubits entanglement superposition"),
        ("quantum hardware scaling", "qubit error correction"),
        ("sourdough bread baking", "flour water salt yeast"),
        ("marathon training plan", "running endurance intervals"),
    ]
    payloads = [{"class": "Doc", "id": str(uuidlib.UUID(int=i + 1)),
                 "properties": {"title": t, "body": b}} for i, (t, b) in enumerate(docs)]
    st, out = _req(srv.port, "POST", "/v1/batch/objects", {"objects": payloads})
    assert st == 200 and all(o["result"]["status"] == "SUCCESS" for o in out)

    # objects got vectors at import
    st, got = _req(srv.port, "GET", f"/v1/objects/Doc/{payloads[0]['id']}?include=vector")
    assert st == 200 and len(got["vector"]) == 256

    q = '{ Get { Doc(nearText: {concepts: ["quantum qubits"]}, limit: 2) { title _additional { distance } } } }'
    st, res = _req(srv.port, "POST", "/v1/graphql", {"query": q})
    assert st == 200, res
    hits = res["data"]["Get"]["Doc"]
    assert len(hits) == 2
    titles = {h["title"] for h in hits}
    assert titles == {"quantum computing breakthrough", "quantum hardware scaling"}

    # bread query finds bread
    q2 = '{ Get { Doc(nearText: {concepts: ["bread flour baking"]}, limit: 1) { title } } }'
    st, res2 = _req(srv.port, "POST", "/v1/graphql", {"query": q2})
    assert res2["data"]["Get"]["Doc"][0]["title"] == "sourdough bread baking"


def test_module_extension_endpoints(neartext_app):
    """/v1/modules/text2vec-local/* user-facing extensions (the reference's
    text2vec-contextionary extensions/rest_user_facing.go + concepts/rest.go
    surface): store a custom concept, then USE it — nearText with the new
    concept must retrieve by the concept's definition."""
    app, srv = neartext_app
    # the fixture's Doc class may already hold the bread/quantum docs from
    # the previous test — add one doc the custom concept should find
    _req(srv.port, "POST", "/v1/schema", {
        "class": "ExtDoc", "vectorizer": "text2vec-local",
        "vectorIndexConfig": {"distance": "cosine"},
        "properties": [{"name": "title", "dataType": ["text"]},
                       {"name": "body", "dataType": ["text"]}],
    })
    payloads = [
        {"class": "ExtDoc", "id": str(uuidlib.UUID(int=101)),
         "properties": {"title": "element post",
                        "body": "a naturally occurring element seen by programmers"}},
        {"class": "ExtDoc", "id": str(uuidlib.UUID(int=102)),
         "properties": {"title": "cooking post",
                        "body": "flour water salt yeast oven"}},
    ]
    st, out = _req(srv.port, "POST", "/v1/batch/objects", {"objects": payloads})
    assert st == 200 and all(o["result"]["status"] == "SUCCESS" for o in out)

    # validation first: bad concept casing / missing definition / bad weight
    st, _ = _req(srv.port, "POST", "/v1/modules/text2vec-local/extensions",
                 {"concept": "FooBarium", "definition": "x", "weight": 1})
    assert st == 422
    st, _ = _req(srv.port, "POST", "/v1/modules/text2vec-local/extensions",
                 {"concept": "foobarium", "weight": 1})
    assert st == 422
    st, _ = _req(srv.port, "POST", "/v1/modules/text2vec-local/extensions",
                 {"concept": "foobarium", "definition": "x", "weight": 2})
    assert st == 422
    # a brand-new concept must be defined at weight 1
    st, _ = _req(srv.port, "POST", "/v1/modules/text2vec-local/extensions",
                 {"concept": "zzzconcept", "definition": "x", "weight": 0.5})
    assert st == 400

    st, ext = _req(srv.port, "POST", "/v1/modules/text2vec-local/extensions", {
        "concept": "foobarium",
        "definition": "a naturally occurring element seen by programmers",
        "weight": 1,
    })
    assert st == 200 and ext["concept"] == "foobarium"
    st, all_ext = _req(srv.port, "GET", "/v1/modules/text2vec-local/extensions")
    assert st == 200 and any(
        e["concept"] == "foobarium" for e in all_ext["extensions"])

    # USE the concept: nearText ["foobarium"] ranks the definition-matching
    # doc first even though no document contains the word itself
    q = '{ Get { ExtDoc(nearText: {concepts: ["foobarium"]}, limit: 1) { title } } }'
    st, res = _req(srv.port, "POST", "/v1/graphql", {"query": q})
    assert st == 200, res
    assert res["data"]["Get"]["ExtDoc"][0]["title"] == "element post", res

    # concepts introspection, incl. a percent-encoded compound concept
    st, info = _req(srv.port, "GET", "/v1/modules/text2vec-local/concepts/foobarium")
    assert st == 200
    assert info["individualWords"][0]["word"] == "foobarium"
    assert info["individualWords"][0]["info"]["custom"] is True
    st, _ = _req(srv.port, "POST", "/v1/modules/text2vec-local/extensions", {
        "concept": "machine learning",
        "definition": "statistical models trained from data", "weight": 1})
    assert st == 200
    st, info = _req(srv.port, "GET",
                    "/v1/modules/text2vec-local/concepts/machine%20learning")
    assert st == 200 and info["custom"] is True
    assert [w["word"] for w in info["individualWords"]] == ["machine", "learning"]

    # unknown module / module without a REST surface
    st, _ = _req(srv.port, "GET", "/v1/modules/nope/extensions")
    assert st == 404
    st, _ = _req(srv.port, "GET", "/v1/modules/text2vec-local/unknown")
    assert st == 404

    # meta reports the module
    st, meta = _req(srv.port, "GET", "/v1/meta")
    assert "text2vec-local" in meta["modules"]


def test_module_extensions_survive_restart(tmp_path):
    """Extensions persist (the reference's extensions-storage role): a
    restarted node keeps embedding the custom concept the way the already-
    imported vectors saw it."""
    from weaviate_tpu.config import Config

    c = Config()
    c.enable_modules = ["text2vec-local"]
    c.persistence.data_path = str(tmp_path / "data")
    app = App(config=c, data_path=str(tmp_path / "data"))
    srv = RestServer(app, port=0)
    srv.start()
    st, _ = _req(srv.port, "POST", "/v1/modules/text2vec-local/extensions", {
        "concept": "glorp", "definition": "distributed vector database",
        "weight": 1})
    assert st == 200
    vec_before = app.modules.get("text2vec-local").vectorize_text(["glorp"])[0]
    srv.stop()
    app.shutdown()

    c2 = Config()
    c2.enable_modules = ["text2vec-local"]
    c2.persistence.data_path = str(tmp_path / "data")
    app2 = App(config=c2, data_path=str(tmp_path / "data"))
    srv2 = RestServer(app2, port=0)
    srv2.start()
    try:
        st, all_ext = _req(srv2.port, "GET", "/v1/modules/text2vec-local/extensions")
        assert st == 200 and [e["concept"] for e in all_ext["extensions"]] == ["glorp"]
        vec_after = app2.modules.get("text2vec-local").vectorize_text(["glorp"])[0]
        np.testing.assert_array_equal(vec_before, vec_after)
    finally:
        srv2.stop()
        app2.shutdown()


def test_patch_revectorizes(neartext_app):
    """Regression: PATCHing text must recompute the module vector, or
    nearText keeps ranking the object by its pre-edit text."""
    app, srv = neartext_app
    uid = str(uuidlib.UUID(int=777))
    st, _ = _req(srv.port, "POST", "/v1/objects", {
        "class": "Doc", "id": uid,
        "properties": {"title": "quantum physics lecture", "body": "entanglement"},
    })
    assert st == 200
    st, before = _req(srv.port, "GET", f"/v1/objects/Doc/{uid}?include=vector")
    st, _ = _req(srv.port, "PATCH", f"/v1/objects/Doc/{uid}", {
        "class": "Doc", "properties": {"title": "chocolate cake dessert",
                                       "body": "sugar butter cocoa"}})
    st, after = _req(srv.port, "GET", f"/v1/objects/Doc/{uid}?include=vector")
    assert st == 200
    assert not np.allclose(before["vector"], after["vector"])
    # the edited object now answers dessert queries, not quantum ones
    q = '{ Get { Doc(nearText: {concepts: ["chocolate dessert"]}, limit: 1) { _additional { id } } } }'
    st, res = _req(srv.port, "POST", "/v1/graphql", {"query": q})
    assert res["data"]["Get"]["Doc"][0]["_additional"]["id"] == uid
    _req(srv.port, "DELETE", f"/v1/objects/Doc/{uid}")


def test_disabled_vectorizer_rejected_at_class_creation(neartext_app):
    app, srv = neartext_app
    st, body = _req(srv.port, "POST", "/v1/schema", {
        "class": "Bad", "vectorizer": "text2vec-typo",
        "properties": [{"name": "t", "dataType": ["text"]}],
    })
    assert st == 422
    assert "not an enabled module" in json.dumps(body)


def test_contextionary_grpc_client(tmp_path):
    """Drive the gRPC sidecar client against an in-process fake vectorizer
    service (the contextionary dial pattern, client/contextionary.go:41)."""
    import grpc

    from weaviate_tpu.modules import contextionary_pb2 as pb
    from weaviate_tpu.modules.text2vec_contextionary import (
        _SERVICE,
        ContextionaryVectorizer,
    )

    local = LocalTextVectorizer(dim=64)

    def vectorize(request, context):
        vecs = local.vectorize_text(list(request.texts))
        return pb.VectorizeReply(
            vectors=[pb.Vector(values=v.tolist()) for v in vecs]
        )

    def meta(request, context):
        return pb.MetaReply(version="fake-1.0", word_count=1000, dimensions=64)

    handlers = {
        "Vectorize": grpc.unary_unary_rpc_method_handler(
            vectorize,
            request_deserializer=pb.VectorizeRequest.FromString,
            response_serializer=pb.VectorizeReply.SerializeToString,
        ),
        "Meta": grpc.unary_unary_rpc_method_handler(
            meta,
            request_deserializer=pb.MetaRequest.FromString,
            response_serializer=pb.MetaReply.SerializeToString,
        ),
    }
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE.strip("/"), handlers),)
    )
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        mod = ContextionaryVectorizer(url=f"127.0.0.1:{port}")
        vecs = mod.vectorize_text(["quantum computing", "bread"])
        assert vecs.shape == (2, 64)
        want = local.vectorize_text(["quantum computing"])[0]
        np.testing.assert_allclose(vecs[0], want, rtol=1e-6)
        assert mod.meta()["version"] == "fake-1.0"
        cd = make_class(vectorizer="text2vec-contextionary")
        from weaviate_tpu.entities.storobj import StorObj

        obj = StorObj(class_name="Doc", uuid=str(uuidlib.uuid4()),
                      properties={"title": "hello world"})
        assert mod.vectorize_object(cd, obj, {}).shape == (64,)
        mod.shutdown()
    finally:
        server.stop(0)


def test_ref2vec_centroid(tmp_path):
    from weaviate_tpu.db import DB
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.entities.vectorindex import parse_and_validate_config
    from weaviate_tpu.modules.ref2vec_centroid import Ref2VecCentroid

    db = DB(str(tmp_path / "data"))
    target_cls = ClassDef(name="Item", properties=[Property(name="t", data_type=["text"])],
                          vector_index_type="hnsw_tpu")
    idx = db.add_class(target_cls, parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"}))
    u1, u2 = str(uuidlib.UUID(int=1)), str(uuidlib.UUID(int=2))
    idx.put_object(StorObj(class_name="Item", uuid=u1, properties={"t": "a"},
                           vector=np.array([1, 0, 0, 0], np.float32)))
    idx.put_object(StorObj(class_name="Item", uuid=u2, properties={"t": "b"},
                           vector=np.array([0, 1, 0, 0], np.float32)))

    mod = Ref2VecCentroid()
    mod.set_db(db)
    owner_cls = ClassDef(
        name="Owner",
        properties=[Property(name="items", data_type=["Item"])],
        vectorizer="ref2vec-centroid",
    )
    owner = StorObj(class_name="Owner", uuid=str(uuidlib.uuid4()), properties={
        "items": [{"beacon": f"weaviate://localhost/Item/{u1}"},
                  {"beacon": f"weaviate://localhost/Item/{u2}"}],
    })
    vec = mod.vectorize_object(owner_cls, owner, {})
    np.testing.assert_allclose(vec, [0.5, 0.5, 0, 0])
    db.shutdown()


def test_backup_fs_backend(tmp_path):
    from weaviate_tpu.modules.backup_fs import FilesystemBackupBackend

    be = FilesystemBackupBackend(str(tmp_path / "backups"))
    be.put_object("b1", "node-0/Doc/shard-0/vector.log", b"\x01\x02")
    assert be.get_object("b1", "node-0/Doc/shard-0/vector.log") == b"\x01\x02"
    be.write_meta("b1", {"status": "SUCCESS"})
    assert be.read_meta("b1")["status"] == "SUCCESS"
    assert be.read_meta("nope") is None
    with pytest.raises(ValueError):
        be.put_object("b1", "../escape", b"x")


# -- explanation additional props (explain.py) -------------------------------
# reference: modules/text2vec-contextionary/additional/{nearestneighbors,
# sempath, interpretation, projector}, payload shapes in additional/models


def _mk_results(vectorizer, texts):
    """SearchResult-shaped rows with module-vectorized objects."""
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.usecases.traverser import SearchResult

    rows = []
    for i, t in enumerate(texts):
        vec = vectorizer.vectorize_text([t])[0]
        obj = StorObj(class_name="Doc", uuid=str(uuidlib.UUID(int=i + 1)),
                      properties={"body": t}, vector=vec)
        rows.append(SearchResult(obj=obj, distance=0.1 * i))
    return rows


def test_explain_nearest_neighbors_and_interpretation():
    v = LocalTextVectorizer()
    results = _mk_results(v, [
        "quantum qubits entanglement physics",
        "bread flour yeast baking oven",
    ])
    nn = v.resolve_additional("nearestNeighbors", results, {"limit": 3})
    assert len(nn) == 2
    concepts0 = [x["concept"] for x in nn[0]["neighbors"]]
    assert len(concepts0) == 3
    # a quantum doc's nearest concepts come from its own wordlist, not bread's
    assert set(concepts0) <= {"quantum", "qubits", "entanglement", "physics"}
    assert nn[0]["neighbors"][0]["distance"] <= nn[0]["neighbors"][-1]["distance"]

    interp = v.resolve_additional("interpretation", results, {})
    src = interp[1]["source"]
    assert {s["concept"] for s in src} == {"bread", "flour", "yeast", "baking", "oven"}
    assert all(0.0 <= s["weight"] <= 1.0 and s["occurrence"] == 1 for s in src)


def test_explain_semantic_path_requires_neartext():
    from weaviate_tpu.modules.provider import ModuleError

    v = LocalTextVectorizer()
    results = _mk_results(v, ["quantum qubits computing"])
    with pytest.raises(ModuleError):
        v.resolve_additional("semanticPath", results, {})

    out = v.resolve_additional(
        "semanticPath", results, {"near_text": {"concepts": ["quantum physics"]}})
    path = out[0]["path"]
    assert len(path) >= 1
    for el in path:
        assert "concept" in el and "distanceToQuery" in el and "distanceToResult" in el
    # the walk moves toward the result: last element is closest to it
    assert path[-1]["distanceToResult"] <= path[0]["distanceToResult"] + 1e-6
    # neighbors in the path link distances both ways
    if len(path) > 1:
        assert "distanceToNext" in path[0] and "distanceToPrevious" in path[-1]


def test_explain_feature_projection_tsne():
    v = LocalTextVectorizer()
    # two tight clusters of texts -> the 2-D projection must separate them
    results = _mk_results(v, [
        "quantum qubits entanglement", "quantum qubits physics",
        "bread flour yeast", "bread flour oven",
    ])
    fp = v.resolve_additional("featureProjection", results, {"dimensions": 2})
    pts = np.array([x["vector"] for x in fp])
    assert pts.shape == (4, 2)
    import itertools

    def d(i, j):
        return float(np.linalg.norm(pts[i] - pts[j]))

    intra = max(d(0, 1), d(2, 3))
    inter = min(d(i, j) for i, j in itertools.product((0, 1), (2, 3)))
    assert inter > intra, (pts, intra, inter)
    # deterministic: same inputs, same layout
    fp2 = v.resolve_additional("featureProjection", results, {"dimensions": 2})
    np.testing.assert_allclose(pts, np.array([x["vector"] for x in fp2]))


def test_explain_props_graphql_e2e(neartext_app):
    """featureProjection + nearestNeighbors + semanticPath through the full
    GraphQL stack (vector fetch is triggered by the selection alone)."""
    app, srv = neartext_app
    _req(srv.port, "POST", "/v1/schema", {
        "class": "XDoc",
        "vectorizer": "text2vec-local",
        "vectorIndexConfig": {"distance": "cosine"},
        "properties": [{"name": "body", "dataType": ["text"]}],
    })
    payloads = [{"class": "XDoc", "id": str(uuidlib.UUID(int=100 + i)),
                 "properties": {"body": b}}
                for i, b in enumerate([
                    "quantum qubits entanglement computing",
                    "quantum hardware error correction",
                    "sourdough bread flour yeast",
                ])]
    st, out = _req(srv.port, "POST", "/v1/batch/objects", {"objects": payloads})
    assert st == 200

    q = ('{ Get { XDoc(nearText: {concepts: ["quantum"]}, limit: 3) { body '
         '_additional { nearestNeighbors { neighbors { concept distance } } '
         'semanticPath { path { concept distanceToQuery distanceToResult } } '
         'featureProjection(dimensions: 2) { vector } } } } }')
    st, res = _req(srv.port, "POST", "/v1/graphql", {"query": q})
    assert st == 200 and not res.get("errors"), res
    hits = res["data"]["Get"]["XDoc"]
    assert len(hits) == 3
    for h in hits:
        add = h["_additional"]
        assert add["nearestNeighbors"]["neighbors"]
        assert add["semanticPath"]["path"]
        assert len(add["featureProjection"]["vector"]) == 2


def test_neartext_aggregate(neartext_app):
    """Aggregate with nearText restricts the doc set via the module
    vectorizer (objectLimit semantics) instead of silently counting all."""
    app, srv = neartext_app
    _req(srv.port, "POST", "/v1/schema", {
        "class": "AggT", "vectorizer": "text2vec-local",
        "vectorIndexConfig": {"distance": "cosine"},
        "properties": [{"name": "body", "dataType": ["text"]}]})
    payloads = [{"class": "AggT", "id": str(uuidlib.UUID(int=200 + i)),
                 "properties": {"body": b}} for i, b in enumerate(
        ["quantum qubits", "quantum errors", "bread flour", "bread yeast", "running shoes"])]
    st, _ = _req(srv.port, "POST", "/v1/batch/objects", {"objects": payloads})
    assert st == 200
    q = ('{ Aggregate { AggT(nearText: {concepts: ["quantum"]}, objectLimit: 2) '
         '{ meta { count } } } }')
    st, res = _req(srv.port, "POST", "/v1/graphql", {"query": q})
    assert st == 200 and not res.get("errors"), res
    assert res["data"]["Aggregate"]["AggT"][0]["meta"]["count"] == 2
    # objectLimit required with nearText
    q2 = '{ Aggregate { AggT(nearText: {concepts: ["quantum"]}) { meta { count } } } }'
    st, res2 = _req(srv.port, "POST", "/v1/graphql", {"query": q2})
    assert res2.get("errors") and "objectLimit" in res2["errors"][0]["message"]
