"""graftsan runtime sanitizer tests (weaviate_tpu/testing/sanitizers.py).

Covers the three sanitizers against seeded bugs (an AB/BA deadlock shape,
a hierarchy inversion, a sync hidden behind a helper, a deliberately
leaked worker), the zero-cost disabled contract through a real served
search (the tracing spy idiom), GRAFTSAN config parsing, the
Condition-wait bookkeeping the coalescer depends on, and the
tools/graftsan CLI (hierarchy validation — the tier-1 form of
`--check-hierarchy` — and report rendering).

Tests that need an INSTALLED sanitizer swap their private instance into
the module global and restore the session's (if any) in finally — the
still-ours discipline keeps a GRAFTSAN=1 CI run and a bare local run both
green.
"""

import json
import os
import subprocess
import sys
import threading
import time
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.testing import sanitizers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, DIM, K = 64, 8, 3


def _mk_app(tmp_path):
    from weaviate_tpu.config import Config
    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.server import App

    cfg = Config()
    cfg.coalescer.enabled = True
    cfg.coalescer.window_ms = 10.0
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    app.schema.add_class({
        "class": "Sa", "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "tag", "dataType": ["text"]}],
    })
    rng = np.random.default_rng(7)
    vecs = rng.integers(-8, 8, (N, DIM)).astype(np.float32)
    idx = app.db.get_index("Sa")
    idx.put_batch([
        StorObj(class_name="Sa", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"tag": "t"}, vector=vecs[i])
        for i in range(N)])
    return app, idx, vecs


def _swap_in(san):
    """Install `san` as the module global; -> the previous one (None when
    the suite runs without GRAFTSAN)."""
    prev = sanitizers.get_sanitizer()
    if prev is not None:
        sanitizers.unconfigure(prev)
    sanitizers.configure(san)
    return prev


def _swap_back(san, prev):
    sanitizers.unconfigure(san)
    if prev is not None:
        sanitizers.configure(prev)


# -- GRAFTSAN config parsing --------------------------------------------------

def test_parse_graftsan_values():
    off = sanitizers.parse_graftsan
    assert off(None) == frozenset()
    assert off("") == frozenset()
    assert off("0") == frozenset()
    assert off("false") == frozenset()
    assert off("1") == sanitizers.ALL_SANITIZERS
    assert off("true") == sanitizers.ALL_SANITIZERS
    assert off("all") == sanitizers.ALL_SANITIZERS
    assert off("lock") == frozenset({"lock"})
    assert off("lock, sync") == frozenset({"lock", "sync"})
    assert off("THREADS") == frozenset({"threads"})


def test_parse_graftsan_rejects_typos():
    # a typo'd sanitizer name must not silently enable nothing
    with pytest.raises(ValueError):
        sanitizers.parse_graftsan("lok")
    with pytest.raises(ValueError):
        sanitizers.parse_graftsan("lock,sink")


# -- lock-order sanitizer -----------------------------------------------------

def test_ab_ba_cycle_detected_with_both_stacks():
    """The classic potential deadlock: thread 1 nests A->B, thread 2 nests
    B->A. Neither schedule actually deadlocks here (they run
    sequentially) — the WITNESS still reports it, with both acquisition
    stacks."""
    san = sanitizers.GraftSan(frozenset({"lock"}), hierarchy={})
    a = san.wrap_lock(threading.Lock(), "t.A")
    b = san.wrap_lock(threading.Lock(), "t.B")

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    for fn in (order_ab, order_ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    vs = [v for v in san.violations() if v.kind == "lock-order-cycle"]
    assert len(vs) == 1
    v = vs[0]
    assert v.key == ("lock-order-cycle", "t.A", "t.B")
    assert "t.A" in v.message and "t.B" in v.message
    # both stacks rendered, each pointing into this test
    assert len(v.stacks) == 2
    assert all("test_sanitizers.py" in s for s in v.stacks)
    assert "order_ba" in v.stacks[0] and "order_ab" in v.stacks[1]


def test_hierarchy_violation_vs_clean_ordering():
    hier = {"t.outer": {"level": 10, "no_fetch_under": False},
            "t.inner": {"level": 20, "no_fetch_under": False}}
    san = sanitizers.GraftSan(frozenset({"lock"}), hierarchy=hier)
    outer = san.wrap_lock(threading.Lock(), "t.outer")
    inner = san.wrap_lock(threading.Lock(), "t.inner")
    # the documented nesting: outer (level 10) then inner (level 20)
    with outer:
        with inner:
            pass
    assert san.violations() == []
    # the inversion: acquiring the outer lock while holding the inner one
    with inner:
        with outer:
            pass
    vs = [v for v in san.violations() if v.kind == "hierarchy"]
    assert len(vs) == 1
    assert vs[0].key == ("hierarchy", "t.inner", "t.outer")
    assert "level" in vs[0].message
    assert len(vs[0].stacks) == 2  # holding-stack + acquiring-stack


def test_reentrant_rlock_and_same_name_locks_are_not_findings():
    """An RLock re-acquire is not an ordering edge, and two same-named
    locks (two shards' "db.shard") held together have no defined order to
    violate."""
    hier = {"t.idx": {"level": 30, "no_fetch_under": False}}
    san = sanitizers.GraftSan(frozenset({"lock"}), hierarchy=hier)
    r = san.wrap_lock(threading.RLock(), "t.idx")
    s1 = san.wrap_lock(threading.Lock(), "t.shard")
    s2 = san.wrap_lock(threading.Lock(), "t.shard")
    with r:
        with r:  # re-entrant
            pass
    with s1:
        with s2:  # same-name pair: no self-edge, no cycle
            pass
    with s2:
        with s1:
            pass
    assert san.violations() == []


def test_condition_wait_keeps_held_bookkeeping_exact():
    """threading.Condition over a registered lock (the coalescer's _cv
    shape): wait() releases and reacquires through the proxy, so the
    held-lock stack must be empty during the wait and restored after."""
    san = sanitizers.GraftSan(frozenset({"lock"}), hierarchy={})
    lk = san.wrap_lock(threading.Lock(), "t.cv")
    cv = threading.Condition(lk)
    seen_during_wait = []
    ready = threading.Event()

    def producer():
        ready.wait(2.0)
        # while the consumer sits in wait() it must hold NOTHING
        seen_during_wait.append(tuple(san.held_lock_names()))
        with cv:
            cv.notify_all()

    held_after_wake = []

    def consumer():
        with cv:
            ready.set()
            cv.wait(timeout=2.0)
            held_after_wake.append(tuple(san.held_lock_names()))

    t1 = threading.Thread(target=consumer)
    t2 = threading.Thread(target=producer)
    t1.start()
    t2.start()
    t1.join(3.0)
    t2.join(3.0)
    assert held_after_wake == [("t.cv",)]
    assert san.held_lock_names() == []  # this thread never held it
    assert san.violations() == []


# -- device-sync sanitizer ----------------------------------------------------

def test_sync_under_lock_caught_through_a_helper_call():
    """The runtime twin of the interprocedural JGL008: np.asarray on a jax
    array inside a helper, called under a no_fetch_under lock — lexical
    analysis of the caller sees nothing; the patched fetch point does."""
    import jax.numpy as jnp

    hier = {"t.idx": {"level": 30, "no_fetch_under": True}}
    san = sanitizers.GraftSan(
        frozenset({"lock", "sync"}), hierarchy=hier, baseline=[])
    prev = _swap_in(san)
    try:
        lk = san.wrap_lock(threading.RLock(), "t.idx")
        dev = jnp.ones((4,), jnp.float32)

        def helper_fetch():
            return np.asarray(dev)  # the hidden sync

        with lk:
            out = helper_fetch()
        assert out.shape == (4,)
        vs = [v for v in san.violations() if v.kind == "sync-under-lock"]
        assert len(vs) == 1
        assert vs[0].key == ("sync-under-lock", "t.idx", "helper_fetch")
        assert "np.asarray" in vs[0].message
        # ...and the same fetch OUTSIDE the lock is clean
        helper_fetch()
        assert len([v for v in san.violations()
                    if v.kind == "sync-under-lock"]) == 1
    finally:
        _swap_back(san, prev)


def test_block_until_ready_under_lock_caught():
    import jax
    import jax.numpy as jnp

    hier = {"t.idx": {"level": 30, "no_fetch_under": True}}
    san = sanitizers.GraftSan(
        frozenset({"lock", "sync"}), hierarchy=hier, baseline=[])
    prev = _swap_in(san)
    try:
        lk = san.wrap_lock(threading.Lock(), "t.idx")
        dev = jnp.ones((4,), jnp.float32)
        with lk:
            jax.block_until_ready(dev)
        assert [v for v in san.violations()
                if v.kind == "sync-under-lock"]
    finally:
        _swap_back(san, prev)


def test_sync_only_mode_still_proxies_locks_and_fires():
    """GRAFTSAN=sync without lock must still catch a sync under a held
    lock: the proxy's held-lock bookkeeping is what check_fetch reads, so
    sync-only wraps locks too (order-graph/hierarchy reporting stays
    off) — a subset the docstring advertises must not silently witness
    nothing and report green."""
    import jax.numpy as jnp

    hier = {"t.idx": {"level": 30, "no_fetch_under": True},
            "t.other": {"level": 10, "no_fetch_under": False}}
    san = sanitizers.GraftSan(
        frozenset({"sync"}), hierarchy=hier, baseline=[])
    prev = _swap_in(san)
    try:
        lk = san.wrap_lock(threading.Lock(), "t.idx")
        other = san.wrap_lock(threading.Lock(), "t.other")
        assert isinstance(lk, sanitizers._SanLock)
        dev = jnp.ones((4,), jnp.float32)

        def sync_only_fetch():
            return np.asarray(dev)

        with lk:
            sync_only_fetch()
        vs = [v for v in san.violations() if v.kind == "sync-under-lock"]
        assert len(vs) == 1
        assert vs[0].key == ("sync-under-lock", "t.idx", "sync_only_fetch")
        # ...but the lock-order witnesses stay gated off: a hierarchy
        # inversion reports nothing in sync-only mode
        with lk:
            with other:
                pass
        assert [v for v in san.violations()
                if v.kind in ("hierarchy", "lock-order-cycle")] == []
    finally:
        _swap_back(san, prev)


def test_named_fetch_point_reports_once_keyed_on_the_caller():
    """One _fetch_packed under a no_fetch_under lock is ONE violation,
    keyed on the caller's site: the named point checks once and
    suppresses its internal np.asarray, so a single baseline entry can
    waive a justified path (and a real finding is not double noise)."""
    import jax.numpy as jnp

    from weaviate_tpu.index import tpu as tpu_mod

    hier = {"t.idx": {"level": 30, "no_fetch_under": True}}
    san = sanitizers.GraftSan(
        frozenset({"lock", "sync"}), hierarchy=hier, baseline=[])
    prev = _swap_in(san)
    try:
        lk = san.wrap_lock(threading.RLock(), "t.idx")
        dev = jnp.ones((4,), jnp.float32)

        def finalize_under_lock():
            return tpu_mod._fetch_packed(dev)

        with lk:
            out = finalize_under_lock()
        assert out.shape == (4,)
        vs = [v for v in san.violations() if v.kind == "sync-under-lock"]
        assert [v.key for v in vs] == [
            ("sync-under-lock", "t.idx", "finalize_under_lock")]
        assert "_fetch_packed" in vs[0].message
    finally:
        _swap_back(san, prev)


def test_sync_baseline_waives_by_key_and_prefix():
    san = sanitizers.GraftSan(
        frozenset({"lock"}), hierarchy={}, baseline=[
            {"kind": "sync-under-lock",
             "key": ["sync-under-lock", "t.idx", "helper"],
             "justification": "seeded"},
            {"kind": "thread-leak", "key": ["thread-leak", "w"],
             "justification": "prefix-waived"},
        ])
    san._report("sync-under-lock", ("sync-under-lock", "t.idx", "helper"),
                "m", [])
    san._report("thread-leak", ("thread-leak", "w", "12345"), "m", [])
    san._report("thread-leak", ("thread-leak", "other", "9"), "m", [])
    assert [v.key[1] for v in san.violations()] == ["other"]
    assert len(san.violations(baselined=True)) == 3


# -- thread-leak sanitizer ----------------------------------------------------

def test_thread_leak_fires_on_deliberately_leaked_worker():
    san = sanitizers.GraftSan(frozenset({"threads"}), hierarchy={})
    before = san.thread_snapshot()
    stop = threading.Event()
    # a watched serving-plane daemon AND an anonymous non-daemon thread
    t1 = threading.Thread(target=stop.wait, name="quality-audit-leak",
                          daemon=True)
    t2 = threading.Thread(target=stop.wait, name="leaky-worker",
                          daemon=False)
    t1.start()
    t2.start()
    try:
        leaked = san.leaked_threads(before, grace_s=0.2)
        assert {t.name for t in leaked} == {"quality-audit-leak",
                                            "leaky-worker"}
        vs = [v for v in san.violations() if v.kind == "thread-leak"]
        assert {v.key[1] for v in vs} == {"quality-audit-leak",
                                          "leaky-worker"}
    finally:
        stop.set()
        t1.join(2.0)
        t2.join(2.0)


def test_thread_snapshot_holds_thread_objects_not_idents():
    """The snapshot compares Thread OBJECTS: pthread ids are recycled by
    the OS, so an ident-keyed snapshot lets a thread that exits mid-test
    donate its ident to a freshly leaked one and mask the leak."""
    snap = sanitizers.GraftSan.thread_snapshot()
    assert snap and all(isinstance(t, threading.Thread) for t in snap)
    assert threading.current_thread() in snap


def test_thread_leak_ignores_stopped_and_preexisting_threads():
    san = sanitizers.GraftSan(frozenset({"threads"}), hierarchy={})
    stop = threading.Event()
    pre = threading.Thread(target=stop.wait, name="quality-audit-pre",
                           daemon=True)
    pre.start()
    try:
        before = san.thread_snapshot()
        # a worker that exits within the grace window is not a leak
        quick = threading.Thread(target=lambda: time.sleep(0.05),
                                 name="quality-audit-quick", daemon=True)
        quick.start()
        assert san.leaked_threads(before, grace_s=2.0) == []
        assert san.violations() == []
    finally:
        stop.set()
        pre.join(2.0)


# -- zero-cost disabled contract ----------------------------------------------

def test_disabled_serving_path_constructs_nothing(tmp_path, monkeypatch):
    """GRAFTSAN unset: a real served search (coalesced lane end to end)
    must construct no GraftSan and no lock proxy, and every fetch point
    stays the pristine library callable — spied by replacing the classes
    any enabled path would have to touch (the tracing spy idiom)."""
    import jax

    from weaviate_tpu.usecases.traverser import GetParams

    prev = sanitizers.get_sanitizer()
    if prev is not None:
        sanitizers.unconfigure(prev)
    try:
        assert sanitizers.get_sanitizer() is None
        # unconfigure removed the fetch-point patches: the originals are
        # back (their modules are numpy/jax, not this one)
        assert sanitizers._patched is None
        assert "sanitizers" not in (np.asarray.__module__ or "")
        assert "sanitizers" not in (jax.block_until_ready.__module__ or "")

        def boom(name):
            def ctor(*a, **kw):
                raise AssertionError(f"sanitizers.{name} constructed "
                                     "while disabled")
            return ctor

        monkeypatch.setattr(sanitizers, "GraftSan", boom("GraftSan"))
        monkeypatch.setattr(sanitizers, "_SanLock", boom("_SanLock"))
        app, idx, vecs = _mk_app(tmp_path)
        try:
            res = app.traverser.get_class(GetParams(
                class_name="Sa",
                near_vector={"vector": (vecs[0] + 0.5).tolist()},
                limit=K))
            assert len(res) == K
            # register_lock passed the raw lock through untouched
            shard = idx.single_local_shard()
            assert type(shard.vector_index._lock) \
                is type(threading.RLock())  # noqa: E721 — exact type IS the contract
            assert type(app.coalescer._lock) \
                is type(threading.Lock())  # noqa: E721
        finally:
            app.shutdown()
    finally:
        if prev is not None:
            sanitizers.configure(prev)


def test_enabled_wraps_registered_locks(tmp_path):
    """GRAFTSAN up: the same App construction registers its serving locks
    with the witness (the one-call shims in index/db/serving)."""
    san = sanitizers.GraftSan(sanitizers.ALL_SANITIZERS)
    prev = _swap_in(san)
    try:
        app, idx, vecs = _mk_app(tmp_path)
        try:
            shard = idx.single_local_shard()
            assert isinstance(shard.vector_index._lock,
                              sanitizers._SanLock)
            assert isinstance(app.coalescer._lock, sanitizers._SanLock)
            assert san.locks_registered["index.tpu"] >= 1
            assert san.locks_registered["db.shard"] >= 1
            assert san.locks_registered["serving.coalescer"] >= 1
        finally:
            app.shutdown()
    finally:
        _swap_back(san, prev)


# -- report + CLI -------------------------------------------------------------

def test_report_shape_and_render(tmp_path):
    san = sanitizers.GraftSan(frozenset({"lock"}), hierarchy={})
    a = san.wrap_lock(threading.Lock(), "t.A")
    b = san.wrap_lock(threading.Lock(), "t.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    doc = san.report()
    assert doc["locks_registered"] == {"t.A": 1, "t.B": 1}
    assert ["t.A", "t.B"] in doc["order_edges"]
    assert doc["violations"] and not doc["violations"][0]["baselined"]
    path = tmp_path / "report.json"
    path.write_text(json.dumps(doc))
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftsan", "--report", str(path)],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 1  # unbaselined violation -> red
    assert "lock-order-cycle" in out.stdout
    assert "edge: t.A -> t.B" in out.stdout


def test_cli_check_hierarchy_is_green_on_the_repo():
    """The tier-1 form of the gate: the committed lock_hierarchy.json and
    the package's register_lock call sites agree, and the runtime
    baseline is well-formed."""
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftsan", "--check-hierarchy"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "agree" in out.stdout


def test_cli_check_hierarchy_catches_drift(tmp_path):
    # an entry nothing registers -> documentation drift -> red
    table = json.load(open(os.path.join(
        REPO, "tools", "graftsan", "lock_hierarchy.json")))
    table["locks"].append({"name": "index.phantom", "level": 99})
    p = tmp_path / "h.json"
    p.write_text(json.dumps(table))
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftsan", "--check-hierarchy",
         "--hierarchy", str(p)],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 1
    assert "index.phantom" in out.stderr


def test_cli_usage_error():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftsan"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 2


def test_load_hierarchy_rejects_malformed_tables(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"locks": [{"name": "a", "level": "x"}]}))
    with pytest.raises(ValueError):
        sanitizers.load_hierarchy(str(p))
    p.write_text(json.dumps({"locks": [{"name": "a", "level": 1},
                                       {"name": "a", "level": 2}]}))
    with pytest.raises(ValueError):
        sanitizers.load_hierarchy(str(p))


def test_fixture_scoped_violation_fails_the_session(tmp_path):
    """A violation first witnessed during MODULE-scoped fixture setup runs
    before the per-test guard's mark, so no test fails for it — and
    first-seen dedup hides in-test repeats of the same key too. The
    conftest sessionfinish escape hatch must fail the otherwise-green
    session (else the shape ships invisibly: the CI report artifact only
    uploads on failure)."""
    workdir = tmp_path / "suite"
    workdir.mkdir()
    with open(os.path.join(REPO, "tests", "conftest.py")) as f:
        (workdir / "conftest.py").write_text(f.read())
    (workdir / "test_escape.py").write_text(
        "import threading\n"
        "import pytest\n"
        "from weaviate_tpu.testing import sanitizers\n"
        "\n"
        "@pytest.fixture(scope='module')\n"
        "def seeded_ab_ba():\n"
        "    san = sanitizers.get_sanitizer()\n"
        "    a = san.wrap_lock(threading.Lock(), 'fixture.A')\n"
        "    b = san.wrap_lock(threading.Lock(), 'fixture.B')\n"
        "    def ab():\n"
        "        with a:\n"
        "            with b:\n"
        "                pass\n"
        "    def ba():\n"
        "        with b:\n"
        "            with a:\n"
        "                pass\n"
        "    for fn in (ab, ba):\n"
        "        t = threading.Thread(target=fn)\n"
        "        t.start()\n"
        "        t.join()\n"
        "    yield\n"
        "\n"
        "def test_rides_the_fixture(seeded_ab_ba):\n"
        "    pass\n")
    env = {k: v for k, v in os.environ.items() if k not in (
        # the inner session must not clobber the OUTER run's CI artifacts
        "GRAFTSAN_REPORT_FILE", "PERF_SUMMARY_FILE", "QUALITY_SUMMARY_FILE",
        "MEMORY_SUMMARY_FILE", "INCIDENTS_SUMMARY_FILE",
        "CONTROL_SUMMARY_FILE", "SLOW_QUERY_LOG_FILE")}
    env["GRAFTSAN"] = "lock"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "pytest", str(workdir), "-q",
         "-p", "no:cacheprovider", "-p", "no:randomly"],
        capture_output=True, text=True, cwd=str(workdir), env=env,
        timeout=300)
    assert out.returncode != 0, out.stdout + out.stderr
    assert "witnessed outside any test body" in out.stderr
    assert "lock-order-cycle" in out.stderr
    # the test itself stayed green — only the session-level check failed
    assert "1 passed" in out.stdout
