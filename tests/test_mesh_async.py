"""Mesh serving promotion (index/mesh.py MeshSnapshot): lock-free async
reads on the 8-virtual-device mesh.

The mesh twin of test_snapshot_reads.py, pinning the contracts the
serving promotion introduced:

1. bit-identical results — mesh snapshot reads (sync AND async
   two-phase) return exactly what a quiesced sync search returns on
   every read-path case: full scan, filtered masked scan, small
   allowList, PQ rescore tier, PQ codes-only tier, and a k wide enough
   that the cross-shard all-gather must merge candidates from every
   device;
2. zero index-lock acquisitions on a warmed async read, plus the fused
   one-fetch / zero-host-translation invariant (costmodel JGL015);
3. snapshot pinning — a dispatch enqueued before delete+compact
   finalizes with the pre-mutation snapshot's answer;
4. read-your-writes — a search immediately after add/delete republishes
   on the slow path and sees the write.
"""

import threading
import time

import numpy as np

from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.index.mesh import MeshVectorIndex
from weaviate_tpu.monitoring import costmodel, tracing
from weaviate_tpu.storage.bitmap import Bitmap

DIM = 16


def _mk_index(tmp_path, n=400, pq=None, seed=0, **cfg_extra):
    rng = np.random.default_rng(seed)
    # small-integer vectors: every L2 distance is exact integer arithmetic
    # in f32 regardless of accumulation order, so equality checks are exact
    vecs = rng.integers(-8, 8, (n, DIM)).astype(np.float32)
    d = {"distance": "l2-squared", **cfg_extra}
    if pq is not None:
        d["pq"] = pq
    cfg = parse_and_validate_config("hnsw_tpu_mesh", d)
    # compress() persists pq.npz even with persist=False, so the shard
    # directory must exist
    (tmp_path / "meshix").mkdir(parents=True, exist_ok=True)
    idx = MeshVectorIndex(cfg, str(tmp_path / "meshix"), persist=False,
                          initial_capacity_per_shard=64)
    idx.add_batch(np.arange(n), vecs)
    idx.flush()
    return idx, vecs, rng


def _case_queries(vecs, rng):
    return vecs[:6] + rng.integers(0, 2, (6, DIM)).astype(np.float32)


def _assert_identical(idx, q, k, allow=None):
    sync_ids, sync_d = idx.search_by_vectors(q, k, allow)
    fin = idx.search_by_vectors_async(q, k, allow)
    async_ids, async_d = fin()
    np.testing.assert_array_equal(sync_ids, async_ids)
    np.testing.assert_array_equal(sync_d, async_d)
    # and a repeat sync search (still quiesced) is bit-identical too
    again_ids, again_d = idx.search_by_vectors(q, k, allow)
    np.testing.assert_array_equal(sync_ids, again_ids)
    np.testing.assert_array_equal(sync_d, again_d)


# -- 1. bit-identical: async two-phase == quiesced sync ----------------------

def test_mesh_bit_identical_sync_async_uncompressed(tmp_path):
    idx, vecs, rng = _mk_index(tmp_path)
    q = _case_queries(vecs, rng)
    _assert_identical(idx, q, 5)                        # full scan
    allow = Bitmap(range(0, 300, 2))
    _assert_identical(idx, q, 5, allow)                 # filtered masked scan
    _assert_identical(idx, q, 5, Bitmap(range(0, 40)))  # small allowList
    # k wide enough that every device's local top-k contributes through
    # the all-gather + final select (400 rows over 8 shards = 50/shard)
    _assert_identical(idx, q, 48)


def test_mesh_bit_identical_sync_async_pq_tiers(tmp_path):
    for rescore in (True, False):
        sub = tmp_path / ("rs" if rescore else "codes")
        sub.mkdir()
        idx, vecs, rng = _mk_index(
            sub, pq={"enabled": False, "segments": 8, "centroids": 16,
                     "rescore": rescore})
        idx.compress()
        assert idx.compressed
        q = _case_queries(vecs, rng)
        _assert_identical(idx, q, 5)                    # PQ tier, unfiltered
        allow = Bitmap(range(0, 300, 2))
        _assert_identical(idx, q, 5, allow)             # PQ tier, filtered


# -- 2. lock-free reads: zero lock acquisitions + one fetch ------------------

class SpyLock:
    def __init__(self, inner):
        self.inner, self.count = inner, 0

    def acquire(self, *a, **kw):
        self.count += 1
        return self.inner.acquire(*a, **kw)

    def release(self):
        return self.inner.release()

    def __enter__(self):
        self.count += 1
        return self.inner.__enter__()

    def __exit__(self, *exc):
        return self.inner.__exit__(*exc)


def test_mesh_async_read_takes_zero_index_locks_one_fetch(tmp_path):
    """The JGL015 invariant on the mesh: a warmed coalesced read acquires
    the mesh index lock ZERO times and fetches from device exactly once,
    with no host-side slot->doc translation (fused packed [B,3k])."""
    idx, vecs, rng = _mk_index(tmp_path)
    q = _case_queries(vecs, rng)
    idx.search_by_vectors(q, 5)  # publish + compile
    prev = tracing.get_tracer()
    tracing.configure(tracing.Tracer(sample_rate=1.0))
    spy = SpyLock(idx._lock)
    idx._lock = spy
    try:
        fin = idx.search_by_vectors_async(q, 5)
        ids, dists = fin()
    finally:
        idx._lock = spy.inner
        tracing.configure(prev)
    assert ids.shape == (6, 5)
    assert spy.count == 0, "mesh async dispatch took the index lock"
    shape = idx.pop_dispatch_shape()
    assert shape is not None
    assert shape.ndev == 8
    assert shape.fetches == 1
    if shape.fused:
        assert shape.translate_ms == 0.0
        assert costmodel.fused_invariant_ok(shape)


def test_mesh_reader_never_blocks_on_writer_held_lock(tmp_path):
    idx, vecs, _ = _mk_index(tmp_path)
    idx.search_by_vectors(vecs[:4], 3)  # publish + compile
    holding = threading.Event()
    release = threading.Event()

    def writer():
        with idx._lock:
            holding.set()
            release.wait(3.0)

    w = threading.Thread(target=writer, daemon=True)
    w.start()
    assert holding.wait(5.0)
    t0 = time.perf_counter()
    ids, _ = idx.search_by_vectors(vecs[:4], 3)
    elapsed = time.perf_counter() - t0
    release.set()
    w.join(timeout=10)
    assert ids.shape == (4, 3)
    assert elapsed < 1.0, (
        f"reader took {elapsed:.2f}s while a writer held the lock — "
        "the mesh snapshot fast path must not touch it")
    assert idx.pop_read_lock_wait() == 0.0


# -- 3. snapshot pinning across delete + compact -----------------------------

def test_mesh_snapshot_pins_arrays_across_delete_and_compact(tmp_path):
    """A dispatch enqueued BEFORE a delete+compact finalizes AFTER it with
    the old snapshot's answer — the per-device slab rebuild cannot tear
    it (non-donated buffers pinned by the MeshSnapshot)."""
    idx, vecs, _ = _mk_index(tmp_path)
    q = vecs[:4].copy()
    expect_ids, expect_d = idx.search_by_vectors(q, 3)
    fin = idx.search_by_vectors_async(q, 3)  # enqueued on snapshot S
    for row in expect_ids:
        for doc in row:
            idx.delete(int(doc))
    idx.compact()
    got_ids, got_d = fin()  # finalizes against pinned snapshot S
    np.testing.assert_array_equal(got_ids, expect_ids)
    np.testing.assert_array_equal(got_d, expect_d)
    # a FRESH search sees the post-mutation state (winners gone)
    new_ids, _ = idx.search_by_vectors(q, 3)
    old = {int(x) for x in expect_ids.ravel()}
    assert not ({int(x) for x in new_ids.ravel()} & old)


# -- 4. read-your-writes through the slow-path republish ---------------------

def test_mesh_read_your_writes_after_staged_mutations(tmp_path):
    idx, vecs, _ = _mk_index(tmp_path, n=100)
    gen0 = idx.snapshot_gen
    v = np.full(DIM, 7.0, np.float32)
    idx.add(5000, v)
    ids, dists = idx.search_by_vectors(v[None, :], 1)
    assert int(ids[0, 0]) == 5000 and float(dists[0, 0]) == 0.0
    assert idx.snapshot_gen > gen0  # the read published a new snapshot
    idx.delete(5000)
    ids, dists = idx.search_by_vectors(v[None, :], 1)
    assert int(ids[0, 0]) != 5000
