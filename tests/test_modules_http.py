"""HTTP/SaaS module family: sidecar vectorizers, readers (qna/sum/ner/
spellcheck), generative, media, and cloud backup backends — all driven
against in-process fake services (the reference tests these modules against
testcontainer sidecars; the fakes play that role here)."""

import base64
import json
import threading
import uuid as uuidlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.modules import Provider
from weaviate_tpu.modules.text2vec_local import LocalTextVectorizer


class FakeService:
    """One fake server covering every sidecar + SaaS route."""

    def __init__(self):
        self.local = LocalTextVectorizer(dim=32)
        self.requests = []
        svc = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def _send(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/meta":
                    return self._send({"model": "fake"})
                self._send({}, 404)

            def do_POST(self):
                body = self._body()
                svc.requests.append((self.path, body, dict(self.headers)))
                if self.path == "/vectors":
                    key = body.get("text") or body.get("image") or ""
                    return self._send(
                        {"vector": svc.local.vectorize_text([key])[0].tolist()})
                if self.path == "/answers":
                    has = "quantum" in body.get("text", "")
                    return self._send({
                        "answer": "qubits" if has else None,
                        "certainty": 0.9 if has else None, "property": "body"})
                if self.path == "/sum":
                    return self._send({"summary": body.get("text", "")[:10] + "..."})
                if self.path == "/ner":
                    return self._send({"tokens": [
                        {"entity": "MISC", "word": w}
                        for w in body.get("text", "").split()[:2]]})
                if self.path == "/spellcheck":
                    return self._send({
                        "text": body.get("text", ""), "didYouMean": "quantum",
                        "numberOfCorrections": 1})
                if self.path == "/vectorize":
                    texts = body.get("texts") or []
                    images = body.get("images") or []
                    return self._send({
                        "textVectors": [svc.local.vectorize_text([t])[0].tolist()
                                        for t in texts],
                        "imageVectors": [svc.local.vectorize_text([i])[0].tolist()
                                         for i in images]})
                if self.path == "/v1/embeddings":  # openai
                    return self._send({"data": [
                        {"index": i,
                         "embedding": svc.local.vectorize_text([t])[0].tolist()}
                        for i, t in enumerate(body.get("input", []))]})
                if self.path == "/v1/embed":  # cohere
                    return self._send({"embeddings": [
                        svc.local.vectorize_text([t])[0].tolist()
                        for t in body.get("texts", [])]})
                if self.path.startswith("/pipeline/feature-extraction/"):  # hf
                    return self._send([
                        svc.local.vectorize_text([t])[0].tolist()
                        for t in body.get("inputs", [])])
                if self.path == "/v1/chat/completions":  # generative
                    prompt = body["messages"][0]["content"]
                    return self._send({"choices": [{"message": {
                        "content": f"GEN[{prompt[:30]}]"}}]})
                self._send({"error": "no route"}, 404)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture(scope="module")
def svc():
    s = FakeService()
    yield s
    s.close()


def make_doc_class(vectorizer="text2vec-transformers"):
    return ClassDef(
        name="Doc",
        properties=[Property(name="title", data_type=["text"]),
                    Property(name="body", data_type=["text"])],
        vectorizer=vectorizer,
    )


def obj(title, body="", cls="Doc"):
    return StorObj(class_name=cls, uuid=str(uuidlib.uuid4()),
                   properties={"title": title, "body": body})


def test_transformers_vectorizer(svc):
    from weaviate_tpu.modules.text2vec_http import TransformersVectorizer

    v = TransformersVectorizer(svc.url)
    vecs = v.vectorize_text(["hello world"])
    assert vecs.shape == (1, 32)
    out = v.vectorize_object(make_doc_class(), obj("quantum", "qubits"), {})
    assert out is not None and out.shape == (32,)
    assert v.meta().get("model") == "fake"


def test_saas_vectorizers(svc):
    from weaviate_tpu.modules.text2vec_http import (
        CohereVectorizer,
        HuggingFaceVectorizer,
        OpenAIVectorizer,
    )

    oa = OpenAIVectorizer("sk-test", base_url=f"{svc.url}/v1")
    assert oa.vectorize_text(["a", "b"]).shape == (2, 32)
    # auth header actually sent
    path, _, headers = svc.requests[-1]
    assert headers.get("Authorization") == "Bearer sk-test"

    co = CohereVectorizer("co-test", base_url=f"{svc.url}/v1")
    assert co.vectorize_text(["a"]).shape == (1, 32)
    hf = HuggingFaceVectorizer("hf-test", base_url=svc.url)
    assert hf.vectorize_text(["a"]).shape == (1, 32)


def _mk_app(tmp_path, provider):
    from weaviate_tpu.server import App

    return App(config=Config(), data_path=str(tmp_path / "data"), modules=provider)


def test_qna_answer_through_graphql(svc, tmp_path):
    from weaviate_tpu.modules.readers import QnATransformers

    p = Provider()
    p.register(LocalTextVectorizer())
    p.register(QnATransformers(svc.url))
    app = _mk_app(tmp_path, p)
    try:
        app.schema.add_class({
            "class": "Doc", "vectorizer": "text2vec-local",
            "vectorIndexConfig": {"distance": "cosine"},
            "properties": [{"name": "title", "dataType": ["text"]},
                           {"name": "body", "dataType": ["text"]}]})
        app.objects.add({"class": "Doc", "properties": {
            "title": "physics", "body": "quantum computers use qubits"}})
        app.objects.add({"class": "Doc", "properties": {
            "title": "baking", "body": "bread needs flour"}})
        res = app.graphql.execute(
            '{ Get { Doc(ask: {question: "what do quantum computers use?"},'
            ' nearText: {concepts: ["quantum"]}, limit: 1)'
            ' { title _additional { answer { result hasAnswer certainty } } } } }'
        )
        assert "errors" not in res, res
        hit = res["data"]["Get"]["Doc"][0]
        assert hit["title"] == "physics"
        assert hit["_additional"]["answer"]["result"] == "qubits"
        assert hit["_additional"]["answer"]["hasAnswer"] is True
    finally:
        app.shutdown()


def test_generative_and_sum_and_ner(svc, tmp_path):
    from weaviate_tpu.modules.readers import (
        GenerativeOpenAI,
        NerTransformers,
        SumTransformers,
    )

    p = Provider()
    p.register(LocalTextVectorizer())
    p.register(GenerativeOpenAI("sk-gen", base_url=f"{svc.url}/v1"))
    p.register(SumTransformers(svc.url))
    p.register(NerTransformers(svc.url))
    app = _mk_app(tmp_path, p)
    try:
        app.schema.add_class({
            "class": "Doc", "vectorizer": "text2vec-local",
            "vectorIndexConfig": {"distance": "cosine"},
            "properties": [{"name": "title", "dataType": ["text"]},
                           {"name": "body", "dataType": ["text"]}]})
        app.objects.add({"class": "Doc", "properties": {
            "title": "physics news", "body": "quantum entanglement discovery"}})
        res = app.graphql.execute(
            '{ Get { Doc(limit: 1) { title _additional {'
            ' generate(singleResult: {prompt: "Summarize {title}"}) { singleResult }'
            ' summary(properties: ["body"]) { property result }'
            ' tokens { entity word } } } } }'
        )
        assert "errors" not in res, res
        add = res["data"]["Get"]["Doc"][0]["_additional"]
        assert add["generate"]["singleResult"].startswith("GEN[Summarize physics news")
        assert add["summary"][0]["property"] == "body"
        assert add["tokens"][0]["word"] == "physics"
    finally:
        app.shutdown()


def test_media_modules(svc):
    from weaviate_tpu.modules.media import Img2VecNeural, Multi2VecClip

    img_b64 = base64.b64encode(b"\x89PNGfake").decode()
    img_cls = ClassDef(name="Pic", vectorizer="img2vec-neural",
                       properties=[Property(name="image", data_type=["blob"])])
    pic = StorObj(class_name="Pic", uuid=str(uuidlib.uuid4()),
                  properties={"image": img_b64})

    iv = Img2VecNeural(svc.url)
    v = iv.vectorize_object(img_cls, pic, {})
    assert v.shape == (32,)

    clip = Multi2VecClip(svc.url)
    both_cls = ClassDef(name="Pic", vectorizer="multi2vec-clip",
                        properties=[Property(name="caption", data_type=["text"]),
                                    Property(name="image", data_type=["blob"])])
    both = StorObj(class_name="Pic", uuid=str(uuidlib.uuid4()),
                   properties={"caption": "a cat", "image": img_b64})
    v2 = clip.vectorize_object(both_cls, both, {})
    assert v2.shape == (32,)
    assert abs(float(np.linalg.norm(v2)) - 1.0) < 1e-5
    assert clip.vectorize_text(["a dog"]).shape == (1, 32)


def test_near_image_query(svc, tmp_path):
    from weaviate_tpu.modules.media import Img2VecNeural

    p = Provider()
    p.register(Img2VecNeural(svc.url))
    app = _mk_app(tmp_path, p)
    try:
        app.schema.add_class({
            "class": "Pic", "vectorizer": "img2vec-neural",
            "vectorIndexConfig": {"distance": "cosine"},
            "properties": [{"name": "image", "dataType": ["blob"]},
                           {"name": "label", "dataType": ["text"]}]})
        imgs = {}
        for label in ("cat", "dog", "fish"):
            b64 = base64.b64encode(f"IMG-{label}".encode()).decode()
            imgs[label] = b64
            app.objects.add({"class": "Pic",
                             "properties": {"image": b64, "label": label}})
        q = json.dumps(imgs["dog"])
        res = app.graphql.execute(
            '{ Get { Pic(nearImage: {image: %s}, limit: 1) { label } } }' % q)
        assert "errors" not in res, res
        assert res["data"]["Get"]["Pic"][0]["label"] == "dog"
    finally:
        app.shutdown()


class FakeBlobStore:
    """One fake server speaking enough S3 / GCS / Azure REST for the backends."""

    def __init__(self):
        self.objects = {}
        self.auth_headers = []
        store = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_PUT(self):
                n = int(self.headers.get("Content-Length") or 0)
                store.objects[self.path.split("?")[0]] = self.rfile.read(n)
                store.auth_headers.append(dict(self.headers))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_POST(self):  # gcs upload
                n = int(self.headers.get("Content-Length") or 0)
                store.objects[self.path] = self.rfile.read(n)
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            def do_GET(self):
                data = store.objects.get(self.path.split("?")[0])
                # gcs read paths differ from upload paths: match by suffix
                if data is None:
                    for k, v in store.objects.items():
                        if k.split("name=")[-1] == self.path.split("/o/")[-1].split("?")[0]:
                            data = v
                            break
                if data is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_s3_backend_sigv4():
    from weaviate_tpu.modules.backup_cloud import S3BackupBackend

    store = FakeBlobStore()
    try:
        be = S3BackupBackend(bucket="bk", access_key="AKIATEST",
                             secret_key="secret", endpoint=store.url)
        be.put_object("b1", "node-0/C/s/vector.log", b"\x01\x02\x03")
        assert be.get_object("b1", "node-0/C/s/vector.log") == b"\x01\x02\x03"
        be.write_meta("b1", {"status": "SUCCESS"})
        assert be.read_meta("b1")["status"] == "SUCCESS"
        assert be.read_meta("ghost") is None
        # SigV4 headers present on writes
        h = store.auth_headers[-1]
        assert h.get("Authorization", "").startswith("AWS4-HMAC-SHA256 Credential=AKIATEST/")
        assert "x-amz-content-sha256" in {k.lower() for k in h}
    finally:
        store.close()


def test_gcs_and_azure_backends():
    from weaviate_tpu.modules.backup_cloud import AzureBackupBackend, GCSBackupBackend

    store = FakeBlobStore()
    try:
        gcs = GCSBackupBackend(bucket="bk", token="tok", base_url=store.url)
        gcs.write_meta("g1", {"status": "SUCCESS"})
        assert gcs.read_meta("g1")["status"] == "SUCCESS"

        az = AzureBackupBackend(account="acct", container="c",
                                sas_token="sv=x&sig=y", base_url=store.url)
        az.put_object("a1", "f.bin", b"zz")
        assert az.get_object("a1", "f.bin") == b"zz"
        az.write_meta("a1", {"status": "SUCCESS"})
        assert az.read_meta("a1")["status"] == "SUCCESS"
        assert az.read_meta("ghost") is None
    finally:
        store.close()


def test_build_provider_full_registry(svc, monkeypatch):
    from weaviate_tpu.modules.provider import build_provider

    monkeypatch.setenv("TRANSFORMERS_INFERENCE_API", svc.url)
    monkeypatch.setenv("QNA_INFERENCE_API", svc.url)
    monkeypatch.setenv("SUM_INFERENCE_API", svc.url)
    monkeypatch.setenv("NER_INFERENCE_API", svc.url)
    monkeypatch.setenv("SPELLCHECK_INFERENCE_API", svc.url)
    monkeypatch.setenv("IMAGE_INFERENCE_API", svc.url)
    monkeypatch.setenv("CLIP_INFERENCE_API", svc.url)
    monkeypatch.setenv("OPENAI_APIKEY", "sk")
    monkeypatch.setenv("COHERE_APIKEY", "co")
    monkeypatch.setenv("HUGGINGFACE_APIKEY", "hf")
    monkeypatch.setenv("BACKUP_S3_BUCKET", "b")
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "k")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "s")
    monkeypatch.setenv("BACKUP_GCS_BUCKET", "b")
    monkeypatch.setenv("BACKUP_GCS_TOKEN", "t")
    monkeypatch.setenv("AZURE_STORAGE_ACCOUNT", "a")
    monkeypatch.setenv("BACKUP_AZURE_CONTAINER", "c")
    monkeypatch.setenv("AZURE_STORAGE_SAS_TOKEN", "sas")
    c = Config()
    c.enable_modules = [
        "text2vec-local", "text2vec-contextionary", "text2vec-transformers",
        "text2vec-openai", "text2vec-cohere", "text2vec-huggingface",
        "ref2vec-centroid", "img2vec-neural", "multi2vec-clip",
        "qna-transformers", "sum-transformers", "ner-transformers",
        "text-spellcheck", "generative-openai",
        "backup-filesystem", "backup-s3", "backup-gcs", "backup-azure",
    ]
    c.contextionary_url = "127.0.0.1:1"
    p = build_provider(c)
    assert len(p.names()) == 18
    assert set(p.additional_properties()) >= {
        "answer", "generate", "summary", "tokens", "spellCheck"}


def test_ask_drives_retrieval(svc, tmp_path):
    """Regression: ask{question} must vectorize the question and retrieve
    relevant objects (not hand arbitrary doc-id-ordered objects to qna)."""
    from weaviate_tpu.modules.readers import QnATransformers

    p = Provider()
    p.register(LocalTextVectorizer())
    p.register(QnATransformers(svc.url))
    app = _mk_app(tmp_path, p)
    try:
        app.schema.add_class({
            "class": "Doc", "vectorizer": "text2vec-local",
            "vectorIndexConfig": {"distance": "cosine"},
            "properties": [{"name": "title", "dataType": ["text"]},
                           {"name": "body", "dataType": ["text"]}]})
        # many irrelevant docs FIRST (lower doc ids), relevant one last
        for i in range(10):
            app.objects.add({"class": "Doc", "properties": {
                "title": f"cooking {i}", "body": f"recipe number {i}"}})
        app.objects.add({"class": "Doc", "properties": {
            "title": "physics", "body": "quantum computers use qubits"}})
        res = app.graphql.execute(
            '{ Get { Doc(ask: {question: "quantum computers"}, limit: 1)'
            ' { title _additional { answer { result } } } } }')
        assert "errors" not in res, res
        hit = res["data"]["Get"]["Doc"][0]
        assert hit["title"] == "physics"
        assert hit["_additional"]["answer"]["result"] == "qubits"
    finally:
        app.shutdown()


def test_qna_openai(svc):
    """qna-openai: extractive answers via the chat-completions API."""
    import uuid as _uuid

    from weaviate_tpu.entities.storobj import StorObj
    from weaviate_tpu.modules.readers import QnAOpenAI
    from weaviate_tpu.usecases.traverser import SearchResult

    mod = QnAOpenAI("sk-qna", base_url=f"{svc.url}/v1")
    rows = [SearchResult(obj=StorObj(
        class_name="D", uuid=str(_uuid.uuid4()),
        properties={"body": "the GEN answer lives here"}))]
    out = mod.resolve_additional("answer", rows, {"question": "where?"})
    assert out[0]["hasAnswer"] and out[0]["result"].startswith("GEN[")
    # auth header reached the API
    assert any(h.get("Authorization") == "Bearer sk-qna"
               for _, _, h in svc.requests)

    with pytest.raises(Exception):
        mod.resolve_additional("answer", rows, {})  # question required
    with pytest.raises(Exception):
        QnAOpenAI("")  # api key required


def test_autocorrect_transformer(svc, tmp_path):
    """bm25/nearText with autocorrect: true run the query through the
    text-spellcheck transformer before searching (texttransformer.go;
    the fake corrects everything to 'quantum')."""
    from weaviate_tpu.modules.readers import TextSpellcheck

    p = Provider()
    p.register(LocalTextVectorizer())
    p.register(TextSpellcheck(svc.url))
    assert p.transform_text(["quntum"]) == ["quantum"]

    app = _mk_app(tmp_path, p)
    try:
        app.schema.add_class({
            "class": "AC", "vectorizer": "text2vec-local",
            "vectorIndexConfig": {"distance": "cosine"},
            "properties": [{"name": "body", "dataType": ["text"]}]})
        import uuid as _uuid

        for i, b in enumerate(["quantum qubits physics", "bread flour yeast"]):
            app.objects.add({"class": "AC", "id": str(_uuid.UUID(int=900 + i)),
                             "properties": {"body": b}})
        # bm25 with a typo: without autocorrect no hits, with it the
        # corrected term matches
        q_plain = '{ Get { AC(bm25: {query: "quntum"}) { body } } }'
        q_fix = '{ Get { AC(bm25: {query: "quntum", autocorrect: true}) { body } } }'
        assert app.graphql.execute(q_plain)["data"]["Get"]["AC"] == []
        hits = app.graphql.execute(q_fix)["data"]["Get"]["AC"]
        assert hits and hits[0]["body"].startswith("quantum")
        # nearText autocorrect: corrected concept ranks the quantum doc first
        q_nt = ('{ Get { AC(nearText: {concepts: ["quntum"], autocorrect: true}, '
                'limit: 1) { body } } }')
        out = app.graphql.execute(q_nt)
        assert out["data"]["Get"]["AC"][0]["body"].startswith("quantum")
    finally:
        app.shutdown()


def test_autocorrect_without_module_errors(tmp_path):
    """autocorrect: true with no transformer enabled is a loud error, not a
    silently-uncorrected search."""
    p = Provider()
    p.register(LocalTextVectorizer())
    app = _mk_app(tmp_path, p)
    try:
        app.schema.add_class({
            "class": "NA", "vectorizer": "text2vec-local",
            "vectorIndexConfig": {"distance": "cosine"},
            "properties": [{"name": "body", "dataType": ["text"]}]})
        out = app.graphql.execute(
            '{ Get { NA(bm25: {query: "x", autocorrect: true}) { body } } }')
        assert out.get("errors") and "transformer" in out["errors"][0]["message"]
    finally:
        app.shutdown()
