"""TpuVectorIndex: exact-recall search, tombstones, allowLists, persistence.

Models the reference's hnsw test tiers: recall fixtures (recall_test.go),
delete/tombstone behavior (delete.go tests), persistence round-trip
(persistence_integration_test.go)."""

import numpy as np
import pytest

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.tpu import TpuVectorIndex
from weaviate_tpu.storage.bitmap import Bitmap


def make_index(tmp_path, metric=vi.DISTANCE_L2, **kw):
    cfg = vi.HnswUserConfig.from_dict({"distance": metric, **kw}, "hnsw_tpu")
    return TpuVectorIndex(cfg, str(tmp_path))


def brute_force(vectors, q, k, metric):
    from weaviate_tpu.ops.distances import single_distance

    d = np.array([single_distance(q, v, metric) for v in vectors])
    order = np.argsort(d, kind="stable")[:k]
    return order, d[order]


@pytest.mark.parametrize("metric", [vi.DISTANCE_L2, vi.DISTANCE_COSINE, vi.DISTANCE_DOT])
def test_exact_recall(tmp_path, rng, metric):
    idx = make_index(tmp_path / metric, metric)
    vecs = rng.standard_normal((500, 24)).astype(np.float32)
    idx.add_batch(np.arange(500), vecs)
    q = rng.standard_normal(24).astype(np.float32)
    ids, dists = idx.search_by_vector(q, 10)
    want_ids, want_d = brute_force(vecs, q, 10, metric)
    assert set(ids.tolist()) == set(want_ids.tolist())
    np.testing.assert_allclose(np.sort(dists), np.sort(want_d), rtol=1e-3, atol=1e-3)


def test_allow_words_cache_invalidated_by_compact(tmp_path, rng):
    """The per-allowList packed-words cache is keyed on (token, n,
    capacity); compact() rebuilds the slot->doc mapping and can restore the
    SAME n and capacity after re-adds — a stale mask would then route other
    docs' allow bits to live slots. compact must refresh the token."""
    idx = make_index(tmp_path, flatSearchCutoff=0)
    vecs = rng.standard_normal((100, 8)).astype(np.float32)
    idx.add_batch(np.arange(100), vecs)
    idx.flush()
    allow = Bitmap(np.arange(0, 100, 2).astype(np.uint64))  # even docs
    q = vecs[10:18]  # docs that survive the upcoming delete of 0..9
    ids, _ = idx.search_by_vectors(q, 3, allow_list=allow)
    assert getattr(allow, "_words_cache", None) is not None  # cache primed
    # shift the mapping while restoring n and capacity exactly
    idx.delete(*range(10))
    idx.flush()
    idx.compact()
    idx.add_batch(np.arange(100, 110), rng.standard_normal((10, 8)).astype(np.float32))
    idx.flush()
    assert idx.n == 100  # the aliasing precondition this test exists for
    ids2, _ = idx.search_by_vectors(q, 3, allow_list=allow)
    sentinel = np.uint64(0xFFFFFFFFFFFFFFFF)
    flat = ids2.ravel()
    flat = flat[flat != sentinel]
    assert all(int(x) % 2 == 0 and int(x) < 100 for x in flat), flat
    # self-queries for surviving allowed docs still win
    for j in range(0, 8, 2):  # queries j are docs 10+j (even, alive)
        assert int(ids2[j][0]) == 10 + j


def test_batched_search(tmp_path, rng):
    idx = make_index(tmp_path)
    vecs = rng.standard_normal((300, 16)).astype(np.float32)
    idx.add_batch(np.arange(300), vecs)
    qs = rng.standard_normal((7, 16)).astype(np.float32)
    ids, dists = idx.search_by_vectors(qs, 5)
    assert ids.shape == (7, 5)
    for bi in range(7):
        want_ids, _ = brute_force(vecs, qs[bi], 5, vi.DISTANCE_L2)
        assert set(ids[bi].tolist()) == set(want_ids.tolist())


def test_delete_tombstones(tmp_path, rng):
    idx = make_index(tmp_path)
    vecs = rng.standard_normal((100, 8)).astype(np.float32)
    idx.add_batch(np.arange(100), vecs)
    q = vecs[7]
    ids, _ = idx.search_by_vector(q, 1)
    assert ids[0] == 7
    idx.delete(7)
    ids, _ = idx.search_by_vector(q, 3)
    assert 7 not in ids.tolist()
    assert len(idx) == 99
    assert not idx.contains(7)


def test_update_same_doc_id(tmp_path, rng):
    idx = make_index(tmp_path)
    v1 = np.ones(8, np.float32)
    v2 = -np.ones(8, np.float32)
    idx.add(1, v1)
    idx.add(1, v2)  # re-add = replace (reference deletes old docID first)
    ids, dists = idx.search_by_vector(v2, 2)
    assert ids[0] == 1
    assert len(idx) == 1
    np.testing.assert_allclose(dists[0], 0.0, atol=1e-4)


def test_allowlist_filtering(tmp_path, rng):
    idx = make_index(tmp_path)
    vecs = rng.standard_normal((200, 8)).astype(np.float32)
    idx.add_batch(np.arange(200), vecs)
    allow = Bitmap([5, 50, 150])
    q = vecs[7]  # closest overall is 7, but it's not allowed
    ids, _ = idx.search_by_vector(q, 10, allow)
    assert set(ids.tolist()) <= {5, 50, 150}
    assert len(ids) == 3


def test_allowlist_large_path(tmp_path, rng):
    # force the full-scan masked path by setting the cutoff to 0
    idx = make_index(tmp_path, flatSearchCutoff=0)
    vecs = rng.standard_normal((100, 8)).astype(np.float32)
    idx.add_batch(np.arange(100), vecs)
    allow = Bitmap(np.arange(0, 100, 2))
    q = rng.standard_normal(8).astype(np.float32)
    ids, _ = idx.search_by_vector(q, 10, allow)
    assert all(i % 2 == 0 for i in ids.tolist())
    assert len(ids) == 10


def test_search_by_vector_distance(tmp_path, rng):
    idx = make_index(tmp_path)
    vecs = rng.standard_normal((100, 4)).astype(np.float32)
    idx.add_batch(np.arange(100), vecs)
    q = vecs[0]
    ids, dists = idx.search_by_vector_distance(q, 1.0, 100)
    assert (dists <= 1.0).all()
    # cross-check against brute force count
    from weaviate_tpu.ops.distances import single_distance

    want = sum(1 for v in vecs if single_distance(q, v, vi.DISTANCE_L2) <= 1.0)
    assert len(ids) == want


def test_persistence_roundtrip(tmp_path, rng):
    p = tmp_path / "shard"
    idx = make_index(p)
    vecs = rng.standard_normal((50, 8)).astype(np.float32)
    idx.add_batch(np.arange(50), vecs)
    idx.delete(3, 4)
    idx.shutdown()

    idx2 = make_index(p)
    idx2.post_startup()
    assert len(idx2) == 48
    q = vecs[10]
    ids, _ = idx2.search_by_vector(q, 1)
    assert ids[0] == 10
    ids, _ = idx2.search_by_vector(vecs[3], 5)
    assert 3 not in ids.tolist()


def test_bulk_replay_mixed_log_matches_prerestart(tmp_path, rng):
    """The vectorized replay (runs of adds parsed as one numpy view + bulk
    staging) must reproduce the EXACT pre-restart state for a log mixing
    adds, deletes, re-adds of deleted docs, duplicate doc ids within a run,
    and a torn tail."""
    from weaviate_tpu.index.tpu import VectorLog

    p = tmp_path / "shard"
    idx = make_index(p)
    vecs = rng.standard_normal((300, 8)).astype(np.float32)
    idx.add_batch(np.arange(300), vecs)
    idx.delete(*range(0, 40, 2))
    idx.add_batch(np.arange(10), vecs[100:110])  # re-add deleted + overwrite
    # in-batch duplicates; the LAST one carries a vector no other doc holds
    # (a shared vector would make the keep-last check a top_k tie-break)
    dup_vecs = rng.standard_normal((3, 8)).astype(np.float32)
    idx.add_batch(np.array([7, 7, 7]), dup_vecs)
    # a >=256-record run MIXING already-known docs (150..299: old slots must
    # tombstone via the per-record path) with fresh ones (300..429: bulk) —
    # exercises the known-filter and keep-mask slicing
    readd_vecs = rng.standard_normal((280, 8)).astype(np.float32)
    idx.add_batch(np.arange(150, 430), readd_vecs)
    idx.flush()
    live_ref = idx.live
    ids_ref, d_ref = idx.search_by_vectors(vecs[:16], 3)
    idx.shutdown()
    # torn tail: a half-written add record must be ignored, not crash
    with open(p / "vector.log", "ab") as f:
        f.write(b"\x01" + b"\x00" * 10)

    idx2 = make_index(p)
    assert idx2.live == live_ref
    ids2, d2 = idx2.search_by_vectors(vecs[:16], 3)
    np.testing.assert_allclose(d2, d_ref, atol=1e-5)
    # doc 7 carries its LAST duplicate's vector
    ids7, d7 = idx2.search_by_vector(dup_vecs[2], 1)
    assert ids7[0] == 7 and d7[0] < 1e-6
    # batch-run parser agrees record-for-record with the scalar parser
    flat = [(op, int(i), None if v is None else v.copy())
            for op, ids_, vv in VectorLog.replay_batches(str(p / "vector.log"))
            for i, v in (zip(ids_, vv) if op == "add" else [(ids_, None)])]
    scalar = list(VectorLog.replay(str(p / "vector.log")))
    assert len(flat) == len(scalar)
    for (o1, i1, v1), (o2, i2, v2) in zip(flat, scalar):
        assert o1 == o2 and i1 == i2
        if v1 is not None:
            np.testing.assert_array_equal(v1, v2)
    idx2.shutdown()


def test_compaction(tmp_path, rng):
    p = tmp_path / "shard"
    idx = make_index(p)
    vecs = rng.standard_normal((60, 8)).astype(np.float32)
    idx.add_batch(np.arange(60), vecs)
    idx.delete(*range(0, 30))
    idx.compact()
    assert len(idx) == 30
    ids, _ = idx.search_by_vector(vecs[45], 1)
    assert ids[0] == 45
    # compacted log replays correctly
    idx.shutdown()
    idx3 = make_index(p)
    assert len(idx3) == 30


def test_growth_past_min_capacity(tmp_path, rng):
    idx = make_index(tmp_path)
    n = 20000  # > _MIN_CAPACITY forces geometric growth
    vecs = rng.standard_normal((n, 8)).astype(np.float32)
    idx.add_batch(np.arange(n), vecs)
    ids, _ = idx.search_by_vector(vecs[n - 1], 1)
    assert ids[0] == n - 1
    assert len(idx) == n
