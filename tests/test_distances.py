"""Distance kernel correctness vs naive numpy — the analog of the reference's
distancer tests (distancer/l2_amd64_test.go: asm kernel vs naive Go impl)."""

import numpy as np
import pytest

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.ops import pairwise_distances, single_distance, normalize_rows


def naive(q, x, metric):
    out = np.zeros((q.shape[0], x.shape[0]), np.float32)
    for i, a in enumerate(q):
        for j, b in enumerate(x):
            out[i, j] = single_distance(a, b, metric)
    return out


@pytest.mark.parametrize(
    "metric",
    [vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE, vi.DISTANCE_MANHATTAN, vi.DISTANCE_HAMMING],
)
def test_pairwise_matches_naive(rng, metric):
    q = rng.standard_normal((5, 32)).astype(np.float32)
    x = rng.standard_normal((37, 32)).astype(np.float32)
    if metric == vi.DISTANCE_HAMMING:
        q = rng.integers(0, 3, (5, 32)).astype(np.float32)
        x = rng.integers(0, 3, (37, 32)).astype(np.float32)
    qq, xx = q, x
    if metric == vi.DISTANCE_COSINE:
        import jax.numpy as jnp

        qq = np.asarray(normalize_rows(jnp.asarray(q)))
        xx = np.asarray(normalize_rows(jnp.asarray(x)))
    got = np.asarray(pairwise_distances(qq, xx, metric))
    want = naive(q, x, metric)
    # l2 uses the matmul expansion ||q||^2 - 2qx + ||x||^2, which trades a few
    # float32 ULPs for MXU throughput; ranking is unaffected
    np.testing.assert_allclose(got, want, rtol=3e-3, atol=3e-3)


def test_l2_with_precomputed_norms(rng):
    q = rng.standard_normal((3, 16)).astype(np.float32)
    x = rng.standard_normal((20, 16)).astype(np.float32)
    norms = (x.astype(np.float64) ** 2).sum(1).astype(np.float32)
    got = np.asarray(pairwise_distances(q, x, vi.DISTANCE_L2, norms))
    want = naive(q, x, vi.DISTANCE_L2)
    np.testing.assert_allclose(got, want, rtol=3e-3, atol=3e-3)


def test_masked_top_k(rng):
    from weaviate_tpu.ops import masked_top_k

    d = np.array([[3.0, 1.0, 2.0, 0.5, 9.0]], np.float32)
    valid = np.array([True, True, True, False, True])
    top, idx = masked_top_k(d, valid, 3)
    np.testing.assert_array_equal(np.asarray(idx)[0], [1, 2, 0])
    np.testing.assert_allclose(np.asarray(top)[0], [1.0, 2.0, 3.0])


def test_masked_top_k_allowlist(rng):
    from weaviate_tpu.ops import masked_top_k

    d = np.array([[3.0, 1.0, 2.0, 0.5, 9.0]], np.float32)
    valid = np.ones(5, bool)
    allow = np.array([True, False, True, False, True])
    top, idx = masked_top_k(d, valid, 5, allow)
    got_idx = np.asarray(idx)[0]
    assert list(got_idx[:3]) == [2, 0, 4]
    assert list(got_idx[3:]) == [-1, -1]
