"""Property-based equivalence of the native LSM point-get plane
(native/lsm_get.cpp via storage/lsm_native.py) against the pure-Python
segment reader, under random operation sequences — puts, overwrites,
deletes, flush points, pair/full compactions. The native reader serves the
production hot path with the GIL released; any divergence from the Python
reader is silent data corruption, so the property IS the contract."""

import shutil
import tempfile

import pytest

pytest.importorskip("hypothesis", reason="optional dep not in this image")
from hypothesis import given, settings
from hypothesis import strategies as st

from weaviate_tpu.storage import lsm_native
from weaviate_tpu.storage.lsm import STRATEGY_REPLACE, Bucket

pytestmark = pytest.mark.skipif(
    not lsm_native.available(), reason="native lsm plane unavailable")

_KEYS = st.integers(min_value=0, max_value=40)


def _key(i: int) -> bytes:
    # mixed-length keys: bytewise order differs from numeric order for a
    # prefix-free-ness check of the binary search
    return (b"k" * (1 + i % 3)) + str(i).encode()


from weaviate_tpu.storage.lsm import _TOMBSTONE

# any value EXCEPT the reserved tombstone marker, which put() refuses
# loudly (storing it would read back as deleted — covered separately below)
_values = st.binary(min_size=0, max_size=64).filter(lambda v: v != _TOMBSTONE)

_ops = st.lists(
    st.one_of(
        st.tuples(st.just("put"), _KEYS, _values),
        st.tuples(st.just("del"), _KEYS, st.just(b"")),
        st.tuples(st.just("flush"), st.just(0), st.just(b"")),
        st.tuples(st.just("compact_pair"), st.just(0), st.just(b"")),
        st.tuples(st.just("compact"), st.just(0), st.just(b"")),
    ),
    min_size=1, max_size=60,
)


@settings(max_examples=120, deadline=None)
@given(ops=_ops)
def test_native_multi_get_equals_python_reader(ops):
    d = tempfile.mkdtemp(prefix="proplsm")
    try:
        b = Bucket(d + "/b", STRATEGY_REPLACE)
        model: dict[bytes, bytes] = {}
        for op, i, v in ops:
            if op == "put":
                b.put(_key(i), v)
                model[_key(i)] = v
            elif op == "del":
                b.delete(_key(i))
                model.pop(_key(i), None)
            elif op == "flush":
                b.flush_memtable()
            elif op == "compact_pair":
                b.compact_pair()
            else:
                b.compact()
        # one final flush so the native plane (segments-only) can see
        # everything on the packed path too
        b.flush_memtable()
        probe = [_key(i) for i in range(45)] + [None, b"", b"missing"]
        got_native = b.multi_get(probe)
        # force the Python reader on the same bucket state
        orig = lsm_native._lib, lsm_native._lib_failed
        lsm_native._lib, lsm_native._lib_failed = None, True
        try:
            got_py = b.multi_get(probe)
        finally:
            lsm_native._lib, lsm_native._lib_failed = orig
        assert got_native == got_py
        # and both agree with the reference model
        for k, v_n in zip(probe, got_native):
            if k is None or k == b"" or k == b"missing":
                assert v_n is None
            else:
                assert v_n == model.get(k), k
    finally:
        shutil.rmtree(d, ignore_errors=True)


# the reserved-tombstone-value guard test lives in test_lsm.py: it has no
# native dependency and must run even where this module is skipped
