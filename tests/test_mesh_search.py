"""Multi-chip sharded search on the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from weaviate_tpu.parallel import MeshSearchPlan
from weaviate_tpu.parallel.mesh_search import make_mesh


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    return make_mesh(8)


def test_sharded_search_matches_bruteforce(mesh, rng):
    plan = MeshSearchPlan(mesh, dim=16, capacity_per_shard=256, metric="l2-squared")
    n = 1000
    vecs = rng.standard_normal((n, 16)).astype(np.float32)
    ids = np.arange(100, 100 + n)
    plan.add_batch(ids, vecs)
    qs = rng.standard_normal((4, 16)).astype(np.float32)
    got_ids, got_d = plan.search(qs, 10)
    assert got_ids.shape == (4, 10)
    for bi in range(4):
        d = ((vecs - qs[bi]) ** 2).sum(1)
        want = set(ids[np.argsort(d)[:10]].tolist())
        assert set(got_ids[bi].tolist()) == want


def test_uneven_shard_fill(mesh, rng):
    plan = MeshSearchPlan(mesh, dim=8, capacity_per_shard=64)
    # only 3 vectors: most shards stay empty, masks must hide garbage
    vecs = rng.standard_normal((3, 8)).astype(np.float32)
    plan.add_batch(np.array([0, 1, 2]), vecs)
    got_ids, got_d = plan.search(vecs[:1], 5)
    assert set(got_ids[0][got_ids[0] >= 0].tolist()) == {0, 1, 2}
    assert got_ids[0][0] == 0
