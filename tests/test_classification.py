"""Classification jobs: kNN vote + zero-shot reference assignment over REST.

Reference test model: usecases/classification tests
(classifier_run_knn.go) — training set with labeled objects, unlabeled
sources gain the majority label of their k nearest neighbors.
"""

import json
import time
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.server import App, RestServer


def _req(port, method, path, body=None):
    import urllib.error
    import urllib.request

    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(f"http://127.0.0.1:{port}{path}", data=data, method=method)
    r.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None


@pytest.fixture
def served(tmp_path):
    app = App(config=Config(), data_path=str(tmp_path / "data"))
    srv = RestServer(app, port=0)
    srv.start()
    yield app, srv
    srv.stop()
    app.shutdown()


def _wait_job(port, job_id, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st, job = _req(port, "GET", f"/v1/classifications/{job_id}")
        assert st == 200
        if job["status"] in ("completed", "failed"):
            return job
        time.sleep(0.05)
    raise TimeoutError("classification job still running")


def _cluster_vec(center, i, dim=8):
    rng = np.random.default_rng(1000 * center + i)
    v = np.zeros(dim, np.float32)
    v[center] = 5.0
    return (v + 0.1 * rng.standard_normal(dim)).astype(np.float32)


def test_knn_classification_journey(served):
    app, srv = served
    _req(srv.port, "POST", "/v1/schema", {
        "class": "Article",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "title", "dataType": ["text"]},
                       {"name": "category", "dataType": ["text"]}],
    })
    objs = []
    # labeled training set: 2 clusters
    for c, label in ((0, "science"), (1, "sports")):
        for i in range(10):
            objs.append({"class": "Article", "id": str(uuidlib.uuid4()),
                         "properties": {"title": f"t{c}{i}", "category": label},
                         "vector": _cluster_vec(c, i).tolist()})
    # unlabeled sources near each cluster
    unlabeled = []
    for c in (0, 1):
        for i in range(100, 105):
            uid = str(uuidlib.uuid4())
            unlabeled.append((uid, c))
            objs.append({"class": "Article", "id": uid,
                         "properties": {"title": f"u{c}{i}"},
                         "vector": _cluster_vec(c, i).tolist()})
    st, out = _req(srv.port, "POST", "/v1/batch/objects", {"objects": objs})
    assert st == 200 and all(o["result"]["status"] == "SUCCESS" for o in out)

    st, job = _req(srv.port, "POST", "/v1/classifications", {
        "class": "Article", "classifyProperties": ["category"],
        "basedOnProperties": ["title"], "type": "knn", "settings": {"k": 3},
    })
    # the async job may already have finished on a fast machine
    assert st == 201 and job["status"] in ("running", "completed")
    final = _wait_job(srv.port, job["id"])
    assert final["status"] == "completed", final
    assert final["meta"]["count"] == 10
    assert final["meta"]["countSucceeded"] == 10

    for uid, c in unlabeled:
        st, got = _req(srv.port, "GET", f"/v1/objects/Article/{uid}")
        want = "science" if c == 0 else "sports"
        assert got["properties"]["category"] == want


def test_zeroshot_classification(served):
    app, srv = served
    _req(srv.port, "POST", "/v1/schema", {
        "class": "Category",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "name", "dataType": ["text"]}],
    })
    cat_ids = {}
    for c, name in ((0, "science"), (1, "sports")):
        uid = str(uuidlib.uuid4())
        cat_ids[name] = uid
        _req(srv.port, "POST", "/v1/objects", {
            "class": "Category", "id": uid, "properties": {"name": name},
            "vector": _cluster_vec(c, 0).tolist()})
    _req(srv.port, "POST", "/v1/schema", {
        "class": "Story",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "title", "dataType": ["text"]},
                       {"name": "ofCategory", "dataType": ["Category"]}],
    })
    story_ids = []
    for c in (0, 1):
        for i in range(3):
            uid = str(uuidlib.uuid4())
            story_ids.append((uid, c))
            _req(srv.port, "POST", "/v1/objects", {
                "class": "Story", "id": uid, "properties": {"title": f"s{c}{i}"},
                "vector": _cluster_vec(c, 50 + i).tolist()})

    st, job = _req(srv.port, "POST", "/v1/classifications", {
        "class": "Story", "classifyProperties": ["ofCategory"], "type": "zeroshot",
    })
    assert st == 201
    final = _wait_job(srv.port, job["id"])
    assert final["status"] == "completed", final
    assert final["meta"]["countSucceeded"] == 6

    for uid, c in story_ids:
        st, got = _req(srv.port, "GET", f"/v1/objects/Story/{uid}")
        want = cat_ids["science" if c == 0 else "sports"]
        beacon = got["properties"]["ofCategory"][0]["beacon"]
        assert beacon.endswith(want)


def test_contextual_classification_journey(tmp_path):
    """text2vec-contextionary-contextual: no training data — sources gain a
    ref to the target whose vector is closest to the boosted centroid of the
    source's most discriminative basedOn words
    (classifier_run_contextual.go journey)."""
    cfg = Config()
    cfg.enable_modules = ["text2vec-local"]
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    srv = RestServer(app, port=0)
    srv.start()
    try:
        _req(srv.port, "POST", "/v1/schema", {
            "class": "Topic", "vectorizer": "text2vec-local",
            "vectorIndexConfig": {"distance": "cosine"},
            "properties": [{"name": "name", "dataType": ["text"]}],
        })
        topic_ids = {}
        for name, words in (("science", "science physics research experiment"),
                            ("sports", "sports football match goal stadium")):
            uid = str(uuidlib.uuid4())
            topic_ids[name] = uid
            st, _ = _req(srv.port, "POST", "/v1/objects", {
                "class": "Topic", "id": uid, "properties": {"name": words}})
            assert st == 200
        _req(srv.port, "POST", "/v1/schema", {
            "class": "Post", "vectorizer": "none",
            "vectorIndexConfig": {"distance": "cosine"},
            "properties": [{"name": "body", "dataType": ["text"]},
                           {"name": "ofTopic", "dataType": ["Topic"]}],
        })
        posts = []
        bodies = {
            "science": "the physics experiment confirmed the research result",
            "sports": "the football match ended with a late goal at the stadium",
        }
        for label, body in bodies.items():
            for i in range(3):
                uid = str(uuidlib.uuid4())
                posts.append((uid, label))
                st, _ = _req(srv.port, "POST", "/v1/objects", {
                    "class": "Post", "id": uid,
                    "properties": {"body": f"{body} number {i}"},
                    "vector": [0.0] * 256})
                assert st == 200

        st, job = _req(srv.port, "POST", "/v1/classifications", {
            "class": "Post", "classifyProperties": ["ofTopic"],
            "basedOnProperties": ["body"],
            "type": "text2vec-contextionary-contextual",
        })
        assert st == 201, job
        final = _wait_job(srv.port, job["id"])
        assert final["status"] == "completed", final
        assert final["meta"]["countSucceeded"] == 6
        assert final["settings"]["minimumUsableWords"] == 3  # defaults applied

        for uid, label in posts:
            st, got = _req(srv.port, "GET", f"/v1/objects/Post/{uid}")
            beacon = got["properties"]["ofTopic"][0]["beacon"]
            assert beacon.endswith(topic_ids[label]), (label, got["properties"])
            addl = got.get("additional") or got.get("_additional") or {}
        # classification metadata stamped (scope + classifiedFields)
        st, got = _req(
            srv.port, "GET",
            f"/v1/objects/Post/{posts[0][0]}?include=classification")
        meta = (got.get("additional") or {}).get("classification") or \
               (got.get("_additional") or {}).get("classification")
        if meta:
            assert meta["scope"] == ["ofTopic"]

        # validation: basedOnProperties required for the contextual type,
        # must exist in the schema, and must be a text property
        st, out = _req(srv.port, "POST", "/v1/classifications", {
            "class": "Post", "classifyProperties": ["ofTopic"],
            "type": "text2vec-contextionary-contextual"})
        assert st == 422
        st, out = _req(srv.port, "POST", "/v1/classifications", {
            "class": "Post", "classifyProperties": ["ofTopic"],
            "basedOnProperties": ["bdy"],  # typo
            "type": "text2vec-contextionary-contextual"})
        assert st == 422
        st, out = _req(srv.port, "POST", "/v1/classifications", {
            "class": "Post", "classifyProperties": ["ofTopic"],
            "basedOnProperties": ["ofTopic"],  # not text
            "type": "text2vec-contextionary-contextual"})
        assert st == 422
    finally:
        srv.stop()
        app.shutdown()


def test_classification_validation(served):
    app, srv = served
    st, out = _req(srv.port, "POST", "/v1/classifications", {"class": "Nope",
                   "classifyProperties": ["x"]})
    assert st == 422
    st, out = _req(srv.port, "GET", "/v1/classifications/" + str(uuidlib.uuid4()))
    assert st == 404


def test_classification_additional_metadata(served):
    """Classified objects carry _additional.classification (id, scope,
    classifiedFields, basedOn — entities/additional/classification.go)."""
    app, srv = served
    _req(srv.port, "POST", "/v1/schema", {
        "class": "Article",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "title", "dataType": ["text"]},
                       {"name": "category", "dataType": ["text"]}],
    })
    objs = []
    for c, label in ((0, "science"), (1, "sports")):
        for i in range(8):
            objs.append({"class": "Article", "id": str(uuidlib.uuid4()),
                         "properties": {"title": f"t{c}{i}", "category": label},
                         "vector": _cluster_vec(c, i).tolist()})
    uid = str(uuidlib.uuid4())
    objs.append({"class": "Article", "id": uid,
                 "properties": {"title": "u0"},
                 "vector": _cluster_vec(0, 99).tolist()})
    st, _ = _req(srv.port, "POST", "/v1/batch/objects", {"objects": objs})
    assert st == 200
    st, job = _req(srv.port, "POST", "/v1/classifications", {
        "class": "Article", "classifyProperties": ["category"],
        "basedOnProperties": ["title"], "type": "knn", "settings": {"k": 3}})
    assert st == 201
    job_id = job["id"]
    final = _wait_job(srv.port, job_id)
    assert final["status"] == "completed"
    q = ('{ Get { Article(where: {path: ["title"], operator: Equal, valueText: "u0"}) '
         '{ category _additional { classification { id scope classifiedFields } } } } }')
    st, res = _req(srv.port, "POST", "/v1/graphql", {"query": q})
    assert st == 200 and not res.get("errors"), res
    hits = res["data"]["Get"]["Article"]
    assert hits and hits[0]["category"]
    cls = hits[0]["_additional"]["classification"]
    assert cls["id"] == job_id
    assert cls["scope"] == ["category"]
    assert cls["classifiedFields"] == ["category"]
