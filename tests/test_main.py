"""Process entry point smoke test: `python -m weaviate_tpu` serves REST +
gRPC + metrics and exits cleanly on SIGTERM (cmd/weaviate-server/main.go
journey)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_main_serves_and_stops(tmp_path):
    port, gport, mport = _free_port(), _free_port(), _free_port()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PERSISTENCE_DATA_PATH": str(tmp_path / "data"),
        "PROMETHEUS_MONITORING_ENABLED": "true",
        "PROMETHEUS_MONITORING_PORT": str(mport),
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    proc = subprocess.Popen(
        [sys.executable, "-m", "weaviate_tpu",
         "--host", "127.0.0.1", "--port", str(port), "--grpc-port", str(gport)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.time() + 60
        up = False
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/.well-known/ready", timeout=2
                ) as r:
                    up = r.status == 200
                    break
            except OSError:
                if proc.poll() is not None:
                    raise AssertionError(
                        f"server exited early:\n{proc.stdout.read()}"
                    )
                time.sleep(0.2)
        assert up, "server never became ready"

        with urllib.request.urlopen(f"http://127.0.0.1:{port}/v1/meta", timeout=5) as r:
            meta = json.loads(r.read())
        assert "version" in meta
        with urllib.request.urlopen(f"http://127.0.0.1:{mport}/metrics", timeout=5) as r:
            assert r.status == 200

        # gRPC port is listening
        s = socket.create_connection(("127.0.0.1", gport), timeout=5)
        s.close()

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        assert rc == 0
        out = proc.stdout.read()
        assert "shutdown complete" in out
    finally:
        if proc.poll() is None:
            proc.kill()
