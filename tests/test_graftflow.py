"""graftflow: call-graph resolution, fixed-point dataflow, the four
interprocedural rules (JGL016-JGL019), the graftsan hierarchy drift
check, and tier-1 enforcement over the real tree.

Everything here is pure AST — no JAX device — synthetic packages are
written to tmp_path; the real-tree checks share one module-scoped build.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftflow import DEFAULT_BASELINE, callgraph, dataflow
from tools.graftflow import rules as flow_rules
from tools.graftflow.engine import analyze_program, parse_suppressions
from tools.graftlint.engine import apply_baseline, load_baseline

PACKAGE = os.path.join(REPO, "weaviate_tpu")

# a synthetic hierarchy for the rule tests: three levels, fetch banned
# under the middle one
TEST_HIERARCHY = {
    "locks": [
        {"name": "t.low", "level": 10, "no_fetch_under": False},
        {"name": "t.mid", "level": 20, "no_fetch_under": True},
        {"name": "t.high", "level": 30, "no_fetch_under": False},
    ]
}


def _build(tmp_path, files: dict, hierarchy=TEST_HIERARCHY):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    hpath = tmp_path / "hierarchy.json"
    hpath.write_text(json.dumps(hierarchy))
    prog = callgraph.build_program(str(pkg), root=str(tmp_path),
                                   hierarchy_path=str(hpath))
    return prog


def _findings(tmp_path, files: dict, hierarchy=TEST_HIERARCHY):
    prog = _build(tmp_path, files, hierarchy)
    s = dataflow.analyze(prog)
    return prog, s, flow_rules.run_rules(prog, s)


LOCKED_CLASS_HEADER = """\
    import threading
    import numpy as np
    import jax.numpy as jnp
    from pkg.san import register_lock

    class Idx:
        def __init__(self):
            self._lock = register_lock(threading.RLock(), "t.mid")
            self._store = jnp.zeros((4, 4))
"""

SAN = """\
    def register_lock(lock, name):
        return lock
"""


# -- call-graph resolution ---------------------------------------------------

class TestResolution:
    def test_method_dispatch_via_constructor_attr_type(self, tmp_path):
        prog = _build(tmp_path, {
            "san.py": SAN,
            "a.py": """\
                from pkg.b import Worker

                class Owner:
                    def __init__(self):
                        self.w = Worker()

                    def go(self):
                        self.w.run()
            """,
            "b.py": """\
                class Worker:
                    def run(self):
                        return 1
            """,
        })
        s = dataflow.analyze(prog)
        scan = s.scans["pkg/a.py:Owner.go"]
        (cs,) = [c for c in scan.calls]
        assert [c.qual for c in cs.callees] == ["pkg/b.py:Worker.run"]

    def test_factory_return_union_resolves_every_branch(self, tmp_path):
        prog = _build(tmp_path, {
            "san.py": SAN,
            "a.py": """\
                from pkg.b import make_index

                class Owner:
                    def __init__(self, kind):
                        self.idx = make_index(kind)

                    def go(self):
                        self.idx.add()
            """,
            "b.py": """\
                class Tpu:
                    def add(self):
                        return "tpu"

                class Mesh:
                    def add(self):
                        return "mesh"

                def make_index(kind):
                    if kind == "tpu":
                        return Tpu()
                    return Mesh()
            """,
        })
        s = dataflow.analyze(prog)
        scan = s.scans["pkg/a.py:Owner.go"]
        quals = sorted(c.qual for cs in scan.calls for c in cs.callees)
        assert quals == ["pkg/b.py:Mesh.add", "pkg/b.py:Tpu.add"]

    def test_self_callback_idiom_resolves_to_bound_method(self, tmp_path):
        prog = _build(tmp_path, {
            "a.py": """\
                class C:
                    def __init__(self, fast):
                        if fast:
                            self._cb = self._fast
                        else:
                            self._cb = self._slow

                    def _fast(self):
                        return 1

                    def _slow(self):
                        return 2

                    def go(self):
                        return self._cb()
            """,
        })
        info = prog.functions["pkg/a.py:C.go"]
        scan = dataflow._scan_function(prog, info)
        quals = sorted(c.qual for cs in scan.calls for c in cs.callees)
        assert quals == ["pkg/a.py:C._fast", "pkg/a.py:C._slow"]

    def test_lambda_callback_participates_in_the_graph(self, tmp_path):
        # facts inside a lambda-bound callback flow to the call site
        prog, s, findings = _findings(tmp_path, {
            "san.py": SAN,
            "a.py": LOCKED_CLASS_HEADER + """\

        def go(self):
            with self._lock:
                self._cb()

        def wire(self):
            self._cb = lambda: np.asarray(self._store)
            """,
        })
        f16 = [f for f in findings if f.code == "JGL016"]
        assert len(f16) == 1 and f16[0].symbol == "Idx.go"
        assert "<lambda" in f16[0].message

    def test_decorator_wrapped_jit_entry_static_names(self, tmp_path):
        prog = _build(tmp_path, {
            "a.py": """\
                from functools import partial
                import jax

                @partial(jax.jit, static_argnames=("k", "metric"))
                def score(rows, q, k, metric):
                    return rows

                plain = jax.jit(score, static_argnums=(2,))
            """,
        })
        mi = prog.modules["pkg.a"]
        assert sorted(mi.jit_entries["score"].static_names) == [
            "k", "metric"]
        assert sorted(mi.jit_entries["plain"].static_names) == ["k"]

    def test_from_import_resolves_cross_module(self, tmp_path):
        prog = _build(tmp_path, {
            "a.py": """\
                from pkg.b import helper

                def go():
                    return helper()
            """,
            "b.py": """\
                def helper():
                    return 1
            """,
        })
        info = prog.functions["pkg/a.py:go"]
        scan = dataflow._scan_function(prog, info)
        quals = [c.qual for cs in scan.calls for c in cs.callees]
        assert quals == ["pkg/b.py:helper"]


# -- fixed-point termination -------------------------------------------------

def test_fixpoint_terminates_on_mutual_recursion(tmp_path):
    prog, s, _ = _findings(tmp_path, {
        "a.py": """\
            import numpy as np
            import jax.numpy as jnp

            def ping(n, store):
                x = jnp.dot(store, store)
                np.asarray(x)
                if n:
                    return pong(n - 1, store)
                return n

            def pong(n, store):
                if n:
                    return ping(n - 1, store)
                return n
        """,
    })
    # both directions of the cycle carry the sync summary
    assert s.syncs["pkg/a.py:ping"]
    assert s.syncs["pkg/a.py:pong"]


def test_fixpoint_terminates_on_self_recursion(tmp_path):
    prog, s, findings = _findings(tmp_path, {
        "a.py": """\
            def rec(n):
                if n:
                    return rec(n - 1)
                return 0
        """,
    })
    assert s.acquires["pkg/a.py:rec"] == {}


# -- JGL016: device sync under a no-fetch lock, any depth --------------------

class TestJGL016:
    def test_deep_chain_flagged_with_call_chain(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "san.py": SAN,
            "a.py": LOCKED_CLASS_HEADER + """\

        def go(self):
            with self._lock:
                self.step1()

        def step1(self):
            self.step2()

        def step2(self):
            import numpy as np
            np.asarray(self._store)
            """,
        })
        f16 = [f for f in findings if f.code == "JGL016"]
        assert len(f16) == 1
        assert f16[0].symbol == "Idx.go"
        assert "depth 2" in f16[0].message
        assert "Idx.step1" in f16[0].message
        assert "Idx.step2" in f16[0].message

    def test_lock_without_no_fetch_under_is_not_flagged(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "san.py": SAN,
            "a.py": LOCKED_CLASS_HEADER.replace('"t.mid"', '"t.low"') + """\

        def go(self):
            with self._lock:
                self.step()

        def step(self):
            import numpy as np
            np.asarray(self._store)
            """,
        })
        assert [f for f in findings if f.code == "JGL016"] == []

    def test_sync_in_nested_closure_does_not_count(self, tmp_path):
        # the finalize-closure idiom: deferred work runs outside the lock
        _, _, findings = _findings(tmp_path, {
            "san.py": SAN,
            "a.py": LOCKED_CLASS_HEADER + """\

        def go(self):
            with self._lock:
                return self.step()

        def step(self):
            import numpy as np

            def finalize():
                return np.asarray(self._store)

            return finalize
            """,
        })
        assert [f for f in findings if f.code == "JGL016"] == []

    def test_clean_tree_yields_no_findings(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "san.py": SAN,
            "a.py": LOCKED_CLASS_HEADER + """\

        def go(self):
            with self._lock:
                self.step()

        def step(self):
            return self._store
            """,
        })
        assert [f for f in findings if f.code == "JGL016"] == []


# -- JGL017: static lock-order conformance -----------------------------------

HIER_CLASS = """\
    import threading
    from pkg.san import register_lock

    class Planes:
        def __init__(self):
            self._low = register_lock(threading.Lock(), "t.low")
            self._mid = register_lock(threading.Lock(), "t.mid")
            self._high = register_lock(threading.Lock(), "t.high")
"""


class TestJGL017:
    def test_descending_acquisition_through_a_call_is_flagged(
            self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "san.py": SAN,
            "a.py": HIER_CLASS + """\

        def go(self):
            with self._mid:
                self.grab()

        def grab(self):
            with self._low:
                return 1
            """,
        })
        f17 = [f for f in findings if f.code == "JGL017"]
        assert len(f17) == 1
        assert "`t.low` (level 10)" in f17[0].message
        assert "`t.mid` (level 20)" in f17[0].message
        assert "Planes.grab" in f17[0].message

    def test_ab_ba_cycle_reports_both_chains(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "san.py": SAN,
            "a.py": HIER_CLASS + """\

        def forward(self):
            with self._mid:
                with self._high:
                    return 1

        def backward(self):
            with self._high:
                self.grab_mid()

        def grab_mid(self):
            with self._mid:
                return 1
            """,
        })
        f17 = [f for f in findings if f.code == "JGL017"]
        assert len(f17) == 1
        msg = f17[0].message
        assert "closes a cycle via" in msg
        # both static chains: the violating path and the legal one back
        assert "Planes.backward" in msg and "Planes.forward" in msg

    def test_conformant_nesting_is_clean_and_edges_derive(self, tmp_path):
        prog, s, findings = _findings(tmp_path, {
            "san.py": SAN,
            "a.py": HIER_CLASS + """\

        def go(self):
            with self._low:
                self.mid_work()

        def mid_work(self):
            with self._mid:
                with self._high:
                    return 1
            """,
        })
        assert [f for f in findings if f.code == "JGL017"] == []
        edges = set(dataflow.lock_edges(prog, s))
        assert ("t.low", "t.mid") in edges
        assert ("t.mid", "t.high") in edges
        # holding low while mid_work eventually grabs high: also an edge
        assert ("t.low", "t.high") in edges

    def test_condition_aliasing_folds_to_the_registered_lock(
            self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "san.py": SAN,
            "a.py": HIER_CLASS + """\

        def setup(self):
            self._cv = threading.Condition(self._mid)

        def go(self):
            with self._high:
                with self._cv:
                    return 1
            """,
        })
        f17 = [f for f in findings if f.code == "JGL017"]
        assert len(f17) == 1
        assert "`t.mid`" in f17[0].message


# -- JGL018: snapshot escape -------------------------------------------------

SNAP_MOD = """\
    class IndexSnapshot:
        def __init__(self, store):
            self.gen = 1
            self.n = 2
            self.store = store

    REGISTRY = {}
"""


class TestJGL018:
    def test_snapshot_bound_to_instance_attr_is_flagged(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "a.py": SNAP_MOD + """\

    class Reader:
        def pin(self, snap):
            self._last_snap = snap
            """,
        })
        f18 = [f for f in findings if f.code == "JGL018"]
        assert len(f18) == 1
        assert "self._last_snap" in f18[0].message

    def test_derived_view_escapes_interprocedurally(self, tmp_path):
        # rows comes back from a helper that returns a view of
        # snap.store — the tuple binding into self state is the escape
        _, _, findings = _findings(tmp_path, {
            "a.py": SNAP_MOD + """\

    def host_rows(snap):
        rows = snap.store[: snap.n]
        return rows, snap.gen

    class Reader:
        def cache(self, snap):
            rows, gen = host_rows(snap)
            self._cache = (gen, rows)
            """,
        })
        f18 = [f for f in findings if f.code == "JGL018"]
        assert len(f18) == 1
        assert "self._cache" in f18[0].message
        assert "view of a snapshot's arrays" in f18[0].message

    def test_module_registry_subscript_is_flagged(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "a.py": SNAP_MOD + """\

    def stash(key, snap):
        REGISTRY[key] = snap
            """,
        })
        f18 = [f for f in findings if f.code == "JGL018"]
        assert len(f18) == 1
        assert "REGISTRY[...]" in f18[0].message

    def test_local_use_and_publish_are_clean(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "a.py": SNAP_MOD + """\

    class Index:
        def publish(self, store):
            snap = IndexSnapshot(store)
            self._snap = snap

        def read(self, snap):
            rows = snap.store[: snap.n]
            return rows.sum()
            """,
        })
        assert [f for f in findings if f.code == "JGL018"] == []

    def test_scalar_fields_do_not_taint(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "a.py": SNAP_MOD + """\

    class Index:
        def note(self, snap):
            self._last_gen = snap.gen
            """,
        })
        assert [f for f in findings if f.code == "JGL018"] == []


# -- JGL019: jit-shape churn -------------------------------------------------

JIT_MOD = """\
    from functools import partial
    import jax

    @partial(jax.jit, static_argnames="k")
    def score(rows, q, k):
        return rows

    def _bucket_rows(n):
        return max(64, n)
"""


class TestJGL019:
    def test_len_into_static_param_is_flagged(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "a.py": JIT_MOD + """\

    def go(rows, q, xs):
        return score(rows, q, k=len(xs))
            """,
        })
        f19 = [f for f in findings if f.code == "JGL019"]
        assert len(f19) == 1
        assert "`k`" in f19[0].message and "score" in f19[0].message

    def test_interprocedural_sink_flags_the_caller(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "a.py": JIT_MOD + """\

    def wrapper(rows, q, k):
        return score(rows, q, k=k)

    def go(rows, q, xs):
        n = xs.shape[0]
        return wrapper(rows, q, n)
            """,
        })
        f19 = [f for f in findings if f.code == "JGL019"]
        assert [f.symbol for f in f19] == ["go"]
        assert "wrapper" in f19[0].message

    def test_bucket_snapped_dim_is_clean(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "a.py": JIT_MOD + """\

    def go(rows, q, xs):
        k = _bucket_rows(len(xs))
        return score(rows, q, k=k)
            """,
        })
        assert [f for f in findings if f.code == "JGL019"] == []

    def test_tainted_non_static_arg_is_clean(self, tmp_path):
        _, _, findings = _findings(tmp_path, {
            "a.py": JIT_MOD + """\

    def go(rows, xs, k):
        return score(rows, xs[: len(xs)], k=k)
            """,
        })
        assert [f for f in findings if f.code == "JGL019"] == []


# -- suppressions ------------------------------------------------------------

def test_reasoned_suppression_is_honored_and_bare_is_not(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text(textwrap.dedent("""\
        class Reader:
            def pin(self, snap):
                self._a = snap  # graftflow: disable=JGL018 audit pin, TLS-bounded
                self._b = snap  # graftflow: disable=JGL018
    """))
    hpath = tmp_path / "h.json"
    hpath.write_text(json.dumps(TEST_HIERARCHY))
    findings = analyze_program(str(pkg), root=str(tmp_path),
                               hierarchy_path=str(hpath))
    f18 = [f for f in findings if f.code == "JGL018"]
    assert len(f18) == 1 and f18[0].line == 4  # bare disable not honored


def test_parse_suppressions_requires_reason():
    src = "x = 1  # graftflow: disable=JGL016\ny = 2  # graftflow: disable=JGL016,JGL017 declared fetch\n"
    sup = parse_suppressions(src)
    assert 1 not in sup
    assert sup[2] == {"JGL016", "JGL017"}


# -- the call-graph cache ----------------------------------------------------

def test_cache_hits_and_invalidates_on_mtime(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text("def f():\n    return 1\n")
    cache = tmp_path / "graph.pkl"
    p1 = callgraph.load_or_build(str(pkg), root=str(tmp_path),
                                 cache_path=str(cache))
    assert cache.exists()
    p2 = callgraph.load_or_build(str(pkg), root=str(tmp_path),
                                 cache_path=str(cache))
    assert sorted(p2.functions) == sorted(p1.functions)
    # grow the file: the mtime+size key must invalidate
    (pkg / "a.py").write_text("def f():\n    return 1\n\ndef g():\n    return 2\n")
    p3 = callgraph.load_or_build(str(pkg), root=str(tmp_path),
                                 cache_path=str(cache))
    assert "pkg/a.py:g" in p3.functions


# -- the real tree: build once, assert many ----------------------------------

@pytest.fixture(scope="module")
def real_program():
    prog = callgraph.build_program(PACKAGE, root=REPO)
    return prog, dataflow.analyze(prog)


def test_hierarchy_edges_are_statically_rediscovered(real_program):
    """The acceptance pin: the lock-order relationships graftsan witnesses
    at runtime must be derivable with zero execution."""
    prog, s = real_program
    edges = set(dataflow.lock_edges(prog, s))
    for expected in [
        ("db.shard", "index.tpu"),       # Shard.put_object -> index.add
        ("db.shard", "index.mesh"),      # same path, mesh engine
        ("index.tpu", "index.tpu.stage_pool"),  # drop() under the index lock
    ]:
        assert expected in edges, (
            f"edge {expected} no longer derivable — the static call graph "
            f"lost a resolution path the runtime sanitizers witness; "
            f"derived: {sorted(edges)}")
    # and every derived edge between table locks must climb levels —
    # JGL017 clean on the committed tree
    levels = {n: row["level"] for n, row in prog.hierarchy.items()}
    for (a, b) in edges:
        if a in levels and b in levels:
            assert levels[a] < levels[b], f"hierarchy violation {a}->{b}"


def test_lock_table_drift_both_directions(real_program):
    """Satellite: tools/graftsan/lock_hierarchy.json vs the locks
    graftflow discovers. A lock in code but not the table (or vice versa)
    fails tier-1 — the hierarchy check is only as good as its table."""
    prog, _ = real_program
    with open(os.path.join(REPO, "tools", "graftsan",
                           "lock_hierarchy.json")) as f:
        table = {e["name"] for e in json.load(f)["locks"]}
    discovered = set(prog.registered_locks)
    assert discovered - table == set(), (
        f"locks registered in code but missing from lock_hierarchy.json: "
        f"{sorted(discovered - table)}")
    assert table - discovered == set(), (
        f"locks in lock_hierarchy.json no longer registered in code: "
        f"{sorted(table - discovered)}")


# every unregistered Lock/RLock inside the hierarchy-governed planes
# (db/, index/, serving/) needs an entry here with its reason — adding a
# lock to these planes means either registering it or justifying it
UNREGISTERED_ALLOWLIST = {
    "weaviate_tpu/db/class_index.py:ClassIndex._lock":
        "class-map mutation guard; never held across index/device calls",
    "weaviate_tpu/db/db.py:DB._lock":
        "top-of-stack class registry guard; only wraps dict ops",
    "weaviate_tpu/index/geo.py:GeoIndex._lock":
        "host-only geo index, no device work, leaf lock",
    "weaviate_tpu/index/hnsw.py:_lib_lock":
        "one-time native library load guard (module import scope)",
    "weaviate_tpu/index/hnsw.py:HnswIndex._lock":
        "host-only hnswlib engine, leaf lock, no device calls under it",
    "weaviate_tpu/serving/controller.py:_TokenBuckets._lock":
        "token-bucket arithmetic only, leaf lock, microsecond hold",
    "weaviate_tpu/serving/controller.py:_summaries_lock":
        "module summary counters, leaf lock",
    "weaviate_tpu/serving/robustness.py:TenantConcurrencyGate._lock":
        "per-tenant admission counters, leaf lock",
    "weaviate_tpu/serving/robustness.py:CircuitBreaker._lock":
        "breaker state flips only, leaf lock",
}

GOVERNED_PREFIXES = ("weaviate_tpu/db/", "weaviate_tpu/index/",
                     "weaviate_tpu/serving/")


def test_unregistered_locks_in_governed_planes_are_allowlisted(
        real_program):
    prog, _ = real_program
    governed = {f"{rel}:{owner}"
                for rel, line, owner in prog.unregistered_locks
                if rel.startswith(GOVERNED_PREFIXES)}
    unexpected = governed - set(UNREGISTERED_ALLOWLIST)
    assert unexpected == set(), (
        f"new unregistered lock(s) in a hierarchy-governed plane — "
        f"register them (sanitizers.register_lock + lock_hierarchy.json) "
        f"or allowlist with a reason: {sorted(unexpected)}")
    gone = set(UNREGISTERED_ALLOWLIST) - governed
    assert gone == set(), (
        f"allowlist entries whose locks vanished — prune them: "
        f"{sorted(gone)}")


# -- tier-1 enforcement over the real tree (the graftlint pattern) -----------

def _apply_real_baseline():
    findings = analyze_program(PACKAGE, root=REPO)
    return apply_baseline(findings, load_baseline(DEFAULT_BASELINE))


def test_tree_has_zero_unbaselined_graftflow_violations():
    new, _, _ = _apply_real_baseline()
    assert new == [], (
        "graftflow found violations outside the baseline — fix them or "
        "suppress inline with a reason (do NOT grow the baseline):\n"
        + "\n".join(f.render() for f in new))


def test_graftflow_baseline_has_no_stale_entries():
    _, _, stale = _apply_real_baseline()
    assert stale == [], (
        "stale graftflow baseline entries (their findings are fixed) — "
        "run python -m tools.graftflow weaviate_tpu --prune-baseline: "
        + json.dumps(stale, indent=2))


def test_graftflow_baseline_entries_all_carry_real_justifications():
    base = load_baseline(DEFAULT_BASELINE)
    assert base["entries"], "baseline unexpectedly empty (fine, but update this test)"
    for e in base["entries"]:
        j = e.get("justification", "")
        assert j and "TODO" not in j, f"unjustified baseline entry: {e}"
        assert e["code"] in ("JGL016", "JGL017", "JGL018", "JGL019"), (
            f"graftflow's baseline only holds its own codes: {e}")


def test_graftflow_cli_gate_is_green_on_the_tree(tmp_path):
    cache = tmp_path / "graftflow-graph.pkl"
    for _ in range(2):  # second run exercises the cache-hit path
        r = subprocess.run(
            [sys.executable, "-m", "tools.graftflow", "weaviate_tpu",
             "--strict-baseline", "--cache", str(cache)],
            cwd=REPO, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
    assert cache.exists()
