"""Test config: force JAX onto a virtual 8-device CPU mesh so the whole suite
(including multi-chip sharding tests) runs anywhere without a TPU — the
TPU-sim tier of the test strategy (SURVEY.md §4 porting implication (d))."""

import os

# force-override: the host env pins JAX_PLATFORMS to the real TPU backend, and
# sitecustomize imports jax at interpreter start, so the env var alone is too
# late — update jax config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # newer JAX spells the virtual-device count as a config option; older
    # builds only honor the XLA_FLAGS form set above
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# CI artifact mirror: when ci_check.sh sets SLOW_QUERY_LOG_FILE, every
# slow-query JSON line the suite's journeys emit (full span trees, tenant
# tags) lands in a file the workflow uploads on failure — a red fairness
# or tracing journey is then debuggable from the artifact alone.
_slow_log_path = os.environ.get("SLOW_QUERY_LOG_FILE")
if _slow_log_path:
    import logging as _logging

    _h = _logging.FileHandler(_slow_log_path, delay=True)
    _h.setFormatter(_logging.Formatter("%(message)s"))
    _logging.getLogger("weaviate_tpu.slowquery").addHandler(_h)


# -- graftsan: runtime concurrency sanitizers (weaviate_tpu/testing/
# -- sanitizers.py) -----------------------------------------------------------
# GRAFTSAN=1 (ci_check.sh exports it for the tier-1 stage) wires the
# lock-order + device-sync + thread-leak sanitizers under the whole suite:
# serving locks constructed after this point are wrapped in order-witnessing
# proxies, the device->host fetch points assert no index/shard lock is held,
# and every test is followed by a thread-snapshot diff. Unset (the default)
# nothing is constructed and nothing is patched — the suite runs exactly as
# before. An unbaselined violation fails the test that first triggered it.
from weaviate_tpu.testing import sanitizers as _sanitizers  # noqa: E402

_graftsan_enabled = _sanitizers.parse_graftsan(os.environ.get("GRAFTSAN"))


def pytest_configure(config):
    if _graftsan_enabled:
        _sanitizers.configure(_sanitizers.GraftSan(_graftsan_enabled))


@pytest.fixture(autouse=True)
def _graftsan_guard():
    san = _sanitizers.get_sanitizer()
    if san is None:
        yield
        return
    mark = san.mark()
    before = (san.thread_snapshot()
              if _sanitizers.THREAD_LEAK in san.enabled else None)
    yield
    failures = []
    for v in san.since(mark):
        failures.append(v.render())
    if before is not None:
        # the leak scan reports through san._report, so re-mark first and
        # collect what IT found (already-baselined leaks stay waived)
        leak_mark = san.mark()
        san.leaked_threads(before)
        for v in san.since(leak_mark):
            failures.append(v.render())
    if failures:
        pytest.fail("graftsan violation(s):\n" + "\n\n".join(failures),
                    pytrace=False)


def pytest_sessionfinish(session, exitstatus):
    _graftsan_sessionfinish(session, exitstatus)
    # post-hatch status: when the graftsan escape hatch just failed the
    # session, the summary artifacts must not stamp exit_status 0
    _summaries_sessionfinish(getattr(session, "exitstatus", exitstatus))


def _graftsan_sessionfinish(session, exitstatus):
    """CI artifact + escape hatch. Dumps the sanitizer's full report
    (violations with stacks, witnessed acquisition-order edges, registry)
    — ci_check.sh sets GRAFTSAN_REPORT_FILE under CI_ARTIFACT_DIR; render
    it with `python -m tools.graftsan --report <file>`. Then: a violation
    first witnessed OUTSIDE a test body (module/session fixture setup,
    session teardown) ran before any _graftsan_guard mark, so no test
    failed for it — and first-seen dedup means an identical in-test
    repeat only bumped its count. On an otherwise-green run those would
    ship invisibly (the CI report artifact only uploads on failure), so
    fail the session here instead."""
    import json as _json
    import sys as _sys

    san = _sanitizers.get_sanitizer()
    if san is None:
        return
    path = os.environ.get("GRAFTSAN_REPORT_FILE")
    if path:
        try:
            with open(path, "w") as f:
                _json.dump(san.report(), f, indent=1)
        except Exception:  # noqa: BLE001 — artifact dump must not fail the run
            pass
    if exitstatus == 0:
        escaped = san.violations()
        if escaped:
            print("\ngraftsan: unbaselined violation(s) witnessed outside "
                  "any test body (fixture setup/teardown?) — failing the "
                  "session:\n\n"
                  + "\n\n".join(v.render() for v in escaped),
                  file=_sys.stderr)
            session.exitstatus = 1


def _summaries_sessionfinish(exitstatus):
    """CI artifact: dump the perf-attribution window summaries AND the
    shadow-recall-auditor summaries of the Apps this session ran
    (monitoring/perf.py and monitoring/quality.py each stash final
    summaries at unconfigure) — ci_check.sh sets PERF_SUMMARY_FILE /
    QUALITY_SUMMARY_FILE under CI_ARTIFACT_DIR and the workflow uploads
    both in ci-failure-logs, so a red run's bundle carries the
    duty-cycle/roofline/ledger picture and the recall picture."""
    import importlib
    import json as _json

    for env_key, module, doc_key in (
            ("PERF_SUMMARY_FILE", "weaviate_tpu.monitoring.perf",
             "windows"),
            ("QUALITY_SUMMARY_FILE", "weaviate_tpu.monitoring.quality",
             "audits"),
            ("MEMORY_SUMMARY_FILE", "weaviate_tpu.monitoring.memory",
             "ledgers"),
            ("INCIDENTS_SUMMARY_FILE", "weaviate_tpu.monitoring.incidents",
             "journals"),
            ("CONTROL_SUMMARY_FILE", "weaviate_tpu.serving.controller",
             "planes")):
        path = os.environ.get(env_key)
        if not path:
            continue
        try:
            mod = importlib.import_module(module)
            summaries = mod.recent_summaries()
            if summaries:
                with open(path, "w") as f:
                    _json.dump({"exit_status": int(exitstatus),
                                doc_key: summaries}, f, indent=1)
        except Exception:  # noqa: BLE001 — artifact dump must not fail the run
            pass


@pytest.fixture
def rng():
    return np.random.default_rng(42)
