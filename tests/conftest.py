"""Test config: force JAX onto a virtual 8-device CPU mesh so the whole suite
(including multi-chip sharding tests) runs anywhere without a TPU — the
TPU-sim tier of the test strategy (SURVEY.md §4 porting implication (d))."""

import os

# force-override: the host env pins JAX_PLATFORMS to the real TPU backend, and
# sitecustomize imports jax at interpreter start, so the env var alone is too
# late — update jax config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # newer JAX spells the virtual-device count as a config option; older
    # builds only honor the XLA_FLAGS form set above
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# CI artifact mirror: when ci_check.sh sets SLOW_QUERY_LOG_FILE, every
# slow-query JSON line the suite's journeys emit (full span trees, tenant
# tags) lands in a file the workflow uploads on failure — a red fairness
# or tracing journey is then debuggable from the artifact alone.
_slow_log_path = os.environ.get("SLOW_QUERY_LOG_FILE")
if _slow_log_path:
    import logging as _logging

    _h = _logging.FileHandler(_slow_log_path, delay=True)
    _h.setFormatter(_logging.Formatter("%(message)s"))
    _logging.getLogger("weaviate_tpu.slowquery").addHandler(_h)


def pytest_sessionfinish(session, exitstatus):
    """CI artifact: dump the perf-attribution window summaries AND the
    shadow-recall-auditor summaries of the Apps this session ran
    (monitoring/perf.py and monitoring/quality.py each stash final
    summaries at unconfigure) — ci_check.sh sets PERF_SUMMARY_FILE /
    QUALITY_SUMMARY_FILE under CI_ARTIFACT_DIR and the workflow uploads
    both in ci-failure-logs, so a red run's bundle carries the
    duty-cycle/roofline/ledger picture and the recall picture."""
    import importlib
    import json as _json

    for env_key, module, doc_key in (
            ("PERF_SUMMARY_FILE", "weaviate_tpu.monitoring.perf",
             "windows"),
            ("QUALITY_SUMMARY_FILE", "weaviate_tpu.monitoring.quality",
             "audits"),
            ("MEMORY_SUMMARY_FILE", "weaviate_tpu.monitoring.memory",
             "ledgers"),
            ("INCIDENTS_SUMMARY_FILE", "weaviate_tpu.monitoring.incidents",
             "journals"),
            ("CONTROL_SUMMARY_FILE", "weaviate_tpu.serving.controller",
             "planes")):
        path = os.environ.get(env_key)
        if not path:
            continue
        try:
            mod = importlib.import_module(module)
            summaries = mod.recent_summaries()
            if summaries:
                with open(path, "w") as f:
                    _json.dump({"exit_status": int(exitstatus),
                                doc_key: summaries}, f, indent=1)
        except Exception:  # noqa: BLE001 — artifact dump must not fail the run
            pass


@pytest.fixture
def rng():
    return np.random.default_rng(42)
