"""MeshVectorIndex ("hnsw_tpu_mesh") on the virtual 8-device CPU mesh:
brute-force parity, deletes, filters, growth, durability replay, and the
full serving path through DB/ClassIndex/Shard."""

import uuid as uuidlib

import jax
import numpy as np
import pytest

from weaviate_tpu.db import DB
from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.entities.vectorindex import (
    ConfigValidationError,
    parse_and_validate_config,
)
from weaviate_tpu.index.mesh import MeshVectorIndex
from weaviate_tpu.storage.bitmap import Bitmap

DIM = 16
SENTINEL = np.iinfo(np.uint64).max


def make_index(tmp_path, metric="l2-squared", persist=True, **cfg):
    config = parse_and_validate_config("hnsw_tpu_mesh", {"distance": metric, **cfg})
    return MeshVectorIndex(
        config, str(tmp_path), persist=persist, initial_capacity_per_shard=64
    )


def brute(vecs, ids, q, k, metric="l2-squared"):
    if metric == "l2-squared":
        d = ((vecs - q) ** 2).sum(1)
    elif metric == "cosine":
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q)
        d = 1.0 - vn @ qn
    else:
        d = -(vecs @ q)
    order = np.argsort(d, kind="stable")[:k]
    return ids[order], d[order]


def test_devices():
    assert len(jax.devices()) >= 8


def test_bruteforce_parity(tmp_path, rng):
    idx = make_index(tmp_path)
    n = 700
    vecs = rng.standard_normal((n, DIM)).astype(np.float32)
    ids = np.arange(10, 10 + n)
    idx.add_batch(ids, vecs)
    qs = rng.standard_normal((5, DIM)).astype(np.float32)
    got_ids, got_d = idx.search_by_vectors(qs, 10)
    assert got_ids.shape == (5, 10)
    for bi in range(5):
        want_ids, want_d = brute(vecs, ids, qs[bi], 10)
        assert set(got_ids[bi].tolist()) == set(want_ids.tolist())
        np.testing.assert_allclose(np.sort(got_d[bi]), np.sort(want_d), rtol=1e-4)
    idx.shutdown()


def test_cosine_metric(tmp_path, rng):
    idx = make_index(tmp_path, metric="cosine")
    vecs = rng.standard_normal((200, DIM)).astype(np.float32)
    ids = np.arange(200)
    idx.add_batch(ids, vecs)
    q = vecs[7]
    got_ids, got_d = idx.search_by_vector(q, 5)
    assert got_ids[0] == 7
    assert got_d[0] < 1e-5
    idx.shutdown()


def test_delete_and_update(tmp_path, rng):
    idx = make_index(tmp_path)
    vecs = rng.standard_normal((100, DIM)).astype(np.float32)
    idx.add_batch(np.arange(100), vecs)
    assert len(idx) == 100
    # delete the true nearest neighbor of q; it must vanish from results
    q = vecs[42]
    idx.delete(42)
    assert len(idx) == 99
    assert not idx.contains(42)
    got_ids, _ = idx.search_by_vector(q, 5)
    assert 42 not in got_ids.tolist()
    # re-add with a new vector: old row tombstoned, new one found
    newv = rng.standard_normal(DIM).astype(np.float32)
    idx.add(42, newv)
    got_ids, got_d = idx.search_by_vector(newv, 1)
    assert got_ids[0] == 42 and got_d[0] < 1e-5
    assert len(idx) == 100
    idx.shutdown()


def test_filtered_search_bitmap(tmp_path, rng):
    idx = make_index(tmp_path)
    n = 300
    vecs = rng.standard_normal((n, DIM)).astype(np.float32)
    ids = np.arange(n)
    idx.add_batch(ids, vecs)
    allowed = np.arange(0, n, 3).astype(np.uint64)  # every 3rd doc
    allow = Bitmap(allowed)
    q = vecs[5]  # 5 is not allowed (5 % 3 != 0)
    got_ids, got_d = idx.search_by_vectors(q[None], 10, allow_list=allow)
    real = got_ids[0][got_ids[0] != SENTINEL]
    assert len(real) == 10
    assert all(int(i) % 3 == 0 for i in real)
    want_ids, _ = brute(vecs[::3], ids[::3], q, 10)
    assert set(int(i) for i in real) == set(want_ids.tolist())
    idx.shutdown()


def test_growth_beyond_initial_capacity(tmp_path, rng):
    idx = make_index(tmp_path)  # 64 rows/chip * 8 chips = 512 initial
    n = 2000
    vecs = rng.standard_normal((n, DIM)).astype(np.float32)
    idx.add_batch(np.arange(n), vecs)
    assert len(idx) == n
    assert idx.n_loc > 64
    q = vecs[1777]
    got_ids, got_d = idx.search_by_vector(q, 3)
    assert got_ids[0] == 1777 and got_d[0] < 1e-5
    idx.shutdown()


def test_durability_replay(tmp_path, rng):
    idx = make_index(tmp_path)
    vecs = rng.standard_normal((150, DIM)).astype(np.float32)
    idx.add_batch(np.arange(150), vecs)
    idx.delete(3, 77)
    idx.add(300, vecs[0] * 2.0)
    idx.shutdown()

    idx2 = make_index(tmp_path)
    assert len(idx2) == 149  # 150 - 2 deleted + 1 added
    assert not idx2.contains(3) and not idx2.contains(77)
    assert idx2.contains(300)
    got_ids, got_d = idx2.search_by_vector(vecs[10], 1)
    assert got_ids[0] == 10 and got_d[0] < 1e-5
    idx2.shutdown()


def test_compact_drops_tombstones(tmp_path, rng):
    idx = make_index(tmp_path)
    vecs = rng.standard_normal((120, DIM)).astype(np.float32)
    idx.add_batch(np.arange(120), vecs)
    idx.delete(*range(0, 120, 2))
    assert len(idx) == 60
    idx.compact()
    assert len(idx) == 60
    assert int(idx._counts.sum()) == 60  # tombstoned slots physically gone
    got_ids, got_d = idx.search_by_vector(vecs[1], 5)
    assert got_ids[0] == 1 and got_d[0] < 1e-5
    assert all(int(i) % 2 == 1 for i in got_ids.tolist())
    idx.shutdown()


def test_insert_with_full_shards_keeps_live_rows(tmp_path, rng):
    """Regression: a whole-mesh insert step must leave chips with no work
    bit-identical — a full slab's clamped offset would otherwise zero its
    last live row."""
    idx = make_index(tmp_path)  # 64 rows/chip * 8 chips
    n = 8 * 64 - 1  # fill every slab except one row on one chip
    vecs = rng.standard_normal((n, DIM)).astype(np.float32)
    idx.add_batch(np.arange(n), vecs)
    idx.add(n, rng.standard_normal(DIM).astype(np.float32))  # 7 chips idle
    # every original vector must still be found exactly
    probe = rng.integers(0, n, 32)
    for i in probe:
        got_ids, got_d = idx.search_by_vector(vecs[i], 1)
        assert got_ids[0] == i and got_d[0] < 1e-5, i
    idx.shutdown()


def test_delete_then_grow_keeps_tombstones(tmp_path, rng):
    """Regression: tombstones staged before a growth must land on the
    remapped rows, and the deleted doc must not resurrect through the
    rebuilt id map."""
    idx = make_index(tmp_path)
    n = 512
    vecs = rng.standard_normal((n, DIM)).astype(np.float32)
    idx.add_batch(np.arange(n), vecs)
    idx.delete(300)  # staged tombstone at old slab layout
    more = rng.standard_normal((4096, DIM)).astype(np.float32)
    idx.add_batch(np.arange(10_000, 14_096), more)  # triggers growth
    assert not idx.contains(300)
    got_ids, _ = idx.search_by_vector(vecs[300], 5)
    assert 300 not in got_ids.tolist()
    # every other original row survived the grow + masked writes
    for i in (0, 1, 299, 301, 511):
        got_ids, got_d = idx.search_by_vector(vecs[i], 1)
        assert got_ids[0] == i and got_d[0] < 1e-5, i
    # compact must not re-add the deleted row either
    idx.compact()
    assert not idx.contains(300)
    got_ids, _ = idx.search_by_vector(vecs[300], 5)
    assert 300 not in got_ids.tolist()
    idx.shutdown()


def test_pq_on_mesh(tmp_path, rng):
    """Mesh PQ (compress.go parity): compress -> recall vs brute force,
    filtered PQ search, post-compress appends encode on write, store
    downcast to bf16."""
    import jax.numpy as jnp

    idx = make_index(tmp_path / "pq")
    vecs = rng.standard_normal((400, DIM)).astype(np.float32)
    idx.add_batch(np.arange(400), vecs)
    idx.flush()
    assert idx.dtype == jnp.float32
    idx.update_user_config(parse_and_validate_config(
        "hnsw_tpu_mesh",
        {"distance": "l2-squared", "pq": {"enabled": True, "segments": 4}}))
    assert idx.compressed and idx.dtype == jnp.bfloat16

    q = vecs[7] + 0.01
    ids, dists = idx.search_by_vector(q, 5)
    want_ids, _ = brute(vecs, np.arange(400), q, 5)
    assert ids[0] == want_ids[0] == 7
    assert len(set(int(x) for x in ids) & set(int(x) for x in want_ids)) >= 4

    # filtered PQ search
    allow = Bitmap(range(100, 200))
    ids_f, _ = idx.search_by_vectors(vecs[150][None, :] + 0.01, 3, allow_list=allow)
    assert int(ids_f[0][0]) == 150
    assert all(100 <= int(x) < 200 for x in ids_f[0])

    # post-compress append is searchable (encode-on-write)
    nv = rng.standard_normal(DIM).astype(np.float32) * 5.0
    idx.add(9999, nv)
    idx.flush()
    ids2, _ = idx.search_by_vector(nv, 1)
    assert int(ids2[0]) == 9999

    # delete under PQ
    idx.delete(7)
    ids3, _ = idx.search_by_vector(q, 3)
    assert 7 not in [int(x) for x in ids3]


def test_pq_mesh_restart(tmp_path, rng):
    """Codebook persists; codes re-derive on replay (AddPQ replay parity)."""
    idx = make_index(tmp_path / "pqr")
    vecs = rng.standard_normal((300, DIM)).astype(np.float32)
    idx.add_batch(np.arange(300), vecs)
    idx.update_user_config(parse_and_validate_config(
        "hnsw_tpu_mesh",
        {"distance": "l2-squared", "pq": {"enabled": True, "segments": 4}}))
    idx.flush()
    del idx

    idx2 = make_index(tmp_path / "pqr")
    assert idx2.compressed
    q = vecs[11] + 0.005
    ids, _ = idx2.search_by_vector(q, 3)
    assert int(ids[0]) == 11
    # compact under PQ keeps searchability
    idx2.delete(0, 1, 2)
    idx2.compact()
    ids2, _ = idx2.search_by_vector(q, 3)
    assert int(ids2[0]) == 11 and 0 not in [int(x) for x in ids2]


def test_search_by_vector_distance(tmp_path, rng):
    idx = make_index(tmp_path)
    base = rng.standard_normal(DIM).astype(np.float32)
    vecs = base + 0.01 * np.arange(50)[:, None].astype(np.float32)
    idx.add_batch(np.arange(50), vecs.astype(np.float32))
    ids, dists = idx.search_by_vector_distance(vecs[0], target_distance=0.01, max_limit=100)
    assert len(ids) > 0
    assert (dists <= 0.01).all()
    idx.shutdown()


# -- through the serving path (Shard / ClassIndex / DB) ----------------------


def make_class(name="MeshArticle"):
    return ClassDef(
        name=name,
        properties=[
            Property(name="title", data_type=["text"]),
            Property(name="wordCount", data_type=["int"]),
            Property(name="published", data_type=["boolean"]),
        ],
        vector_index_type="hnsw_tpu_mesh",
    )


def new_obj(i, dim=8, cls="MeshArticle"):
    rng = np.random.default_rng(i)
    return StorObj(
        class_name=cls,
        uuid=str(uuidlib.UUID(int=i + 1)),
        properties={"title": f"hello {i}", "wordCount": i, "published": i % 2 == 0},
        vector=rng.standard_normal(dim).astype(np.float32),
    )


def test_mesh_through_shard(tmp_path):
    cfg = parse_and_validate_config("hnsw_tpu_mesh", {"distance": "l2-squared"})
    db = DB(str(tmp_path / "data"))
    idx = db.add_class(make_class(), cfg)
    objs = [new_obj(i) for i in range(60)]
    idx.put_batch(objs)

    res = idx.object_vector_search(objs[17].vector, k=5)
    assert res[0][0].obj.uuid == objs[17].uuid

    # filtered search goes through the device bitmap path
    flt = LocalFilter.from_dict(
        {"operator": "Equal", "path": ["published"], "valueBoolean": True}
    )
    res = idx.object_vector_search(objs[4].vector, k=10, flt=flt)
    assert len(res[0]) == 10
    assert all(r.obj.properties["published"] is True for r in res[0])

    # delete through the shard: object disappears from vector results
    idx.delete_object(objs[17].uuid)
    res = idx.object_vector_search(objs[17].vector, k=5)
    assert all(r.obj.uuid != objs[17].uuid for r in res[0])
    db.shutdown()


def test_mesh_restart_through_db(tmp_path):
    cfg = parse_and_validate_config("hnsw_tpu_mesh", {"distance": "l2-squared"})
    db1 = DB(str(tmp_path / "data"))
    idx = db1.add_class(make_class(), cfg)
    objs = [new_obj(i) for i in range(40)]
    idx.put_batch(objs)
    idx.delete_object(objs[8].uuid)
    db1.flush()
    db1.shutdown()

    db2 = DB(str(tmp_path / "data"))
    idx2 = db2.add_class(make_class(), cfg)
    assert idx2.object_count() == 39
    res = idx2.object_vector_search(objs[3].vector, k=3)
    assert res[0][0].obj.uuid == objs[3].uuid
    res = idx2.object_vector_search(objs[8].vector, k=5)
    assert all(r.obj.uuid != objs[8].uuid for r in res[0])
    db2.shutdown()


def test_pq_mesh_large_k_and_manhattan_guard(tmp_path, rng):
    """k > r_chunk cap exercises the pool-covers-k clamp; non-matmul
    metrics refuse to compress instead of silently mis-scoring."""
    config = parse_and_validate_config(
        "hnsw_tpu_mesh", {"distance": "l2-squared"})
    idx = MeshVectorIndex(config, str(tmp_path / "pqk"),
                          initial_capacity_per_shard=1024)
    vecs = rng.standard_normal((400, DIM)).astype(np.float32)
    idx.add_batch(np.arange(400), vecs)
    idx.update_user_config(parse_and_validate_config(
        "hnsw_tpu_mesh",
        {"distance": "l2-squared", "pq": {"enabled": True, "segments": 4}}))
    ids, dists = idx.search_by_vectors(vecs[:2] + 0.001, 300)
    real = ids[0][dists[0] != np.inf]
    assert len(real) >= 300 - 1  # pool covered k

    man = make_index(tmp_path / "man", metric="manhattan")
    mvecs = rng.standard_normal((300, DIM)).astype(np.float32)
    man.add_batch(np.arange(300), mvecs)
    with pytest.raises(ConfigValidationError):
        man.update_user_config(parse_and_validate_config(
            "hnsw_tpu_mesh",
            {"distance": "manhattan", "pq": {"enabled": True, "segments": 4}}))
    # the rejected pq-enable must not stick in config: adds and searches
    # keep working (a sticky pq.enabled would re-raise from _flush_pending)
    assert not man.config.pq.enabled
    man.add_batch(np.arange(300, 320),
                  rng.standard_normal((20, DIM)).astype(np.float32))
    ids, _ = man.search_by_vectors(mvecs[:1], 5)
    assert ids[0][0] == 0


def test_mesh_bulk_replay_matches_prerestart(tmp_path, rng):
    """A large (>256-record runs) mixed log — adds, deletes, re-adds,
    in-run duplicates — restores onto the mesh with the exact pre-restart
    state via the bulk replay path."""
    config = parse_and_validate_config("hnsw_tpu_mesh", {"distance": "l2-squared"})
    idx = MeshVectorIndex(config, str(tmp_path / "br"),
                          initial_capacity_per_shard=1024)
    n = 1500
    vecs = rng.standard_normal((n, DIM)).astype(np.float32)
    idx.add_batch(np.arange(n), vecs)
    idx.delete(*range(0, 50, 2))
    idx.add_batch(np.arange(10), vecs[500:510])  # re-adds incl. deleted
    dup_vecs = rng.standard_normal((3, DIM)).astype(np.float32)
    idx.add_batch(np.array([7, 7, 7]), dup_vecs)
    idx.flush()
    live_ref = idx.live
    ids_ref, d_ref = idx.search_by_vectors(vecs[100:116], 3)
    idx.flush()
    del idx

    idx2 = MeshVectorIndex(config, str(tmp_path / "br"),
                           initial_capacity_per_shard=1024)
    assert idx2.live == live_ref
    ids2, d2 = idx2.search_by_vectors(vecs[100:116], 3)
    np.testing.assert_allclose(d2, d_ref, atol=1e-4)
    ids7, d7 = idx2.search_by_vector(dup_vecs[2], 1)
    assert ids7[0] == 7 and d7[0] < 1e-5


def test_mesh_gmin_fused_kernel_matches_exact(tmp_path, rng):
    """Slabs big enough for the fused group-min path (n_loc >= 16384):
    results must match exact numpy, the kernel must actually engage, and
    deletes + filters must hold (interpret mode on the CPU mesh)."""
    from weaviate_tpu.storage.bitmap import Bitmap

    config = parse_and_validate_config("hnsw_tpu_mesh", {"distance": "l2-squared"})
    idx = MeshVectorIndex(config, str(tmp_path / "g"),
                          initial_capacity_per_shard=16384)
    n = 3000
    vecs = rng.standard_normal((n, DIM)).astype(np.float32)
    idx.add_batch(np.arange(n), vecs)
    for doc in range(0, 30, 2):
        idx.delete(doc)
    q = vecs[:16] + 0.001 * rng.standard_normal((16, DIM)).astype(np.float32)
    ids, dists = idx.search_by_vectors(q, 5)
    # the fused path was eligible AND actually served (validated shape)
    assert not idx._gmin_broken and idx._gmin_validated
    assert idx._gmin_plan(16, 5) is not None
    live = np.array([d for d in range(n) if not (d < 30 and d % 2 == 0)])
    dd = ((q[:, None, :] - vecs[live][None, :, :]) ** 2).sum(-1)
    want = live[np.argsort(dd, axis=1)[:, :5]]
    for i in range(16):
        assert set(int(x) for x in ids[i]) == set(int(x) for x in want[i]), i
    # filtered: allowList restricted to docs < 500
    allow = Bitmap(np.arange(500).astype(np.uint64))
    ids_f, _ = idx.search_by_vectors(q, 5, allow)
    flat = ids_f[ids_f != np.uint64(0xFFFFFFFFFFFFFFFF)]
    assert all(int(x) < 500 for x in flat)


def test_mesh_pq_codes_fused_kernel_matches_legacy(tmp_path, rng):
    """Codes-only tier on the mesh: slabs big enough for the fused
    per-shard ADC kernel (n_loc/G >= 64) must serve through it (separate
    validation domain), with the same winners as the legacy reconstruction
    scan."""
    config = parse_and_validate_config(
        "hnsw_tpu_mesh", {"distance": "l2-squared"})
    idx = MeshVectorIndex(config, str(tmp_path / "pqm"),
                          initial_capacity_per_shard=1024)
    n = 2000
    vecs = rng.standard_normal((n, DIM)).astype(np.float32)
    idx.add_batch(np.arange(n), vecs)
    idx.update_user_config(parse_and_validate_config(
        "hnsw_tpu_mesh",
        {"distance": "l2-squared",
         "pq": {"enabled": True, "segments": 8, "centroids": 32,
                "rescore": False}}))
    assert idx.compressed
    q = vecs[:16] + 0.001 * rng.standard_normal((16, DIM)).astype(np.float32)
    ids_f, d_f = idx.search_by_vectors(q, 5)
    assert idx._pqg_state._gmin_validated and not idx._pqg_state._gmin_broken
    idx._pqg_state._gmin_broken = True  # force the legacy recon scan
    ids_l, d_l = idx.search_by_vectors(q, 5)
    idx._pqg_state._gmin_broken = False
    for i in range(16):
        assert set(int(x) for x in ids_f[i]) == set(int(x) for x in ids_l[i]), i
        # the legacy scan computes ADC in bf16 matmuls; the fused path
        # rescores its candidates in f32 — same quantizer, small skew
        np.testing.assert_allclose(np.sort(d_f[i]), np.sort(d_l[i]),
                                   rtol=0.08, atol=0.05)
    # deletes hold through the fused path
    idx.delete(0, 2)
    ids_d, _ = idx.search_by_vectors(q[:4], 3)
    flat = ids_d.ravel()
    assert 0 not in [int(x) for x in flat] and 2 not in [int(x) for x in flat]


def test_pq_mesh_compact_keeps_f32_log(tmp_path, rng):
    """compact() under PQ rewrites the log from the f32 host copy, not the
    bf16-downcast device store."""
    idx = make_index(tmp_path / "pqc")
    vecs = rng.standard_normal((300, DIM)).astype(np.float32)
    idx.add_batch(np.arange(300), vecs)
    idx.update_user_config(parse_and_validate_config(
        "hnsw_tpu_mesh",
        {"distance": "l2-squared", "pq": {"enabled": True, "segments": 4}}))
    idx.delete(0, 1)
    idx.compact()
    idx.flush()
    del idx
    # replayed vectors are bit-exact f32 originals
    from weaviate_tpu.index.tpu import VectorLog
    got = {doc: vec for op, doc, vec in VectorLog.replay(
        str(tmp_path / "pqc" / "vector.log")) if op == "add"}
    np.testing.assert_array_equal(got[42], vecs[42])
    assert 0 not in got and 1 not in got
