"""Self-tuning degradation control plane (serving/controller.py).

Covers the four controllers' decide logic against synthetic sensor
feeds (brownout ladder staging + square-wave hysteresis, recall-floor
backoff + bucket-snapped cuts, lane window/depth steering, token-bucket
rate math), the clamped actuate helper, the fail-static guarantees
(tick-thread death reverts + journals; a stalled thread's leases lapse
at the readers; unconfigure restores every knob), the serving-path
integration (tenant_rate sheds with time-to-next-token, brownout
margin/cap/Retry-After knobs at coalescer admission, drain-rate-derived
gate hints, the rescore_r cap in the index), the disabled-mode
zero-construction spy, /debug/controllers + weaviate_controller_*
exposure, config parsing/validation, and the end-to-end brownout storm
journey under the PR-5 seeded device-error storm.
"""

import http.client
import json
import threading
import time
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.config.config import ConfigError, load_config
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.monitoring import incidents
from weaviate_tpu.serving import controller, robustness
from weaviate_tpu.serving.controller import (
    KNOB_CAP_SCALE,
    KNOB_MARGIN,
    KNOB_RATE_SCALE,
    KNOB_RESCORE_CAP,
    KNOB_RETRY_SCALE,
    KNOB_WINDOW_S,
    R_BUCKETS,
    ControlPlane,
)
from weaviate_tpu.testing import faults
from weaviate_tpu.usecases.traverser import GetParams

N, DIM, K = 200, 16, 5


@pytest.fixture(autouse=True)
def _clean_controller_globals():
    """Isolate the module global: a plane another test forgot must not
    leak into the disabled-default assertions here (and ours must not
    leak out into other files' serving paths)."""
    saved = controller._plane
    controller._plane = None
    yield
    controller._plane = saved


@pytest.fixture(autouse=True)
def _clean_incident_globals():
    saved = (incidents._journal, incidents._engine, incidents._recorder)
    incidents._journal = incidents._engine = incidents._recorder = None
    yield
    incidents._journal, incidents._engine, incidents._recorder = saved


def _plane(**overrides) -> ControlPlane:
    """Unstarted plane for deterministic tick() driving."""
    return ControlPlane(start=False, **overrides)


def _mk_app(tmp_path, **cfg_edits):
    from weaviate_tpu.server import App

    cfg = Config()
    cfg.coalescer.enabled = True
    cfg.coalescer.window_ms = 200.0
    for k, v in cfg_edits.items():
        obj = cfg
        parts = k.split("__")
        for p in parts[:-1]:
            obj = getattr(obj, p)
        setattr(obj, parts[-1], v)
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    app.schema.add_class({
        "class": "Ctl", "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "tag", "dataType": ["text"]}],
    })
    rng = np.random.default_rng(13)
    vecs = rng.integers(-8, 8, (N, DIM)).astype(np.float32)
    idx = app.db.get_index("Ctl")
    idx.put_batch([
        StorObj(class_name="Ctl", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"tag": "t"}, vector=vecs[i])
        for i in range(N)])
    return app, idx, vecs


# -- the clamped actuate helper + leased store --------------------------------


def test_set_knob_clamps_every_knob():
    p = _plane()
    assert p._set_knob(KNOB_MARGIN, 99.0, "t") == 4.0
    assert p._set_knob(KNOB_MARGIN, 0.1, "t") == 1.0
    assert p._set_knob(KNOB_CAP_SCALE, 0.01, "t") == 0.25
    assert p._set_knob(KNOB_CAP_SCALE, 3.0, "t") == 1.0
    assert p._set_knob(KNOB_RETRY_SCALE, 0.5, "t") == 1.0
    assert p._set_knob(KNOB_RETRY_SCALE, 100.0, "t") == 8.0
    assert p._set_knob(KNOB_RATE_SCALE, 0.0, "t") == 0.25
    # the window clamp band comes from config (and always includes the
    # coalescer's configured default)
    lo, hi = p._clamps[KNOB_WINDOW_S]
    assert p._set_knob(KNOB_WINDOW_S, 0.0, "t") == lo
    assert p._set_knob(KNOB_WINDOW_S, 60.0, "t") == hi


def test_rescore_cap_is_bucket_snapped():
    p = _plane()
    assert p._set_knob(KNOB_RESCORE_CAP, 500, "t") == 128
    assert p._set_knob(KNOB_RESCORE_CAP, 97, "t") == 96
    assert p._set_knob(KNOB_RESCORE_CAP, 63, "t") == 48
    assert p._set_knob(KNOB_RESCORE_CAP, 1, "t") == 32
    for v in R_BUCKETS:
        assert p._set_knob(KNOB_RESCORE_CAP, v, "t") == v


def test_readers_default_when_disabled_and_read_actuated_when_configured():
    # disabled: every reader is the configured default
    assert controller.coalescer_window_s(0.0015) == 0.0015
    assert controller.admission_margin() == 1.0
    assert controller.tenant_cap_scale() == 1.0
    assert controller.retry_after_scale() == 1.0
    assert controller.rescore_r_cap(128) == 128
    assert controller.take_rate_token("t") is None
    p = controller.configure(_plane())
    p._set_knob(KNOB_MARGIN, 2.0, "t")
    p._set_knob(KNOB_RESCORE_CAP, 64, "t")
    assert controller.admission_margin() == 2.0
    assert controller.rescore_r_cap(128) == 64
    # the cap can never RAISE the index's own maximum
    assert controller.rescore_r_cap(48) == 48


def test_stale_lease_reverts_reader_to_default():
    """A stalled tick thread (no lease refresh) fail-statics at the
    reader in bounded time — no watchdog thread needed."""
    p = controller.configure(_plane())
    p._set_knob(KNOB_MARGIN, 2.0, "t")
    assert controller.admission_margin() == 2.0
    p.lease_s = 0.05
    time.sleep(0.12)
    assert controller.admission_margin() == 1.0
    # ...and a tick's refresh re-arms the lease
    p._refresh_leases()
    assert controller.admission_margin() == 2.0


# -- controller 1: burn-rate brownout -----------------------------------------


def test_brownout_ladder_escalates_and_recovers_with_hysteresis():
    p = _plane(hold_ticks=3)
    burn = {"fast": 100.0}
    p._sense_burn = lambda: (burn["fast"], None)
    p.tick()
    assert p.brownout_stage == 1
    assert p._read(KNOB_MARGIN, 1.0) == p.cfg.brownout_margin
    p.tick()
    assert p.brownout_stage == 2
    assert p._read(KNOB_CAP_SCALE, 1.0) == p.cfg.brownout_cap_scale
    assert p._read(KNOB_RETRY_SCALE, 1.0) == p.cfg.brownout_retry_scale
    assert p._read(KNOB_RATE_SCALE, 1.0) == p.cfg.brownout_rate_scale
    p.tick()
    assert p.brownout_stage == 3
    p.tick()
    assert p.brownout_stage == 3  # the ladder tops out
    # recovery: one stage down per hold_ticks CONSECUTIVE clean ticks
    burn["fast"] = 0.0
    for expected in (3, 3, 2, 2, 2, 1, 1, 1, 0):
        p.tick()
        assert p.brownout_stage == expected
    assert p._read(KNOB_MARGIN, 1.0) == 1.0
    assert p._read(KNOB_CAP_SCALE, 1.0) == 1.0


def test_brownout_square_wave_does_not_oscillate():
    """A burn flapping around the threshold faster than hold_ticks must
    not flap the ladder: the clean-tick counter resets on every burning
    tick, so the stage ratchets up and NEVER steps down mid-wave."""
    p = _plane(hold_ticks=3)
    seq = [100.0, 0.0] * 10  # square wave, period 2 < hold_ticks
    stages = []
    for fast in seq:
        p._sense_burn = lambda fast=fast: (fast, None)
        p.tick()
        stages.append(p.brownout_stage)
    # monotone non-decreasing through the whole wave — zero oscillation
    assert all(b >= a for a, b in zip(stages, stages[1:]))
    assert stages[-1] == 3


def test_brownout_slow_burn_holds_stage_one():
    p = _plane(hold_ticks=2)
    p._sense_burn = lambda: (None, 5.0)  # smolder, no cliff
    for _ in range(5):
        p.tick()
    assert p.brownout_stage == 1  # lights stage 1 and HOLDS — never escalates


def test_brownout_slow_burn_decays_aggressive_stages_to_one():
    """A short fast-burn storm ratchets to stage 3; once the 5 m cliff
    clears, residue in the 1 h window must not PIN stage 3 for the rest
    of the hour — the smolder decays the aggressive stages back to 1 on
    the hysteresis clock and holds there until the slow window clears."""
    p = _plane(hold_ticks=2)
    burn = {"fast": 100.0, "slow": 100.0}
    p._sense_burn = lambda: (burn["fast"], burn["slow"])
    for _ in range(3):
        p.tick()
    assert p.brownout_stage == 3
    burn["fast"] = 0.0
    burn["slow"] = 5.0  # the hour window still tallies the storm
    for expected in (3, 2, 2, 1, 1, 1, 1):  # one stage per hold_ticks, floor 1
        p.tick()
        assert p.brownout_stage == expected
    burn["slow"] = 0.0  # hour window finally clear: normal serving
    p.tick(), p.tick()
    assert p.brownout_stage == 0


def test_straggler_tick_after_shutdown_revert_is_reverted():
    """shutdown() with a stalled tick thread: its join times out and
    shutdown reverts — but the straggling tick completes later and
    re-actuates. The actuation re-arms the (idempotent) revert, so the
    straggler's own exit path restores the defaults it disturbed."""
    p = _plane(hold_ticks=1)
    p._sense_burn = lambda: (100.0, None)
    p.tick()
    assert p._read(KNOB_MARGIN, 1.0) == p.cfg.brownout_margin
    # shutdown's revert (no thread was started, join is a no-op)
    p.shutdown()
    assert p._reverted and p._read(KNOB_MARGIN, 1.0) == 1.0
    # a straggling tick that was already in flight completes now
    p.tick()
    assert not p._reverted  # the actuation re-armed the revert
    assert p._read(KNOB_MARGIN, 1.0) == p.cfg.brownout_margin
    # ...and the run loop's finally (stop is set) reverts it again
    p.revert_all("control plane shutdown")
    assert p._reverted and p._read(KNOB_MARGIN, 1.0) == 1.0
    # idempotent: with nothing re-actuated a repeat call is a no-op
    emitted = []
    p.metrics = None
    orig = incidents.emit
    incidents.emit = lambda kind, **kw: emitted.append(kind)
    try:
        p.revert_all("again")
    finally:
        incidents.emit = orig
    assert emitted == []


def test_brownout_stage3_pauses_and_restores_sampling():
    from weaviate_tpu.monitoring import quality, tracing

    tracer = tracing.configure(tracing.Tracer(sample_rate=0.7))
    auditor = quality.configure(quality.QualityAuditor(
        sample_rate=0.3, start_workers=False))
    try:
        p = _plane(hold_ticks=1)
        burn = {"fast": 100.0}
        p._sense_burn = lambda: (burn["fast"], None)
        for _ in range(3):
            p.tick()
        assert p.brownout_stage == 3
        assert tracer.sample_rate == 0.0
        assert auditor.sample_rate == 0.0
        burn["fast"] = 0.0
        p.tick()  # 3 -> 2 restores optional work
        assert p.brownout_stage == 2
        assert tracer.sample_rate == 0.7
        assert auditor.sample_rate == 0.3
    finally:
        tracing.unconfigure(tracer)
        quality.unconfigure(auditor)


# -- controller 2: recall-guarded candidate budget ----------------------------


def test_budget_cuts_on_slack_holds_in_dead_band_and_backs_off():
    p = _plane(hold_ticks=2, recall_floor=0.98, recall_slack=0.015,
               recall_backoff_margin=0.005)
    sense = {"ewma": 1.0}
    p._sense_recall = lambda: sense["ewma"]
    # slack (1.0 >= 0.995): cut one bucket per hold_ticks
    p.tick()
    assert p._read(KNOB_RESCORE_CAP, 128) == 128  # held, not yet
    p.tick()
    assert p._read(KNOB_RESCORE_CAP, 128) == 96
    p.tick(), p.tick()
    assert p._read(KNOB_RESCORE_CAP, 128) == 64
    # dead band (floor+margin <= ewma < floor+slack): hold position
    sense["ewma"] = 0.99
    for _ in range(4):
        p.tick()
    assert p._read(KNOB_RESCORE_CAP, 128) == 64
    # near the floor: back off IMMEDIATELY (no hysteresis on restores)
    sense["ewma"] = 0.982
    p.tick()
    assert p._read(KNOB_RESCORE_CAP, 128) == 96
    p.tick()
    assert p._read(KNOB_RESCORE_CAP, 128) == 128


def test_budget_reverts_without_recall_signal():
    """No auditor (or a cold one) => the budget may not stay cut: the
    meter that vouched for the cut is gone."""
    p = _plane(hold_ticks=1)
    p._sense_recall = lambda: 1.0
    p.tick(), p.tick()
    assert p._read(KNOB_RESCORE_CAP, 128) < 128
    p._sense_recall = lambda: None
    p.tick()
    assert p._read(KNOB_RESCORE_CAP, 128) == 128


def test_budget_holds_cap_while_brownout_pauses_sampling():
    """When the ladder ITSELF silenced the meter (stage 3), the budget
    holds the last vouched-for cap: restoring to 128 would 4x per-query
    work exactly while the SLO burns, and cutting further would act on
    a frozen EWMA."""
    from weaviate_tpu.monitoring import quality

    auditor = quality.configure(quality.QualityAuditor(
        sample_rate=0.5, start_workers=False))
    try:
        p = _plane(hold_ticks=1, recall_min_samples=2)
        for _ in range(4):
            auditor.window.record("exact_scan", 1.0, 1.0, 0.0, 1, 0.0)
        p.tick(), p.tick()
        held = p._read(KNOB_RESCORE_CAP, 128)
        assert held < 128  # fresh signal: cut
        p._pause_sampling()  # what _enter_stage(3) does
        for _ in range(3):
            p.tick()
        assert p._read(KNOB_RESCORE_CAP, 128) == held  # held, not moved
        p._resume_sampling()  # recovery: fresh signal, steering resumes
        assert p._sense_recall() is not None
        p.tick()  # slack still holds, so the cut can deepen again
        assert p._read(KNOB_RESCORE_CAP, 128) <= held
    finally:
        quality.unconfigure(auditor)


def test_budget_reads_paused_auditor_as_no_signal():
    """Brownout stage 3 zeroes the auditor's sample gate; the
    QualityWindow never decays, so its EWMA is then FROZEN, not fresh —
    the budget must treat a paused gate as no signal (revert, never cut
    on pre-pause numbers while actual recall is unmeasured)."""
    from weaviate_tpu.monitoring import quality

    auditor = quality.configure(quality.QualityAuditor(
        sample_rate=0.5, start_workers=False))
    try:
        p = _plane(hold_ticks=1, recall_min_samples=2)
        for _ in range(4):
            auditor.window.record("exact_scan", 1.0, 1.0, 0.0, 1, 0.0)
        p.tick(), p.tick()
        assert p._read(KNOB_RESCORE_CAP, 128) < 128  # fresh signal: cut
        auditor.set_sample_rate(0.0)                 # stage-3 pause
        assert p._sense_recall() is None
        p.tick()
        assert p._read(KNOB_RESCORE_CAP, 128) == 128  # reverted, held
        auditor.set_sample_rate(0.5)                 # gate back open
        assert p._sense_recall() is not None
    finally:
        quality.unconfigure(auditor)


def test_budget_min_samples_via_real_auditor_window():
    from weaviate_tpu.monitoring import quality

    auditor = quality.configure(quality.QualityAuditor(
        sample_rate=0.5, start_workers=False))
    try:
        p = _plane(recall_min_samples=4)
        assert p._sense_recall() is None  # cold window: no signal
        for _ in range(4):
            auditor.window.record("exact_scan", 0.97, 1.0, 0.0, 1, 0.0)
        ew = p._sense_recall()
        assert ew is not None and 0.96 < ew <= 0.98
    finally:
        quality.unconfigure(auditor)


def test_rescore_r_cap_steers_index_budget(tmp_path):
    """index/tpu.py _rescore_r honors the controller cap — but a cap too
    small for a query's 2k slack threshold is IGNORED for that query
    (zeroing r would force the full-precision exact scan, strictly MORE
    device work; the budget controller may only cut)."""
    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.tpu import TpuVectorIndex

    cfg = vi.HnswUserConfig.from_dict(
        {"distance": vi.DISTANCE_L2}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, str(tmp_path), persist=False)
    assert idx._rescore_r(10, 100_000) == 40           # static: 4k
    p = controller.configure(_plane())
    p._set_knob(KNOB_RESCORE_CAP, 32, "budget")
    assert idx._rescore_r(10, 100_000) == 32           # capped
    # k=20 needs r >= 40 > cap: the cap lapses to the static 128 for this
    # query — identical to controller-off (r=4k=80), NOT the exact scan
    assert idx._rescore_r(20, 100_000) == 80
    # deep k where even the static max leaves no slack: exact scan either way
    assert idx._rescore_r(80, 100_000) == 0
    controller.unconfigure(p)
    assert idx._rescore_r(10, 100_000) == 40           # reverted


# -- controller 3: coalescer window / pipeline depth --------------------------


def test_lanes_widen_window_when_queue_dominated_and_walk_back():
    from weaviate_tpu.serving.coalescer import QueryCoalescer

    co = QueryCoalescer(window_s=0.002, max_batch=64)
    try:
        p = _plane(hold_ticks=2, coalescer=co, depth_max=2)
        sig = {"duty_cycle": 0.95, "queue_wait_mean_ms": 30.0,
               "dispatches": 50}
        p._sense_lanes = lambda: dict(sig)
        default = co.window_s
        p.tick(), p.tick()
        widened = p._read(KNOB_WINDOW_S, default)
        assert widened == pytest.approx(default * 1.5)
        # starved device, short waits: walk back toward the default
        sig.update(duty_cycle=0.1, queue_wait_mean_ms=0.0)
        p.tick(), p.tick()
        assert p._read(KNOB_WINDOW_S, default) == pytest.approx(default)
        # too little traffic: hold (no actuation from 4 dispatches)
        sig.update(dispatches=2, duty_cycle=0.95, queue_wait_mean_ms=30.0)
        p.tick(), p.tick()
        assert p._read(KNOB_WINDOW_S, default) == pytest.approx(default)
    finally:
        co.shutdown()


def test_lanes_hysteresis_counts_one_direction_only():
    """A load flapping between queue-dominated and device-starved every
    tick must never actuate the window: the hold counter tracks
    CONSECUTIVE qualifying ticks in ONE direction, so mixed evidence
    (one widen tick + one narrow tick) is not hold_ticks=2 of anything."""
    from weaviate_tpu.serving.coalescer import QueryCoalescer

    co = QueryCoalescer(window_s=0.002, max_batch=64)
    try:
        p = _plane(hold_ticks=2, coalescer=co, depth_max=2)
        widen = {"duty_cycle": 0.95, "queue_wait_mean_ms": 30.0,
                 "dispatches": 50}
        narrow = {"duty_cycle": 0.1, "queue_wait_mean_ms": 0.0,
                  "dispatches": 50}
        square = [widen, narrow]
        i = {"n": 0}

        def sense():
            i["n"] += 1
            return dict(square[i["n"] % 2])

        p._sense_lanes = sense
        default = co.window_s
        for _ in range(8):
            p.tick()
        assert p._read(KNOB_WINDOW_S, default) == pytest.approx(default)
        assert p._depth == p._depth_default
    finally:
        co.shutdown()


def test_lanes_window_clamped_at_configured_max():
    from weaviate_tpu.serving.coalescer import QueryCoalescer

    co = QueryCoalescer(window_s=0.002, max_batch=64)
    try:
        p = _plane(hold_ticks=1, coalescer=co, window_max_ms=4.0)
        p._sense_lanes = lambda: {"duty_cycle": 0.95,
                                  "queue_wait_mean_ms": 100.0,
                                  "dispatches": 50}
        for _ in range(10):
            p.tick()
        assert p._read(KNOB_WINDOW_S, co.window_s) == pytest.approx(0.004)
    finally:
        co.shutdown()


def test_pipeline_depth_deficit_mechanics():
    """Depth up releases permits immediately; depth down queues a
    deficit that completing lanes absorb — an in-flight dispatch is
    never forcibly reclaimed."""
    from weaviate_tpu.serving.coalescer import QueryCoalescer, _Lane

    co = QueryCoalescer(window_s=60.0, max_batch=64, pipeline_depth=1)
    try:
        assert co.set_pipeline_depth(3) == 3
        # 3 permits live: all three acquires succeed without blocking
        for _ in range(3):
            assert co._inflight.acquire(blocking=False)
        co.set_pipeline_depth(1)
        assert co._depth_deficit == 2
        # two lane completions pay down the deficit instead of releasing
        for _ in range(2):
            lane = _Lane(("k",), None, None, K, False, 0.0)
            co._release_lane(lane)
        assert co._depth_deficit == 0
        assert not co._inflight.acquire(blocking=False)
        # the third completion frees the single configured slot again
        co._release_lane(_Lane(("k2",), None, None, K, False, 0.0))
        assert co._inflight.acquire(blocking=False)
        co._inflight.release()
    finally:
        co.shutdown()


def test_lanes_deepen_pipeline_on_bubble_and_restore():
    from weaviate_tpu.serving.coalescer import QueryCoalescer

    co = QueryCoalescer(window_s=0.002, max_batch=64, pipeline_depth=1)
    try:
        p = _plane(hold_ticks=1, coalescer=co, depth_max=2)
        # pipeline bubble: device idle while work queues
        p._sense_lanes = lambda: {"duty_cycle": 0.1,
                                  "queue_wait_mean_ms": 50.0,
                                  "dispatches": 50}
        p.tick()
        assert co._depth == 2
        # device saturated: extra depth walks back to the default
        p._sense_lanes = lambda: {"duty_cycle": 0.95,
                                  "queue_wait_mean_ms": 0.5,
                                  "dispatches": 50}
        p.tick()
        assert co._depth == 1
    finally:
        co.shutdown()


# -- controller 4: tenant token-bucket rate quotas ----------------------------


def test_token_bucket_rate_weight_and_retry_hint():
    b = controller._TokenBuckets(rate_qps=10.0, burst_s=0.01,
                                 weights={"heavy": 2.0})
    # burst = max(rate*burst_s, 1) = 1 token: the second take sheds
    assert b.take("light") is None
    ra = b.take("light")
    assert ra is not None and 0.0 < ra <= 0.1
    # time-to-next-token scales with the tenant's rate: the weight-2
    # tenant refills twice as fast
    assert b.take("heavy") is None
    ra2 = b.take("heavy")
    assert ra2 is not None and ra2 < ra
    # brownout rate_scale shrinks the refill => a LONGER hint (pin the
    # bucket to empty so wall-clock refill can't race the comparison)
    b2 = controller._TokenBuckets(rate_qps=10.0, burst_s=0.1)
    assert b2.take("t") is None
    with b2._lock:
        b2._buckets["t"][0] = 0.0
        b2._buckets["t"][1] = time.monotonic()
    assert b2.take("t", scale=0.5) == pytest.approx(1.0 / 5.0, rel=0.2)


def test_token_bucket_refills_and_prunes():
    b = controller._TokenBuckets(rate_qps=50.0, burst_s=0.02)
    assert b.take("t") is None
    assert b.take("t") is not None
    time.sleep(0.05)  # > 1/50 s: a token accrued
    assert b.take("t") is None
    b.prune(idle_s=0.0)
    assert b.stats()["tenants"] == 0


def test_rate_quota_sheds_tenant_rate_at_admission(tmp_path):
    app, idx, vecs = _mk_app(tmp_path)
    p = controller.configure(_plane(tenant_rate_qps=0.5,
                                    tenant_rate_burst_s=1.0))
    try:
        shard = idx.single_local_shard()
        co = app.coalescer
        w = co.submit(shard, vecs[0], K, tenant="rated")
        assert w is not None
        with pytest.raises(robustness.OverloadedError) as ei:
            co.submit(shard, vecs[1], K, tenant="rated")
        assert "tenant_rate" not in str(ei.value)  # message names the quota
        assert "rate quota" in str(ei.value)
        # Retry-After = time-to-next-token (2 s at 0.5 qps, one spent)
        assert 0.5 < ei.value.retry_after_s <= 2.5
        assert co.stats()["shed"].get("tenant_rate") == 1
        assert co.stats()["tenants"]["rated"]["shed"]["tenant_rate"] == 1
        # a different tenant has its own bucket
        assert co.submit(shard, vecs[2], K, tenant="other-t") is not None
    finally:
        controller.unconfigure(p)
        app.shutdown()


# -- brownout knobs at coalescer admission ------------------------------------


def test_admission_margin_sheds_deadline_unreachable_earlier(tmp_path):
    app, idx, vecs = _mk_app(tmp_path, coalescer__window_ms=60_000.0)
    p = controller.configure(_plane())
    try:
        shard = idx.single_local_shard()
        co = app.coalescer
        # backlog + a warmed drain EWMA: est_wait = 1 row / 10 rows/s
        assert co.submit(shard, vecs[0], K, tenant="m") is not None
        co._tenants["m"].ewma_rows_per_s = 10.0
        with robustness.deadline_scope(250.0):
            # est 0.1 s < 0.25 s remaining: admitted at margin 1.0
            assert co.submit(shard, vecs[1], K, tenant="m") is not None
        p._set_knob(KNOB_MARGIN, 4.0, "brownout")
        with robustness.deadline_scope(250.0), \
                pytest.raises(robustness.OverloadedError) as ei:
            co.submit(shard, vecs[2], K, tenant="m")
        assert "deadline_unreachable" in str(ei.value)
        assert co.stats()["shed"].get("deadline_unreachable") == 1
        assert ei.value.retry_after_s > 0
    finally:
        controller.unconfigure(p)
        app.shutdown()


def test_tenant_cap_scale_shrinks_budget_and_retry_scale_applies(tmp_path):
    app, idx, vecs = _mk_app(
        tmp_path, coalescer__window_ms=60_000.0,
        coalescer__max_queued_rows=40, coalescer__max_request_rows=4,
        tenancy__max_queued_rows_fraction=0.5)
    p = controller.configure(_plane())
    try:
        shard = idx.single_local_shard()
        co = app.coalescer
        assert co._tenant_row_cap == 20
        # another tenant has work (the budget only fires then)
        assert co.submit(shard, vecs[0], K, tenant="light") is not None
        for i in range(4):  # tenant "big": 16 rows in system
            assert co.submit(shard, vecs[4 * i: 4 * i + 4], K,
                             tenant="big") is not None
        # 16+4 <= 20: admitted at scale 1.0... but at scale 0.5 (cap 10)
        # the SAME submit sheds, with the Retry-After hint scaled 2x
        p._set_knob(KNOB_CAP_SCALE, 0.5, "brownout")
        p._set_knob(KNOB_RETRY_SCALE, 2.0, "brownout")
        with pytest.raises(robustness.OverloadedError) as ei:
            co.submit(shard, vecs[16:20], K, tenant="big")
        assert "tenant_budget" in str(ei.value)
        assert "tenant cap 10" in str(ei.value)
        base = max(co.window_s * 4.0, 0.05)  # cold-start drain hint
        assert ei.value.retry_after_s == pytest.approx(2.0 * base)
        # back at scale 1.0 the request fits the configured cap again
        p._set_knob(KNOB_CAP_SCALE, 1.0, "brownout")
        assert co.submit(shard, vecs[16:20], K, tenant="big") is not None
    finally:
        controller.unconfigure(p)
        app.shutdown()


def test_gate_retry_after_uses_drain_ewma(tmp_path):
    """The front-door concurrency gate's Retry-After derives from the
    coalescer's per-tenant drain EWMA (the PR-11 satellite) instead of
    the old fixed 1 s — and falls back to 1 s only while cold."""
    app, idx, vecs = _mk_app(tmp_path,
                             tenancy__max_concurrent_requests=1)
    try:
        gate = app.tenant_gate
        assert gate.enter("g")  # occupy the single slot
        with pytest.raises(robustness.OverloadedError) as cold:
            with robustness.tenant_concurrency("g"):
                pass
        assert cold.value.retry_after_s == 1.0  # no EWMA yet
        app.coalescer._ewma_rows_per_s = 40.0  # warmed drain estimate
        with pytest.raises(robustness.OverloadedError) as warm:
            with robustness.tenant_concurrency("g"):
                pass
        # max(1 row, ...) / (40 rows/s * depth 1) = 0.025 s — but the
        # gate floors at 0.25 s: its slots free on a request-duration
        # cadence, and a tenant whose slots are held by DIRECT-path
        # requests puts no rows in the coalescer at all, so a tiny
        # idle-queue drain hint would invite refusal churn
        assert warm.value.retry_after_s == pytest.approx(0.25)
        # a congested SHARED queue is the honest drain clock for a
        # gate-capped tenant (it holds almost no rows of its own): the
        # hint scales with the global backlog, so a storm's conformant
        # abuser backs off proportionally to real queue drain
        app.coalescer._queued_rows = 80  # 80 rows / (40 rows/s) = 2 s
        with pytest.raises(robustness.OverloadedError) as congested:
            with robustness.tenant_concurrency("g"):
                pass
        assert congested.value.retry_after_s == pytest.approx(2.0)
        app.coalescer._queued_rows = 0
        gate.leave("g")
    finally:
        app.shutdown()


# -- fail-static: death, stall, unconfigure -----------------------------------


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_tick_die_reverts_knobs_and_journals(tmp_path):
    """The serving.controller.tick fault point's `die` action kills the
    tick thread; its finally must revert every actuated knob to the
    configured default, journal a controller_revert, and leave serving
    on static defaults."""
    journal = incidents.OpsJournal(size=64)
    incidents.configure(journal=journal)
    inj = faults.configure(faults.FaultInjector(seed=3))
    p = controller.configure(ControlPlane(start=False, tick_s=0.01,
                                          hold_ticks=1))
    p._sense_burn = lambda: (100.0, None)
    p.tick()  # actuate: stage 1 engages the margin knob
    assert controller.admission_margin() > 1.0
    try:
        inj.plan("serving.controller.tick", "die", times=1)
        t = threading.Thread(target=p._run, daemon=True)
        p._thread = t
        t.start()
        t.join(timeout=5.0)
        assert not t.is_alive(), "die did not kill the tick thread"
        # fail-static: every knob back at its configured default
        assert controller.admission_margin() == 1.0
        assert controller.rescore_r_cap(128) == 128
        assert p.brownout_stage == 0 and p._reverted
        kinds = {e["kind"] for e in journal.tail()}
        assert "controller_revert" in kinds
        assert "fault_injected" in kinds
    finally:
        faults.unconfigure(inj)
        controller.unconfigure(p)


def test_unconfigure_restores_every_knob_and_object_state():
    from weaviate_tpu.monitoring import quality, tracing
    from weaviate_tpu.serving.coalescer import QueryCoalescer

    tracer = tracing.configure(tracing.Tracer(sample_rate=1.0))
    auditor = quality.configure(quality.QualityAuditor(
        sample_rate=0.4, start_workers=False))
    co = QueryCoalescer(window_s=0.002, pipeline_depth=1)
    try:
        p = controller.configure(_plane(coalescer=co, hold_ticks=1))
        burn = {"fast": 100.0}
        p._sense_burn = lambda: (burn["fast"], None)
        for _ in range(3):
            p.tick()                       # ladder to stage 3
        p._actuate_depth(2, "test")
        p._set_knob(KNOB_RESCORE_CAP, 48, "budget")
        assert p.brownout_stage == 3 and co._depth == 2
        assert tracer.sample_rate == 0.0 and auditor.sample_rate == 0.0
        controller.unconfigure(p)
        assert controller.get_plane() is None
        assert controller.admission_margin() == 1.0
        assert controller.tenant_cap_scale() == 1.0
        assert controller.retry_after_scale() == 1.0
        assert controller.rescore_r_cap(128) == 128
        assert co._depth == 1
        assert tracer.sample_rate == 1.0 and auditor.sample_rate == 0.4
        assert p.brownout_stage == 0
        # the final summary was stashed for the CI artifact
        assert any(s.get("reverted") for s in controller.recent_summaries())
    finally:
        tracing.unconfigure(tracer)
        quality.unconfigure(auditor)
        co.shutdown()


def test_actuations_are_journaled_with_burst_coalescing():
    journal = incidents.OpsJournal(size=64)
    incidents.configure(journal=journal)
    p = _plane()
    p._set_knob(KNOB_MARGIN, 2.0, "brownout", reason="stage 1")
    p._set_knob(KNOB_MARGIN, 3.0, "brownout", reason="stage 1")
    tail = journal.tail()
    acts = [e for e in tail if e["kind"] == "controller_actuation"]
    # burst kind: two actuations of ONE knob coalesce into one counted
    # ring entry per (kind, scope) within the burst window
    assert len(acts) == 1 and acts[0]["count"] == 2
    assert acts[0]["scope"] == KNOB_MARGIN
    assert p._actuations["brownout"] == 2
    assert len(p._recent) == 2


# -- disabled mode / lifecycle ------------------------------------------------


def test_disabled_serving_path_constructs_nothing(tmp_path, monkeypatch):
    built = []
    for name in ("ControlPlane", "_TokenBuckets"):
        orig = getattr(controller, name)

        def make(orig=orig, name=name):
            class Spy(orig):
                def __init__(self, *a, **kw):
                    built.append(name)
                    super().__init__(*a, **kw)
            return Spy
        monkeypatch.setattr(controller, name, make())
    app, idx, vecs = _mk_app(tmp_path)  # CONTROL_PLANE_ENABLED off
    try:
        assert app.control_plane is None
        assert controller.get_plane() is None
        r = app.traverser.get_class(GetParams(
            class_name="Ctl", near_vector={"vector": vecs[0].tolist()},
            limit=K))
        assert len(r) == K
        assert built == []
    finally:
        app.shutdown()


def test_enabled_app_wires_configures_and_reverts_on_shutdown(tmp_path):
    app, idx, vecs = _mk_app(tmp_path, controller__enabled=True,
                             controller__tick_s=30.0)
    try:
        p = controller.get_plane()
        assert p is app.control_plane and p is not None
        assert p.coalescer is app.coalescer
        assert p._thread is not None and p._thread.is_alive()
        p._set_knob(KNOB_MARGIN, 2.0, "brownout")
    finally:
        app.shutdown()
    assert controller.get_plane() is None
    assert controller.admission_margin() == 1.0


def test_debug_controllers_endpoint_and_metrics(tmp_path):
    from weaviate_tpu.server import RestServer

    app, idx, vecs = _mk_app(tmp_path, controller__enabled=True,
                             controller__tick_s=30.0)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        app.control_plane._set_knob(KNOB_RESCORE_CAP, 96, "budget")
        app.control_plane._publish_gauges()
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        conn.request("GET", "/debug/controllers")
        doc = json.loads(conn.getresponse().read())
        conn.close()
        assert doc["enabled"] is True
        assert doc["controllers"]["brownout"]["stage"] == 0
        assert doc["knobs"][KNOB_RESCORE_CAP] == {
            "value": 96, "default": 128.0, "actuated": True}
        assert doc["knobs"]["pipeline_depth"]["actuated"] is False
        assert doc["thread_alive"] is True
        text = app.metrics.expose().decode()
        assert "weaviate_controller_brownout_stage" in text
        assert 'weaviate_controller_knob{knob="rescore_r_cap"} 96.0' in text
        assert "weaviate_controller_actuations_total" in text
    finally:
        srv.stop()
        app.shutdown()


def test_debug_controllers_disabled_reports_disabled(tmp_path):
    from weaviate_tpu.server import RestServer

    app, idx, vecs = _mk_app(tmp_path)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        conn.request("GET", "/debug/controllers")
        doc = json.loads(conn.getresponse().read())
        conn.close()
        assert doc == {"enabled": False}
    finally:
        srv.stop()
        app.shutdown()


def test_flight_recorder_bundle_carries_controller_section(tmp_path):
    app, idx, vecs = _mk_app(tmp_path, controller__enabled=True,
                             controller__tick_s=30.0,
                             incidents__dir=str(tmp_path / "inc"))
    try:
        app.control_plane._set_knob(KNOB_MARGIN, 2.0, "brownout")
        bundle = app.flight_recorder.capture("manual", reason="test")
        assert "controllers" in bundle
        assert bundle["controllers"]["knobs"][KNOB_MARGIN]["actuated"]
    finally:
        app.shutdown()


# -- config -------------------------------------------------------------------


def test_config_env_parsing():
    cfg = load_config({
        "CONTROL_PLANE_ENABLED": "true",
        "CONTROLLER_TICK_S": "0.5",
        "CONTROLLER_HOLD_TICKS": "5",
        "CONTROLLER_BROWNOUT_ENABLED": "false",
        "CONTROLLER_RECALL_FLOOR": "0.95",
        "CONTROLLER_WINDOW_MAX_MS": "10",
        "CONTROLLER_DEPTH_MAX": "3",
        "TENANT_RATE_QPS": "25",
        "TENANT_RATE_BURST_S": "1.5",
    })
    c = cfg.controller
    assert c.enabled and c.tick_s == 0.5 and c.hold_ticks == 5
    assert not c.brownout_enabled and c.budget_enabled
    assert c.recall_floor == 0.95 and c.window_max_ms == 10.0
    assert c.depth_max == 3
    assert c.tenant_rate_qps == 25.0 and c.tenant_rate_burst_s == 1.5


@pytest.mark.parametrize("env", [
    {"CONTROLLER_TICK_S": "0"},
    {"CONTROLLER_HOLD_TICKS": "0"},
    {"CONTROLLER_BROWNOUT_MARGIN": "0.5"},
    {"CONTROLLER_BROWNOUT_CAP_SCALE": "0"},
    {"CONTROLLER_BROWNOUT_RETRY_SCALE": "0.9"},
    {"CONTROLLER_RECALL_FLOOR": "1.5"},
    {"CONTROLLER_RECALL_SLACK": "0"},
    {"CONTROLLER_RECALL_MIN_SAMPLES": "0"},
    {"CONTROLLER_WINDOW_MIN_MS": "0"},
    {"CONTROLLER_WINDOW_MIN_MS": "8", "CONTROLLER_WINDOW_MAX_MS": "6"},
    {"CONTROLLER_DEPTH_MAX": "0"},
    {"CONTROLLER_DUTY_LO": "0.9", "CONTROLLER_DUTY_HI": "0.8"},
    {"TENANT_RATE_QPS": "-1"},
    {"TENANT_RATE_BURST_S": "0"},
])
def test_config_validation_rejects(env):
    with pytest.raises(ConfigError):
        load_config(env)


# -- the storm journey --------------------------------------------------------


def test_brownout_storm_journey(tmp_path):
    """End to end under the PR-5 seeded device-error storm: concurrent
    REST clients under tight deadlines against an undersized queue push
    the SLO engine into fast burn -> the brownout ladder engages
    (journaled stage transitions + actuations), every shed reply
    carries a Retry-After, nothing hangs, and App shutdown reverts
    every knob."""
    from weaviate_tpu.server import RestServer

    app, idx, vecs = _mk_app(
        tmp_path,
        coalescer__window_ms=2.0,
        coalescer__max_queued_rows=8,
        coalescer__max_request_rows=4,
        controller__enabled=True,
        controller__tick_s=0.05,
        controller__hold_ticks=2,
        robustness__breaker_reset_ms=100.0,
        robustness__fault_injection=(
            "index.tpu.dispatch:device_error:times=inf:p=0.4"),
        robustness__fault_injection_seed=11,
        incidents__slo_min_events=5,
        incidents__dir=str(tmp_path / "inc"))
    srv = RestServer(app, port=0)
    srv.start()
    gql = ('{ Get { Ctl(limit: %d, nearVector: {vector: %s}) '
           '{ _additional { distance } } } }')
    stop = threading.Event()
    outcomes = {"ok": 0, "shed": 0, "deadline": 0, "error": 0}
    retry_after_seen = []
    lock = threading.Lock()

    def client(tid):
        lrng = np.random.default_rng(300 + tid)
        while not stop.is_set():
            q = vecs[int(lrng.integers(0, N))]
            body = json.dumps({"query": gql % (
                K, json.dumps([float(x) for x in q]))})
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=25)
            try:
                conn.request("POST", "/v1/graphql", body=body, headers={
                    "Content-Type": "application/json",
                    "X-Request-Timeout-Ms": "60"})
                resp = conn.getresponse()
                resp.read()
                with lock:
                    if resp.status == 200:
                        outcomes["ok"] += 1
                    elif resp.status == 429:
                        outcomes["shed"] += 1
                        ra = resp.getheader("Retry-After")
                        if ra is not None:
                            retry_after_seen.append(int(ra))
                    elif resp.status == 504:
                        outcomes["deadline"] += 1
                    else:
                        outcomes["error"] += 1
            except Exception:  # noqa: BLE001 — outcome accounting
                with lock:
                    outcomes["error"] += 1
            finally:
                conn.close()

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(8)]
    try:
        for t in threads:
            t.start()
        deadline = time.monotonic() + 12.0
        while time.monotonic() < deadline \
                and app.control_plane.brownout_stage < 1:
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), "client hung"
        p = app.control_plane
        assert p.brownout_stage >= 1, (
            f"brownout never engaged: outcomes={outcomes}")
        # the ladder's moves are journaled under the new kinds
        counts = app.ops_journal.counts()
        assert counts.get("controller_brownout", 0) >= 1
        assert counts.get("controller_actuation", 0) >= 1
        # the engaged ladder is visible on the serving path
        assert controller.admission_margin() > 1.0
        # every shed reply carried a retry hint
        assert all(ra >= 1 for ra in retry_after_seen)
        summary = p.summary()
        assert summary["controllers"]["brownout"]["stage"] == p.brownout_stage
        assert summary["actuations"].get("brownout", 0) >= 1
    finally:
        stop.set()
        srv.stop()
        app.shutdown()
    # shutdown reverted the world to static defaults
    assert controller.get_plane() is None
    assert controller.admission_margin() == 1.0
    assert controller.rescore_r_cap(128) == 128
