"""Concurrency stress tier — the analog of the reference's `-race`
integration runs (test/integration/run.sh:29-31): real disk, threads
hammering the same shard/bucket/index concurrently, asserting invariants
instead of data races (CPython's runtime surfaces races as corrupted
structures, lost updates, or exceptions rather than a sanitizer report).

Kept short enough for every CI run; crank _SECONDS up for a soak.
"""

import threading
import time
import uuid as uuidlib

import numpy as np

from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.storage.lsm import STRATEGY_ROARINGSET, Store

_SECONDS = 1.5
DIM = 8


def _run_all(workers):
    """Run workers until the deadline; re-raise the first error from any."""
    errors: list[BaseException] = []
    stop = threading.Event()

    def wrap(fn):
        def go():
            try:
                while not stop.is_set():
                    fn()
            except BaseException as e:  # noqa: BLE001 — collected + re-raised
                errors.append(e)
                stop.set()
        return go

    threads = [threading.Thread(target=wrap(w), daemon=True) for w in workers]
    for t in threads:
        t.start()
    deadline = time.monotonic() + _SECONDS
    while time.monotonic() < deadline and not stop.is_set():
        time.sleep(0.02)
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "worker wedged (deadlock?)"
    if errors:
        raise errors[0]


def test_lsm_bucket_concurrent_readers_writers_compaction(tmp_path):
    store = Store(str(tmp_path / "lsm"))
    b = store.create_or_load_bucket("rs", STRATEGY_ROARINGSET,
                                    memtable_max_bytes=4096)
    seq = iter(range(10_000_000))
    lock = threading.Lock()

    def writer():
        with lock:
            i = next(seq)
        b.roaring_add_many(f"k{i % 7}".encode(), [i])

    def reader():
        got = b.roaring_get(b"k3")
        arr = got.to_array()
        # ids under one key keep the key's residue (torn writes would not)
        assert all(int(x) % 7 == 3 for x in arr[:50])

    def compactor():
        store.compact_once()
        time.sleep(0.01)

    _run_all([writer, writer, reader, reader, compactor])
    total = sum(len(b.roaring_get(f"k{j}".encode())) for j in range(7))
    with lock:
        written = next(seq)
    assert total == written
    store.shutdown()


def test_shard_concurrent_crud_search(tmp_path):
    from weaviate_tpu.db.shard import Shard

    cd = ClassDef(name="Conc", properties=[
        Property(name="t", data_type=["text"]),
        Property(name="n", data_type=["int"]),
    ], vector_index_type="hnsw_tpu")
    shard = Shard("shard-0", str(tmp_path / "conc" / "shard-0"), cd,
                  parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"}))
    rng = np.random.default_rng(0)
    base = [StorObj(class_name="Conc", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"t": f"doc {i}", "n": i},
                    vector=rng.standard_normal(DIM).astype(np.float32))
            for i in range(300)]
    shard.put_batch(base)
    seq = iter(range(100_000, 10_000_000))
    lock = threading.Lock()
    deleted = []

    def writer():
        with lock:
            i = next(seq)
        shard.put_object(StorObj(
            class_name="Conc", uuid=str(uuidlib.UUID(int=i + 1)),
            properties={"t": f"doc {i}", "n": i},
            vector=np.random.default_rng(i).standard_normal(DIM).astype(np.float32)))

    def deleter():
        with lock:
            if len(deleted) >= 250:
                return
            target = base[len(deleted)]
            deleted.append(target)
        shard.delete_object(target.uuid)

    def searcher():
        q = np.random.default_rng(1).standard_normal((4, DIM)).astype(np.float32)
        res = shard.object_vector_search(q, k=5)
        assert len(res) == 4
        for rows in res:
            ds = [r.distance for r in rows]
            assert ds == sorted(ds)

    def bm25():
        rows = shard.object_search(10, None, {"query": "doc"})
        assert len(rows) <= 10

    _run_all([writer, writer, deleter, searcher, bm25])
    # every surviving uuid readable; every deleted uuid gone
    for o in deleted:
        assert shard.object_by_uuid(o.uuid, False) is None
    for o in base[len(deleted):]:
        assert shard.object_by_uuid(o.uuid, False) is not None
    shard.shutdown()


def test_tpu_index_concurrent_add_search_compact(tmp_path):
    from weaviate_tpu.index.tpu import TpuVectorIndex

    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    idx = TpuVectorIndex(cfg, str(tmp_path / "ix"), persist=False)
    rng = np.random.default_rng(0)
    idx.add_batch(np.arange(500), rng.standard_normal((500, DIM)).astype(np.float32))
    seq = iter(range(1000, 10_000_000))
    lock = threading.Lock()

    def adder():
        with lock:
            i = next(seq)
        idx.add(i, np.random.default_rng(i).standard_normal(DIM).astype(np.float32))

    def deleter():
        with lock:
            i = next(seq)
        idx.add(i, np.random.default_rng(i).standard_normal(DIM).astype(np.float32))
        idx.delete(i)

    def searcher():
        q = np.random.default_rng(2).standard_normal((8, DIM)).astype(np.float32)
        ids, dists = idx.search_by_vectors(q, 3)
        assert ids.shape[0] == 8

    def compactor():
        idx.compact()
        time.sleep(0.05)

    _run_all([adder, deleter, searcher, compactor])
    # live count consistent: 500 base + adds - deletes, all deletes applied
    ids, _ = idx.search_by_vectors(
        np.zeros((1, DIM), np.float32), min(10, len(idx)))
    assert len(idx) >= 500


def test_shard_async_search_races_writes(tmp_path):
    """The async serving path (deferred hydration) racing batch writes and
    deletes: finalize() must always hydrate a consistent snapshot — sorted
    distances, no duplicate uuids within a row, no exceptions — while the
    LSM and the device store churn underneath it."""
    from weaviate_tpu.db.shard import Shard

    cd = ClassDef(name="Race", properties=[
        Property(name="t", data_type=["text"]),
    ], vector_index_type="hnsw_tpu")
    shard = Shard("shard-0", str(tmp_path / "race" / "shard-0"), cd,
                  parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"}))
    rng = np.random.default_rng(3)
    base = [StorObj(class_name="Race", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"t": f"doc {i}"},
                    vector=rng.standard_normal(DIM).astype(np.float32))
            for i in range(400)]
    shard.put_batch(base)
    seq = iter(range(500_000, 10_000_000))
    lock = threading.Lock()

    def batch_writer():
        with lock:
            start = next(seq)
        objs = [StorObj(class_name="Race", uuid=str(uuidlib.UUID(int=start * 100 + j)),
                        properties={"t": f"doc {start} {j}"},
                        vector=np.random.default_rng(start + j)
                        .standard_normal(DIM).astype(np.float32))
                for j in range(8)]
        errs = shard.put_batch(objs)
        assert all(e is None for e in errs)

    def deleter():
        with lock:
            i = next(seq)
        u = str(uuidlib.UUID(int=i + 1))
        shard.put_object(StorObj(
            class_name="Race", uuid=u, properties={"t": "x"},
            vector=np.random.default_rng(i).standard_normal(DIM).astype(np.float32)))
        shard.delete_object(u)

    def async_searcher():
        q = np.random.default_rng(7).standard_normal((8, DIM)).astype(np.float32)
        done = shard.object_vector_search_async(q, 5)
        rows = done()
        assert len(rows) == 8
        for res in rows:
            ds = [r.distance for r in res]
            assert ds == sorted(ds)
            uuids = [r.obj.uuid for r in res]
            assert len(set(uuids)) == len(uuids)

    _run_all([batch_writer, deleter, async_searcher, async_searcher])
    # post-race sanity: a fresh async search hydrates every winner
    done = shard.object_vector_search_async(
        np.stack([o.vector for o in base[:4]]), 3)
    rows = done()
    assert all(rows[i][0].obj.uuid == base[i].uuid for i in range(4))
    shard.shutdown()
