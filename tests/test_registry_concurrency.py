"""JGL005/JGL004 satellite regressions: the index-type registry survives an
8-thread hammer (the lock added after graftlint flagged the unlocked
mutation), and device-fallback observability always counts while logging at
most once per interval."""

import logging
import threading


from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.monitoring.metrics import (
    get_metrics,
    record_device_fallback,
)

N_THREADS = 8
N_ROUNDS = 200


def test_register_index_type_hammered_from_8_threads():
    added = [f"hammer-{t}-{i}" for t in range(N_THREADS) for i in range(N_ROUNDS)]
    errors = []
    start = threading.Barrier(N_THREADS)

    def worker(t):
        try:
            start.wait()
            for i in range(N_ROUNDS):
                name = f"hammer-{t}-{i}"
                vi.register_index_type(
                    name, lambda d, _n=name: vi.HnswUserConfig.from_dict(d, "hnsw"))
                # interleave reads: lookups race the writers in production
                # (schema create resolves types while modules register)
                cfg = vi.parse_and_validate_config(name, None)
                assert cfg is not None
                assert name in vi.registered_index_types()
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(N_THREADS)]
    try:
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        assert not errors, errors
        registered = set(vi.registered_index_types())
        assert set(added) <= registered
    finally:
        with vi._parsers_lock:
            for name in added:
                vi._PARSERS.pop(name, None)


def _counter_value(component, reason):
    c = get_metrics().device_fallbacks.labels(component=component, reason=reason)
    return c._value.get()


def test_record_device_fallback_counts_every_call(caplog):
    before = _counter_value("test.comp", "unit")
    with caplog.at_level(logging.WARNING, logger="weaviate_tpu.monitoring.fallback"):
        logged = [record_device_fallback("test.comp", "unit",
                                         RuntimeError("boom"), interval=3600)
                  for _ in range(50)]
    assert _counter_value("test.comp", "unit") == before + 50
    # rate limit: exactly one log line for the burst
    assert logged.count(True) == 1
    msgs = [r for r in caplog.records
            if "test.comp" in r.getMessage() and "reason=unit" in r.getMessage()]
    assert len(msgs) == 1


def test_record_device_fallback_hammered_from_8_threads(caplog):
    before = _counter_value("test.hammer", "burst")
    start = threading.Barrier(N_THREADS)
    logged_flags = []
    lock = threading.Lock()

    def worker():
        start.wait()
        for _ in range(N_ROUNDS):
            flag = record_device_fallback("test.hammer", "burst", interval=3600)
            with lock:
                logged_flags.append(flag)

    with caplog.at_level(logging.WARNING, logger="weaviate_tpu.monitoring.fallback"):
        threads = [threading.Thread(target=worker) for _ in range(N_THREADS)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
    # every call counted, no lost increments
    assert _counter_value("test.hammer", "burst") == before + N_THREADS * N_ROUNDS
    # the log gate admits exactly one writer per interval
    assert logged_flags.count(True) == 1


def test_record_device_fallback_log_false_still_counts(caplog):
    before = _counter_value("test.silent", "counted")
    with caplog.at_level(logging.WARNING, logger="weaviate_tpu.monitoring.fallback"):
        assert record_device_fallback("test.silent", "counted", log=False) is False
    assert _counter_value("test.silent", "counted") == before + 1
    assert not [r for r in caplog.records if "test.silent" in r.getMessage()]
