"""hnsw_tpu_mesh through the FULL serving stack on the virtual 8-CPU mesh:
REST schema + batch import, gRPC BatchSearch, and restart-replay onto a
DIFFERENT mesh size (the placement-independence claim in index/mesh.py —
the vector log carries no device placement, so an operator can move a
shard between pod slices and the replay re-balances).
"""

import json
import uuid as uuidlib

import grpc  # noqa: F401 — ensures grpcio present for the client
import numpy as np
import pytest

from weaviate_tpu.grpcapi import weaviate_pb2 as pb
from weaviate_tpu.server import App, RestServer
from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

DIM = 16
N = 300


def _req(port, method, path, body=None):
    import urllib.request

    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=30) as resp:
        raw = resp.read()
        return resp.status, json.loads(raw) if raw else None


def _mk_app(tmp_path):
    # mesh size comes from the class's vectorIndexConfig.meshDevices (the
    # restart half of the test edits it in the persisted schema)
    app = App(data_path=str(tmp_path / "data"))
    srv = RestServer(app, port=0)
    srv.start()
    gsrv = GrpcServer(app, port=0)
    gsrv.start()
    return app, srv, gsrv


def _batch_search(gport, vecs, k=3):
    client = SearchClient(f"127.0.0.1:{gport}")
    try:
        req = pb.BatchSearchRequest(requests=[
            pb.SearchRequest(class_name="MeshDoc", limit=k,
                             near_vector=pb.NearVectorParams(vector=v.tolist()))
            for v in vecs
        ])
        return client.batch_search(req)
    finally:
        client.close()


@pytest.fixture
def data():
    rng = np.random.default_rng(21)
    return rng.standard_normal((N, DIM)).astype(np.float32)


def test_mesh_index_grpc_e2e_and_mesh_size_change(tmp_path, data):
    app, srv, gsrv = _mk_app(tmp_path)
    st, _ = _req(srv.port, "POST", "/v1/schema", {
        "class": "MeshDoc",
        "vectorIndexType": "hnsw_tpu_mesh",
        "vectorIndexConfig": {"distance": "l2-squared", "meshDevices": 8},
        "properties": [{"name": "rank", "dataType": ["int"]}],
    })
    assert st == 200
    objs = [{
        "class": "MeshDoc", "id": str(uuidlib.UUID(int=i + 1)),
        "properties": {"rank": i}, "vector": data[i].tolist(),
    } for i in range(N)]
    st, res = _req(srv.port, "POST", "/v1/batch/objects", {"objects": objs})
    assert st == 200 and all(o["result"]["status"] == "SUCCESS" for o in res)

    # the index actually serving is the mesh implementation over 8 devices
    from weaviate_tpu.index.mesh import MeshVectorIndex

    shard = next(iter(app.db.get_index("MeshDoc").shards.values()))
    assert isinstance(shard.vector_index, MeshVectorIndex)
    assert shard.vector_index.n_dev == 8

    reply = _batch_search(gsrv.port, data[:8])
    assert len(reply.replies) == 8
    for i, one in enumerate(reply.replies):
        assert not one.error_message
        assert one.results[0].id == str(uuidlib.UUID(int=i + 1))
        assert json.loads(one.results[0].properties_json)["rank"] == i
        assert one.results[0].distance < 1e-3

    # delete a doc, then restart the whole app onto a SMALLER mesh: the
    # operator edits the class config (schema.json survives, the vector log
    # replays onto 4 devices) — results must be identical minus the delete
    st, _ = _req(srv.port, "DELETE", f"/v1/objects/MeshDoc/{uuidlib.UUID(int=3)}")
    assert st == 204
    srv.stop()
    gsrv.stop()
    app.shutdown()

    schema_path = tmp_path / "data" / "schema.json"
    raw = json.loads(schema_path.read_text())
    for cd in raw["classes"]:
        if cd["class"] == "MeshDoc":
            cd["vectorIndexConfig"]["meshDevices"] = 4
    schema_path.write_text(json.dumps(raw))

    app2, srv2, gsrv2 = _mk_app(tmp_path)
    try:
        shard2 = next(iter(app2.db.get_index("MeshDoc").shards.values()))
        assert isinstance(shard2.vector_index, MeshVectorIndex)
        assert shard2.vector_index.n_dev == 4  # re-balanced onto 4 devices
        assert shard2.vector_index.live == N - 1

        reply = _batch_search(gsrv2.port, data[:8])
        for i, one in enumerate(reply.replies):
            if i == 2:  # deleted doc: its own vector now finds a neighbor
                assert one.results[0].id != str(uuidlib.UUID(int=3))
                continue
            assert one.results[0].id == str(uuidlib.UUID(int=i + 1))
            assert json.loads(one.results[0].properties_json)["rank"] == i
    finally:
        srv2.stop()
        gsrv2.stop()
        app2.shutdown()
