"""GraphQL parser + executor and aggregator tests.

Reference surfaces: adapters/handlers/graphql/local/{get,aggregate,explore},
adapters/repos/db/aggregator.
"""

import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.db import DB
from weaviate_tpu.graphql import GraphQLExecutor, GraphQLParseError, parse_query
from weaviate_tpu.graphql.parser import EnumValue, Field
from weaviate_tpu.schema import AutoSchema, SchemaManager
from weaviate_tpu.usecases.aggregator import AggregateParams, Aggregator
from weaviate_tpu.usecases.objects import BatchManager, ObjectsManager
from weaviate_tpu.usecases.traverser import Explorer, Traverser


# -- parser ------------------------------------------------------------------


def test_parse_basic_get():
    op = parse_query(
        """
        { Get { Article(limit: 5, where: {operator: Equal, path: ["title"], valueText: "x"})
            { title wordCount _additional { id distance } } } }
        """
    )
    get = op.selections[0]
    assert get.name == "Get"
    art = get.selections[0]
    assert art.name == "Article"
    assert art.args["limit"] == 5
    assert isinstance(art.args["where"]["operator"], EnumValue)
    assert str(art.args["where"]["operator"]) == "Equal"
    assert art.args["where"]["path"] == ["title"]
    names = [s.name for s in art.selections]
    assert names == ["title", "wordCount", "_additional"]


def test_parse_variables_fragments_aliases():
    op = parse_query(
        """
        query Q($lim: Int = 3, $vec: [Float]) {
          first: Get { Article(limit: $lim, nearVector: {vector: $vec}) {
            title
            writtenBy { ... on Author { name } }
            ...extra
          } }
        }
        fragment extra on Article { wordCount }
        """,
        variables={"vec": [0.1, 0.2]},
    )
    get = op.selections[0]
    assert get.out_name == "first"
    art = get.selections[0]
    assert art.args["limit"] == 3  # default applied
    assert art.args["nearVector"]["vector"] == [0.1, 0.2]
    frag_types = [s.type_name for s in art.selections if not isinstance(s, Field)]
    assert "Article" in frag_types  # named fragment inlined


def test_parse_errors():
    with pytest.raises(GraphQLParseError):
        parse_query("mutation { x }")
    with pytest.raises(GraphQLParseError):
        parse_query("{ Get { A(limit: $nope) { t } } }")
    with pytest.raises(GraphQLParseError):
        parse_query('{ Get { A(s: "unterminated) { t } } }')


# -- executor + aggregator ---------------------------------------------------


@pytest.fixture
def gql(tmp_path):
    db = DB(str(tmp_path / "data"))
    mgr = SchemaManager(str(tmp_path / "schema.json"), migrator=db)
    om = ObjectsManager(db, mgr, auto_schema=AutoSchema(mgr))
    bm = BatchManager(om)
    explorer = Explorer(db, mgr)
    trav = Traverser(explorer)
    agg = Aggregator(db, mgr, explorer)
    ex = GraphQLExecutor(trav, agg, mgr, db)

    mgr.add_class(
        {
            "class": "Article",
            "properties": [
                {"name": "title", "dataType": ["text"]},
                {"name": "wordCount", "dataType": ["int"]},
                {"name": "published", "dataType": ["boolean"]},
            ],
            "vectorIndexConfig": {"distance": "l2-squared"},
        }
    )
    rng = np.random.default_rng(3)
    vecs = rng.standard_normal((30, 8)).astype(np.float32)
    bm.add_objects(
        [
            {
                "class": "Article",
                "id": str(uuidlib.UUID(int=i + 1)),
                "properties": {
                    "title": f"piece number{i}",
                    "wordCount": i * 10,
                    "published": i % 2 == 0,
                },
                "vector": vecs[i].tolist(),
            }
            for i in range(30)
        ]
    )
    yield ex, vecs, om, mgr
    db.shutdown()


def test_get_near_vector(gql):
    ex, vecs, om, mgr = gql
    res = ex.execute(
        "query($v: [Float]) { Get { Article(nearVector: {vector: $v}, limit: 3) "
        "{ title _additional { id distance } } } }",
        variables={"v": vecs[4].tolist()},
    )
    assert "errors" not in res, res.get("errors")
    rows = res["data"]["Get"]["Article"]
    assert len(rows) == 3
    assert rows[0]["_additional"]["id"] == str(uuidlib.UUID(int=5))
    assert rows[0]["_additional"]["distance"] < 1e-3
    assert rows[0]["title"] == "piece number4"


def test_get_where_and_bm25(gql):
    ex, vecs, om, mgr = gql
    res = ex.execute(
        '{ Get { Article(where: {operator: And, operands: ['
        "{operator: Equal, path: [\"published\"], valueBoolean: true}, "
        "{operator: GreaterThan, path: [\"wordCount\"], valueInt: 100}"
        "]}, limit: 20) { wordCount published } } }"
    )
    rows = res["data"]["Get"]["Article"]
    assert rows and all(r["published"] and r["wordCount"] > 100 for r in rows)

    res2 = ex.execute('{ Get { Article(bm25: {query: "number7"}) { title _additional { score } } } }')
    rows2 = res2["data"]["Get"]["Article"]
    assert len(rows2) == 1 and rows2[0]["title"] == "piece number7"
    assert float(rows2[0]["_additional"]["score"]) > 0


def test_get_hybrid_and_sort(gql):
    ex, vecs, om, mgr = gql
    res = ex.execute(
        "query($v: [Float]) { Get { Article(hybrid: {query: \"number11\", vector: $v, alpha: 0.5}, limit: 5)"
        " { title } } }",
        variables={"v": vecs[11].tolist()},
    )
    assert res["data"]["Get"]["Article"][0]["title"] == "piece number11"

    res2 = ex.execute(
        '{ Get { Article(sort: [{path: ["wordCount"], order: desc}], limit: 30) { wordCount } } }'
    )
    counts = [r["wordCount"] for r in res2["data"]["Get"]["Article"]]
    assert counts == sorted(counts, reverse=True)


def test_get_cross_reference(gql):
    ex, vecs, om, mgr = gql
    mgr.add_class({"class": "Author", "properties": [{"name": "name", "dataType": ["text"]}]})
    mgr.add_property("Article", {"name": "writtenBy", "dataType": ["Author"]})
    a = om.add({"class": "Author", "properties": {"name": "grace"}})
    om.add_reference(
        str(uuidlib.UUID(int=1)), "Article", "writtenBy", f"weaviate://localhost/Author/{a.uuid}"
    )
    res = ex.execute(
        '{ Get { Article(where: {operator: Equal, path: ["wordCount"], valueInt: 0}) '
        "{ title writtenBy { ... on Author { name _additional { id } } } } } }"
    )
    rows = res["data"]["Get"]["Article"]
    assert len(rows) == 1
    assert rows[0]["writtenBy"] == [{"name": "grace", "_additional": {"id": a.uuid}}]


def test_aggregate(gql):
    ex, vecs, om, mgr = gql
    res = ex.execute(
        "{ Aggregate { Article { meta { count } wordCount { mean maximum minimum count } "
        "published { totalTrue percentageFalse } title { topOccurrences { value occurs } } } } }"
    )
    assert "errors" not in res, res.get("errors")
    agg = res["data"]["Aggregate"]["Article"][0]
    assert agg["meta"]["count"] == 30
    assert agg["wordCount"]["maximum"] == 290
    assert agg["wordCount"]["mean"] == pytest.approx(145.0)
    assert agg["published"]["totalTrue"] == 15
    assert agg["published"]["percentageFalse"] == pytest.approx(0.5)
    assert len(agg["title"]["topOccurrences"]) == 5

    # grouped + filtered
    res2 = ex.execute(
        '{ Aggregate { Article(groupBy: ["published"], where: '
        '{operator: LessThan, path: ["wordCount"], valueInt: 100}) '
        "{ groupedBy { value } meta { count } wordCount { count sum } } } }"
    )
    groups = res2["data"]["Aggregate"]["Article"]
    assert len(groups) == 2
    total = sum(g["meta"]["count"] for g in groups)
    assert total == 10


def test_aggregate_near_vector(gql):
    ex, vecs, om, mgr = gql
    res = ex.execute(
        "query($v: [Float]) { Aggregate { Article(nearVector: {vector: $v}, objectLimit: 5) "
        "{ meta { count } } } }",
        variables={"v": vecs[0].tolist()},
    )
    assert res["data"]["Aggregate"]["Article"][0]["meta"]["count"] == 5


def test_explore(gql):
    ex, vecs, om, mgr = gql
    res = ex.execute(
        "query($v: [Float]) { Explore(nearVector: {vector: $v}, limit: 2) "
        "{ beacon className distance } }",
        variables={"v": vecs[0].tolist()},
    )
    assert "errors" not in res, res.get("errors")
    hits = res["data"]["Explore"]

    # declared-but-missing variable must error, not silently resolve to null
    res_missing = ex.execute(
        "query($v: [Float]) { Explore(nearVector: {vector: $v}) { beacon } }"
    )
    assert res_missing["errors"]
    assert len(hits) == 2
    assert hits[0]["className"] == "Article"
    assert str(uuidlib.UUID(int=1)) in hits[0]["beacon"]


def test_error_paths(gql):
    ex, vecs, om, mgr = gql
    res = ex.execute("{ Get { NoSuchClass { x } } }")
    assert res["errors"]
    res2 = ex.execute("{ Nope { x } }")
    assert res2["errors"]
    res3 = ex.execute("{ Get { Article(")
    assert res3["errors"]


def test_aggregate_api_direct(tmp_path):
    """Aggregator date aggs direct (no fixture class has dates)."""
    db = DB(str(tmp_path / "d2"))
    mgr = SchemaManager(str(tmp_path / "s2.json"), migrator=db)
    om = ObjectsManager(db, mgr, auto_schema=AutoSchema(mgr))
    for i in range(5):
        om.add(
            {
                "class": "Event",
                "properties": {"when": f"2023-0{i+1}-01T00:00:00Z", "n": i},
            }
        )
    agg = Aggregator(db, mgr)
    out = agg.aggregate(
        AggregateParams(
            class_name="Event",
            properties={"when": ["count", "minimum", "maximum"], "n": ["median", "mode"]},
        )
    )[0]
    assert out["when"]["count"] == 5
    assert out["when"]["minimum"].startswith("2023-01-01")
    assert out["when"]["maximum"].startswith("2023-05-01")
    assert out["n"]["median"] == 2.0
    db.shutdown()


def test_introspection_schema(gql):
    """__schema reflects the live data schema per class/property (the
    reference rebuilds its GraphQL schema on every schema change)."""
    ex = gql[0]
    res = ex.execute(
        "{ __schema { queryType { name } types { name kind fields { name } } } }"
    )
    assert "errors" not in res, res
    sch = res["data"]["__schema"]
    assert sch["queryType"]["name"] == "WeaviateQuery"
    by_name = {t["name"]: t for t in sch["types"]}
    assert "Article" in by_name
    fields = {f["name"] for f in by_name["Article"]["fields"]}
    assert {"title", "wordCount", "_additional"} <= fields
    assert "GetObjectsObj" in by_name
    assert {f["name"] for f in by_name["GetObjectsObj"]["fields"]} >= {"Article"}


def test_introspection_type_lookup(gql):
    ex = gql[0]
    res = ex.execute(
        '{ __type(name: "Article") { name kind fields { name type { kind name ofType { name } } } } }'
    )
    assert "errors" not in res, res
    t = res["data"]["__type"]
    assert t["name"] == "Article" and t["kind"] == "OBJECT"
    ftypes = {f["name"]: f["type"] for f in t["fields"]}
    assert ftypes["wordCount"]["name"] == "Int"
    res2 = ex.execute('{ __type(name: "Nope") { name } }')
    assert res2["data"]["__type"] is None


def test_schema_validation_errors(gql):
    ex, _, _, _ = gql
    """Unknown args/props/_additional are errors, not silent nulls — the
    behavior the reference gets from its generated schema
    (class_builder_fields.go)."""
    for q, frag in [
        ('{ Get { Article(limit: 1) { nosuchprop } } }', "no property"),
        ('{ Get { Article(nosucharg: 3) { title } } }', "unknown argument"),
        ('{ Get { Article { _additional { nosuchmeta } } } }', "unknown _additional"),
        ('{ Aggregate { Article(nosucharg: 1) { meta { count } } } }', "unknown argument"),
        ('{ Aggregate { Article { nosuchprop { count } } } }', "no property"),
    ]:
        out = ex.execute(q)
        assert out.get("errors"), q
        assert frag in out["errors"][0]["message"], (q, out["errors"])
    # known surface still validates clean
    ok = ex.execute('{ Get { Article(limit: 1) { title _additional { id } } } }')
    assert not ok.get("errors")
