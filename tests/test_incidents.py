"""Incident flight recorder + SLO burn-rate engine (monitoring/incidents.py).

Covers the full incident journey — seeded fault-injection device-error
storm -> breaker OPEN -> exactly one bundle on disk carrying all four
plane summaries + the journal tail — plus the unit surface: bounded
journal ring + burst coalescing + foreign-kind fold, SLO burn math /
fire-once / recovery re-arm / per-tenant overrides, recorder rate
limiting + disk-budget pruning, the SIGTERM/atexit teardown chain, the
disabled-mode zero-construction spy, the /debug/incidents + /debug/slo +
/metrics e2e, and config parsing/validation.
"""

import json
import os
import queue as stdqueue
import threading
import time
import urllib.error
import urllib.request
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.config.config import ConfigError, load_config
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.monitoring import incidents
from weaviate_tpu.monitoring.metrics import noop_metrics
from weaviate_tpu.serving import robustness
from weaviate_tpu.testing import faults
from weaviate_tpu.usecases.traverser import GetParams

N, DIM, K = 300, 16, 5


@pytest.fixture(autouse=True)
def _clean_incident_globals():
    """Isolate the module globals: an App another test file forgot to
    shut down must not leak its journal/engine/recorder into the
    None-assertions here (and ours must not leak out)."""
    saved = (incidents._journal, incidents._engine, incidents._recorder)
    incidents._journal = incidents._engine = incidents._recorder = None
    yield
    incidents._journal, incidents._engine, incidents._recorder = saved


# -- the ops-event journal ----------------------------------------------------


def test_journal_bounded_ring_folds_foreign_kinds():
    j = incidents.OpsJournal(size=4)
    for i in range(10):
        j.emit("breaker_open", scope=f"s{i}")  # non-burst kind: appends
    tail = j.tail()
    assert len(tail) == 4  # bounded ring
    assert [e["scope"] for e in tail] == ["s6", "s7", "s8", "s9"]
    j.emit("no_such_kind", scope="x")
    assert j.tail()[-1]["kind"] == "other"
    counts = j.counts()
    assert counts["breaker_open"] == 10 and counts["other"] == 1


def test_journal_burst_coalescing_and_window_expiry():
    j = incidents.OpsJournal(size=64, burst_window_s=0.05)
    for _ in range(500):
        j.emit("shed_burst", scope="queue_full")
    j.emit("shed_burst", scope="tenant_budget")  # distinct scope: own entry
    tail = j.tail()
    qf = [e for e in tail if e.get("scope") == "queue_full"]
    assert len(qf) == 1 and qf[0]["count"] == 500
    assert len([e for e in tail if e.get("scope") == "tenant_budget"]) == 1
    # a storm cannot wipe low-frequency events out of the ring
    j.emit("breaker_open", scope="device")
    for _ in range(1000):
        j.emit("shed_burst", scope="queue_full")
    assert any(e["kind"] == "breaker_open" for e in j.tail())
    # after the burst window passes, a NEW entry starts
    time.sleep(0.06)
    j.emit("shed_burst", scope="queue_full")
    assert len([e for e in j.tail()
                if e.get("scope") == "queue_full"]) == 2
    assert j.counts()["shed_burst"] == 1502


def test_journal_burst_entry_evicted_from_ring_restarts():
    """An ongoing burst whose coalesced entry was pushed out of the ring
    must start a NEW ring entry — not keep counting into the evicted
    dict, invisible to tail() for the rest of the storm."""
    j = incidents.OpsJournal(size=4, burst_window_s=60.0)
    j.emit("shed_burst", scope="queue_full")
    for i in range(4):  # evicts the burst entry
        j.emit("breaker_open", scope=f"s{i}")
    assert not any(e["kind"] == "shed_burst" for e in j.tail())
    j.emit("shed_burst", scope="queue_full")  # the storm continues
    qf = [e for e in j.tail() if e["kind"] == "shed_burst"]
    assert len(qf) == 1 and qf[0]["count"] == 1
    assert j.counts()["shed_burst"] == 2


def test_module_emit_is_noop_and_guarded_when_unconfigured():
    assert incidents.get_journal() is None
    incidents.emit("breaker_open", scope="x")  # must not raise
    incidents.note_request("ok", 1.0)
    assert incidents.trigger("manual") is False


# -- the SLO engine -----------------------------------------------------------


def _engine(**kw):
    kw.setdefault("availability_target", 0.9)  # budget 0.1
    kw.setdefault("min_events", 10)
    return incidents.SloEngine(**kw)


def test_slo_burn_math_and_budget_remaining():
    e = _engine()
    for _ in range(15):
        e.note("ok", 1.0)
    for _ in range(5):
        e.note("shed", 1.0)
    doc = e.summary()
    avail = doc["slos"][0]
    # bad fraction 5/20 = 0.25; budget 0.1 -> burn 2.5x on both windows
    assert avail["windows"]["5m"]["burn_rate"] == pytest.approx(2.5)
    assert avail["windows"]["1h"]["burn_rate"] == pytest.approx(2.5)
    # budget spent = 2.5 -> remaining clamps at 0
    assert avail["budget_remaining_1h"] == 0.0
    assert doc["requests_total"] == 20
    assert doc["outcomes"] == {"ok": 15, "shed": 5}


def test_slo_min_events_gate_and_client_outcomes_spend_nothing():
    e = _engine(min_events=50)
    for _ in range(20):
        e.note("error", 1.0)
    assert e.summary()["slos"][0]["windows"]["5m"]["burn_rate"] is None
    e2 = _engine()
    for _ in range(20):
        e2.note("client", 1.0)  # 4xx family: total, never budget
    s = e2.summary()["slos"][0]
    assert s["windows"]["5m"]["requests"] == 20
    assert s["windows"]["5m"]["burn_rate"] == 0.0


def test_slo_alert_fires_once_journals_and_recovers(tmp_path):
    j = incidents.OpsJournal(size=64)
    rec = incidents.FlightRecorder(str(tmp_path / "inc"), rate_limit_s=0.0)
    incidents.configure(journal=j, engine=None, recorder=rec)
    try:
        e = _engine(fast_burn_threshold=2.0, slow_burn_threshold=100.0)
        for _ in range(10):
            e.note("error", 1.0)  # burn 10x >= 2.0 -> alert
        e.summary()
        s = e.summary()["slos"][0]
        assert s["alerting"] is True and s["alerts_fired"] == 1
        kinds = [ev["kind"] for ev in j.tail()]
        assert kinds.count("slo_burn") == 1  # fire-once per transition
        # sustained burn does not re-fire
        for _ in range(10):
            e.note("error", 1.0)
        e.summary()
        assert [ev["kind"] for ev in j.tail()].count("slo_burn") == 1
        # recovery: flood with oks until under threshold, then re-arm
        for _ in range(500):
            e.note("ok", 1.0)
        s = e.summary()["slos"][0]
        assert s["alerting"] is False
        assert any(ev["kind"] == "slo_recovered" for ev in j.tail())
    finally:
        incidents.unconfigure(journal=j, recorder=rec)


def test_slo_latency_objective_judges_completed_requests():
    e = incidents.SloEngine(availability_target=0.999,
                            latency_p99_ms=100.0, min_events=10)
    for _ in range(18):
        e.note("ok", 10.0)
    for _ in range(2):
        e.note("ok", 500.0)  # over target
    e.note("shed", 10_000.0)  # sheds never count against latency
    doc = e.summary()
    lat = [s for s in doc["slos"] if s["slo"] == "latency_p99"][0]
    assert lat["latency_target_ms"] == 100.0
    assert lat["windows"]["5m"]["requests"] == 20  # shed excluded
    # slow fraction 2/20 = 0.1 over a 0.01 budget -> burn 10x
    assert lat["windows"]["5m"]["burn_rate"] == pytest.approx(10.0)


def test_slo_per_tenant_override_counts_only_its_tenant():
    e = incidents.SloEngine(availability_target=0.999, min_events=5,
                            tenant_targets={"gold": 0.9})
    for _ in range(10):
        e.note("ok", 1.0, tenant="gold")
    for _ in range(10):
        e.note("shed", 1.0, tenant="bronze")
    doc = e.summary()
    gold = [s for s in doc["slos"] if s["slo"] == "availability:gold"][0]
    assert gold["tenant"] == "gold"
    assert gold["windows"]["5m"]["requests"] == 10
    assert gold["windows"]["5m"]["burn_rate"] == 0.0
    # the global SLO saw everything
    glob = [s for s in doc["slos"] if s["slo"] == "availability"][0]
    assert glob["windows"]["5m"]["requests"] == 20


def test_slo_gauges_stay_bounded_under_1k_tenants():
    """1000 distinct tenants' traffic must not mint per-tenant SLO
    series: only the config-declared overrides (plus the defaults) may
    appear in the exposition."""
    m = noop_metrics()
    e = incidents.SloEngine(availability_target=0.99, latency_p99_ms=50.0,
                            min_events=1,
                            tenant_targets={"gold": 0.999, "silver": 0.99},
                            metrics=m)
    for i in range(1000):
        e.note("ok", 1.0, tenant=f"t{i}")
    e.summary()  # forces evaluation + gauge publication
    text = m.expose().decode()
    series = [ln for ln in text.splitlines()
              if ln.startswith("weaviate_slo_burn_rate{")]
    slos = {ln.split('slo="')[1].split('"')[0] for ln in series}
    assert slos <= {"availability", "latency_p99",
                    "availability:gold", "availability:silver"}
    assert len(series) <= 4 * 2  # each slo x {5m, 1h}


# -- the flight recorder ------------------------------------------------------


def test_recorder_rate_limit_per_class_and_force(tmp_path):
    rec = incidents.FlightRecorder(str(tmp_path), rate_limit_s=60.0)
    p1 = rec.dump_now("breaker_open", reason="first")
    assert p1 is not None and os.path.exists(p1)
    assert rec.dump_now("breaker_open", reason="limited") is None
    # a different class has its own bucket
    assert rec.dump_now("memory_exhaustion", reason="other") is not None
    # force (teardown/manual) is exempt
    assert rec.dump_now("breaker_open", reason="forced",
                        force=True) is not None
    st = rec.stats()
    assert st["dumped"] == 3 and st["rate_limited"] == 1


def test_recorder_unadmitted_capture_does_not_silence_class(
        tmp_path, monkeypatch):
    """A dropped (queue-full) or failed capture must leave its incident
    class un-stamped: the next trigger retries instead of being
    rate-limited for the whole window with no bundle on disk."""
    rec = incidents.FlightRecorder(str(tmp_path), rate_limit_s=300.0)
    # (a) a failed synchronous write (e.g. ENOSPC) does not stamp

    def boom(bundle):
        raise OSError("enospc")
    monkeypatch.setattr(rec, "_write", boom)
    assert rec.dump_now("breaker_open") is None
    monkeypatch.undo()
    p = rec.dump_now("breaker_open")
    assert p is not None and os.path.exists(p)
    # (b) queue full with the worker wedged: the trigger drops un-stamped
    monkeypatch.setattr(rec, "_ensure_worker", lambda: None)
    while True:
        try:
            rec._queue.put_nowait(("manual", "fill", None))
        except stdqueue.Full:
            break
    assert rec.trigger("memory_exhaustion") is False
    while True:
        try:
            rec._queue.get_nowait()
        except stdqueue.Empty:
            break
    assert rec.trigger("memory_exhaustion") is True


def test_recorder_worker_capture_failure_rearms_class(tmp_path, monkeypatch):
    """An admitted async capture whose write fails re-arms its class so a
    later trigger can still preserve the incident."""
    rec = incidents.FlightRecorder(str(tmp_path), rate_limit_s=300.0)
    calls = []

    def boom(bundle):
        calls.append(1)
        raise OSError("enospc")
    monkeypatch.setattr(rec, "_write", boom)
    try:
        assert rec.trigger("breaker_open") is True
        deadline = time.monotonic() + 5.0
        while not calls and time.monotonic() < deadline:
            time.sleep(0.02)
        assert calls, "worker never attempted the capture"
        monkeypatch.undo()
        # the un-stamp lands just after the failed write; poll until
        # re-armed
        deadline = time.monotonic() + 5.0
        admitted = False
        while time.monotonic() < deadline:
            if rec.trigger("breaker_open"):
                admitted = True
                break
            time.sleep(0.02)
        assert admitted
        deadline = time.monotonic() + 5.0
        while not rec.index() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert rec.index() and rec.index()[0]["class"] == "breaker_open"
    finally:
        # trigger() started the capture worker: a directly-constructed
        # recorder must stop it the way App shutdown's unconfigure does,
        # or the thread outlives the test (the graftsan leak detector
        # flagged exactly this)
        rec.shutdown()


def test_bundle_names_unique_across_recorders_sharing_a_dir(tmp_path):
    """Several recorders (CI runs many Apps per process) sharing one
    INCIDENT_DIR within the same second must never compute the same
    bundle path and overwrite each other's evidence."""
    a = incidents.FlightRecorder(str(tmp_path), rate_limit_s=0.0)
    b = incidents.FlightRecorder(str(tmp_path), rate_limit_s=0.0)
    names = {os.path.basename(a.dump_now("manual", force=True)),
             os.path.basename(b.dump_now("manual", force=True)),
             os.path.basename(a.dump_now("manual", force=True))}
    assert len(names) == 3
    assert len(a.index()) == 3
    assert all(e["class"] == "manual" for e in a.index())


def test_recorder_disk_budget_prunes_oldest_keeps_newest(tmp_path):
    rec = incidents.FlightRecorder(str(tmp_path), rate_limit_s=0.0,
                                   max_bytes=1)  # smaller than one bundle
    paths = []
    for i in range(4):
        p = rec.dump_now("manual", reason=f"b{i}", force=True)
        assert p is not None
        paths.append(p)
        time.sleep(0.01)
    left = rec.index()
    # the budget is below one bundle: only the just-written one survives
    assert len(left) == 1
    assert left[0]["file"] == os.path.basename(paths[-1])


def test_bundle_sections_guarded_and_time_consistent(tmp_path):
    j = incidents.OpsJournal(size=16)
    j.emit("breaker_open", scope="device")
    e = _engine()
    e.note("ok", 1.0)
    rec = incidents.FlightRecorder(str(tmp_path), journal=j, engine=e)
    rec.add_stats_provider("coalescer", lambda: {"lanes": 3})
    rec.add_stats_provider("broken", lambda: 1 / 0)
    rec.set_config_fingerprint({"sha256_16": "abc", "knobs": {}})
    t0 = time.time()
    path = rec.dump_now("manual", reason="unit", force=True)
    bundle = json.load(open(path))
    assert bundle["incident"]["class"] == "manual"
    assert abs(bundle["incident"]["ts_unix"] - t0) < 5.0
    assert bundle["config"]["sha256_16"] == "abc"
    assert any(ev["kind"] == "breaker_open"
               for ev in bundle["journal"]["tail"])
    assert bundle["slo"]["requests_total"] == 1
    assert bundle["coalescer"]["lanes"] == 3
    # one broken provider costs its section, never the bundle
    assert "error" in bundle["broken"]
    for name in ("journal", "slo", "coalescer"):
        assert abs(bundle[name]["captured_unix"]
                   - bundle["incident"]["ts_unix"]) < 5.0


def test_breaker_open_emits_and_triggers(tmp_path):
    j = incidents.OpsJournal(size=32)
    rec = incidents.FlightRecorder(str(tmp_path), journal=j,
                                   rate_limit_s=300.0)
    incidents.configure(journal=j, recorder=rec)
    try:
        br = robustness.CircuitBreaker(failure_threshold=2,
                                       reset_timeout_s=0.05)
        br.record_failure(RuntimeError("x"))
        br.record_failure(RuntimeError("x"))
        assert br.state() == robustness.STATE_OPEN
        kinds = [ev["kind"] for ev in j.tail()]
        assert "breaker_open" in kinds
        # the async capture lands on disk
        deadline = time.monotonic() + 5.0
        while not rec.index() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(rec.index()) == 1
        assert rec.index()[0]["class"] == "breaker_open"
        # half-open + close journal too
        time.sleep(0.06)
        assert br.allow()
        br.record_success()
        kinds = [ev["kind"] for ev in j.tail()]
        assert "breaker_half_open" in kinds and "breaker_closed" in kinds
    finally:
        incidents.unconfigure(journal=j, recorder=rec)


def test_grpc_batch_search_classifies_internal_errors(monkeypatch):
    """A failure inside the batch path spends availability budget like the
    Search twin — a batch-only outage must not be invisible to the SLO."""
    import grpc

    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server.grpc_server import SearchServicer

    eng = incidents.SloEngine()
    incidents.configure(engine=eng)
    try:
        class DummyApp:
            config = Config()
        sv = SearchServicer(DummyApp())

        def boom(request, start):
            raise RuntimeError("batch lane died")
        monkeypatch.setattr(sv, "_batch_search", boom)

        class Abort(Exception):
            pass

        class Ctx:
            code = None

            def invocation_metadata(self):
                return ()

            def time_remaining(self):
                return None

            def set_trailing_metadata(self, md):
                pass

            def abort(self, code, msg):
                self.code = code
                raise Abort(msg)

        ctx = Ctx()
        with pytest.raises(Abort):
            sv.BatchSearch(pb.BatchSearchRequest(
                requests=[pb.SearchRequest(class_name="C", limit=1)]), ctx)
        assert ctx.code == grpc.StatusCode.INTERNAL
        assert eng.summary()["outcomes"] == {"error": 1}

        # an invalid-tenant abort counts as "client" like the REST twin
        class BadTenantCtx(Ctx):
            def invocation_metadata(self):
                return (("x-tenant-id", "no spaces allowed"),)

        for rpc, req in ((sv.Search, pb.SearchRequest()),
                         (sv.BatchSearch, pb.BatchSearchRequest())):
            ctx2 = BadTenantCtx()
            with pytest.raises(Abort):
                rpc(req, ctx2)
            assert ctx2.code == grpc.StatusCode.INVALID_ARGUMENT
        assert eng.summary()["outcomes"] == {"error": 1, "client": 2}
    finally:
        incidents.unconfigure(engine=eng)


# -- disabled mode: the zero-construction spy ---------------------------------


def test_disabled_serving_path_constructs_nothing(tmp_path, monkeypatch):
    built = []
    for name in ("OpsJournal", "SloEngine", "FlightRecorder"):
        orig = getattr(incidents, name)

        def make(orig=orig, name=name):
            class Spy(orig):
                def __init__(self, *a, **kw):
                    built.append(name)
                    super().__init__(*a, **kw)
            return Spy
        monkeypatch.setattr(incidents, name, make())
    from weaviate_tpu.server import App

    cfg = Config()
    cfg.incidents.enabled = False
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    try:
        assert app.ops_journal is None and app.slo_engine is None \
            and app.flight_recorder is None
        assert incidents.get_journal() is None
        app.schema.add_class({
            "class": "Inc", "vectorIndexType": "hnsw_tpu",
            "vectorIndexConfig": {"distance": "l2-squared"},
            "properties": [{"name": "tag", "dataType": ["text"]}]})
        rng = np.random.default_rng(7)
        vecs = rng.integers(-8, 8, (64, DIM)).astype(np.float32)
        idx = app.db.get_index("Inc")
        idx.put_batch([
            StorObj(class_name="Inc", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"tag": "t"}, vector=vecs[i])
            for i in range(len(vecs))])
        r = app.traverser.get_class(GetParams(
            class_name="Inc", near_vector={"vector": vecs[0].tolist()},
            limit=K))
        assert len(r) == K
        assert built == []
    finally:
        app.shutdown()


# -- the full incident journey (acceptance e2e) -------------------------------


def _mk_incident_app(tmp_path, **cfg_kw):
    from weaviate_tpu.server import App

    cfg = Config()
    cfg.coalescer.enabled = True
    cfg.coalescer.window_ms = 20.0
    cfg.tracing.enabled = True
    cfg.quality.audit_sample_rate = 1.0
    cfg.robustness.breaker_failure_threshold = 3
    cfg.robustness.breaker_reset_ms = 30_000.0  # stays OPEN for the test
    cfg.incidents.dir = str(tmp_path / "incidents")
    # disk headroom on a nearly-full CI host must not add a second
    # bundle class mid-test; 0 disables the memory alerts cleanly
    cfg.memory.headroom_alert_pct = 0.0
    for k, v in cfg_kw.items():
        setattr(cfg.incidents, k, v)
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    app.schema.add_class({
        "class": "Inc", "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "tag", "dataType": ["text"]}]})
    rng = np.random.default_rng(23)
    vecs = rng.integers(-8, 8, (N, DIM)).astype(np.float32)
    idx = app.db.get_index("Inc")
    idx.put_batch([
        StorObj(class_name="Inc", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"tag": "even" if i % 2 == 0 else "odd"},
                vector=vecs[i])
        for i in range(N)])
    return app, idx, vecs


def test_device_error_storm_produces_exactly_one_bundle(tmp_path):
    """The acceptance journey: a seeded device-error storm trips the
    breaker under a closed loop of concurrent clients -> exactly ONE
    rate-limited breaker_open bundle whose four plane summaries and
    journal tail are present and mutually time-consistent."""
    app, idx, vecs = _mk_incident_app(tmp_path)
    inj = faults.configure(faults.FaultInjector(seed=7))
    try:
        queries = [vecs[i] + 0.5 for i in range(16)]
        # warm once so audits/perf have a clean dispatch first
        app.traverser.get_class(GetParams(
            class_name="Inc", near_vector={"vector": queries[0].tolist()},
            limit=K))
        inj.plan("index.tpu.dispatch", "device_error", times=None)

        errs = []

        def run(i):
            try:
                app.traverser.get_class(GetParams(
                    class_name="Inc",
                    near_vector={"vector": queries[i].tolist()}, limit=K))
            except Exception as e:  # noqa: BLE001 — recorded for assert
                errs.append(e)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), "request hung"
        assert errs == []
        # sequential requests deterministically finish tripping it
        for _ in range(6):
            if app.breaker.state() == robustness.STATE_OPEN:
                break
            app.traverser.get_class(GetParams(
                class_name="Inc",
                near_vector={"vector": queries[2].tolist()}, limit=K))
        assert app.breaker.state() == robustness.STATE_OPEN
        # keep serving while OPEN: more fallbacks, more shed-free traffic
        for i in range(4):
            app.traverser.get_class(GetParams(
                class_name="Inc",
                near_vector={"vector": queries[i].tolist()}, limit=K))
        # the async capture lands; the storm produced EXACTLY one bundle
        rec = app.flight_recorder
        deadline = time.monotonic() + 5.0
        while not rec.index() and time.monotonic() < deadline:
            time.sleep(0.02)
        bundles = [b for b in rec.index() if b["class"] == "breaker_open"]
        assert len(bundles) == 1
        assert [b["class"] for b in rec.index()] == ["breaker_open"]
        path = os.path.join(rec.incident_dir, bundles[0]["file"])
        bundle = json.load(open(path))
        # all four plane summaries present...
        assert "perf" in bundle and "dispatches" in bundle["perf"]
        assert "quality" in bundle and "audits" in bundle["quality"]
        assert "memory" in bundle and "device" in bundle["memory"]
        assert "traces" in bundle and "tail" in bundle["traces"]
        # ...the journal tail carries the causal chain...
        kinds = {ev["kind"] for ev in bundle["journal"]["tail"]}
        assert "fault_injected" in kinds
        assert "breaker_open" in kinds
        # ...and every section is time-consistent with the incident stamp
        t_inc = bundle["incident"]["ts_unix"]
        for name in ("journal", "perf", "quality", "memory"):
            assert abs(bundle[name]["captured_unix"] - t_inc) < 10.0
        # the breaker section recorded the OPEN state the trigger saw
        assert bundle["breaker"]["state_name"] in ("open", "half_open")
        # coalescer stats rode in via the App's provider
        assert "coalescer" in bundle
    finally:
        faults.unconfigure(inj)
        app.shutdown()


# -- teardown chaining --------------------------------------------------------


def test_sigterm_teardown_dumps_then_preserves_sig_ign(tmp_path, monkeypatch):
    """stop capture -> dump bundle -> re-deliver: with prev=SIG_IGN the
    chain still swallows the signal (PR-7 semantics), and a live
    recorder leaves a forced teardown bundle."""
    import signal

    from weaviate_tpu.monitoring import profiling

    rec = incidents.FlightRecorder(str(tmp_path), rate_limit_s=300.0)
    incidents.configure(recorder=rec)
    profiling.register_teardown_hook(incidents.teardown_dump)
    monkeypatch.setitem(profiling._teardown_state, "prev_sigterm",
                        signal.SIG_IGN)
    try:
        profiling._sigterm_teardown(signal.SIGTERM, None)  # must not raise
        idx = rec.index()
        assert len(idx) == 1 and idx[0]["class"] == "teardown"
        # forced: a second teardown (atexit after SIGTERM) dumps again
        profiling._atexit_teardown()
        assert len(rec.index()) == 2
    finally:
        incidents.unconfigure(recorder=rec)


def test_clean_shutdown_then_teardown_dumps_nothing(tmp_path):
    rec = incidents.FlightRecorder(str(tmp_path))
    incidents.configure(recorder=rec)
    incidents.unconfigure(recorder=rec)  # the App.shutdown path
    assert incidents.teardown_dump() is None
    assert rec.index() == []


def test_emergency_dump_without_recorder(tmp_path):
    assert incidents.get_recorder() is None
    out = str(tmp_path / "bench-incidents")
    p = incidents.emergency_dump("unreachable device (rc=3)",
                                 directory=out,
                                 detail={"probe": "timeout"})
    assert p is not None and os.path.dirname(p) == out
    bundle = json.load(open(p))
    assert bundle["incident"]["class"] == "bench"
    assert bundle["incident"]["detail"]["probe"] == "timeout"


# -- REST + metrics e2e -------------------------------------------------------


def test_debug_endpoints_and_metrics_e2e(tmp_path):
    from weaviate_tpu.server import App
    from weaviate_tpu.server.rest import RestServer

    cfg = Config()
    cfg.incidents.dir = str(tmp_path / "incidents")
    cfg.incidents.slo_latency_p99_ms = 1000.0
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    srv = RestServer(app, port=0)
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"

    def get(path):
        return json.load(urllib.request.urlopen(base + path, timeout=30))

    try:
        incidents.emit("breaker_open", scope="device")
        # a served request feeds the SLO engine through the REST hook
        get("/v1/meta")
        slo = get("/debug/slo")
        assert slo["enabled"] is True
        assert {s["slo"] for s in slo["slos"]} == {"availability",
                                                   "latency_p99"}
        assert slo["requests_total"] >= 1
        inc = get("/debug/incidents")
        assert inc["enabled"] is True
        assert any(ev["kind"] == "breaker_open"
                   for ev in inc["journal"]["tail"])
        assert inc["bundles"] == []
        # explicit dump trigger
        req = urllib.request.Request(base + "/debug/incidents/dump",
                                     method="POST")
        dumped = json.load(urllib.request.urlopen(req, timeout=30))
        assert os.path.exists(dumped["file"])
        assert get("/debug/incidents")["bundles"][0]["class"] == "manual"
        # the debug index page lists the new surfaces
        root = get("/debug/")
        assert "/debug/incidents" in root["endpoints"]
        assert "/debug/slo" in root["endpoints"]
        # metrics exposition: ops events counted, burn gauges present
        text = urllib.request.urlopen(base + "/metrics",
                                      timeout=30).read().decode()
        assert 'weaviate_ops_events_total{kind="breaker_open"}' in text
        assert "weaviate_slo_burn_rate" in text
        assert 'weaviate_incident_bundles_total{class="manual"}' in text
    finally:
        srv.stop()
        app.shutdown()


def test_disabled_endpoints_report_disabled(tmp_path):
    from weaviate_tpu.server import App
    from weaviate_tpu.server.rest import RestServer

    cfg = Config()
    cfg.incidents.enabled = False
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    srv = RestServer(app, port=0)
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        assert json.load(urllib.request.urlopen(
            base + "/debug/slo", timeout=30))["enabled"] is False
        assert json.load(urllib.request.urlopen(
            base + "/debug/incidents", timeout=30))["enabled"] is False
        req = urllib.request.Request(base + "/debug/incidents/dump",
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
    finally:
        srv.stop()
        app.shutdown()


# -- ledger integration + CI stash --------------------------------------------


def test_incident_dir_is_a_memory_ledger_disk_component(tmp_path):
    from weaviate_tpu.monitoring import memory as memledger

    led = memledger.MemoryLedger()
    led.set_disk_path(str(tmp_path))
    rec = incidents.FlightRecorder(str(tmp_path / "incidents"),
                                   rate_limit_s=0.0)
    # hermetic view of the module-level registry: recorders other suite
    # tests' Apps registered (and that are still referenced) must not
    # sum into this assertion
    with memledger._providers_lock:
        saved = dict(memledger._disk_providers)
        memledger._disk_providers.clear()
    try:
        memledger.register_disk_provider(
            rec, lambda r: {"incident_bundles": r.dir_bytes()})
        rec.dump_now("manual", force=True)
        comps = led.refresh_disk()
        assert comps["incident_bundles"] == rec.dir_bytes() > 0
    finally:
        with memledger._providers_lock:
            memledger._disk_providers.clear()
            memledger._disk_providers.update(saved)


def test_unconfigure_stashes_journal_for_ci_artifact():
    j = incidents.OpsJournal(size=8)
    incidents.configure(journal=j)
    j.emit("breaker_open", scope="device")
    incidents.unconfigure(journal=j)
    stashed = incidents.recent_summaries()
    assert stashed and stashed[-1]["events_total"] == 1
    assert stashed[-1]["counts"]["breaker_open"] == 1


def test_event_kinds_match_graftlint_mirror():
    from tools.graftlint import rules as glrules

    assert frozenset(incidents.EVENT_KINDS) == glrules.JOURNAL_EVENT_KINDS


# -- config -------------------------------------------------------------------


def test_incidents_config_parsing():
    cfg = load_config({
        "INCIDENTS_ENABLED": "1",
        "INCIDENT_JOURNAL_SIZE": "128",
        "INCIDENT_DIR": "/tmp/inc",
        "INCIDENT_DIR_MAX_BYTES": "1048576",
        "INCIDENT_RATE_LIMIT_S": "10",
        "SLO_AVAILABILITY_TARGET": "0.995",
        "SLO_LATENCY_P99_MS": "250",
        "SLO_FAST_BURN_THRESHOLD": "10",
        "SLO_SLOW_BURN_THRESHOLD": "2",
        "SLO_MIN_EVENTS": "5",
        "SLO_TENANT_AVAILABILITY_TARGETS": "gold=0.999,silver=0.99",
    })
    ic = cfg.incidents
    assert ic.enabled and ic.journal_size == 128
    assert ic.dir == "/tmp/inc" and ic.dir_max_bytes == 1 << 20
    assert ic.rate_limit_s == 10.0
    assert ic.slo_availability_target == 0.995
    assert ic.slo_latency_p99_ms == 250.0
    assert ic.slo_fast_burn == 10.0 and ic.slo_slow_burn == 2.0
    assert ic.slo_min_events == 5
    assert ic.slo_tenant_targets == {"gold": 0.999, "silver": 0.99}
    assert load_config({"INCIDENTS_ENABLED": "0"}).incidents.enabled is False


def test_incidents_config_validation_rejects_bad_values():
    for env in (
        {"INCIDENT_JOURNAL_SIZE": "0"},
        {"INCIDENT_DIR_MAX_BYTES": "-1"},
        {"INCIDENT_RATE_LIMIT_S": "-1"},
        {"SLO_AVAILABILITY_TARGET": "1.5"},
        {"SLO_AVAILABILITY_TARGET": "0"},
        {"SLO_LATENCY_P99_MS": "-5"},
        {"SLO_FAST_BURN_THRESHOLD": "0"},
        {"SLO_MIN_EVENTS": "0"},
        {"SLO_TENANT_AVAILABILITY_TARGETS": "gold=1.5"},
        {"SLO_TENANT_AVAILABILITY_TARGETS": "notargets"},
    ):
        with pytest.raises(ConfigError):
            load_config(env)
