"""Request-lifecycle robustness: deadlines, overload shedding, the device
circuit breaker + host fallback plane, and the fault-injection harness.

Failure journeys run against the REAL serving stack (App + coalescer +
shard + index) with faults injected at the named points — deterministic
(seeded/count-windowed schedules, integer-valued vectors so host and
device results are bit-comparable), tier-1 fast.
"""

import http.client
import json
import threading
import time
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.serving import robustness
from weaviate_tpu.serving.coalescer import CoalescerTimeoutError
from weaviate_tpu.testing import faults
from weaviate_tpu.usecases.traverser import GetParams

N, DIM, K = 300, 16, 5


# -- unit: deadlines ----------------------------------------------------------


def test_deadline_scope_and_check():
    assert robustness.current_deadline() is None
    assert robustness.remaining_s() is None
    robustness.check_deadline("nowhere")  # unbounded: no-op
    with robustness.deadline_scope(50.0) as d:
        assert d is not None and robustness.current_deadline() is d
        assert 0.0 < robustness.remaining_s() <= 0.05
        robustness.check_deadline("fresh")  # not yet expired
    assert robustness.current_deadline() is None
    # <= 0 is the unbounded no-op scope
    with robustness.deadline_scope(0.0) as d:
        assert d is None and robustness.current_deadline() is None


def test_deadline_expiry_raises():
    with robustness.deadline_scope(1.0):
        time.sleep(0.01)
        assert robustness.remaining_s() == 0.0
        with pytest.raises(robustness.DeadlineExceededError):
            robustness.check_deadline("stage")


def test_deadline_scopes_nest_and_restore():
    with robustness.deadline_scope(10_000.0) as outer:
        with robustness.deadline_scope(1.0) as inner:
            assert robustness.current_deadline() is inner
        assert robustness.current_deadline() is outer


# -- unit: circuit breaker ----------------------------------------------------


def test_breaker_state_machine():
    br = robustness.CircuitBreaker(failure_threshold=3, reset_timeout_s=0.05,
                                   half_open_probes=1)
    assert br.state() == robustness.STATE_CLOSED and br.allow()
    err = faults.InjectedDeviceError("boom")
    br.record_failure(err)
    br.record_failure(err)
    assert br.state() == robustness.STATE_CLOSED  # below threshold
    br.record_failure(err)
    assert br.state() == robustness.STATE_OPEN
    assert not br.allow()  # open: fallback
    time.sleep(0.06)
    assert br.allow()            # cooldown over: half-open probe 1
    assert br.state() == robustness.STATE_HALF_OPEN
    assert not br.allow()        # probe budget (1) spent
    br.record_failure(err)       # probe failed
    assert br.state() == robustness.STATE_OPEN
    time.sleep(0.06)
    assert br.allow()
    br.record_success()          # probe succeeded
    assert br.state() == robustness.STATE_CLOSED and br.allow()


def test_breaker_success_resets_consecutive_count():
    br = robustness.CircuitBreaker(failure_threshold=2, reset_timeout_s=9.0)
    e = faults.InjectedDeviceError("x")
    br.record_failure(e)
    br.record_success()
    br.record_failure(e)
    assert br.state() == robustness.STATE_CLOSED  # never 2 consecutive


def test_is_device_error_classification():
    assert robustness.is_device_error(faults.InjectedDeviceError("x"))
    assert robustness.is_device_error(faults.InjectedOOMError("x"))
    assert not robustness.is_device_error(ValueError("bad k"))
    assert not robustness.is_device_error(RuntimeError("logic bug"))

    class Custom(RuntimeError):
        device_error = True

    assert robustness.is_device_error(Custom("backend says device died"))


# -- unit: fault injector -----------------------------------------------------


def test_fault_injector_count_window():
    inj = faults.FaultInjector()
    inj.plan("p", "device_error", times=2, after=1)
    inj.fire("p")  # skipped (after=1)
    with pytest.raises(faults.InjectedDeviceError):
        inj.fire("p")
    with pytest.raises(faults.InjectedDeviceError):
        inj.fire("p")
    inj.fire("p")  # window (times=2) exhausted
    assert inj.fired("p") == 4 and inj.injected("p") == 2


def test_fault_injector_seeded_bernoulli_is_reproducible():
    def decisions(seed):
        inj = faults.FaultInjector(seed=seed)
        inj.plan("p", "device_error", times=None, p=0.5)
        out = []
        for _ in range(64):
            try:
                inj.fire("p")
                out.append(0)
            except faults.InjectedDeviceError:
                out.append(1)
        return out

    a, b = decisions(7), decisions(7)
    assert a == b and 0 < sum(a) < 64  # same schedule, actually mixed
    assert decisions(8) != a           # a different seed differs


def test_fault_injector_from_spec_and_gating():
    inj = faults.from_spec(
        "a.b:stall:stall_ms=1;c.d:oom:times=1;e.f:device_error:times=inf:p=0.5",
        seed=3)
    inj.fire("a.b")  # stalls 1ms, no error
    with pytest.raises(faults.InjectedOOMError):
        inj.fire("c.d")
    with pytest.raises(ValueError):
        faults.from_spec("justapoint")
    with pytest.raises(ValueError):
        faults.from_spec("a:device_error:bogus=1")
    # disabled fast path: no injector configured => fire is a no-op
    assert faults.get_injector() is None
    faults.fire("a.b")


def test_config_rejects_bad_fault_spec():
    from weaviate_tpu.config.config import ConfigError, load_config

    with pytest.raises(ConfigError):
        load_config({"FAULT_INJECTION": "nocolon"})
    cfg = load_config({"FAULT_INJECTION": "db.shard.search:oom:times=1",
                       "QUERY_TIMEOUT_MS": "250",
                       "BREAKER_FAILURE_THRESHOLD": "2"})
    assert cfg.robustness.query_timeout_ms == 250.0
    assert cfg.robustness.breaker_failure_threshold == 2


# -- fixtures -----------------------------------------------------------------


def _mk_app(tmp_path, *, coalesce=True, window_ms=30.0, max_queued_rows=4096,
            wait_timeout_s=30.0, breaker_threshold=3, breaker_reset_ms=150.0,
            query_timeout_ms=0.0, vecs=None, n=N):
    from weaviate_tpu.server import App

    cfg = Config()
    cfg.coalescer.enabled = coalesce
    cfg.coalescer.window_ms = window_ms
    cfg.coalescer.max_queued_rows = max_queued_rows
    cfg.coalescer.wait_timeout_s = wait_timeout_s
    cfg.robustness.breaker_failure_threshold = breaker_threshold
    cfg.robustness.breaker_reset_ms = breaker_reset_ms
    cfg.robustness.query_timeout_ms = query_timeout_ms
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    app.schema.add_class({
        "class": "Ro", "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "tag", "dataType": ["text"]}],
    })
    if vecs is None:
        rng = np.random.default_rng(23)
        # integer-valued vectors: distances are exact in f32, so host
        # fallback results are bit-comparable to device results
        vecs = rng.integers(-8, 8, (n, DIM)).astype(np.float32)
    idx = app.db.get_index("Ro")
    idx.put_batch([
        StorObj(class_name="Ro", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"tag": "even" if i % 2 == 0 else "odd"},
                vector=vecs[i])
        for i in range(len(vecs))])
    return app, idx, vecs


def _tie_free_queries(vecs, count):
    out, i = [], 0
    while len(out) < count:
        q = vecs[i] + 0.5
        i += 1
        d = np.sort(((vecs - q) ** 2).sum(1))[: K + 8]
        if len(np.unique(d)) == len(d):
            out.append(q)
    return out


def _rows(results):
    return [(r.obj.uuid, r.distance) for r in results]


# -- host fallback plane ------------------------------------------------------


def test_host_fallback_parity_with_device(tmp_path):
    """search_by_vectors_host returns exactly what the device path returns
    on tie-free integer data (the breaker can swap planes mid-journey
    without changing any answer), including post-delete and filtered."""
    app, idx, vecs = _mk_app(tmp_path, coalesce=False)
    try:
        shard = idx.single_local_shard()
        vidx = shard.vector_index
        queries = np.stack(_tie_free_queries(vecs, 6))
        dev_ids, dev_d = vidx.search_by_vectors(queries, K)
        host_ids, host_d = vidx.search_by_vectors_host(queries, K)
        np.testing.assert_array_equal(dev_ids, host_ids)
        np.testing.assert_array_equal(dev_d, host_d)
        # deletes invalidate the cached host rows via the snapshot gen
        for uid in (1, 2, 3):
            shard.delete_object(str(uuidlib.UUID(int=uid)))
        dev_ids, dev_d = vidx.search_by_vectors(queries, K)
        host_ids, host_d = vidx.search_by_vectors_host(queries, K)
        np.testing.assert_array_equal(dev_ids, host_ids)
        np.testing.assert_array_equal(dev_d, host_d)
        # filtered: allowList masks the same docs on both planes
        allow = shard.build_allow_list(LocalFilter.from_dict({
            "path": ["tag"], "operator": "Equal", "valueText": "even"}))
        dev_ids, dev_d = vidx.search_by_vectors(queries, K, allow)
        host_ids, host_d = vidx.search_by_vectors_host(queries, K, allow)
        np.testing.assert_array_equal(dev_ids, host_ids)
        np.testing.assert_array_equal(dev_d, host_d)
    finally:
        app.shutdown()


def test_host_plane_parity_after_pq_recompress_and_compact(tmp_path):
    """The host plane is the quality auditor's ground truth (monitoring/
    quality.py) as well as the breaker's fallback: it must stay exact
    through a declarative PQ re-compress and through delete+compact.
    Integer vectors are bf16-exact, so the PQ-rescore device tier and the
    f32 host plane return identical answers on tie-free queries."""
    app, idx, vecs = _mk_app(tmp_path, coalesce=False)
    try:
        shard = idx.single_local_shard()
        vidx = shard.vector_index
        queries = np.stack(_tie_free_queries(vecs, 6))

        def assert_parity():
            dev_ids, dev_d = vidx.search_by_vectors(queries, K)
            host_ids, host_d = vidx.search_by_vectors_host(queries, K)
            np.testing.assert_array_equal(dev_ids, host_ids)
            np.testing.assert_array_equal(dev_d, host_d)
            # ...and the pinned audit entry agrees with the live one
            snap = vidx._snap
            pin_ids, pin_d = vidx.search_by_vectors_host_pinned(
                snap, queries, K)
            np.testing.assert_array_equal(host_ids, pin_ids)
            np.testing.assert_array_equal(host_d, pin_d)

        assert_parity()
        # declarative PQ compress (the config-update trigger): the device
        # tier flips to pq_rescore_bf16; the host plane keeps serving the
        # full-precision rows (host_vecs under PQ)
        cfg = vidx.config
        cfg.pq.enabled = True
        cfg.pq.segments = 4
        cfg.pq.centroids = 16
        vidx.compress()
        assert vidx.compressed
        assert_parity()
        # deletes + compact: slots rebuild wholesale (fresh allow token,
        # re-encoded codes); both planes must track the surviving docs
        for uid in range(1, 30):
            shard.delete_object(str(uuidlib.UUID(int=uid)))
        vidx.compact()
        assert len(vidx) == N - 29
        assert_parity()
        # filtered parity survives the rebuild too
        allow = shard.build_allow_list(LocalFilter.from_dict({
            "path": ["tag"], "operator": "Equal", "valueText": "even"}))
        dev_ids, dev_d = vidx.search_by_vectors(queries, K, allow)
        host_ids, host_d = vidx.search_by_vectors_host(queries, K, allow)
        np.testing.assert_array_equal(dev_ids, host_ids)
        np.testing.assert_array_equal(dev_d, host_d)
    finally:
        app.shutdown()


# -- journey: device error mid-coalesced-dispatch -> breaker -> recovery ------


def test_device_error_journey_breaker_trips_and_recovers(tmp_path):
    """Repeated injected device failure mid-coalesced-dispatch: every rider
    still gets a correct answer (lane fails -> direct retry -> breaker
    trips -> host fallback serves), the breaker is observable OPEN in
    /metrics, and once the fault clears a half-open probe closes it and
    the device serves again."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=20.0, breaker_threshold=3,
                             breaker_reset_ms=150.0)
    inj = faults.configure(faults.FaultInjector())
    try:
        queries = _tie_free_queries(vecs, 8)
        expected = [
            _rows(idx.object_vector_search(q, K)[0]) for q in queries]
        inj.plan("index.tpu.dispatch", "device_error", times=None)

        got = [None] * len(queries)
        errs = [None] * len(queries)

        def run(i):
            try:
                got[i] = _rows(app.traverser.get_class(GetParams(
                    class_name="Ro",
                    near_vector={"vector": queries[i].tolist()}, limit=K)))
            except Exception as e:  # noqa: BLE001 — recorded for assert
                errs[i] = e

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), "request hung"
        # zero hung, zero crashed: every request resolved to the CORRECT
        # answer via the breaker-routed host fallback
        assert errs == [None] * len(queries)
        assert got == expected
        # the parallel batch may have coalesced into fewer than `threshold`
        # failed dispatches; sequential requests (one failed dispatch each
        # while closed) deterministically finish tripping the breaker
        for _ in range(6):
            if app.breaker.state() == robustness.STATE_OPEN:
                break
            r = _rows(app.traverser.get_class(GetParams(
                class_name="Ro", near_vector={"vector": queries[2].tolist()},
                limit=K)))
            assert r == expected[2]
        assert app.breaker.state() == robustness.STATE_OPEN
        exposed = app.metrics.expose().decode()
        assert "weaviate_breaker_state 1.0" in exposed
        assert "weaviate_device_fallback_total" in exposed

        # while OPEN, serving keeps working from the host plane
        again = _rows(app.traverser.get_class(GetParams(
            class_name="Ro", near_vector={"vector": queries[0].tolist()},
            limit=K)))
        assert again == expected[0]

        # fault clears -> cooldown -> half-open probe succeeds -> CLOSED
        inj.clear()
        time.sleep(0.2)
        probe = _rows(app.traverser.get_class(GetParams(
            class_name="Ro", near_vector={"vector": queries[1].tolist()},
            limit=K)))
        assert probe == expected[1]
        assert app.breaker.state() == robustness.STATE_CLOSED
        assert "weaviate_breaker_state 0.0" in app.metrics.expose().decode()
        # recovery releases the host fallback copy (a full f32 store
        # materialization at scale — it must not stay pinned)
        assert idx.single_local_shard().vector_index._host_rows_cache is None
    finally:
        faults.unconfigure(inj)
        app.shutdown()


def test_zero_device_work_never_feeds_the_breaker(tmp_path):
    """A search that succeeds WITHOUT device work (empty-allowList early
    return) must not reset the consecutive-failure count: interleaved
    empty-filter queries on a dying device would otherwise keep the
    breaker from ever tripping."""
    app, idx, vecs = _mk_app(tmp_path, coalesce=False, breaker_threshold=2)
    inj = faults.configure(faults.FaultInjector())
    try:
        shard = idx.single_local_shard()
        empty_flt = LocalFilter.from_dict({
            "path": ["tag"], "operator": "Equal", "valueText": "nosuchtag"})
        inj.plan("index.tpu.dispatch", "device_error", times=1)
        r = shard.object_vector_search(vecs[0], K)  # failure #1 (fallback)
        assert r[0]
        assert app.breaker.state() == robustness.STATE_CLOSED
        # empty-allow success: zero device work, must NOT reset the count
        assert shard.object_vector_search(vecs[0], K, flt=empty_flt) == [[]]
        inj.plan("index.tpu.dispatch", "device_error", times=1)
        shard.object_vector_search(vecs[0], K)      # failure #2 -> trips
        assert app.breaker.state() == robustness.STATE_OPEN
    finally:
        faults.unconfigure(inj)
        app.shutdown()


def test_rest_zero_timeout_header_cannot_opt_out_of_default(tmp_path):
    """X-Request-Timeout-Ms: 0 falls back to the operator's
    QUERY_TIMEOUT_MS default (the gRPC twin's semantics) — a client
    cannot make itself unbounded."""
    from weaviate_tpu.server import RestServer

    app, idx, vecs = _mk_app(tmp_path, window_ms=2000.0,
                             query_timeout_ms=40.0)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        st, _, out = _rest(srv.port, "POST", "/v1/graphql",
                           {"query": _gql_near(vecs[0])},
                           headers={"X-Request-Timeout-Ms": "0"})
        assert st == 504, out
    finally:
        srv.stop()
        app.shutdown()


def test_allocator_oom_on_write_is_a_device_error(tmp_path):
    """index.tpu.alloc injection: a store-growth OOM surfaces as a device
    error (recognized by the breaker's classifier), not a silent hang."""
    app, idx, vecs = _mk_app(tmp_path, coalesce=False, n=64)
    inj = faults.configure(faults.FaultInjector())
    try:
        inj.plan("index.tpu.alloc", "oom", times=1)
        shard = idx.single_local_shard()
        big = np.ones((20000, DIM), np.float32)
        with pytest.raises(faults.InjectedOOMError) as ei:
            shard.vector_index.add_batch(list(range(10_000, 30_000)), big)
        assert robustness.is_device_error(ei.value)
    finally:
        faults.unconfigure(inj)
        app.shutdown()


def test_async_enqueue_device_error_defers_host_fallback(tmp_path):
    """A device error at the ASYNC enqueue returns a deferred host-fallback
    closure; calling it later (another thread, after the except frame is
    gone) still serves the correct answer — regression for the cleared
    except-variable capture."""
    app, idx, vecs = _mk_app(tmp_path, coalesce=False)
    inj = faults.configure(faults.FaultInjector())
    try:
        shard = idx.single_local_shard()
        q = _tie_free_queries(vecs, 1)[0]
        expected = _rows(shard.object_vector_search(q, K)[0])
        inj.plan("index.tpu.dispatch", "device_error", times=1)
        done = shard.object_vector_search_async(q, K)
        out = [None]
        t = threading.Thread(target=lambda: out.__setitem__(0, done()))
        t.start()
        t.join(timeout=30)
        assert _rows(out[0][0]) == expected
    finally:
        faults.unconfigure(inj)
        app.shutdown()


# -- journey: deadline expired in queue ---------------------------------------


def test_deadline_expires_in_admission_queue(tmp_path):
    """A request whose deadline is shorter than the coalescer window fails
    fast with DeadlineExceededError — bounded by its own budget, far
    before the window flush — instead of occupying dispatch rows."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=2000.0)
    try:
        t0 = time.monotonic()
        with robustness.deadline_scope(40.0):
            with pytest.raises(robustness.DeadlineExceededError):
                app.traverser.get_class(GetParams(
                    class_name="Ro", near_vector={"vector": vecs[0].tolist()},
                    limit=K))
        elapsed = time.monotonic() - t0
        assert elapsed < 1.0, f"not fail-fast: {elapsed:.2f}s"
        assert "weaviate_deadline_expired_total" in \
            app.metrics.expose().decode()
    finally:
        app.shutdown()


def test_already_expired_request_never_dispatches(tmp_path):
    app, idx, vecs = _mk_app(tmp_path, coalesce=False)
    try:
        with robustness.deadline_scope(1.0):
            time.sleep(0.01)
            with pytest.raises(robustness.DeadlineExceededError):
                app.traverser.get_class(GetParams(
                    class_name="Ro",
                    near_vector={"vector": vecs[0].tolist()}, limit=K))
    finally:
        app.shutdown()


# -- journey: queue-full shedding ---------------------------------------------


def test_queue_full_sheds_with_retry_after(tmp_path):
    """Admission beyond max_queued_rows sheds (OverloadedError with a
    retry hint) instead of queueing unboundedly; the python-side and
    prometheus shed counters both move."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=5000.0, max_queued_rows=3)
    try:
        shard = idx.single_local_shard()
        co = app.coalescer
        waits = [co.submit(shard, vecs[i], K) for i in range(3)]
        assert all(w is not None for w in waits)
        with pytest.raises(robustness.OverloadedError) as ei:
            co.submit(shard, vecs[3], K)
        assert ei.value.retry_after_s > 0
        assert co.stats()["shed"].get("queue_full") == 1
        assert 'weaviate_requests_shed_total{reason="queue_full"} 1.0' in \
            app.metrics.expose().decode()
    finally:
        app.shutdown()  # queued waiters get the shutdown error


def test_shed_requests_do_not_fall_through_to_direct_path(tmp_path):
    """A shed MUST shed: the traverser propagates OverloadedError instead
    of retrying the direct path (which would defeat admission control)."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=5000.0, max_queued_rows=2)
    try:
        shard = idx.single_local_shard()
        for i in range(2):
            assert app.coalescer.submit(shard, vecs[i], K) is not None
        with pytest.raises(robustness.OverloadedError):
            app.traverser.get_class(GetParams(
                class_name="Ro", near_vector={"vector": vecs[5].tolist()},
                limit=K))
    finally:
        app.shutdown()


# -- journey: flush-thread death liveness -------------------------------------


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_flush_thread_death_keeps_clients_live(tmp_path):
    """Injected flush-thread death mid-flush (a BaseException the loop's
    `except Exception` defense cannot catch, with a lane IN FLIGHT):
    the stranded waiter hits its bounded wait and retries direct; later
    submits bypass with `flusher_dead`. Zero hangs, every request gets
    its correct answer."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=50.0, wait_timeout_s=0.5)
    inj = faults.configure(faults.FaultInjector())
    try:
        q = _tie_free_queries(vecs, 1)[0]
        expected = _rows(idx.object_vector_search(q, K)[0])
        # the flusher dies AT the lane dispatch: the lane is stranded
        # (never resolved, never failed) and the thread is gone
        inj.plan("serving.coalescer.dispatch", "die", times=1)
        t0 = time.monotonic()
        got = _rows(app.traverser.get_class(GetParams(
            class_name="Ro", near_vector={"vector": q.tolist()}, limit=K)))
        elapsed = time.monotonic() - t0
        assert got == expected          # served via the direct-path retry
        assert elapsed < 5.0, f"hang: {elapsed:.1f}s"
        # flusher is dead now: admission refuses instead of queueing into
        # lanes nobody will flush — and serving still works
        deadline = time.monotonic() + 5.0
        while app.coalescer._thread.is_alive() \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not app.coalescer._thread.is_alive()
        got2 = _rows(app.traverser.get_class(GetParams(
            class_name="Ro", near_vector={"vector": q.tolist()}, limit=K)))
        assert got2 == expected
        assert app.coalescer.stats()["bypass"].get("flusher_dead", 0) >= 1
    finally:
        faults.unconfigure(inj)
        app.shutdown()


def test_dead_pool_task_wakes_waiters(tmp_path):
    """A dispatch-pool submission that dies after admission (cancelled, or
    killed outside its own error handling) wakes its waiters through the
    future reaper — nobody waits out the liveness bound."""
    from concurrent.futures import Future

    app, idx, vecs = _mk_app(tmp_path, window_ms=10.0, wait_timeout_s=20.0)
    try:
        co = app.coalescer
        shard = idx.single_local_shard()

        class DyingPool:
            def submit(self, fn, *a, **kw):
                fut = Future()
                # the task "ran" but died outside its error handling
                fut.set_exception(faults.InjectedThreadDeath("pool died"))
                return fut

            def shutdown(self, wait=True):
                pass

        co._dispatch_pool = DyingPool()
        w = co.submit(shard, vecs[0], K)
        assert w is not None
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="dispatch task died"):
            w()
        # woken by the reaper, not by the 20 s liveness bound
        assert time.monotonic() - t0 < 5.0
    finally:
        app.shutdown()


def test_waiter_timeout_is_bounded_and_typed(tmp_path):
    """With the flusher wedged (never flushing: huge window) and no
    deadline, a waiter raises CoalescerTimeoutError at its liveness cap."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=60_000.0,
                             wait_timeout_s=0.25)
    try:
        w = app.coalescer.submit(idx.single_local_shard(), vecs[0], K)
        assert w is not None
        t0 = time.monotonic()
        with pytest.raises(CoalescerTimeoutError):
            w()
        assert 0.2 < time.monotonic() - t0 < 3.0
    finally:
        app.shutdown()


# -- REST / gRPC surfaces -----------------------------------------------------


def _gql_near(vec):
    return ('{ Get { Ro(limit: %d, nearVector: {vector: %s}) '
            '{ tag _additional { distance } } } }'
            % (K, json.dumps([float(x) for x in vec])))


def _rest(port, method, path, body=None, headers=None, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        data = json.dumps(body).encode() if body is not None else None
        hdrs = {"Content-Type": "application/json", **(headers or {})}
        conn.request(method, path, body=data, headers=hdrs)
        resp = conn.getresponse()
        payload = resp.read()
        return resp.status, dict(resp.getheaders()), \
            json.loads(payload) if payload else None
    finally:
        conn.close()


def test_rest_deadline_and_shed_statuses(tmp_path):
    """X-Request-Timeout-Ms -> 504 on queue expiry; a full admission queue
    -> 429 with a Retry-After header; a malformed header -> 400."""
    from weaviate_tpu.server import RestServer

    # cap 3: the 504 request's expired waiter holds its queue row until
    # the (never-reached) window flush prunes it, so the two filler
    # submits below bring the queue exactly to the cap
    app, idx, vecs = _mk_app(tmp_path, window_ms=5000.0, max_queued_rows=3)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        body = {"query": _gql_near(vecs[0])}
        st, hdrs, out = _rest(
            srv.port, "POST", "/v1/graphql", body,
            headers={"X-Request-Timeout-Ms": "40"})
        assert st == 504, out
        assert "deadline" in out["error"][0]["message"]

        # fill the queue so the next request sheds
        shard = idx.single_local_shard()
        for i in range(2):
            assert app.coalescer.submit(shard, vecs[i], K) is not None
        st, hdrs, out = _rest(srv.port, "POST", "/v1/graphql",
                              {"query": _gql_near(vecs[5])})
        assert st == 429, out
        assert int(hdrs.get("Retry-After", "0")) >= 1
        assert "overloaded" in out["error"][0]["message"]

        st, _, out = _rest(srv.port, "POST", "/v1/graphql", body,
                           headers={"X-Request-Timeout-Ms": "soon"})
        assert st == 400
    finally:
        srv.stop()
        app.shutdown()


def test_rest_generous_deadline_serves_normally(tmp_path):
    from weaviate_tpu.server import RestServer

    app, idx, vecs = _mk_app(tmp_path, window_ms=5.0)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        st, _, out = _rest(
            srv.port, "POST", "/v1/graphql",
            {"query": _gql_near(vecs[0])},
            headers={"X-Request-Timeout-Ms": "15000"})
        assert st == 200 and "errors" not in out
        assert len(out["data"]["Get"]["Ro"]) == K
    finally:
        srv.stop()
        app.shutdown()


def test_grpc_deadline_and_overload_codes(tmp_path):
    """x-request-timeout-ms metadata -> DEADLINE_EXCEEDED; a full queue ->
    RESOURCE_EXHAUSTED with retry-after-s trailing metadata."""
    import grpc

    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

    # cap 3: the DEADLINE_EXCEEDED request's expired waiter holds its
    # queue row until the window flush (see the REST twin above)
    app, idx, vecs = _mk_app(tmp_path, window_ms=5000.0, max_queued_rows=3)
    srv = GrpcServer(app, port=0)
    srv.start()
    cl = SearchClient(f"127.0.0.1:{srv.port}")
    try:
        req = pb.SearchRequest(
            class_name="Ro", limit=K,
            near_vector=pb.NearVectorParams(vector=vecs[0].tolist()))
        with pytest.raises(grpc.RpcError) as ei:
            cl.search(req, metadata=(("x-request-timeout-ms", "40"),))
        assert ei.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED

        shard = idx.single_local_shard()
        for i in range(2):
            assert app.coalescer.submit(shard, vecs[i], K) is not None
        with pytest.raises(grpc.RpcError) as ei:
            cl.search(req)
        assert ei.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        md = {k: v for k, v in (ei.value.trailing_metadata() or ())}
        assert float(md.get("retry-after-s", 0)) > 0
    finally:
        cl.close()
        srv.stop()
        app.shutdown()


def test_grpc_config_default_survives_transport_deadline(tmp_path):
    """The stub's implicit 30 s transport deadline must NOT override the
    operator's QUERY_TIMEOUT_MS: with no explicit metadata, a request that
    would sit past the config default gets DEADLINE_EXCEEDED."""
    import grpc

    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

    app, idx, vecs = _mk_app(tmp_path, window_ms=500.0,
                             query_timeout_ms=40.0)
    srv = GrpcServer(app, port=0)
    srv.start()
    cl = SearchClient(f"127.0.0.1:{srv.port}")
    try:
        req = pb.SearchRequest(
            class_name="Ro", limit=K,
            near_vector=pb.NearVectorParams(vector=vecs[0].tolist()))
        t0 = time.monotonic()
        with pytest.raises(grpc.RpcError) as ei:
            cl.search(req)  # 30 s transport timeout, no metadata
        assert ei.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
        assert time.monotonic() - t0 < 2.0  # the 40 ms default applied
        # an EXPLICIT override may extend past the default (header twin)
        rep = cl.search(req, metadata=(("x-request-timeout-ms", "20000"),))
        assert len(rep.results) == K
    finally:
        cl.close()
        srv.stop()
        app.shutdown()


# -- closed-loop acceptance (scaled): injected failure under load -------------


def test_closed_loop_under_injected_device_failure(tmp_path):
    """The acceptance criterion, scaled to tier-1: a closed-loop run with
    repeated injected device failure completes with ZERO hung requests and
    zero crashes — every request resolves to success, a fast
    deadline/shed error, or a breaker-routed host-fallback answer, and the
    breaker/shed metrics are observable in the exposition."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=5.0, breaker_threshold=3,
                             breaker_reset_ms=50.0, wait_timeout_s=2.0,
                             max_queued_rows=256)
    inj = faults.configure(faults.FaultInjector(seed=11))
    try:
        inj.plan("index.tpu.dispatch", "device_error", times=None, p=0.25)
        queries = _tie_free_queries(vecs, 8)
        expected = {i: _rows(idx.object_vector_search(q, K)[0])
                    for i, q in enumerate(queries)}
        CLIENTS, PER = 16, 12
        outcomes = [[] for _ in range(CLIENTS)]
        unresolved = [PER] * CLIENTS

        def loop(tid):
            rng = np.random.default_rng(tid)
            for _ in range(PER):
                qi = int(rng.integers(0, len(queries)))
                try:
                    with robustness.deadline_scope(1500.0):
                        res = _rows(app.traverser.get_class(GetParams(
                            class_name="Ro",
                            near_vector={"vector": queries[qi].tolist()},
                            limit=K)))
                    outcomes[tid].append(
                        "ok" if res == expected[qi] else "wrong")
                except robustness.OverloadedError:
                    outcomes[tid].append("shed")
                except robustness.DeadlineExceededError:
                    outcomes[tid].append("deadline")
                except Exception as e:  # noqa: BLE001 — outcome accounting
                    outcomes[tid].append(f"error:{type(e).__name__}:{e}")
                unresolved[tid] -= 1

        threads = [threading.Thread(target=loop, args=(i,))
                   for i in range(CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "client thread hung"
        assert sum(unresolved) == 0, "requests left unresolved"
        flat = [o for per in outcomes for o in per]
        # zero crashes/unknowns: every request resolved to success or a
        # fast lifecycle error; and correctness held (host fallback is
        # exact — answers never went wrong, even mid-breaker-flap)
        bad = [o for o in flat
               if o not in ("ok", "shed", "deadline")]
        assert not bad, f"unexpected outcomes: {bad[:5]}"
        assert flat.count("ok") > 0
        exposed = app.metrics.expose().decode()
        assert "weaviate_breaker_state" in exposed
        assert "weaviate_requests_shed_total" in exposed
        assert "weaviate_deadline_expired_total" in exposed
    finally:
        faults.unconfigure(inj)
        app.shutdown()


# -- httputil backoff ---------------------------------------------------------


def test_http_retry_jittered_backoff(monkeypatch):
    from weaviate_tpu.cluster.httputil import Http

    class FlakyConn:
        def __init__(self, fail_times):
            self.fail = fail_times

        def request(self, *a, **kw):
            if self.fail[0] > 0:
                self.fail[0] -= 1
                raise OSError("connection reset")

        def getresponse(self):
            class R:
                status = 200

                def read(self):
                    return b"{}"

            return R()

        def close(self):
            pass

    h = Http(timeout=1.0, attempts=3, backoff_base_s=0.05)
    fail = [2]
    sleeps = []
    monkeypatch.setattr(h, "_sleep", lambda s: sleeps.append(s))
    monkeypatch.setattr(h, "_conn", lambda host: (FlakyConn(fail), False))
    h._rng.seed(42)
    status, _ = h.request("n1:1234", "GET", "/x")
    assert status == 200
    # attempt 1 (stale-socket retry) is immediate; attempt 2 backs off
    # with jitter in [0.5, 1.5] * base
    assert len(sleeps) == 1
    assert 0.025 <= sleeps[0] <= 0.075
    # two instances never sleep in lockstep (jitter decorrelates retries)
    h2 = Http(timeout=1.0, attempts=3, backoff_base_s=0.05)
    h2._rng.seed(43)
    assert h._backoff_s(2) != h2._backoff_s(2)


def test_http_exhausts_attempts_then_raises(monkeypatch):
    from weaviate_tpu.cluster.httputil import Http

    calls = []

    class DeadConn:
        def request(self, *a, **kw):
            calls.append(1)
            raise ConnectionRefusedError("down")

        def close(self):
            pass

    h = Http(timeout=1.0, attempts=3)
    monkeypatch.setattr(h, "_sleep", lambda s: None)
    monkeypatch.setattr(h, "_conn", lambda host: (DeadConn(), False))
    with pytest.raises(OSError):
        h.request("n1:1234", "GET", "/x")
    assert len(calls) == 3  # per-attempt bounded: exactly `attempts` tries


def test_http_nonidempotent_fresh_conn_failure_never_retries(monkeypatch):
    """A POST that dies mid-read on a FRESH connection must NOT re-execute
    (the peer may already have applied a 2PC prepare/commit); a stale
    reused keep-alive socket still gets its immediate retry."""
    from weaviate_tpu.cluster.httputil import Http

    calls = []

    class MidReadDeath:
        def request(self, *a, **kw):
            calls.append(1)

        def getresponse(self):
            raise TimeoutError("timed out reading the response")

        def close(self):
            pass

    h = Http(timeout=1.0, attempts=3)
    monkeypatch.setattr(h, "_sleep", lambda s: None)
    monkeypatch.setattr(h, "_conn", lambda host: (MidReadDeath(), False))
    with pytest.raises(OSError):
        h.request("n1:1234", "POST", "/replicas/x", body=b"{}")
    assert len(calls) == 1  # executed once, never re-sent

    # reused keep-alive: the send provably never executed -> retried
    calls.clear()
    seq = [True, False]  # first conn reused (stale), retry conn fresh

    class StaleThenOk(MidReadDeath):
        def __init__(self, ok):
            self.ok = ok

        def getresponse(self):
            if not self.ok:
                raise ConnectionResetError("stale keep-alive")

            class R:
                status = 200

                def read(self):
                    return b"{}"

            return R()

    conns = [StaleThenOk(False), StaleThenOk(True)]
    monkeypatch.setattr(h, "_conn",
                        lambda host: (conns[len(calls)], seq[len(calls)]))
    status, _ = h.request("n1:1234", "POST", "/replicas/x", body=b"{}")
    assert status == 200 and len(calls) == 2


def test_breaker_half_open_probe_slot_expires():
    """An abandoned probe (dispatch died without a success/failure verdict)
    must not wedge the breaker in HALF_OPEN forever: after one cooldown
    with no verdict the probe slot recycles."""
    br = robustness.CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05,
                                   half_open_probes=1)
    br.record_failure(faults.InjectedDeviceError("x"))
    assert br.state() == robustness.STATE_OPEN
    time.sleep(0.06)
    assert br.allow()                 # probe granted...
    assert not br.allow()             # ...slot taken
    # the probe is abandoned (no record_*); after another cooldown the
    # slot recycles instead of locking every caller onto the host plane
    time.sleep(0.06)
    assert br.allow()
    br.record_success()
    assert br.state() == robustness.STATE_CLOSED


def test_is_device_error_excludes_jax_programming_errors():
    """jax.* tracer/concretization errors are deterministic code bugs —
    they must NOT read as device incidents; jaxlib runtime errors do."""
    prog = type("ConcretizationTypeError", (RuntimeError,), {})
    prog.__module__ = "jax.errors"
    assert not robustness.is_device_error(prog("tracer leak"))
    rt = type("SomeRuntimeFault", (RuntimeError,), {})
    rt.__module__ = "jaxlib.xla_extension"
    assert robustness.is_device_error(rt("device halted"))
    named = type("XlaRuntimeError", (RuntimeError,), {})
    named.__module__ = "somewhere.else"
    assert robustness.is_device_error(named("RESOURCE_EXHAUSTED"))
