"""4-bit Quick-ADC scan plane + three-stage re-ranking funnel
(ops/pq4.py, pq.bits=4 in index/tpu.py and index/mesh.py).

Pins the funnel PR's contracts:

1. FUNNEL == EXACT when the budgets cover the candidate set (rc >= n):
   stage 3 reports exact distances, so on tie-free integer data the
   funnel's answer equals the exact scan's — per tier (full store, IVF,
   mesh), fused == legacy, sync == async.
2. The OPQ rotation is a real rotation (orthonormal round-trip), it
   lowers quantization error on correlated data, and the 4-bit ladder is
   fit in the SAME rotated space as the 8-bit one (pinned matrix).
3. Snapshot pinning: a dispatch enqueued before re-compress/compact
   answers from the OLD generation's arrays.
4. Composition: the funnel serves under IVF probing, filters,
   tombstones, and the mesh's per-device scan.
5. Disabled mode (bits=8) is zero-hop: no funnel entry point runs.
6. The satellites: pack/unpack layout, byte-LUT math, VMEM tile
   planning, plan_funnel floors, controller funnel-budget ladder,
   costmodel stage attribution, perf tier tallies, memory-ledger
   components, health()["pq"]["funnel"], graftlint frozensets.
"""

import numpy as np
import pytest

from weaviate_tpu.compress.pq import pack_codes4, unpack_codes4
from weaviate_tpu.config.config import (
    PQ4_FUNNEL_C_BUCKETS,
    PQ4_FUNNEL_RESCORE_BUCKETS,
    IvfConfig,
)
from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.index import tpu
from weaviate_tpu.index.tpu import TpuVectorIndex
from weaviate_tpu.monitoring import costmodel, memory, perf, quality, tracing
from weaviate_tpu.ops import pq4 as pq4_ops
from weaviate_tpu.serving import controller
from weaviate_tpu.serving.controller import (
    KNOB_FUNNEL_C,
    KNOB_FUNNEL_RESCORE,
    ControlPlane,
)
from weaviate_tpu.storage.bitmap import Bitmap

DIM = 16
SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


@pytest.fixture(autouse=True)
def _reset_globals():
    saved = controller._plane
    controller._plane = None
    yield
    controller._plane = saved
    tpu.set_ivf_config(None)
    tpu.set_fused_enabled(None)
    tracing.configure(None)
    perf.configure(None)
    memory.configure(None)


PQ4 = {"enabled": True, "segments": 4, "centroids": 32, "bits": 4,
       "rescore": True, "rotation": "opq"}


def _mk_index(tmp_path, n=256, seed=0, name="f4", pq=PQ4, **cfg_extra):
    """Small-integer vectors: every L2 distance is exact integer
    arithmetic in f32/bf16 regardless of accumulation order, so
    funnel-vs-exact equality checks are exact (the fused-dispatch test
    convention). n <= 256 keeps rc (top rescore bucket) >= live rows: the
    funnel budgets cover everything and stage 3 IS the exact answer.
    n == 256 is also the declarative-compress threshold floor."""
    rng = np.random.default_rng(seed)
    vecs = rng.integers(-8, 8, (n, DIM)).astype(np.float32)
    # exactTopK: stage-1 keeps are lax.top_k, so with budgets >= live rows
    # the funnel is a complete scan (approx_min_k recall is the bench's
    # domain, not an equality pin's)
    d = {"distance": "l2-squared", "exactTopK": True, **cfg_extra}
    if pq is not None:
        d["pq"] = pq
    cfg = parse_and_validate_config("hnsw_tpu", d)
    idx = TpuVectorIndex(cfg, str(tmp_path / name), persist=False)
    idx.add_batch(np.arange(n), vecs)
    idx.flush()
    if pq is not None and pq.get("bits") == 4:
        assert idx.compressed and idx._codes4 is not None
        assert idx._pq4 is not None and idx._pq4.centroids == 16
    return idx, vecs


def _brute(vecs, q, k):
    d = ((vecs - q) ** 2).sum(1)
    order = np.argsort(d, kind="stable")[:k]
    return order, d[order]


# -- 1. funnel == exact when the budgets cover the set ------------------------


def test_funnel_matches_exact_fused_legacy_sync_async(tmp_path):
    idx, vecs = _mk_index(tmp_path)
    q = (vecs[:12] + 0.25).astype(np.float32)
    lanes = {}
    for fused in (True, False):
        tpu.set_fused_enabled(fused)
        lanes[("sync", fused)] = idx.search_by_vectors(q, 5)
        lanes[("async", fused)] = idx.search_by_vectors_async(q, 5)()
    want_ids, want_d = zip(*(_brute(vecs, q[i], 5) for i in range(len(q))))
    for (lane, fused), (ids, dists) in lanes.items():
        for i in range(len(q)):
            np.testing.assert_allclose(
                dists[i], want_d[i], rtol=0, atol=1e-4,
                err_msg=f"{lane} fused={fused} q{i}")
            assert {int(x) for x in ids[i]} == {int(x) for x in want_ids[i]}, \
                (lane, fused, i)
    # every lane bit-agrees with every other (same program, same snapshot)
    ref_ids, ref_d = lanes[("sync", True)]
    for key, (ids, dists) in lanes.items():
        np.testing.assert_array_equal(ids, ref_ids, err_msg=str(key))
        np.testing.assert_array_equal(dists, ref_d, err_msg=str(key))


def test_funnel_dispatches_on_the_pq_adc4_tier(tmp_path):
    idx, vecs = _mk_index(tmp_path)
    assert idx.dispatch_tier(idx._read_snapshot()) == costmodel.TIER_PQ_ADC4
    tracing.configure(tracing.Tracer(sample_rate=1.0))
    win = perf.configure(perf.PerfWindow(window_s=60.0))
    idx.search_by_vectors(vecs[:8] + 0.25, 5)
    shape = idx.pop_dispatch_shape()
    assert shape is not None and shape.tier == costmodel.TIER_PQ_ADC4
    assert shape.bytes_per_row == idx._pq4.segments // 2
    assert shape.extra["funnel_c"] >= shape.extra["funnel_rescore"] >= 5
    win.record_dispatch(shape, rows=8)
    assert win.summary()["tiers"].get(costmodel.TIER_PQ_ADC4) == 1


def test_funnel_respects_filters_and_tombstones(tmp_path):
    idx, vecs = _mk_index(tmp_path)
    for doc in range(0, 40, 2):
        idx.delete(doc)
    idx.flush()
    idx.config.flat_search_cutoff = 0  # stay on the masked full-scan path
    allow = Bitmap(np.arange(120).astype(np.uint64))
    ids, _ = idx.search_by_vectors(vecs[:12] + 0.25, 5, allow_list=allow)
    flat = ids.ravel()
    flat = flat[flat != SENTINEL]
    assert all(int(x) < 120 for x in flat)
    assert all(int(x) % 2 == 1 or int(x) >= 40 for x in flat)


def test_funnel_composes_with_ivf_probe(tmp_path):
    """top_p = all partitions + budgets >= n: the probed funnel equals
    the exact answer; a real filter composes through the probe."""
    tpu.set_ivf_config(IvfConfig(enabled=True, nlist=8, min_n=64, top_p=8,
                                 train_sample=4096, train_iters=4))
    idx, vecs = _mk_index(tmp_path, name="ivf4")
    assert idx._ivf_centroids is not None  # trained at import
    q = (vecs[:10] + 0.25).astype(np.float32)
    for fused in (True, False):
        tpu.set_fused_enabled(fused)
        ids, dists = idx.search_by_vectors(q, 5)
        for i in range(len(q)):
            want_ids, want_d = _brute(vecs, q[i], 5)
            np.testing.assert_allclose(dists[i], want_d, rtol=0, atol=1e-4)
            assert {int(x) for x in ids[i]} == {int(x) for x in want_ids}
    allow = Bitmap(np.arange(100, 200).astype(np.uint64))
    ids_f, _ = idx.search_by_vectors(q, 5, allow_list=allow)
    flat = ids_f.ravel()
    flat = flat[flat != SENTINEL]
    assert flat.size and all(100 <= int(x) < 200 for x in flat)


def test_funnel_snapshot_pins_across_recompress_and_compact(tmp_path):
    """Enqueue -> delete winners + compact (which re-encodes BOTH
    ladders) -> finalize answers from the OLD snapshot's codes4/opq."""
    tpu.set_fused_enabled(True)
    idx, vecs = _mk_index(tmp_path)
    q = (vecs[:4] + 0.25).astype(np.float32)
    want = idx.search_by_vectors(q, 5)
    fin = idx.search_by_vectors_async(q, 5)
    winners = [int(x) for x in np.unique(want[0]) if x != SENTINEL]
    idx.delete(*winners[:3])
    idx.compact()
    assert idx._codes4 is not None  # the 4-bit ladder survived compact
    got = fin()
    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_array_equal(got[1], want[1])
    fresh = idx.search_by_vectors(q, 5)
    assert not set(winners[:3]) & {int(x) for x in fresh[0].ravel()}


def test_funnel_on_mesh_parity_filters_and_append(tmp_path, rng):
    """The mesh's per-device funnel: compress-to-4-bit parity vs brute
    force, filtered search, post-compress append, delete, and the pq4
    device slabs in the per-device ledger components."""
    import os

    from weaviate_tpu.index.mesh import MeshVectorIndex

    config = parse_and_validate_config(
        "hnsw_tpu_mesh", {"distance": "l2-squared", "exactTopK": True})
    os.makedirs(tmp_path / "m4", exist_ok=True)  # codebook save target
    idx = MeshVectorIndex(config, str(tmp_path / "m4"), persist=False,
                          initial_capacity_per_shard=64)
    vecs = rng.integers(-8, 8, (400, DIM)).astype(np.float32)
    idx.add_batch(np.arange(400), vecs)
    idx.flush()
    idx.update_user_config(parse_and_validate_config(
        "hnsw_tpu_mesh",
        {"distance": "l2-squared", "exactTopK": True, "pq": PQ4}))
    assert idx.compressed and idx._codes4 is not None
    assert idx._pq4 is not None and idx._pq4.centroids == 16
    comps = idx._memory_components()
    assert comps["pq4_codes"] > 0 and comps["opq_rot"] > 0

    q = (vecs[:10] + 0.25).astype(np.float32)
    ids, dists = idx.search_by_vectors(q, 5)
    for i in range(len(q)):
        want_ids, want_d = _brute(vecs, q[i], 5)
        np.testing.assert_allclose(dists[i], want_d, rtol=0, atol=1e-4)
        assert {int(x) for x in ids[i]} == {int(x) for x in want_ids}

    allow = Bitmap(range(100, 200))
    ids_f, _ = idx.search_by_vectors(vecs[150][None, :] + 0.25, 3,
                                     allow_list=allow)
    assert int(ids_f[0][0]) == 150
    assert all(100 <= int(x) < 200 for x in ids_f[0] if x != SENTINEL)

    nv = rng.integers(-8, 8, DIM).astype(np.float32) * 5.0
    idx.add(9999, nv)
    idx.flush()
    ids2, _ = idx.search_by_vector(nv, 1)
    assert int(ids2[0]) == 9999

    idx.delete(int(ids[0][0]))
    ids3, _ = idx.search_by_vectors(q[:1], 3)
    assert int(ids[0][0]) not in [int(x) for x in ids3[0]]
    idx.shutdown()


def test_bits8_mode_never_touches_the_funnel(tmp_path, monkeypatch):
    """Disabled mode (the default 8-bit ladder) is zero-hop: no funnel
    entry point may run, and no 4-bit slabs exist."""
    def boom(*a, **k):
        raise AssertionError("funnel entry point touched in bits=8 mode")

    for name in ("search_pq4_funnel", "search_pq4_funnel_fused",
                 "search_ivf_pq4", "search_ivf_pq4_fused",
                 "pq4_funnel_topk", "plan_funnel"):
        monkeypatch.setattr(pq4_ops, name, boom)
    pq8 = {"enabled": True, "segments": 4, "centroids": 32, "rescore": True}
    idx, vecs = _mk_index(tmp_path, pq=pq8, name="no4")
    assert idx._codes4 is None and idx._pq4 is None
    assert idx._opq_rot_dev is None
    ids, _ = idx.search_by_vectors(vecs[:8] + 0.25, 5)
    assert ids.shape == (8, 5)
    assert "funnel" not in idx.health()["pq"]


# -- 2. OPQ rotation ----------------------------------------------------------


def _correlated(rng, n=1200, d=DIM):
    """Anisotropic, cross-segment-correlated data: where OPQ helps."""
    basis = np.linalg.qr(rng.standard_normal((d, d)))[0]
    scales = np.linspace(3.0, 0.1, d)
    return (rng.standard_normal((n, d)) * scales) @ basis.T


def test_opq_rotation_roundtrip_and_recall_improves(rng):
    from weaviate_tpu.compress.pq import ProductQuantizer
    from weaviate_tpu.entities import vectorindex as vi

    vecs = _correlated(rng).astype(np.float32)

    def fit(rotation):
        pq = ProductQuantizer(DIM, 4, 32, vi.DISTANCE_L2,
                              vi.PQ_ENCODER_KMEANS, "normal", rotation)
        pq.fit(vecs)
        return pq

    plain, opq = fit(vi.PQ_ROTATION_NONE), fit(vi.PQ_ROTATION_OPQ)
    r = opq.rotation_matrix
    assert r is not None and r.shape == (DIM, DIM)
    np.testing.assert_allclose(r @ r.T, np.eye(DIM), atol=1e-4)

    def recon_err(pq):
        recon = pq.decode(pq.encode(vecs))  # decode maps back to input space
        return float(((vecs - recon) ** 2).sum(1).mean())

    assert recon_err(opq) < recon_err(plain) * 0.9  # real improvement

    # the 4-bit ladder pins the 8-bit ladder's rotation: same basis
    pq4 = ProductQuantizer(DIM, 4, 16, vi.DISTANCE_L2,
                           vi.PQ_ENCODER_KMEANS, "normal",
                           vi.PQ_ROTATION_NONE)
    pq4.fit(vecs, rotation_matrix=opq.rotation_matrix)
    np.testing.assert_array_equal(pq4.rotation_matrix, opq.rotation_matrix)
    # rotation_dev() is total: identity when nothing was fitted
    ident = plain.rotation_dev()
    np.testing.assert_allclose(np.asarray(ident), np.eye(DIM), atol=1e-6)


def test_opq_index_applies_rotation_at_dispatch(tmp_path):
    """The index stores the rotation once ([D, D] device constant) and
    ranks in rotated space — searching still finds raw-space neighbors."""
    idx, vecs = _mk_index(tmp_path, name="rot")
    assert idx._opq_rot_dev is not None
    comps = idx._memory_components()
    assert comps["opq_rot"] == DIM * DIM * 4
    ids, _ = idx.search_by_vectors(vecs[:6] + 0.25, 1)
    np.testing.assert_array_equal(ids[:, 0], np.arange(6))


# -- 3. ops-level satellites --------------------------------------------------


def test_pack_unpack_roundtrip(rng):
    codes = rng.integers(0, 16, (50, 12)).astype(np.uint8)
    packed = pack_codes4(codes)
    assert packed.shape == (50, 6) and packed.dtype == np.uint8
    # layout: byte j = seg j | seg (mb + j) << 4
    np.testing.assert_array_equal(packed[:, 0] & 15, codes[:, 0])
    np.testing.assert_array_equal(packed[:, 0] >> 4, codes[:, 6])
    np.testing.assert_array_equal(unpack_codes4(packed), codes)
    with pytest.raises(ValueError):
        pack_codes4(codes[:, :11])  # odd M never packs


def test_byte_lut_matches_per_segment_sum(rng):
    import jax.numpy as jnp

    m, ds = 6, 4
    cb = rng.standard_normal((m, 16, ds)).astype(np.float32)
    q = rng.standard_normal((3, m * ds)).astype(np.float32)
    lut = np.asarray(pq4_ops.byte_lut(jnp.asarray(q), jnp.asarray(cb)))
    codes = rng.integers(0, 16, (20, m)).astype(np.uint8)
    packed = pack_codes4(codes)
    got = lut[:, (np.arange(m // 2) * 256)[None, :] + packed.astype(np.int64)
              ].sum(-1)
    qs = q.reshape(3, m, ds)
    # straightforward reference: sum of per-segment q.centroid dots
    want = np.zeros((3, 20), np.float32)
    for b in range(3):
        for r in range(20):
            want[b, r] = sum(
                qs[b, s] @ cb[s, codes[r, s]] for s in range(m))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_plan_tiles_pq4_respects_budget():
    from weaviate_tpu.ops.gmin_scan import _VMEM_BUDGET

    qb, scg, mseg, fp = pq4_ops.plan_tiles_pq4(16384, 128, 65536, 16, 16)
    assert fp <= _VMEM_BUDGET and qb >= 64 and scg >= 64
    qb2, scg2, _, _ = pq4_ops.plan_tiles_pq4(512, 2048, 4096, 16, 256)
    assert qb2 >= 64 and scg2 >= 64


def test_plan_funnel_floors_and_caps():
    c_top = PQ4_FUNNEL_C_BUCKETS[-1]
    rc_top = PQ4_FUNNEL_RESCORE_BUCKETS[-1]
    # big index, top budgets: C = c_cap, rc = rc_cap
    rg4, rc = pq4_ops.plan_funnel(10, 1 << 20, c_top, rc_top)
    assert rg4 * 16 == c_top and rc == rc_top
    # tiny index: both stages clamp to what exists
    rg4, rc = pq4_ops.plan_funnel(10, 64, c_top, rc_top)
    assert rg4 == 4 and rc == 64
    # k deeper than the cut: rc floors at k (never starves coverage)
    rg4, rc = pq4_ops.plan_funnel(300, 1 << 20, c_top, rc_top)
    assert rc == 300
    # k deeper than the whole stage-1 keep: rc collapses to the keep
    rg4, rc = pq4_ops.plan_funnel(100, 80, c_top, rc_top)
    assert rg4 == 5 and rc == 80


# -- 4. the controller's funnel-budget ladder ---------------------------------


def _plane(**overrides) -> ControlPlane:
    return ControlPlane(start=False, **overrides)


def test_funnel_caps_cut_back_off_and_revert():
    p = _plane(hold_ticks=1, recall_floor=0.98, recall_slack=0.015,
               recall_backoff_margin=0.005)
    sense = {"ewma": 1.0}
    p._sense_recall = lambda: sense["ewma"]
    c_top, c_next = PQ4_FUNNEL_C_BUCKETS[-1], PQ4_FUNNEL_C_BUCKETS[-2]
    r_top, r_next = (PQ4_FUNNEL_RESCORE_BUCKETS[-1],
                     PQ4_FUNNEL_RESCORE_BUCKETS[-2])
    p.tick(), p.tick()
    assert p._read(KNOB_FUNNEL_C, c_top) < c_top
    assert p._read(KNOB_FUNNEL_RESCORE, r_top) < r_top
    # near the floor: back off immediately
    sense["ewma"] = 0.982
    depth_c = p._read(KNOB_FUNNEL_C, c_top)
    p.tick()
    assert p._read(KNOB_FUNNEL_C, c_top) > depth_c
    # signal loss: revert to the static max
    p._sense_recall = lambda: None
    p.tick()
    assert p._read(KNOB_FUNNEL_C, c_top) == c_top
    assert p._read(KNOB_FUNNEL_RESCORE, r_top) == r_top
    # summary reports both ladder positions
    b = p.summary()["controllers"]["budget"]
    assert b["funnel_c_cap"] == c_top and b["funnel_rescore_cap"] == r_top
    assert c_next < c_top and r_next < r_top  # ladder really has rungs


def test_funnel_caps_hold_while_sampling_paused():
    p = _plane(hold_ticks=1, recall_min_samples=2)
    auditor = quality.configure(quality.QualityAuditor(
        sample_rate=0.5, start_workers=False))
    try:
        for _ in range(4):
            auditor.window.record("exact_scan", 1.0, 1.0, 0.0, 1, 0.0)
        p.tick(), p.tick()
        c_top = PQ4_FUNNEL_C_BUCKETS[-1]
        held = p._read(KNOB_FUNNEL_C, c_top)
        assert held < c_top
        p._pause_sampling()
        for _ in range(3):
            p.tick()
        assert p._read(KNOB_FUNNEL_C, c_top) == held  # held, not moved
    finally:
        quality.unconfigure(auditor)


def test_funnel_readers_default_and_never_raise():
    c_top = PQ4_FUNNEL_C_BUCKETS[-1]
    assert controller.funnel_c_cap(c_top) == c_top  # no plane: default
    assert controller.funnel_rescore_cap(64) == 64
    p = controller.configure(_plane())
    p._set_knob(KNOB_FUNNEL_C, PQ4_FUNNEL_C_BUCKETS[0], "t")
    p._set_knob(KNOB_FUNNEL_RESCORE, PQ4_FUNNEL_RESCORE_BUCKETS[0], "t")
    assert controller.funnel_c_cap(c_top) == PQ4_FUNNEL_C_BUCKETS[0]
    # the cap may only CUT: it never raises a smaller configured default
    assert controller.funnel_c_cap(128) == 128
    assert controller.funnel_rescore_cap(16) == 16


def test_funnel_knobs_bucket_snapped_and_journaled():
    p = _plane()
    assert p._set_knob(KNOB_FUNNEL_C, 999999, "t") == PQ4_FUNNEL_C_BUCKETS[-1]
    assert p._set_knob(KNOB_FUNNEL_C, 1, "t") == PQ4_FUNNEL_C_BUCKETS[0]
    for v in PQ4_FUNNEL_C_BUCKETS:
        assert p._set_knob(KNOB_FUNNEL_C, v, "t") == v
    for v in PQ4_FUNNEL_RESCORE_BUCKETS:
        assert p._set_knob(KNOB_FUNNEL_RESCORE, v, "t") == v
    # actuations ride the shared journal path (same _set_knob ->
    # _journal_actuation as every other knob): the /debug deque carries
    # each funnel-budget move attributed to its controller
    knobs_seen = {r["knob"] for r in p._recent}
    assert {KNOB_FUNNEL_C, KNOB_FUNNEL_RESCORE} <= knobs_seen
    assert p._recent[-1]["controller"] == "t"


def test_index_budget_floor_ignores_starving_caps(tmp_path):
    """A cap too shallow for this query's k lapses to the static max —
    the controller may only cut work, never break coverage."""
    idx, _ = _mk_index(tmp_path, name="floor")
    p = controller.configure(_plane())
    p._set_knob(KNOB_FUNNEL_C, PQ4_FUNNEL_C_BUCKETS[0], "t")      # 256
    p._set_knob(KNOB_FUNNEL_RESCORE, PQ4_FUNNEL_RESCORE_BUCKETS[0], "t")
    rg4, rc = idx._funnel_budgets(100, 100000)  # 4k > 256, 2k > 32
    assert rg4 * 16 == PQ4_FUNNEL_C_BUCKETS[-1]
    assert rc == PQ4_FUNNEL_RESCORE_BUCKETS[-1]
    rg4, rc = idx._funnel_budgets(10, 100000)   # caps respected when sane
    assert rg4 * 16 == PQ4_FUNNEL_C_BUCKETS[0]
    assert rc == PQ4_FUNNEL_RESCORE_BUCKETS[0]


# -- 5. monitoring satellites -------------------------------------------------


def test_costmodel_funnel_stage_attribution():
    shape = costmodel.DispatchShape(
        costmodel.TIER_PQ_ADC4, n=100000, dim=64, batch=8, bytes_per_row=8,
        k=10, extra={"funnel_c": 4096, "funnel_rescore": 256,
                     "funnel_stage2_bytes_per_row": 16,
                     "funnel_stage3_bytes_per_row": 128})
    want = 100000 * 8 + 8 * (4096 * 16 + 256 * 128)
    assert shape.bytes() == want
    # stage attribution is per QUERY and tier-gated: other tiers ignore it
    other = costmodel.DispatchShape(
        costmodel.TIER_PQ_CODES, n=100000, dim=64, batch=8, bytes_per_row=16,
        extra={"funnel_c": 4096, "funnel_stage2_bytes_per_row": 16})
    assert other.bytes() == 100000 * 16


def test_memory_ledger_accounts_pq4_components(tmp_path):
    ledger = memory.configure(memory.MemoryLedger(
        metrics=__import__("weaviate_tpu.monitoring.metrics",
                           fromlist=["noop_metrics"]).noop_metrics()))
    idx, _ = _mk_index(tmp_path, name="led")
    comps = idx._memory_components()
    for name in ("pq4_codes", "pq4_norms", "opq_rot"):
        assert name in memory.DEVICE_COMPONENTS  # bounded gauge labels
        assert comps[name] > 0
    # bit-exact: the 4-bit slab is M/2 bytes per capacity row
    assert comps["pq4_codes"] == idx.capacity * idx._pq4.segments // 2


def test_health_reports_funnel_ladder_state(tmp_path):
    idx, vecs = _mk_index(tmp_path)
    idx.search_by_vectors(vecs[:8] + 0.25, 5)
    pq_h = idx.health()["pq"]
    assert pq_h["bits"] == 4 and pq_h["opq"] is True
    f = pq_h["funnel"]
    assert f["c_cap"] == PQ4_FUNNEL_C_BUCKETS[-1]
    assert f["rescore_cap"] == PQ4_FUNNEL_RESCORE_BUCKETS[-1]
    assert f["dispatches"] >= 1
    assert (f["mean_stage1_rows"] >= f["mean_stage2_survivors"]
            >= f["mean_stage3_survivors"] >= 5)


# -- 6. graftlint frozensets --------------------------------------------------


def test_graftlint_covers_pq4_snapshot_fields_and_funnel_knobs():
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.graftlint import analyze_source

    src = (
        "import jax, jax.numpy as jnp\n"
        "class Idx:\n"
        "    def _enable(self, c4, n4, r):\n"
        "        self._codes4 = jax.device_put(jnp.asarray(c4))\n"
        "        self._recon_norms4 = jax.device_put(jnp.asarray(n4))\n"
        "        self._opq_rot_dev = jax.device_put(jnp.asarray(r))\n"
    )
    hits = [f.code for f in analyze_source(
        src, "weaviate_tpu/index/fake_index.py")]
    assert hits.count("JGL012") == 3
    stamped = src + "        self._stamp_memory()\n"
    assert "JGL012" not in [f.code for f in analyze_source(
        stamped, "weaviate_tpu/index/fake_index.py")]

    knob_src = (
        "def f(p):\n"
        "    p._knobs['funnel_c_cap'] = 256\n"
        "    p._knobs['funnel_rescore_cap'] = 32\n"
    )
    hits = [f.code for f in analyze_source(
        knob_src, "weaviate_tpu/usecases/fake_host.py")]
    assert hits.count("JGL014") == 2
    assert "JGL014" not in [f.code for f in analyze_source(
        knob_src, "weaviate_tpu/serving/controller.py")]
