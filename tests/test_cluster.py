"""In-process multi-node cluster harness.

The analog of the reference's clusterintegrationtest
(adapters/repos/db/clusterintegrationtest/cluster_integration_test.go:61-80):
N real DBs + real cluster-API HTTP servers on random ports + static
membership. Covers: schema 2PC propagation, distributed CRUD with remote
routing, scatter-gather search, replication with consistency levels,
read repair, node-failure behavior, scale-out, and /v1/nodes aggregation.
"""

import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.cluster.node import ClusterNode
from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.usecases.replica import ReplicationError

DIM = 8


def make_cluster(tmp_path, n=3, **kw):
    names = [f"node-{i}" for i in range(n)]
    nodes = [
        ClusterNode(str(tmp_path / name), name, node_names=names, **kw)
        for name in names
    ]
    for node in nodes:
        node.start()
    peers = {n.node_name: n.address for n in nodes}
    for node in nodes:
        node.join({k: v for k, v in peers.items() if k != node.node_name})
    return nodes


def teardown_cluster(nodes):
    for n in nodes:
        try:
            n.shutdown()
        except Exception:
            pass


def make_class(name="Dist", shards=3, replicas=1):
    return ClassDef(
        name=name,
        properties=[
            Property(name="title", data_type=["text"]),
            Property(name="wordCount", data_type=["int"]),
        ],
        vector_index_type="hnsw_tpu",
        vector_index_config={"distance": "l2-squared"},
        sharding_config={"desiredCount": shards},
        replication_config={"factor": replicas},
    )


def new_obj(i, cls="Dist"):
    rng = np.random.default_rng(i)
    return StorObj(
        class_name=cls,
        uuid=str(uuidlib.UUID(int=i + 1)),
        properties={"title": f"obj number {i}", "wordCount": i},
        vector=rng.standard_normal(DIM).astype(np.float32),
    )


@pytest.fixture
def cluster3(tmp_path):
    nodes = make_cluster(tmp_path, 3)
    yield nodes
    teardown_cluster(nodes)


def test_schema_tx_propagates(cluster3):
    n0, n1, n2 = cluster3
    n0.schema.add_class(make_class())
    for n in cluster3:
        assert n.schema.get_class("Dist") is not None
        assert n.db.get_index("Dist") is not None
    # shards are spread: each node holds only its assigned shards
    total_local = sum(len(n.db.get_index("Dist").shards) for n in cluster3)
    assert total_local == 3  # desiredCount=3, rf=1: one shard per node
    # delete propagates too
    n1.schema.delete_class("Dist")
    for n in cluster3:
        assert n.schema.get_class("Dist") is None


def test_schema_tx_add_property(cluster3):
    n0, n1, _ = cluster3
    n0.schema.add_class(make_class())
    n1.schema.add_property("Dist", Property(name="extra", data_type=["text"]))
    for n in cluster3:
        assert n.schema.get_class("Dist").get_property("extra") is not None


def test_distributed_crud_and_search(cluster3):
    n0, n1, n2 = cluster3
    n0.schema.add_class(make_class())
    idx0 = n0.db.get_index("Dist")
    objs = [new_obj(i) for i in range(60)]
    errs = idx0.put_batch(objs)
    assert all(e is None for e in errs)

    # every node sees the full logical index
    for n in cluster3:
        idx = n.db.get_index("Dist")
        assert idx.object_count() == 60

    # read an object whose shard is NOT local to n1
    idx1 = n1.db.get_index("Dist")
    remote_obj = next(
        o for o in objs if idx1._local_shard(idx1.shard_for(o.uuid)) is None
    )
    got = idx1.object_by_uuid(remote_obj.uuid)
    assert got is not None
    assert got.properties["title"] == remote_obj.properties["title"]
    assert got.vector is not None

    # scatter-gather vector search from a different node
    idx2 = n2.db.get_index("Dist")
    res = idx2.object_vector_search(objs[17].vector, k=5)
    assert res[0][0].obj.uuid == objs[17].uuid

    # filtered search across nodes
    flt = LocalFilter.from_dict(
        {"operator": "LessThan", "path": ["wordCount"], "valueInt": 10}
    )
    res = idx2.object_vector_search(objs[3].vector, k=20, flt=flt)
    assert 0 < len(res[0]) <= 10
    assert all(r.obj.properties["wordCount"] < 10 for r in res[0])

    # bm25 across nodes
    hits = idx1.object_search(limit=10, keyword_ranking={"query": "number"})
    assert len(hits) == 10

    # delete via a non-owner node
    assert idx1.delete_object(remote_obj.uuid)
    assert not idx1.exists(remote_obj.uuid)
    assert idx0.object_count() == 59


def test_replicated_write_and_consistency_levels(tmp_path):
    nodes = make_cluster(tmp_path, 3)
    try:
        n0, n1, n2 = nodes
        n0.schema.add_class(make_class(shards=2, replicas=2))
        idx0 = n0.db.get_index("Dist")
        objs = [new_obj(i) for i in range(30)]
        errs = idx0.put_batch(objs)
        assert all(e is None for e in errs)

        # each shard exists on exactly 2 nodes
        state = n0.schema.sharding_state("Dist")
        for shard in state.all_physical_shards():
            owners = state.belongs_to_nodes(shard)
            assert len(owners) == 2
            live = sum(
                1 for n in nodes
                if n.db.get_index("Dist")._local_shard(shard) is not None
            )
            assert live == 2

        # replicated single put + consistent read from every node
        extra = new_obj(1000)
        idx0.put_object(extra, cl="ALL")
        for n in nodes:
            got = n.db.get_index("Dist").object_by_uuid(extra.uuid, cl="QUORUM")
            assert got is not None

        # kill one node: QUORUM (2 of 2... n replicas=2 -> quorum=2) — use ONE
        n2.server.shutdown()
        n0.cluster.mark("node-2", False)
        n1.cluster.mark("node-2", False)
        # writes to shards replicated on node-2: ALL must fail, ONE succeeds
        state = n0.schema.sharding_state("Dist")
        victim = next(
            o for o in [new_obj(i) for i in range(2000, 2100)]
            if "node-2" in state.belongs_to_nodes(idx0.shard_for(o.uuid))
        )
        with pytest.raises(ReplicationError):
            idx0.put_object(victim, cl="ALL")
        idx0.put_object(victim, cl="ONE")
        got = idx0.object_by_uuid(victim.uuid, cl="ONE")
        assert got is not None
    finally:
        teardown_cluster(nodes)


def test_read_repair(tmp_path):
    nodes = make_cluster(tmp_path, 2)
    try:
        n0, n1 = nodes
        n0.schema.add_class(make_class(shards=1, replicas=2))
        idx0 = n0.db.get_index("Dist")
        obj = new_obj(7)
        idx0.put_object(obj, cl="ALL")
        shard_name = idx0.shard_for(obj.uuid)

        # simulate DATA LOSS on one replica (not a deletion): remove the
        # object and clear the tombstone, as if the replica lost a write
        stale_shard = n1.db.get_index("Dist")._local_shard(shard_name)
        assert stale_shard is not None
        stale_shard.delete_object(obj.uuid)
        stale_shard._deleted.clear()
        assert stale_shard.object_by_uuid(obj.uuid) is None

        # a QUORUM read via n1 sees the divergence and repairs the stale copy
        got = n1.db.get_index("Dist").object_by_uuid(obj.uuid, cl="QUORUM")
        assert got is not None
        assert stale_shard.object_by_uuid(obj.uuid) is not None  # repaired
    finally:
        teardown_cluster(nodes)


def test_delete_not_resurrected_by_read_repair(tmp_path):
    """A deletion must win over a stale live copy: the repairer propagates
    the delete instead of resurrecting the object."""
    nodes = make_cluster(tmp_path, 2)
    try:
        n0, n1 = nodes
        n0.schema.add_class(make_class(shards=1, replicas=2))
        idx0 = n0.db.get_index("Dist")
        obj = new_obj(5)
        idx0.put_object(obj, cl="ALL")
        shard_name = idx0.shard_for(obj.uuid)

        # replicated delete ONLY on n0's replica (simulate a missed delete
        # on n1 by deleting directly through n0's local shard with a
        # coordinator-style tombstone)
        s0 = n0.db.get_index("Dist")._local_shard(shard_name)
        s1 = n1.db.get_index("Dist")._local_shard(shard_name)
        s0.delete_object(obj.uuid)
        assert s1.object_by_uuid(obj.uuid) is not None  # n1 is stale

        # QUORUM read: the tombstone outranks the stale live copy
        got = n0.db.get_index("Dist").object_by_uuid(obj.uuid, cl="QUORUM")
        assert got is None
        assert s1.object_by_uuid(obj.uuid) is None  # delete propagated
        assert not n0.db.get_index("Dist").exists(obj.uuid, cl="QUORUM")
    finally:
        teardown_cluster(nodes)


def test_replica_timestamps_converge(tmp_path):
    """Coordinator-stamped times: replicas store identical updateTime, so a
    consistent read triggers no repair ping-pong, and an update preserves
    the original creation time."""
    nodes = make_cluster(tmp_path, 2)
    try:
        n0, n1 = nodes
        n0.schema.add_class(make_class(shards=1, replicas=2))
        idx0 = n0.db.get_index("Dist")
        obj = new_obj(9)
        stored = idx0.put_object(obj, cl="ALL")
        created = stored.creation_time_unix
        shard_name = idx0.shard_for(obj.uuid)
        s0 = n0.db.get_index("Dist")._local_shard(shard_name)
        s1 = n1.db.get_index("Dist")._local_shard(shard_name)
        o0 = s0.object_by_uuid(obj.uuid)
        o1 = s1.object_by_uuid(obj.uuid)
        assert o0.last_update_time_unix == o1.last_update_time_unix
        assert o0.creation_time_unix == o1.creation_time_unix

        # update through the replicated path: times still identical, and the
        # reported creation time is the ORIGINAL one
        obj2 = new_obj(9)
        obj2.properties["title"] = "updated"
        stored2 = idx0.put_object(obj2, cl="ALL")
        assert stored2.creation_time_unix == created
        o0b = s0.object_by_uuid(obj.uuid)
        o1b = s1.object_by_uuid(obj.uuid)
        assert o0b.creation_time_unix == o1b.creation_time_unix == created
        assert o0b.last_update_time_unix == o1b.last_update_time_unix
    finally:
        teardown_cluster(nodes)


def test_scale_out(tmp_path):
    nodes = make_cluster(tmp_path, 2)
    try:
        n0, n1 = nodes
        n0.schema.add_class(make_class(shards=1, replicas=1))
        idx0 = n0.db.get_index("Dist")
        objs = [new_obj(i) for i in range(25)]
        assert all(e is None for e in idx0.put_batch(objs))
        state = n0.schema.sharding_state("Dist")
        shard_name = state.all_physical_shards()[0]
        owners = state.belongs_to_nodes(shard_name)
        assert len(owners) == 1
        source = next(n for n in nodes if n.node_name == owners[0])
        target = next(n for n in nodes if n.node_name != owners[0])
        assert target.db.get_index("Dist")._local_shard(shard_name) is None

        # raise the replication factor: scaler pushes files to the new replica
        source.schema.update_class("Dist", {"replicationConfig": {"factor": 2}})

        new_state = target.schema.sharding_state("Dist")
        assert len(new_state.belongs_to_nodes(shard_name)) == 2
        tshard = target.db.get_index("Dist")._local_shard(shard_name)
        assert tshard is not None
        assert tshard.object_count() == 25
        got = tshard.object_by_uuid(objs[3].uuid)
        assert got is not None and got.properties["wordCount"] == 3
    finally:
        teardown_cluster(nodes)


def test_full_app_rest_cluster(tmp_path):
    """Two full Apps (REST + cluster graph) wired via CLUSTER_* config:
    schema created over REST on node A is queryable over REST on node B,
    with consistency_level accepted on the wire."""
    import json
    import socket
    import urllib.request

    from weaviate_tpu.config import Config
    from weaviate_tpu.server import App, RestServer

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    pa, pb = free_port(), free_port()
    cfgs = []
    for name, port, peer in (("node-a", pa, f"node-b@127.0.0.1:{pb}"),
                             ("node-b", pb, f"node-a@127.0.0.1:{pa}")):
        c = Config()
        c.cluster.hostname = name
        c.cluster.data_bind_port = port
        c.cluster.join = [peer]
        cfgs.append(c)

    apps, servers = [], []
    try:
        for i, c in enumerate(cfgs):
            app = App(config=c, data_path=str(tmp_path / f"app{i}"))
            srv = RestServer(app, port=0)
            srv.start()
            apps.append(app)
            servers.append(srv)

        def req(port, method, path, body=None):
            url = f"http://127.0.0.1:{port}{path}"
            data = json.dumps(body).encode() if body is not None else None
            r = urllib.request.Request(url, data=data, method=method)
            r.add_header("Content-Type", "application/json")
            with urllib.request.urlopen(r, timeout=30) as resp:
                raw = resp.read()
                return resp.status, json.loads(raw) if raw else None

        st, _ = req(servers[0].port, "POST", "/v1/schema", {
            "class": "AppDist",
            "properties": [{"name": "title", "dataType": ["text"]}],
            "vectorIndexType": "hnsw_tpu",
            "vectorIndexConfig": {"distance": "l2-squared"},
            "shardingConfig": {"desiredCount": 2},
        })
        assert st == 200
        # schema propagated to node B
        st, sch = req(servers[1].port, "GET", "/v1/schema")
        assert st == 200
        assert any(c["class"] == "AppDist" for c in sch["classes"])

        # import via node A (objects land on both nodes' shards)
        objs = [{"class": "AppDist", "id": str(uuidlib.UUID(int=i + 1)),
                 "properties": {"title": f"t{i}"},
                 "vector": np.random.default_rng(i).standard_normal(4).tolist()}
                for i in range(10)]
        st, out = req(servers[0].port, "POST", "/v1/batch/objects", {"objects": objs})
        assert st == 200
        assert all(o["result"]["status"] == "SUCCESS" for o in out)

        # read each object via node B with a consistency level
        st, got = req(
            servers[1].port, "GET",
            f"/v1/objects/AppDist/{objs[3]['id']}?consistency_level=ONE",
        )
        assert st == 200 and got["properties"]["title"] == "t3"

        # /v1/nodes aggregates both nodes
        st, nodes = req(servers[0].port, "GET", "/v1/nodes")
        assert st == 200
        assert {n["name"] for n in nodes["nodes"]} == {"node-a", "node-b"}
        total = sum(n["stats"]["objectCount"] for n in nodes["nodes"] if "stats" in n)
        assert total == 10
    finally:
        for s in servers:
            s.stop()
        for a in apps:
            a.shutdown()


def test_nodes_status_aggregation(cluster3):
    n0, _, _ = cluster3
    n0.schema.add_class(make_class())
    idx0 = n0.db.get_index("Dist")
    idx0.put_batch([new_obj(i) for i in range(12)])
    statuses = n0.nodes_status()
    assert len(statuses) == 3
    assert {s["name"] for s in statuses} == {"node-0", "node-1", "node-2"}
    total = sum(s["stats"]["objectCount"] for s in statuses if "stats" in s)
    assert total == 12


def test_late_joiner_syncs_schema(tmp_path):
    """startup_cluster_sync.go: a node joining AFTER classes were created
    adopts the cluster schema at startup instead of waiting for the next
    DDL transaction."""
    names = ["node-0", "node-1", "node-2"]
    early = [ClusterNode(str(tmp_path / n), n, node_names=names) for n in names[:2]]
    try:
        for n in early:
            n.start()
        early[0].join({early[1].node_name: early[1].address})
        early[1].join({early[0].node_name: early[0].address})
        early[0].schema.add_class(make_class(shards=3))
        assert early[1].schema.get_class("Dist") is not None

        # node-2 starts later with an empty disk
        late = ClusterNode(str(tmp_path / "node-2"), "node-2", node_names=names)
        late.start()
        late.join({n.node_name: n.address for n in early})
        for n in early:
            n.cluster.register("node-2", late.address)
        assert late.schema.get_class("Dist") is None
        adopted = late.sync_schema()
        assert adopted == 1
        assert late.schema.get_class("Dist") is not None
        # and it now serves its shard of the ring
        assert late.db.get_index("Dist") is not None
        idx0 = early[0].db.get_index("Dist")
        objs = [new_obj(i) for i in range(30)]
        assert all(e is None for e in idx0.put_batch(objs))
        res = late.db.get_index("Dist").object_vector_search(objs[3].vector, k=1)
        assert res[0][0].obj.uuid == objs[3].uuid
        late.shutdown()
    finally:
        teardown_cluster(early)


def test_gossip_cluster_auto_discovery(tmp_path):
    """Gossip-backed ClusterNodes: each node joins with ONE seed address and
    the full membership (names + dialable cluster-API addresses) propagates;
    the late joiner can then sync schema from discovered peers."""
    import time

    names = ["node-0", "node-1", "node-2"]
    nodes = [
        ClusterNode(str(tmp_path / n), n, node_names=names,
                    enable_gossip=True, gossip_interval=0.1)
        for n in names
    ]
    try:
        for n in nodes:
            n.start()
        seed = nodes[0].gossip.gossip_addr
        for n in nodes[1:]:
            n.join_gossip([seed])
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(sorted(n.cluster.all_names()) == names for n in nodes):
                break
            time.sleep(0.05)
        assert all(sorted(n.cluster.all_names()) == names for n in nodes)
        # discovered addresses are the real cluster-API endpoints
        assert nodes[2].cluster.node_address("node-0") == nodes[0].advertise
        nodes[0].schema.add_class(make_class(shards=3))
        # new classes shard over the DISCOVERED membership (not just the
        # static construction-time list), and every node derives the SAME
        # ring (the coordinator persists its node assignment in the 2PC
        # payload / shardingConfig)
        st0 = nodes[0].schema.sharding_state("Dist")
        owners = {st0.belongs_to_nodes(s)[0] for s in st0.all_physical_shards()}
        assert owners == set(names)
        st2 = nodes[2].schema.sharding_state("Dist")
        assert all(st2.belongs_to_nodes(s) == st0.belongs_to_nodes(s)
                   for s in st0.all_physical_shards())
        # nodes_status aggregates over gossip-discovered members
        statuses = nodes[1].nodes_status()
        assert {s["name"] for s in statuses} == set(names)
    finally:
        teardown_cluster(nodes)


def test_distributed_aggregation(cluster3):
    """Aggregate over a sharded class reaches REMOTE shards through the
    cluster API :aggregations endpoint (clusterapi indices.go analog) —
    counts/sums/median come from the full logical data set, filtered
    aggregation respects the filter cluster-wide."""
    from weaviate_tpu.usecases.aggregator import AggregateParams, Aggregator

    n0, n1, n2 = cluster3
    n0.schema.add_class(make_class("AggDist"))
    idx0 = n0.db.get_index("AggDist")
    objs = [new_obj(i, "AggDist") for i in range(40)]
    assert all(e is None for e in idx0.put_batch(objs))

    # aggregate from a node that does NOT hold every shard
    idx1 = n1.db.get_index("AggDist")
    local = sum(1 for s, sh in idx1._all_shard_targets() if sh is not None)
    total = len(idx1._all_shard_targets())
    assert local < total  # the test is vacuous unless some shards are remote

    agg = Aggregator(n1.db, n1.schema)
    out = agg.aggregate(AggregateParams(
        class_name="AggDist", include_meta_count=True,
        properties={"wordCount": ["count", "sum", "mean", "median", "minimum", "maximum"]},
    ))
    a = out[0]
    assert a["meta"]["count"] == 40
    wc = a["wordCount"]
    assert wc["count"] == 40
    assert wc["sum"] == sum(range(40))
    assert wc["minimum"] == 0 and wc["maximum"] == 39
    assert wc["median"] == 19.5

    # filtered aggregation, cluster-wide
    flt = LocalFilter.from_dict(
        {"operator": "LessThan", "path": ["wordCount"], "valueInt": 10})
    out = agg.aggregate(AggregateParams(
        class_name="AggDist", filters=flt, include_meta_count=True,
        properties={"wordCount": ["count", "sum"]},
    ))
    assert out[0]["meta"]["count"] == 10
    assert out[0]["wordCount"]["sum"] == sum(range(10))

    # grouped aggregation sees all shards
    out = agg.aggregate(AggregateParams(
        class_name="AggDist", group_by=["title"], include_meta_count=True))
    assert len(out) == 40  # every title unique -> one group per object


def test_ten_node_cluster_scatter_gather(tmp_path):
    """The reference's clusterintegrationtest scale: 10 in-process nodes,
    real cluster-API servers, distributed import + search + aggregate
    (cluster_integration_test.go:61-80)."""
    from weaviate_tpu.usecases.aggregator import AggregateParams, Aggregator

    nodes = make_cluster(tmp_path, 10)
    try:
        n0 = nodes[0]
        n0.schema.add_class(make_class("Ten", shards=10))
        idx0 = n0.db.get_index("Ten")
        objs = [new_obj(i, "Ten") for i in range(120)]
        assert all(e is None for e in idx0.put_batch(objs))

        # schema propagated everywhere; every node serves the whole index
        for n in nodes:
            assert n.schema.get_class("Ten") is not None
        idx7 = nodes[7].db.get_index("Ten")
        assert idx7.object_count() == 120

        # search from three different coordinators hits the same winner
        for ni in (1, 4, 9):
            idx = nodes[ni].db.get_index("Ten")
            res = idx.object_vector_search(objs[42].vector, k=3)
            assert res[0][0].obj.uuid == objs[42].uuid

        # cluster-wide aggregate from the last node
        agg = Aggregator(nodes[9].db, nodes[9].schema)
        out = agg.aggregate(AggregateParams(
            class_name="Ten", include_meta_count=True,
            properties={"wordCount": ["count", "sum"]},
        ))
        assert out[0]["meta"]["count"] == 120
        assert out[0]["wordCount"]["sum"] == sum(range(120))
    finally:
        teardown_cluster(nodes)


def test_distributed_meta_count_fast_path(cluster3):
    """include_meta_count with no properties ships per-shard integers over
    the :aggregations countOnly wire, never objects."""
    from weaviate_tpu.usecases.aggregator import AggregateParams, Aggregator

    n0, n1, _ = cluster3
    n0.schema.add_class(make_class("CntDist"))
    idx0 = n0.db.get_index("CntDist")
    assert all(e is None for e in idx0.put_batch(
        [new_obj(i, "CntDist") for i in range(50)]))
    agg = Aggregator(n1.db, n1.schema)
    out = agg.aggregate(AggregateParams(class_name="CntDist", include_meta_count=True))
    assert out == [{"meta": {"count": 50}}]
    flt = LocalFilter.from_dict(
        {"operator": "GreaterThanEqual", "path": ["wordCount"], "valueInt": 40})
    out = agg.aggregate(AggregateParams(
        class_name="CntDist", include_meta_count=True, filters=flt))
    assert out == [{"meta": {"count": 10}}]


def test_is_consistent_probe(tmp_path):
    """_additional.isConsistent digest-compares replicas (finder.go
    CheckConsistency): consistent after an ALL write, inconsistent when a
    replica holds a stale copy, consistent again after read repair."""
    nodes = make_cluster(tmp_path, 2)
    try:
        n0, n1 = nodes
        n0.schema.add_class(make_class("Cons", shards=1, replicas=2))
        idx0 = n0.db.get_index("Cons")
        obj = new_obj(5, "Cons")
        idx0.put_object(obj, cl="ALL")
        shard = idx0.shard_for(obj.uuid)
        assert idx0.is_consistent(obj.uuid, idx0.object_by_uuid(
            obj.uuid).last_update_time_unix)

        # make node-1's replica stale: bump the copy on node-0 only
        sh0 = n0.db.get_index("Cons")._local_shard(shard)
        sh1 = n1.db.get_index("Cons")._local_shard(shard)
        assert sh0 is not None and sh1 is not None
        newer = sh0.merge_object(obj.uuid, {"title": "edited"},
                                 update_time=obj.last_update_time_unix + 5000)
        assert not idx0.is_consistent(obj.uuid, newer.last_update_time_unix)

        # a QUORUM read repairs the stale replica; probe flips back
        got = idx0.object_by_uuid(obj.uuid, cl="QUORUM")
        assert got.properties["title"] == "edited"
        assert idx0.is_consistent(obj.uuid, got.last_update_time_unix)
    finally:
        teardown_cluster(nodes)
