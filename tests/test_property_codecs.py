"""Property-based tests (hypothesis) for the hand-rolled binary codecs —
the storobj image, the vector log, pack/unpack top-k, and uuid key
derivation. These formats cross restarts and the wire; a fuzzer finds the
encoding edge cases example tests never enumerate.

Reference test model: the Go side gets this safety from its typed
marshallers; here the codecs are bespoke, so the properties ARE the spec.
"""

import math
import uuid as uuidlib

import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="optional dep not in this image")
from hypothesis import given, settings
from hypothesis import strategies as st

from weaviate_tpu.entities.storobj import StorObj

_SETTINGS = dict(max_examples=200, deadline=None)

# JSON-representable property values (what import validation admits)
_scalars = st.one_of(
    st.none(),
    st.booleans(),
    st.integers(min_value=-(2**53), max_value=2**53),
    st.floats(allow_nan=False, allow_infinity=False, width=32, allow_subnormal=False),
    st.text(max_size=40),
)
_props = st.dictionaries(
    st.text(min_size=1, max_size=16),
    st.one_of(_scalars, st.lists(_scalars, max_size=5)),
    max_size=6,
)


@settings(**_SETTINGS)
@given(
    props=_props,
    dim=st.integers(min_value=0, max_value=48),
    doc_id=st.integers(min_value=0, max_value=2**62),
    uuid_int=st.integers(min_value=0, max_value=2**128 - 1),
    created=st.integers(min_value=1, max_value=2**52),
)
def test_storobj_roundtrip(props, dim, doc_id, uuid_int, created):
    rng = np.random.default_rng(0)
    vec = rng.standard_normal(dim).astype(np.float32) if dim else None
    obj = StorObj(
        class_name="C", uuid=str(uuidlib.UUID(int=uuid_int)),
        properties=props, vector=vec, doc_id=doc_id,
        creation_time_unix=created, last_update_time_unix=created + 5,
    )
    raw = obj.to_binary()
    back = StorObj.from_binary(raw)
    assert back.uuid == obj.uuid
    assert back.doc_id == doc_id
    assert back.creation_time_unix == created
    assert back.last_update_time_unix == created + 5
    if dim:
        np.testing.assert_array_equal(back.vector, vec)
    else:
        assert back.vector is None
    # float32 round-trips through JSON may change repr but not value class;
    # compare with tolerance for floats, exactly otherwise
    assert set(back.properties) == set(props)
    for k, v in props.items():
        got = back.properties[k]
        if isinstance(v, float):
            assert math.isclose(got, v, rel_tol=1e-6, abs_tol=1e-9)
        elif isinstance(v, list):
            assert len(got) == len(v)
            for a, b in zip(got, v):
                if isinstance(b, float):
                    assert math.isclose(a, b, rel_tol=1e-6, abs_tol=1e-9)
                else:
                    assert a == b
        else:
            assert got == v
    # pristine image reuse: an untouched decode re-encodes byte-identically
    assert StorObj.from_binary(raw).to_binary() == raw


@settings(**_SETTINGS)
@given(uuid_int=st.integers(min_value=0, max_value=2**128 - 1))
def test_uuid_key_derivation_matches_stdlib(uuid_int):
    from weaviate_tpu.db.shard import _uuid_bytes

    u = str(uuidlib.UUID(int=uuid_int))
    assert _uuid_bytes(u) == uuidlib.UUID(u).bytes
    assert _uuid_bytes(u.upper()) == uuidlib.UUID(u).bytes


@settings(**_SETTINGS)
@given(
    n=st.integers(min_value=1, max_value=40),
    dim=st.integers(min_value=1, max_value=24),
    n_deletes=st.integers(min_value=0, max_value=10),
    torn=st.integers(min_value=0, max_value=20),
    data=st.data(),
)
def test_vector_log_batch_parser_equals_scalar(n, dim, n_deletes,
                                               torn, data):
    """replay_batches flattens to exactly replay() for arbitrary interleaved
    add/delete logs with arbitrary torn tails."""
    from weaviate_tpu.index.tpu import VectorLog

    import shutil
    import tempfile

    # the permutation draw can raise hypothesis control-flow exceptions, so
    # EVERYTHING after mkdtemp sits under the cleanup finally
    rng = np.random.default_rng(n * 1000 + dim)
    tmpdir = tempfile.mkdtemp()
    try:
        path = str(__import__("pathlib").Path(tmpdir) / "vector.log")
        log = VectorLog(path)
        ops = ["add"] * n + ["delete"] * n_deletes
        order = data.draw(st.permutations(ops))
        for i, op in enumerate(order):
            if op == "add":
                log.append_add(i, rng.standard_normal(dim).astype(np.float32))
            else:
                log.append_delete(i)
        log.flush()
        log.close()
        if torn:
            with open(path, "ab") as f:
                f.write(bytes(range(torn))[:torn])

        scalar = list(VectorLog.replay(path))
        flat = [
            (op, int(i), None if vv is None else v.copy())
            for op, ids_, vv in VectorLog.replay_batches(path)
            for i, v in (zip(ids_, vv) if op == "add" else [(ids_, None)])
        ]
        assert len(flat) == len(scalar)
        for (o1, i1, v1), (o2, i2, v2) in zip(flat, scalar):
            assert o1 == o2 and i1 == i2
            if v1 is not None:
                np.testing.assert_array_equal(v1, v2)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


@settings(**_SETTINGS)
@given(
    b=st.integers(min_value=1, max_value=8),
    k=st.integers(min_value=1, max_value=16),
    data=st.data(),
)
def test_pack_unpack_topk_roundtrip(b, k, data):
    """pack_topk/unpack_topk preserve (distance, index) pairs bit-exactly
    for finite non-negative distances and -1 sentinels."""
    import jax.numpy as jnp

    from weaviate_tpu.ops.topk import pack_topk, unpack_topk

    dists = np.array(
        data.draw(st.lists(
            st.lists(st.floats(min_value=0, max_value=65504.0, width=32, allow_subnormal=False),
                     min_size=k, max_size=k),
            min_size=b, max_size=b)),
        dtype=np.float32)
    idx = np.array(
        data.draw(st.lists(
            st.lists(st.integers(min_value=-1, max_value=2**31 - 2),
                     min_size=k, max_size=k),
            min_size=b, max_size=b)),
        dtype=np.int32)
    packed = np.asarray(pack_topk(jnp.asarray(dists), jnp.asarray(idx)))
    d2, i2 = unpack_topk(packed)
    np.testing.assert_array_equal(i2, idx)
    np.testing.assert_array_equal(d2, dists)
