"""Partition-pruned search: the clustered IVF scan plane (ROADMAP item 3).

Pins the IVF PR's contracts:

1. ``top_p = all partitions`` is equivalent to the flat fused path on
   every read tier — exact, filtered scan, PQ rescore, PQ codes-only —
   sync == async, fused == legacy: distances BIT-equal, ids equal up to
   reordering inside exact-distance tie groups (on tie-free data that is
   bit-identity; the helper degenerates to array_equal there);
2. disabled IVF is a true zero-hop no-op: nothing trains, no device
   slabs exist, the dispatch gate is one comparison;
3. snapshot isolation survives the recluster lifecycle: a dispatch
   enqueued on an old snapshot answers from the OLD layout even when a
   recluster + compact replaces every IVF array underneath it (the PR-4
   torn-read pin, extended to partition tables);
4. the padded-bucket layout keeps jit shapes stable across inserts, the
   probe respects deletes/re-adds/filters through the flat kernels' own
   masking semantics, and the new device slabs are ledger-accounted
   bit-equal to their buffers' nbytes;
5. the ``ivf_top_p`` controller knob is the second recall-guarded
   budget: bucket-snapped, cut only under measured recall slack,
   reverted on ANY signal loss (a paused auditor reads as no-signal).
"""

import numpy as np
import pytest

from weaviate_tpu.config.config import (ConfigError, IVF_TOP_P_BUCKETS,
                                        IvfConfig, load_config)
from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.index import tpu
from weaviate_tpu.index.tpu import TpuVectorIndex
from weaviate_tpu.monitoring import memory, perf, tracing
from weaviate_tpu.ops import ivf as ivf_ops
from weaviate_tpu.serving import controller
from weaviate_tpu.serving.controller import KNOB_IVF_TOP_P, ControlPlane
from weaviate_tpu.storage.bitmap import Bitmap

DIM = 16


@pytest.fixture(autouse=True)
def _reset_globals():
    yield
    tpu.set_ivf_config(None)
    tpu.set_fused_enabled(None)
    tracing.configure(None)
    perf.configure(None)
    controller.configure(None)


def _ivf(**kw) -> IvfConfig:
    base = dict(enabled=True, nlist=8, min_n=256, top_p=8,
                train_sample=4096, train_iters=4)
    base.update(kw)
    return IvfConfig(**base)


def _mk_index(tmp_path, n=600, pq=None, seed=3, name="ivfx", spread=100,
              **cfg_extra):
    """Integer vectors: every distance is exact integer arithmetic in
    f32 (and in bf16 products), so cross-kernel equality checks are
    exact; a wide value range keeps distance ties rare."""
    rng = np.random.default_rng(seed)
    vecs = rng.integers(-spread, spread, (n, DIM)).astype(np.float32)
    d = {"distance": "l2-squared", **cfg_extra}
    if pq is not None:
        d["pq"] = pq
    cfg = parse_and_validate_config("hnsw_tpu", d)
    idx = TpuVectorIndex(cfg, str(tmp_path / name), persist=False)
    idx.add_batch(np.arange(n), vecs)
    idx.flush()
    return idx, vecs


def assert_tie_equiv(got, want, msg=""):
    """Distances must be BIT-equal; ids must match exactly wherever the
    distance is unique, and as a set inside an exact-tie group (selection
    order within a tie is unspecified — on tie-free data this is
    array_equal)."""
    np.testing.assert_array_equal(got[1], want[1], err_msg=msg)
    for r in range(want[1].shape[0]):
        gd, gi, wi = want[1][r], got[0][r], want[0][r]
        for v in np.unique(gd):
            sel = gd == v
            assert set(gi[sel].tolist()) == set(wi[sel].tolist()), \
                f"{msg}: tie-group mismatch row {r} dist {v}"


# -- 1. top_p = all ≡ flat, every tier, sync+async, fused+legacy --------------


def _tiers(tmp_path, n=600):
    out = []
    idx, vecs = _mk_index(tmp_path, n=n, name="exact", exactTopK=True)
    out.append(("exact", idx, vecs, None))
    cutoff = idx.config.flat_search_cutoff
    out.append(("filtered_scan", idx, vecs,
                Bitmap(np.arange(0, cutoff + 64, dtype=np.uint64))))
    pq_r, vecs_r = _mk_index(
        tmp_path, n=n, name="pqr", exactTopK=True,
        pq={"enabled": True, "segments": 4, "centroids": 16})
    assert pq_r.compressed and pq_r._rescore_dev is not None
    out.append(("pq_rescore", pq_r, vecs_r, None))
    pq_c, vecs_c = _mk_index(
        tmp_path, n=n, name="pqc", exactTopK=True,
        pq={"enabled": True, "segments": 4, "centroids": 16,
            "rescore": False})
    assert pq_c.compressed and pq_c._rescore_dev is None
    out.append(("pq_codes", pq_c, vecs_c, None))
    return out


def test_top_p_all_matches_flat_all_tiers_sync_async(tmp_path):
    tpu.set_ivf_config(_ivf())  # trains at import time (min_n < n)
    tiers = _tiers(tmp_path)
    for name, idx, vecs, allow in tiers:
        assert idx._ivf_buckets is not None, name
        q = vecs[:9] + np.float32(1.0)
        for fused in (True, False):
            tpu.set_fused_enabled(fused)
            # top_p=8 == nlist: every partition probed
            tpu.set_ivf_config(_ivf())
            i_sync = idx.search_by_vectors(q, 10, allow)
            i_async = idx.search_by_vectors_async(q, 10, allow)()
            tpu.set_ivf_config(None)  # flat control on the same index
            flat = idx.search_by_vectors(q, 10, allow)
            tag = f"{name} fused={fused}"
            assert_tie_equiv(i_sync, flat, tag + " sync")
            assert_tie_equiv(i_async, flat, tag + " async")
            assert i_sync[0].dtype == np.uint64, tag
            assert i_sync[1].dtype == np.float32, tag


def test_ivf_target_distance_matches_flat(tmp_path):
    tpu.set_ivf_config(_ivf())
    idx, vecs = _mk_index(tmp_path, exactTopK=True)
    q = vecs[5] + np.float32(1.0)
    ids_i, d_i = idx.search_by_vector_distance(q, 3000.0, 64)
    tpu.set_ivf_config(None)
    ids_f, d_f = idx.search_by_vector_distance(q, 3000.0, 64)
    np.testing.assert_array_equal(d_i, d_f)
    assert set(ids_i.tolist()) == set(ids_f.tolist())
    assert len(ids_i) > 0


# -- 2. disabled = zero-hop no-op ---------------------------------------------


def test_ivf_disabled_is_true_noop(tmp_path):
    idx, vecs = _mk_index(tmp_path)  # no settings anywhere
    assert idx._ivf_centroids is None
    assert idx._ivf_buckets is None
    snap = idx._read_snapshot()
    assert snap.ivf_buckets is None
    assert idx._ivf_plan(snap, 10) is None
    comps = idx._memory_components()
    assert not any(k.startswith("ivf") for k in comps)
    st = idx.ivf_stats()
    assert st["dispatches"] == 0
    h = idx.health()["ivf"]
    assert h == {"enabled": False, "trained": False}


def test_ivf_enabled_below_min_n_does_not_train(tmp_path):
    tpu.set_ivf_config(_ivf(min_n=100000))
    idx, _ = _mk_index(tmp_path)
    assert idx._ivf_centroids is None
    ids, _d = idx.search_by_vectors(np.zeros(DIM, np.float32)[None], 5)
    assert ids.shape == (1, 5)


def test_ivf_skips_non_matmul_metrics(tmp_path):
    tpu.set_ivf_config(_ivf())
    rng = np.random.default_rng(0)
    vecs = rng.integers(0, 2, (600, DIM)).astype(np.float32)
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "manhattan"})
    idx = TpuVectorIndex(cfg, str(tmp_path / "man"), persist=False)
    idx.add_batch(np.arange(600), vecs)
    idx.flush()
    assert idx._ivf_centroids is None  # never trains
    ids, _ = idx.search_by_vectors(vecs[:3], 5)
    assert ids.shape[0] == 3


# -- 3. training / layout invariants ------------------------------------------


def test_training_publishes_a_complete_layout(tmp_path):
    tpu.set_ivf_config(_ivf())
    idx, vecs = _mk_index(tmp_path)
    snap = idx._read_snapshot()
    assert snap.ivf_centroids is not None and snap.ivf_buckets is not None
    nlist, cap_p, gen = snap.ivf_meta
    assert nlist == 8 and gen == 1
    buckets = np.asarray(snap.ivf_buckets)
    assert buckets.shape == (nlist, cap_p)
    slots = buckets[buckets >= 0]
    # every live slot appears in exactly one bucket
    assert sorted(slots.tolist()) == list(range(600))
    assert int(idx._ivf_fills.sum()) == 600


def test_bucket_shapes_stay_stable_across_small_inserts(tmp_path):
    tpu.set_ivf_config(_ivf())
    idx, vecs = _mk_index(tmp_path)
    cap_p0 = idx._ivf_meta[1]
    gen0 = idx._ivf_gen
    rng = np.random.default_rng(9)
    extra = rng.integers(-100, 100, (16, DIM)).astype(np.float32)
    idx.add_batch(np.arange(600, 616), extra)
    idx.flush()
    # incremental assignment, no retrain, same padded width: the search
    # program's jit key ([nlist, cap_p]) is unchanged
    assert idx._ivf_gen == gen0
    assert idx._ivf_meta[1] == cap_p0
    # and the O(batch) incremental fold kept the bucket table COMPLETE:
    # every slot (old and new) bucketed exactly once
    buckets = np.asarray(idx._read_snapshot().ivf_buckets)
    assert sorted(buckets[buckets >= 0].tolist()) == list(range(616))
    # ...so the new rows are immediately findable through the probe
    ids, _ = idx.search_by_vectors(extra[:3], 1)
    assert ids[:, 0].tolist() == [600, 601, 602]


def test_growth_triggers_recluster(tmp_path):
    tpu.set_ivf_config(_ivf(retrain_growth=0.5))
    idx, vecs = _mk_index(tmp_path)
    gen0 = idx._ivf_gen
    rng = np.random.default_rng(11)
    more = rng.integers(-100, 100, (400, DIM)).astype(np.float32)
    idx.add_batch(np.arange(1000, 1400), more)  # 600 -> 1000 rows >= 1.5x
    idx.flush()
    assert idx._ivf_gen == gen0 + 1
    assert idx._ivf_trained_n == 1000


def test_ivf_respects_deletes_and_readds(tmp_path):
    tpu.set_ivf_config(_ivf())
    idx, vecs = _mk_index(tmp_path, exactTopK=True)
    q = vecs[7][None, :].astype(np.float32)
    ids, _ = idx.search_by_vectors(q, 3)
    winner = int(ids[0, 0])
    assert winner == 7
    idx.delete(7)
    ids2, _ = idx.search_by_vectors(q, 3)
    assert 7 not in ids2[0].tolist()
    # re-add with a fresh vector: the NEWEST slot must serve it
    idx.add(7, vecs[7])
    ids3, _ = idx.search_by_vectors(q, 3)
    assert int(ids3[0, 0]) == 7


def test_small_allowlist_keeps_the_gather_tier(tmp_path):
    tpu.set_ivf_config(_ivf())
    idx, vecs = _mk_index(tmp_path, exactTopK=True)
    before = idx.ivf_stats()["dispatches"]
    allow = Bitmap(np.array([3, 7, 11, 401], dtype=np.uint64))
    q = vecs[:4] + np.float32(1.0)
    got = idx.search_by_vectors(q, 4, allow)
    tpu.set_ivf_config(None)
    flat = idx.search_by_vectors(q, 4, allow)
    assert_tie_equiv(got, flat, "gather")
    # the gather tier never went through the probe
    assert idx.ivf_stats()["dispatches"] == before


def test_probe_prunes_and_keeps_recall_on_clustered_data(tmp_path):
    rng = np.random.default_rng(1)
    n = 4000
    centers = rng.standard_normal((64, DIM)).astype(np.float32) * 8
    vecs = (centers[rng.integers(0, 64, n)]
            + 0.3 * rng.standard_normal((n, DIM)).astype(np.float32))
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    tpu.set_ivf_config(_ivf(nlist=64, top_p=8, min_n=512))
    idx = TpuVectorIndex(cfg, str(tmp_path / "clu"), persist=False)
    idx.add_batch(np.arange(n), vecs)
    idx.flush()
    q = vecs[:32] + np.float32(0.01)
    # exact numpy ground truth (NOT the flat scan: on near-duplicate
    # clustered data the flat bf16 fast pass loses ~15% recall to L2
    # cancellation, while the IVF candidate pass scores survivors at
    # exact f32 — the probe must be measured against the truth)
    d = ((q ** 2).sum(1)[:, None] - 2.0 * q @ vecs.T
         + (vecs ** 2).sum(1)[None, :])
    gt = np.argsort(d, axis=1)[:, :10]
    ids, _ = idx.search_by_vectors(q, 10)
    rec = np.mean([len(set(a) & set(b)) / 10
                   for a, b in zip(ids.tolist(), gt.tolist())])
    assert rec >= 0.95
    st = idx.ivf_stats()
    assert st["probed_fraction"] is not None and st["probed_fraction"] < 1.0


def test_pca_prefilter_cuts_candidates_and_keeps_recall(tmp_path):
    tpu.set_ivf_config(_ivf(pca_dim=8))
    idx, vecs = _mk_index(tmp_path, n=1200, name="pca")
    snap = idx._read_snapshot()
    assert snap.ivf_pca_proj is not None and snap.ivf_pca_rows is not None
    plan = idx._ivf_plan(snap, 10)
    assert plan is not None and plan[1] > 0  # prefilter active
    assert plan[1] < plan[0] * snap.ivf_meta[1]  # ...and actually cuts
    q = vecs[:16] + np.float32(1.0)
    ids, _ = idx.search_by_vectors(q, 10)
    tpu.set_ivf_config(None)
    flat_ids, _ = idx.search_by_vectors(q, 10)
    rec = np.mean([len(set(a) & set(b)) / 10
                   for a, b in zip(ids.tolist(), flat_ids.tolist())])
    assert rec >= 0.9


# -- 4. snapshot isolation across the recluster lifecycle ---------------------


def test_enqueued_dispatch_survives_recluster_and_compact(tmp_path):
    """The PR-4 torn-read pin, extended to partition tables: enqueue on
    an old snapshot, then delete the winners, force a recluster AND a
    compact underneath — finalize must return the OLD layout's exact
    answer."""
    tpu.set_ivf_config(_ivf())
    idx, vecs = _mk_index(tmp_path, exactTopK=True)
    q = vecs[:5] + np.float32(1.0)
    expected = idx.search_by_vectors(q, 10)
    fin = idx.search_by_vectors_async(q, 10)  # enqueued on the OLD snap
    winners = set(int(i) for i in expected[0][:, 0])
    idx.delete(*winners)
    rng = np.random.default_rng(21)
    more = rng.integers(-100, 100, (600, DIM)).astype(np.float32)
    idx.add_batch(np.arange(2000, 2600), more)  # growth => recluster
    idx.compact()                               # and a full rebuild
    assert idx._ivf_gen >= 2
    got = fin()
    assert_tie_equiv(got, expected, "pinned snapshot")


def test_compact_reclusters_on_the_dense_slot_space(tmp_path):
    tpu.set_ivf_config(_ivf())
    idx, vecs = _mk_index(tmp_path)
    gen0 = idx._ivf_gen
    idx.delete(*range(0, 200))
    idx.compact()
    assert idx._ivf_gen == gen0 + 1
    snap = idx._read_snapshot()
    buckets = np.asarray(snap.ivf_buckets)
    slots = buckets[buckets >= 0]
    assert sorted(slots.tolist()) == list(range(400))  # dense, complete
    ids, _ = idx.search_by_vectors(vecs[300][None], 3)
    assert int(ids[0, 0]) == 300


# -- 5. observability: health, ledger, costmodel, stats -----------------------


def test_health_reports_partition_layout(tmp_path):
    tpu.set_ivf_config(_ivf())
    idx, vecs = _mk_index(tmp_path)
    idx.search_by_vectors(vecs[:3], 5)
    h = idx.health()["ivf"]
    assert h["enabled"] and h["trained"]
    assert h["nlist"] == 8 and h["last_recluster_gen"] == 1
    b = h["buckets"]
    assert b["fill_min"] >= 0 and b["fill_max"] <= h["bucket_capacity"]
    assert 0.0 <= b["padding_waste"] < 1.0
    assert len(b["fill_histogram"]) == 8
    assert sum(b["fill_histogram"]) == h["nlist"]
    assert b["imbalance"] >= 1.0
    p = h["probes"]
    # probed_fraction is device work vs the flat scan and may exceed 1.0
    # on padding-heavy tiny layouts — that is the honest number telling
    # the operator IVF is not yet worth it at this corpus size
    assert p["dispatches"] >= 1 and p["probed_fraction"] > 0


def test_new_slabs_are_ledger_accounted_bit_equal(tmp_path):
    tpu.set_ivf_config(_ivf(pca_dim=8))
    idx, _ = _mk_index(tmp_path, name="led")
    comps = idx._memory_components()
    for name, arr in (("ivf_centroids", idx._ivf_centroids),
                      ("ivf_buckets", idx._ivf_buckets),
                      ("ivf_pca_proj", idx._ivf_pca_proj),
                      ("ivf_pca_rows", idx._ivf_pca_rows)):
        assert name in memory.DEVICE_COMPONENTS
        assert comps[name] == arr.nbytes  # bit-equal, analytic
    # the HOST twins (centroid matrix, PCA basis, assignment mirror)
    # are a ledger component too — /debug/memory must not underreport
    # the write path's resident state
    assert "ivf_host" in memory.HOST_COMPONENTS
    host = memory.index_host_components(idx)
    assert host["ivf_host"] == (idx._ivf_centroids_host.nbytes
                                + idx._ivf_pca_host.nbytes
                                + idx._ivf_assign.nbytes)
    # drop() releases every slab from the accounting
    idx.drop()
    comps = idx._memory_components()
    assert not any(k.startswith("ivf") for k in comps)
    assert "ivf_host" not in memory.index_host_components(idx)


def test_top_p_snap_extends_beyond_the_ladder():
    """A large-nlist layout legitimately probes hundreds of partitions:
    past the ladder's 128 top the snap continues on pow2 octaves (still
    bounded jit shapes) instead of silently collapsing the probe."""
    snap = tpu._snap_top_p
    assert snap(5) == 4
    assert snap(128) == 128
    assert snap(300) == 256
    assert snap(4096) == 4096
    assert snap(5000) == 4096
    # beyond the ladder entirely (explicitly-configured giant nlist):
    # pow2 octaves keep the static set bounded
    assert snap(10000) == 8192


def test_dispatch_shape_carries_probed_aware_flops(tmp_path):
    tpu.set_ivf_config(_ivf(nlist=8, top_p=2))
    idx, vecs = _mk_index(tmp_path, n=2000, name="shape")
    tracing.configure(tracing.Tracer(sample_rate=1.0))
    try:
        idx.search_by_vectors(vecs[:4], 10)
        shape = idx.pop_dispatch_shape()
        assert shape is not None
        nlist, cap_p, _ = idx._ivf_meta
        probed = 2 * cap_p + nlist
        assert shape.n == probed          # not snap.n: no phantom work
        assert shape.n < 2000
        d = shape.describe()
        assert d["ivf"] is True
        assert d["ivf_top_p"] == 2
        assert 0 < d["probed_fraction"] < 1.0
        assert shape.flops() == int(round(2.0 * 4 * probed * DIM))
    finally:
        tracing.configure(None)


# -- 6. the ivf_top_p controller knob (second recall-guarded budget) ----------


def _plane(**overrides) -> ControlPlane:
    return ControlPlane(start=False, **overrides)


def test_ivf_budget_cuts_on_slack_and_backs_off():
    p = _plane(hold_ticks=2, recall_floor=0.98, recall_slack=0.015,
               recall_backoff_margin=0.005)
    sense = {"ewma": 1.0}
    p._sense_recall = lambda: sense["ewma"]
    top = IVF_TOP_P_BUCKETS[-1]
    p.tick()
    assert p._read(KNOB_IVF_TOP_P, top) == top  # held one tick
    p.tick()
    assert p._read(KNOB_IVF_TOP_P, top) == IVF_TOP_P_BUCKETS[-2]
    # near the floor: back off immediately, no hysteresis on restores
    sense["ewma"] = 0.982
    p.tick()
    assert p._read(KNOB_IVF_TOP_P, top) == top


def test_ivf_budget_reverts_on_signal_loss_and_paused_auditor():
    top = IVF_TOP_P_BUCKETS[-1]
    p = _plane(hold_ticks=1)
    p._sense_recall = lambda: 1.0
    p.tick(), p.tick()
    assert p._read(KNOB_IVF_TOP_P, top) < top
    # a PAUSED sample gate is no-signal for the probe budget (unlike the
    # rescore cap's hold): the knob reverts to the configured default
    p._sampling_paused = True
    p.tick()
    assert p._read(KNOB_IVF_TOP_P, top) == top
    p._sampling_paused = False
    p.tick(), p.tick()
    assert p._read(KNOB_IVF_TOP_P, top) < top
    p._sense_recall = lambda: None
    p.tick()
    assert p._read(KNOB_IVF_TOP_P, top) == top


def test_deep_k_widens_the_probe_for_coverage(tmp_path):
    """A k deeper than the probed candidate set would starve selection:
    the plan widens up the bucket ladder until ~4k candidates are
    covered, no matter what the config or controller cap says."""
    tpu.set_ivf_config(_ivf(nlist=8, top_p=1))
    idx, vecs = _mk_index(tmp_path, name="deepk")
    snap = idx._read_snapshot()
    cap_p = snap.ivf_meta[1]
    assert idx._ivf_plan(snap, 10)[0] == 1          # shallow k: as asked
    deep_k = cap_p  # 4k = 4*cap_p > 1*cap_p: must widen
    top_p = idx._ivf_plan(snap, deep_k)[0]
    assert top_p * cap_p >= min(4 * deep_k, 8 * cap_p)
    ids, dists = idx.search_by_vectors(vecs[:2], deep_k)
    assert ids.shape[1] >= min(deep_k, 600)


def test_ivf_top_p_cap_reader_is_clamped_and_bucket_snapped():
    assert controller.ivf_top_p_cap(8) == 8  # no plane: default
    p = _plane()
    controller.configure(p)
    try:
        p._set_knob(KNOB_IVF_TOP_P, 5, "budget")  # snaps to 4
        assert controller.ivf_top_p_cap(8) == 4
        assert controller.ivf_top_p_cap(2) == 2   # never exceeds default
    finally:
        controller.configure(None)


def test_controller_cap_steers_the_live_probe_count(tmp_path):
    tpu.set_ivf_config(_ivf(nlist=8, top_p=8))
    idx, vecs = _mk_index(tmp_path, name="steer")
    snap = idx._read_snapshot()
    assert idx._ivf_plan(snap, 10)[0] == 8
    p = _plane()
    controller.configure(p)
    try:
        p._set_knob(KNOB_IVF_TOP_P, 2, "budget")
        assert idx._ivf_plan(snap, 10)[0] == 2
        # the cut path still serves correct results
        ids, _ = idx.search_by_vectors(vecs[:3], 5)
        assert ids.shape == (3, 5)
    finally:
        controller.configure(None)
    assert idx._ivf_plan(snap, 10)[0] == 8  # plane gone: static again


def test_budget_summary_reports_both_caps():
    p = _plane(hold_ticks=1)
    p._sense_recall = lambda: 1.0
    p.tick(), p.tick()
    s = p.summary()["controllers"]["budget"]
    assert s["rescore_r_cap"] < 128
    assert s["ivf_top_p_cap"] < IVF_TOP_P_BUCKETS[-1]
    p.revert_all("test")
    s = p.summary()["controllers"]["budget"]
    assert s["ivf_top_p_cap"] == IVF_TOP_P_BUCKETS[-1]


# -- 7. config / settings plumbing --------------------------------------------


def test_ivf_env_parse_and_validation():
    env = {"IVF_ENABLED": "true", "IVF_NLIST": "64", "IVF_TOP_P": "4",
           "IVF_MIN_N": "1000", "IVF_PCA_DIM": "8",
           "IVF_TRAIN_SAMPLE": "8192", "IVF_TRAIN_ITERS": "3",
           "IVF_RETRAIN_GROWTH": "0.25"}
    cfg = load_config(env)
    assert cfg.ivf.enabled and cfg.ivf.nlist == 64
    assert cfg.ivf.top_p == 4 and cfg.ivf.pca_dim == 8
    assert cfg.ivf.train_iters == 3 and cfg.ivf.retrain_growth == 0.25
    for bad in ({"IVF_NLIST": "-1"}, {"IVF_TOP_P": "-2"},
                {"IVF_MIN_N": "0"}, {"IVF_PCA_DIM": "-1"},
                {"IVF_PREFILTER_C": "-1"}, {"IVF_TRAIN_SAMPLE": "8"},
                {"IVF_TRAIN_ITERS": "0"}, {"IVF_RETRAIN_GROWTH": "0"}):
        with pytest.raises(ConfigError):
            load_config({"IVF_ENABLED": "true", **bad})


def test_ivf_settings_env_fallback_and_token_revert(monkeypatch):
    tok = tpu.set_ivf_config(None)  # clear cached env parse
    assert tpu.ivf_settings() is None
    monkeypatch.setenv("IVF_ENABLED", "true")
    monkeypatch.setenv("IVF_NLIST", "32")
    tpu.set_ivf_config(None)  # drop cache: revert means re-read
    s = tpu.ivf_settings()
    assert s is not None and s.nlist == 32
    # an override wins over the env; its token reverts only itself
    tok = tpu.set_ivf_config(IvfConfig(enabled=False))
    assert tpu.ivf_settings() is None
    tok2 = tpu.set_ivf_config(IvfConfig(enabled=True, nlist=4))
    tpu.unset_ivf_config(tok)  # stale token: the newer override survives
    assert tpu.ivf_settings().nlist == 4
    tpu.unset_ivf_config(tok2)
    assert tpu.ivf_settings().nlist == 32  # back to the env


def test_kmeans_helpers_are_deterministic_and_complete():
    rng = np.random.default_rng(5)
    rows = rng.standard_normal((1000, 8)).astype(np.float32)
    c1 = ivf_ops.kmeans_fit(rows, 16, iters=4, seed=7)
    c2 = ivf_ops.kmeans_fit(rows, 16, iters=4, seed=7)
    np.testing.assert_array_equal(c1, c2)
    assign = ivf_ops.assign_partitions(rows, c1)
    assert assign.shape == (1000,) and assign.min() >= 0 \
        and assign.max() < 16
    buckets, fills = ivf_ops.build_buckets(assign, 16)
    assert buckets.shape[1] % 128 == 0
    assert int(fills.sum()) == 1000
    got = np.sort(buckets[buckets >= 0])
    np.testing.assert_array_equal(got, np.arange(1000))
    # pinned cap_p is kept while it still fits
    b2, _ = ivf_ops.build_buckets(assign, 16, cap_p=buckets.shape[1])
    assert b2.shape == buckets.shape
    proj = ivf_ops.pca_fit(rows, 4)
    assert proj.shape == (8, 4) and proj.dtype == np.float32
