"""Fused PQ-ADC group-min kernel (ops/pq_gmin.py) vs the legacy
reconstruction scan and exact-ADC numpy ground truth — interpret mode on
the CPU mesh (the compiled Mosaic path is exercised on real TPU by
bench.py, same contract as the dense kernel's tests)."""

import numpy as np
import pytest

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.tpu import TpuVectorIndex
from weaviate_tpu.ops import pq_gmin
from weaviate_tpu.storage.bitmap import Bitmap


def _mk_pq_index(tmp_path, metric=vi.DISTANCE_L2, n=2000, d=32, segments=8,
                 centroids=32, seed=0):
    rng = np.random.default_rng(seed)
    vecs = rng.standard_normal((n, d)).astype(np.float32)
    if metric == vi.DISTANCE_COSINE:
        vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    cfg = vi.HnswUserConfig.from_dict(
        {"distance": metric,
         "pq": {"enabled": True, "segments": segments,
                "centroids": centroids, "rescore": False}}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, str(tmp_path / "pqg"), persist=False)
    idx.add_batch(np.arange(n), vecs)
    idx.flush()
    assert idx.compressed and idx._rescore_dev is None
    return idx, vecs, rng


def _exact_adc(idx, q, k, metric):
    """Ground truth from the actual reconstructions: ADC distance order."""
    codes = np.asarray(idx._codes[: idx.n])
    recon = idx._pq.decode(codes)
    if metric == vi.DISTANCE_L2:
        d = ((q[:, None, :] - recon[None, :, :]) ** 2).sum(-1)
    elif metric == vi.DISTANCE_DOT:
        d = -(q @ recon.T)
    else:
        d = 1.0 - q @ recon.T
    return d


@pytest.mark.parametrize("metric", [vi.DISTANCE_L2, vi.DISTANCE_DOT,
                                    vi.DISTANCE_COSINE])
def test_pq_gmin_matches_exact_adc(tmp_path, metric):
    idx, vecs, rng = _mk_pq_index(tmp_path, metric)
    q = rng.standard_normal((16, vecs.shape[1])).astype(np.float32)
    if metric == vi.DISTANCE_COSINE:
        q /= np.linalg.norm(q, axis=1, keepdims=True)
    ids, dists = idx.search_by_vectors(q, 5)
    # the fused kernel actually served (validated shape, separate domain)
    assert idx._pqg_state._gmin_validated and not idx._pqg_state._gmin_broken
    d = _exact_adc(idx, q, 5, metric)
    want_ids = np.argsort(d, axis=1, kind="stable")[:, :5]
    want_d = np.sort(d, axis=1)[:, :5]
    for i in range(len(q)):
        # ADC ties are common at coarse codebooks: compare distances and
        # demand heavy id overlap
        np.testing.assert_allclose(dists[i], want_d[i], rtol=1e-2, atol=1e-2)
        assert len(set(int(x) for x in ids[i]) &
                   set(int(x) for x in want_ids[i])) >= 4


def test_pq_gmin_matches_legacy_recon_path(tmp_path):
    """The fused kernel and the legacy reconstruction scan are two
    implementations of the same ADC tier: same winners on the same index."""
    idx, vecs, rng = _mk_pq_index(tmp_path, n=3000)
    q = vecs[:12] + 0.01 * rng.standard_normal((12, vecs.shape[1])).astype(np.float32)
    ids_fused, d_fused = idx.search_by_vectors(q, 5)
    assert idx._pqg_state._gmin_validated
    idx._pqg_state._gmin_broken = True  # force the legacy path
    ids_legacy, d_legacy = idx.search_by_vectors(q, 5)
    idx._pqg_state._gmin_broken = False
    for i in range(len(q)):
        assert set(int(x) for x in ids_fused[i]) == set(int(x) for x in ids_legacy[i]), i
        np.testing.assert_allclose(np.sort(d_fused[i]), np.sort(d_legacy[i]),
                                   rtol=1e-2, atol=1e-2)


def test_pq_gmin_tombstones_and_filter(tmp_path):
    idx, vecs, rng = _mk_pq_index(tmp_path, n=2000)
    for doc in range(0, 40, 2):
        idx.delete(doc)
    idx.flush()
    q = vecs[:16] + 0.005 * rng.standard_normal((16, vecs.shape[1])).astype(np.float32)
    idx.config.flat_search_cutoff = 0  # force the masked full-scan path
    allow = Bitmap(np.arange(200).astype(np.uint64))
    ids, _ = idx.search_by_vectors(q, 5, allow_list=allow)
    assert idx._pqg_state._gmin_validated
    sentinel = np.uint64(0xFFFFFFFFFFFFFFFF)
    flat = ids.ravel()
    flat = flat[flat != sentinel]
    assert all(int(x) < 200 for x in flat)
    assert all(int(x) % 2 == 1 or int(x) >= 40 for x in flat)


def test_pq_gmin_small_batch_uses_legacy(tmp_path):
    idx, vecs, _ = _mk_pq_index(tmp_path, n=1500)
    ids, _ = idx.search_by_vectors(vecs[:2], 3)  # b < 8
    assert not idx._pqg_state._gmin_validated
    assert ids.shape == (2, 3)


def test_pq_gmin_large_centroids_uses_legacy(tmp_path):
    """uint16 codebooks (centroids > 256) stay on the recon scan."""
    idx, vecs, rng = _mk_pq_index(tmp_path, n=1500, centroids=300)
    q = vecs[:16]
    ids, _ = idx.search_by_vectors(q, 3)
    assert not idx._pqg_state._gmin_validated
    assert ids.shape[0] == 16


def test_pq_gmin_failure_separate_from_dense(tmp_path, monkeypatch):
    """A failing PQ kernel must not disable the dense gmin path (separate
    failure domains)."""
    idx, vecs, rng = _mk_pq_index(tmp_path, n=1500)

    def boom(*a, **k):
        raise RuntimeError("mosaic says no")

    # both entries: the fused-dispatch default routes through the _fused
    # twin, the legacy toggle through the plain one — either failing must
    # break only the PQ domain
    monkeypatch.setattr(pq_gmin, "search_pq_gmin", boom)
    monkeypatch.setattr(pq_gmin, "search_pq_gmin_fused", boom)
    q = vecs[:16]
    ids, _ = idx.search_by_vectors(q, 3)  # falls back, still answers
    assert ids.shape[0] == 16
    assert idx._pqg_state._gmin_shape_broken
    assert not idx._gmin_broken and not idx._gmin_shape_broken


def test_cb_chunks_roundtrip():
    """build_cb_chunks block-diagonal layout reconstructs exactly."""
    rng = np.random.default_rng(3)
    m, c, ds = 12, 16, 4  # m % mseg != 0 exercises the ragged tail
    cb = rng.standard_normal((m, c, ds)).astype(np.float32)
    mseg = min(pq_gmin._MSEG, m)
    chunks = pq_gmin.build_cb_chunks(cb, mseg)
    codes = rng.integers(0, c, (20, m))
    want = np.concatenate([cb[s, codes[:, s]] for s in range(m)], axis=1)
    nchunks = chunks.shape[0]
    pad = nchunks * mseg - m
    codes_p = np.pad(codes, ((0, 0), (0, pad)))
    got = np.zeros((20, m * ds), np.float32)
    for t in range(nchunks):
        oh = np.zeros((20, mseg * c), np.float32)
        for s in range(mseg):
            oh[np.arange(20), s * c + codes_p[:, t * mseg + s]] = 1.0
        got += oh @ chunks[t]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_plan_tiles_pq_respects_budget():
    from weaviate_tpu.ops.gmin_scan import _VMEM_BUDGET

    # SIFT1M serving shape
    qb, scg, mseg, fp = pq_gmin.plan_tiles_pq(16384, 128, 65536, 16, 32, 256)
    assert fp <= _VMEM_BUDGET and qb >= 64 and scg >= 64
    # pathologically wide vectors must still plan under budget or shrink
    qb2, scg2, _, fp2 = pq_gmin.plan_tiles_pq(512, 2048, 4096, 16, 512, 256)
    assert qb2 >= 64 and scg2 >= 64
