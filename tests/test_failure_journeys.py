"""Compound failure journeys: chained disaster scenarios in one test each.

Two journeys the reference exercises across clusterintegrationtest and the
commit-log corruption fixer (adapters/repos/db/clusterintegrationtest/,
adapters/repos/db/vector/hnsw/corrupt_commit_logs_fixer.go), here chained
end-to-end instead of per-subsystem:

1. import -> backup -> node dies losing its disk (gossip marks it dead) ->
   node returns empty -> restore from backup -> replicated QUORUM read with
   read repair.
2. crash with BOTH a torn LSM WAL tail and a torn vector-log tail ->
   recovery serves the surviving prefix consistently -> post-recovery
   writes survive another restart.
"""

import shutil
import time
import uuid as uuidlib

import numpy as np

from weaviate_tpu.cluster.node import ClusterNode
from weaviate_tpu.db import DB
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.modules import Provider
from weaviate_tpu.modules.backup_fs import FilesystemBackupBackend
from weaviate_tpu.usecases.backup import BackupScheduler

from tests.test_cluster import make_class, new_obj, teardown_cluster

DIM = 8


def _wait_until(pred, timeout=15.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


def _attach_backup(node, shared_root):
    p = Provider()
    p.register(FilesystemBackupBackend(shared_root))
    sched = BackupScheduler(
        node.db, node.schema, p, node_name=node.node_name,
        cluster=node.cluster, node_client=node.transfer_client,
    )
    node.api.backup = sched
    return sched


def test_backup_node_loss_restore_quorum_journey(tmp_path):
    """import -> backup -> kill node-2 AND wipe its disk (gossip marks it
    dead) -> node-2 returns empty and is revived -> cluster-wide restore
    from the backup -> diverge one replica -> QUORUM read repairs it."""
    names = ["node-0", "node-1", "node-2"]
    shared_root = str(tmp_path / "shared-backups")
    nodes = [
        ClusterNode(str(tmp_path / n), n, node_names=names,
                    enable_gossip=True, gossip_interval=0.1)
        for n in names
    ]
    try:
        for n in nodes:
            n.start()
        seed = nodes[0].gossip.gossip_addr
        for n in nodes[1:]:
            n.join_gossip([seed])
        assert _wait_until(lambda: all(
            sorted(n.cluster.all_names()) == names for n in nodes))
        for n in nodes:
            _attach_backup(n, shared_root)

        # 1. import: rf=3 so every shard lives on all three nodes and
        # QUORUM (2/3) survives one node loss
        nodes[0].schema.add_class(make_class(shards=2, replicas=3))
        idx0 = nodes[0].db.get_index("Dist")
        objs = [new_obj(i) for i in range(40)]
        assert all(e is None for e in idx0.put_batch(objs))

        # 2. backup while everyone is alive
        sched0 = nodes[0].api.backup
        sched0.backup("filesystem", {"id": "journey1"})
        assert sched0.wait("journey1")["status"] == "SUCCESS"

        # 3. disaster: node-2 dies and its data directory is lost
        nodes[2].shutdown()
        shutil.rmtree(str(tmp_path / "node-2"))
        assert _wait_until(
            lambda: not nodes[0].cluster.is_alive("node-2")
            and not nodes[1].cluster.is_alive("node-2")), \
            "gossip never marked the dead node"

        # survivors still answer QUORUM reads (2 of 3 replicas)
        got = nodes[0].db.get_index("Dist").object_by_uuid(
            objs[7].uuid, cl="QUORUM")
        assert got is not None and got.properties["wordCount"] == 7

        # 4. node-2 returns on the same identity with an EMPTY disk,
        # rejoins via gossip, and syncs the schema from the cluster
        n2 = ClusterNode(str(tmp_path / "node-2"), "node-2", node_names=names,
                         enable_gossip=True, gossip_interval=0.1)
        n2.start()
        n2.join_gossip([seed])
        nodes[2] = n2
        assert _wait_until(lambda: nodes[0].cluster.is_alive("node-2")
                           and nodes[1].cluster.is_alive("node-2")), \
            "returned node never revived"
        _attach_backup(n2, shared_root)
        # the returned node's disk is empty: adopt the cluster schema
        # (startup_cluster_sync.go semantics)
        if n2.schema.get_class("Dist") is None:
            n2.sync_schema()
        assert n2.schema.get_class("Dist") is not None, \
            "returned node never adopted the cluster schema"

        # 5. cluster-wide restore from the backup: drop the class, then
        # restore brings every node's shards back (incl. the wiped node)
        nodes[0].schema.delete_class("Dist")
        for n in nodes:
            assert n.db.get_index("Dist") is None
        sched0.restore("filesystem", "journey1", {})
        assert sched0.wait("journey1", restore=True)["status"] == "SUCCESS"
        for n in nodes:
            idx = n.db.get_index("Dist")
            assert idx is not None
            local = sum(s.object_count() for s in idx.shards.values())
            assert local == 40  # rf=3: every node holds every object

        # 6. replicated read at QUORUM with repair: one replica silently
        # loses an object (data loss, not deletion), a QUORUM read detects
        # the divergence and backfills it
        obj = objs[11]
        shard_name = nodes[0].db.get_index("Dist").shard_for(obj.uuid)
        stale = nodes[1].db.get_index("Dist")._local_shard(shard_name)
        assert stale is not None
        stale.delete_object(obj.uuid)
        stale._deleted.clear()
        assert stale.object_by_uuid(obj.uuid) is None
        got = nodes[1].db.get_index("Dist").object_by_uuid(obj.uuid, cl="QUORUM")
        assert got is not None and got.properties["wordCount"] == 11
        assert stale.object_by_uuid(obj.uuid) is not None  # repaired

        # and the restored data actually serves vector search, cluster-wide
        res = nodes[2].db.get_index("Dist").object_vector_search(
            objs[5].vector, k=3)
        assert res[0][0].obj.uuid == objs[5].uuid
    finally:
        teardown_cluster(nodes)


def test_torn_wal_and_vector_log_crash_recovery(tmp_path):
    """One crash tears BOTH durability logs: the LSM objects-bucket WAL gets
    a half-written record appended AND the vector log loses bytes mid-record
    plus gains a garbage tail. Recovery must serve the fully-written prefix
    consistently (object store and vector index agree on it), and
    post-recovery writes must survive a further clean restart."""
    rng = np.random.default_rng(3)

    def make_db(path):
        db = DB(str(path))
        if db.get_index("J") is None:
            cd = ClassDef(
                name="J",
                properties=[Property(name="t", data_type=["text"]),
                            Property(name="n", data_type=["int"])],
                sharding_config={"desiredCount": 1},
            )
            db.add_class(cd, parse_and_validate_config(
                "hnsw_tpu", {"distance": "l2-squared"}))
        return db

    vecs = rng.standard_normal((43, DIM)).astype(np.float32)

    def obj(i):
        return StorObj(class_name="J", uuid=str(uuidlib.UUID(int=i + 1)),
                       properties={"t": f"x{i}", "n": i}, vector=vecs[i])

    root = tmp_path / "data"
    db = make_db(root)
    idx = db.get_index("J")
    # wave A: 40 objects, flushed -> must survive any tail corruption
    assert all(e is None for e in idx.put_batch([obj(i) for i in range(40)]))
    for s in idx.shards.values():
        s.flush()
    # wave B: 3 more objects land in the WAL/vector-log tails
    assert all(e is None for e in idx.put_batch([obj(i) for i in range(40, 43)]))
    shard_path = next(iter(idx.shards.values())).path
    db.shutdown()

    # the crash: tear both logs. The WAL gains a half-written record; the
    # vector log loses the end of its last record AND gains a torn header.
    wal = f"{shard_path}/lsm/objects/bucket.wal"
    vlog = f"{shard_path}/vector.log"
    with open(wal, "ab") as f:
        f.write(b"\x07\x01\xff\xfe")
    with open(vlog, "r+b") as f:
        f.seek(0, 2)
        f.truncate(f.tell() - 5)
    with open(vlog, "ab") as f:
        f.write(b"\x01" + b"\x00" * 9)

    # recovery: the shard must open and serve the surviving prefix
    db2 = make_db(root)
    idx2 = db2.get_index("J")
    shard2 = next(iter(idx2.shards.values()))
    for i in range(40):
        got = shard2.object_by_uuid(obj(i).uuid)
        assert got is not None and got.properties["n"] == i
        ids, d = shard2.vector_index.search_by_vector(vecs[i], 1)
        assert int(ids[0]) == got.doc_id and d[0] < 1e-5, i
    # torn-tail writes may be partially lost, but reads must not crash and
    # anything the object store kept must be intact
    for i in range(40, 43):
        got = shard2.object_by_uuid(obj(i).uuid)
        if got is not None:
            assert got.properties["n"] == i
    assert shard2.object_count() >= 40

    # post-recovery writes work and survive a clean restart
    extra = StorObj(class_name="J", uuid=str(uuidlib.UUID(int=1000)),
                    properties={"t": "post-crash", "n": 1000},
                    vector=rng.standard_normal(DIM).astype(np.float32))
    idx2.put_object(extra)
    db2.shutdown()

    db3 = make_db(root)
    shard3 = next(iter(db3.get_index("J").shards.values()))
    got = shard3.object_by_uuid(extra.uuid)
    assert got is not None and got.properties["t"] == "post-crash"
    ids, d = shard3.vector_index.search_by_vector(np.asarray(extra.vector), 1)
    assert int(ids[0]) == got.doc_id and d[0] < 1e-5
    db3.shutdown()
