"""Config env parsing, auth composer, adminlist, metrics registry.

Reference test model: usecases/config tests + auth composer/adminlist tests.
"""

import base64
import json

import pytest

from weaviate_tpu.auth import (
    Authenticator,
    Authorizer,
    ForbiddenError,
    UnauthorizedError,
)
from weaviate_tpu.config import ConfigError, load_config
from weaviate_tpu.monitoring import noop_metrics


def test_defaults():
    cfg = load_config({})
    assert cfg.persistence.data_path == "./data"
    assert cfg.auth.anonymous.enabled is True
    assert cfg.query_defaults_limit == 25
    assert cfg.query_maximum_results == 10000
    assert cfg.cluster.gossip_bind_port == 7946
    assert cfg.monitoring.enabled is False


def test_env_surface():
    cfg = load_config({
        "PERSISTENCE_DATA_PATH": "/tmp/w",
        "QUERY_DEFAULTS_LIMIT": "50",
        "QUERY_MAXIMUM_RESULTS": "500",
        "PROMETHEUS_MONITORING_ENABLED": "true",
        "PROMETHEUS_MONITORING_PORT": "9999",
        "CLUSTER_HOSTNAME": "node1",
        "CLUSTER_JOIN": "a:7946, b:7946",
        "ENABLE_MODULES": "text2vec-contextionary,backup-filesystem",
        "DEFAULT_VECTORIZER_MODULE": "text2vec-contextionary",
        "TRACK_VECTOR_DIMENSIONS": "true",
        "GRPC_PORT": "50055",
    })
    assert cfg.persistence.data_path == "/tmp/w"
    assert cfg.query_defaults_limit == 50
    assert cfg.monitoring.enabled and cfg.monitoring.port == 9999
    assert cfg.cluster.join == ["a:7946", "b:7946"]
    assert cfg.enable_modules == ["text2vec-contextionary", "backup-filesystem"]
    assert cfg.track_vector_dimensions is True
    assert cfg.grpc_port == 50055


def test_invalid_int_rejected():
    with pytest.raises(ConfigError):
        load_config({"QUERY_MAXIMUM_RESULTS": "lots"})


def test_apikey_requires_keys_and_users():
    with pytest.raises(ConfigError):
        load_config({"AUTHENTICATION_APIKEY_ENABLED": "true"})
    with pytest.raises(ConfigError):
        load_config({
            "AUTHENTICATION_APIKEY_ENABLED": "true",
            "AUTHENTICATION_APIKEY_ALLOWED_KEYS": "k1,k2",
            "AUTHENTICATION_APIKEY_USERS": "a,b,c",  # mismatch
        })


def _auth_cfg(**env):
    return load_config(env).auth


def test_anonymous_disabled_when_apikey_on():
    cfg = load_config({
        "AUTHENTICATION_APIKEY_ENABLED": "true",
        "AUTHENTICATION_APIKEY_ALLOWED_KEYS": "secret1,secret2",
        "AUTHENTICATION_APIKEY_USERS": "alice,bob",
    })
    a = Authenticator(cfg.auth)
    p = a.principal_from_bearer("secret2")
    assert p.username == "bob"
    with pytest.raises(UnauthorizedError):
        a.principal_from_bearer("wrong")
    with pytest.raises(UnauthorizedError):
        a.principal_from_bearer(None)  # anonymous off by default with apikey on


def test_single_user_for_all_keys():
    cfg = load_config({
        "AUTHENTICATION_APIKEY_ENABLED": "true",
        "AUTHENTICATION_APIKEY_ALLOWED_KEYS": "k1,k2",
        "AUTHENTICATION_APIKEY_USERS": "svc",
    })
    a = Authenticator(cfg.auth)
    assert a.principal_from_bearer("k1").username == "svc"
    assert a.principal_from_bearer("k2").username == "svc"


def test_anonymous_principal():
    a = Authenticator(load_config({}).auth)
    p = a.principal_from_bearer(None)
    assert p.anonymous and p.username == "anonymous"


def test_oidc_fails_closed_without_validator():
    cfg = load_config({
        "AUTHENTICATION_OIDC_ENABLED": "true",
        "AUTHENTICATION_OIDC_ISSUER": "https://issuer",
        "AUTHENTICATION_OIDC_USERNAME_CLAIM": "email",
    })
    claims = base64.urlsafe_b64encode(
        json.dumps({"email": "u@x.io"}).encode()).decode().rstrip("=")
    token = f"h.{claims}.sig"
    # forged/unsigned tokens are rejected unless a validator is wired
    with pytest.raises(UnauthorizedError):
        Authenticator(cfg.auth).principal_from_bearer(token)
    # an explicitly-opted-in unverified validator (dev/test only) parses claims
    a = Authenticator(cfg.auth)
    a.oidc_validator = a.unverified_claims_validator()
    assert a.principal_from_bearer(token).username == "u@x.io"


def test_adminlist():
    cfg = load_config({
        "AUTHORIZATION_ADMINLIST_ENABLED": "true",
        "AUTHORIZATION_ADMINLIST_USERS": "root",
        "AUTHORIZATION_ADMINLIST_READONLY_USERS": "viewer",
    })
    z = Authorizer(cfg.authz)
    from weaviate_tpu.auth.auth import Principal

    z.authorize(Principal("root"), "create", "schema/things")
    z.authorize(Principal("viewer"), "get", "schema/things")
    with pytest.raises(ForbiddenError):
        z.authorize(Principal("viewer"), "create", "schema/things")
    with pytest.raises(ForbiddenError):
        z.authorize(Principal("stranger"), "get", "schema/things")


def test_adminlist_disabled_allows_all():
    from weaviate_tpu.auth.auth import Principal

    z = Authorizer(load_config({}).authz)
    z.authorize(Principal("anyone"), "delete", "objects")  # no raise


def test_metrics_registry_exposition():
    m = noop_metrics()
    m.object_count.labels(class_name="A", shard_name="s0").set(5)
    m.query_durations.labels(class_name="A", query_type="vector").observe(1.5)
    m.vector_index_ops.labels(operation="add", class_name="A", shard_name="s0").inc(3)
    text = m.expose().decode()
    assert 'weaviate_object_count{class_name="A",shard_name="s0"} 5.0' in text
    assert "weaviate_queries_durations_ms_bucket" in text
    assert "weaviate_vector_index_operations_total" in text


def test_metrics_isolated_registries():
    m1, m2 = noop_metrics(), noop_metrics()
    m1.object_count.labels(class_name="A", shard_name="s").set(1)
    assert b"weaviate_object_count" not in m2.expose() or \
        b'class_name="A"' not in m2.expose()


def test_vector_index_records_metrics(tmp_path):
    """The TPU index populates the hnsw metrics.go-parity families on
    flush/delete (ops, durations, tombstones, size, dimensions)."""
    import numpy as np

    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.tpu import TpuVectorIndex

    m = noop_metrics()
    cfg = vi.HnswUserConfig.from_dict({"distance": "l2-squared"}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, str(tmp_path / "C" / "s0"), "s0",
                         metrics=m, persist=False)
    vecs = np.random.default_rng(0).standard_normal((64, 8)).astype(np.float32)
    idx.add_batch(np.arange(64), vecs)
    idx.flush()
    idx.delete(0, 1, 2)
    idx.flush()
    text = m.expose().decode()
    assert 'weaviate_vector_index_operations_total{class_name="C",operation="add",shard_name="s0"} 64.0' in text
    assert 'weaviate_vector_index_tombstones{class_name="C",shard_name="s0"} 3.0' in text
    assert "weaviate_vector_index_durations_ms_bucket" in text
    assert 'weaviate_vector_index_size{class_name="C",shard_name="s0"}' in text
    assert 'weaviate_vector_dimensions_sum{class_name="C",shard_name="s0"}' in text


def test_native_hnsw_records_metrics(tmp_path):
    import numpy as np

    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.hnsw import HnswIndex

    m = noop_metrics()
    cfg = vi.HnswUserConfig.from_dict({"distance": "l2-squared"}, "hnsw")
    idx = HnswIndex(cfg, str(tmp_path / "C" / "s1"), "s1", metrics=m, persist=False)
    vecs = np.random.default_rng(0).standard_normal((50, 8)).astype(np.float32)
    idx.add_batch(np.arange(50), vecs)
    idx.delete(0)
    idx.cleanup_tombstones()
    text = m.expose().decode()
    assert 'weaviate_vector_index_operations_total{class_name="C",operation="add",shard_name="s1"} 50.0' in text
    assert "weaviate_vector_index_tombstone_cleanup_threads_total" in text
