"""Fully fused device dispatch (index/tpu.py): one program from scan to
final doc ids, zero host post-processing.

Pins the fused-dispatch PR's contracts:

1. bit-identity — fused vs legacy (host slot_to_doc translation) return
   EXACTLY the same ids and distances on every tier: exact scan, PQ
   rescore, PQ codes-only, small-allowList gather (compressed and not),
   and target-distance widening; sync == async both ways;
2. snapshot pinning survives fusion — enqueue, then delete the winners
   and compact(): finalize still returns the OLD snapshot's exact doc
   ids (the device translation table is pinned by the snapshot like
   every other device buffer);
3. the perf-ledger invariant — a fused dispatch records exactly ONE
   blocking fetch and ZERO host-translation time
   (costmodel.fused_invariant_ok; the window counts violations);
4. the satellites — the sorted doc->slot map is gone (gather resolves
   via a cached vectorized membership pass), the slot_to_doc COW copy is
   gone from the write path (append-only invariant), R_BUCKETS has one
   source of truth in config, and the enqueue staging pool reuses
   per-bucket host buffers.
"""

import numpy as np
import pytest

from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.index import tpu
from weaviate_tpu.index.tpu import TpuVectorIndex
from weaviate_tpu.monitoring import costmodel, perf, tracing
from weaviate_tpu.storage.bitmap import Bitmap

DIM = 16


@pytest.fixture(autouse=True)
def _reset_globals():
    yield
    tpu.set_fused_enabled(None)
    tracing.configure(None)
    perf.configure(None)


def _mk_index(tmp_path, n=500, pq=None, seed=0, name="fx", **cfg_extra):
    rng = np.random.default_rng(seed)
    # small-integer vectors: every L2 distance is exact integer arithmetic
    # in f32 regardless of accumulation order, so equality checks are exact
    vecs = rng.integers(-8, 8, (n, DIM)).astype(np.float32)
    d = {"distance": "l2-squared", **cfg_extra}
    if pq is not None:
        d["pq"] = pq
    cfg = parse_and_validate_config("hnsw_tpu", d)
    idx = TpuVectorIndex(cfg, str(tmp_path / name), persist=False)
    idx.add_batch(np.arange(n), vecs)
    idx.flush()
    return idx, vecs


def _tiers(tmp_path, n=500):
    """(name, index, allowList) per read tier, sharing one dataset."""
    out = []
    idx, vecs = _mk_index(tmp_path, n=n, name="exact")
    out.append(("exact", idx, vecs, None))
    cutoff = idx.config.flat_search_cutoff
    big_allow = Bitmap(np.arange(0, cutoff + 64, dtype=np.uint64))
    out.append(("filtered_scan", idx, vecs, big_allow))
    out.append(("gather", idx, vecs,
                Bitmap(np.array([3, 7, 11, 401], dtype=np.uint64))))
    pq_r, vecs_r = _mk_index(
        tmp_path, n=n, name="pqr",
        pq={"enabled": True, "segments": 4, "centroids": 16})
    assert pq_r.compressed and pq_r._rescore_dev is not None
    out.append(("pq_rescore", pq_r, vecs_r, None))
    pq_c, vecs_c = _mk_index(
        tmp_path, n=n, name="pqc",
        pq={"enabled": True, "segments": 4, "centroids": 16,
            "rescore": False})
    assert pq_c.compressed and pq_c._rescore_dev is None
    out.append(("pq_codes", pq_c, vecs_c, None))
    out.append(("pq_gather", pq_c, vecs_c,
                Bitmap(np.array([3, 7, 11], dtype=np.uint64))))
    return out


# -- 1. fused == legacy bit identity, sync == async ---------------------------


def test_fused_legacy_bit_identity_all_tiers_sync_and_async(tmp_path):
    for name, idx, vecs, allow in _tiers(tmp_path):
        q = vecs[:9] + 0.01
        tpu.set_fused_enabled(True)
        f_sync = idx.search_by_vectors(q, 10, allow)
        f_async = idx.search_by_vectors_async(q, 10, allow)()
        tpu.set_fused_enabled(False)
        l_sync = idx.search_by_vectors(q, 10, allow)
        l_async = idx.search_by_vectors_async(q, 10, allow)()
        for got in (f_sync, f_async, l_async):
            np.testing.assert_array_equal(got[0], l_sync[0], err_msg=name)
            np.testing.assert_array_equal(got[1], l_sync[1], err_msg=name)
        assert f_sync[0].dtype == np.uint64, name
        assert f_sync[1].dtype == np.float32, name


def test_fused_target_distance_widening_matches_legacy(tmp_path):
    idx, vecs = _mk_index(tmp_path)
    q = vecs[5] + 0.01
    tpu.set_fused_enabled(True)
    ids_f, d_f = idx.search_by_vector_distance(q, 300.0, 64)
    tpu.set_fused_enabled(False)
    ids_l, d_l = idx.search_by_vector_distance(q, 300.0, 64)
    np.testing.assert_array_equal(ids_f, ids_l)
    np.testing.assert_array_equal(d_f, d_l)
    assert len(ids_f) > 0


def test_fused_missing_slots_carry_legacy_sentinel(tmp_path):
    """Fewer matches than k: missing slots must read inf/2^64-1 exactly
    like the legacy host translation emitted (np.int64(-1) as uint64)."""
    idx, vecs = _mk_index(tmp_path)
    cutoff = idx.config.flat_search_cutoff
    # masked full scan with only 3 live matches (the rest are absent ids)
    allow = Bitmap(np.array(
        [0, 1, 2] + list(range(10**6, 10**6 + cutoff + 50)),
        dtype=np.uint64))
    tpu.set_fused_enabled(True)
    ids, dists = idx.search_by_vectors(vecs[:2] + 0.01, 8, allow)
    assert (ids[:, 3:] == np.uint64(0xFFFFFFFFFFFFFFFF)).all()
    assert np.isinf(dists[:, 3:]).all()


def test_fused_keeps_64bit_doc_ids(tmp_path):
    """Doc ids above 2^32 survive the device translation table's two-word
    round trip bit-exactly (jax may run with x64 disabled)."""
    cfg = parse_and_validate_config("hnsw_tpu", {"distance": "l2-squared"})
    idx = TpuVectorIndex(cfg, str(tmp_path / "big"), persist=False)
    big = np.array([2**63 + 7, 2**40 + 1, 3], dtype=np.uint64)
    vecs = np.eye(3, DIM, dtype=np.float32)
    idx.add_batch(big.astype(np.int64), vecs)
    idx.flush()
    tpu.set_fused_enabled(True)
    ids, _ = idx.search_by_vectors(vecs, 3)
    assert {int(x) for x in ids[0]} == {int(x) for x in big}


# -- 2. snapshot pinning across delete + compact ------------------------------


def test_fused_finalize_pins_snapshot_across_delete_compact(tmp_path):
    """Enqueue -> delete the winners + compact -> finalize returns the
    OLD snapshot's exact answer, on every tier (the PR-4 contract, now
    including the device slot->doc table)."""
    tpu.set_fused_enabled(True)
    for name, idx, vecs, allow in _tiers(tmp_path):
        q = vecs[:4] + 0.01
        want = idx.search_by_vectors(q, 5, allow)
        fin = idx.search_by_vectors_async(q, 5, allow)
        winners = [int(x) for x in np.unique(want[0])
                   if x != 0xFFFFFFFFFFFFFFFF]
        idx.delete(*winners[:3])
        idx.compact()
        got = fin()
        np.testing.assert_array_equal(got[0], want[0], err_msg=name)
        np.testing.assert_array_equal(got[1], want[1], err_msg=name)
        # and a FRESH search sees the post-delete world
        fresh = idx.search_by_vectors(q, 5, allow)
        if winners[:3]:
            assert not set(winners[:3]) & {int(x) for x in fresh[0].ravel()}


# -- 3. the perf-ledger fused-dispatch invariant ------------------------------


def _with_perf_window():
    tracing.configure(tracing.Tracer(sample_rate=1.0))
    return perf.configure(perf.PerfWindow(window_s=60.0))


def _pop_shape(idx):
    s = idx.pop_dispatch_shape()
    assert s is not None
    return s


def test_fused_invariant_one_fetch_zero_translation(tmp_path):
    win = _with_perf_window()
    tpu.set_fused_enabled(True)
    for name, idx, vecs, allow in _tiers(tmp_path):
        ids, dists = idx.search_by_vectors(vecs[:4] + 0.01, 5, allow)
        shape = _pop_shape(idx)
        assert shape.fused is True, name
        assert shape.fetches == 1, name
        assert shape.translate_ms == 0.0, name
        assert costmodel.fused_invariant_ok(shape), name
        win.record_dispatch(shape, rows=4)
    s = win.summary()
    assert s["fused"]["dispatches"] == 6
    assert s["fused"]["violations"] == 0


def test_legacy_dispatch_measures_translation_and_passes_trivially(tmp_path):
    win = _with_perf_window()
    tpu.set_fused_enabled(False)
    idx, vecs = _mk_index(tmp_path)
    idx.search_by_vectors(vecs[:4] + 0.01, 5)
    shape = _pop_shape(idx)
    assert shape.fused is False
    assert shape.fetches == 1
    assert shape.translate_ms >= 0.0  # measured, not -1
    assert costmodel.fused_invariant_ok(shape)  # no claim, no violation
    win.record_dispatch(shape, rows=4)
    s = win.summary()
    assert s["fused"] == {"dispatches": 0, "violations": 0}


def test_fused_invariant_violation_is_counted(tmp_path):
    win = _with_perf_window()
    shape = costmodel.DispatchShape(costmodel.TIER_EXACT, n=100, dim=DIM,
                                    batch=4, bytes_per_row=DIM * 4, k=5)
    shape.fused = True
    shape.fetches = 2  # a second blocking fetch broke the contract
    shape.translate_ms = 0.0
    assert not costmodel.fused_invariant_ok(shape)
    win.record_dispatch(shape, rows=4)
    assert win.summary()["fused"] == {"dispatches": 1, "violations": 1}


def test_fused_empty_gather_owes_no_fetch(tmp_path):
    """The empty-allowList gather early return runs no device work: zero
    fetches is NOT an invariant violation there (shape.n == 0)."""
    _with_perf_window()
    tpu.set_fused_enabled(True)
    idx, vecs = _mk_index(tmp_path)
    allow = Bitmap(np.array([10**7, 10**7 + 1], dtype=np.uint64))
    ids, dists = idx.search_by_vectors(vecs[:2], 5, allow)
    assert ids.shape == (2, 0)
    shape = _pop_shape(idx)
    assert shape.fused and shape.fetches == 0 and shape.n == 0
    assert costmodel.fused_invariant_ok(shape)


# -- 4. satellites ------------------------------------------------------------


def test_sorted_map_is_gone_and_gather_slots_cache_on_allowlist(tmp_path):
    idx, vecs = _mk_index(tmp_path)
    snap = idx._read_snapshot()
    assert not hasattr(snap, "_sorted_map")
    assert not hasattr(snap, "sorted_doc_slots")
    allow = Bitmap(np.array([3, 7, 11], dtype=np.uint64))
    idx.search_by_vectors(vecs[:2], 3, allow)
    cached = allow._slots_cache
    assert cached[0] == (snap.allow_token, snap.n, snap.capacity)
    np.testing.assert_array_equal(cached[1], [3, 7, 11])
    # second search reuses the cached slots object
    idx.search_by_vectors(vecs[:2], 3, allow)
    assert allow._slots_cache[1] is cached[1]


def test_gather_cached_allowlist_never_returns_deleted_docs(tmp_path):
    """The review-caught staleness hole: the per-allowList slot cache's
    (allow_token, n, capacity) key does not change on deletes, so a
    REUSED AllowList object after a delete hits a stale slot list — the
    gather kernels must mask tombstones on device with the dispatching
    snapshot's own tombs (both tiers, fused and legacy)."""
    for compress in (False, True):
        pq = ({"enabled": True, "segments": 4, "centroids": 16}
              if compress else None)
        idx, vecs = _mk_index(tmp_path, pq=pq,
                              name=f"stale{int(compress)}")
        allow = Bitmap(np.array([3, 7, 11], dtype=np.uint64))
        q = vecs[:2] + 0.01
        for fused in (True, False):
            tpu.set_fused_enabled(fused)
            ids0, _ = idx.search_by_vectors(q, 3, allow)  # warms the cache
            assert 3 in {int(x) for x in ids0.ravel()}
        idx.delete(3)
        idx.flush()
        for fused in (True, False):
            tpu.set_fused_enabled(fused)
            ids1, d1 = idx.search_by_vectors(q, 3, allow)  # same object
            got = {int(x) for x in ids1.ravel() if x != 2**64 - 1}
            assert got == {7, 11}, (compress, fused, ids1, d1)
        tpu.set_fused_enabled(None)


def test_gather_fully_deleted_filter_short_circuits_empty(tmp_path):
    """An allowList whose every match is tombstoned in the dispatching
    snapshot must return the (b, 0) empty shape with ZERO device work —
    even through a stale cached slot list (the short-circuit consults
    the snapshot's own host mirror per dispatch, never the cache)."""
    _with_perf_window()
    idx, vecs = _mk_index(tmp_path)
    allow = Bitmap(np.array([3, 7], dtype=np.uint64))
    q = vecs[:2] + 0.01
    idx.search_by_vectors(q, 3, allow)  # warm the slot cache
    idx.pop_dispatch_shape()
    idx.delete(3, 7)
    idx.flush()
    for fused in (True, False):
        tpu.set_fused_enabled(fused)
        ids, dists = idx.search_by_vectors(q, 3, allow)
        assert ids.shape == (2, 0) and dists.shape == (2, 0), fused
        shape = _pop_shape(idx)
        assert shape.n == 0 and shape.fetches == 0, fused
    tpu.set_fused_enabled(None)


def test_gather_resolves_readded_doc_to_newest_slot(tmp_path):
    idx, vecs = _mk_index(tmp_path)
    idx.delete(7)
    idx.add(7, np.full(DIM, 1.0, np.float32))
    allow = Bitmap(np.array([7], dtype=np.uint64))
    ids, dists = idx.search_by_vectors(np.ones((1, DIM), np.float32), 3,
                                       allow)
    # the old tombstoned slot is gathered but device-masked to the
    # sentinel; exactly ONE live hit survives — the re-added vector
    finite = np.isfinite(dists[0])
    assert finite.sum() == 1
    assert int(ids[0][finite][0]) == 7
    assert abs(float(dists[0][finite][0])) < 1e-6  # the NEW vector


def test_gather_old_pinned_snapshot_keeps_its_predelete_world(tmp_path):
    """The reverse staleness direction (review-caught): a dispatch pinned
    on an OLD snapshot must keep returning docs live in ITS world even
    when the shared slot cache was (re)computed after a delete — the
    cached list carries no tombstone knowledge; each dispatch's own
    device tombs mask decides."""
    tpu.set_fused_enabled(True)
    idx, vecs = _mk_index(tmp_path)
    allow = Bitmap(np.array([3, 7, 11], dtype=np.uint64))
    q = vecs[:2] + 0.01
    snap_a = idx._read_snapshot()
    idx.delete(3)
    idx.flush()  # publishes B; (allow_token, n, capacity) unchanged
    # warm the cache from B's world
    ids_b, _ = idx.search_by_vectors(q, 3, allow)
    assert 3 not in {int(x) for x in ids_b.ravel()}
    # a dispatch pinned on A consumes the same cache — doc 3 must be back
    ids_a, dists_a = idx._dispatch_search(snap_a, q, 3, allow)()
    assert 3 in {int(x) for x in ids_a.ravel()}
    tpu.set_fused_enabled(None)


def test_slot_to_doc_cow_copy_dropped_host_tombs_kept(tmp_path):
    idx, vecs = _mk_index(tmp_path)
    snap = idx._read_snapshot()
    s2d_obj = snap.slot_to_doc
    # append within capacity: slot_to_doc mutates in place past snap.n —
    # NO copy (the append-only invariant), and the snapshot's prefix is
    # untouched
    idx.add(10_001, vecs[0])
    idx.flush()
    assert idx._slot_to_doc is s2d_obj
    assert idx._snap.slot_to_doc is s2d_obj
    # a delete still copy-on-writes the host tombstone mirror the old
    # snapshot pins
    tombs_obj = idx._host_tombs
    assert idx._snap.host_tombs is tombs_obj
    idx.delete(3)
    idx.flush()
    assert idx._host_tombs is not tombs_obj
    assert not snap.host_tombs[3]  # the pinned view never tore


def test_r_buckets_single_source_of_truth():
    from weaviate_tpu.config.config import RESCORE_R_BUCKETS
    from weaviate_tpu.serving import controller

    assert controller.R_BUCKETS is RESCORE_R_BUCKETS
    assert tpu.RESCORE_R_BUCKETS is RESCORE_R_BUCKETS
    assert RESCORE_R_BUCKETS[-1] == 128


def test_stage_pool_reuses_query_buffers(tmp_path):
    idx, vecs = _mk_index(tmp_path)
    q = vecs[:3] + 0.01
    ids1, _ = idx.search_by_vectors(q, 5)
    key = (tpu._bucket_b(3), DIM)
    assert len(idx._stage_free.get(key, [])) == 1
    buf = idx._stage_free[key][0]
    ids2, _ = idx.search_by_vectors(q, 5)
    # same buffer went out and came back; results stay correct
    assert idx._stage_free[key][0] is buf
    np.testing.assert_array_equal(ids1, ids2)
    # the pool is bounded
    assert all(len(v) <= TpuVectorIndex._STAGE_POOL_CAP
               for v in idx._stage_free.values())


def test_stage_pool_ledger_component_and_drop(tmp_path):
    from weaviate_tpu.monitoring import memory

    idx, vecs = _mk_index(tmp_path)
    idx.search_by_vectors(vecs[:3] + 0.01, 5)
    comps = memory.index_host_components(idx)
    want = sum(b.nbytes for bufs in idx._stage_free.values() for b in bufs)
    assert want > 0 and comps["stage_buffers"] == want
    assert "stage_buffers" in memory.HOST_COMPONENTS
    idx.drop()
    assert idx._stage_free == {}
    assert "stage_buffers" not in memory.index_host_components(idx)


def test_prefetch_failure_strands_stage_buffer(tmp_path):
    """A finalize that fails BEFORE the blocking fetch must NOT return
    its staging buffer to the pool: the enqueued program may not have
    consumed the (possibly aliased, cpu backend) host memory yet, and a
    recycled buffer could corrupt a retried dispatch."""
    from weaviate_tpu.testing import faults

    idx, vecs = _mk_index(tmp_path)
    q = vecs[:3] + 0.01
    idx.search_by_vectors(q, 5)  # park one buffer
    key = (tpu._bucket_b(3), DIM)
    assert len(idx._stage_free[key]) == 1
    inj = faults.configure(faults.from_spec("index.tpu.finalize:device_error:times=1"))
    try:
        fin = idx.search_by_vectors_async(q, 5)  # checks the buffer out
        assert len(idx._stage_free[key]) == 0
        with pytest.raises(Exception):
            fin()
        # stranded, not recycled
        assert len(idx._stage_free[key]) == 0
    finally:
        faults.configure(None)
        del inj
    # a healthy dispatch parks a fresh buffer again
    idx.search_by_vectors(q, 5)
    assert len(idx._stage_free[key]) == 1


def test_drop_blocks_stage_buffer_reparking(tmp_path):
    """An in-flight dispatch finalizing AFTER drop() must not re-park
    its staging buffer into the cleared pool (stage_buffers must read 0
    after drop; a re-created index may use a different dim)."""
    idx, vecs = _mk_index(tmp_path)
    fin = idx.search_by_vectors_async(vecs[:3] + 0.01, 5)
    idx.drop()
    fin()
    assert idx._stage_free == {}


def test_fused_override_token_still_ours_discipline(tmp_path):
    """set_fused_enabled returns a token; unset reverts only the CURRENT
    override (a stale token is a no-op) — and App.shutdown() uses it, so
    a torn-down App leaves no toggle residue while a newer App's setting
    survives."""
    t1 = tpu.set_fused_enabled(False)
    t2 = tpu.set_fused_enabled(True)
    tpu.unset_fused_enabled(t1)  # stale: the newer override survives
    assert tpu.fused_dispatch_enabled() is True
    tpu.unset_fused_enabled(t2)  # current: reverts to the env default
    assert tpu._fused_override is None
    # App-level: shutdown reverts its own override
    from weaviate_tpu.config import Config
    from weaviate_tpu.server import App

    tpu._fused_env = None
    cfg = Config()
    cfg.fused_dispatch_enabled = False
    app = App(config=cfg, data_path=str(tmp_path / "appdata"))
    try:
        assert tpu.fused_dispatch_enabled() is False
    finally:
        app.shutdown()
    assert tpu.fused_dispatch_enabled() is True  # env default restored


def test_fused_toggle_env_and_setter(monkeypatch):
    tpu.set_fused_enabled(None)
    tpu._fused_env = None
    monkeypatch.setenv("FUSED_DISPATCH_ENABLED", "false")
    assert tpu.fused_dispatch_enabled() is False
    tpu.set_fused_enabled(True)
    assert tpu.fused_dispatch_enabled() is True
    tpu.set_fused_enabled(None)
    assert tpu.fused_dispatch_enabled() is False  # env default again
    tpu._fused_env = None  # drop the cached env parse for other tests


def test_config_knob_parses(monkeypatch):
    from weaviate_tpu.config import load_config

    monkeypatch.setenv("FUSED_DISPATCH_ENABLED", "false")
    assert load_config().fused_dispatch_enabled is False
    monkeypatch.delenv("FUSED_DISPATCH_ENABLED")
    assert load_config().fused_dispatch_enabled is True
