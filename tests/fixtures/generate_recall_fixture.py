"""Regenerate the committed recall fixture bit-identically.

Reference: adapters/repos/db/vector/hnsw/generate_recall_datasets.go + the
hnswlib cross-check (test_recall_hnswlib.py) — a frozen dataset with exact
ground truth that every index implementation is measured against
(recall_test.go:32,137).

The data is CLUSTERED (gaussian mixture), not uniform: uniform random
high-dim data makes ANN trivially easy and PQ codebooks meaningless; the
mixture gives the fixture teeth. Ground truth is exact float64 brute force.

Run from the repo root:  python tests/fixtures/generate_recall_fixture.py
"""

import os

import numpy as np

N, D, NQ, K = 8192, 32, 200, 100
N_CLUSTERS = 64
SEED = 20260729


def generate():
    rng = np.random.default_rng(SEED)
    centers = rng.standard_normal((N_CLUSTERS, D)).astype(np.float64) * 4.0
    assign = rng.integers(0, N_CLUSTERS, N)
    vectors = centers[assign] + rng.standard_normal((N, D))
    q_assign = rng.integers(0, N_CLUSTERS, NQ)
    queries = centers[q_assign] + rng.standard_normal((NQ, D)) * 1.2

    # exact ground truth in float64 (l2-squared)
    gt = np.empty((NQ, K), np.int32)
    for i in range(NQ):
        d = ((vectors - queries[i]) ** 2).sum(1)
        gt[i] = np.argsort(d, kind="stable")[:K]

    # cosine ground truth on the same data (normalized)
    vn = vectors / np.linalg.norm(vectors, axis=1, keepdims=True)
    qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
    gt_cos = np.empty((NQ, K), np.int32)
    sims = qn @ vn.T
    for i in range(NQ):
        gt_cos[i] = np.argsort(-sims[i], kind="stable")[:K]

    return (
        vectors.astype(np.float32),
        queries.astype(np.float32),
        gt,
        gt_cos,
    )


if __name__ == "__main__":
    vectors, queries, gt, gt_cos = generate()
    out = os.path.join(os.path.dirname(__file__), "recall_fixture.npz")
    np.savez_compressed(out, vectors=vectors, queries=queries, gt=gt, gt_cos=gt_cos)
    print(f"wrote {out}: vectors {vectors.shape}, queries {queries.shape}, gt {gt.shape}")
