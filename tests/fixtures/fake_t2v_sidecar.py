"""Standalone fake transformers-inference sidecar: the HTTP contract the
text2vec-transformers module speaks (modules/text2vec-transformers/clients
in the reference; POST /vectors {"text": ...} -> {"vector": [...]}, GET
/meta, GET /.well-known/ready). Run as a real process so the container
acceptance tier reproduces the docker-compose topology (server container +
inference container over TCP) without requiring a docker daemon.

Usage: python tests/fixtures/fake_t2v_sidecar.py <port> [dim]
Prints "READY <port>" on stdout once listening.
"""

import hashlib
import json
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def embed(text: str, dim: int):
    """Deterministic, normalized pseudo-embedding: same text -> same vector
    across processes (restart journeys depend on this)."""
    out = []
    i = 0
    while len(out) < dim:
        h = hashlib.sha256(f"{i}:{text}".encode()).digest()
        out.extend(b / 255.0 - 0.5 for b in h)
        i += 1
    v = out[:dim]
    norm = sum(x * x for x in v) ** 0.5 or 1.0
    return [x / norm for x in v]


def main():
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    dim = int(sys.argv[2]) if len(sys.argv) > 2 else 32

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def _send(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/meta", "/.well-known/ready", "/.well-known/live"):
                return self._send({"model": "fake-t2v", "dim": dim})
            self._send({"error": "not found"}, 404)

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            if self.path.rstrip("/") == "/vectors":
                text = body.get("text") or ""
                return self._send({"text": text, "vector": embed(text, dim)})
            self._send({"error": "not found"}, 404)

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    print(f"READY {httpd.server_address[1]}", flush=True)
    httpd.serve_forever()


if __name__ == "__main__":
    main()
