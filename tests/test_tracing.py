"""End-to-end request tracing (monitoring/tracing.py) + its wiring.

The two acceptance-critical properties pinned here:

  1. ATTRIBUTION IDENTITY — a coalesced multi-request run yields traces
     where each rider's attributed device time sums exactly to the
     dispatch's device span (shares are rows_i/actual_rows over the REAL
     rows; padding overhead is reported separately as padding_waste, never
     smeared into shares).

  2. DISABLED = ZERO TRACING WORK — with TRACING_ENABLED unset, the
     serving hot path creates no Span, no Trace, no DispatchRecord, and
     never consults the Tracer (spied by replacing the classes on the
     module; serving code reaches them through module-global lookups, so a
     single construction would trip the spy).

Plus trace propagation across every coalescer edge: bypass lanes,
wrong-dim isolation, dispatch error, shutdown — each must CLOSE or
annotate the rider traces, never leak an open span.
"""

import json
import logging
import threading
import time
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.monitoring import tracing
from weaviate_tpu.serving.coalescer import (
    CoalescerShutdownError,
    QueryCoalescer,
)
from weaviate_tpu.usecases.traverser import GetParams

N, DIM, K = 400, 16, 5


@pytest.fixture(autouse=True)
def _reset_global_tracer():
    """Tests install process-global tracers; never let one leak across."""
    yield
    tracing.configure(None)


def _mk_app(tmp_path, tracing_on=True, coalesce=True, window_ms=200.0,
            sample_rate=1.0, ring_size=256, slow_ms=0.0):
    from weaviate_tpu.server import App

    cfg = Config()
    cfg.coalescer.enabled = coalesce
    cfg.coalescer.window_ms = window_ms
    cfg.tracing.enabled = tracing_on
    cfg.tracing.sample_rate = sample_rate
    cfg.tracing.ring_size = ring_size
    cfg.tracing.slow_query_threshold_ms = slow_ms
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    app.schema.add_class({
        "class": "Tr", "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "tag", "dataType": ["text"]}],
    })
    rng = np.random.default_rng(11)
    vecs = rng.integers(-8, 8, (N, DIM)).astype(np.float32)
    idx = app.db.get_index("Tr")
    idx.put_batch([
        StorObj(class_name="Tr", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"tag": "even" if i % 2 == 0 else "odd"},
                vector=vecs[i])
        for i in range(N)])
    return app, idx, vecs


def _walk_spans(span_dict):
    yield span_dict
    for c in span_dict.get("children", []):
        yield from _walk_spans(c)


def _dispatch_spans(trace_dicts):
    """All 'dispatch' attribution spans across a list of trace dicts."""
    out = []
    for tr in trace_dicts:
        for s in _walk_spans(tr["root"]):
            if s["name"] == "dispatch":
                out.append(s)
    return out


def _get(app, vec, flt=None, limit=K):
    return app.traverser.get_class(GetParams(
        class_name="Tr", near_vector={"vector": vec.tolist()},
        filters=flt, limit=limit))


# -- the attribution identity (acceptance criterion) --------------------------

def test_coalesced_attribution_identity(tmp_path):
    """Concurrent single-query requests coalesce into shared dispatches;
    every rider's trace carries a dispatch span whose device_ms share sums
    (across the dispatch's riders) to the dispatch's device span."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        n_req = 10
        barrier = threading.Barrier(n_req)

        def run(i):
            with tracing.request("test", f"q{i}"):
                barrier.wait()
                _get(app, vecs[i] + 0.5)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_req)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        snap = app.tracer.snapshot()
        assert len(snap) == n_req
        by_dispatch: dict = {}
        for d in _dispatch_spans(snap):
            by_dispatch.setdefault(d["attrs"]["dispatch_id"], []).append(
                d["attrs"])
        assert by_dispatch, "no dispatch spans attributed"
        coalesced = [v for v in by_dispatch.values() if len(v) > 1]
        assert coalesced, "requests never shared a dispatch"
        total_riders = 0
        for riders in by_dispatch.values():
            total_riders += len(riders)
            device_total = riders[0]["dispatch_device_ms"]
            # the identity: rider device shares sum to the dispatch span
            assert sum(a["device_ms"] for a in riders) == pytest.approx(
                device_total, rel=1e-9)
            # shares over ACTUAL rows (each request here is one row)
            assert len(riders) == riders[0]["actual_rows"]
            assert sum(a["share"] for a in riders) == pytest.approx(
                1.0, rel=1e-6)
            # padding slack is reported, not smeared into the shares
            assert riders[0]["padded_rows"] >= riders[0]["actual_rows"]
            waste = riders[0]["padding_waste"]
            assert waste == pytest.approx(
                1.0 - riders[0]["actual_rows"] / riders[0]["padded_rows"],
                abs=1e-4)
        assert total_riders == n_req  # every request attributed exactly once
    finally:
        app.shutdown()


def test_dispatch_facts_padded_jit_and_queue_wait(tmp_path):
    """A traced request records the dispatch facts: padded width from the
    index's bucket, the first-sighting-of-this-jit-shape bit (True once,
    False after), occupancy, and the lane queue wait."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=30.0)
    try:
        for i in range(2):
            with tracing.request("test", f"q{i}"):
                _get(app, vecs[i] + 0.5)
        d1, d2 = _dispatch_spans(app.tracer.snapshot())
        a1, a2 = d1["attrs"], d2["attrs"]
        assert a1["padded_rows"] == idx.single_local_shard() \
            .vector_index.padded_width(1)
        assert a1["jit_shape_first_seen"] is True
        assert a2["jit_shape_first_seen"] is False  # same (padded, k) shape
        assert a1["coalesced"] is True and a1["lane_requests"] == 1
        # the deadline flush means the lone request waited ~the window
        assert a1["queue_wait_ms"] >= 10.0
        assert {"device_search", "hydrate"} <= {
            c["name"] for c in d1["children"]}
        # snapshot read-plane facts: the generation the dispatch read and
        # its lock wait (0.0 = the lock-free fast path; the import already
        # published, so neither dispatch pays the read-your-writes flush)
        vidx = idx.single_local_shard().vector_index
        assert a1["snapshot_gen"] == vidx.snapshot_gen
        assert a1["lock_wait_ms"] == 0.0 and a2["lock_wait_ms"] == 0.0
    finally:
        app.shutdown()


def test_jit_shape_registered_even_for_untraced_dispatches(tmp_path):
    """Shape registration must see EVERY dispatch while the tracer is up:
    under sampling the compile-paying dispatch is usually unsampled, and
    the next sampled dispatch of the warm shape must NOT read first-seen."""
    app, idx, vecs = _mk_app(tmp_path, coalesce=False)
    try:
        # no request context: rec is None, but the dispatch registers
        idx.object_vector_search(vecs[0] + 0.5, K)
        with tracing.request("test", "q"):
            _get(app, vecs[1] + 0.5)
        d = _dispatch_spans(app.tracer.snapshot())
        assert len(d) == 1
        assert d[0]["attrs"]["jit_shape_first_seen"] is False
    finally:
        app.shutdown()


# -- disabled => zero tracing work on the serving path ------------------------

def test_disabled_serving_path_makes_zero_tracing_calls(tmp_path, monkeypatch):
    """TRACING_ENABLED unset: serving requests (direct AND coalesced paths,
    gRPC end to end) must construct no Span/Trace/DispatchRecord and never
    call Tracer.start_request — spied by replacing the module-global
    classes every call site resolves at call time."""
    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

    app, idx, vecs = _mk_app(tmp_path, tracing_on=False)
    calls = []

    def spy(name):
        def boom(*a, **kw):
            calls.append(name)
            raise AssertionError(f"tracing.{name} touched while disabled")
        return boom

    monkeypatch.setattr(tracing, "Span", spy("Span"))
    monkeypatch.setattr(tracing, "Trace", spy("Trace"))
    monkeypatch.setattr(tracing, "DispatchRecord", spy("DispatchRecord"))
    monkeypatch.setattr(tracing.Tracer, "start_request",
                        spy("Tracer.start_request"))
    srv = GrpcServer(app, port=0, max_workers=8)
    srv.start()
    try:
        assert app.tracer is None
        assert tracing.get_tracer() is None
        # coalesced lane
        res = _get(app, vecs[0] + 0.5)
        assert len(res) == K
        # direct path (coalescer bypass via oversize batched group)
        out = app.traverser.get_class_batched([
            GetParams(class_name="Tr",
                      near_vector={"vector": (vecs[i] + 0.5).tolist()},
                      limit=K)
            for i in range(20)])
        assert not any(isinstance(r, Exception) for r in out)
        # gRPC end to end (the handler wrap + request-id metadata path)
        cl = SearchClient(f"127.0.0.1:{srv.port}")
        try:
            rep = cl.search(pb.SearchRequest(
                class_name="Tr", limit=K,
                near_vector=pb.NearVectorParams(
                    vector=(vecs[1] + 0.5).tolist())))
            assert len(rep.results) == K
        finally:
            cl.close()
        assert calls == []
    finally:
        srv.stop()
        app.shutdown()


def test_unsampled_request_serves_with_no_trace(tmp_path):
    """sample_rate=0: the tracer exists but every request is sampled out —
    serving still works, the ring stays empty, no span context leaks."""
    app, idx, vecs = _mk_app(tmp_path, sample_rate=0.0)
    try:
        with tracing.request("test", "q") as tr:
            assert tr is None
            assert tracing.current_span() is None
            res = _get(app, vecs[0] + 0.5)
        assert len(res) == K
        assert app.tracer.snapshot() == []
    finally:
        app.shutdown()


# -- propagation across every coalescer edge ----------------------------------

def test_bypass_lane_annotates_trace_and_records_direct_dispatch(tmp_path):
    """A cold-filter bypass annotates the trace with the reason AND the
    direct-path dispatch that serves it still records its phase spans
    (including the filter phase)."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        flt = LocalFilter.from_dict(
            {"operator": "Equal", "path": ["tag"], "valueText": "even"})
        with tracing.request("test", "cold") as tr:
            res = _get(app, vecs[0] + 0.5, flt=flt)
        assert len(res) == K
        doc = app.tracer.snapshot()[0]
        spans = list(_walk_spans(doc["root"]))
        tv = [s for s in spans if s["name"] == "traverser.get_class"][0]
        assert tv["attrs"]["coalescer_bypass"] == "cold_filter"
        d = [s for s in spans if s["name"] == "dispatch"]
        assert len(d) == 1 and d[0]["attrs"].get("coalesced") is not True
        assert {"filter", "device_search", "hydrate"} <= {
            c["name"] for c in d[0]["children"]}
        assert doc["duration_ms"] is not None  # root closed
    finally:
        app.shutdown()


def test_wrong_dim_fails_alone_and_lane_mates_attribute(tmp_path):
    """Dim isolation: the malformed request's trace gets the coalescer
    error annotation; its would-be lane-mates still get clean attribution."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        shard = idx.single_local_shard()
        co = QueryCoalescer(window_s=0.05, max_batch=64, max_request_rows=4)
        try:
            waits, traces = [], []

            def submit(vec, name):
                with tracing.request("test", name) as tr:
                    traces.append(tr)
                    return co.submit(shard, vec, K)

            for i in range(3):
                waits.append(submit(vecs[i], f"good{i}"))
            bad_wait = submit(np.zeros(DIM * 2, np.float32), "bad")
            for w in waits:
                assert len(w()) == 1
            with pytest.raises(Exception):
                bad_wait()
            time.sleep(0.1)  # annotation lands before the waiter wakes,
            # but the good lanes' finish() may still be in flight
            docs = {t.name: t.to_dict() for t in traces}
            assert "coalescer_error" in docs["bad"]["root"]["attrs"]
            for i in range(3):
                d = _dispatch_spans([docs[f"good{i}"]])
                assert len(d) == 1
                assert "coalescer_error" not in \
                    docs[f"good{i}"]["root"].get("attrs", {})
        finally:
            co.shutdown()
    finally:
        app.shutdown()


def test_dispatch_error_annotates_and_direct_retry_traces(tmp_path):
    """An injected dispatch failure: the rider trace carries the coalescer
    error AND the retry marker AND the direct dispatch that re-served it —
    the doubled device work is visible, not silent."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=30.0)
    try:
        shard = idx.single_local_shard()
        boom = RuntimeError("injected dispatch failure")

        def exploding(*a, **kw):
            raise boom

        shard.object_vector_search_async = exploding
        try:
            with tracing.request("test", "q") as tr:
                res = _get(app, vecs[0] + 0.5)
            assert len(res) == K  # served by the direct retry
        finally:
            del shard.object_vector_search_async
        doc = app.tracer.snapshot()[0]
        spans = list(_walk_spans(doc["root"]))
        tv = [s for s in spans if s["name"] == "traverser.get_class"][0]
        assert "coalescer_error" in tv["attrs"]
        assert "coalescer_retry_direct" in tv["attrs"]
        d = _dispatch_spans([doc])
        assert len(d) == 1 and d[0]["attrs"].get("coalesced") is not True
    finally:
        app.shutdown()


def test_shutdown_annotates_queued_waiters(tmp_path):
    """Waiters queued at shutdown: the trace records the shutdown, the
    waiter raises, and the request trace still closes."""
    app, idx, vecs = _mk_app(tmp_path)
    try:
        shard = idx.single_local_shard()
        co = QueryCoalescer(window_s=60.0, max_batch=64, max_request_rows=4)
        with tracing.request("test", "q") as tr:
            w = co.submit(shard, vecs[0], K)
            assert w is not None
            co.shutdown()
            with pytest.raises(CoalescerShutdownError):
                w()
        doc = app.tracer.snapshot()[0]
        assert "coalescer_shutdown" in doc["root"]["attrs"]
        assert doc["duration_ms"] is not None
    finally:
        app.shutdown()


# -- exposure surfaces --------------------------------------------------------

def test_debug_traces_endpoint_and_request_id_headers(tmp_path):
    """REST: traceparent honored (trace joins the caller's trace id),
    X-Request-Id echoed on success AND error replies, /debug/traces serves
    the ring behind the data-plane authorizer."""
    import urllib.error
    import urllib.request

    from weaviate_tpu.server.rest import RestServer

    app, idx, vecs = _mk_app(tmp_path)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        gq = ("{ Get { Tr(nearVector: {vector: %s}, limit: 3) "
              "{ _additional { id } } } }" % (vecs[0] + 0.5).tolist())
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/graphql",
            data=json.dumps({"query": gq}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": tp, "X-Request-Id": "rid-42"})
        resp = urllib.request.urlopen(req, timeout=30)
        assert resp.headers.get("X-Request-Id") == "rid-42"
        assert "errors" not in json.loads(resp.read())
        # error envelope carries a (generated) request id too
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/not-a-route", timeout=10)
        assert ei.value.headers.get("X-Request-Id")
        # a traced response EMITS the server's traceparent: same trace id
        # as the inbound header, this server's own (fresh) span id
        resp_tp = tracing.parse_traceparent(resp.headers.get("traceparent"))
        assert resp_tp is not None
        assert resp_tp[0] == "ab" * 16 and resp_tp[1] != "cd" * 8
        dbg = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/traces?limit=5",
            timeout=10).read())
        assert dbg["enabled"] is True and dbg["count"] >= 1
        tr = dbg["traces"][-1]
        assert tr["trace_id"] == "ab" * 16
        assert tr["parent_span_id"] == "cd" * 8
        assert tr["request_id"] == "rid-42"
        assert tr["kind"] == "rest"
        # the graphql span nests under the rest root
        names = {s["name"] for s in _walk_spans(tr["root"])}
        assert {"request", "graphql.get", "traverser.get_class",
                "dispatch"} <= names
    finally:
        srv.stop()
        app.shutdown()


def test_grpc_trailing_request_id_and_trace(tmp_path):
    """gRPC: x-request-id honored and echoed as trailing metadata; the
    trace records kind=grpc with the inbound traceparent's trace id."""
    import grpc

    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server.grpc_server import GrpcServer

    app, idx, vecs = _mk_app(tmp_path)
    srv = GrpcServer(app, port=0, max_workers=8)
    srv.start()
    try:
        tp = "00-" + "12" * 16 + "-" + "34" * 8 + "-01"
        ch = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
        call = ch.unary_unary(
            "/weaviatetpu.v1.Weaviate/Search",
            request_serializer=pb.SearchRequest.SerializeToString,
            response_deserializer=pb.SearchReply.FromString)
        rep, info = call.with_call(
            pb.SearchRequest(class_name="Tr", limit=K,
                             near_vector=pb.NearVectorParams(
                                 vector=(vecs[0] + 0.5).tolist())),
            metadata=(("x-request-id", "grid-9"), ("traceparent", tp)))
        ch.close()
        assert len(rep.results) == K
        md = dict(info.trailing_metadata() or ())
        assert md.get("x-request-id") == "grid-9"
        out_tp = tracing.parse_traceparent(md.get("traceparent"))
        assert out_tp is not None and out_tp[0] == "12" * 16
        doc = app.tracer.snapshot()[-1]
        assert doc["kind"] == "grpc"
        assert doc["trace_id"] == "12" * 16
        assert doc["request_id"] == "grid-9"
    finally:
        srv.stop()
        app.shutdown()


def test_slow_query_log_emits_full_span_tree(tmp_path, caplog):
    """A trace over the threshold logs ONE structured JSON line with the
    whole span tree on the weaviate_tpu.slowquery logger."""
    app, idx, vecs = _mk_app(tmp_path, slow_ms=0.0001)
    try:
        with caplog.at_level(logging.WARNING, logger="weaviate_tpu.slowquery"):
            with tracing.request("test", "slow-one"):
                _get(app, vecs[0] + 0.5)
        lines = [r.getMessage() for r in caplog.records
                 if r.name == "weaviate_tpu.slowquery"]
        assert len(lines) == 1
        doc = json.loads(lines[0])
        assert doc["slow_query"] is True and doc["name"] == "slow-one"
        assert any(s["name"] == "dispatch"
                   for s in _walk_spans(doc["root"]))
    finally:
        app.shutdown()


def test_ring_buffer_is_bounded(tmp_path):
    app, idx, vecs = _mk_app(tmp_path, ring_size=4, window_ms=10.0)
    try:
        for i in range(9):
            with tracing.request("test", f"q{i}"):
                _get(app, vecs[i] + 0.5)
        snap = app.tracer.snapshot()
        assert len(snap) == 4
        assert [t["name"] for t in snap] == ["q5", "q6", "q7", "q8"]
    finally:
        app.shutdown()


def test_trace_metrics_exposed(tmp_path):
    """Exemplar counters land in the app's Metrics registry."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=10.0)
    try:
        with tracing.request("test", "q"):
            _get(app, vecs[0] + 0.5)
        text = app.metrics.expose().decode()
        assert 'weaviate_traces_total{kind="test",outcome="ok"} 1.0' in text
        assert 'weaviate_trace_phase_ms_count{phase="device_search"} 1.0' \
            in text
        assert 'weaviate_trace_phase_ms_count{phase="queue_wait"} 1.0' in text
        assert 'weaviate_trace_dispatch_rows_total{kind="actual"} 1.0' in text
        assert 'weaviate_trace_dispatch_rows_total{kind="padded"} 1.0' in text
    finally:
        app.shutdown()


def test_tracing_config_env_parsing():
    from weaviate_tpu.config import ConfigError, load_config

    cfg = load_config({
        "TRACING_ENABLED": "true",
        "TRACING_SAMPLE_RATE": "0.25",
        "TRACING_RING_SIZE": "64",
        "SLOW_QUERY_THRESHOLD_MS": "250",
    })
    assert cfg.tracing.enabled is True
    assert cfg.tracing.sample_rate == 0.25
    assert cfg.tracing.ring_size == 64
    assert cfg.tracing.slow_query_threshold_ms == 250.0
    assert load_config({}).tracing.enabled is False
    with pytest.raises(ConfigError):
        load_config({"TRACING_SAMPLE_RATE": "1.5"})
    with pytest.raises(ConfigError):
        load_config({"TRACING_RING_SIZE": "0"})


def test_request_id_cleaning_blocks_header_injection():
    """An inbound X-Request-Id is echoed into a response header: CR/LF and
    non-printables must never survive, and an empty/garbage id is replaced
    with a generated one."""
    assert tracing.clean_request_id("abc-123") == "abc-123"
    assert tracing.clean_request_id(
        "evil\r\nSet-Cookie: x=1") == "evilSet-Cookie:x=1"
    assert len(tracing.clean_request_id("x" * 500)) == 128
    for empty in (None, "", "   ", "\r\n"):
        rid = tracing.clean_request_id(empty)
        assert rid and len(rid) == 32  # generated


def test_traceparent_parsing_rejects_malformed():
    good = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    assert tracing.parse_traceparent(good) == ("ab" * 16, "cd" * 8, "01")
    for bad in (None, "", "garbage", "00-xyz-abc-01",
                "01-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # wrong version
                "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",   # zero trace id
                "00-" + "ab" * 16 + "-" + "0" * 16 + "-01"):  # zero parent
        assert tracing.parse_traceparent(bad) is None
