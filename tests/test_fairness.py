"""Multi-tenant fairness and isolation (the PR-6 tentpole): tenant
identity end to end, weighted-fair admission (DRR + per-tenant budgets +
per-tenant shed estimates), bounded-cardinality per-tenant metrics, the
allowList cache's per-tenant share bound, and the abusive-tenant storm
journey on the fault harness.

Journeys run against the REAL serving stack (App + coalescer + shard +
index) like tests/test_robustness.py; timing assertions are deliberately
loose functional bounds (the tight 2x-p99 isolation claim is bench.py
--tenants' job on a quiet host, not a shared CI runner's).
"""

import http.client
import json
import logging
import threading
import time
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import Config
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.monitoring import tracing
from weaviate_tpu.monitoring.metrics import TenantLabeler, noop_metrics
from weaviate_tpu.serving import robustness
from weaviate_tpu.testing import faults
from weaviate_tpu.usecases.traverser import GetParams

N, DIM, K = 300, 16, 5


@pytest.fixture(autouse=True)
def _reset_globals():
    """Tests install process-global tracers/metrics; never leak across."""
    yield
    tracing.configure(None)


# -- unit: tenant identity ----------------------------------------------------


def test_validate_tenant_id_accepts_and_rejects():
    assert robustness.validate_tenant_id(None) is None
    assert robustness.validate_tenant_id("") is None
    assert robustness.validate_tenant_id("  ") is None
    assert robustness.validate_tenant_id(" acme-prod_1 ") == "acme-prod_1"
    for bad in ("two words", "crlf\r\nInjected: 1", "tab\there",
                "bß", "x" * 65,
                # reserved system identities: "other" is the aggregate
                # metric bucket, "multi" the merged-dispatch trace tag —
                # a client claiming either would hide inside the aggregate
                "other", "Multi"):
        with pytest.raises(ValueError):
            robustness.validate_tenant_id(bad)


def test_tenant_scope_and_effective_tenant():
    assert robustness.current_tenant() is None
    # no explicit identity: the queried class name is the accounting key
    assert robustness.effective_tenant("Cls") == "Cls"
    with robustness.tenant_scope("t1"):
        assert robustness.current_tenant() == "t1"
        assert robustness.effective_tenant("Cls") == "t1"
        with robustness.tenant_scope(None):  # None scope = no-op
            assert robustness.current_tenant() == "t1"
    assert robustness.current_tenant() is None


# -- unit: bounded tenant labels ----------------------------------------------


def test_tenant_labeler_top_k_plus_other():
    lab = TenantLabeler(top_k=2)
    assert lab.observe("a") == "a"
    assert lab.observe("b") == "b"
    assert lab.observe("c") == "other"     # set full, c is not heavier
    assert lab.label_for("a") == "a" and lab.label_for("c") == "other"
    # c becomes genuinely heavy: it displaces the weakest labeled tenant
    for _ in range(10):
        last = lab.observe("c")
    assert last == "c"
    assert lab.label_for("c") == "c"
    assert "other" in (lab.label_for("a"), lab.label_for("b"))


def test_tenant_labeler_lifetime_cardinality_and_memory_bounded():
    lab = TenantLabeler(top_k=4, max_tracked=64)
    seen = set()
    for i in range(1000):
        t = f"tenant-{i}"
        # escalating traffic so promotion pressure is constant
        for _ in range(i % 7 + 1):
            seen.add(lab.observe(t))
    # lifetime label values are hard-capped at 3*top_k (+ "other")
    assert len(seen) <= 3 * 4 + 1 and "other" in seen
    assert len(lab._counts) <= 64 + 4  # pruned to max_tracked + labeled


def test_metrics_cardinality_bounded_under_1k_distinct_tenants():
    """1000 distinct tenant ids shedding through the robustness helpers
    mint a bounded set of label values in the exposition, not 1000."""
    m = noop_metrics()
    robustness.set_metrics(m)
    try:
        for i in range(1000):
            robustness.count_tenant_shed(f"t{i}", "queue_full")
            robustness.count_tenant_deadline(f"t{i}")
        exposed = m.expose().decode()
        labels = set()
        for line in exposed.splitlines():
            if line.startswith("weaviate_tenant_requests_shed_total{"):
                for part in line.split("{", 1)[1].split("}")[0].split(","):
                    k, _, v = part.partition("=")
                    if k == "tenant":
                        labels.add(v.strip('"'))
        top_k = m.tenant_labels.top_k
        assert 0 < len(labels) <= 3 * top_k + 1
        assert "other" in labels
    finally:
        robustness.unset_metrics(m)


# -- fixtures -----------------------------------------------------------------


def _mk_app(tmp_path, *, coalesce=True, window_ms=30.0, max_queued_rows=4096,
            fraction=0.5, weights=None, wait_timeout_s=30.0,
            max_request_rows=16, tracing_on=False, slow_ms=0.0, n=N):
    from weaviate_tpu.server import App

    cfg = Config()
    cfg.coalescer.enabled = coalesce
    cfg.coalescer.window_ms = window_ms
    cfg.coalescer.max_queued_rows = max_queued_rows
    cfg.coalescer.max_request_rows = max_request_rows
    cfg.coalescer.wait_timeout_s = wait_timeout_s
    cfg.tenancy.max_queued_rows_fraction = fraction
    cfg.tenancy.weights = dict(weights or {})
    cfg.tracing.enabled = tracing_on
    cfg.tracing.slow_query_threshold_ms = slow_ms
    app = App(config=cfg, data_path=str(tmp_path / "data"))
    app.schema.add_class({
        "class": "Fa", "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "tag", "dataType": ["text"]}],
    })
    rng = np.random.default_rng(29)
    vecs = rng.integers(-8, 8, (n, DIM)).astype(np.float32)
    idx = app.db.get_index("Fa")
    idx.put_batch([
        StorObj(class_name="Fa", uuid=str(uuidlib.UUID(int=i + 1)),
                properties={"tag": "even" if i % 2 == 0 else "odd"},
                vector=vecs[i])
        for i in range(n)])
    return app, idx, vecs


def _get(app, vec, limit=K):
    return app.traverser.get_class(GetParams(
        class_name="Fa", near_vector={"vector": vec.tolist()}, limit=limit))


# -- weighted-fair admission --------------------------------------------------


def test_lane_key_includes_tenant_and_default_is_class_name(tmp_path):
    """Two tenants' identical queries land in SEPARATE lanes (isolation);
    anonymous requests account to the class name."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=5000.0)
    try:
        co = app.coalescer
        shard = idx.single_local_shard()
        assert co.submit(shard, vecs[0], K) is not None
        with robustness.tenant_scope("paying-tenant"):
            assert co.submit(shard, vecs[1], K) is not None
        with co._lock:
            tenants = sorted(ln.tenant for ln in co._lanes.values())
        assert tenants == ["Fa", "paying-tenant"]
        st = co.stats()["tenants"]
        assert st["Fa"]["rows_in_system"] == 1
        assert st["paying-tenant"]["rows_in_system"] == 1
    finally:
        app.shutdown()


def test_drr_order_honors_weights(tmp_path):
    """Deficit-round-robin drains due lanes 2:1 for a weight-2 tenant."""
    from weaviate_tpu.serving.coalescer import _Lane

    app, idx, vecs = _mk_app(tmp_path, weights={"heavy": 2.0})
    try:
        co = app.coalescer

        def lane(tenant, rows):
            ln = _Lane(None, None, None, K, False, 0.0, tenant=tenant,
                       tenant_label=tenant)
            ln.rows = rows
            return ln

        due = [lane("heavy", co.max_batch) for _ in range(4)] \
            + [lane("light", co.max_batch) for _ in range(4)]
        with co._lock:
            co._drr_cursor = 0
            order = [ln.tenant for ln in co._drr_order(due)]
        # per DRR round: heavy's deficit covers 2 full lanes, light's 1
        assert order == ["heavy", "heavy", "light", "heavy", "heavy",
                         "light", "light", "light"]
        # rotation start advances across cycles: the same tenant does not
        # structurally go first every flush
        due2 = [lane("heavy", co.max_batch), lane("light", co.max_batch)]
        with co._lock:
            order2 = [ln.tenant for ln in co._drr_order(due2)]
        assert order2[0] == "light"
        # single-tenant due lists keep FIFO order untouched
        due3 = [lane("only", 1), lane("only", 2), lane("only", 3)]
        with co._lock:
            assert [ln.rows for ln in co._drr_order(due3)] == [1, 2, 3]
    finally:
        app.shutdown()


def test_tenant_budget_sheds_abuser_not_light(tmp_path):
    """With other tenants waiting, a tenant beyond its row-budget share
    sheds (`tenant_budget`) while the others keep admitting; alone, the
    same tenant may use the whole queue."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=5000.0, max_queued_rows=8,
                             fraction=0.5, max_request_rows=2)
    try:
        co = app.coalescer
        shard = idx.single_local_shard()
        assert co._tenant_row_cap == 4
        with robustness.tenant_scope("abuser"):
            for i in range(4):
                assert co.submit(shard, vecs[i], K) is not None
            # no one else waiting: the cap does NOT fire (a lone tenant
            # may fill the queue)
            assert co.submit(shard, vecs[4], K) is not None
        with robustness.tenant_scope("light"):
            assert co.submit(shard, vecs[5], K) is not None
        with robustness.tenant_scope("abuser"):
            with pytest.raises(robustness.OverloadedError) as ei:
                co.submit(shard, vecs[6], K)
            assert "tenant_budget" in str(ei.value)
        # the light tenant still admits against ITS budget
        with robustness.tenant_scope("light"):
            assert co.submit(shard, vecs[7], K) is not None
        st = co.stats()
        assert st["tenants"]["abuser"]["shed"] == {"tenant_budget": 1}
        assert st["tenants"]["light"]["shed"] == {}
        # per-tenant accounting is visible in /metrics under the bounded
        # tenant labels (the satellite contract)
        exposed = app.metrics.expose().decode()
        assert ('weaviate_tenant_requests_shed_total'
                '{reason="tenant_budget",tenant="abuser"} 1.0') in exposed
        assert 'tenant="light"' in exposed  # admitted-requests counter
    finally:
        app.shutdown()


def test_per_tenant_shed_estimate_spares_light_tenants(tmp_path):
    """Deadline-unreachable shedding uses the TENANT'S own backlog: a
    deadline request from a tenant with an empty queue admits even while
    another tenant has a deep backlog (the old global estimate shed it)."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=5000.0, max_queued_rows=64,
                             fraction=1.0)
    try:
        co = app.coalescer
        shard = idx.single_local_shard()
        with co._lock:
            # a known drain rate so the estimator is armed: 100 rows/s
            co._ewma_rows_per_s = 100.0
            co._tenant_state("abuser").ewma_rows_per_s = 100.0
        with robustness.tenant_scope("abuser"):
            for i in range(40):
                assert co.submit(shard, vecs[i % 8], K) is not None
            # 40 rows / 100 rows/s = 400 ms backlog >> a 50 ms deadline
            with robustness.deadline_scope(50.0):
                with pytest.raises(robustness.OverloadedError) as ei:
                    co.submit(shard, vecs[0], K)
            assert "deadline_unreachable" in str(ei.value)
        # same deadline, different tenant, empty backlog: admits
        with robustness.tenant_scope("light"):
            with robustness.deadline_scope(50.0):
                assert co.submit(shard, vecs[1], K) is not None
    finally:
        app.shutdown()


# -- tenant tags: REST -> trace -> slow-query log -----------------------------


def _rest(port, method, path, body=None, headers=None, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        data = json.dumps(body).encode() if body is not None else None
        hdrs = {"Content-Type": "application/json", **(headers or {})}
        conn.request(method, path, body=data, headers=hdrs)
        resp = conn.getresponse()
        payload = resp.read()
        return resp.status, dict(resp.getheaders()), \
            json.loads(payload) if payload else None
    finally:
        conn.close()


def _gql_near(vec):
    return ('{ Get { Fa(limit: %d, nearVector: {vector: %s}) '
            '{ tag _additional { distance } } } }'
            % (K, json.dumps([float(x) for x in vec])))


def test_tenant_tag_propagates_rest_to_trace_to_slow_log(tmp_path, caplog):
    """X-Tenant-Id rides the contextvar into the trace root, the
    coalescer admission annotation, the dispatch record — and lands in
    the slow-query JSON line, so 'whose query was slow' is answerable."""
    from weaviate_tpu.server import RestServer

    app, idx, vecs = _mk_app(tmp_path, tracing_on=True, slow_ms=0.0001)
    srv = RestServer(app, port=0)
    srv.start()
    try:
        with caplog.at_level(logging.WARNING,
                             logger="weaviate_tpu.slowquery"):
            st, hdrs, out = _rest(
                srv.port, "POST", "/v1/graphql",
                {"query": _gql_near(vecs[0])},
                headers={"X-Tenant-Id": "tenant-42"})
            assert st == 200 and "errors" not in out
        traces = app.tracer.snapshot()
        mine = [t for t in traces
                if t["root"].get("attrs", {}).get("tenant") == "tenant-42"]
        assert mine, f"no trace tagged tenant-42 in {len(traces)} traces"
        # the tag reaches span level too (admission annotation or the
        # graphql.get span), not just the root attr
        def walk(s):
            yield s
            for c in s.get("children", []):
                yield from walk(c)
        spans = list(walk(mine[-1]["root"]))
        assert any(s.get("attrs", {}).get("tenant") == "tenant-42"
                   for s in spans)
        # the slow log is emitted by Tracer.finish on the HANDLER thread
        # AFTER the ring append (and possibly after the response was
        # read), so the record can trail the snapshot() above — poll
        # briefly instead of racing it
        deadline = time.monotonic() + 5.0
        lines: list = []
        while not lines and time.monotonic() < deadline:
            lines = [r.getMessage() for r in caplog.records
                     if r.name == "weaviate_tpu.slowquery"]
            if not lines:
                time.sleep(0.02)
        assert lines
        docs = [json.loads(ln) for ln in lines]
        assert any(d["root"].get("attrs", {}).get("tenant") == "tenant-42"
                   for d in docs)
    finally:
        srv.stop()
        app.shutdown()


def test_tenant_header_injection_rejected(tmp_path):
    """An injection-shaped X-Tenant-Id is REJECTED (400), never cleaned
    into an accounting key; gRPC metadata gets INVALID_ARGUMENT."""
    import grpc

    from weaviate_tpu.grpcapi import weaviate_pb2 as pb
    from weaviate_tpu.server import RestServer
    from weaviate_tpu.server.grpc_server import GrpcServer, SearchClient

    app, idx, vecs = _mk_app(tmp_path)
    srv = RestServer(app, port=0)
    srv.start()
    gsrv = GrpcServer(app, port=0)
    gsrv.start()
    cl = SearchClient(f"127.0.0.1:{gsrv.port}")
    try:
        st, _, out = _rest(srv.port, "POST", "/v1/graphql",
                           {"query": _gql_near(vecs[0])},
                           headers={"X-Tenant-Id": "two words"})
        assert st == 400
        assert "tenant" in out["error"][0]["message"]
        st, _, _ = _rest(srv.port, "POST", "/v1/graphql",
                         {"query": _gql_near(vecs[0])},
                         headers={"X-Tenant-Id": "x" * 65})
        assert st == 400
        # a VALID tenant header serves normally
        st, _, out = _rest(srv.port, "POST", "/v1/graphql",
                           {"query": _gql_near(vecs[0])},
                           headers={"X-Tenant-Id": "fine-1"})
        assert st == 200 and "errors" not in out
        req = pb.SearchRequest(
            class_name="Fa", limit=K,
            near_vector=pb.NearVectorParams(vector=vecs[0].tolist()))
        with pytest.raises(grpc.RpcError) as ei:
            cl.search(req, metadata=(("x-tenant-id", "two words"),))
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        rep = cl.search(req, metadata=(("x-tenant-id", "fine-2"),))
        assert len(rep.results) == K
    finally:
        cl.close()
        gsrv.stop()
        srv.stop()
        app.shutdown()


# -- allowList cache: per-tenant share bound ----------------------------------


def test_allow_cache_bounds_each_tenants_share(tmp_path):
    """An abusive tenant issuing unique filters evicts ITS OWN oldest
    entries once it dominates the cache — another tenant's hot entry
    survives a 20-unique-filter storm (the old global LRU evicted it)."""
    from weaviate_tpu.db.shard import Shard, filter_signature
    from weaviate_tpu.entities.filters import LocalFilter
    from weaviate_tpu.entities.schema import ClassDef, Property
    from weaviate_tpu.entities.vectorindex import parse_and_validate_config

    cd = ClassDef(name="Ten", properties=[
        Property(name="n", data_type=["int"]),
    ], vector_index_type="hnsw_tpu")
    shard = Shard("s0", str(tmp_path / "ten"), cd,
                  parse_and_validate_config(
                      "hnsw_tpu", {"distance": "l2-squared"}))
    try:
        rng = np.random.default_rng(1)
        shard.put_batch([
            StorObj(class_name="Ten", uuid=str(uuidlib.UUID(int=i + 1)),
                    properties={"n": i},
                    vector=rng.standard_normal(DIM).astype(np.float32))
            for i in range(40)])

        def flt(i):
            return LocalFilter.from_dict(
                {"operator": "Equal", "path": ["n"], "valueInt": i})

        with robustness.tenant_scope("victim"):
            hot = shard.build_allow_list(flt(0))
        # the abusive tenant floods the 16-entry cache with unique filters
        with robustness.tenant_scope("abuser"):
            for i in range(1, 21):
                shard.build_allow_list(flt(i))
        # the victim's entry SURVIVED (same cached Bitmap object), and the
        # abuser's share is bounded at the cache cap minus other tenants
        assert filter_signature(flt(0)) in shard._allow_cache
        with robustness.tenant_scope("victim"):
            assert shard.build_allow_list(flt(0)) is hot
        owners = [t for (_, _, t) in shard._allow_cache.values()]
        assert owners.count("abuser") <= 15
        assert owners.count("victim") == 1
        # single-tenant behavior is untouched plain LRU (pinned by
        # tests/test_snapshot_reads.py::test_allow_cache_lru_eviction_order)
    finally:
        shard.shutdown()


def test_coalesced_filtered_allow_cache_attributes_lane_tenant(tmp_path):
    """A coalesced FILTERED dispatch builds its allowList on the dispatch
    pool, where the request's ContextVars don't follow — the lane's
    explicit tenant handoff must attribute the cache entry to the
    submitting tenant, not the class-name fallback (mis-attribution
    would pool every coalesced entry under one bucket and void the
    per-tenant share bound)."""
    from weaviate_tpu.db.shard import filter_signature
    from weaviate_tpu.entities.filters import LocalFilter

    app, idx, vecs = _mk_app(tmp_path, window_ms=40.0)
    try:
        shard = idx.single_local_shard()
        flt = LocalFilter.from_dict({
            "path": ["tag"], "operator": "Equal", "valueText": "even"})
        with robustness.tenant_scope("filt-tenant"):
            # first sighting: cold signature bypasses (direct path,
            # serving thread) and warms the recency map
            app.traverser.get_class(GetParams(
                class_name="Fa", filters=flt,
                near_vector={"vector": vecs[0].tolist()}, limit=K))
            # invalidate the cached entry so the next query REBUILDS it
            shard.put_batch([StorObj(
                class_name="Fa", uuid=str(uuidlib.UUID(int=9000)),
                properties={"tag": "odd"}, vector=vecs[1])])
            # hot signature now queues: the allowList is rebuilt on the
            # dispatch pool under the lane's tenant scope
            app.traverser.get_class(GetParams(
                class_name="Fa", filters=flt,
                near_vector={"vector": vecs[2].tolist()}, limit=K))
        entry = shard._allow_cache.get(filter_signature(flt))
        assert entry is not None
        assert entry[2] == "filt-tenant", entry[2]
    finally:
        app.shutdown()


# -- fault point + abusive-tenant storm journey -------------------------------


def test_admit_fault_point_fires_before_queue_state(tmp_path):
    """serving.coalescer.admit: an injected failure at admission raises to
    the caller and strands nothing (no queued rows, no tenant rows)."""
    app, idx, vecs = _mk_app(tmp_path, window_ms=5000.0)
    inj = faults.configure(faults.FaultInjector())
    try:
        co = app.coalescer
        shard = idx.single_local_shard()
        inj.plan("serving.coalescer.admit", "device_error", times=1)
        with pytest.raises(faults.InjectedDeviceError):
            co.submit(shard, vecs[0], K)
        assert inj.fired("serving.coalescer.admit") == 1
        with co._lock:
            assert co._queued_rows == 0 and not co._lanes
        # the next admission serves normally
        assert co.submit(shard, vecs[0], K) is not None
    finally:
        faults.unconfigure(inj)
        app.shutdown()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_abusive_tenant_storm_light_tenants_stay_isolated(tmp_path):
    """The acceptance journey scaled to tier-1: an abusive tenant floods
    the admission queue while the fault harness slows every lane dispatch
    (a seeded storm). Light tenants: every request completes correctly,
    ZERO of them shed, and their p99 stays under a loose absolute bound —
    while the abusive tenant absorbs the shedding on its own label."""
    # cap = max(int(16 * 0.125), max_request_rows) = 2 queued rows for any
    # one tenant while others wait — far below the 10 abusive in-flight
    # requests, so the abuser structurally MUST shed while light traffic
    # is live
    app, idx, vecs = _mk_app(tmp_path, window_ms=5.0, max_queued_rows=16,
                             fraction=0.125, max_request_rows=2,
                             wait_timeout_s=20.0)
    inj = faults.configure(faults.FaultInjector(seed=31))
    try:
        # the storm: every coalesced lane dispatch stalls 15 ms — queue
        # pressure without device flakiness, deterministic via the seed
        inj.plan("serving.coalescer.dispatch", "stall", times=None,
                 stall_s=0.015)
        expected = {i: [(r.obj.uuid, r.distance) for r in _get(app, vecs[i])]
                    for i in range(4)}

        stop = threading.Event()
        abusive_out = {"ok": 0, "shed": 0, "other": 0}
        ab_lock = threading.Lock()

        def abuse(tid):
            rng = np.random.default_rng(tid)
            with robustness.tenant_scope("abuser"):
                while not stop.is_set():
                    qi = int(rng.integers(0, 4))
                    try:
                        _get(app, vecs[qi])
                        key = "ok"
                    except robustness.OverloadedError:
                        key = "shed"
                        time.sleep(0.001)  # don't starve the 2-core host
                    except Exception:  # noqa: BLE001 — outcome accounting
                        key = "other"
                    with ab_lock:
                        abusive_out[key] += 1

        PER = 10
        light_lat = {"light-1": [], "light-2": []}
        light_err = []

        def light(tenant):
            with robustness.tenant_scope(tenant):
                for j in range(PER):
                    qi = j % 4
                    t0 = time.monotonic()
                    try:
                        got = [(r.obj.uuid, r.distance)
                               for r in _get(app, vecs[qi])]
                        if got != expected[qi]:
                            light_err.append((tenant, "wrong answer"))
                    except Exception as e:  # noqa: BLE001 — recorded
                        light_err.append((tenant, f"{type(e).__name__}: {e}"))
                    light_lat[tenant].append(time.monotonic() - t0)
                    time.sleep(0.005)

        abusers = [threading.Thread(target=abuse, args=(i,), daemon=True)
                   for i in range(10)]
        lights = [threading.Thread(target=light, args=(t,))
                  for t in light_lat]
        # lights first: their queued rows make "others are waiting" true
        # from the abusive burst's very first submit — the budget cap
        # (2 rows) then sheds the 10-deep abusive burst structurally
        for t in lights:
            t.start()
        time.sleep(0.1)
        for t in abusers:
            t.start()
        for t in lights:
            t.join(timeout=60)
        stop.set()
        for t in abusers:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in abusers + lights), "hang"

        # light tenants: complete, correct, unshed
        assert light_err == []
        assert all(len(v) == PER for v in light_lat.values())
        st = app.coalescer.stats()
        for t in light_lat:
            assert sum(st["tenants"].get(t, {}).get(
                "shed", {}).values()) == 0, st["tenants"]
        # the abuser absorbed real shedding on ITS label
        ab_shed = sum(st["tenants"]["abuser"]["shed"].values())
        assert abusive_out["shed"] > 0 and ab_shed > 0
        assert abusive_out["other"] == 0
        # loose absolute tail bound: stalled dispatches are 15 ms and the
        # abuser's backlog is budget-capped, so a light request never
        # waits out a deep queue (CI-safe bound, not the bench's 2x gate)
        for t, lat in light_lat.items():
            p99 = float(np.percentile(np.asarray(lat), 99))
            assert p99 < 5.0, f"{t} p99 {p99:.2f}s under storm"
        exposed = app.metrics.expose().decode()
        assert 'weaviate_tenant_requests_shed_total' in exposed
        assert 'tenant="abuser"' in exposed
    finally:
        faults.unconfigure(inj)
        app.shutdown()


# -- config surface -----------------------------------------------------------


def test_tenancy_config_parsing_and_validation():
    from weaviate_tpu.config.config import ConfigError, load_config

    cfg = load_config({"TENANT_WEIGHTS": "acme=4, beta=2.5",
                       "TENANT_MAX_QUEUED_ROWS_FRACTION": "0.25",
                       "TENANT_METRICS_TOP_K": "5"})
    assert cfg.tenancy.weights == {"acme": 4.0, "beta": 2.5}
    assert cfg.tenancy.max_queued_rows_fraction == 0.25
    assert cfg.tenancy.metrics_top_k == 5
    for bad in ({"TENANT_WEIGHTS": "noweight"},
                {"TENANT_WEIGHTS": "a=zero"},
                {"TENANT_WEIGHTS": "a=-1"},
                {"TENANT_MAX_QUEUED_ROWS_FRACTION": "0"},
                {"TENANT_MAX_QUEUED_ROWS_FRACTION": "1.5"},
                {"TENANT_METRICS_TOP_K": "0"}):
        with pytest.raises(ConfigError):
            load_config(bad)


# -- bench_matrix satellite: stale rows + rc=3 preservation -------------------


def test_merge_matrix_marks_legacy_rows_stale_true(tmp_path, monkeypatch):
    import bench

    mfile = tmp_path / "m.json"
    monkeypatch.setattr(bench, "MATRIX_FILE", str(mfile))
    monkeypatch.setattr(bench, "_MATRIX_PREIMAGE", None)
    monkeypatch.setenv("BENCH_GATE", "0")
    mfile.write_text(json.dumps({
        "legacy_tpu": {"qps": 5.0},                      # pre-provenance
        "live_cpu": {"backend": "cpu", "qps": 100.0},
    }))
    data = bench._merge_matrix({"new_row": {"backend": "cpu", "qps": 1.0}})
    assert data["legacy_tpu"]["stale"] is True
    assert "stale_note" in data["legacy_tpu"]
    assert data["legacy_tpu"]["backend"] == "tpu-v5e"
    assert "stale" not in data["live_cpu"]


def test_rc3_unreachable_exit_never_overwrites_live_rows(tmp_path,
                                                         monkeypatch):
    """The preimage restore: a session that overwrote a live row and then
    hit the rc=3 unreachable-device exit puts the live row back; rows it
    newly ADDED survive (they were measured before the device died)."""
    import bench

    mfile = tmp_path / "m.json"
    monkeypatch.setattr(bench, "MATRIX_FILE", str(mfile))
    monkeypatch.setattr(bench, "_MATRIX_PREIMAGE", None)
    monkeypatch.setenv("BENCH_GATE", "0")
    live = {"backend": "tpu-v5e", "round": 6, "qps": 777.0}
    stale = {"backend": "tpu-v5e", "round": 2, "stale": True, "qps": 1.0}
    mfile.write_text(json.dumps({"headline_tpu": live,
                                 "old_tpu": stale}))
    bench._merge_matrix({
        "headline_tpu": {"backend": "tpu-v5e", "round": 7, "qps": 3.0},
        "fresh_row": {"backend": "tpu-v5e", "round": 7, "qps": 9.0},
        "old_tpu": {"backend": "tpu-v5e", "round": 7, "qps": 8.0},
    })
    restored = bench._restore_live_rows()
    assert restored == ["headline_tpu"]
    on_disk = json.loads(mfile.read_text())
    assert on_disk["headline_tpu"] == live        # live history restored
    assert on_disk["fresh_row"]["qps"] == 9.0     # new keys kept
    assert on_disk["old_tpu"]["qps"] == 8.0       # stale rows replaceable


def test_probe_device_failure_restores_then_exits_rc3(tmp_path, monkeypatch):
    import subprocess
    import sys
    import types

    import bench

    mfile = tmp_path / "m.json"
    monkeypatch.setattr(bench, "MATRIX_FILE", str(mfile))
    monkeypatch.setattr(bench, "_MATRIX_PREIMAGE", None)
    monkeypatch.setenv("BENCH_GATE", "0")
    live = {"backend": "tpu-v5e", "round": 6, "qps": 42.0}
    mfile.write_text(json.dumps({"row_tpu": live}))
    bench._merge_matrix({"row_tpu": {"backend": "tpu-v5e", "qps": 0.1}})
    fake_jax = types.SimpleNamespace(
        config=types.SimpleNamespace(jax_platforms="tpu"))
    monkeypatch.setitem(sys.modules, "jax", fake_jax)
    monkeypatch.setattr(subprocess, "run", lambda *a, **k: (_ for _ in ()).throw(
        subprocess.TimeoutExpired(cmd="probe", timeout=1)))
    # the rc=3 exit also dumps an incident bundle (PR-10 satellite):
    # route it into the test tmp dir, not the checkout's cwd
    inc_dir = tmp_path / "incidents"
    monkeypatch.setenv("INCIDENT_DIR", str(inc_dir))
    with pytest.raises(SystemExit) as ei:
        bench._probe_device(timeout_s=1)
    assert ei.value.code == 3
    assert json.loads(mfile.read_text())["row_tpu"] == live
    bundles = list(inc_dir.glob("incident-*.json"))
    assert len(bundles) == 1  # the dying session preserved its evidence
    doc = json.loads(bundles[0].read_text())
    assert doc["incident"]["class"] == "bench"
    assert "unreachable device" in doc["incident"]["reason"]
