"""Product quantization: encoders, LUT math, compressed index search.

Reference test model: ssdhelpers/product_quantization_test.go (encode/decode
roundtrip, LUT distance vs exact), hnsw recall_test.go:137 (recall bar).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from weaviate_tpu.compress.pq import ProductQuantizer, build_lut, lut_scan_block
from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.tpu import TpuVectorIndex


def _cfg(**pq_kwargs):
    d = {"distance": "l2-squared"}
    if pq_kwargs:
        d["pq"] = pq_kwargs
    return vi.HnswUserConfig.from_dict(d)


@pytest.fixture()
def data():
    rng = np.random.default_rng(7)
    # clustered data so PQ codebooks have structure to find
    centers = rng.standard_normal((8, 32)) * 5.0
    x = centers[rng.integers(0, 8, 2000)] + rng.standard_normal((2000, 32))
    return x.astype(np.float32)


def test_kmeans_roundtrip_error(data):
    pq = ProductQuantizer(dim=32, segments=8, centroids=64, metric="l2-squared")
    pq.fit(data)
    codes = pq.encode(data)
    assert codes.shape == (2000, 8) and codes.dtype == np.uint8
    recon = pq.decode(codes)
    # quantization must beat the trivial all-mean reconstruction by a lot
    mse = np.mean((recon - data) ** 2)
    mse_mean = np.mean((data - data.mean(0)) ** 2)
    assert mse < 0.25 * mse_mean


def test_tile_encoder_roundtrip(data):
    pq = ProductQuantizer(
        dim=32, segments=32, centroids=32, metric="l2-squared",
        encoder=vi.PQ_ENCODER_TILE, distribution=vi.PQ_DISTRIBUTION_NORMAL)
    pq.fit(data)
    recon = pq.decode(pq.encode(data))
    mse = np.mean((recon - data) ** 2)
    mse_mean = np.mean((data - data.mean(0)) ** 2)
    assert mse < 0.25 * mse_mean


def test_tile_requires_scalar_segments():
    with pytest.raises(vi.ConfigValidationError):
        ProductQuantizer(dim=32, segments=8, centroids=16, metric="l2-squared",
                         encoder=vi.PQ_ENCODER_TILE)


def test_lut_distance_matches_decoded_distance(data):
    """Asymmetric LUT-sum distance == exact distance to the decoded vector
    (the defining property of the reference's DistanceLookUpTable)."""
    pq = ProductQuantizer(dim=32, segments=8, centroids=64, metric="l2-squared")
    pq.fit(data)
    codes = pq.encode(data[:128])
    q = data[500:504]
    lut = build_lut(jnp.asarray(q), jnp.asarray(pq.codebook), "l2-squared")
    d_lut = np.asarray(lut_scan_block(jnp.asarray(codes.astype(np.int32)), lut))
    recon = pq.decode(codes)
    d_exact = ((q[:, None, :] - recon[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(d_lut, d_exact, rtol=1e-3, atol=1e-2)


def test_lut_dot_and_cosine(data):
    pq = ProductQuantizer(dim=32, segments=8, centroids=64, metric="dot")
    pq.fit(data)
    codes = pq.encode(data[:64])
    q = data[100:102]
    lut = build_lut(jnp.asarray(q), jnp.asarray(pq.codebook), "dot")
    d_lut = np.asarray(lut_scan_block(jnp.asarray(codes.astype(np.int32)), lut))
    recon = pq.decode(codes)
    np.testing.assert_allclose(d_lut, -(q @ recon.T), rtol=1e-3, atol=1e-2)


def test_save_load_roundtrip(tmp_path, data):
    pq = ProductQuantizer(dim=32, segments=8, centroids=64, metric="l2-squared")
    pq.fit(data)
    p = str(tmp_path / "pq.npz")
    pq.save(p)
    pq2 = ProductQuantizer.load(p)
    np.testing.assert_array_equal(pq.encode(data[:50]), pq2.encode(data[:50]))


# -- compressed index ---------------------------------------------------------

def _recall(idx, data, queries, k=10):
    ids, _ = idx.search_by_vectors(queries, k)
    d = ((queries[:, None, :] - data[None, :, :]) ** 2).sum(-1)
    truth = np.argsort(d, axis=1)[:, :k]
    hits = sum(len(set(ids[i].tolist()) & set(truth[i].tolist())) for i in range(len(queries)))
    return hits / (len(queries) * k)


def test_compressed_index_recall(tmp_path, data):
    cfg = _cfg(enabled=False, segments=8, centroids=64)
    idx = TpuVectorIndex(cfg, str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(len(data)), data)
    # explicit compression via config update (compress.go trigger)
    new = vi.HnswUserConfig.from_dict(
        {"distance": "l2-squared", "pq": {"enabled": True, "segments": 8, "centroids": 64}})
    idx.update_user_config(new)
    assert idx.compressed
    queries = data[:32]
    rec = _recall(idx, data, queries)
    assert rec >= 0.95, f"compressed recall {rec}"


def test_compressed_no_rescore_lower_recall_still_works(tmp_path, data):
    cfg = _cfg(enabled=True, segments=8, centroids=64, rescore=False)
    idx = TpuVectorIndex(cfg, str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(len(data)), data)
    idx.flush()
    assert idx.compressed
    rec = _recall(idx, data, data[:16])
    assert rec >= 0.3  # raw PQ distances: approximate by design (8x4-dim
    # segments, 64 centroids => coarse cells; rescore=True is the default)


def test_compressed_filtered_search(tmp_path, data):
    from weaviate_tpu.storage.bitmap import Bitmap

    cfg = _cfg(enabled=True, segments=8, centroids=64)
    cfg.flat_search_cutoff = 10  # force the bitmap path, not the gather path
    idx = TpuVectorIndex(cfg, str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(len(data)), data)
    idx.flush()
    assert idx.compressed
    allow = Bitmap(np.arange(0, len(data), 2).astype(np.uint64))
    ids, _ = idx.search_by_vectors(data[:8], 5, allow)
    valid = ids[ids != np.uint64(0xFFFFFFFFFFFFFFFF)]
    assert (valid % 2 == 0).all()


def test_compressed_gather_path(tmp_path, data):
    from weaviate_tpu.storage.bitmap import Bitmap

    cfg = _cfg(enabled=True, segments=8, centroids=64)
    idx = TpuVectorIndex(cfg, str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(len(data)), data)
    idx.flush()
    allow = Bitmap(np.arange(100).astype(np.uint64))  # < flatSearchCutoff
    ids, dists = idx.search_by_vector(data[50], 5, allow)
    assert ids[0] == 50 and dists[0] < 1e-3


def test_compressed_delete_and_update(tmp_path, data):
    cfg = _cfg(enabled=True, segments=8, centroids=64)
    idx = TpuVectorIndex(cfg, str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(len(data)), data)
    idx.flush()
    idx.delete(0)
    ids, _ = idx.search_by_vector(data[0], 3)
    assert 0 not in ids.tolist()
    # re-add under a new vector
    idx.add(0, data[1])
    ids, dists = idx.search_by_vector(data[1], 2)
    assert {0, 1} <= set(ids.tolist())


def test_compressed_persistence_restore(tmp_path, data):
    path = str(tmp_path / "shard")
    cfg = _cfg(enabled=True, segments=8, centroids=64)
    idx = TpuVectorIndex(cfg, path)
    idx.add_batch(np.arange(len(data)), data)
    idx.flush()
    assert idx.compressed
    ids_before, _ = idx.search_by_vector(data[3], 5)
    idx.shutdown()

    idx2 = TpuVectorIndex(_cfg(enabled=True, segments=8, centroids=64), path)
    assert idx2.compressed  # codebook reloaded from pq.npz
    ids_after, _ = idx2.search_by_vector(data[3], 5)
    np.testing.assert_array_equal(ids_before, ids_after)
    idx2.shutdown()


def test_pq_immutable_disable(tmp_path, data):
    cfg = _cfg(enabled=True, segments=8, centroids=64)
    idx = TpuVectorIndex(cfg, str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(512), data[:512])
    idx.flush()
    off = _cfg(enabled=False, segments=8, centroids=64)
    with pytest.raises(vi.ConfigValidationError):
        idx.update_user_config(off)


def test_pq_enable_rejection_does_not_stick(tmp_path, data):
    """segments that don't divide dims reject the pq-enable update — and the
    rejected config must not stick, or _flush_pending's declarative trigger
    would re-raise on every later add/search."""
    idx = TpuVectorIndex(_cfg(), str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(512), data[:512])
    bad = _cfg(enabled=True, segments=7, centroids=64)  # 7 ∤ 32
    with pytest.raises(vi.ConfigValidationError):
        idx.update_user_config(bad)
    assert not idx.config.pq.enabled
    idx.add_batch(np.arange(512, 560), data[512:560])
    ids, _ = idx.search_by_vector(data[0], 5)
    assert ids[0] == 0


def test_pq_rescore_serves_from_store_scan(tmp_path, data):
    """With rescore enabled the bf16 row copy is already in HBM, so the
    fast scan runs straight over it (codes are write/restart-side only) —
    results must match exact numpy within bf16 tolerance."""
    cfg = _cfg(enabled=True, segments=8, centroids=64)
    idx = TpuVectorIndex(cfg, str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(1000), data[:1000])
    idx.flush()
    assert idx.compressed and idx._rescore_dev is not None
    q = data[:32] + 0.001 * np.random.default_rng(1).standard_normal((32, 32)).astype(np.float32)
    ids, dists = idx.search_by_vectors(q, 5)
    d = ((q[:, None, :] - data[None, :1000, :]) ** 2).sum(-1)
    want = np.argsort(d, axis=1)[:, :5]
    hit = np.mean([len(set(ids[i].tolist()) & set(want[i].tolist())) / 5
                   for i in range(32)])
    assert hit >= 0.96
    np.testing.assert_array_equal(ids[:, 0], np.arange(32, dtype=np.uint64))
    # distances come from the bf16 row copy, not the PQ approximation
    np.testing.assert_allclose(dists[:, 0], d[np.arange(32), ids[:, 0].astype(int)],
                               rtol=2e-2, atol=2e-2)


def test_pq_manhattan_rides_store_scan(tmp_path):
    """manhattan compressed search rides the bf16 rescore-store scan (the
    old 131-QPS LUT gather path is gone for it)."""
    rng = np.random.default_rng(5)
    base = rng.standard_normal((600, 32)).astype(np.float32)
    cfg = vi.HnswUserConfig.from_dict(
        {"distance": "manhattan",
         "pq": {"enabled": True, "segments": 8, "centroids": 32}}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, str(tmp_path / "man"), persist=False)
    idx.add_batch(np.arange(600), base)
    idx.flush()
    assert idx.compressed
    ids, dists = idx.search_by_vectors(base[:8], 3)
    np.testing.assert_array_equal(ids[:, 0], np.arange(8, dtype=np.uint64))
    d = np.abs(base[:8, None, :] - base[None, :, :]).sum(-1)
    want = np.argsort(d, axis=1)[:, :3]
    for i in range(8):
        assert len(set(ids[i].tolist()) & set(want[i].tolist())) >= 2


def test_pq_hamming_rejected(tmp_path):
    """hamming + kmeans-PQ has no meaningful ADC (mean centroids fail every
    exact-equality test) — compress must refuse, not mis-rank."""
    cfg = vi.HnswUserConfig.from_dict(
        {"distance": "hamming",
         "pq": {"enabled": True, "segments": 8, "centroids": 32}}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, str(tmp_path / "ham"), persist=False)
    rng = np.random.default_rng(5)
    idx.add_batch(np.arange(600), rng.integers(0, 4, (600, 32)).astype(np.float32))
    ids, _ = idx.search_by_vectors(
        rng.integers(0, 4, (8, 32)).astype(np.float32), 3)
    # declarative trigger auto-disables (invalid-config path) and the
    # uncompressed hamming scan keeps serving
    assert not idx.compressed and not idx.config.pq.enabled
    assert ids.shape == (8, 3)


def test_pq_async_dispatch_matches_sync(tmp_path, data):
    """The async serving dispatch pipelines PQ-with-rescore (bf16 store
    scan) instead of degrading to a blocking search; results match sync."""
    cfg = _cfg(enabled=True, segments=8, centroids=64)
    idx = TpuVectorIndex(cfg, str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(1000), data[:1000])
    idx.flush()
    assert idx.compressed
    q = data[:32]
    fin = idx.search_by_vectors_async(q, 5)
    ids_a, d_a = fin()
    ids_s, d_s = idx.search_by_vectors(q, 5)
    np.testing.assert_array_equal(ids_a, ids_s)
    np.testing.assert_allclose(d_a, d_s, rtol=1e-5)
    # codes-only tier still answers (synchronously) through the same API
    cfg2 = _cfg(enabled=True, segments=8, centroids=64, rescore=False)
    idx2 = TpuVectorIndex(cfg2, str(tmp_path / "s2"), persist=False)
    idx2.add_batch(np.arange(1000), data[:1000])
    idx2.flush()
    assert idx2.compressed and idx2._rescore_dev is None
    fin2 = idx2.search_by_vectors_async(q, 5)
    ids2, _ = fin2()
    assert ids2.shape == (32, 5)


def test_persisted_rejected_pq_serves_uncompressed(tmp_path, data):
    """A pq.npz this build refuses (e.g. a hamming codebook persisted by an
    older build) must not make the shard unloadable — restore logs a warning
    and serves uncompressed."""
    path = str(tmp_path / "shard")
    cfg = vi.HnswUserConfig.from_dict({"distance": "hamming"}, "hnsw_tpu")
    rng = np.random.default_rng(2)
    base = rng.integers(0, 4, (300, 32)).astype(np.float32)
    idx = TpuVectorIndex(cfg, path)
    idx.add_batch(np.arange(300), base)
    idx.flush()
    idx.shutdown()
    import os

    np.savez(os.path.join(path, "pq"), codebook=np.zeros((8, 32, 4), np.float32),
             dim=32, segments=8, centroids=32, metric="hamming",
             encoder="kmeans", distribution="log-normal")
    idx2 = TpuVectorIndex(cfg, path)
    assert not idx2.compressed and idx2.n == 300
    ids, _ = idx2.search_by_vector(base[5], 3)
    assert ids[0] == 5
    idx2.shutdown()


def test_pq_declared_invalid_auto_disables(tmp_path, data):
    """pq declared at class creation with segments that turn out not to
    divide dims (unknowable before the first import) auto-disables with a
    warning at the compression threshold instead of erroring every
    subsequent add/search."""
    cfg = _cfg(enabled=True, segments=7, centroids=64)  # 7 ∤ 32
    idx = TpuVectorIndex(cfg, str(tmp_path / "s"), persist=False)
    idx.add_batch(np.arange(512), data[:512])  # crosses the 256 threshold
    ids, _ = idx.search_by_vector(data[0], 5)  # search flushes -> triggers
    assert ids[0] == 0
    assert not idx.config.pq.enabled and not idx.compressed
    idx.add_batch(np.arange(512, 560), data[512:560])
    ids, _ = idx.search_by_vector(data[1], 5)
    assert ids[0] == 1


def test_compressed_large_k(tmp_path, rng):
    """Regression: k larger than the per-chunk candidate quota must widen
    the pool instead of crashing the final top_k."""
    from weaviate_tpu.entities import vectorindex as vi
    from weaviate_tpu.index.tpu import TpuVectorIndex

    cfg = vi.HnswUserConfig.from_dict(
        {"distance": "l2-squared",
         "pq": {"enabled": False, "segments": 8, "centroids": 64}}, "hnsw_tpu")
    idx = TpuVectorIndex(cfg, str(tmp_path), persist=False)
    data = rng.standard_normal((2000, 16)).astype(np.float32)
    idx.add_batch(np.arange(2000), data)
    idx.compress()
    ids, dists = idx.search_by_vectors(data[:4], 300)
    assert ids.shape[1] == 300
    assert ids[0][0] == 0 and dists[0][0] < 1.0


def test_rescore_false_warns_at_config_time(caplog):
    """pq.rescore=false is a measured 4x recall drop (codes-only recall@10
    0.24 vs 0.99 rescored) — the config parse must say so loudly while
    still accepting the opt-in (VERDICT r4 item 6). Rate-limited: a fleet
    restart parses one config per shard, and one warning per minute says
    everything N copies would."""
    import logging

    from weaviate_tpu.entities import vectorindex as vi_mod

    vi_mod._rescore_warn_last[0] = 0.0  # reset the process-wide rate limit
    with caplog.at_level(logging.WARNING, logger="weaviate_tpu.entities.vectorindex"):
        cfg = _cfg(enabled=True, segments=8, rescore=False)
    assert cfg.pq.rescore is False  # still legal — a warning, not an error
    assert any("rescore" in r.message and "recall" in r.message
               for r in caplog.records)

    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="weaviate_tpu.entities.vectorindex"):
        # within the rate-limit window: a second rescore=False parse is quiet
        _cfg(enabled=True, segments=8, rescore=False)
        _cfg(enabled=True, segments=8, rescore=True)
        _cfg(enabled=False, rescore=False)  # pq off: nothing to warn about
    assert not [r for r in caplog.records if "rescore" in r.message]
