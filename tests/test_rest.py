"""REST API surface tests: in-process server driven over real HTTP.

Reference test model: test/acceptance REST journeys (schema -> import ->
query -> delete) against the /v1 endpoint groups (SURVEY.md Appendix A).
"""

import json
import urllib.error
import urllib.request
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.config import load_config
from weaviate_tpu.server import App, RestServer


def _req(port, method, path, body=None, token=None, raw=False, timeout=30):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            payload = resp.read()
            if raw:
                return resp.status, payload
            return resp.status, json.loads(payload) if payload else None
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else None


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    app = App(data_path=str(tmp_path_factory.mktemp("data")))
    srv = RestServer(app, port=0)
    srv.start()
    yield srv
    srv.stop()
    app.shutdown()


@pytest.fixture(scope="module")
def port(server):
    return server.port


UUID1 = str(uuidlib.UUID(int=1))
UUID2 = str(uuidlib.UUID(int=2))


def test_well_known_and_meta(port):
    assert _req(port, "GET", "/v1/.well-known/live", raw=True)[0] == 200
    assert _req(port, "GET", "/v1/.well-known/ready", raw=True)[0] == 200
    st, meta = _req(port, "GET", "/v1/meta")
    assert st == 200 and "version" in meta


def test_schema_crud(port):
    st, cd = _req(port, "POST", "/v1/schema", {
        "class": "Article",
        "properties": [
            {"name": "title", "dataType": ["text"]},
            {"name": "wordCount", "dataType": ["int"]},
        ],
        "vectorIndexConfig": {"distance": "l2-squared"},
    })
    assert st == 200 and cd["class"] == "Article"

    st, schema = _req(port, "GET", "/v1/schema")
    assert st == 200 and [c["class"] for c in schema["classes"]] == ["Article"]

    st, got = _req(port, "GET", "/v1/schema/Article")
    assert st == 200 and {p["name"] for p in got["properties"]} == {"title", "wordCount"}

    st, prop = _req(port, "POST", "/v1/schema/Article/properties",
                    {"name": "summary", "dataType": ["text"]})
    assert st == 200 and prop["name"] == "summary"

    st, _ = _req(port, "POST", "/v1/schema", {"class": "Article"})
    assert st == 422  # duplicate

    st, shards = _req(port, "GET", "/v1/schema/Article/shards")
    assert st == 200 and len(shards) >= 1


def test_objects_crud(port):
    st, obj = _req(port, "POST", "/v1/objects", {
        "class": "Article", "id": UUID1,
        "properties": {"title": "hello world", "wordCount": 7},
        "vector": [0.1] * 8,
    })
    assert st == 200 and obj["id"] == UUID1

    st, got = _req(port, "GET", f"/v1/objects/Article/{UUID1}?include=vector")
    assert st == 200 and got["properties"]["title"] == "hello world"
    assert len(got["vector"]) == 8

    # legacy path without class
    st, got = _req(port, "GET", f"/v1/objects/{UUID1}")
    assert st == 200 and got["class"] == "Article"

    st, _ = _req(port, "HEAD", f"/v1/objects/Article/{UUID1}", raw=True)
    assert st == 204

    st, _ = _req(port, "PUT", f"/v1/objects/Article/{UUID1}", {
        "properties": {"title": "updated", "wordCount": 9}, "vector": [0.2] * 8})
    assert st == 200

    st, _ = _req(port, "PATCH", f"/v1/objects/Article/{UUID1}",
                 {"properties": {"wordCount": 11}})
    assert st in (200, 204)
    st, got = _req(port, "GET", f"/v1/objects/Article/{UUID1}")
    assert got["properties"]["title"] == "updated"
    assert got["properties"]["wordCount"] == 11

    st, listing = _req(port, "GET", "/v1/objects?class=Article")
    assert st == 200 and listing["totalResults"] == 1

    st, _ = _req(port, "DELETE", f"/v1/objects/Article/{UUID1}", raw=True)
    assert st == 204
    st, _ = _req(port, "GET", f"/v1/objects/Article/{UUID1}")
    assert st == 404


def test_object_validation_errors(port):
    # invalid uuid -> 422 (auto-schema would accept an unknown class, so the
    # error case here is identity, reference parity: AUTOSCHEMA_ENABLED=true)
    st, err = _req(port, "POST", "/v1/objects", {
        "class": "Article", "id": "not-a-uuid", "properties": {"title": "x"}})
    assert st == 422 and "error" in err
    st, _ = _req(port, "PATCH", f"/v1/objects/{UUID1}", {"properties": {}})
    assert st == 422  # PATCH requires a class


def test_batch_and_graphql(port):
    rng = np.random.default_rng(5)
    vecs = rng.standard_normal((20, 8)).astype(np.float32)
    objs = [{
        "class": "Article",
        "id": str(uuidlib.UUID(int=100 + i)),
        "properties": {"title": f"batch doc {i}", "wordCount": i},
        "vector": vecs[i].tolist(),
    } for i in range(20)]
    st, results = _req(port, "POST", "/v1/batch/objects", {"objects": objs})
    assert st == 200
    assert all(r["result"]["status"] == "SUCCESS" for r in results)

    q = {"query": """{ Get { Article(nearVector: {vector: %s}, limit: 3)
        { title _additional { id distance } } } }""" % json.dumps(vecs[4].tolist())}
    st, res = _req(port, "POST", "/v1/graphql", q)
    assert st == 200, res
    arts = res["data"]["Get"]["Article"]
    assert arts[0]["title"] == "batch doc 4"
    assert arts[0]["_additional"]["distance"] < 1e-3

    # aggregate
    st, res = _req(port, "POST", "/v1/graphql", {"query":
        "{ Aggregate { Article { meta { count } wordCount { mean maximum } } } }"})
    assert st == 200
    agg = res["data"]["Aggregate"]["Article"][0]
    assert agg["meta"]["count"] == 20

    # graphql parse error -> errors array, not a 500
    st, res = _req(port, "POST", "/v1/graphql", {"query": "{ Get { Article(limit: 1..2) { title } } }"})
    assert st == 200 and res["errors"]

    # batch delete by filter
    st, res = _req(port, "DELETE", "/v1/batch/objects", {
        "match": {"class": "Article",
                  "where": {"operator": "LessThan", "path": ["wordCount"], "valueInt": 5}},
    })
    assert st == 200 and res["results"]["successful"] == 5


def test_nodes_and_metrics(port):
    st, nodes = _req(port, "GET", "/v1/nodes")
    assert st == 200 and nodes["nodes"][0]["status"] == "HEALTHY"
    st, body = _req(port, "GET", "/metrics", raw=True)
    assert st == 200


def test_references(port):
    _req(port, "POST", "/v1/schema", {
        "class": "Author", "properties": [{"name": "name", "dataType": ["text"]}]})
    _req(port, "POST", "/v1/schema/Article/properties",
         {"name": "writtenBy", "dataType": ["Author"]})
    st, _ = _req(port, "POST", "/v1/objects", {
        "class": "Author", "id": UUID2, "properties": {"name": "ada"},
        "vector": [0.5] * 8})
    assert st == 200
    aid = str(uuidlib.UUID(int=300))
    _req(port, "POST", "/v1/objects", {
        "class": "Article", "id": aid,
        "properties": {"title": "with ref", "wordCount": 1}, "vector": [0.3] * 8})
    st, _ = _req(port, "POST", f"/v1/objects/Article/{aid}/references/writtenBy",
                 {"beacon": f"weaviate://localhost/Author/{UUID2}"})
    assert st == 200
    st, got = _req(port, "GET", f"/v1/objects/Article/{aid}")
    refs = got["properties"]["writtenBy"]
    assert refs and UUID2 in refs[0]["beacon"]
    st, _ = _req(port, "DELETE", f"/v1/objects/Article/{aid}/references/writtenBy",
                 {"beacon": f"weaviate://localhost/Author/{UUID2}"})
    assert st == 204


def test_unknown_route_and_method(port):
    st, _ = _req(port, "GET", "/v1/nope")
    assert st == 404
    st, _ = _req(port, "DELETE", "/v1/schema")
    assert st == 405


def test_backup_backend_not_enabled(port):
    # backup subsystem exists, but the backend module isn't enabled:
    # a clear 422, not a 501 stub
    st, body = _req(port, "POST", "/v1/backups/filesystem", {"id": "b1"})
    assert st == 422
    assert "backend module" in json.dumps(body)


def test_apikey_auth(tmp_path):
    cfg = load_config({
        "AUTHENTICATION_APIKEY_ENABLED": "true",
        "AUTHENTICATION_APIKEY_ALLOWED_KEYS": "sekret",
        "AUTHENTICATION_APIKEY_USERS": "alice",
        "AUTHORIZATION_ADMINLIST_ENABLED": "true",
        "AUTHORIZATION_ADMINLIST_USERS": "alice",
    })
    app = App(config=cfg, data_path=str(tmp_path / "d"))
    srv = RestServer(app, port=0)
    srv.start()
    try:
        st, _ = _req(srv.port, "GET", "/v1/schema")
        assert st == 401
        st, _ = _req(srv.port, "GET", "/v1/schema", token="wrong")
        assert st == 401
        st, schema = _req(srv.port, "GET", "/v1/schema", token="sekret")
        assert st == 200 and schema == {"classes": []}
        # liveness stays open without auth
        assert _req(srv.port, "GET", "/v1/.well-known/live", raw=True)[0] == 200
    finally:
        srv.stop()
        app.shutdown()


def test_pprof_surface(port):
    """/debug/pprof endpoints (configure_api.go:25 always-mounts pprof;
    ours is a sys._current_frames() sampler — monitoring/profiling.py)."""
    st, idx = _req(port, "GET", "/debug/pprof/", raw=True)
    assert st == 200 and b"profile" in idx

    st, dump = _req(port, "GET", "/debug/pprof/goroutine", raw=True)
    assert st == 200 and b"thread" in dump
    # the HTTP worker thread serving this very request is in the dump
    assert b"_dispatch" in dump or b"h_pprof_goroutine" in dump

    # short CPU profile while a busy thread runs -> its frames show up
    import threading

    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(i * i for i in range(1000))

    t = threading.Thread(target=spin, name="spinner", daemon=True)
    t.start()
    try:
        st, prof = _req(port, "GET", "/debug/pprof/profile?seconds=0.3&hz=200", raw=True)
    finally:
        stop.set()
        t.join()
    assert st == 200
    assert b"spin" in prof, prof[:400]

    # heap: first call arms tracemalloc, second returns a report
    st, h1 = _req(port, "GET", "/debug/pprof/heap", raw=True)
    assert st == 200
    st, h2 = _req(port, "GET", "/debug/pprof/heap?limit=5", raw=True)
    assert st == 200 and (b"total tracked" in h2 or b"armed" in h2)

    st, cl = _req(port, "GET", "/debug/pprof/cmdline", raw=True)
    assert st == 200 and cl


def test_pprof_device_trace(port):
    """/debug/pprof/trace captures a JAX device trace (the TPU twin of
    pprof's execution trace) and reports where it was written."""
    # Starting/stopping the JAX device profiler costs ~15s on its own and
    # degrades further when the full suite loads the machine; the default
    # 30s socket timeout flakes under that contention.
    st, body = _req(port, "GET", "/debug/pprof/trace?seconds=0.2", raw=True, timeout=180)
    assert st == 200, body[:300]
    assert b"device trace written to" in body
    # the reported directory exists and holds the capture
    import os

    trace_dir = body.decode().splitlines()[0].split(" to ", 1)[1].strip()
    assert os.path.isdir(trace_dir)
    names = []
    for root, _dirs, files in os.walk(trace_dir):
        names.extend(files)
    assert names, "trace capture produced no files"
