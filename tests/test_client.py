"""Python client library driven against a real in-process server — the
acceptance-test role the reference's generated client plays
(test/acceptance via client/)."""

import time
import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.client import Client, ClientError
from weaviate_tpu.config import Config
from weaviate_tpu.server import App, RestServer

UUID1 = str(uuidlib.UUID(int=1))


@pytest.fixture(scope="module")
def client(tmp_path_factory):
    c = Config()
    c.enable_modules = ["text2vec-local", "backup-filesystem"]
    c.backup_filesystem_path = str(tmp_path_factory.mktemp("bk"))
    app = App(config=c, data_path=str(tmp_path_factory.mktemp("data")))
    srv = RestServer(app, port=0)
    srv.start()
    cl = Client(f"http://127.0.0.1:{srv.port}")
    yield cl
    srv.stop()
    app.shutdown()


def test_liveness_meta(client):
    assert client.is_ready() and client.is_live()
    assert "version" in client.get_meta()


def test_schema_and_crud(client):
    client.schema.create_class({
        "class": "Book",
        "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "title", "dataType": ["text"]},
                       {"name": "pages", "dataType": ["int"]}],
    })
    assert any(c["class"] == "Book" for c in client.schema.get()["classes"])
    client.schema.add_property("Book", {"name": "isbn", "dataType": ["text"]})

    uid = client.data_object.create(
        {"title": "Snow Crash", "pages": 440}, "Book", uuid=UUID1,
        vector=np.arange(4, dtype=float).tolist())
    assert uid == UUID1
    got = client.data_object.get_by_id(UUID1, "Book", with_vector=True)
    assert got["properties"]["title"] == "Snow Crash"
    assert len(got["vector"]) == 4
    assert client.data_object.exists(UUID1, "Book")

    client.data_object.update({"pages": 441}, "Book", UUID1)
    assert client.data_object.get_by_id(UUID1, "Book")["properties"]["pages"] == 441
    client.data_object.replace({"title": "Snow Crash 2", "pages": 500}, "Book",
                               UUID1, vector=[1.0, 2.0, 3.0, 4.0])
    got = client.data_object.get_by_id(UUID1, "Book")
    assert got["properties"]["title"] == "Snow Crash 2"

    shards = client.schema.get_class_shards("Book")
    assert shards and shards[0]["status"] == "READY"

    client.data_object.delete(UUID1, "Book")
    assert client.data_object.get_by_id(UUID1, "Book") is None


def test_batch_and_query_builder(client):
    client.schema.create_class({
        "class": "Film",
        "vectorIndexType": "hnsw_tpu",
        "vectorIndexConfig": {"distance": "l2-squared"},
        "properties": [{"name": "title", "dataType": ["text"]},
                       {"name": "year", "dataType": ["int"]}],
    })
    rng = np.random.default_rng(5)
    objs = [{"class": "Film", "id": str(uuidlib.UUID(int=100 + i)),
             "properties": {"title": f"film about topic {i}", "year": 1990 + i},
             "vector": rng.standard_normal(8).tolist()} for i in range(20)]
    out = client.batch.create_objects(objs)
    assert all(o["result"]["status"] == "SUCCESS" for o in out)

    res = (client.query.get("Film", ["title", "year"])
           .with_near_vector({"vector": objs[7]["vector"]})
           .with_limit(3)
           .with_additional(["id", "distance"])
           .do())
    assert res[0]["_additional"]["id"] == objs[7]["id"]
    assert res[0]["_additional"]["distance"] < 1e-5

    res = (client.query.get("Film", ["title", "year"])
           .with_where({"operator": "LessThan", "path": ["year"], "valueInt": 1995})
           .with_sort({"path": ["year"], "order": "desc"})
           .with_limit(10)
           .do())
    years = [r["year"] for r in res]
    assert years == sorted(years, reverse=True) and max(years) < 1995

    res = (client.query.get("Film", ["title"])
           .with_bm25("topic 7", properties=["title"]).with_limit(3).do())
    assert any("7" in r["title"] for r in res)

    agg = client.query.aggregate("Film", "meta { count }")
    assert agg[0]["meta"]["count"] == 20

    dry = client.batch.delete_objects(
        "Film", {"operator": "GreaterThan", "path": ["year"], "valueInt": 2005},
        dry_run=True)
    assert dry["results"]["matches"] == 4
    out = client.batch.delete_objects(
        "Film", {"operator": "GreaterThan", "path": ["year"], "valueInt": 2005})
    assert out["results"]["successful"] == 4


def test_neartext_and_refs(client):
    client.schema.create_class({
        "class": "Note", "vectorizer": "text2vec-local",
        "vectorIndexConfig": {"distance": "cosine"},
        "properties": [{"name": "text", "dataType": ["text"]}],
    })
    a = client.data_object.create({"text": "gradient descent optimizer"}, "Note")
    client.data_object.create({"text": "pizza dough hydration"}, "Note")
    res = (client.query.get("Note", ["text"])
           .with_near_text({"concepts": ["gradient descent"]})
           .with_limit(1).with_additional("id").do())
    assert res[0]["_additional"]["id"] == a

    client.schema.create_class({
        "class": "Author",
        "properties": [{"name": "name", "dataType": ["text"]},
                       {"name": "wrote", "dataType": ["Note"]}],
    })
    au = client.data_object.create({"name": "ada"}, "Author")
    client.data_object.reference_add("Author", au, "wrote", "Note", a)
    got = client.data_object.get_by_id(au, "Author")
    assert got["properties"]["wrote"][0]["beacon"].endswith(a)


def test_backup_via_client(client):
    client.backup.create("filesystem", "clibak", include=["Note"])
    deadline = time.time() + 30
    while time.time() < deadline:
        st = client.backup.status("filesystem", "clibak")
        if st["status"] in ("SUCCESS", "FAILED"):
            break
        time.sleep(0.05)
    assert st["status"] == "SUCCESS"


def test_nodes_and_errors(client):
    nodes = client.cluster.get_nodes_status()
    assert nodes and nodes[0]["status"] == "HEALTHY"
    with pytest.raises(ClientError) as ei:
        client.schema.create_class({"class": "Book"})  # duplicate
    assert ei.value.status == 422


def test_module_extensions_via_client(client):
    """client.modules: store a custom concept, list it, introspect it, and
    USE it through nearText — the full extensions journey client-side."""
    ext = client.modules.create_extension(
        "text2vec-local", "zanthor",
        "a mythical creature that reviews pull requests")
    assert ext["concept"] == "zanthor" and ext["weight"] == 1.0
    assert any(e["concept"] == "zanthor"
               for e in client.modules.get_extensions("text2vec-local"))
    info = client.modules.get_concept("text2vec-local", "zanthor")
    assert info["individualWords"][0]["info"]["custom"] is True

    client.schema.create_class({
        "class": "ExtClientDoc", "vectorizer": "text2vec-local",
        "vectorIndexConfig": {"distance": "cosine"},
        "properties": [{"name": "body", "dataType": ["text"]}]})
    client.batch.create_objects([
        {"class": "ExtClientDoc",
         "properties": {"body": "a mythical creature reviewing pull requests"}},
        {"class": "ExtClientDoc",
         "properties": {"body": "sourdough starter hydration schedule"}},
    ])
    hits = (client.query.get("ExtClientDoc", ["body"])
            .with_near_text({"concepts": ["zanthor"]}).with_limit(1).do())
    assert "mythical" in hits[0]["body"]

    # validation surfaces as ClientError
    with pytest.raises(ClientError):
        client.modules.create_extension("text2vec-local", "BadCase", "x")
    with pytest.raises(ClientError):
        client.modules.get_extensions("no-such-module")
