"""LSM-backed sorter pushdown + background pair compaction.

Reference: adapters/repos/db/sorter/ (sort keys extracted from LSM, only
the returned page hydrated) and lsmkv/segment_group_compaction.go
(background pair merges keep the segment stack bounded).
"""

import uuid as uuidlib

import numpy as np
import pytest

from weaviate_tpu.db import DB
from weaviate_tpu.entities.schema import ClassDef, Property
from weaviate_tpu.entities.storobj import StorObj
from weaviate_tpu.entities.vectorindex import parse_and_validate_config
from weaviate_tpu.storage.lsm import STRATEGY_REPLACE, Store


def make_class():
    return ClassDef(
        name="Sortable",
        properties=[
            Property(name="title", data_type=["text"]),
            Property(name="rank", data_type=["int"]),
            Property(name="score", data_type=["number"]),
        ],
        vector_index_type="hnsw_tpu",
    )


@pytest.fixture
def idx(tmp_path):
    db = DB(str(tmp_path / "data"))
    index = db.add_class(make_class(), parse_and_validate_config("hnsw_tpu", {}))
    objs = []
    rng = np.random.default_rng(3)
    for i in range(40):
        objs.append(StorObj(
            class_name="Sortable", uuid=str(uuidlib.UUID(int=i + 1)),
            properties={
                "title": f"title {chr(97 + (i * 7) % 26)}{i}",
                "rank": (i * 13) % 40,
                # every 5th object has no score: missing-last semantics
                **({"score": float((i * 31) % 17)} if i % 5 else {}),
            },
            vector=rng.standard_normal(4).astype(np.float32),
        ))
    index.put_batch(objs)
    yield index
    db.shutdown()


def test_sort_pushdown_numeric(idx):
    res = idx.object_search(10, sort=[{"path": ["rank"], "order": "asc"}])
    ranks = [r.obj.properties["rank"] for r in res]
    assert ranks == sorted(ranks)
    assert ranks[0] == 0

    res = idx.object_search(10, sort=[{"path": ["rank"], "order": "desc"}])
    ranks = [r.obj.properties["rank"] for r in res]
    assert ranks == sorted(ranks, reverse=True)
    assert ranks[0] == 39


def test_sort_pushdown_string_and_paging(idx):
    res = idx.object_search(40, sort=[{"path": ["title"], "order": "asc"}])
    titles = [r.obj.properties["title"] for r in res]
    assert titles == sorted(titles)
    # paging: offset walks the same global order
    page2 = idx.object_search(5, offset=5, sort=[{"path": ["title"], "order": "asc"}])
    assert [r.obj.properties["title"] for r in page2] == titles[5:10]


def test_sort_missing_values_last(idx):
    res = idx.object_search(40, sort=[{"path": ["score"], "order": "asc"}])
    scores = [r.obj.properties.get("score") for r in res]
    present = [s for s in scores if s is not None]
    assert present == sorted(present)
    # all missing values trail, in both directions
    assert all(s is None for s in scores[len(present):])
    res_d = idx.object_search(40, sort=[{"path": ["score"], "order": "desc"}])
    scores_d = [r.obj.properties.get("score") for r in res_d]
    assert scores_d[: len(present)] == sorted(present, reverse=True)


def test_sort_special_keys(idx):
    res = idx.object_search(40, sort=[{"path": ["_id"], "order": "asc"}])
    uuids = [r.obj.uuid for r in res]
    assert uuids == sorted(uuids)


def test_pair_compaction_bounds_segments(tmp_path):
    store = Store(str(tmp_path / "lsm"))
    b = store.create_or_load_bucket("obj", STRATEGY_REPLACE)
    # create many segments via repeated flushes (with deletes interleaved)
    for round_i in range(12):
        for i in range(20):
            b.put(f"k{round_i}-{i}".encode(), f"v{round_i}-{i}".encode())
        if round_i % 3 == 0 and round_i > 0:
            b.delete(f"k{round_i - 1}-0".encode())
        b.flush_memtable()
    assert b.segment_count() == 12
    merges = store.compact_once(max_segments=4)
    assert merges > 0
    assert b.segment_count() <= 4
    # every live key still resolves, deletes stay deleted
    assert b.get(b"k7-3") == b"v7-3"
    assert b.get(b"k0-0") == b"v0-0"
    assert b.get(b"k8-0") is None  # deleted in round 9
    store.shutdown()


def test_compaction_cycle_thread(tmp_path):
    import time

    store = Store(str(tmp_path / "lsm"))
    b = store.create_or_load_bucket("obj", STRATEGY_REPLACE)
    for round_i in range(10):
        b.put(f"r{round_i}".encode(), b"x")
        b.flush_memtable()
    store.start_compaction_cycle(interval=0.05, max_segments=3)
    deadline = time.time() + 10
    while time.time() < deadline and b.segment_count() > 3:
        time.sleep(0.05)
    assert b.segment_count() <= 3
    assert b.get(b"r7") == b"x"
    store.shutdown()


def test_pair_compaction_survives_restart(tmp_path):
    """Regression: the merged oldest pair must keep its position in the
    filename-ordered load sequence — a fresh counter name would make the
    oldest data load as newest after restart, resurrecting stale values
    and deleted keys."""
    root = str(tmp_path / "lsm")
    store = Store(root)
    b = store.create_or_load_bucket("obj", STRATEGY_REPLACE)
    b.put(b"k", b"v1")
    b.flush_memtable()          # 00000000.seg holds k=v1
    b.put(b"other", b"x")
    b.flush_memtable()
    b.put(b"k", b"v2")          # newer segment overrides
    b.put(b"dead", b"soon")
    b.flush_memtable()
    b.delete(b"dead")
    b.flush_memtable()
    while b.segment_count() > 2:
        assert b.compact_pair()
    assert b.get(b"k") == b"v2"
    assert b.get(b"dead") is None
    store.shutdown()

    store2 = Store(root)
    b2 = store2.create_or_load_bucket("obj", STRATEGY_REPLACE)
    assert b2.get(b"k") == b"v2"        # not resurrected to v1
    assert b2.get(b"dead") is None      # delete survives restart
    assert b2.get(b"other") == b"x"
    store2.shutdown()


def test_sort_with_cursor_rejected(idx):
    with pytest.raises(ValueError):
        idx.object_search(5, sort=[{"path": ["rank"]}],
                          cursor_after=str(uuidlib.UUID(int=1)))


def test_sort_mixed_types_no_crash(tmp_path):
    """Regression: auto-schema drift can leave one property holding numbers
    in some objects and strings in others — sorting must order by type rank
    instead of raising."""
    from weaviate_tpu.db.sorter import sort_results
    from weaviate_tpu.db.shard import SearchResult

    rows = []
    for i, v in enumerate([3, "apple", None, 1.5, {"lat": 2}, "zebra", 7]):
        props = {} if v is None else {"mixed": v}
        rows.append(SearchResult(obj=StorObj(
            class_name="M", uuid=str(uuidlib.UUID(int=i + 1)), properties=props)))
    out = sort_results(rows, [{"path": ["mixed"], "order": "asc"}])
    vals = [r.obj.properties.get("mixed") for r in out]
    assert vals[:2] == [1.5, 3] or vals[:3] == [1.5, 3, 7]  # numbers first, ordered
    assert vals[-1] is None  # missing last
    out_d = sort_results(rows, [{"path": ["mixed"], "order": "desc"}])
    vals_d = [r.obj.properties.get("mixed") for r in out_d]
    assert vals_d[0] == 7 and vals_d[-1] is None


def test_backup_during_write_load_with_compaction(tmp_path):
    """Regression: a backup must not race the background compaction cycle
    (segment files deleted mid-copy) nor sweep half-written tmp files."""
    import threading
    import time

    from weaviate_tpu.modules import Provider
    from weaviate_tpu.modules.backup_fs import FilesystemBackupBackend
    from weaviate_tpu.usecases.backup import BackupScheduler
    from weaviate_tpu.schema import SchemaManager

    db = DB(str(tmp_path / "data"))
    mgr = SchemaManager(str(tmp_path / "schema.json"), migrator=db)
    mgr.add_class({
        "class": "Busy", "vectorIndexType": "hnsw_tpu",
        "properties": [{"name": "t", "dataType": ["text"]}]})
    idx = db.get_index("Busy")
    shard = next(iter(idx.shards.values()))
    # churn writer creating many segments + aggressive compaction cycle
    shard.store.start_compaction_cycle(interval=0.01, max_segments=2)
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            idx.put_object(StorObj(class_name="Busy", uuid=str(uuidlib.uuid4()),
                                   properties={"t": f"x{i}"}))
            if i % 5 == 0:
                shard.store.flush_all()
            i += 1

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    p = Provider()
    p.register(FilesystemBackupBackend(str(tmp_path / "bk")))
    sched = BackupScheduler(db, mgr, p)
    try:
        for n in range(3):
            sched.backup("filesystem", {"id": f"load{n}"})
            final = sched.wait(f"load{n}", timeout=60)
            assert final["status"] == "SUCCESS", final
            time.sleep(0.05)
    finally:
        stop.set()
        t.join(timeout=5)
        db.shutdown()
